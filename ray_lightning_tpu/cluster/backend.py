"""Cluster backend abstraction + in-memory object store.

The reference leans on Ray core for four services (SURVEY §2.2): actor
scheduling, object transfer (``ray.put`` at ``ray_ddp.py:340``), the
distributed queue, and teardown.  This module provides those behind a small
interface so the framework runs:

* **LocalBackend** (default, zero deps): process actors on this machine —
  the analogue of ``ray.init()`` auto-bootstrapping a local cluster
  (reference ``ray_ddp.py:125-126``).  This is also the mode used on a TPU
  pod slice where an external launcher (GKE, xpk, mpirun) starts one driver
  per slice.
* **RayBackend**: if real Ray *is* installed, the same interface maps onto
  ``@ray.remote`` actors with resource reservations
  (``RayExecutor.options(num_cpus=..., resources=...)``, reference
  ``ray_ddp.py:183-189``) — keeping Ray as control plane while the data
  plane stays XLA/ICI.  Gated with the ``Unavailable`` pattern.

Object store: ``put()`` eagerly serializes with cloudpickle into an
:class:`ObjectRef` whose payload travels inside actor RPC messages — the
driver serializes the model **once** and every worker deserializes its own
copy, exactly the ``ray.put(model)`` / implicit-get dance of reference
``ray_ddp.py:339-353``.
"""

from __future__ import annotations

import os
from typing import Any, Callable, Dict, List, Optional, Union

from . import rpc
from .actor import ProcessActor
from .queue import DriverQueue

__all__ = [
    "ObjectRef",
    "ClusterBackend",
    "LocalBackend",
    "RemoteBackend",
    "RayBackend",
    "get_backend",
    "ray_is_available",
]


def ray_is_available() -> bool:
    try:
        import ray  # noqa: F401

        return True
    except ImportError:
        return False


class ObjectRef:
    """An object reference (≙ ``ray.ObjectRef``), by value or by segment.

    Serialization happens exactly once at ``put`` time; each ``get`` call
    deserializes a fresh copy (so workers never alias driver state — the
    property the reference gets from Ray's object store).  Large payloads
    on a single-host backend travel *by segment*: the bytes live in one
    checksummed tmpfs segment (:mod:`..cluster.shm`, the plasma analogue)
    and only the path crosses the actor sockets — N local workers cost one
    write + N page-cache reads instead of N socket copies.
    """

    __slots__ = ("_payload", "_segment_path", "_nbytes")

    def __init__(self, payload: Optional[bytes] = None,
                 segment_path: Optional[str] = None, nbytes: int = 0):
        self._payload = payload
        self._segment_path = segment_path
        self._nbytes = len(payload) if payload is not None else nbytes

    @classmethod
    def from_object(cls, obj: Any) -> "ObjectRef":
        return cls(payload=rpc.dumps(obj))

    @classmethod
    def from_object_via_store(
        cls, obj: Any, store, min_segment_bytes: int
    ) -> "ObjectRef":
        """Spill to a segment when the payload is worth it; the caller
        guarantees every reader shares the store's host."""
        payload = rpc.dumps(obj)
        if len(payload) < min_segment_bytes:
            return cls(payload=payload)
        path = store.put(payload)
        return cls(segment_path=path, nbytes=len(payload))

    def get(self) -> Any:
        if self._segment_path is not None:
            from .shm import SegmentStore

            return rpc.loads(SegmentStore.get(self._segment_path))
        return rpc.loads(self._payload)

    def release(self) -> None:
        """Reclaim the backing segment NOW (idempotent).

        Segments otherwise live until backend shutdown — a strategy that
        runs many fits on one backend (the PBT path) would leak tmpfs RAM
        proportional to fits × model size.  After release, ``get()`` on
        this ref is invalid."""
        if self._segment_path is not None:
            try:
                os.unlink(self._segment_path)
            except OSError:
                pass
            self._segment_path = None
        self._payload = None

    @property
    def nbytes(self) -> int:
        return self._nbytes


class ClusterBackend:
    """Interface every control-plane backend implements."""

    def create_actor(
        self,
        name: str,
        env: Optional[Dict[str, str]] = None,
        num_cpus: float = 1,
        resources: Optional[Dict[str, float]] = None,
    ):
        raise NotImplementedError

    def put(self, obj: Any) -> ObjectRef:
        raise NotImplementedError

    def create_queue(self) -> DriverQueue:
        raise NotImplementedError

    def shutdown(self) -> None:
        pass


class LocalBackend(ClusterBackend):
    """Process actors on the local host (spawn).

    All readers share this host, so ``put`` spills payloads above
    ``min_segment_bytes`` (default 1 MiB, ``RLT_SEGMENT_MIN_BYTES``) into
    the shared-memory segment store instead of the RPC stream.
    """

    def __init__(self, min_segment_bytes: Optional[int] = None):
        from .shm import SegmentStore

        self._actors: List[ProcessActor] = []
        self._store = SegmentStore()
        self.min_segment_bytes = (
            min_segment_bytes
            if min_segment_bytes is not None
            else int(os.environ.get("RLT_SEGMENT_MIN_BYTES", 1 << 20))
        )

    def create_actor(
        self,
        name: str,
        env: Optional[Dict[str, str]] = None,
        num_cpus: float = 1,
        resources: Optional[Dict[str, float]] = None,
    ) -> ProcessActor:
        actor = ProcessActor(name=name, env=env)
        self._actors.append(actor)
        return actor

    def put(self, obj: Any) -> ObjectRef:
        return ObjectRef.from_object_via_store(
            obj, self._store, self.min_segment_bytes
        )

    def create_queue(self) -> DriverQueue:
        return DriverQueue()

    def shutdown(self) -> None:
        for a in self._actors:
            try:
                a.kill()
            except Exception:  # noqa: BLE001 - best-effort teardown
                pass
        self._actors.clear()
        self._store.unlink_all()


class RemoteBackend(ClusterBackend):
    """Multi-host control plane over node agents — the "infinite laptop".

    ≙ Ray Client + multi-node scheduling in the reference (``README.md:
    82-95``): the driver (a workstation or a CPU-only coordinator VM) holds
    one :class:`.agent.AgentClient` per TPU host and places actors
    round-robin across them.  Actors dial the driver back directly, and
    the distributed queue binds all interfaces — so the only topology
    requirement is driver↔host TCP reachability, exactly Ray Client's.

    ``hosts``: list of ``"ip[:port]"`` agent addresses (or the
    ``RLT_HOSTS`` env var, comma-separated, via :func:`get_backend`).
    """

    def __init__(self, hosts: List[str], token: Optional[str] = None):
        from .agent import AgentClient

        if not hosts:
            raise ValueError("RemoteBackend needs at least one agent host")
        self._clients = [AgentClient(h, token=token) for h in hosts]
        self._rr = 0
        self._actors: List[ProcessActor] = []

    def create_actor(
        self,
        name: str,
        env: Optional[Dict[str, str]] = None,
        num_cpus: float = 1,
        resources: Optional[Dict[str, float]] = None,
    ) -> ProcessActor:
        from .agent import agent_launcher

        client = self._clients[self._rr % len(self._clients)]
        self._rr += 1
        actor = ProcessActor(
            name=name,
            env=env,
            launcher=agent_launcher(client),
            bind_host="0.0.0.0",
            advertise_host=rpc.get_node_ip(),
        )
        self._actors.append(actor)
        return actor

    def put(self, obj: Any) -> ObjectRef:
        return ObjectRef.from_object(obj)

    def create_queue(self) -> DriverQueue:
        return DriverQueue(host="0.0.0.0", advertise_host=rpc.get_node_ip())

    def shutdown(self) -> None:
        for a in self._actors:
            try:
                a.kill()
            except Exception:  # noqa: BLE001 - best-effort teardown
                pass
        self._actors.clear()
        for c in self._clients:
            c.close()
        self._clients = []


class _RayActorAdapter:
    """Wraps a Ray actor handle behind the :class:`ProcessActor` surface."""

    def __init__(self, handle, name: str):
        self._handle = handle
        self.name = name

    def submit(self, fn: Callable, *args: Any, **kwargs: Any):
        ref = self._handle.execute.remote(fn, *args, **kwargs)
        return _RayFutureAdapter(ref)

    def execute(self, fn: Callable, *args: Any, **kwargs: Any) -> Any:
        import ray

        return ray.get(self._handle.execute.remote(fn, *args, **kwargs))

    def set_env_vars(self, env: Dict[str, str]) -> None:
        from .actor import _remote_set_env_vars

        self.execute(_remote_set_env_vars, env)

    def get_node_ip(self) -> str:
        from .actor import _remote_get_node_ip

        return self.execute(_remote_get_node_ip)

    def get_device_info(self) -> Dict[str, Any]:
        from .actor import _remote_get_device_info

        return self.execute(_remote_get_device_info)

    def is_alive(self) -> bool:
        return True

    def kill(self, timeout: float = 5.0) -> None:
        import ray

        ray.kill(self._handle, no_restart=True)


class _RayFutureAdapter:
    """Duck-typed ``concurrent.futures.Future`` over a Ray object ref."""

    def __init__(self, ref):
        self._ref = ref

    def result(self, timeout: Optional[float] = None) -> Any:
        import ray

        return ray.get(self._ref, timeout=timeout)

    def done(self) -> bool:
        import ray

        ready, _ = ray.wait([self._ref], timeout=0)
        return bool(ready)

    def exception(self, timeout: Optional[float] = None):
        try:
            self.result(timeout=timeout)
            return None
        except Exception as e:  # noqa: BLE001
            return e


class RayBackend(ClusterBackend):
    """Real-Ray control plane, used only when Ray is installed.

    Actors are reserved with custom resources so the scheduler pins one
    actor per TPU host (e.g. ``resources={"TPU": 4}``) — the analogue of
    GPU reservations at reference ``ray_ddp.py:183-189``.
    """

    def __init__(self):
        import ray

        if not ray.is_initialized():
            ray.init()  # ≙ reference ray_ddp.py:125-126
        self._ray = ray
        self._actors: List[_RayActorAdapter] = []

    def create_actor(
        self,
        name: str,
        env: Optional[Dict[str, str]] = None,
        num_cpus: float = 1,
        resources: Optional[Dict[str, float]] = None,
    ) -> _RayActorAdapter:
        ray = self._ray

        @ray.remote
        class _Shell:
            def execute(self, fn, *args, **kwargs):
                return fn(*args, **kwargs)

        # runtime_env starts the worker process WITH the env in place —
        # import-time vars (JAX_PLATFORMS/XLA_FLAGS/TPU_VISIBLE_CHIPS) must
        # be set before the worker's first jax import, matching
        # ProcessActor's pre-exec semantics.
        handle = _Shell.options(
            num_cpus=num_cpus,
            resources=resources or None,
            name=name,
            runtime_env={"env_vars": {k: str(v) for k, v in (env or {}).items()}},
        ).remote()
        adapter = _RayActorAdapter(handle, name)
        self._actors.append(adapter)
        return adapter

    def put(self, obj: Any) -> ObjectRef:
        # Keep by-value semantics for interface uniformity; Ray's own object
        # store is still used for the RPC arguments themselves.
        return ObjectRef.from_object(obj)

    def create_queue(self) -> DriverQueue:
        return DriverQueue(host="0.0.0.0", advertise_host=rpc.get_node_ip())

    def shutdown(self) -> None:
        for a in self._actors:
            try:
                a.kill()
            except Exception:  # noqa: BLE001
                pass
        self._actors.clear()


def get_backend(
    name: Union[str, ClusterBackend, None] = None,
) -> ClusterBackend:
    """Select the control plane.

    ``name`` may be a ClusterBackend instance (used as-is — how a
    configured :class:`RemoteBackend` is passed through a strategy), or a
    string: priority explicit ``name`` > ``RLT_BACKEND`` env var >
    ``local``.  ``"remote"`` reads agent addresses from ``RLT_HOSTS``;
    ``"ray"`` requires Ray installed.
    """
    if isinstance(name, ClusterBackend):
        return name
    name = name or os.environ.get("RLT_BACKEND", "local")
    if name == "ray":
        if not ray_is_available():
            raise ImportError(
                "RLT_BACKEND=ray requested but Ray is not installed; "
                "falling back is disabled to avoid silent behavior changes."
            )
        return RayBackend()
    if name == "remote":
        hosts = [h for h in os.environ.get("RLT_HOSTS", "").split(",") if h]
        return RemoteBackend(hosts)
    if name == "local":
        return LocalBackend()
    raise ValueError(
        f"Unknown cluster backend {name!r} (expected local|remote|ray)"
    )
