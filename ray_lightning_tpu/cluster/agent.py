"""Node agent: remote-host worker launch — the laptop-driver property.

The reference gets two things from Ray that the local backend alone cannot
provide: scheduling actors onto *other* machines, and driving a cluster
from a workstation that is not part of it (Ray Client, reference
``README.md:82-95``, ``tests/test_client*.py``).  This module supplies
both with one small daemon:

* **NodeAgent** — runs on every TPU host (``python -m
  ray_lightning_tpu.cluster.agent --port 7077``).  It accepts
  token-authenticated driver connections and spawns/kills actor child
  processes on its host.  The children dial the *driver* back directly
  (the same length-prefixed-cloudpickle RPC as local actors), so the
  agent is control-plane-only: zero bytes of task traffic flow through it.
* **AgentClient** — the driver side: one persistent connection per host,
  multiplexing spawn/poll/kill requests.
* **agent_launcher** — adapts an AgentClient into the ``launcher``
  callable of :class:`.actor.ProcessActor`, so a remote actor is the same
  object as a local one from the strategy layer's point of view.

Trust model matches Ray's: a shared secret (``--token`` /
``RLT_AGENT_TOKEN``) gates the agent, and payloads are cloudpickle —
agents must only listen on cluster-internal networks.
"""

from __future__ import annotations

import argparse
import hmac
import os
import socket
import subprocess
import threading
import time
import traceback
from typing import Any, Dict, Optional, Tuple

from . import rpc

__all__ = [
    "NodeAgent",
    "AgentClient",
    "AgentError",
    "agent_launcher",
    "DEFAULT_AGENT_PORT",
]

DEFAULT_AGENT_PORT = 7077


class AgentError(RuntimeError):
    """A node agent refused or failed a request."""


# ---------------------------------------------------------------------------
# Server side
# ---------------------------------------------------------------------------

class NodeAgent:
    """Per-host spawn daemon (see module docstring)."""

    def __init__(self, host: str = "0.0.0.0", port: int = DEFAULT_AGENT_PORT,
                 token: Optional[str] = None):
        self._token = (token if token is not None
                       else os.environ.get("RLT_AGENT_TOKEN", ""))
        # An agent executes arbitrary pickled callables for whoever
        # authenticates; an empty token on a non-loopback bind would be
        # unauthenticated remote code execution.  Refuse loudly.
        if not self._token and not host.startswith("127."):
            raise ValueError(
                "NodeAgent on a non-loopback interface requires a token "
                "(--token or RLT_AGENT_TOKEN): it spawns arbitrary code "
                "for authenticated peers."
            )
        self._server = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._server.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._server.bind((host, port))
        self._server.listen(16)
        self.port = self._server.getsockname()[1]
        self._procs: Dict[int, subprocess.Popen] = {}
        self._lock = threading.Lock()
        self._closed = False
        self._accept_thread: Optional[threading.Thread] = None

    # -- request handlers ---------------------------------------------------
    def _handle(self, msg: Tuple) -> Tuple:
        kind = msg[0]
        if kind == "ping":
            # Host load/memory ride the ping so the driver can attach
            # straggler context ("rank 3 is slow AND its host is at
            # load 40") without a second RPC (telemetry/aggregate.py).
            from ray_lightning_tpu.telemetry.aggregate import host_stats

            return ("ok", {"ip": rpc.get_node_ip(),
                           "pid_count": len(self._procs),
                           **host_stats()})
        if kind == "spawn":
            from .actor import spawn_child

            _, spec = msg
            proc = spawn_child(
                spec["connect_host"], spec["port"], spec["authkey_hex"],
                spec.get("env") or {},
            )
            with self._lock:
                self._procs[proc.pid] = proc
            return ("ok", proc.pid)
        if kind == "poll":
            _, pid = msg
            with self._lock:
                proc = self._procs.get(pid)
            if proc is None:
                return ("ok", -1)  # unknown pid ≙ long dead
            code = proc.poll()
            return ("ok", code)
        if kind == "kill":
            _, pid, grace_s = msg
            with self._lock:
                proc = self._procs.pop(pid, None)
            if proc is not None and proc.poll() is None:
                proc.terminate()
                try:
                    proc.wait(grace_s)
                except subprocess.TimeoutExpired:
                    proc.kill()
            return ("ok", None)
        return ("err", f"unknown agent request {kind!r}")

    def _serve_conn(self, conn: socket.socket) -> None:
        try:
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            # Pre-auth frame: cap the length so an unauthenticated peer
            # cannot claim a multi-GiB payload and exhaust agent memory.
            presented = rpc.recv_frame(conn, max_len=1024).decode()
            if not hmac.compare_digest(presented, self._token):
                rpc.send_obj(conn, ("err", "bad token"))
                return
            rpc.send_obj(conn, ("ok", None))
            while not self._closed:
                msg = rpc.loads(rpc.recv_frame(conn))
                if msg[0] == "bye":
                    return
                try:
                    out = self._handle(msg)
                except Exception:  # noqa: BLE001 - report, keep serving
                    out = ("err", traceback.format_exc())
                rpc.send_obj(conn, out)
        except (ConnectionError, OSError):
            pass
        finally:
            conn.close()

    def _accept_loop(self) -> None:
        while not self._closed:
            try:
                conn, _ = self._server.accept()
            except OSError:
                return
            threading.Thread(
                target=self._serve_conn, args=(conn,), daemon=True
            ).start()

    def start(self) -> None:
        """Serve in a background thread (tests / embedded use)."""
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name="rlt-agent-accept", daemon=True
        )
        self._accept_thread.start()

    def serve_forever(self) -> None:
        self._accept_loop()

    def shutdown(self) -> None:
        self._closed = True
        try:
            self._server.close()
        except OSError:
            pass
        with self._lock:
            procs, self._procs = dict(self._procs), {}
        for proc in procs.values():
            if proc.poll() is None:
                proc.terminate()


# ---------------------------------------------------------------------------
# Driver side
# ---------------------------------------------------------------------------

class AgentClient:
    """One persistent, lock-protected connection to a host's NodeAgent."""

    def __init__(self, address: str, token: Optional[str] = None,
                 timeout_s: float = 30.0):
        if ":" in address:
            host, port_s = address.rsplit(":", 1)
            port = int(port_s)
        else:
            host, port = address, DEFAULT_AGENT_PORT
        self.host = host
        self.port = port
        token = (token if token is not None
                 else os.environ.get("RLT_AGENT_TOKEN", ""))
        self._sock = socket.create_connection((host, port), timeout=timeout_s)
        self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._lock = threading.Lock()
        rpc.send_frame(self._sock, token.encode())
        status, payload = rpc.recv_obj(self._sock)
        if status != "ok":
            self._sock.close()
            raise AgentError(f"Agent {address}: {payload}")

    def _request(self, msg: Tuple) -> Any:
        with self._lock:
            rpc.send_obj(self._sock, msg)
            status, payload = rpc.recv_obj(self._sock)
        if status != "ok":
            raise AgentError(f"Agent {self.host}:{self.port}: {payload}")
        return payload

    def ping(self) -> Dict[str, Any]:
        return self._request(("ping",))

    def spawn(self, connect_host: str, port: int, authkey_hex: str,
              env: Dict[str, str]) -> int:
        return self._request(("spawn", {
            "connect_host": connect_host, "port": port,
            "authkey_hex": authkey_hex, "env": env,
        }))

    def poll(self, pid: int) -> Optional[int]:
        return self._request(("poll", pid))

    def kill(self, pid: int, grace_s: float = 5.0) -> None:
        self._request(("kill", pid, grace_s))

    def close(self) -> None:
        try:
            with self._lock:
                rpc.send_obj(self._sock, ("bye",))
        except (OSError, ValueError):
            pass
        try:
            self._sock.close()
        except OSError:
            pass


class _RemoteProcHandle:
    """Popen-shaped handle over an agent-spawned child, so ProcessActor's
    startup/teardown code is identical for local and remote actors."""

    def __init__(self, client: AgentClient, pid: int):
        self._client = client
        self.pid = pid
        self.returncode: Optional[int] = None

    # Transient-transport retry budget: one slow/dropped agent RPC must
    # not read as "child died" (that verdict triggers a full elastic
    # respawn upstream, parallel/strategies.py).
    _POLL_RETRIES = 3
    _POLL_BACKOFF_S = 0.2

    def poll(self) -> Optional[int]:
        if self.returncode is not None:
            return self.returncode
        for attempt in range(self._POLL_RETRIES):
            try:
                self.returncode = self._client.poll(self.pid)
                return self.returncode
            except AgentError:
                # A structured agent REPLY (unknown pid): deterministic —
                # the child is genuinely gone; retrying can't change it.
                self.returncode = -1
                return self.returncode
            except (ConnectionError, OSError, TimeoutError):
                # Transport hiccup: back off and re-ask before declaring
                # death.
                if attempt + 1 < self._POLL_RETRIES:
                    time.sleep(self._POLL_BACKOFF_S * (attempt + 1))
        self.returncode = -1  # agent unreachable after retries
        return self.returncode

    def terminate(self) -> None:
        try:
            self._client.kill(self.pid)
        except (AgentError, ConnectionError, OSError):
            pass
        if self.returncode is None:
            self.returncode = -15

    kill = terminate

    def wait(self, timeout: Optional[float] = None) -> int:
        # kill() on the agent already waited through the grace period.
        code = self.poll()
        return code if code is not None else 0


def agent_launcher(client: AgentClient):
    """Adapt an AgentClient into a ProcessActor ``launcher``."""

    def launch(connect_host: str, port: int, authkey_hex: str,
               env: Dict[str, str], name: str) -> _RemoteProcHandle:
        pid = client.spawn(connect_host, port, authkey_hex, env)
        return _RemoteProcHandle(client, pid)

    return launch


def main(argv=None) -> None:
    parser = argparse.ArgumentParser(
        description="ray_lightning_tpu node agent (run one per TPU host)")
    parser.add_argument("--host", default="0.0.0.0")
    parser.add_argument("--port", type=int, default=DEFAULT_AGENT_PORT)
    parser.add_argument("--token", default=None,
                        help="shared secret (default: $RLT_AGENT_TOKEN)")
    args = parser.parse_args(argv)
    agent = NodeAgent(host=args.host, port=args.port, token=args.token)
    print(f"[rlt-agent] listening on {args.host}:{agent.port}", flush=True)
    try:
        agent.serve_forever()
    except KeyboardInterrupt:
        agent.shutdown()


if __name__ == "__main__":
    main()
