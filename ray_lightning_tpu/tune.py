"""Tune integration: report/checkpoint callbacks + resource factory.

≙ ``/root/reference/ray_lightning/tune.py`` (L6 of the layer map).  The
callbacks travel pickled to worker rank 0 and fire inside the fit loop;
metric/checkpoint payloads cross back to the driver as **thunks** on the
distributed queue, because reporting only works inside the trial session
process (reference ``tune.py:130-134`` and SURVEY §3.3).

Backend resolution mirrors the reference's ``TUNE_INSTALLED`` guard
(``tune.py:13-27``): if real Ray Tune is importable, thunks call
``ray.tune.report``; otherwise they report into this package's native
trial session (:mod:`ray_lightning_tpu.tuning`).  Either way the worker
side is identical — only the driver-side thunk body differs.
"""

from __future__ import annotations

import os
from typing import Any, Dict, List, Optional, Union

from ray_lightning_tpu.core.callbacks import Callback
from ray_lightning_tpu.session import get_session, is_session_enabled
from ray_lightning_tpu.utils.state_stream import to_state_stream

try:  # real Ray Tune, if present (reference tune.py:13-27)
    from ray import tune as _ray_tune  # type: ignore

    RAY_TUNE_INSTALLED = True
except ImportError:
    _ray_tune = None
    RAY_TUNE_INSTALLED = False

__all__ = [
    "TuneReportCallback",
    "TuneReportCheckpointCallback",
    "get_tune_resources",
    "RAY_TUNE_INSTALLED",
]


# ---------------------------------------------------------------------------
# Driver-side report/checkpoint executors (module-level: queue thunks
# capture these by reference and run them in the trial-session process)
# ---------------------------------------------------------------------------

def _driver_report(metrics: Dict[str, float]) -> None:
    if RAY_TUNE_INSTALLED and _ray_tune is not None:
        _ray_tune.report(metrics)
        return
    from ray_lightning_tpu.tuning.session import report

    report(**metrics)


def _driver_write_checkpoint(
    payload: bytes, step: int, filename: str,
    metrics: Optional[Dict[str, float]] = None,
) -> None:
    """≙ _TuneCheckpointCallback._handle driver half (reference
    ``tune.py:169-178``): write bytes into the trial's checkpoint dir.

    Under real Ray Tune, metrics+checkpoint MUST travel in ONE
    ``tune.report`` call — separate calls would break the
    checkpoint↔metric association and double-count training_iteration.
    """
    if RAY_TUNE_INSTALLED and _ray_tune is not None:
        import tempfile

        with tempfile.TemporaryDirectory() as tmp:
            path = os.path.join(tmp, filename)
            with open(path, "wb") as f:
                f.write(payload)
            _ray_tune.report(
                metrics or {},
                checkpoint=_ray_tune.Checkpoint.from_directory(tmp),
            )
        return
    from ray_lightning_tpu.tuning.session import (
        checkpoint_dir, get_trial_session,
    )

    path = os.path.join(checkpoint_dir(step), filename)
    with open(path, "wb") as f:
        f.write(payload)
    # Record the exact FILE for PBT's exploit step: a later trial handed
    # this path via restore_path can feed it straight to
    # ``Trainer(resume_from_checkpoint=...)``.
    get_trial_session().note_checkpoint(path)
    if metrics:
        _driver_report(metrics)


class TuneReportCallback(Callback):
    """Report trainer metrics to the tuner on a Lightning-style hook.

    ≙ reference ``TuneReportCallback`` (``tune.py:59-134``): ``metrics``
    maps reported-name → trainer metric name (list/str = identity map);
    ``on`` picks the firing hook (default ``validation_end``).  Worker
    rank 0 ships ``lambda: report(**got)`` through the queue; running
    outside any remote session (LocalStrategy), it reports directly.
    """

    _VALID_ON = ("validation_end", "train_epoch_end", "batch_end")

    def __init__(
        self,
        metrics: Union[None, str, List[str], Dict[str, str]] = None,
        on: str = "validation_end",
    ):
        if on not in self._VALID_ON:
            # ≙ the reference's TuneCallback hook validation — a typo'd
            # hook must fail loudly, not silently never report.
            raise ValueError(
                f"on={on!r} is not supported; choose from {self._VALID_ON}"
            )
        if isinstance(metrics, str):
            metrics = [metrics]
        self._metrics = metrics
        self._on = on

    # -- metric extraction (≙ reference _get_report_dict, tune.py:110-128) --
    def _get_report_dict(self, trainer) -> Optional[Dict[str, float]]:
        source = trainer.callback_metrics
        if not source:
            return None
        if self._metrics is None:
            return {k: float(v) for k, v in source.items()}
        if isinstance(self._metrics, list):
            pairs = {m: m for m in self._metrics}
        else:
            pairs = dict(self._metrics)
        out: Dict[str, float] = {}
        for report_as, metric_name in pairs.items():
            if metric_name in source:
                out[report_as] = float(source[metric_name])
        return out or None

    def _handle(self, trainer, module) -> None:
        if not trainer.is_global_zero:
            return
        got = self._get_report_dict(trainer)
        if got is None:
            return
        if is_session_enabled() and get_session().queue is not None:
            # ═══ queue boundary: executes in the trial driver ═══
            get_session().put_queue(lambda: _driver_report(got))
        else:
            _driver_report(got)

    # -- hook dispatch -------------------------------------------------------
    def on_validation_epoch_end(self, trainer, module) -> None:
        if self._on == "validation_end":
            self._handle(trainer, module)

    def on_train_epoch_end(self, trainer, module) -> None:
        if self._on == "train_epoch_end":
            self._handle(trainer, module)

    def on_train_batch_end(self, trainer, module, logs, batch_idx) -> None:
        if self._on == "batch_end":
            self._handle(trainer, module)


class _TuneCheckpointCallback(Callback):
    """Ship a full trainer checkpoint through the queue to the trial dir.

    ≙ reference ``_TuneCheckpointCallback`` (``tune.py:136-178``): worker
    dumps the checkpoint payload to bytes, driver writes them under
    ``checkpoint_dir(step)``.
    """

    _VALID_ON = ("validation_end", "train_epoch_end")

    def __init__(self, filename: str = "checkpoint", on: str = "validation_end"):
        if on not in self._VALID_ON:
            raise ValueError(
                f"on={on!r} is not supported; choose from {self._VALID_ON}"
            )
        self._filename = filename
        self._on = on

    def _payload(self, trainer) -> Optional[bytes]:
        """Collective gather on every rank; serialization on rank 0 only."""
        payload_dict = trainer.checkpoint_payload()
        if not trainer.is_global_zero:
            return None
        return to_state_stream(payload_dict)

    def _handle(self, trainer, module) -> None:
        payload = self._payload(trainer)
        if payload is None:
            return
        step = trainer.global_step
        filename = self._filename
        if is_session_enabled() and get_session().queue is not None:
            get_session().put_queue(
                lambda: _driver_write_checkpoint(payload, step, filename)
            )
        else:
            _driver_write_checkpoint(payload, step, filename)

    def on_validation_epoch_end(self, trainer, module) -> None:
        if self._on == "validation_end":
            self._handle(trainer, module)

    def on_train_epoch_end(self, trainer, module) -> None:
        if self._on == "train_epoch_end":
            self._handle(trainer, module)


class TuneReportCheckpointCallback(Callback):
    """Checkpoint + report in ONE tuner transaction (≙ reference
    ``TuneReportCheckpointCallback``, ``tune.py:180-236``): the metric and
    the checkpoint it scores travel in a single thunk/report so the tuner
    associates them (and training_iteration counts once per epoch)."""

    def __init__(
        self,
        metrics: Union[None, str, List[str], Dict[str, str]] = None,
        filename: str = "checkpoint",
        on: str = "validation_end",
    ):
        self._checkpoint = _TuneCheckpointCallback(filename, on)
        self._report = TuneReportCallback(metrics, on)
        self._on = on

    def _handle(self, trainer, module) -> None:
        payload = self._checkpoint._payload(trainer)  # collective
        if payload is None:
            return  # non-zero rank
        got = self._report._get_report_dict(trainer)
        step = trainer.global_step
        filename = self._checkpoint._filename

        def thunk(payload=payload, step=step, filename=filename, got=got):
            _driver_write_checkpoint(payload, step, filename, metrics=got)

        if is_session_enabled() and get_session().queue is not None:
            get_session().put_queue(thunk)
        else:
            thunk()

    def on_validation_epoch_end(self, trainer, module) -> None:
        if self._on == "validation_end":
            self._handle(trainer, module)

    def on_train_epoch_end(self, trainer, module) -> None:
        if self._on == "train_epoch_end":
            self._handle(trainer, module)


def get_tune_resources(
    num_workers: int = 1,
    num_cpus_per_worker: int = 1,
    use_tpu: bool = True,
    tpu_chips_per_worker: int = 4,
) -> Any:
    """Per-trial resource request (≙ reference ``get_tune_resources``,
    ``tune.py:32-56``): one head bundle (the trial driver) + N worker
    bundles.  Returns a ``PlacementGroupFactory`` under real Ray Tune,
    else a plain dict the native tuner records."""
    head = {"CPU": 1}
    worker = {"CPU": num_cpus_per_worker}
    if use_tpu:
        worker["TPU"] = tpu_chips_per_worker
    bundles = [head] + [dict(worker) for _ in range(num_workers)]
    if RAY_TUNE_INSTALLED and _ray_tune is not None:
        from ray.tune import PlacementGroupFactory  # type: ignore

        return PlacementGroupFactory(bundles, strategy="PACK")
    return {"bundles": bundles, "strategy": "PACK"}
