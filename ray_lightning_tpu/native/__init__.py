"""Native runtime bindings (C++ via ctypes) with pure-Python fallback.

The reference's runtime substrate is Ray core's C++ (raylet + plasma
object store, SURVEY §2.2); this package is the TPU build's native layer
for the host-side data path: checksummed write-once/read-many payload
segments (see ``src/rlt_native.cc`` for the on-disk format) plus fast
CRC32C.  The library is compiled on first use with the system ``g++``
(no pip deps) and cached next to the source; when no compiler is
available every entry point transparently falls back to pure Python
writing the *identical* format, so the control plane never hard-depends
on the toolchain (the ``Unavailable`` degradation pattern, reference
``util.py:40-44``).
"""

from __future__ import annotations

import ctypes
import os
import struct
import subprocess
import threading
import zlib
from typing import Optional, Tuple

__all__ = [
    "native_available",
    "crc32c",
    "crc32c_is_hw",
    "write_segment",
    "read_segment",
    "segment_len",
    "SEGMENT_HEADER_SIZE",
]

_MAGIC = b"RLTSEG1\0"
_ALGO_CRC32C = 1
_ALGO_ZLIB = 2
SEGMENT_HEADER_SIZE = 32
_HEADER = struct.Struct("<8sQII8x")

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_lib_tried = False


def _src_dir() -> str:
    return os.path.join(os.path.dirname(__file__), "src")


def _so_path() -> str:
    return os.path.join(_src_dir(), "librlt_native.so")


def _build() -> Optional[str]:
    """Compile the library if missing/stale; None when impossible."""
    src = os.path.join(_src_dir(), "rlt_native.cc")
    out = _so_path()
    if not os.path.exists(src):
        return None
    if (os.path.exists(out)
            and os.path.getmtime(out) >= os.path.getmtime(src)):
        return out
    tmp = out + f".tmp{os.getpid()}"
    cmd = ["g++", "-O3", "-shared", "-fPIC", "-std=c++17",
           "-o", tmp, src]
    try:
        subprocess.run(
            cmd, check=True, capture_output=True, timeout=120,
        )
        os.replace(tmp, out)  # atomic: concurrent builders race safely
        return out
    except (OSError, subprocess.SubprocessError):
        try:
            os.unlink(tmp)
        except OSError:
            pass
        return None


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _lib_tried
    if _lib is not None or _lib_tried:
        return _lib
    with _lock:
        if _lib is not None or _lib_tried:
            return _lib
        _lib_tried = True
        if os.environ.get("RLT_DISABLE_NATIVE"):
            return None
        path = _build()
        if path is None:
            return None
        try:
            lib = ctypes.CDLL(path)
        except OSError:
            return None
        lib.rlt_crc32c.restype = ctypes.c_uint32
        lib.rlt_crc32c.argtypes = [
            ctypes.c_char_p, ctypes.c_uint64, ctypes.c_uint32]
        lib.rlt_crc32c_is_hw.restype = ctypes.c_int
        lib.rlt_write_segment.restype = ctypes.c_int
        lib.rlt_write_segment.argtypes = [
            ctypes.c_char_p, ctypes.c_char_p, ctypes.c_uint64,
            ctypes.POINTER(ctypes.c_uint32)]
        lib.rlt_segment_len.restype = ctypes.c_int64
        lib.rlt_segment_len.argtypes = [ctypes.c_char_p]
        lib.rlt_read_segment.restype = ctypes.c_int
        lib.rlt_read_segment.argtypes = [
            ctypes.c_char_p, ctypes.c_char_p, ctypes.c_uint64,
            ctypes.c_int]
        _lib = lib
        return _lib


def native_available() -> bool:
    return _load() is not None


def crc32c_is_hw() -> bool:
    lib = _load()
    return bool(lib and lib.rlt_crc32c_is_hw())


def crc32c(data: bytes, crc: int = 0) -> int:
    """CRC32C (Castagnoli).  Hardware/native when the C++ library loads,
    else the table-driven Python fallback — same polynomial, same chaining
    semantics, so checksums are portable across the two paths."""
    lib = _load()
    if lib is None:
        return _crc32c_py(data, crc)
    return lib.rlt_crc32c(data, len(data), crc)


def _checksum(data: bytes) -> Tuple[int, int]:
    lib = _load()
    if lib is not None:
        return lib.rlt_crc32c(data, len(data), 0), _ALGO_CRC32C
    return zlib.crc32(data) & 0xFFFFFFFF, _ALGO_ZLIB


class SegmentError(RuntimeError):
    """Corrupt, truncated, or missing payload segment."""


def write_segment(path: str, payload: bytes) -> None:
    """Write-once segment create (fails if ``path`` exists)."""
    lib = _load()
    if lib is not None:
        crc = ctypes.c_uint32(0)
        rc = lib.rlt_write_segment(
            path.encode(), payload, len(payload), ctypes.byref(crc))
        if rc != 0:
            raise SegmentError(
                f"native write_segment({path!r}) failed: {os.strerror(-rc)}")
        return
    checksum, algo = _checksum(payload)
    header = _HEADER.pack(_MAGIC, len(payload), checksum, algo)
    fd = os.open(path, os.O_WRONLY | os.O_CREAT | os.O_EXCL, 0o600)
    try:
        for buf in (header, payload):
            view = memoryview(buf)
            while view:  # os.write may be partial (~2 GiB Linux cap)
                view = view[os.write(fd, view):]
    finally:
        os.close(fd)


def _read_header(path: str) -> Tuple[int, int, int]:
    """(payload_len, checksum, algo) — length clamped against the file
    size so a corrupted header can't drive a huge allocation."""
    file_len = os.stat(path).st_size
    with open(path, "rb") as f:
        raw = f.read(SEGMENT_HEADER_SIZE)
    if len(raw) < SEGMENT_HEADER_SIZE:
        raise SegmentError(f"segment {path!r}: truncated header")
    magic, length, checksum, algo = _HEADER.unpack(raw)
    if magic != _MAGIC:
        raise SegmentError(f"segment {path!r}: bad magic")
    if length > file_len - SEGMENT_HEADER_SIZE:
        raise SegmentError(
            f"segment {path!r}: header claims {length} payload bytes but "
            f"the file holds {file_len - SEGMENT_HEADER_SIZE}"
        )
    return length, checksum, algo


def segment_len(path: str) -> int:
    return _read_header(path)[0]


def read_segment(path: str, verify: bool = True) -> bytes:
    """Read + (optionally) checksum-verify a segment's payload.

    Native CRC32C segments are verified in C without the GIL; fallback
    (zlib-tagged) segments are verified in Python — each side can read
    the other's files, so a native driver interoperates with a
    fallback-only worker and vice versa.
    """
    length, checksum, algo = _read_header(path)
    lib = _load()
    if lib is not None:
        buf = ctypes.create_string_buffer(length)
        rc = lib.rlt_read_segment(
            path.encode(), buf, length, 1 if verify else 0)
        if rc != 0:
            raise SegmentError(
                f"read_segment({path!r}) failed: {os.strerror(-rc)}")
        payload = buf.raw[:length]
        # Native verify covers algo-1 only; cross-check zlib-tagged files.
        if (verify and algo == _ALGO_ZLIB
                and (zlib.crc32(payload) & 0xFFFFFFFF) != checksum):
            raise SegmentError(f"segment {path!r}: checksum mismatch")
        return payload

    with open(path, "rb") as f:
        f.seek(SEGMENT_HEADER_SIZE)
        payload = f.read(length)
    if len(payload) != length:
        raise SegmentError(f"segment {path!r}: truncated payload")
    if verify:
        if algo == _ALGO_ZLIB:
            ok = (zlib.crc32(payload) & 0xFFFFFFFF) == checksum
        else:
            # CRC32C without the native lib: pure-Python table (slow but
            # correct; only hit when driver had the lib and worker lacks it).
            ok = _crc32c_py(payload) == checksum
        if not ok:
            raise SegmentError(f"segment {path!r}: checksum mismatch")
    return payload


_py_table = None


def _crc32c_py(data: bytes, crc: int = 0) -> int:
    """Software CRC32C with the same seed-chaining contract as the native
    entry point: ``crc32c(b, crc32c(a)) == crc32c(a + b)``."""
    global _py_table
    if _py_table is None:
        poly = 0x82F63B78
        table = []
        for i in range(256):
            c = i
            for _ in range(8):
                c = (c >> 1) ^ (poly if c & 1 else 0)
            table.append(c)
        _py_table = table
    c = crc ^ 0xFFFFFFFF
    for b in data:
        c = (c >> 8) ^ _py_table[(c ^ b) & 0xFF]
    return c ^ 0xFFFFFFFF
