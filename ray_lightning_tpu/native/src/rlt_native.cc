// Native runtime core for ray_lightning_tpu.
//
// The reference's control plane rides on Ray core's C++ runtime (raylet +
// plasma shared-memory object store); this library is the TPU build's
// native equivalent for the host-side data path:
//
//   * CRC32C (Castagnoli) — hardware-accelerated on SSE4.2, slicing-by-8
//     in software — for object-store and state-stream integrity.
//   * Segment I/O — write-once / read-many payload segments under
//     /dev/shm (tmpfs ⇒ page-cache speed), with the checksum verified on
//     read.  Calls run without the Python GIL (plain C ABI via ctypes),
//     so multi-actor reads overlap with driver work.
//
// Segment layout (little-endian, 32-byte header):
//   [0..8)   magic   "RLTSEG1\0"
//   [8..16)  payload length (u64)
//   [16..20) checksum (u32)
//   [20..24) checksum algo (u32): 1 = CRC32C, 2 = zlib CRC32 (py fallback)
//   [24..32) reserved
//   [32..)   payload
//
// The Python wrapper (ray_lightning_tpu/native/__init__.py) writes the
// identical format in pure Python when this library is unavailable.

#include <cerrno>
#include <cstdint>
#include <cstring>

#include <fcntl.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

namespace {

constexpr uint64_t kMagic = 0x003147'45'53'54'4c'52ULL;  // "RLTSEG1\0" LE
constexpr uint32_t kAlgoCrc32c = 1;
constexpr uint64_t kHeaderSize = 32;

struct Header {
  uint64_t magic;
  uint64_t payload_len;
  uint32_t checksum;
  uint32_t algo;
  uint64_t reserved;
};
static_assert(sizeof(Header) == kHeaderSize, "header must be 32 bytes");

// ---------------------------------------------------------------------------
// CRC32C
// ---------------------------------------------------------------------------

uint32_t g_tables[8][256];
bool g_tables_ready = false;

void init_tables() {
  constexpr uint32_t poly = 0x82f63b78u;  // reflected Castagnoli
  for (uint32_t i = 0; i < 256; ++i) {
    uint32_t crc = i;
    for (int j = 0; j < 8; ++j)
      crc = (crc >> 1) ^ ((crc & 1) ? poly : 0);
    g_tables[0][i] = crc;
  }
  for (uint32_t i = 0; i < 256; ++i)
    for (int t = 1; t < 8; ++t)
      g_tables[t][i] =
          (g_tables[t - 1][i] >> 8) ^ g_tables[0][g_tables[t - 1][i] & 0xff];
  g_tables_ready = true;
}

uint32_t crc32c_sw(const uint8_t* p, uint64_t len, uint32_t crc) {
  if (!g_tables_ready) init_tables();
  crc = ~crc;
  while (len >= 8) {
    uint64_t word;
    std::memcpy(&word, p, 8);
    word ^= crc;
    crc = g_tables[7][word & 0xff] ^ g_tables[6][(word >> 8) & 0xff] ^
          g_tables[5][(word >> 16) & 0xff] ^ g_tables[4][(word >> 24) & 0xff] ^
          g_tables[3][(word >> 32) & 0xff] ^ g_tables[2][(word >> 40) & 0xff] ^
          g_tables[1][(word >> 48) & 0xff] ^ g_tables[0][word >> 56];
    p += 8;
    len -= 8;
  }
  while (len--) crc = (crc >> 8) ^ g_tables[0][(crc ^ *p++) & 0xff];
  return ~crc;
}

#if defined(__x86_64__)
__attribute__((target("sse4.2"))) uint32_t crc32c_hw(const uint8_t* p,
                                                     uint64_t len,
                                                     uint32_t crc) {
  crc = ~crc;
  while (len >= 8) {
    uint64_t word;
    std::memcpy(&word, p, 8);
    crc = static_cast<uint32_t>(
        __builtin_ia32_crc32di(static_cast<uint64_t>(crc), word));
    p += 8;
    len -= 8;
  }
  while (len >= 1) {
    crc = __builtin_ia32_crc32qi(crc, *p++);
    --len;
  }
  return ~crc;
}

bool have_sse42() {
  static const bool ok = __builtin_cpu_supports("sse4.2");
  return ok;
}
#endif

uint32_t crc32c_dispatch(const void* data, uint64_t len, uint32_t crc) {
  const uint8_t* p = static_cast<const uint8_t*>(data);
#if defined(__x86_64__)
  if (have_sse42()) return crc32c_hw(p, len, crc);
#endif
  return crc32c_sw(p, len, crc);
}

int write_all(int fd, const void* buf, uint64_t len) {
  const uint8_t* p = static_cast<const uint8_t*>(buf);
  while (len) {
    ssize_t n = ::write(fd, p, len);
    if (n < 0) {
      if (errno == EINTR) continue;
      return -errno;
    }
    p += n;
    len -= static_cast<uint64_t>(n);
  }
  return 0;
}

}  // namespace

extern "C" {

// Incremental CRC32C; pass 0 as the initial crc.
uint32_t rlt_crc32c(const void* data, uint64_t len, uint32_t crc) {
  return crc32c_dispatch(data, len, crc);
}

// 1 when the hardware CRC path is active (introspection/tests).
int rlt_crc32c_is_hw(void) {
#if defined(__x86_64__)
  return have_sse42() ? 1 : 0;
#else
  return 0;
#endif
}

// Write a complete segment file.  Returns 0 on success, -errno on failure.
// On success *crc_out holds the payload CRC32C.
int rlt_write_segment(const char* path, const void* data, uint64_t len,
                      uint32_t* crc_out) {
  Header hdr;
  hdr.magic = kMagic;
  hdr.payload_len = len;
  hdr.checksum = crc32c_dispatch(data, len, 0);
  hdr.algo = kAlgoCrc32c;
  hdr.reserved = 0;

  int fd = ::open(path, O_WRONLY | O_CREAT | O_EXCL, 0600);
  if (fd < 0) return -errno;
  int rc = write_all(fd, &hdr, sizeof(hdr));
  if (rc == 0) rc = write_all(fd, data, len);
  if (::close(fd) != 0 && rc == 0) rc = -errno;
  if (rc != 0) ::unlink(path);
  if (rc == 0 && crc_out) *crc_out = hdr.checksum;
  return rc;
}

// Payload length of a segment, or -errno / -EBADMSG for a bad header.
int64_t rlt_segment_len(const char* path) {
  int fd = ::open(path, O_RDONLY);
  if (fd < 0) return -errno;
  Header hdr;
  ssize_t n = ::read(fd, &hdr, sizeof(hdr));
  ::close(fd);
  if (n != static_cast<ssize_t>(sizeof(hdr)) || hdr.magic != kMagic)
    return -EBADMSG;
  return static_cast<int64_t>(hdr.payload_len);
}

// Read a segment's payload into out (capacity out_len).  verify != 0
// checks the stored CRC32C (only for algo 1 segments; algo 2 segments are
// verified by the Python side).  Returns 0, -errno, -EBADMSG on a corrupt
// header/checksum, or -ENOSPC when out_len is too small.
int rlt_read_segment(const char* path, void* out, uint64_t out_len,
                     int verify) {
  int fd = ::open(path, O_RDONLY);
  if (fd < 0) return -errno;

  struct stat st;
  if (::fstat(fd, &st) != 0) {
    int e = -errno;
    ::close(fd);
    return e;
  }
  uint64_t file_len = static_cast<uint64_t>(st.st_size);
  if (file_len < kHeaderSize) {
    ::close(fd);
    return -EBADMSG;
  }
  void* mapped = ::mmap(nullptr, file_len, PROT_READ, MAP_SHARED, fd, 0);
  ::close(fd);
  if (mapped == MAP_FAILED) return -errno;

  const Header* hdr = static_cast<const Header*>(mapped);
  const uint8_t* payload = static_cast<const uint8_t*>(mapped) + kHeaderSize;
  int rc = 0;
  if (hdr->magic != kMagic || hdr->payload_len > file_len - kHeaderSize) {
    rc = -EBADMSG;
  } else if (hdr->payload_len > out_len) {
    rc = -ENOSPC;
  } else {
    if (verify && hdr->algo == kAlgoCrc32c &&
        crc32c_dispatch(payload, hdr->payload_len, 0) != hdr->checksum) {
      rc = -EBADMSG;
    } else {
      std::memcpy(out, payload, hdr->payload_len);
    }
  }
  ::munmap(mapped, file_len);
  return rc;
}

}  // extern "C"
