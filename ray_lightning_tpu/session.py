"""Per-worker session context.

TPU-native analogue of the reference's worker session singleton
(``/root/reference/ray_lightning/session.py:1-63``).  Each worker process
(one per TPU host) holds a process-global session exposing:

* ``rank`` — the worker/host rank assigned by the driver;
* ``queue`` — a handle to the driver-side distributed queue, used by
  callbacks running deep inside the fit loop (e.g. Tune report callbacks) to
  ship thunks/metrics back to the driver mid-training;
* TPU extras the reference had no need for: the ``mesh`` the host
  participates in and its local device list.

The session is deliberately a module-level singleton (reference
``session.py:27-36``): callbacks fire many frames below the strategy and
cannot thread a context object through Lightning-shaped hook signatures.
"""

from __future__ import annotations

import threading
from typing import Any, Optional

__all__ = [
    "TpuTrainingSession",
    "init_session",
    "get_session",
    "shutdown_session",
    "is_session_enabled",
    "get_actor_rank",
    "put_queue",
]


class TpuTrainingSession:
    """Worker-side context for one training run on one host actor."""

    def __init__(
        self,
        rank: int,
        queue: Optional[Any] = None,
        num_workers: int = 1,
        local_devices: Optional[list] = None,
        mesh: Optional[Any] = None,
    ):
        self.rank = rank
        self.queue = queue
        self.num_workers = num_workers
        self.local_devices = local_devices or []
        self.mesh = mesh

    def put_queue(self, item: Any) -> None:
        """Ship ``item`` (often a cloudpickled thunk) to the driver.

        Reference parity: ``session.py:20-24`` — items are drained by the
        driver's result pump (:func:`ray_lightning_tpu.util.process_results`)
        and, if callable, executed in driver context.
        """
        if self.queue is None:
            raise ValueError(
                "No queue is attached to this session. A queue is created "
                "only when the driver enables streaming (Tune session or "
                "metrics streaming)."
            )
        self.queue.put(item)


_session_lock = threading.Lock()
_session: Optional[TpuTrainingSession] = None


def init_session(*args, **kwargs) -> TpuTrainingSession:
    """Install the process-global session (reference ``session.py:30-36``)."""
    global _session
    with _session_lock:
        if _session is not None:
            raise ValueError(
                "A TpuTrainingSession is already active in this process. "
                "Call shutdown_session() first."
            )
        _session = TpuTrainingSession(*args, **kwargs)
        return _session


def get_session() -> TpuTrainingSession:
    """Reference ``session.py:39-53``."""
    if _session is None:
        raise ValueError(
            "No TpuTrainingSession is active. init_session() is called by "
            "the strategy on each worker before the fit loop starts."
        )
    return _session


def is_session_enabled() -> bool:
    return _session is not None


def shutdown_session() -> None:
    global _session
    with _session_lock:
        _session = None


def get_actor_rank() -> int:
    """Rank of the calling worker (reference ``session.py:56-58``)."""
    return get_session().rank


def put_queue(item: Any) -> None:
    """Module-level convenience (reference ``session.py:61-63``)."""
    get_session().put_queue(item)
