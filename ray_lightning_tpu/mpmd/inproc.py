"""In-process MPMD pipeline: every stage worker a thread, one
interpreter.

The mesh-of-meshes execution plane without the actor plane: worker
``p`` gets its own device subset (``jax.devices()`` sliced into
disjoint groups), its own :class:`~.stage.StageRunner` with separately
compiled programs, and a :class:`~.transfer.LocalChannel` transport
along the worker ring.  Because the runners are transport-agnostic
this is the SAME code path the actor plane drives — only the wire
differs — which makes it the fast parity harness for tests and the
``dryrun_multichip`` mpmd flavor (4 virtual CPU devices → 2 stages ×
2-device meshes, no subprocess spawn).
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Dict, List, Optional

from ray_lightning_tpu.mpmd.plan import MpmdSpec, StagePlan
from ray_lightning_tpu.mpmd.stage import StageRunner
from ray_lightning_tpu.mpmd.transfer import (
    LocalChannel,
    Mailbox,
    WireCodec,
    WireDtypeConfig,
)

__all__ = ["split_micro_batches", "run_inproc_pipeline_fit"]


def split_micro_batches(batch: Any, n_micro: int) -> List[Any]:
    """Row-split one full batch pytree into ``n_micro`` equal
    micro-batches (leading axis; ragged counts are a loud error — a
    silently smaller last micro-batch would break mean-of-means grad
    parity)."""
    import jax

    leaves = jax.tree_util.tree_leaves(batch)
    if not leaves:
        raise ValueError("empty batch")
    rows = leaves[0].shape[0]
    if rows % n_micro:
        raise ValueError(
            f"batch of {rows} rows not divisible into {n_micro} "
            "micro-batches"
        )
    mb = rows // n_micro
    return [
        jax.tree_util.tree_map(
            lambda a, i=i: a[i * mb:(i + 1) * mb], batch
        )
        for i in range(n_micro)
    ]


def run_inproc_pipeline_fit(
    spec: MpmdSpec,
    full_params: Any,
    tx_factory: Callable[[], Any],
    batches: Callable[[int], Any],
    steps: int,
    n_workers: int,
    n_micro: int,
    schedule: str = "1f1b",
    interleave: int = 1,
    device_groups: Optional[List[list]] = None,
    recv_timeout_s: float = 120.0,
    trace_dir: Optional[str] = None,
    wire_dtype: Any = None,
) -> Dict[str, Any]:
    """Run a full MPMD fit with stage workers as threads; returns
    per-step losses (loss worker), per-worker steady-state stats, and
    the reassembled final params."""
    import jax

    plan = StagePlan.split(spec.n_layers, n_workers * interleave)
    if device_groups is not None and len(device_groups) != n_workers:
        raise ValueError(
            f"{len(device_groups)} device groups for {n_workers} workers"
        )

    meshes: List[Any] = []
    for p in range(n_workers):
        if device_groups is None:
            meshes.append(None)
        else:
            import numpy as np
            from jax.sharding import Mesh

            meshes.append(
                Mesh(np.asarray(device_groups[p]), ("data",))
            )

    wire_cfg = WireDtypeConfig.coerce(wire_dtype)

    def _codec() -> Optional[WireCodec]:
        # One codec PER channel: int8 error-feedback residuals live on
        # the sender side, keyed by micro-batch slot — sharing a codec
        # across channels would cross-pollinate residuals.
        return WireCodec(wire_cfg) if wire_cfg.active else None

    mailboxes = [Mailbox() for _ in range(n_workers)]
    runners: List[StageRunner] = []
    for p in range(n_workers):
        runners.append(StageRunner(
            spec, plan, p, n_workers, schedule, n_micro, tx_factory(),
            interleave=interleave,
            mesh=meshes[p],
            mailbox=mailboxes[p],
            send_next=LocalChannel(
                mailboxes[(p + 1) % n_workers], codec=_codec()
            ),
            send_prev=LocalChannel(
                mailboxes[(p - 1) % n_workers], codec=_codec()
            ),
            recv_timeout_s=recv_timeout_s,
            trace_dir=trace_dir,
        ))
        runners[p].init_state(full_params)

    # Pre-split every step's micro-batches once so the embed and loss
    # workers consume identical rows without re-invoking the source.
    step_micro = {
        s: split_micro_batches(batches(s), n_micro) for s in range(steps)
    }

    errors: List[BaseException] = []
    lock = threading.Lock()

    def drive(runner: StageRunner) -> None:
        try:
            runner.run_fit(
                steps,
                lambda s: step_micro[s] if runner.needs_batches else None,
            )
        except BaseException as e:  # noqa: BLE001 - joined below
            with lock:
                errors.append(e)
            # Unblock peers waiting on this worker's sends.
            for box in mailboxes:
                box.fail(e)

    threads = [
        # daemon: a wedged stage must not pin the interpreter open after
        # the harness gives up joining (errors surface via `errors`).
        threading.Thread(
            target=drive, args=(r,), name=f"rlt-mpmd-w{r.worker}",
            daemon=True,
        )
        for r in runners
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    if errors:
        raise errors[0]

    # Reassemble: global stage g lives on worker g % P as chunk g // P.
    parts = [
        runners[g % n_workers].chunk_params_host()[g // n_workers]
        for g in range(plan.n_stages)
    ]
    loss_worker = runners[(plan.n_stages - 1) % n_workers]
    return {
        "losses": loss_worker.losses,
        "per_stage_stats": [r.fit_stats() for r in runners],
        "xfer": [r.xfer_stats() for r in runners],
        "step_summaries": [r.step_summaries for r in runners],
        "op_costs": [r.op_costs() for r in runners],
        "params": spec.assemble_params(parts, plan),
        "final_step": int(jax.device_get(loss_worker.state.step)),
    }
