"""Inter-stage transfer lane: activations/activation-grads between
stage actors.

Reuses the cluster control-plane primitives instead of inventing a new
wire: each stage process owns a :class:`~..cluster.queue.DriverQueue`
**inbox** (TCP server), and its neighbors hold plain
:class:`~..cluster.queue.QueueHandle` clients to it — the same
machinery that crosses DCN between hosts of a pod, with the
payload-scaled + chunked send timeouts ``cluster/queue.py`` grew for
exactly these multi-MB tensors.  Same-host stages skip the TCP payload
entirely: the tensor bytes go through the shared-memory
:class:`~..cluster.shm.SegmentStore` (write once to tmpfs, read at
page-cache speed) and only the segment path rides the queue.

**Double-buffered recv**: the inbox's pump thread drains the socket
into a keyed :class:`Mailbox` *continuously*, so micro-batch ``i+1``'s
activation streams in while the stage computes on ``i`` — a
``RECV(mb)`` instruction only blocks when the payload has not fully
arrived yet, and that blocked time is measured and reported as pipeline
bubble.

Wire item shape (schema-pinned in ``telemetry/schema.py`` as
``mpmd_xfer``)::

    {"type": "mpmd_xfer", "kind": "act"|"grad", "step": int, "mb": int,
     "data": bytes} | {..., "shm": path}
"""

from __future__ import annotations

import os
import threading
import time
from typing import Any, Dict, Optional, Tuple

import numpy as np

from ray_lightning_tpu.cluster import rpc
from ray_lightning_tpu.cluster.queue import DriverQueue, QueueHandle

__all__ = [
    "Mailbox",
    "StageInbox",
    "LocalChannel",
    "QueueChannel",
    "encode_tree",
    "decode_tree",
    "resolve_payload",
    "SHM_THRESHOLD_BYTES",
]

# Same-host payloads above this ride tmpfs segments instead of the TCP
# loopback (one copy + page cache vs kernel socket buffers both ways).
SHM_THRESHOLD_BYTES = 256 << 10


def encode_tree(tree: Any) -> bytes:
    """Host-ify and serialize an array pytree (activations are numpy by
    the time they leave a stage — ``StageRunner`` device_gets first)."""
    import jax

    host = jax.tree_util.tree_map(np.asarray, tree)
    return rpc.dumps(host)


def decode_tree(payload: bytes) -> Any:
    return rpc.loads(payload)


def resolve_payload(item: Dict[str, Any], unlink: bool = True) -> bytes:
    """Payload bytes of a ``data``/``shm`` wire item (the one-of pair
    every queue-plane tensor frame uses: MPMD activation transfers and
    the serve plane's KV handoffs alike).

    Segment lifetime is write-once/read-once, CONSUMER-owned: an
    ``shm`` payload is read and then unlinked here, so tmpfs is
    reclaimed the moment the bytes are out.  The producer's teardown
    sweep (``sweep_stale_segments``) is the crash backstop for frames
    that never reach a consumer — a producer killed ``-9`` mid-handoff
    leaves segments whose owner pid is gone, and the next sweep (actor
    kill, engine close, router failover) collects them.
    """
    shm_path = item.get("shm")
    if shm_path is None:
        return item["data"]
    from ray_lightning_tpu.cluster.shm import SegmentStore

    payload = SegmentStore.get(shm_path)
    if unlink:
        try:
            os.unlink(shm_path)
        except OSError:
            pass
    return payload


class Mailbox:
    """Keyed rendezvous: the pump thread ``deliver``s payloads as they
    arrive; ``recv`` blocks until its key shows up (and reports how long
    it actually waited — the bubble signal).

    ``deliver`` optionally files the frame's trace envelope alongside
    the payload; :meth:`recv_traced` surfaces it so a stage can adopt
    the step's distributed-trace identity from its upstream neighbor."""

    def __init__(self):
        self._cond = threading.Condition()
        self._items: Dict[Tuple, Any] = {}      # guarded by self._cond
        self._error: Optional[BaseException] = None  # guarded by self._cond

    def deliver(self, key: Tuple, payload: Any,
                trace: Optional[Dict[str, Any]] = None) -> None:
        with self._cond:
            self._items[key] = (payload, trace)
            self._cond.notify_all()

    def fail(self, exc: BaseException) -> None:
        """Poison the mailbox: every current and future recv raises."""
        with self._cond:
            self._error = exc
            self._cond.notify_all()

    def ready(self, key: Tuple) -> bool:
        with self._cond:
            return key in self._items

    def recv(self, key: Tuple, timeout: float = 120.0) -> Tuple[Any, float]:
        """Blocking receive → ``(payload, blocked_seconds)``."""
        payload, blocked, _ = self.recv_traced(key, timeout)
        return payload, blocked

    def recv_traced(
        self, key: Tuple, timeout: float = 120.0
    ) -> Tuple[Any, float, Optional[Dict[str, Any]]]:
        """Blocking receive → ``(payload, blocked_seconds, trace)``
        where ``trace`` is the sender's trace envelope (None on
        untraced frames)."""
        deadline = time.monotonic() + timeout
        t0 = time.perf_counter()
        with self._cond:
            while key not in self._items:
                if self._error is not None:
                    raise RuntimeError(
                        f"transfer lane failed while waiting for {key}"
                    ) from self._error
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise TimeoutError(
                        f"transfer recv timed out after {timeout:.0f}s "
                        f"waiting for {key} (peer stage dead or wedged?)"
                    )
                self._cond.wait(min(remaining, 1.0))
            payload, trace = self._items.pop(key)
        return payload, time.perf_counter() - t0, trace


class StageInbox:
    """A stage's receive plane: a DriverQueue server + the pump thread
    that files decoded payloads into the :class:`Mailbox` (this thread
    IS the comm/compute overlap)."""

    def __init__(self, host: str = "127.0.0.1",
                 advertise_host: Optional[str] = None):
        self.queue = DriverQueue(host=host, advertise_host=advertise_host)
        self.mailbox = Mailbox()
        self._closed = threading.Event()
        self._pump = threading.Thread(
            target=self._pump_loop, name="rlt-mpmd-inbox", daemon=True
        )
        self._pump.start()

    @property
    def handle(self) -> QueueHandle:
        return self.queue.handle

    def _pump_loop(self) -> None:
        import queue as _pyqueue

        while not self._closed.is_set():
            try:
                item = self.queue.get(timeout=0.2)
            except _pyqueue.Empty:
                continue
            except Exception as e:  # noqa: BLE001 - server torn down
                if not self._closed.is_set():
                    self.mailbox.fail(e)
                return
            try:
                self._file(item)
            except Exception as e:  # noqa: BLE001 - a malformed frame
                # must poison recvs loudly, not vanish in a daemon thread
                self.mailbox.fail(e)
                return

    def _file(self, item: Any) -> None:
        if not (isinstance(item, dict) and item.get("type") == "mpmd_xfer"):
            raise ValueError(f"unexpected item on stage inbox: {type(item)}")
        key = (
            item["kind"], int(item["step"]), int(item["mb"]),
            int(item.get("chunk", 0)),
        )
        self.mailbox.deliver(key, decode_tree(resolve_payload(item)),
                             trace=item.get("trace"))

    def close(self) -> None:
        self._closed.set()
        self.queue.shutdown()


class LocalChannel:
    """In-process channel straight into a :class:`Mailbox` — the
    transport of the threaded in-process pipeline (tests, the inline
    parity harness)."""

    def __init__(self, mailbox: Mailbox):
        self._mailbox = mailbox
        self.bytes_sent = 0

    def send(self, kind: str, step: int, mb: int, tree: Any,
             chunk: int = 0, trace=None) -> None:
        # Round-trip through the real encoder: in-process parity runs
        # must exercise the same host-ification the wire path does
        # (the trace envelope rides the same inject the wire uses).
        payload = encode_tree(tree)
        self.bytes_sent += len(payload)
        envelope: Dict[str, Any] = {}
        if trace is not None:
            from ray_lightning_tpu.telemetry.propagate import inject

            inject(envelope, trace)
        self._mailbox.deliver(
            (kind, step, mb, chunk), decode_tree(payload),
            trace=envelope.get("trace"),
        )


class QueueChannel:
    """Cross-process channel to a neighbor stage's :class:`StageInbox`.

    ``same_host=True`` routes payloads above ``shm_threshold`` through
    the segment store; the TCP frame then carries only the path.
    """

    def __init__(self, handle: QueueHandle, same_host: bool = False,
                 shm_threshold: int = SHM_THRESHOLD_BYTES):
        self._handle = handle
        self._store = None
        if same_host:
            from ray_lightning_tpu.cluster.shm import SegmentStore

            self._store = SegmentStore(prefix="rlt-seg")
        self._shm_threshold = shm_threshold
        self.bytes_sent = 0
        self.shm_sends = 0

    def send(self, kind: str, step: int, mb: int, tree: Any,
             chunk: int = 0, trace=None) -> None:
        payload = encode_tree(tree)
        self.bytes_sent += len(payload)
        item: Dict[str, Any] = {
            "type": "mpmd_xfer", "kind": kind, "step": int(step),
            "mb": int(mb), "chunk": int(chunk),
        }
        if trace is not None:
            from ray_lightning_tpu.telemetry.propagate import inject

            inject(item, trace)
        if self._store is not None and len(payload) >= self._shm_threshold:
            item["shm"] = self._store.put(payload)
            self.shm_sends += 1
        else:
            item["data"] = payload
        self._handle.put(item)

    def close(self) -> None:
        self._handle.close()
        if self._store is not None:
            self._store.unlink_all()
