"""Inter-stage transfer lane: activations/activation-grads between
stage actors.

Reuses the cluster control-plane primitives instead of inventing a new
wire: each stage process owns a :class:`~..cluster.queue.DriverQueue`
**inbox** (TCP server), and its neighbors hold plain
:class:`~..cluster.queue.QueueHandle` clients to it — the same
machinery that crosses DCN between hosts of a pod, with the
payload-scaled + chunked send timeouts ``cluster/queue.py`` grew for
exactly these multi-MB tensors.  Same-host stages skip the TCP payload
entirely: the tensor bytes go through the shared-memory
:class:`~..cluster.shm.SegmentStore` (write once to tmpfs, read at
page-cache speed) and only the segment path rides the queue.

**Double-buffered recv**: the inbox's pump thread drains the socket
into a keyed :class:`Mailbox` *continuously*, so micro-batch ``i+1``'s
activation streams in while the stage computes on ``i`` — a
``RECV(mb)`` instruction only blocks when the payload has not fully
arrived yet, and that blocked time is measured and reported as pipeline
bubble.

**Quantized wire** (``wire_dtype`` knob / ``RLT_MPMD_WIRE_DTYPE``): the
DCN segments between stages ship full-width f32 by default — the same
bandwidth waste grad_comm already fixed for the data-parallel wire.  A
:class:`WireCodec` on the send channel applies the block-scaled codec
host-side before serialization: activations in bf16 or int8,
activation-grads in int8 **with a sender-side error-feedback residual**
(keyed per (kind, mb, chunk, leaf) and persisting across steps, so the
compression error telescopes like grad_sync's EF).  Encoded leaves ride
the wire as self-describing tagged dicts; ``decode_tree`` dequantizes
transparently, so receivers need no codec config.

Wire item shape (schema-pinned in ``telemetry/schema.py`` as
``mpmd_xfer``)::

    {"type": "mpmd_xfer", "kind": "act"|"grad", "step": int, "mb": int,
     "data": bytes} | {..., "shm": path}   # + optional "enc": "a:…,g:…"
"""

from __future__ import annotations

import dataclasses
import os
import threading
import time
from typing import Any, Dict, Optional, Tuple

import numpy as np

from ray_lightning_tpu.cluster import rpc
from ray_lightning_tpu.cluster.queue import DriverQueue, QueueHandle
from ray_lightning_tpu.fault.inject import (
    FaultBlackhole,
    fire as _fault_fire,
)

__all__ = [
    "Mailbox",
    "StageInbox",
    "LocalChannel",
    "QueueChannel",
    "WireDtypeConfig",
    "WireCodec",
    "encode_tree",
    "decode_tree",
    "resolve_payload",
    "SHM_THRESHOLD_BYTES",
]

# Same-host payloads above this ride tmpfs segments instead of the TCP
# loopback (one copy + page cache vs kernel socket buffers both ways).
SHM_THRESHOLD_BYTES = 256 << 10


def encode_tree(tree: Any) -> bytes:
    """Host-ify and serialize an array pytree (activations are numpy by
    the time they leave a stage — ``StageRunner`` device_gets first)."""
    import jax

    host = jax.tree_util.tree_map(np.asarray, tree)
    return rpc.dumps(host)


def decode_tree(payload: bytes) -> Any:
    """Deserialize a wire payload, transparently dequantizing any
    codec-tagged leaves (``WireCodec`` output is self-describing, so the
    receive side needs no wire-dtype config — an f32 sender and an int8
    sender land in the same mailbox)."""
    return _dewire_tree(rpc.loads(payload))


# -- quantized wire codec ----------------------------------------------------

_WIRE_TAG = "__wire__"
_WIRE_DTYPES = ("f32", "bf16", "int8")


@dataclasses.dataclass(frozen=True)
class WireDtypeConfig:
    """Per-direction DCN payload dtypes for the pipeline transfer lane.

    ``act`` applies to forward activation segments, ``grad`` to backward
    activation-grad segments.  ``"f32"`` is the legacy full-width wire;
    ``"bf16"`` halves the bytes with rounding only; ``"int8"`` is the
    block-scaled codec (~3.9× fewer bytes) — on the grad direction it
    additionally carries a sender-side error-feedback residual, the same
    telescoping-error discipline as ``grad_sync`` int8_ef.
    """

    act: str = "f32"
    grad: str = "f32"
    block_size: int = 256

    def __post_init__(self):
        for field in ("act", "grad"):
            v = getattr(self, field)
            if v not in _WIRE_DTYPES:
                raise ValueError(
                    f"mpmd_wire_dtype {field}={v!r}: expected one of "
                    f"{_WIRE_DTYPES}"
                )
        if self.block_size < 1:
            raise ValueError("block_size must be >= 1")

    @property
    def active(self) -> bool:
        return self.act != "f32" or self.grad != "f32"

    @property
    def enc(self) -> str:
        """Compact mode string recorded on wire items / telemetry."""
        return f"act:{self.act},grad:{self.grad}"

    @classmethod
    def coerce(cls, value: Any) -> "WireDtypeConfig":
        """None | str | dict | WireDtypeConfig → WireDtypeConfig.

        ``None`` reads the ``RLT_MPMD_WIRE_DTYPE`` env bus (forwarded to
        workers like ``RLT_GRAD_COMM``); absent that, f32 — compression
        is always opt-in.  A bare mode string applies to both
        directions; ``"act:bf16,grad:int8"`` sets them independently.
        """
        if isinstance(value, cls):
            return value
        if value is None:
            value = os.environ.get("RLT_MPMD_WIRE_DTYPE") or "f32"
        if isinstance(value, dict):
            kw = dict(value)
        elif isinstance(value, str):
            s = value.strip().lower()
            if not s:
                s = "f32"
            if ":" in s:
                kw = {}
                for part in s.split(","):
                    k, _, v = part.partition(":")
                    kw[k.strip()] = v.strip()
            else:
                kw = {"act": s, "grad": s}
        else:
            raise TypeError(
                f"mpmd_wire_dtype must be a mode string, dict or "
                f"WireDtypeConfig; got {type(value).__name__}"
            )
        unknown = set(kw) - {"act", "grad", "block_size"}
        if unknown:
            raise ValueError(
                f"mpmd_wire_dtype: unknown keys {sorted(unknown)} "
                "(expected act/grad/block_size)"
            )
        return cls(**kw)


def _quantize_leaf_int8(flat: np.ndarray, block: int):
    """Block-scaled int8 of a flat f32 vector → (q int8, scales f32).
    Mirrors ``ops/collective_quant.quantize_block_scaled`` (absmax/127,
    zero blocks get scale 1.0 so they quantize exactly) but runs
    host-side in numpy — the transfer lane is host memory by the time
    it serializes."""
    n = flat.size
    pad = (-n) % block
    if pad:
        flat = np.pad(flat, (0, pad))
    blocks = flat.reshape(-1, block)
    scales = np.abs(blocks).max(axis=1).astype(np.float32) / 127.0
    scales[scales == 0.0] = 1.0
    q = np.clip(np.rint(blocks / scales[:, None]), -127, 127).astype(
        np.int8
    )
    return q.reshape(-1), scales


def _dewire_leaf(leaf: Any) -> Any:
    if not (isinstance(leaf, dict) and _WIRE_TAG in leaf):
        return leaf
    mode = leaf[_WIRE_TAG]
    if mode == "bf16":
        return np.asarray(leaf["data"]).astype(leaf["dtype"])
    if mode == "int8":
        block = int(leaf["block"])
        q = np.asarray(leaf["q"], np.float32).reshape(-1, block)
        deq = (q * np.asarray(leaf["s"], np.float32)[:, None]).reshape(-1)
        shape = tuple(leaf["shape"])
        n = int(np.prod(shape)) if shape else 1
        return deq[:n].reshape(shape).astype(leaf["dtype"])
    raise ValueError(f"unknown wire codec tag {mode!r}")


def _dewire_tree(tree: Any) -> Any:
    import jax

    return jax.tree_util.tree_map(
        _dewire_leaf, tree,
        is_leaf=lambda x: isinstance(x, dict) and _WIRE_TAG in x,
    )


class WireCodec:
    """Sender-side payload codec + wire accounting for one channel.

    Holds the per-(kind, mb, chunk, leaf) error-feedback residuals for
    the int8 grad direction: pipeline schedules re-send the same
    (mb, chunk) slots every step, so each slot's compression error is
    re-added to the next step's payload before quantizing and
    telescopes instead of accumulating.  A slot whose leaf shape
    changes (e.g. a ragged final batch) resets its residual to zero —
    correctness first, one step of error lost.

    Accounting: ``bytes_full_width`` is the analytic f32 footprint of
    every float leaf (plus raw bytes of non-float leaves) — the
    denominator the ``mpmd_xfer`` wire-ratio artifact divides by.
    """

    def __init__(self, cfg: WireDtypeConfig):
        self.cfg = cfg
        self._resid: Dict[Tuple, np.ndarray] = {}
        self.bytes_full_width = 0

    def mode_for(self, kind: str) -> str:
        return self.cfg.grad if kind == "grad" else self.cfg.act

    def encode_payload(
        self, kind: str, step: int, mb: int, chunk: int, tree: Any
    ) -> bytes:
        """Host-ify, wire-encode and serialize one segment payload."""
        import jax

        del step  # residual slots are keyed per (mb, chunk), not step
        mode = self.mode_for(kind)
        use_ef = mode == "int8" and kind == "grad"
        counter = [0]

        def _encode(leaf):
            idx = counter[0]
            counter[0] += 1
            a = np.asarray(leaf)
            if not np.issubdtype(a.dtype, np.floating):
                self.bytes_full_width += a.nbytes
                return a
            self.bytes_full_width += a.size * 4
            if mode == "f32":
                return a
            if mode == "bf16":
                import ml_dtypes

                return {
                    _WIRE_TAG: "bf16",
                    "data": a.astype(ml_dtypes.bfloat16),
                    "dtype": a.dtype.str,
                }
            flat = a.astype(np.float32, copy=False).reshape(-1)
            key = (kind, int(mb), int(chunk), idx)
            if use_ef:
                resid = self._resid.get(key)
                if resid is not None and resid.shape == flat.shape:
                    flat = flat + resid
            q, scales = _quantize_leaf_int8(flat, self.cfg.block_size)
            if use_ef:
                deq = (
                    q.astype(np.float32).reshape(-1, self.cfg.block_size)
                    * scales[:, None]
                ).reshape(-1)[: flat.size]
                self._resid[key] = flat - deq
            return {
                _WIRE_TAG: "int8",
                "q": q,
                "s": scales,
                "shape": tuple(a.shape),
                "dtype": a.dtype.str,
                "block": self.cfg.block_size,
            }

        wired = jax.tree_util.tree_map(_encode, tree)
        return rpc.dumps(wired)


def resolve_payload(item: Dict[str, Any], unlink: bool = True) -> bytes:
    """Payload bytes of a ``data``/``shm`` wire item (the one-of pair
    every queue-plane tensor frame uses: MPMD activation transfers and
    the serve plane's KV handoffs alike).

    Segment lifetime is write-once/read-once, CONSUMER-owned: an
    ``shm`` payload is read and then unlinked here, so tmpfs is
    reclaimed the moment the bytes are out.  The producer's teardown
    sweep (``sweep_stale_segments``) is the crash backstop for frames
    that never reach a consumer — a producer killed ``-9`` mid-handoff
    leaves segments whose owner pid is gone, and the next sweep (actor
    kill, engine close, router failover) collects them.
    """
    shm_path = item.get("shm")
    if shm_path is None:
        return item["data"]
    from ray_lightning_tpu.cluster.shm import SegmentStore

    payload = SegmentStore.get(shm_path)
    if unlink:
        try:
            os.unlink(shm_path)
        except OSError:
            pass
    return payload


class Mailbox:
    """Keyed rendezvous: the pump thread ``deliver``s payloads as they
    arrive; ``recv`` blocks until its key shows up (and reports how long
    it actually waited — the bubble signal).

    ``deliver`` optionally files the frame's trace envelope alongside
    the payload; :meth:`recv_traced` surfaces it so a stage can adopt
    the step's distributed-trace identity from its upstream neighbor."""

    def __init__(self):
        self._cond = threading.Condition()
        self._items: Dict[Tuple, Any] = {}      # guarded by self._cond
        self._error: Optional[BaseException] = None  # guarded by self._cond

    def deliver(self, key: Tuple, payload: Any,
                trace: Optional[Dict[str, Any]] = None) -> None:
        with self._cond:
            self._items[key] = (payload, trace)
            self._cond.notify_all()

    def fail(self, exc: BaseException) -> None:
        """Poison the mailbox: every current and future recv raises."""
        with self._cond:
            self._error = exc
            self._cond.notify_all()

    def ready(self, key: Tuple) -> bool:
        with self._cond:
            return key in self._items

    def recv(self, key: Tuple, timeout: float = 120.0) -> Tuple[Any, float]:
        """Blocking receive → ``(payload, blocked_seconds)``."""
        payload, blocked, _ = self.recv_traced(key, timeout)
        return payload, blocked

    def recv_traced(
        self, key: Tuple, timeout: float = 120.0
    ) -> Tuple[Any, float, Optional[Dict[str, Any]]]:
        """Blocking receive → ``(payload, blocked_seconds, trace)``
        where ``trace`` is the sender's trace envelope (None on
        untraced frames)."""
        deadline = time.monotonic() + timeout
        t0 = time.perf_counter()
        with self._cond:
            while key not in self._items:
                if self._error is not None:
                    raise RuntimeError(
                        f"transfer lane failed while waiting for {key}"
                    ) from self._error
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise TimeoutError(
                        f"transfer recv timed out after {timeout:.0f}s "
                        f"waiting for {key} (peer stage dead or wedged?)"
                    )
                self._cond.wait(min(remaining, 1.0))
            payload, trace = self._items.pop(key)
        return payload, time.perf_counter() - t0, trace


class StageInbox:
    """A stage's receive plane: a DriverQueue server + the pump thread
    that files decoded payloads into the :class:`Mailbox` (this thread
    IS the comm/compute overlap)."""

    def __init__(self, host: str = "127.0.0.1",
                 advertise_host: Optional[str] = None):
        self.queue = DriverQueue(host=host, advertise_host=advertise_host)
        self.mailbox = Mailbox()
        self._closed = threading.Event()
        self._pump = threading.Thread(
            target=self._pump_loop, name="rlt-mpmd-inbox", daemon=True
        )
        self._pump.start()

    @property
    def handle(self) -> QueueHandle:
        return self.queue.handle

    def _pump_loop(self) -> None:
        import queue as _pyqueue

        while not self._closed.is_set():
            try:
                item = self.queue.get(timeout=0.2)
            except _pyqueue.Empty:
                continue
            except Exception as e:  # noqa: BLE001 - server torn down
                if not self._closed.is_set():
                    self.mailbox.fail(e)
                return
            try:
                self._file(item)
            except Exception as e:  # noqa: BLE001 - a malformed frame
                # must poison recvs loudly, not vanish in a daemon thread
                self.mailbox.fail(e)
                return

    def _file(self, item: Any) -> None:
        if not (isinstance(item, dict) and item.get("type") == "mpmd_xfer"):
            raise ValueError(f"unexpected item on stage inbox: {type(item)}")
        key = (
            item["kind"], int(item["step"]), int(item["mb"]),
            int(item.get("chunk", 0)),
        )
        self.mailbox.deliver(key, decode_tree(resolve_payload(item)),
                             trace=item.get("trace"))

    def close(self) -> None:
        self._closed.set()
        self.queue.shutdown()


def _channel_xfer_stats(channel) -> Dict[str, Any]:
    """Wire accounting view shared by both channel flavors."""
    codec: Optional[WireCodec] = channel._codec
    sent = channel.bytes_sent
    full = codec.bytes_full_width if codec is not None else sent
    return {
        "bytes_sent": sent,
        "bytes_full_width": full,
        "wire_ratio": round(full / sent, 3) if sent else None,
        "enc": codec.cfg.enc if codec is not None else "act:f32,grad:f32",
    }


class LocalChannel:
    """In-process channel straight into a :class:`Mailbox` — the
    transport of the threaded in-process pipeline (tests, the inline
    parity harness)."""

    def __init__(self, mailbox: Mailbox, codec: Optional[WireCodec] = None):
        self._mailbox = mailbox
        self._codec = codec
        self.bytes_sent = 0

    def send(self, kind: str, step: int, mb: int, tree: Any,
             chunk: int = 0, trace=None) -> None:
        # Round-trip through the real encoder (and, when configured, the
        # real wire codec): in-process parity runs must exercise the
        # same host-ification + quantization the wire path does
        # (the trace envelope rides the same inject the wire uses).
        if self._codec is not None:
            payload = self._codec.encode_payload(kind, step, mb, chunk, tree)
        else:
            payload = encode_tree(tree)
        self.bytes_sent += len(payload)
        envelope: Dict[str, Any] = {}
        if trace is not None:
            from ray_lightning_tpu.telemetry.propagate import inject

            inject(envelope, trace)
        self._mailbox.deliver(
            (kind, step, mb, chunk), decode_tree(payload),
            trace=envelope.get("trace"),
        )

    def xfer_stats(self) -> Dict[str, Any]:
        return _channel_xfer_stats(self)


class QueueChannel:
    """Cross-process channel to a neighbor stage's :class:`StageInbox`.

    ``same_host=True`` routes payloads above ``shm_threshold`` through
    the segment store; the TCP frame then carries only the path.
    """

    def __init__(self, handle: QueueHandle, same_host: bool = False,
                 shm_threshold: int = SHM_THRESHOLD_BYTES,
                 codec: Optional[WireCodec] = None):
        self._handle = handle
        self._store = None
        if same_host:
            from ray_lightning_tpu.cluster.shm import SegmentStore

            self._store = SegmentStore(prefix="rlt-seg")
        self._shm_threshold = shm_threshold
        self._codec = codec
        self.bytes_sent = 0
        self.shm_sends = 0

    def send(self, kind: str, step: int, mb: int, tree: Any,
             chunk: int = 0, trace=None) -> None:
        if self._codec is not None:
            payload = self._codec.encode_payload(kind, step, mb, chunk, tree)
        else:
            payload = encode_tree(tree)
        self.bytes_sent += len(payload)
        item: Dict[str, Any] = {
            "type": "mpmd_xfer", "kind": kind, "step": int(step),
            "mb": int(mb), "chunk": int(chunk),
        }
        if self._codec is not None:
            item["enc"] = self._codec.cfg.enc
        if trace is not None:
            from ray_lightning_tpu.telemetry.propagate import inject

            inject(item, trace)
        if self._store is not None and len(payload) >= self._shm_threshold:
            item["shm"] = self._store.put(payload)
            self.shm_sends += 1
            # Chaos plane: the training fault grammar's torn/shm_vanish
            # pins corrupt/unlink the segment between write and read —
            # a quantized payload must then fail LOUDLY at decode (the
            # inbox poisons its mailbox), never dequantize garbage.
            try:
                _fault_fire("handoff_send", step=step, path=item["shm"])
            except FaultBlackhole:
                return  # partition semantics: the frame vanishes in flight
        else:
            item["data"] = payload
        self._handle.put(item)

    def xfer_stats(self) -> Dict[str, Any]:
        stats = _channel_xfer_stats(self)
        stats["shm_sends"] = self.shm_sends
        return stats

    def close(self) -> None:
        self._handle.close()
        if self._store is not None:
            self._store.unlink_all()
