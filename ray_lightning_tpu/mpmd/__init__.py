"""MPMD pipeline parallelism: mesh-of-meshes stages over the DCN queue
plane.

The SPMD GPipe flavor (:mod:`ray_lightning_tpu.parallel.pipeline`) keeps
every stage inside ONE jitted program on ONE mesh — it cannot scale past
a single pod.  This package implements the JaxPP-shaped alternative
(PAPERS.md "Scaling Deep Learning Training with MPMD Pipeline
Parallelism"): each pipeline stage is a **separately compiled program on
its own mesh** inside its own :class:`~..cluster.actor.ProcessActor`,
stages exchange activations/activation-gradients over an explicit
transfer lane (shared-memory segments same-host, TCP queues across DCN),
and a per-stage instruction stream (GPipe or 1F1B) schedules
FWD/BWD/SEND/RECV/UPDATE.

Modules:

* :mod:`.plan` — :class:`StagePlan` (contiguous layer split) and
  :class:`MpmdSpec` (the model-decomposition contract + GPT adapter);
* :mod:`.schedule` — instruction streams, validation/simulation, and
  the ``bubble_fraction`` / ``stage_occupancy`` accounting;
* :mod:`.transfer` — the inter-stage data lane (double-buffered recv);
* :mod:`.stage` — :class:`StageRunner`, the per-stage executor (runs
  in-process for tests, inside an actor for real fits);
* :mod:`.worker` — the actor-side entry point + checkpoint discovery;
* :mod:`.reference` — the single-mesh SPMD GPipe reference fit the
  MPMD plane is parity-gated against.

The user-facing driver is
:class:`ray_lightning_tpu.parallel.strategies.MpmdStrategy`.
"""

from ray_lightning_tpu.mpmd.plan import (  # noqa: F401
    MpmdSpec,
    StagePlan,
    gpt_mpmd_spec,
    resolve_mpmd_spec,
)
from ray_lightning_tpu.mpmd.schedule import (  # noqa: F401
    Instr,
    build_schedule,
    build_streams,
    bubble_from_timeline,
    fleet_pipeline_stats,
    gpipe_schedule,
    interleaved_streams,
    one_f_one_b_schedule,
    simulate_streams,
    validate_streams,
)

__all__ = [
    "MpmdSpec",
    "StagePlan",
    "gpt_mpmd_spec",
    "resolve_mpmd_spec",
    "Instr",
    "build_schedule",
    "build_streams",
    "interleaved_streams",
    "gpipe_schedule",
    "one_f_one_b_schedule",
    "validate_streams",
    "simulate_streams",
    "bubble_from_timeline",
    "fleet_pipeline_stats",
]
