"""StageRunner: one pipeline worker as its own compiled programs on its
own mesh.

The MPMD dual of the megastep scan: where the SPMD pipeline compiles
every stage into ONE program, a ``StageRunner`` owns a *stage-local*
device mesh and separately compiled fwd/bwd/update programs built from
the model's :class:`~.plan.MpmdSpec` decomposition, and walks an
explicit :mod:`~.schedule` instruction stream — receiving activations
from the previous worker, sending to the next, accumulating gradients
on device, and applying the optimizer at ``UPDATE``.

Under interleaving a worker hosts ``v`` model **chunks** (global stage
``g = chunk * P + worker``), each with its own programs and gradient
accumulator; one combined optimizer update covers all chunks (adamw is
elementwise, so the per-chunk updates equal the single-program fit's).

Backward follows the JaxPP recompute shape: ``FWD`` stashes only the
chunk's INPUT activation per in-flight micro-batch (not the full
residual set); ``BWD`` re-runs the chunk forward inside ``jax.vjp`` —
~⅓ more chunk FLOPs for a P×-smaller stash, and fwd/bwd stay separately
schedulable programs.

The runner is deliberately transport- and process-agnostic: handed a
:class:`~.transfer.Mailbox` + ring-channel pair it runs identically as
a thread in one process (the fast parity tests), inside a
:class:`~..cluster.actor.ProcessActor` (the real plane), or single-
worker with no transport at all (P=1 degenerate pipe).

Every executed instruction lands in a timeline record; per-optimizer-
step summaries (:func:`~.schedule.bubble_from_timeline`) are the
``bubble_fraction`` / ``stage_occupancy`` metric family the telemetry
plane exports.
"""

from __future__ import annotations

import os
import re
import time
from typing import Any, Callable, Dict, List, Optional

from ray_lightning_tpu.mpmd import schedule as sched
from ray_lightning_tpu.mpmd.plan import MpmdSpec, StagePlan

__all__ = ["StageRunner", "stage_ckpt_name", "STAGE_CKPT_RE"]

# mpmd-step<N>-stage<P>.ckpt — one single-file crc-framed checkpoint per
# worker per retained optimizer step (utils/state_stream framing).
STAGE_CKPT_RE = re.compile(
    r"^mpmd-step(?P<step>\d+)-stage(?P<stage>\d+)\.ckpt$"
)


def stage_ckpt_name(step: int, worker: int) -> str:
    return f"mpmd-step{step:08d}-stage{worker}.ckpt"


class StageRunner:
    """Execute one worker's instruction stream over its own mesh."""

    def __init__(
        self,
        spec: MpmdSpec,
        plan: StagePlan,
        worker: int,
        n_workers: int,
        schedule: str,
        n_micro: int,
        tx,
        interleave: int = 1,
        mesh=None,
        mailbox=None,
        send_next=None,
        send_prev=None,
        recv_timeout_s: float = 120.0,
        keep_ckpts: int = 2,
        trace_dir: Optional[str] = None,
    ):
        if plan.n_stages != n_workers * interleave:
            raise ValueError(
                f"plan has {plan.n_stages} stages; {n_workers} workers x "
                f"interleave {interleave} needs {n_workers * interleave}"
            )
        self.spec = spec
        self.plan = plan
        self.worker = worker
        self.n_workers = n_workers
        self.interleave = interleave
        self.schedule_name = schedule
        self.n_micro = n_micro
        self.tx = tx
        self.mesh = mesh
        self.mailbox = mailbox
        self.send_next = send_next
        self.send_prev = send_prev
        self.recv_timeout_s = recv_timeout_s
        self.keep_ckpts = keep_ckpts
        self.n_stages = plan.n_stages
        # Global stage ids hosted here, by chunk.
        self.stages = [
            c * n_workers + worker for c in range(interleave)
        ]
        self.hosts_embed = 0 in self.stages
        self.hosts_loss = (self.n_stages - 1) in self.stages
        self.needs_batches = self.hosts_embed or self.hosts_loss
        needs_recv = any(g > 0 for g in self.stages)
        needs_send = any(g < self.n_stages - 1 for g in self.stages)
        if (needs_recv or needs_send) and mailbox is None:
            raise ValueError(f"worker {worker} needs a mailbox")
        if needs_send and send_next is None:
            raise ValueError(f"worker {worker} needs a send_next channel")
        if any(g > 0 for g in self.stages) and send_prev is None:
            raise ValueError(f"worker {worker} needs a send_prev channel")
        self.stream = sched.build_streams(
            schedule, n_workers, n_micro, interleave
        )[worker]
        self.state = None
        self.step_summaries: List[Dict[str, float]] = []
        self.losses: List[float] = []
        # Per-op durations from steady-state steps (the first executed
        # step carries compiles and is excluded) — feeds the
        # measured-cost schedule-bubble decomposition.
        self._op_durs: Dict[str, List[float]] = {}
        self._steps_run = 0
        self._acc: Optional[List[Any]] = None
        self._compiled = False
        # Distributed tracing (docs/OBSERVABILITY.md): the EMBED worker
        # mints each step's trace identity and stamps it on its SEND
        # frames; downstream workers adopt it from their first traced
        # RECV — so a whole step's cross-worker instruction spans share
        # one trace_id without any side-channel agreement.  Wall-clock
        # spans, exported as trace-mpmd-stage<k>.jsonl at fit end.
        import time as _time
        import uuid as _uuid

        from ray_lightning_tpu.telemetry.spans import SpanTracer

        self._trace_dir = trace_dir
        self._trace_run = _uuid.uuid4().hex[:8]
        self.tracer = SpanTracer(
            enabled=trace_dir is not None, maxlen=65536, rank=worker,
            clock=_time.time,
        )

    # -- program construction ----------------------------------------------
    def _build_programs(self) -> None:
        import jax
        import jax.numpy as jnp

        from ray_lightning_tpu.telemetry.program_ledger import ledgered_jit

        spec = self.spec
        self._fwd: List[Any] = []
        self._bwd: List[Any] = []
        for c, g in enumerate(self.stages):
            first = g == 0
            last = g == self.n_stages - 1

            def fwd_first(params, batch):
                return spec.stage_fn(
                    params["blocks"], spec.embed_fn(params, batch)
                )

            def fwd_mid(params, x):
                return spec.stage_fn(params["blocks"], x)

            def loss_last(params, x, batch):
                return spec.loss_fn(
                    params, spec.stage_fn(params["blocks"], x), batch
                )

            def loss_single(params, batch):
                x = spec.stage_fn(
                    params["blocks"], spec.embed_fn(params, batch)
                )
                return spec.loss_fn(params, x, batch)

            if first and last:
                fwd = ledgered_jit(loss_single, site=f"mpmd/fwd_s{g}")

                def bwd(params, batch, _f=loss_single):
                    return jax.grad(lambda p: _f(p, batch)[0])(params)

                bwd = ledgered_jit(bwd, site=f"mpmd/bwd_s{g}")
            elif first:
                fwd = ledgered_jit(fwd_first, site=f"mpmd/fwd_s{g}")

                def bwd(params, batch, dy, _f=fwd_first):
                    _, vjp = jax.vjp(lambda p: _f(p, batch), params)
                    (dp,) = vjp(dy)
                    return dp

                bwd = ledgered_jit(bwd, site=f"mpmd/bwd_s{g}")
            elif last:
                fwd = ledgered_jit(loss_last, site=f"mpmd/fwd_s{g}")

                def bwd(params, x, batch, _f=loss_last):
                    return jax.grad(
                        lambda p, xx: _f(p, xx, batch)[0], argnums=(0, 1)
                    )(params, x)

                bwd = ledgered_jit(bwd, site=f"mpmd/bwd_s{g}")
            else:
                fwd = ledgered_jit(fwd_mid, site=f"mpmd/fwd_s{g}")

                def bwd(params, x, dy, _f=fwd_mid):
                    _, vjp = jax.vjp(_f, params, x)
                    return vjp(dy)  # (dparams, dx)

                bwd = ledgered_jit(bwd, site=f"mpmd/bwd_s{g}")
            self._fwd.append(fwd)
            self._bwd.append(bwd)

        self._acc_add = ledgered_jit(
            lambda acc, g: jax.tree_util.tree_map(jnp.add, acc, g),
            site="mpmd/acc_add", arg_names=("acc", "grads"),
        )
        self._zeros_like = ledgered_jit(
            lambda p: jax.tree_util.tree_map(jnp.zeros_like, p),
            site="mpmd/zeros_like", arg_names=("params",),
        )
        n = float(self.n_micro)
        tx = self.tx

        def apply_update(state, acc_chunks):
            grads = {
                "chunks": [
                    jax.tree_util.tree_map(lambda g: g / n, acc)
                    for acc in acc_chunks
                ]
            }
            return state.apply_gradients(grads, tx)

        self._apply = ledgered_jit(
            apply_update, site="mpmd/apply_update", donate_argnums=(0,)
        )
        self._compiled = True

    # -- placement -----------------------------------------------------------
    def _replicated(self, tree):
        import jax

        if self.mesh is None:
            return tree
        from ray_lightning_tpu.parallel import sharding as shardlib

        return jax.device_put(tree, shardlib.replicated(self.mesh))

    def _batch_placed(self, tree):
        """Intra-stage GSPMD: batch rows sharded over the stage mesh's
        data axes (activations and raw batches share the leading-axis
        contract)."""
        import jax

        if self.mesh is None:
            return jax.tree_util.tree_map(jax.numpy.asarray, tree)
        from ray_lightning_tpu.parallel import sharding as shardlib

        return jax.device_put(tree, shardlib.batch_sharding(self.mesh))

    # -- state ----------------------------------------------------------------
    def init_state(self, full_params) -> None:
        """Slice this worker's chunk params out of a full host param
        tree and build the local optimizer state (every worker inits
        from the same deterministic full init, so stages agree on
        boundary shapes without communicating)."""
        import numpy as np

        import jax

        from ray_lightning_tpu.core.module import TrainState

        chunks = [
            self.spec.split_params(full_params, self.plan, g)
            for g in self.stages
        ]
        # Host-copy before placement: the update program donates the
        # state, and device_put may alias the caller's buffers as
        # shards — donating an alias would delete the caller's params
        # (the inproc harness hands the SAME full tree to every stage).
        chunks = jax.tree_util.tree_map(lambda a: np.array(a), chunks)
        params = self._replicated({"chunks": chunks})
        self.state = TrainState.create(params, self.tx)
        if not self._compiled:
            self._build_programs()
        self._acc = [
            self._zeros_like(p) for p in self.state.params["chunks"]
        ]

    def load_state(self, state) -> None:
        """Adopt a (host) TrainState — the resume path."""
        self.state = self._replicated(state)
        if not self._compiled:
            self._build_programs()
        self._acc = [
            self._zeros_like(p) for p in self.state.params["chunks"]
        ]

    def host_state(self):
        import jax

        return jax.tree_util.tree_map(
            lambda a: jax.device_get(a), self.state
        )

    def chunk_params_host(self) -> List[Any]:
        """Per-GLOBAL-stage host param trees (ordered by this worker's
        chunk index — the strategy reassembles across workers)."""
        import jax

        return [
            jax.device_get(p) for p in self.state.params["chunks"]
        ]

    # -- checkpointing --------------------------------------------------------
    def write_checkpoint(self, restart_dir: str, step: int) -> str:
        from ray_lightning_tpu.utils.state_stream import (
            state_stream_to_file,
            to_state_stream,
        )

        os.makedirs(restart_dir, exist_ok=True)
        path = os.path.join(
            restart_dir, stage_ckpt_name(step, self.worker)
        )
        state_stream_to_file(
            to_state_stream({"state": self.host_state(), "step": step}),
            path,
        )
        self._prune_checkpoints(restart_dir)
        return path

    def _prune_checkpoints(self, restart_dir: str) -> None:
        """Keep the newest ``keep_ckpts`` steps of THIS worker
        (previous-good fallback needs one older survivor, same
        retention contract as the SPMD restart dir)."""
        mine = []
        try:
            entries = os.listdir(restart_dir)
        except OSError:
            return
        for entry in entries:
            m = STAGE_CKPT_RE.match(entry)
            if m and int(m.group("stage")) == self.worker:
                mine.append((int(m.group("step")), entry))
        for _, entry in sorted(mine)[:-self.keep_ckpts]:
            try:
                os.unlink(os.path.join(restart_dir, entry))
            except OSError:
                pass

    def load_checkpoint(self, prefix: str) -> int:
        """Load ``<prefix>-stage<k>.ckpt`` (driver-brokered resume
        prefix, see :func:`~.worker.latest_mpmd_checkpoint`); returns
        the optimizer step to resume FROM."""
        from ray_lightning_tpu.utils.state_stream import (
            load_state_stream,
            state_stream_from_file,
        )

        path = f"{prefix}-stage{self.worker}.ckpt"
        payload = load_state_stream(state_stream_from_file(path))
        self.load_state(payload["state"])
        return int(payload["step"])

    # -- execution ------------------------------------------------------------
    def run_fit(
        self,
        steps: int,
        micro_batches_for: Callable[[int], Optional[List[Any]]],
        start_step: int = 0,
        restart_dir: Optional[str] = None,
        ckpt_every: int = 1,
        on_step: Optional[Callable[[int, Dict[str, Any]], None]] = None,
        drain_check: Optional[Callable[[], Optional[str]]] = None,
    ) -> Dict[str, Any]:
        """Drive optimizer steps ``start_step .. steps-1`` of the
        stream.

        ``micro_batches_for(step)`` returns this worker's micro-batch
        list (embed/loss workers) or ``None`` (interior workers).  A
        pending drain request (``drain_check`` returning a reason) is
        honored at the step boundary: the worker writes its drain
        checkpoint and raises :class:`~..fault.drain.PreemptedError` —
        the per-stage half of the graceful-drain contract.
        """
        from ray_lightning_tpu.fault import inject as _chaos
        from ray_lightning_tpu.fault.drain import PreemptedError

        if self.state is None:
            raise RuntimeError("init_state/load_state must run first")
        try:
            for step in range(start_step, steps):
                reason = (drain_check() if drain_check is not None
                          else None)
                if reason:
                    ckpt = None
                    if restart_dir is not None:
                        self.write_checkpoint(restart_dir, step)
                        ckpt = os.path.join(
                            restart_dir, f"mpmd-step{step:08d}"
                        )
                    raise PreemptedError(
                        f"stage worker {self.worker} drained at step "
                        f"{step}",
                        checkpoint=ckpt, step=step, rank=self.worker,
                        reason=reason,
                    )
                _chaos.fire("step", step=step, epoch=0, rank=self.worker)
                logs = self._run_opt_step(step, micro_batches_for(step))
                if self.hosts_loss:
                    self.losses.append(
                        float(logs.get("loss", float("nan")))
                    )
                if (restart_dir is not None
                        and (step + 1) % max(ckpt_every, 1) == 0):
                    self.write_checkpoint(restart_dir, step + 1)
                if on_step is not None:
                    on_step(step, logs)
        finally:
            self.export_trace()
        return {
            "losses": self.losses,
            "step_summaries": self.step_summaries,
            "stats": self.fit_stats(),
        }

    def export_trace(self) -> Optional[str]:
        """Write this worker's span JSONL (a drain/crash exits through
        here too — partial timelines still stitch)."""
        if self._trace_dir is None or not self.tracer.events():
            return None
        path = os.path.join(
            self._trace_dir, f"trace-mpmd-stage{self.worker}.jsonl"
        )
        try:
            os.makedirs(self._trace_dir, exist_ok=True)
            self.tracer.export_jsonl(path)
        except OSError:
            return None
        return path

    def _run_opt_step(
        self, step: int, micro: Optional[List[Any]]
    ) -> Dict[str, Any]:
        import jax
        import numpy as np

        if self.needs_batches:
            if micro is None or len(micro) != self.n_micro:
                raise ValueError(
                    f"worker {self.worker} needs {self.n_micro} "
                    f"micro-batches at step {step}, got "
                    f"{None if micro is None else len(micro)}"
                )
            micro = [self._batch_placed(m) for m in micro]
        timeline: List[Dict[str, Any]] = []
        stash_x: Dict[Any, Any] = {}
        stash_y: Dict[Any, Any] = {}
        stash_dy: Dict[Any, Any] = {}
        stash_dx: Dict[Any, Any] = {}
        mb_losses: List[float] = []
        n_workers = self.n_workers
        # The step's distributed-trace context: minted here on the
        # embed worker, adopted from the first traced RECV elsewhere.
        step_ctx = None
        if self.tracer.enabled and self.hosts_embed:
            from ray_lightning_tpu.telemetry.propagate import root_context

            step_ctx = root_context(f"mpmd-{self._trace_run}-s{step}")

        for instr in self.stream:
            op, mb, c = instr.op, instr.mb, instr.chunk
            blocked = 0.0
            t0 = time.perf_counter()
            if op == sched.RECV_ACT:
                tree, blocked, w_trace = self.mailbox.recv_traced(
                    ("act", step, mb, c), timeout=self.recv_timeout_s
                )
                if step_ctx is None:
                    step_ctx = self._adopt_trace(w_trace)
                stash_x[(c, mb)] = self._batch_placed(tree)
            elif op == sched.RECV_GRAD:
                tree, blocked, w_trace = self.mailbox.recv_traced(
                    ("grad", step, mb, c), timeout=self.recv_timeout_s
                )
                if step_ctx is None:
                    step_ctx = self._adopt_trace(w_trace)
                stash_dy[(c, mb)] = self._batch_placed(tree)
            elif op == sched.FWD:
                g = self.stages[c]
                params = self.state.params["chunks"][c]
                first, last = g == 0, g == self.n_stages - 1
                if first and last:
                    loss, _ = self._fwd[c](params, micro[mb])
                    mb_losses.append(float(jax.device_get(loss)))
                elif first:
                    y = self._fwd[c](params, micro[mb])
                    jax.block_until_ready(y)
                    stash_y[(c, mb)] = y
                elif last:
                    loss, _ = self._fwd[c](
                        params, stash_x[(c, mb)], micro[mb]
                    )
                    mb_losses.append(float(jax.device_get(loss)))
                else:
                    y = self._fwd[c](params, stash_x[(c, mb)])
                    jax.block_until_ready(y)
                    stash_y[(c, mb)] = y
            elif op == sched.SEND_ACT:
                y = stash_y.pop((c, mb))
                g = self.stages[c]
                self.send_next.send(
                    "act", step, mb, jax.device_get(y),
                    chunk=(g + 1) // n_workers, trace=step_ctx,
                )
            elif op == sched.BWD:
                g = self.stages[c]
                params = self.state.params["chunks"][c]
                first, last = g == 0, g == self.n_stages - 1
                if first and last:
                    dp = self._bwd[c](params, micro[mb])
                elif first:
                    dp = self._bwd[c](
                        params, micro[mb], stash_dy.pop((c, mb))
                    )
                elif last:
                    dp, dx = self._bwd[c](
                        params, stash_x.pop((c, mb)), micro[mb]
                    )
                    stash_dx[(c, mb)] = dx
                else:
                    dp, dx = self._bwd[c](
                        params, stash_x.pop((c, mb)),
                        stash_dy.pop((c, mb)),
                    )
                    stash_dx[(c, mb)] = dx
                self._acc[c] = self._acc_add(self._acc[c], dp)
                jax.block_until_ready(self._acc[c])
            elif op == sched.SEND_GRAD:
                dx = stash_dx.pop((c, mb))
                g = self.stages[c]
                self.send_prev.send(
                    "grad", step, mb, jax.device_get(dx),
                    chunk=(g - 1) // n_workers, trace=step_ctx,
                )
            elif op == sched.UPDATE:
                self.state = self._apply(self.state, self._acc)
                jax.block_until_ready(self.state.params)
                self._acc = [
                    self._zeros_like(p)
                    for p in self.state.params["chunks"]
                ]
            t1 = time.perf_counter()
            timeline.append({
                "op": op, "mb": mb, "t0": t0, "t1": t1,
                "blocked_s": blocked,
            })
            if step_ctx is not None:
                # Per-instruction span under the worker's step span:
                # the stitched view's compute-vs-blocked-recv lanes.
                from ray_lightning_tpu.telemetry.propagate import (
                    child_context, trace_args,
                )

                wall_t1 = time.time()
                self.tracer.record(
                    op.lower(), wall_t1 - (t1 - t0), t1 - t0,
                    args=trace_args(
                        child_context(step_ctx), step=step, mb=mb,
                        stage=self.stages[c], worker=self.worker,
                        blocked_s=round(blocked, 6),
                    ),
                )
            if self._steps_run > 0 and op in (
                    sched.FWD, sched.BWD, sched.SEND_ACT,
                    sched.SEND_GRAD):
                key = "SEND" if op.startswith("SEND") else op
                self._op_durs.setdefault(key, []).append(t1 - t0)
        self._steps_run += 1
        summary = sched.bubble_from_timeline(timeline)
        summary["step"] = step
        self.step_summaries.append(summary)
        if step_ctx is not None and timeline:
            from ray_lightning_tpu.telemetry.propagate import trace_args

            wall_end = time.time()
            dur = timeline[-1]["t1"] - timeline[0]["t0"]
            self.tracer.record(
                "mpmd_step" if self.hosts_embed else "mpmd_stage_step",
                wall_end - dur, dur,
                args=trace_args(
                    step_ctx, step=step, worker=self.worker,
                    busy_s=round(summary.get("busy_s", 0.0), 6),
                    blocked_s=round(summary.get("blocked_s", 0.0), 6),
                    bubble_fraction=round(
                        summary.get("bubble_fraction", 0.0), 6),
                ),
            )
        logs: Dict[str, Any] = dict(summary)
        if self.hosts_loss and mb_losses:
            logs["loss"] = float(np.mean(mb_losses))
        return logs

    def _adopt_trace(self, envelope) -> Optional[Any]:
        """Adopt the step's trace identity from an upstream frame's
        envelope: this worker's step span id is DERIVED
        (``<trace_id>.w<worker>``, parent = the embed worker's root) so
        the whole fleet agrees without a registry."""
        if not self.tracer.enabled or not envelope:
            return None
        from ray_lightning_tpu.telemetry.propagate import (
            TraceContext, extract,
        )

        ctx = extract({"trace": envelope})
        if ctx is None:
            return None
        return TraceContext(
            ctx.trace_id, f"{ctx.trace_id}.w{self.worker}",
            ctx.root_span_id,
        )

    def op_costs(self) -> Dict[str, float]:
        """Median steady-state per-op durations (seconds) — the inputs
        of :func:`~.schedule.measured_schedule_bubble`."""
        import numpy as np

        return {
            op: float(np.median(durs))
            for op, durs in self._op_durs.items() if durs
        }

    def xfer_stats(self) -> Dict[str, Any]:
        """Aggregate wire accounting over this worker's SEND channels
        (``None`` entries — edge workers — contribute nothing).  Feeds
        the strategy's ``mpmd_xfer`` telemetry block; ``wire_ratio`` is
        full-width-bytes / encoded-bytes, so 1.0 means the codec is off
        and ≥3 means the int8 arm is earning its keep."""
        agg: Dict[str, Any] = {
            "bytes_sent": 0, "bytes_full_width": 0, "wire_ratio": 1.0,
        }
        enc = None
        for ch in (self.send_next, self.send_prev):
            stats = getattr(ch, "xfer_stats", None)
            if stats is None:
                continue
            s = stats()
            agg["bytes_sent"] += int(s.get("bytes_sent", 0))
            agg["bytes_full_width"] += int(s.get("bytes_full_width", 0))
            if s.get("enc"):
                enc = s["enc"]
            if "shm_sends" in s:
                agg["shm_sends"] = (
                    agg.get("shm_sends", 0) + int(s["shm_sends"])
                )
        if agg["bytes_sent"] > 0:
            agg["wire_ratio"] = (
                agg["bytes_full_width"] / agg["bytes_sent"]
            )
        if enc is not None:
            agg["enc"] = enc
        return agg

    def fit_stats(self) -> Dict[str, float]:
        """Steady-state worker summary: the first optimizer step
        carries every program's compile and is excluded when later
        steps exist (a compile-dominated bubble number would be
        meaningless for schedule A/Bs)."""
        window = (
            self.step_summaries[1:]
            if len(self.step_summaries) > 1
            else self.step_summaries
        )
        if not window:
            return {
                "bubble_fraction": 0.0,
                "stage_occupancy": 0.0,
                "busy_s": 0.0,
                "blocked_s": 0.0,
                "wall_s": 0.0,
            }
        keys = ("bubble_fraction", "stage_occupancy", "busy_s",
                "blocked_s", "wall_s")
        return {
            k: float(sum(s[k] for s in window) / len(window))
            for k in keys
        }
