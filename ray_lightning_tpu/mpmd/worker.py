"""Actor-side entry points + checkpoint discovery for the MPMD plane.

One :class:`~..cluster.actor.ProcessActor` per pipeline stage worker:
the driver first asks each actor to open its transfer inbox
(:func:`_remote_create_inbox` — the handle is brokered back and
distributed to the ring neighbors), then submits
:func:`_stage_execute_remote`, which builds the stage-local mesh,
splits the model, and drives the :class:`~.stage.StageRunner` through
the fit.  Everything here is top-level and import-light so cloudpickle
ships it by reference.

Fault plane: the worker honors the process-wide drain flag at step
boundaries (writes its ``mpmd-step*-stage*.ckpt`` drain checkpoint and
raises :class:`~..fault.drain.PreemptedError`), and crashed workers'
shared-memory segments are reclaimed by the sweep the strategy runs on
kill (``cluster/shm.py``).  Restart discovery
(:func:`latest_mpmd_checkpoint`) resumes at the newest optimizer step
for which EVERY stage has a crc-verified checkpoint — stages must agree
on the step or the pipeline would train skewed params.
"""

from __future__ import annotations

import os
from typing import Any, Dict, List, Optional, Tuple

from ray_lightning_tpu.mpmd.stage import STAGE_CKPT_RE, StageRunner
from ray_lightning_tpu.mpmd.transfer import (
    QueueChannel,
    StageInbox,
    WireCodec,
    WireDtypeConfig,
)

__all__ = [
    "latest_mpmd_checkpoint",
    "_remote_create_inbox",
    "_stage_execute_remote",
]

# The actor process's live inbox (module-global: it must outlive the
# _remote_create_inbox call and be found by _stage_execute_remote).
_INBOX: Optional[StageInbox] = None


def _remote_create_inbox(loopback: bool = True) -> Tuple[str, int]:
    """Open (or re-open) this actor's transfer inbox; returns the
    (host, port) its neighbors dial.  Re-invocation closes the previous
    inbox — each fit attempt gets a fresh lane (a respawned peer must
    never read a dead attempt's frames)."""
    global _INBOX
    if _INBOX is not None:
        _INBOX.close()
        _INBOX = None
    if loopback:
        _INBOX = StageInbox(host="127.0.0.1")
    else:
        from ray_lightning_tpu.cluster import rpc

        _INBOX = StageInbox(
            host="0.0.0.0", advertise_host=rpc.get_node_ip()
        )
    handle = _INBOX.handle
    return handle.host, handle.port


def _collect_batches(datamodule, config,
                     max_needed: Optional[int] = None) -> List[Any]:
    """Materialize the deterministic batch sequence every batch-consuming
    stage worker replays (embed and loss workers must see identical
    rows; both build the shipped datamodule from the same seed).

    ``max_needed`` (the resolved step count, when known) bounds the
    buffer: the fit indexes ``batches[step % len]``, so more than
    ``steps`` batches are never read — without the cap a max_steps fit
    over a large (or streaming/unbounded) loader would buffer the whole
    epoch per stage worker before the first optimizer step."""
    datamodule.setup("fit")
    loader = datamodule.train_dataloader()
    limit = getattr(config, "limit_train_batches", -1)
    batches: List[Any] = []
    for i, batch in enumerate(loader):
        if limit is not None and 0 <= limit <= i:
            break
        if max_needed is not None and len(batches) >= max_needed:
            break
        batches.append(batch)
    if not batches:
        raise ValueError("train dataloader yielded no batches")
    return batches


def _resolve_steps(config, n_batches: int) -> int:
    max_steps = getattr(config, "max_steps", -1)
    if max_steps and max_steps > 0:
        return max_steps
    return n_batches * max(getattr(config, "max_epochs", 1), 1)


def _stage_execute_remote(
    task_ref,
    worker_rank: int,
    queue_handle,
    prev_addr: Optional[Tuple[str, int]],
    next_addr: Optional[Tuple[str, int]],
) -> Dict[str, Any]:
    """Run one stage worker's whole fit inside its actor."""
    global _INBOX
    task = task_ref.get()
    n_workers = task["n_workers"]
    interleave = task["interleave"]
    n_micro = task["n_micro"]
    config = task["config"]

    from ray_lightning_tpu.cluster.queue import QueueHandle
    from ray_lightning_tpu.fault import drain as drain_mod
    from ray_lightning_tpu.fault import inject as _chaos
    from ray_lightning_tpu.mpmd.inproc import split_micro_batches
    from ray_lightning_tpu.mpmd.plan import StagePlan, resolve_mpmd_spec
    from ray_lightning_tpu.parallel.mesh import MeshSpec, build_mesh

    _chaos.set_rank(worker_rank)
    _chaos.fire("spawn", rank=worker_rank)
    drain_mod.reset_drain()
    drain_mod.set_fit_active(True)

    module = task["module"]
    spec = resolve_mpmd_spec(module)
    plan = StagePlan.split(spec.n_layers, n_workers * interleave)
    mesh = build_mesh(MeshSpec(task.get("mesh_axes")))

    class _Ctx:  # modules read trainer.mesh for sharding hints
        grad_sync_active = False

    _Ctx.mesh = mesh
    module.trainer = _Ctx()

    tx_factory = task.get("tx_factory") or spec.tx_factory
    tx = tx_factory() if tx_factory is not None else (
        module.configure_optimizers()
    )
    # The (tx, lr_schedule) convention — but optax transformations ARE
    # NamedTuples, so "has no init" is the discriminator, not tuple-ness.
    if isinstance(tx, tuple) and not hasattr(tx, "init"):
        tx = tx[0]

    # coerce(None) falls back to the bridged RLT_MPMD_WIRE_DTYPE env
    # knob, so actor workers honor it even when the task omits the key.
    wire_cfg = WireDtypeConfig.coerce(task.get("wire_dtype"))

    def channel(addr):
        if addr is None:
            return None
        return QueueChannel(
            QueueHandle(addr[0], addr[1]),
            same_host=task.get("same_host", False),
            # One codec per channel: int8 EF residuals are sender-side.
            codec=WireCodec(wire_cfg) if wire_cfg.active else None,
        )

    send_next = channel(next_addr)
    send_prev = channel(prev_addr)

    runner = StageRunner(
        spec, plan, worker_rank, n_workers,
        task["schedule"], n_micro, tx,
        interleave=interleave,
        mesh=mesh,
        mailbox=None if _INBOX is None else _INBOX.mailbox,
        send_next=send_next,
        send_prev=send_prev,
        recv_timeout_s=task.get("recv_timeout_s", 120.0),
        trace_dir=task.get("trace_dir"),
    )

    start_step = 0
    resume_prefix = task.get("resume_prefix")
    if resume_prefix:
        start_step = runner.load_checkpoint(resume_prefix)
    else:
        import jax

        runner.init_state(
            module.init_params(jax.random.PRNGKey(config.seed))
        )

    batches = None
    if runner.needs_batches:
        batches = _collect_batches(
            task["datamodule"], config, max_needed=task.get("steps")
        )
    steps = task.get("steps")  # driver-resolved (max_steps) when set
    if steps is None:
        if batches is None:
            raise ValueError(
                f"interior stage worker {worker_rank} cannot derive the "
                "step count from data it never loads; set "
                "Trainer(max_steps=...) for pipelines deeper than 2 "
                "workers"
            )
        steps = _resolve_steps(config, len(batches))

    micro_cache: Dict[int, List[Any]] = {}

    def micro_for(step: int):
        if batches is None:
            return None
        if step not in micro_cache:
            micro_cache.clear()  # one step in flight at a time
            micro_cache[step] = split_micro_batches(
                batches[step % len(batches)], n_micro
            )
        return micro_cache[step]

    def on_step(step: int, logs: Dict[str, Any]) -> None:
        item = {
            "type": "mpmd_stage",
            "stage": worker_rank,
            "step": step,
            "bubble_fraction": float(logs.get("bubble_fraction", 0.0)),
            "stage_occupancy": float(logs.get("stage_occupancy", 0.0)),
            "busy_s": float(logs.get("busy_s", 0.0)),
            "blocked_s": float(logs.get("blocked_s", 0.0)),
        }
        if "loss" in logs:
            item["loss"] = float(logs["loss"])
        try:
            queue_handle.put(item)
        except Exception:  # noqa: BLE001 - telemetry must not kill a fit
            pass

    def drain_check() -> Optional[str]:
        return "preempt" if drain_mod.drain_requested() else None

    try:
        runner.run_fit(
            steps,
            micro_for,
            start_step=start_step,
            restart_dir=task.get("restart_dir"),
            ckpt_every=task.get("ckpt_every", 1),
            on_step=on_step,
            drain_check=drain_check,
        )
    finally:
        drain_mod.set_fit_active(False)
        for ch in (send_next, send_prev):
            if ch is not None:
                try:
                    ch.close()
                except Exception:  # noqa: BLE001 - teardown best-effort
                    pass

    import jax

    last_logs: Dict[str, float] = {}
    if runner.hosts_loss and runner.losses:
        last_logs = {
            "loss": runner.losses[-1], "train_loss": runner.losses[-1],
        }
    return {
        "rank": worker_rank,
        "chunks": runner.chunk_params_host(),
        "losses": list(runner.losses),
        "stats": runner.fit_stats(),
        "op_costs": runner.op_costs(),
        "xfer": runner.xfer_stats(),
        "final_step": int(jax.device_get(runner.state.step)),
        "callback_metrics": last_logs,
        "hosts_loss": runner.hosts_loss,
        "steps": steps,
    }


def latest_mpmd_checkpoint(
    restart_dir: Optional[str], n_workers: int
) -> Dict[str, Any]:
    """Newest optimizer step with a COMPLETE, crc-verified checkpoint
    set (one file per stage worker).  Steps with missing or corrupt
    members are walked past — and reported, so silent storage problems
    become ``ckpt_corrupt`` events like the SPMD plane's."""
    corrupt: List[Dict[str, Any]] = []
    if restart_dir is None:
        return {"path": None, "corrupt": corrupt}
    try:
        entries = os.listdir(restart_dir)
    except OSError:
        return {"path": None, "corrupt": corrupt}
    by_step: Dict[int, Dict[int, str]] = {}
    for entry in entries:
        m = STAGE_CKPT_RE.match(entry)
        if m:
            by_step.setdefault(int(m.group("step")), {})[
                int(m.group("stage"))
            ] = os.path.join(restart_dir, entry)
    from ray_lightning_tpu.utils.state_stream import verify_stream_file

    for step in sorted(by_step, reverse=True):
        members = by_step[step]
        if set(members) != set(range(n_workers)):
            continue  # incomplete set (a stage died mid-write)
        problems = []
        for stage, path in sorted(members.items()):
            errs = verify_stream_file(path)
            if errs:
                problems.append({"path": path, "problems": errs[:3]})
        if problems:
            corrupt.extend(problems)
            continue
        return {
            "path": os.path.join(restart_dir, f"mpmd-step{step:08d}"),
            "corrupt": corrupt,
        }
    return {"path": None, "corrupt": corrupt}
