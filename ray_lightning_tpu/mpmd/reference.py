"""Single-mesh SPMD GPipe reference fit — the MPMD parity oracle.

Same model decomposition (:class:`~.plan.MpmdSpec`), same micro-batch
count, same optimizer — but every stage lives inside ONE jitted program
on ONE ``pipe``-axis mesh via
:func:`~..parallel.pipeline.pipeline_apply`.  The MPMD plane must match
this fit's per-step losses to ``atol 1e-5`` in f32 (micro-batch-mean
gradients equal full-batch-mean gradients for equal micro sizes, adamw
is elementwise, so the two formulations compute the same math up to
float association order).  Exercised by ``tests/test_mpmd.py`` and the
``dryrun_multichip`` mpmd flavor.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional

from ray_lightning_tpu.mpmd.plan import MpmdSpec

__all__ = ["gpipe_reference_fit"]


def gpipe_reference_fit(
    spec: MpmdSpec,
    full_params: Any,
    tx,
    batches: Callable[[int], Any],
    steps: int,
    n_stages: int,
    n_micro: int,
    devices: Optional[list] = None,
) -> Dict[str, Any]:
    """Train ``steps`` optimizer steps of the single-program GPipe
    formulation; returns ``{"losses": [...], "state": final}``.

    ``full_params`` must already carry the spec's untied layout (for
    GPT: ``head_w`` present — see :func:`~.plan.gpt_mpmd_spec`);
    ``batches(step)`` yields the SAME full batch the MPMD fit splits
    into micro-batches at that step.
    """
    import jax
    import numpy as np
    from jax.sharding import Mesh

    from ray_lightning_tpu.core.module import TrainState
    from ray_lightning_tpu.parallel.pipeline import pipeline_apply

    if devices is None:
        devices = jax.devices()
    if len(devices) < n_stages:
        raise ValueError(
            f"reference fit needs {n_stages} devices, have {len(devices)}"
        )
    mesh = Mesh(np.asarray(devices[:n_stages]), ("pipe",))

    def loss_fn(params, batch):
        x0 = spec.embed_fn(params, batch)
        out = pipeline_apply(
            spec.stage_fn, params["blocks"], x0, mesh,
            num_microbatches=n_micro,
        )
        loss, _ = spec.loss_fn(params, out, batch)
        return loss

    @jax.jit
    def train_step(state: TrainState, batch):
        loss, grads = jax.value_and_grad(loss_fn)(state.params, batch)
        return state.apply_gradients(grads, tx), loss

    state = TrainState.create(full_params, tx)
    losses: List[float] = []
    for step in range(steps):
        state, loss = train_step(state, batches(step))
        losses.append(float(jax.device_get(loss)))
    return {"losses": losses, "state": jax.device_get(state)}
