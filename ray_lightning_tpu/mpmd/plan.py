"""Stage planning: how a stacked-layer model splits into MPMD stages.

Two contracts live here:

* :class:`StagePlan` — WHERE the model splits: ``P`` contiguous runs of
  the stacked ``(L, ...)`` layer axis, reusing the layer-axis split math
  of the SPMD pipeline (:func:`..parallel.pipeline.layer_splits`) so the
  two pipeline flavors agree on stage boundaries by construction.
  Non-divisible layer counts balance the remainder onto the earliest
  stages.

* :class:`MpmdSpec` — HOW one stage computes: the model decomposed into
  ``embed_fn`` (prologue: raw batch → first activations, stage 0 only),
  ``stage_fn`` (a contiguous run of stacked layers — the SAME signature
  :func:`..parallel.pipeline.pipeline_apply` uses), and ``loss_fn``
  (epilogue: last activations + batch → ``(loss, logs)``, last stage
  only), plus the param split/assemble pair.  Everything is a pure
  function of ``(params, ...)`` so each stage can jit its own programs.

Optimizer note: each stage applies the module's optax transformation to
ITS param shard only.  Elementwise transforms (sgd/adam/adamw + masks /
schedules) then update identically to a single-program fit; transforms
that couple leaves ACROSS stages (global-norm clipping) do not decompose
— pass a per-stage-safe ``tx`` for exact parity (docs/ARCHITECTURE.md
round 12).

Tied embeddings: pipelining splits the first and last stage into
different programs, so a weight shared between the embedding and the LM
head would need a cross-stage gradient reduction every step.  The GPT
adapter UNTIES instead: the last stage gets its own ``head_w``
initialized from ``wte`` (standard MPMD practice; the reference fit in
:mod:`.reference` unties identically so parity is apples-to-apples).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, List, Optional, Tuple

from ray_lightning_tpu.parallel.pipeline import layer_splits

__all__ = ["StagePlan", "MpmdSpec", "gpt_mpmd_spec", "resolve_mpmd_spec"]


@dataclasses.dataclass(frozen=True)
class StagePlan:
    """``P`` contiguous stages over an ``n_layers``-deep stacked model."""

    n_layers: int
    n_stages: int
    boundaries: Tuple[int, ...]

    @classmethod
    def split(cls, n_layers: int, n_stages: int) -> "StagePlan":
        return cls(
            n_layers=n_layers,
            n_stages=n_stages,
            boundaries=layer_splits(n_layers, n_stages),
        )

    def stage_bounds(self, stage: int) -> Tuple[int, int]:
        """Layer interval ``[start, stop)`` owned by ``stage``."""
        if not 0 <= stage < self.n_stages:
            raise ValueError(
                f"stage {stage} out of range for {self.n_stages} stages"
            )
        return self.boundaries[stage], self.boundaries[stage + 1]

    def stage_layers(self, stage: int) -> int:
        start, stop = self.stage_bounds(stage)
        return stop - start

    def is_first(self, stage: int) -> bool:
        return stage == 0

    def is_last(self, stage: int) -> bool:
        return stage == self.n_stages - 1

    def slice_stacked(self, stacked: Any, stage: int) -> Any:
        """Slice every leaf of a stacked ``(L, ...)`` pytree to this
        stage's layer run."""
        import jax

        start, stop = self.stage_bounds(stage)
        return jax.tree_util.tree_map(lambda a: a[start:stop], stacked)


@dataclasses.dataclass
class MpmdSpec:
    """Model-decomposition contract for the MPMD pipeline plane.

    ``embed_fn(stage0_params, batch) -> x0`` · ``stage_fn(blocks, x) ->
    x`` · ``loss_fn(last_params, x, batch) -> (loss, logs)``.  Per-stage
    param pytrees come from ``split_params(full_params, plan, stage)``
    and reassemble with ``assemble_params(stage_params_list, plan)``.
    """

    n_layers: int
    embed_fn: Callable[[Any, Any], Any]
    stage_fn: Callable[[Any, Any], Any]
    loss_fn: Callable[[Any, Any, Any], Tuple[Any, Dict[str, Any]]]
    split_params: Callable[[Any, StagePlan, int], Any]
    assemble_params: Callable[[List[Any], StagePlan], Any]
    # Optional per-stage optimizer factory; None = the module's
    # configure_optimizers() applied per stage (see the module docstring
    # for the cross-stage-coupling caveat).
    tx_factory: Optional[Callable[[], Any]] = None


def _gpt_untie(full_params: Dict[str, Any]) -> Dict[str, Any]:
    """Add the untied LM head (``head_w`` := ``wte``) when absent."""
    if "head_w" in full_params:
        return full_params
    out = dict(full_params)
    out["head_w"] = full_params["wte"]
    return out


def gpt_mpmd_spec(module, compute_dtype=None) -> MpmdSpec:
    """Decompose a dense :class:`~..models.gpt.GPT` module into MPMD
    stages: ``wte``/``wpe`` embedding prologue on stage 0, the
    :func:`~..models.gpt.make_block_stage` trunk per stage, and the
    ``ln_f`` + untied-LM-head cross-entropy epilogue on the last stage.
    """
    import jax
    import jax.numpy as jnp

    from ray_lightning_tpu.models.gpt import (
        _layer_norm,
        gpt_adamw,
        make_block_stage,
    )

    cfg = module.config
    if compute_dtype is None:
        compute_dtype = (
            jnp.bfloat16 if module.precision in ("bf16", "bfloat16")
            else jnp.float32
        )
    stage_fn = make_block_stage(cfg, compute_dtype=compute_dtype)

    def embed_fn(params, batch):
        tokens = batch["tokens"][:, :-1]
        t = tokens.shape[1]
        return (params["wte"][tokens] + params["wpe"][:t]).astype(
            compute_dtype
        )

    def loss_fn(params, x, batch):
        targets = batch["tokens"][:, 1:]
        x = _layer_norm(x, params["ln_f_g"], params["ln_f_b"])
        logits = jnp.einsum(
            "btd,vd->btv",
            x.astype(jnp.float32),
            params["head_w"].astype(jnp.float32),
        )
        logz = jax.nn.logsumexp(logits, axis=-1)
        ll = jnp.take_along_axis(
            logits, targets[..., None], axis=-1
        )[..., 0]
        loss = (logz - ll).mean()
        return loss, {"loss": loss}

    def split_params(full, plan: StagePlan, stage: int):
        full = _gpt_untie(full)
        out: Dict[str, Any] = {
            "blocks": plan.slice_stacked(full["blocks"], stage)
        }
        if plan.is_first(stage):
            out["wte"] = full["wte"]
            out["wpe"] = full["wpe"]
        if plan.is_last(stage):
            out["ln_f_g"] = full["ln_f_g"]
            out["ln_f_b"] = full["ln_f_b"]
            out["head_w"] = full["head_w"]
        return out

    def assemble_params(stage_params: List[Any], plan: StagePlan):
        if len(stage_params) != plan.n_stages:
            raise ValueError(
                f"{len(stage_params)} stage param trees for "
                f"{plan.n_stages} stages"
            )
        import numpy as np

        first, last = stage_params[0], stage_params[-1]
        blocks = {
            key: np.concatenate(
                [np.asarray(sp["blocks"][key]) for sp in stage_params],
                axis=0,
            )
            for key in first["blocks"]
        }
        return {
            "wte": np.asarray(first["wte"]),
            "wpe": np.asarray(first["wpe"]),
            "blocks": blocks,
            "ln_f_g": np.asarray(last["ln_f_g"]),
            "ln_f_b": np.asarray(last["ln_f_b"]),
            "head_w": np.asarray(last["head_w"]),
        }

    return MpmdSpec(
        n_layers=cfg.n_layer,
        embed_fn=embed_fn,
        stage_fn=stage_fn,
        loss_fn=loss_fn,
        split_params=split_params,
        assemble_params=assemble_params,
        # The family's adamw WITHOUT the global-norm clip: the clip
        # couples leaves across stages and does not decompose — per-
        # stage clipping would be a silently different optimizer (the
        # module docstring's cross-stage-coupling caveat, made real).
        tx_factory=lambda: gpt_adamw(cfg),
    )


def resolve_mpmd_spec(module) -> MpmdSpec:
    """The MpmdSpec for a module: an explicit ``module.mpmd_spec()``
    wins; GPT modules get the built-in adapter; anything else is a
    loud error (pipelining needs model knowledge no generic wrapper
    can infer)."""
    maker = getattr(module, "mpmd_spec", None)
    if maker is not None:
        spec = maker()
        if not isinstance(spec, MpmdSpec):
            raise TypeError(
                f"{type(module).__name__}.mpmd_spec() returned "
                f"{type(spec).__name__}, expected MpmdSpec"
            )
        return spec
    from ray_lightning_tpu.models.gpt import GPT

    if isinstance(module, GPT):
        return gpt_mpmd_spec(module)
    raise TypeError(
        f"MpmdStrategy needs a stage decomposition for "
        f"{type(module).__name__}: implement mpmd_spec() -> MpmdSpec "
        "(see ray_lightning_tpu.mpmd.plan) or use a GPT module."
    )
