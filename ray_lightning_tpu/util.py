"""Driver-side utilities: the result pump.

TPU-native analogue of ``/root/reference/ray_lightning/util.py:47-68``.
While worker actors run the fit loop, the driver sits in
:func:`process_results`, interleaving two duties:

1. drain the distributed queue — items are either plain metric payloads or
   **thunks** (cloudpickled callables) that must execute *in driver
   context* (the Tune-report indirection, reference ``tune.py:130-134``:
   ``tune.report`` only works inside the Tune session process);
2. poll worker futures so a worker crash surfaces immediately as an
   exception instead of a hang (reference ``util.py:55-68``);
3. run the ``on_tick`` hook between drains — the RunMonitor's watchdog
   heartbeat-age/stall checks happen here, because a *hung* fleet sends
   no items to react to (``telemetry/monitor.py``).
"""

from __future__ import annotations

import time
import warnings
from typing import Any, Callable, List, Optional, Sequence

from .cluster.queue import DriverQueue

__all__ = ["process_results", "handle_queue_item"]


def handle_queue_item(item: Any) -> Any:
    """Execute a queue item in driver context (reference ``util.py:47-52``)."""
    if callable(item):
        return item()
    return item


def _drain_queue(queue: Optional[DriverQueue], on_item: Optional[Callable]) -> None:
    if queue is None:
        return
    while not queue.empty():
        item = queue.get_nowait()
        result = handle_queue_item(item)
        if on_item is not None and not callable(item):
            # A raising observer must not abandon the pump: the futures
            # (and the fit result riding them) are still out there, and
            # bailing here would leak the workers AND drop the result.
            try:
                on_item(result)
            except Exception as e:  # noqa: BLE001 - observer, not owner
                warnings.warn(
                    f"stream-item callback failed ({e!r}); item dropped"
                )


def _safe_tick(on_tick: Optional[Callable[[], None]]) -> None:
    if on_tick is None:
        return
    try:
        on_tick()
    except Exception as e:  # noqa: BLE001 - monitoring must never cost
        # the fit result.
        warnings.warn(f"pump tick callback failed ({e!r})")


def process_results(
    futures: Sequence[Any],
    queue: Optional[DriverQueue] = None,
    poll_interval_s: float = 0.1,
    on_item: Optional[Callable[[Any], None]] = None,
    on_tick: Optional[Callable[[], None]] = None,
) -> List[Any]:
    """Block until all worker futures resolve, pumping the queue meanwhile.

    Raises the first worker exception encountered (fail-fast, matching the
    reference where ``ray.get`` re-raises worker errors and crashes fit —
    SURVEY §5 "failure detection").  Before raising, the queue is drained a
    final time so late metrics/thunks are not lost.

    ``on_item`` observes non-thunk items; ``on_tick`` runs once per poll
    iteration.  Both are *observers*: an exception from either is warned
    about and swallowed — the fit result must survive a broken callback.
    """
    futures = list(futures)
    while True:
        _drain_queue(queue, on_item)
        _safe_tick(on_tick)
        done = [f for f in futures if f.done()]
        # Fail fast: one dead worker must raise immediately — its peers may
        # be blocked inside a collective waiting for it and will never
        # finish (reference raises from ray.get inside the poll loop,
        # util.py:55-63).
        for f in done:
            exc = f.exception()
            if exc is not None:
                _drain_queue(queue, on_item)
                raise exc
        if len(done) == len(futures):
            break
        time.sleep(poll_interval_s)
    _drain_queue(queue, on_item)
    return [f.result() for f in futures]
