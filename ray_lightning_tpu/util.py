"""Driver-side utilities: the result pump.

TPU-native analogue of ``/root/reference/ray_lightning/util.py:47-68``.
While worker actors run the fit loop, the driver sits in
:func:`process_results`, interleaving two duties:

1. drain the distributed queue — items are either plain metric payloads or
   **thunks** (cloudpickled callables) that must execute *in driver
   context* (the Tune-report indirection, reference ``tune.py:130-134``:
   ``tune.report`` only works inside the Tune session process);
2. poll worker futures so a worker crash surfaces immediately as an
   exception instead of a hang (reference ``util.py:55-68``).
"""

from __future__ import annotations

import time
from typing import Any, Callable, List, Optional, Sequence

from .cluster.queue import DriverQueue

__all__ = ["process_results", "handle_queue_item"]


def handle_queue_item(item: Any) -> Any:
    """Execute a queue item in driver context (reference ``util.py:47-52``)."""
    if callable(item):
        return item()
    return item


def _drain_queue(queue: Optional[DriverQueue], on_item: Optional[Callable]) -> None:
    if queue is None:
        return
    while not queue.empty():
        item = queue.get_nowait()
        result = handle_queue_item(item)
        if on_item is not None and not callable(item):
            on_item(result)


def process_results(
    futures: Sequence[Any],
    queue: Optional[DriverQueue] = None,
    poll_interval_s: float = 0.1,
    on_item: Optional[Callable[[Any], None]] = None,
) -> List[Any]:
    """Block until all worker futures resolve, pumping the queue meanwhile.

    Raises the first worker exception encountered (fail-fast, matching the
    reference where ``ray.get`` re-raises worker errors and crashes fit —
    SURVEY §5 "failure detection").  Before raising, the queue is drained a
    final time so late metrics/thunks are not lost.
    """
    futures = list(futures)
    while True:
        _drain_queue(queue, on_item)
        done = [f for f in futures if f.done()]
        # Fail fast: one dead worker must raise immediately — its peers may
        # be blocked inside a collective waiting for it and will never
        # finish (reference raises from ray.get inside the poll loop,
        # util.py:55-63).
        for f in done:
            exc = f.exception()
            if exc is not None:
                _drain_queue(queue, on_item)
                raise exc
        if len(done) == len(futures):
            break
        time.sleep(poll_interval_s)
    _drain_queue(queue, on_item)
    return [f.result() for f in futures]
