"""Step-stats engine: where does the step time go, and how fast is it.

Per-step wall time is split into three host-observable phases:

* **data_wait** — time the loop spent blocked on the (prefetched) input
  pipeline before the batch was ready;
* **dispatch** — time inside the jitted step call.  Under async dispatch
  this is host-side tracing/enqueue cost, NOT device compute — on a
  healthy run it is small and roughly constant;
* **device step** — measured on a periodic sampling window: every
  ``sample_every``-th step the engine calls ``block_until_ready`` on the
  step's outputs, so that step's wall time includes device execution.
  Sampling keeps the async-dispatch pipeline intact between samples (a
  per-step sync would serialize host and device and show up as exactly
  the overhead this subsystem promises not to add).

On top of the split: examples/sec + tokens/sec throughput, an analytic
FLOPs MFU estimate for the GPT/ViT model families (the same accounting
``bench.py`` publishes, now computed live inside any fit), recompile
counters hooked via ``jax.monitoring`` event listeners, and
``jax.local_devices()`` memory stats where the backend exposes them
(TPU yes, CPU no — best-effort by design).

The first step is recorded as **compile** (trace + XLA compile dominate
it) and excluded from steady-state aggregates; without that exclusion a
short fit's ``step_time_ms`` would be mostly compiler.
"""

from __future__ import annotations

import os
import time
from typing import Any, Dict, Optional, Tuple

__all__ = [
    "StepStats",
    "model_flops_per_token",
    "vit_flops_per_example",
    "flops_for_module",
    "peak_flops_per_chip",
    "compile_event_count",
    "compile_time_total_s",
]


# ---------------------------------------------------------------------------
# Analytic FLOPs (the published-MFU accounting, shared with bench.py)
# ---------------------------------------------------------------------------

def model_flops_per_token(cfg: Any, attn: str = "full") -> float:
    """Fwd+bwd matmul FLOPs per token for the GPT family (backward = 2x
    forward, no remat-recompute credit).

    ``attn="full"`` charges the full S² attention matrix (the standard
    published-MFU convention); ``attn="causal"`` charges the causal half
    the kernels actually execute.
    """
    d, L, s, V = cfg.d_model, cfg.n_layer, cfg.seq_len, cfg.vocab_size
    mm = 24 * L * d * d          # qkv + proj + mlp weight matmuls
    attn_term = 4 * L * s * d    # QK^T and AV, full square
    if attn == "causal":
        attn_term /= 2
    head = 2 * d * V             # tied LM head
    return 3.0 * (mm + attn_term + head)


def vit_flops_per_example(cfg: Any) -> float:
    """Fwd+bwd matmul FLOPs per image for the ViT family (patch embed +
    transformer blocks over ``n_patches + 1`` tokens + classifier head)."""
    d, L = cfg.d_model, cfg.n_layer
    s = cfg.n_patches + 1        # +1 CLS token
    mm = 24 * L * d * d * s      # block weight matmuls, whole sequence
    attn_term = 4 * L * s * s * d
    embed = 2 * cfg.patch_dim * d * cfg.n_patches
    head = 2 * d * cfg.num_classes
    return 3.0 * (mm + attn_term + embed + head)


def flops_for_module(module: Any) -> Tuple[Optional[float], Optional[int]]:
    """``(flops_per_example, tokens_per_example)`` for a known model
    family, ``(None, None)`` otherwise (MFU is then simply not reported
    — never guessed)."""
    cfg = getattr(module, "cfg", None) or getattr(module, "config", None)
    if cfg is None:
        return None, None
    kind = type(cfg).__name__
    try:
        if kind == "GPTConfig":
            return model_flops_per_token(cfg) * cfg.seq_len, cfg.seq_len
        if kind == "ViTConfig":
            return vit_flops_per_example(cfg), None
    except AttributeError:
        return None, None
    return None, None


# Peak bf16 FLOP/s per chip by device_kind substring (dense MXU peak).
_PEAK_FLOPS = (
    ("v5 lite", 197e12),   # v5e
    ("v5e", 197e12),
    ("v5p", 459e12),
    ("v6", 918e12),        # Trillium
    ("v4", 275e12),
    ("v3", 123e12),
    ("v2", 45e12),
)


def peak_flops_per_chip() -> Optional[float]:
    """Dense bf16 peak of the local accelerator, or ``None`` when the
    backend has no published peak (CPU meshes: an "MFU" against an
    arbitrary denominator would be noise, so none is reported).
    ``RLT_TELEMETRY_PEAK`` overrides (also how CPU tests pin the MFU
    math)."""
    env = os.environ.get("RLT_TELEMETRY_PEAK")
    if env:
        return float(env)
    import jax

    try:
        dev = jax.devices()[0]
    except RuntimeError:
        return None
    if dev.platform != "tpu":
        return None
    kind = dev.device_kind.lower()
    for key, peak in _PEAK_FLOPS:
        if key in kind:
            return peak
    return 197e12  # unknown TPU: assume v5e-class


# ---------------------------------------------------------------------------
# Recompile counter (process-wide jax.monitoring hook)
# ---------------------------------------------------------------------------

# One listener per process, installed on first use: jax.monitoring has no
# per-listener deregistration (clear_event_listeners drops EVERYTHING),
# so a listener per StepStats would accumulate across tuner-sweep fits.
_COMPILES = [0]
_COMPILE_S = [0.0]
_LISTENER = [False]
_COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"


def _install_listener() -> None:
    if _LISTENER[0]:
        return
    import jax.monitoring

    def _on_duration(event: str, duration: float, **kw) -> None:
        if event == _COMPILE_EVENT:
            _COMPILES[0] += 1
            _COMPILE_S[0] += float(duration)

    jax.monitoring.register_event_duration_secs_listener(_on_duration)
    _LISTENER[0] = True


def compile_event_count() -> int:
    """Process-lifetime XLA backend compiles observed so far.

    Installs the jax.monitoring listener on first call: every consumer
    of this counter measures DELTAS (``before = compile_event_count()``
    … ``assert compile_event_count() - before == 0``), and without the
    eager install a process that never built a :class:`StepStats` —
    a standalone serve test, a bench entry point — would pin
    "zero recompiles" against a counter that was never counting."""
    _install_listener()
    return _COMPILES[0]


def compile_time_total_s() -> float:
    """Process-lifetime seconds spent inside XLA backend compiles (the
    duration side of the same jax.monitoring event
    :func:`compile_event_count` counts).  Heartbeats and the StepStats
    report surface it so a fleet whose wall time is going to the
    compiler says so instead of reading as slow steps."""
    _install_listener()
    return _COMPILE_S[0]


# ---------------------------------------------------------------------------
# The engine
# ---------------------------------------------------------------------------

class _Agg:
    """Running min/max/sum of one per-step duration."""

    __slots__ = ("n", "total", "min", "max")

    def __init__(self):
        self.n = 0
        self.total = 0.0
        self.min = float("inf")
        self.max = 0.0

    def add(self, v: float) -> None:
        self.n += 1
        self.total += v
        if v < self.min:
            self.min = v
        if v > self.max:
            self.max = v

    def add_scaled(self, total: float, n: int) -> None:
        """Book ``n`` steps observed as ONE wall measurement (a megastep
        stride): the mean stays exact (``total``/``n`` ride the sums);
        min/max see the stride's per-step AVERAGE — inner-step extremes
        are invisible to the host by design."""
        self.n += n
        self.total += total
        per = total / n
        if per < self.min:
            self.min = per
        if per > self.max:
            self.max = per

    def summary_ms(self) -> Dict[str, float]:
        if not self.n:
            return {}
        return {
            "mean_ms": 1e3 * self.total / self.n,
            "min_ms": 1e3 * self.min,
            "max_ms": 1e3 * self.max,
        }


class StepStats:
    """Aggregates the per-step timing split for one fit on one rank.

    The loop owns the clocks (it has the marks anyway) and feeds each
    step via :meth:`record_step`; this class only aggregates — cheap
    float math, no device traffic, no allocation per step beyond the
    aggregator updates.
    """

    def __init__(self, sample_every: int = 32,
                 flops_per_example: Optional[float] = None,
                 tokens_per_example: Optional[int] = None,
                 peak_flops: Optional[float] = None,
                 n_chips: int = 1):
        if sample_every < 1:
            raise ValueError("sample_every must be >= 1")
        self.sample_every = sample_every
        self.flops_per_example = flops_per_example
        self.tokens_per_example = tokens_per_example
        self.measured_flops_per_example: Optional[float] = None
        self.mfu_basis = "analytic"
        self._drift_warned = False
        self.peak_flops = peak_flops
        self.n_chips = max(int(n_chips), 1)
        _install_listener()
        self._compiles_at_start = compile_event_count()
        self._compile_s_at_start = compile_time_total_s()
        self.compile_ms: Optional[float] = None
        self.steps = 0
        self.examples = 0
        self.tokens = 0
        self._step = _Agg()
        self._data_wait = _Agg()
        self._dispatch = _Agg()
        self._device = _Agg()   # sampled (block_until_ready) steps only
        self._t_first: Optional[float] = None
        self._t_last: Optional[float] = None

    def configure_model(self, module: Any) -> None:
        """Late-bind the analytic-FLOPs model (the loop knows the module
        after telemetry is built)."""
        if self.flops_per_example is None:
            fpe, tpe = flops_for_module(module)
            self.flops_per_example = fpe
            self.tokens_per_example = tpe
        if self.peak_flops is None:
            self.peak_flops = peak_flops_per_chip()

    def configure_measured_flops(self, flops_per_example: float) -> None:
        """Adopt the program ledger's XLA-measured FLOPs as the MFU
        numerator (``mfu_basis`` flips to ``"measured"``).  The drift
        guard fires once when the measured number disagrees with the
        analytic ``model_flops_per_token`` accounting by more than 10%
        — either the hand-written model drifted from the architecture,
        or XLA is executing work the model does not charge (remat,
        padding); both mean the published MFU needs a second look."""
        if flops_per_example <= 0:
            return
        analytic = self.flops_per_example
        if analytic and not self._drift_warned:
            drift = abs(flops_per_example - analytic) / analytic
            if drift > 0.10:
                self._drift_warned = True
                import logging

                logging.getLogger(
                    "ray_lightning_tpu.telemetry"
                ).warning(
                    "MFU drift: ledger-measured FLOPs/example %.3e vs "
                    "analytic %.3e (%.1f%% apart) — MFU now reports on "
                    "the measured basis",
                    flops_per_example, analytic, 100.0 * drift,
                )
        self.measured_flops_per_example = float(flops_per_example)
        self.mfu_basis = "measured"

    # -- per-step feed ------------------------------------------------------
    def should_sample(self) -> bool:
        """True when the NEXT recorded step should block_until_ready so
        its wall time includes device compute.  Never the compile step
        (step 0), always shortly after it (step 1 gives an early honest
        number), then every ``sample_every``-th."""
        if self.steps == 0:
            return False
        return self.steps == 1 or self.steps % self.sample_every == 0

    def should_sample_stride(self, k: int) -> bool:
        """Stride-shaped :meth:`should_sample`: never the compile stride
        (the first record), always the stride right after it (the early
        honest number), then whenever the stride crosses the
        ``sample_every`` cadence — so megastep fits sample device time at
        the same step frequency the per-step loop does."""
        if self.steps == 0:
            return False
        return (
            self.steps <= k
            or (self.steps // self.sample_every)
            != ((self.steps + k) // self.sample_every)
        )

    def _record_midfit_compile(self, wall_s: float, k: int) -> None:
        """A first-use program compiled MID-fit (megastep's lazy tail /
        chaos-degraded single-step program, or the fused scan after a
        singles-only start): book the wall as compile time and excise
        the interval from the throughput window — steady-state
        ``step_time_ms``/``dispatch_ms``/tokens-per-sec must not carry a
        multi-second XLA outlier the way a hidden ordinary record would.
        """
        self.compile_ms = (self.compile_ms or 0.0) + 1e3 * wall_s
        self.steps += k
        if self._t_first is not None:
            self._t_first += wall_s

    def record_stride(self, stride_s: float, data_wait_s: float,
                      dispatch_s: float, examples: int, k: int,
                      sampled: bool = False, compiled: bool = False) -> None:
        """One megastep stride = ``k`` micro-steps in one dispatch.

        Headline attribution divides by ``k``: ``step_time_ms`` stays a
        PER-MICRO-STEP number (comparable across megastep on/off runs),
        with ``k`` steps booked per call via the scaled aggregators.
        The first stride is booked as compile, like step 0 on the
        per-step path — it is dominated by the scan trace + XLA compile
        (the k-1 fused steps riding along are noise next to it).
        ``compiled=True`` marks a mid-fit first-use compile (see
        :meth:`_record_midfit_compile`).
        """
        if self.steps == 0:
            self.compile_ms = 1e3 * stride_s
            self.steps = k
            self._t_first = time.perf_counter()
            return
        if compiled:
            self._record_midfit_compile(stride_s, k)
            return
        self.steps += k
        self.examples += int(examples)
        if self.tokens_per_example:
            self.tokens += int(examples) * self.tokens_per_example
        self._step.add_scaled(stride_s, k)
        self._data_wait.add_scaled(data_wait_s, k)
        self._dispatch.add_scaled(dispatch_s, k)
        if sampled:
            self._device.add_scaled(stride_s, k)
        self._t_last = time.perf_counter()

    def record_step(self, step_s: float, data_wait_s: float,
                    dispatch_s: float, examples: int,
                    sampled: bool = False, compiled: bool = False) -> None:
        """One loop iteration: total wall, input wait, jit-call time.

        ``sampled=True`` marks a step whose caller synced the device
        before the end mark — its wall time feeds the device-step
        aggregate.  Step 0 is booked as compile time, not steady state;
        ``compiled=True`` marks a mid-fit first-use compile (see
        :meth:`_record_midfit_compile`).
        """
        if self.steps == 0:
            self.compile_ms = 1e3 * step_s
            self.steps = 1
            self._t_first = time.perf_counter()
            return
        if compiled:
            self._record_midfit_compile(step_s, 1)
            return
        self.steps += 1
        self.examples += int(examples)
        if self.tokens_per_example:
            self.tokens += int(examples) * self.tokens_per_example
        self._step.add(step_s)
        self._data_wait.add(data_wait_s)
        self._dispatch.add(dispatch_s)
        if sampled:
            self._device.add(step_s)
        self._t_last = time.perf_counter()

    # -- derived numbers ----------------------------------------------------
    @property
    def recompiles(self) -> int:
        """XLA backend compiles since this fit started (>1 on a shape
        change or donation-layout miss — the silent 20-40s step)."""
        return compile_event_count() - self._compiles_at_start

    def throughput(self) -> Dict[str, float]:
        if self._t_first is None or self._t_last is None:
            return {}
        wall = self._t_last - self._t_first
        if wall <= 0 or not self.examples:
            return {}
        out = {"examples_per_sec": self.examples / wall}
        if self.tokens:
            out["tokens_per_sec"] = self.tokens / wall
        return out

    def mfu(self) -> Optional[float]:
        """Model-FLOPs utilisation vs the chip's dense peak, ``None``
        when either side is unknown.  The numerator is the ledger's
        XLA-measured FLOPs when :meth:`configure_measured_flops` ran
        (``mfu_basis == "measured"``), the analytic model otherwise."""
        fpe = self.measured_flops_per_example or self.flops_per_example
        if not (fpe and self.peak_flops):
            return None
        tp = self.throughput().get("examples_per_sec")
        if not tp:
            return None
        return tp * fpe / (self.peak_flops * self.n_chips)

    def memory_stats(self) -> Dict[str, float]:
        """Device memory stats where the backend exposes them."""
        try:
            import jax

            stats = jax.local_devices()[0].memory_stats()
        except Exception:  # noqa: BLE001 - absent on CPU, best-effort
            return {}
        if not stats:
            return {}
        out = {}
        for key in ("bytes_in_use", "peak_bytes_in_use", "bytes_limit"):
            if key in stats:
                out[key] = float(stats[key])
        return out

    def headline(self) -> Dict[str, float]:
        """The numbers a fit surfaces through ``callback_metrics``."""
        out: Dict[str, float] = {}
        if self._step.n:
            out["step_time_ms"] = 1e3 * self._step.total / self._step.n
            out["data_wait_ms"] = (
                1e3 * self._data_wait.total / self._data_wait.n
            )
            out["dispatch_ms"] = (
                1e3 * self._dispatch.total / self._dispatch.n
            )
        if self._device.n:
            out["device_step_ms"] = 1e3 * self._device.total / self._device.n
        out.update(self.throughput())
        m = self.mfu()
        if m is not None:
            out["mfu"] = m
        out["recompiles"] = float(self.recompiles)
        return out

    def summary(self) -> Dict[str, Any]:
        """Full picklable snapshot (rides the result package)."""
        out: Dict[str, Any] = {
            "steps": self.steps,
            "examples": self.examples,
            "recompiles": self.recompiles,
            "sample_every": self.sample_every,
        }
        if self.tokens:
            out["tokens"] = self.tokens
        if self.compile_ms is not None:
            out["compile_ms"] = self.compile_ms
        # XLA-reported compile seconds for THIS fit (jax.monitoring
        # durations, satellite of the program ledger): compile_ms above
        # is the step-0 wall, this is the compiler's own accounting —
        # including mid-fit lazy programs that never dominate a step.
        compile_s = compile_time_total_s() - self._compile_s_at_start
        if compile_s > 0:
            out["compile_total_s"] = round(compile_s, 6)
        for name, agg in (("step", self._step),
                          ("data_wait", self._data_wait),
                          ("dispatch", self._dispatch),
                          ("device_step", self._device)):
            for k, v in agg.summary_ms().items():
                out[f"{name}_{k}"] = v
        out.update(self.throughput())
        m = self.mfu()
        if m is not None:
            out["mfu"] = m
            out["mfu_basis"] = self.mfu_basis
        mem = self.memory_stats()
        if mem:
            out["memory"] = mem
        return out
