"""Driver-side run monitor: live liveness/progress tracking for a fit.

While workers train, the driver sits in ``process_results`` pumping the
queue.  :class:`RunMonitor` rides that pump (``on_item`` consumes the
typed stream items, ``tick`` runs between drains) and turns the
heartbeat stream (``telemetry/heartbeat.py``) into actionable state:

* **liveness** — a rank whose beats stop for ``hang_intervals``
  heartbeat periods is flagged ``heartbeat_lost`` (process/network
  death the futures may take much longer to surface);
* **hang** — beats flowing but the progress counter frozen for
  ``hang_intervals`` periods flags a ``stall`` (the wedged-collective
  signature).  The monitor then requests an out-of-band py-stack +
  device-memory dump from the suspect worker
  (``ProcessActor.dump_stacks`` — served even while the fit call is
  running) and records it as a ``stack_dump`` event;
* **live stragglers** — a rank lagging the fleet median ``global_step``
  by more than ``straggler_lag_steps`` is flagged while the skew is
  happening, not post-hoc;
* **abort** — with ``abort_after_s`` set, a hang persisting past the
  deadline triggers the abort callback (the strategy kills the worker
  set; the fit raises instead of waiting forever);
* **export** — a ``live.json`` snapshot for ``tools/rlt_top.py`` and an
  optional OpenMetrics textfile / localhost HTTP endpoint
  (``telemetry/export_prom.py``).

Single-threaded by design: ``on_item``/``tick``/``finalize`` are all
called from the driver's pump loop.  jax-free.
"""

from __future__ import annotations

import collections
import dataclasses
import json
import os
import statistics
import time
from typing import Any, Callable, Dict, List, Optional

__all__ = ["MonitorConfig", "RunMonitor", "make_event"]

_EVENT_CAP = 500
_RANK_LOG_CAP = 50
_STACK_EVENT_CHAR_CAP = 32768


@dataclasses.dataclass(frozen=True)
class MonitorConfig:
    """Driver-side monitor knobs (``monitor=`` on any strategy, or the
    ``RLT_MONITOR_*`` / ``RLT_PROM_*`` env bus)."""

    heartbeat_s: float = 5.0       # mirrors TelemetryConfig.heartbeat_s
    hang_intervals: int = 3        # K: silence/stall budget in beats
    abort_after_s: Optional[float] = None   # None = never abort
    straggler_lag_steps: int = 200
    live_every_s: float = 1.0      # live.json / prom refresh cadence
    out_dir: Optional[str] = None  # live.json home (None = no file)
    prom_file: Optional[str] = None
    prom_port: Optional[int] = None

    def __post_init__(self):
        if self.heartbeat_s <= 0:
            raise ValueError("heartbeat_s must be > 0")
        if self.hang_intervals < 1:
            raise ValueError("hang_intervals must be >= 1")
        if self.abort_after_s is not None and self.abort_after_s <= 0:
            raise ValueError("abort_after_s must be > 0 (or None)")

    @classmethod
    def coerce(cls, value: Any,
               heartbeat_s: Optional[float] = None) -> "MonitorConfig":
        """None | dict | MonitorConfig → MonitorConfig, with the
        ``RLT_MONITOR_*``/``RLT_PROM_*`` env bus filling unset knobs —
        the same resolution contract as ``TelemetryConfig.coerce``."""
        if isinstance(value, cls):
            return value
        if value is None:
            kw: Dict[str, Any] = {}
        elif isinstance(value, dict):
            kw = dict(value)
        else:
            raise TypeError(
                "monitor must be a dict or MonitorConfig; got "
                f"{type(value).__name__}"
            )
        if heartbeat_s is not None:
            kw.setdefault("heartbeat_s", heartbeat_s)
        env_map = {
            "hang_intervals": ("RLT_MONITOR_HANG_INTERVALS", int),
            "abort_after_s": ("RLT_MONITOR_ABORT_S", float),
            "straggler_lag_steps": ("RLT_MONITOR_STRAGGLER_LAG", int),
            "out_dir": ("RLT_MONITOR_DIR", str),
            "prom_file": ("RLT_PROM_FILE", str),
            "prom_port": ("RLT_PROM_PORT", int),
        }
        for field, (var, cast) in env_map.items():
            raw = os.environ.get(var)
            if raw and field not in kw:
                kw[field] = cast(raw)
        return cls(**kw)


def make_event(kind: str, rank: int, **fields: Any) -> Dict[str, Any]:
    """A schema-shaped event document
    (``telemetry/schema.py:validate_event``); rank -1 = fleet-wide."""
    return {"type": "event", "kind": kind, "rank": rank,
            "ts": time.time(), **fields}


class _RankState:
    """Everything the monitor knows about one rank."""

    __slots__ = (
        "beats", "last_beat", "last_beat_at", "last_progress_at",
        "progress_seen", "done", "flagged_lost", "flagged_stalled",
        "flagged_straggler", "logs", "crash_bundle", "drain_ckpt",
    )

    def __init__(self):
        self.beats = 0
        self.last_beat: Dict[str, Any] = {}
        self.last_beat_at: Optional[float] = None
        self.last_progress_at: Optional[float] = None
        self.progress_seen = False  # armed only after real progress
        self.done = False
        self.flagged_lost = False
        self.flagged_stalled = False
        self.flagged_straggler = False
        self.logs: collections.deque = collections.deque(
            maxlen=_RANK_LOG_CAP
        )
        self.crash_bundle: Optional[str] = None
        self.drain_ckpt: Optional[str] = None  # drain event's checkpoint

    def status(self, now: float, hang_s: float) -> str:
        if self.crash_bundle:
            return "crashed"
        if self.done:
            return "done"
        if self.flagged_lost or (
            self.last_beat_at is not None
            and now - self.last_beat_at > hang_s
        ):
            return "lost"
        if self.flagged_stalled:
            return "stalled"
        return "ok"


class RunMonitor:
    """Consumes one fit's stream items; see module docstring.

    ``dump_cb(rank) -> dict`` asks the strategy for a py-stack dump of
    one worker; ``abort_cb(reason)`` asks it to kill the worker set.
    Both optional — the monitor degrades to pure bookkeeping.
    """

    def __init__(self, config: MonitorConfig, world_size: int,
                 dump_cb: Optional[Callable[[int], Dict[str, Any]]] = None,
                 abort_cb: Optional[Callable[[str], None]] = None,
                 now_fn: Callable[[], float] = time.monotonic):
        self.config = config
        self.world_size = world_size
        self._dump_cb = dump_cb
        self._abort_cb = abort_cb
        self._now = now_fn
        self._ranks: Dict[int, _RankState] = {}
        self.events: List[Dict[str, Any]] = []
        self.beats_received = 0
        self.aborted = False
        self.abort_reason: Optional[str] = None
        self._hang_since: Optional[float] = None
        self._last_check = now_fn()
        self._last_live_write = 0.0
        self._exporter = None
        if config.prom_file or config.prom_port is not None:
            from .export_prom import PromExporter

            self._exporter = PromExporter(
                textfile=config.prom_file, port=config.prom_port
            )
        # Optional trend retention (the SLO/capacity plane's sensing
        # layer): when a TimeSeriesStore is attached, every beat's
        # step-stats land as per-rank series — windowed step-time
        # percentiles and throughput slopes for the fleet scheduler.
        # None (the default) costs nothing on the beat path.
        self.timeseries = None

    def attach_timeseries(self, store) -> None:
        """Feed per-rank heartbeat step-stats into a
        :class:`~ray_lightning_tpu.telemetry.timeseries.TimeSeriesStore`."""
        self.timeseries = store

    # -- stream consumption -------------------------------------------------
    def _state(self, rank: int) -> _RankState:
        st = self._ranks.get(rank)
        if st is None:
            st = self._ranks[rank] = _RankState()
        return st

    def on_item(self, item: Any) -> None:
        if not isinstance(item, dict):
            return
        kind = item.get("type")
        if kind == "heartbeat":
            self._on_beat(item)
        elif kind == "event":
            self._record_event(item)
            if item.get("kind") == "crash":
                st = self._state(int(item.get("rank", -1)))
                st.crash_bundle = item.get("bundle")
            elif item.get("kind") == "drain":
                # A preemption drain in flight: remember the checkpoint
                # so a death in the drain window can NAME it.
                st = self._state(int(item.get("rank", -1)))
                st.drain_ckpt = item.get("ckpt") or st.drain_ckpt
        elif kind == "log":
            self._state(int(item.get("rank", 0))).logs.append(item)

    def _on_beat(self, beat: Dict[str, Any]) -> None:
        now = self._now()
        st = self._state(int(beat.get("rank", 0)))
        prev = st.last_beat
        st.beats += 1
        self.beats_received += 1
        if self.timeseries is not None:
            rank = int(beat.get("rank", 0))
            ts = beat.get("ts")
            for key, kind in (("step_time_ms", "hist"),
                              ("data_wait_ms", "hist"),
                              ("examples_per_sec", "gauge"),
                              ("progress", "counter")):
                value = beat.get(key)
                if isinstance(value, (int, float)):
                    self.timeseries.observe(
                        f"rank{rank}.{key}", value, kind=kind, ts=ts,
                    )
        st.last_beat = beat
        st.last_beat_at = now
        st.flagged_lost = False
        prev_progress = prev.get("progress", 0) if prev else 0
        phase_changed = bool(prev) and (
            beat.get("phase") != prev.get("phase")
        )
        advanced = (
            not prev
            or beat.get("progress", 0) > prev_progress
            or phase_changed
        )
        if advanced:
            st.last_progress_at = now
            if st.flagged_stalled:
                self._record_event(make_event(
                    "resumed", int(beat.get("rank", 0)),
                    message="progress resumed after stall",
                ))
            st.flagged_stalled = False
        # Stall detection arms per PHASE, after the first progress made
        # inside it: every phase's first step may hide a 20-40s XLA
        # compile (train step 0, first validation batch, a shape-change
        # recompile after a phase flip) that must not read as a hang.
        # heartbeat_lost still covers outright death during a compile.
        if phase_changed:
            st.progress_seen = False
        elif beat.get("progress", 0) > prev_progress:
            st.progress_seen = True
        if beat.get("done"):
            st.done = True

    def _record_event(self, event: Dict[str, Any]) -> None:
        if len(self.events) < _EVENT_CAP:
            self.events.append(event)

    # -- periodic checks (the pump's on_tick) -------------------------------
    def tick(self) -> None:
        now = self._now()
        check_every = max(0.05, min(1.0, self.config.heartbeat_s / 2.0))
        if now - self._last_check >= check_every:
            self._last_check = now
            self._check(now)
        self._maybe_export(now)

    def _check(self, now: float) -> None:
        cfg = self.config
        hang_s = cfg.hang_intervals * cfg.heartbeat_s
        hang_live = False
        for rank, st in sorted(self._ranks.items()):
            if st.done or st.crash_bundle or st.last_beat_at is None:
                continue
            # Beats stopped entirely: process/network death.
            if now - st.last_beat_at > hang_s:
                hang_live = True
                if not st.flagged_lost:
                    st.flagged_lost = True
                    self._record_event(make_event(
                        "heartbeat_lost", rank,
                        age_s=round(now - st.last_beat_at, 3),
                        message=(
                            f"rank {rank}: no heartbeat for "
                            f"{cfg.hang_intervals} intervals"
                        ),
                    ))
                    self._request_dump(rank)
                continue
            # Beats flowing, progress frozen: the wedged-collective
            # signature.  "closing" is exempt (final gather/serialize
            # legitimately shows no step progress), and detection only
            # arms after the rank has made real progress once — a long
            # first compile must not read as a hang.
            if (
                st.progress_seen
                and st.last_beat.get("phase") != "closing"
                and st.last_progress_at is not None
                and now - st.last_progress_at > hang_s
            ):
                hang_live = True
                if not st.flagged_stalled:
                    st.flagged_stalled = True
                    self._record_event(make_event(
                        "stall", rank,
                        age_s=round(now - st.last_progress_at, 3),
                        message=(
                            f"rank {rank}: beats flowing but progress "
                            f"frozen at step "
                            f"{st.last_beat.get('global_step', 0)}"
                        ),
                    ))
                    self._request_dump(rank)
        self._check_stragglers()
        # Abort deadline: measured from the moment a hang was first
        # detected, cleared when every rank is healthy again.
        if hang_live:
            if self._hang_since is None:
                self._hang_since = now
            if (
                cfg.abort_after_s is not None
                and not self.aborted
                and now - self._hang_since > cfg.abort_after_s
            ):
                self._abort(now)
        else:
            self._hang_since = None

    def _check_stragglers(self) -> None:
        live = [
            (rank, st) for rank, st in self._ranks.items()
            if st.last_beat and not st.done and not st.crash_bundle
        ]
        if len(live) < 2:
            return
        steps = [st.last_beat.get("global_step", 0) for _, st in live]
        median = statistics.median(steps)
        for rank, st in live:
            lag = median - st.last_beat.get("global_step", 0)
            if lag > self.config.straggler_lag_steps:
                if not st.flagged_straggler:
                    st.flagged_straggler = True
                    self._record_event(make_event(
                        "straggler", rank, lag_steps=int(lag),
                        message=(
                            f"rank {rank} lags the fleet median by "
                            f"{int(lag)} steps"
                        ),
                    ))
            else:
                st.flagged_straggler = False

    def _request_dump(self, rank: int) -> None:
        if self._dump_cb is None:
            return
        try:
            dump = self._dump_cb(rank) or {}
        except Exception as e:  # noqa: BLE001 - a dead worker cannot dump
            self._record_event(make_event(
                "stack_dump", rank, error=f"dump failed: {e!r}",
            ))
            return
        stacks = str(dump.get("stacks", ""))
        if len(stacks) > _STACK_EVENT_CHAR_CAP:
            stacks = stacks[:_STACK_EVENT_CHAR_CAP] + "\n…[truncated]"
        event = make_event("stack_dump", rank, stacks=stacks)
        mem = dump.get("device_memory")
        if isinstance(mem, dict) and mem:
            event["device_memory"] = mem
        self._record_event(event)

    def _abort(self, now: float) -> None:
        self.aborted = True
        suspects = sorted(
            rank for rank, st in self._ranks.items()
            if st.flagged_stalled or st.flagged_lost
        )
        self.abort_reason = (
            f"hang persisted past abort_after_s="
            f"{self.config.abort_after_s}s (suspect rank(s) {suspects})"
        )
        self._record_event(make_event(
            "abort", suspects[0] if len(suspects) == 1 else -1,
            message=self.abort_reason,
        ))
        if self._abort_cb is not None:
            try:
                self._abort_cb(self.abort_reason)
            except Exception as e:  # noqa: BLE001 - the raise path still
                # surfaces worker death; record that the abort misfired.
                self._record_event(make_event(
                    "abort", -1, error=f"abort callback failed: {e!r}",
                ))

    # -- surfaces -----------------------------------------------------------
    def snapshot(self) -> Dict[str, Any]:
        """Picklable live view (rlt_top / prom / live.json)."""
        now = self._now()
        hang_s = self.config.hang_intervals * self.config.heartbeat_s
        ranks = {}
        for rank, st in sorted(self._ranks.items()):
            entry = dict(st.last_beat)
            entry.pop("type", None)
            if st.last_beat_at is not None:
                entry["age_s"] = round(now - st.last_beat_at, 3)
            entry["status"] = st.status(now, hang_s)
            if st.crash_bundle:
                entry["bundle"] = st.crash_bundle
            ranks[str(rank)] = entry
        return {
            "ts": time.time(),
            "world_size": self.world_size,
            "ranks_reporting": len(self._ranks),
            "beats": self.beats_received,
            "aborted": self.aborted,
            "ranks": ranks,
            "events": self.events[-50:],
        }

    def event_counts(self) -> Dict[str, int]:
        counts: Dict[str, int] = {}
        for e in self.events:
            counts[e["kind"]] = counts.get(e["kind"], 0) + 1
        return counts

    def crash_bundles(self) -> List[str]:
        """Flight-bundle paths reported by crashed ranks, rank order."""
        return [
            st.crash_bundle
            for _, st in sorted(self._ranks.items())
            if st.crash_bundle
        ]

    def drain_checkpoints(self) -> List[str]:
        """Drain-checkpoint paths reported by draining ranks, rank
        order, deduped (on a multi-rank mesh every rank names the same
        sharded checkpoint directory)."""
        seen: List[str] = []
        for _, st in sorted(self._ranks.items()):
            if st.drain_ckpt and st.drain_ckpt not in seen:
                seen.append(st.drain_ckpt)
        return seen

    def last_heartbeat_age_s(self, rank: int) -> Optional[float]:
        st = self._ranks.get(rank)
        if st is None or st.last_beat_at is None:
            return None
        return round(self._now() - st.last_beat_at, 3)

    def _maybe_export(self, now: float) -> None:
        if now - self._last_live_write < self.config.live_every_s:
            return
        self._last_live_write = now
        self._export()

    def _export(self) -> None:
        snap = None
        if self.config.out_dir:
            snap = self.snapshot()
            try:
                os.makedirs(self.config.out_dir, exist_ok=True)
                path = os.path.join(self.config.out_dir, "live.json")
                tmp = path + ".tmp"
                with open(tmp, "w") as f:
                    json.dump(snap, f, indent=2, default=str)
                os.replace(tmp, path)
            except OSError:
                pass
        if self._exporter is not None:
            self._exporter.update(
                snap or self.snapshot(), self.event_counts()
            )

    def report(self) -> Dict[str, Any]:
        """The post-fit ``trainer.monitor_report`` payload."""
        snap = self.snapshot()
        report = {
            "events": list(self.events),
            "event_counts": self.event_counts(),
            "ranks": snap["ranks"],
            "beats": self.beats_received,
            "aborted": self.aborted,
            "crash_bundles": self.crash_bundles(),
        }
        if self.abort_reason:
            report["abort_reason"] = self.abort_reason
        logs = {
            str(rank): list(st.logs)
            for rank, st in sorted(self._ranks.items()) if st.logs
        }
        if logs:
            report["logs"] = logs
        return report

    def finalize(self) -> Dict[str, Any]:
        """Final export + exporter teardown; returns the report."""
        self._export()
        if self._exporter is not None:
            self._exporter.close()
            self._exporter = None
        return self.report()
