"""Telemetry artifact schemas + validators (the drift gate).

Artifact families leaving this subsystem: JSONL span dumps, Chrome
``trace_event`` documents, the ``telemetry`` block inside
``BENCH_*.json``, and — since the live-monitor round — the stream items
the worker→driver queue carries (``heartbeat``, ``event``, ``log``,
``metrics``) plus the crash flight bundle ``flight_recorder.py``
persists.  Downstream consumers (Perfetto, the trace-summary tool,
``rlt_top``, round-over-round bench comparison, post-mortem tooling)
parse them long after the producing code has moved on — so the schema
is written down HERE, and ``tools/check_telemetry_schema.py`` (wired
into ``format.sh``) fails fast when a producer drifts.

Validators return a list of problem strings (empty = valid) instead of
raising, so the CLI can report every problem in one pass.  jax-free.
"""

from __future__ import annotations

from typing import Any, Dict, List

__all__ = [
    "validate_span",
    "validate_span_jsonl",
    "validate_chrome_trace",
    "validate_trace_context",
    "validate_bench_trace",
    "validate_bench_telemetry",
    "validate_bench_fault",
    "validate_bench_host_overhead",
    "validate_bench_opt_state",
    "validate_bench_residual_policy",
    "validate_heartbeat",
    "validate_event",
    "validate_log_item",
    "validate_stream_item",
    "validate_flight_bundle",
    "validate_serve_request",
    "validate_serve_reply",
    "validate_serve_snapshot",
    "validate_serve_kv_handoff",
    "validate_serve_adapter_load",
    "validate_serve_migration",
    "validate_router_snapshot",
    "validate_bench_serve",
    "validate_bench_spec_decode",
    "validate_bench_prefix_cache",
    "validate_bench_chunked_prefill",
    "validate_bench_serve_disagg",
    "validate_bench_serve_chaos",
    "validate_bench_multi_lora",
    "validate_mpmd_stage_item",
    "validate_mpmd_xfer",
    "validate_mpmd_snapshot",
    "validate_bench_mpmd",
    "validate_bench_comm_overlap",
    "validate_program_row",
    "validate_recompile_record",
    "validate_program_snapshot",
    "validate_bench_programs",
    "validate_timeseries_point",
    "validate_slo_alert",
    "validate_capacity_snapshot",
    "validate_bench_slo",
    "FLIGHT_BUNDLE_SCHEMA_ID",
]

# JSONL span schema: required key → allowed types.
_SPAN_REQUIRED = {
    "name": str,
    "ts": (int, float),
    "dur": (int, float),
    "rank": int,
    "tid": int,
    "depth": int,
}
_SPAN_OPTIONAL = {"args": dict}

# Chrome complete-event schema (the subset our exporter emits and
# Perfetto requires).
_CHROME_X_REQUIRED = {
    "name": str,
    "ts": (int, float),
    "dur": (int, float),
    "pid": int,
    "tid": int,
}


def _check_fields(obj: Dict[str, Any], required: dict, optional: dict,
                  where: str) -> List[str]:
    problems = []
    if not isinstance(obj, dict):
        return [f"{where}: expected object, got {type(obj).__name__}"]
    for key, types in required.items():
        if key not in obj:
            problems.append(f"{where}: missing required key {key!r}")
        elif not isinstance(obj[key], types) or isinstance(obj[key], bool):
            problems.append(
                f"{where}: key {key!r} has type "
                f"{type(obj[key]).__name__}"
            )
    for key, types in optional.items():
        if key in obj and not isinstance(obj[key], types):
            problems.append(
                f"{where}: optional key {key!r} has type "
                f"{type(obj[key]).__name__}"
            )
    unknown = set(obj) - set(required) - set(optional)
    if unknown:
        problems.append(f"{where}: unknown keys {sorted(unknown)}")
    return problems


def validate_span(span: Dict[str, Any], where: str = "span") -> List[str]:
    problems = _check_fields(span, _SPAN_REQUIRED, _SPAN_OPTIONAL, where)
    if not problems and span["dur"] < 0:
        problems.append(f"{where}: negative dur {span['dur']}")
    return problems


# ---------------------------------------------------------------------------
# Distributed tracing: the trace-context envelope wire frames carry
# ---------------------------------------------------------------------------

# The "trace" dict riding (OPTIONALLY — old producers stay wire-
# compatible) every queue-plane frame family: serve_request,
# serve_kv_handoff, replica/prefill beats, mpmd_xfer, mpmd_stage,
# heartbeat and event items.  ``ts`` is the producer's wall-clock SEND
# time (epoch seconds) so the consumer can book the transfer interval.
_TRACE_CTX_REQUIRED = {
    "trace_id": str,
    "span_id": str,
}
_TRACE_CTX_OPTIONAL = {
    "parent_span_id": str,
    "ts": (int, float),
}


def validate_trace_context(trace: Any,
                           where: str = "trace") -> List[str]:
    problems = _check_fields(
        trace, _TRACE_CTX_REQUIRED, _TRACE_CTX_OPTIONAL, where
    )
    if not problems:
        if not trace["trace_id"]:
            problems.append(f"{where}: empty trace_id")
        if not trace["span_id"]:
            problems.append(f"{where}: empty span_id")
    return problems


def _check_optional_trace(item: Dict[str, Any], where: str) -> List[str]:
    """Validate the optional trace envelope when a frame carries one."""
    if isinstance(item, dict) and "trace" in item:
        return validate_trace_context(item["trace"], f"{where}.trace")
    return []


def validate_span_jsonl(lines: List[str], where: str = "jsonl") -> List[str]:
    """Validate a span JSONL dump given as decoded lines."""
    import json

    problems = []
    for i, line in enumerate(lines):
        line = line.strip()
        if not line:
            continue
        try:
            obj = json.loads(line)
        except ValueError as e:
            problems.append(f"{where}:{i + 1}: not JSON ({e})")
            continue
        problems.extend(validate_span(obj, f"{where}:{i + 1}"))
    return problems


def validate_chrome_trace(doc: Any, where: str = "trace") -> List[str]:
    """Validate a Chrome ``trace_event`` document (our exporter's
    ``{"traceEvents": [...]}`` form; ``ph=="X"`` events only — other
    phases pass through, Perfetto tolerates them)."""
    problems: List[str] = []
    if not isinstance(doc, dict):
        return [f"{where}: expected a trace document object"]
    events = doc.get("traceEvents")
    if not isinstance(events, list):
        return [f"{where}: missing/invalid traceEvents list"]
    for i, ev in enumerate(events):
        if not isinstance(ev, dict):
            problems.append(f"{where}[{i}]: event is not an object")
            continue
        if ev.get("ph") != "X":
            continue
        for key, types in _CHROME_X_REQUIRED.items():
            if key not in ev:
                problems.append(f"{where}[{i}]: missing {key!r}")
            elif (not isinstance(ev[key], types)
                  or isinstance(ev[key], bool)):
                problems.append(
                    f"{where}[{i}]: {key!r} has type "
                    f"{type(ev[key]).__name__}"
                )
        if isinstance(ev.get("dur"), (int, float)) and ev["dur"] < 0:
            problems.append(f"{where}[{i}]: negative dur")
    return problems


# ---------------------------------------------------------------------------
# Live-monitor stream items (the worker→driver queue wire format)
# ---------------------------------------------------------------------------

# Heartbeat: the compact per-rank liveness/progress record the
# HeartbeatPublisher enqueues every RLT_HEARTBEAT_S seconds.
_HEARTBEAT_REQUIRED = {
    "type": str,          # always "heartbeat"
    "rank": int,
    "seq": int,           # per-publisher monotonic counter
    "ts": (int, float),   # wall-clock (time.time) at compose
    "global_step": int,
    "micro_step": int,
    "epoch": int,
    "progress": int,      # loop progress counter (train + val batches)
    "phase": str,         # coarse loop phase: init/train/validation/closing
}
_HEARTBEAT_OPTIONAL = {
    "step_time_ms": (int, float),
    "data_wait_ms": (int, float),
    "examples_per_sec": (int, float),
    "open_span": str,            # deepest open span (full tier only)
    "device_memory": dict,       # jax memory_stats subset, best-effort
    "host_load": (int, float),   # 1-minute load average
    "done": bool,                # final beat before the publisher stops
    "trace": dict,               # optional trace-context envelope
    "compile_total_s": (int, float),  # process XLA compile seconds so far
}

# Event: structured monitor/worker occurrences (stall, stack_dump,
# heartbeat_lost, straggler, crash, abort — and, since the recovery-
# plane round: drain, preempt_restart, backoff, elastic_restart,
# ckpt_corrupt; since the elastic-world round: resize,
# resize_rejected).  rank == -1 means fleet-wide.
_EVENT_REQUIRED = {
    "type": str,          # always "event"
    "kind": str,
    "rank": int,
    "ts": (int, float),
}
_EVENT_OPTIONAL = {
    "message": str,
    "stacks": str,        # formatted py-stack dump (stack_dump events)
    "bundle": str,        # flight-bundle path (crash events)
    "error": str,
    "lag_steps": int,
    "age_s": (int, float),
    "device_memory": dict,
    "detail": dict,
    "ckpt": str,          # drain / restart / ckpt_corrupt checkpoint path
    "delay_s": (int, float),    # backoff events: the observed delay
    "attempt": int,             # backoff / elastic_restart ordinal
    "recover_s": (int, float),  # elastic_restart/resize: respawn time
    "old_world": int,           # resize/resize_rejected: world before
    "new_world": int,           # resize/resize_rejected: world after
    "trace": dict,              # optional trace-context envelope
}

# Log: a rank-tagged forwarded logging record (warning+ severity).
_LOG_REQUIRED = {
    "type": str,          # always "log"
    "rank": int,
    "ts": (int, float),
    "level": str,
    "logger": str,
    "message": str,
}

FLIGHT_BUNDLE_SCHEMA_ID = "rlt-flight-bundle-v1"

# Crash flight bundle: the post-mortem document flight_recorder.py
# persists under the telemetry dir on uncaught worker exceptions.
_BUNDLE_REQUIRED = {
    "schema": str,        # FLIGHT_BUNDLE_SCHEMA_ID
    "rank": int,
    "ts": (int, float),
    "error": str,         # repr of the exception
    "traceback": str,
    "global_step": int,
    "micro_step": int,
    "epoch": int,
    "phase": str,
    "fingerprint": dict,  # env/device identity (python, jax, RLT_* knobs)
}
_BUNDLE_OPTIONAL = {
    "spans": list,        # last-N span dicts from the ring
    "step_stats": dict,
    "counters": dict,
    "logs": list,         # ring-buffered rank-tagged log lines
    "device_memory": dict,
    "stacks": str,        # all-thread py stacks at crash time
    "callback_metrics": dict,  # metrics at crash time (async log fetch
                               # flushed first — latest boundary landed)
    "programs": dict,     # program-ledger snapshot (what was compiled,
                          # what recompiled, and why — crash forensics)
}


def _validate_typed(obj: Any, expect_type: str, required: dict,
                    optional: dict, where: str) -> List[str]:
    problems = _check_fields(obj, required, optional, where)
    if not problems and obj.get("type") != expect_type:
        problems.append(
            f"{where}: type is {obj['type']!r}, expected {expect_type!r}"
        )
    return problems


def validate_heartbeat(item: Any, where: str = "heartbeat") -> List[str]:
    problems = _validate_typed(
        item, "heartbeat", _HEARTBEAT_REQUIRED, _HEARTBEAT_OPTIONAL, where
    )
    if not problems:
        for key in ("seq", "global_step", "micro_step", "progress"):
            if item[key] < 0:
                problems.append(f"{where}: negative {key} {item[key]}")
        problems += _check_optional_trace(item, where)
    return problems


def validate_event(item: Any, where: str = "event") -> List[str]:
    problems = _validate_typed(
        item, "event", _EVENT_REQUIRED, _EVENT_OPTIONAL, where
    )
    if not problems:
        if item["rank"] < -1:
            problems.append(f"{where}: invalid rank {item['rank']}")
        problems += _check_optional_trace(item, where)
    return problems


def validate_log_item(item: Any, where: str = "log") -> List[str]:
    return _validate_typed(item, "log", _LOG_REQUIRED, {}, where)


def validate_stream_item(item: Any, where: str = "item") -> List[str]:
    """Dispatch on ``item["type"]`` — the one entry point for consumers
    that see the raw queue stream (``metrics`` items are loop-internal
    and intentionally not schema-pinned here beyond the type routing)."""
    if not isinstance(item, dict):
        return [f"{where}: expected object, got {type(item).__name__}"]
    kind = item.get("type")
    if kind == "heartbeat":
        return validate_heartbeat(item, where)
    if kind == "event":
        return validate_event(item, where)
    if kind == "log":
        return validate_log_item(item, where)
    if kind == "metrics":
        return []
    if kind == "mpmd_stage":
        return validate_mpmd_stage_item(item, where)
    return [f"{where}: unknown stream item type {kind!r}"]


def validate_flight_bundle(doc: Any, where: str = "bundle") -> List[str]:
    problems = _check_fields(
        doc, _BUNDLE_REQUIRED, _BUNDLE_OPTIONAL, where
    )
    if problems:
        return problems
    if doc["schema"] != FLIGHT_BUNDLE_SCHEMA_ID:
        problems.append(
            f"{where}: schema is {doc['schema']!r}, expected "
            f"{FLIGHT_BUNDLE_SCHEMA_ID!r}"
        )
    for i, span in enumerate(doc.get("spans", [])):
        problems += validate_span(span, f"{where}.spans[{i}]")
    if "programs" in doc:
        problems += validate_program_snapshot(
            doc["programs"], f"{where}.programs"
        )
    return problems


# ---------------------------------------------------------------------------
# Program ledger (telemetry/program_ledger.py): the compiled-executable
# observatory — per-program cost/memory rows, recompile forensics, and
# the bench ``programs`` block
# ---------------------------------------------------------------------------

# One compiled executable: identity + the XLA accounting captured at
# first dispatch.  ``signature`` is the compact abstract-argument
# rendering the recompile diff is computed over; accounting keys are
# best-effort (a backend without cost_analysis still gets a row).
_PROGRAM_ROW_REQUIRED = {
    "site": str,          # stable call-site name, e.g. "serve/decode"
    "variant": int,       # 0 = first compile at the site
    "ncalls": int,
    "compile_s": (int, float),   # measured lower()+compile() wall
    "signature": str,
}
_PROGRAM_ROW_OPTIONAL = {
    "backend": str,
    "donated": str,                    # donate_argnums rendering
    "flops": (int, float),             # cost_analysis
    "bytes_accessed": (int, float),    # cost_analysis
    "argument_bytes": int,             # memory_analysis
    "output_bytes": int,
    "temp_bytes": int,
    "alias_bytes": int,
    "generated_code_bytes": int,
}

#: The delta kinds a recompile attribution may carry.
RECOMPILE_KINDS = ("shape", "dtype", "structure", "donation", "static")

# A recompile attribution: which site, which argument, what changed.
_RECOMPILE_REQUIRED = {
    "type": str,          # always "recompile"
    "site": str,
    "kind": str,          # one of RECOMPILE_KINDS
    "argument": str,      # offending argument (leaf path included)
    "ts": (int, float),
}
_RECOMPILE_OPTIONAL = {
    "old": str,
    "new": str,
    "variant": int,       # the variant index the recompile created
    "rank": int,
}

# The full observatory snapshot (flight bundles, rlt_top, serve-live).
_PROGRAM_SNAPSHOT_REQUIRED = {
    "programs": list,
    "recompiles": list,
    "compile_time_total_s": (int, float),
}
_PROGRAM_SNAPSHOT_OPTIONAL = {
    "dropped": int,       # rows past the ring cap
}

# The bench ``programs`` block: ledger coverage + the dispatch-overhead
# A/B (``ledger_overhead_pct`` nullable — the probe is best-effort).
_BENCH_PROGRAMS_REQUIRED = {
    "n_programs": int,
    "compile_time_total_s": (int, float),
    "recompile_events": int,
    "ledger_overhead_pct": (int, float, type(None)),
}
_BENCH_PROGRAMS_OPTIONAL = {
    "rows": list,         # program rows (validate_program_row each)
    "hbm": dict,          # program_ledger.hbm_report()
    "roofline": dict,     # program_ledger.roofline(...)
    "mfu_basis": str,     # "analytic" | "measured"
    "dropped": int,
}


def validate_program_row(row: Any, where: str = "program") -> List[str]:
    problems = _check_fields(
        row, _PROGRAM_ROW_REQUIRED, _PROGRAM_ROW_OPTIONAL, where
    )
    if not problems:
        if not row["site"]:
            problems.append(f"{where}: empty site")
        for key in ("variant", "ncalls", "compile_s"):
            if row[key] < 0:
                problems.append(f"{where}: negative {key} {row[key]}")
    return problems


def validate_recompile_record(rec: Any,
                              where: str = "recompile") -> List[str]:
    problems = _validate_typed(
        rec, "recompile", _RECOMPILE_REQUIRED, _RECOMPILE_OPTIONAL, where
    )
    if not problems:
        if rec["kind"] not in RECOMPILE_KINDS:
            problems.append(
                f"{where}: kind {rec['kind']!r} not in "
                f"{RECOMPILE_KINDS}"
            )
        if not rec["argument"]:
            problems.append(f"{where}: empty argument attribution")
        if not rec["site"]:
            problems.append(f"{where}: empty site")
    return problems


def validate_program_snapshot(snap: Any,
                              where: str = "programs") -> List[str]:
    problems = _check_fields(
        snap, _PROGRAM_SNAPSHOT_REQUIRED, _PROGRAM_SNAPSHOT_OPTIONAL, where
    )
    if problems:
        return problems
    for i, row in enumerate(snap["programs"]):
        problems += validate_program_row(row, f"{where}.programs[{i}]")
    for i, rec in enumerate(snap["recompiles"]):
        problems += validate_recompile_record(
            rec, f"{where}.recompiles[{i}]"
        )
    if snap["compile_time_total_s"] < 0:
        problems.append(f"{where}: negative compile_time_total_s")
    return problems


def validate_bench_programs(block: Any,
                            where: str = "programs") -> List[str]:
    """Validate the ``programs`` block of a ``BENCH_*.json`` artifact
    (absent on pre-ledger rounds)."""
    problems = _check_fields(
        block, _BENCH_PROGRAMS_REQUIRED, _BENCH_PROGRAMS_OPTIONAL, where
    )
    if problems:
        return problems
    if block["n_programs"] < 0:
        problems.append(f"{where}: negative n_programs")
    if block["recompile_events"] < 0:
        problems.append(f"{where}: negative recompile_events")
    basis = block.get("mfu_basis")
    if basis is not None and basis not in ("analytic", "measured"):
        problems.append(f"{where}: invalid mfu_basis {basis!r}")
    for i, row in enumerate(block.get("rows", [])):
        problems += validate_program_row(row, f"{where}.rows[{i}]")
    return problems


# ---------------------------------------------------------------------------
# Serving plane (serve/): wire items, live snapshot, bench block
# ---------------------------------------------------------------------------

# The client → engine submission item (serve/client.py → engine inbox).
_SERVE_REQUEST_REQUIRED = {
    "type": str,              # always "serve_request"
    "rid": str,
    "prompt": list,           # int token ids
    "max_new_tokens": int,
    "reply": list,            # [host, port] of the client's reply queue
}
_SERVE_REQUEST_OPTIONAL = {
    "temperature": (int, float),
    "eos_token_id": (int, type(None)),
    "top_k": (int, type(None)),       # shape-static sampler truncation
    "spec": (int, type(None)),        # per-request draft count cap
    # Multi-tenant LoRA: the adapter (tenant) to decode through
    # (None/absent = the shared base model).
    "adapter": (str, type(None)),
    "deadline_s": (int, float, type(None)),
    # Disaggregated serving: the router's fleet-wide sampling-stream
    # identity (absent/None = the engine assigns its own ordinal).
    "sample_seed": (int, type(None)),
    # Brownout shed class: 0 (default) sheds first under fleet
    # overload, >= 1 survives to the shed rung (router admission).
    "priority": int,
    # Client hedged resubmit: a duplicate submission of an ALREADY
    # in-flight rid — the router places it on a second replica, first
    # terminal wins, the loser is cancelled.
    "hedge": bool,
    # Distributed tracing: the request's trace-context envelope
    # (validate_trace_context; absent on untraced producers).
    "trace": dict,
}

# Engine → client replies: the per-token stream and the completion.
_SERVE_TOKEN_REQUIRED = {
    "type": str,              # "serve_token"
    "rid": str,
    "index": int,             # re-emitted from 0 after a preemption
    "token": int,
}
_SERVE_DONE_REQUIRED = {
    "type": str,              # "serve_done"
    "rid": str,
    # finished/rejected/expired/invalid/error, plus the resilience
    # outcomes: "shed" (brownout overload reply, retryable) and
    # "cancelled" (hedge loser / operator drop, retryable).
    "status": str,
    "tokens": list,
}
_SERVE_DONE_OPTIONAL = {
    # eos/length/rejected/expired/brownout/cancelled
    "reason": (str, type(None)),
    "error": str,                  # invalid submissions only
}


def validate_serve_request(item: Any,
                           where: str = "serve_request") -> List[str]:
    problems = _validate_typed(
        item, "serve_request", _SERVE_REQUEST_REQUIRED,
        _SERVE_REQUEST_OPTIONAL, where,
    )
    if not problems:
        if item["max_new_tokens"] < 1:
            problems.append(f"{where}: max_new_tokens < 1")
        if not item["prompt"]:
            problems.append(f"{where}: empty prompt")
        if len(item["reply"]) != 2:
            problems.append(f"{where}: reply is not [host, port]")
        problems += _check_optional_trace(item, where)
    return problems


def validate_serve_reply(item: Any, where: str = "serve_reply") -> List[str]:
    """Dispatch over the engine → client reply family."""
    if not isinstance(item, dict):
        return [f"{where}: expected object, got {type(item).__name__}"]
    kind = item.get("type")
    if kind == "serve_token":
        problems = _validate_typed(
            item, "serve_token", _SERVE_TOKEN_REQUIRED, {}, where
        )
        if not problems and item["index"] < 0:
            problems.append(f"{where}: negative index")
        return problems
    if kind == "serve_done":
        return _validate_typed(
            item, "serve_done", _SERVE_DONE_REQUIRED,
            _SERVE_DONE_OPTIONAL, where,
        )
    return [f"{where}: unknown serve reply type {kind!r}"]


# The live SLO snapshot (ServeStats.snapshot → serve-live.json, the
# OpenMetrics serve gauges and rlt_top's serve pane).
_SERVE_SNAPSHOT_REQUIRED = {
    "ts": (int, float),
    "counters": dict,
    "gauges": dict,
    "latency": dict,
}
# "phases" appears only on TRACING engines (ServeStats.note_phase is
# lazily fed by the request tracer) — per critical-path phase p50/p95;
# "adapters" only on multi-LoRA engines (ServeStats.note_adapter) —
# per-tenant token/completion accounting, the fairness surface.
_SERVE_SNAPSHOT_OPTIONAL = {
    "phases": dict,
    "adapters": dict,
    # Prefix-cache engines only (ServeStats.set_prefix, fed from
    # PrefixIndex.stats each gauge refresh).
    "prefix": dict,
    # Capacity-plane engines only (serve/capacity.py::CapacityOracle —
    # the headroom oracle's latest capacity_snapshot, so beats carry
    # it to the router for free).
    "capacity": dict,
}
_SERVE_PREFIX_REQUIRED = {
    "hit_rate": (int, float),
    "lookups": int,
    "hits": int,
    "blocks_claimed": int,
    "blocks_inserted": int,
    "blocks_evicted": int,
    "cached_blocks": int,
}
_SERVE_ADAPTER_ENTRY_FIELDS = {
    "tokens_out": int,
    "completed": int,
}
_SERVE_LATENCY_KEYS = ("ttft", "token", "queue_wait", "e2e")
_SERVE_LATENCY_FIELDS = {
    "n": int,
    "p50_ms": (int, float),
    "p99_ms": (int, float),
    "max_ms": (int, float),
}
_SERVE_PHASE_FIELDS = {
    "n": int,
    "p50_ms": (int, float),
    "p95_ms": (int, float),
}


def validate_serve_snapshot(doc: Any,
                            where: str = "serve_snapshot") -> List[str]:
    problems = _check_fields(
        doc, _SERVE_SNAPSHOT_REQUIRED, _SERVE_SNAPSHOT_OPTIONAL, where
    )
    if problems:
        return problems
    for phase, summary in doc.get("phases", {}).items():
        problems += _check_fields(
            summary, _SERVE_PHASE_FIELDS, {},
            f"{where}.phases.{phase}",
        )
    for key, value in doc["counters"].items():
        if not isinstance(value, int) or isinstance(value, bool):
            problems.append(f"{where}: counter {key!r} is not an int")
    for key, value in doc["gauges"].items():
        if not isinstance(value, (int, float)) or isinstance(value, bool):
            problems.append(f"{where}: gauge {key!r} is not numeric")
    rate = doc["gauges"].get("spec_acceptance_rate")
    if isinstance(rate, (int, float)) and not 0.0 <= rate <= 1.0:
        problems.append(
            f"{where}: spec_acceptance_rate {rate} outside [0, 1]"
        )
    spread = doc["gauges"].get("lora_fairness_spread")
    if isinstance(spread, (int, float)) and not 0.0 <= spread <= 1.0:
        problems.append(
            f"{where}: lora_fairness_spread {spread} outside [0, 1]"
        )
    if "prefix" in doc:
        prefix_problems = _check_fields(
            doc["prefix"], _SERVE_PREFIX_REQUIRED, {}, f"{where}.prefix"
        )
        if not prefix_problems:
            hr = doc["prefix"]["hit_rate"]
            if not 0.0 <= hr <= 1.0:
                prefix_problems.append(
                    f"{where}.prefix: hit_rate {hr} outside [0, 1]"
                )
            if doc["prefix"]["hits"] > doc["prefix"]["lookups"]:
                prefix_problems.append(
                    f"{where}.prefix: hits > lookups"
                )
        problems += prefix_problems
    for name, entry in doc.get("adapters", {}).items():
        problems += _check_fields(
            entry, _SERVE_ADAPTER_ENTRY_FIELDS, {},
            f"{where}.adapters.{name}",
        )
    counters = doc["counters"]
    if all(isinstance(counters.get(k), int)
           for k in ("spec_accepted", "spec_drafted")):
        if counters["spec_accepted"] > counters["spec_drafted"]:
            problems.append(
                f"{where}: spec_accepted {counters['spec_accepted']} > "
                f"spec_drafted {counters['spec_drafted']}"
            )
    for family, summary in doc["latency"].items():
        if family not in _SERVE_LATENCY_KEYS:
            problems.append(f"{where}: unknown latency family {family!r}")
            continue
        problems += _check_fields(
            summary, _SERVE_LATENCY_FIELDS, {},
            f"{where}.latency.{family}",
        )
    if "capacity" in doc:
        problems += validate_capacity_snapshot(
            doc["capacity"], f"{where}.capacity"
        )
    return problems


# ---------------------------------------------------------------------------
# Fleet SLO & capacity plane (telemetry/timeseries.py, telemetry/slo.py,
# serve/capacity.py): store persistence points, burn-rate alert events,
# headroom-oracle snapshots
# ---------------------------------------------------------------------------

# One retained bin of a TimeSeriesStore series (dump_jsonl / points).
# hist bins surface their per-bin median as ``value`` plus the merged
# sample count ``n``; counter/gauge bins carry the bin value alone.
_TIMESERIES_POINT_REQUIRED = {
    "type": str,          # always "timeseries_point"
    "name": str,
    "kind": str,          # counter | gauge | hist
    "ts": (int, float),   # bin START (bin_index * interval_s)
    "value": (int, float),
}
_TIMESERIES_POINT_OPTIONAL = {
    "n": int,             # hist bins only: merged sample count
}
_TIMESERIES_KINDS = ("counter", "gauge", "hist")


def validate_timeseries_point(point: Any,
                              where: str = "timeseries_point"
                              ) -> List[str]:
    problems = _validate_typed(
        point, "timeseries_point", _TIMESERIES_POINT_REQUIRED,
        _TIMESERIES_POINT_OPTIONAL, where,
    )
    if problems:
        return problems
    if point["kind"] not in _TIMESERIES_KINDS:
        problems.append(f"{where}: unknown kind {point['kind']!r}")
    if not point["name"]:
        problems.append(f"{where}: empty series name")
    if "n" in point:
        if point["kind"] != "hist":
            problems.append(
                f"{where}: sample count n on a "
                f"{point['kind']} bin"
            )
        elif point["n"] < 1:
            problems.append(f"{where}: n < 1")
    return problems


# The slo_alert event's ``detail`` payload (the event envelope itself
# is the stock _EVENT_* shape — alerts ride the existing event plane).
_SLO_ALERT_DETAIL_REQUIRED = {
    "slo": str,
    "mode": str,                        # ratio | threshold
    "target": (int, float),            # the objective, in (0, 1)
    "burn_rate": (int, float),         # budget-burn multiple observed
    "error_rate": (int, float),        # over the slow window, [0, 1]
    "fast_window_s": (int, float),
    "slow_window_s": (int, float),
    "threshold_burn": (int, float),    # the pair's firing bound
}


def validate_slo_alert(item: Any, where: str = "slo_alert") -> List[str]:
    problems = validate_event(item, where)
    if problems:
        return problems
    if item.get("kind") != "slo_alert":
        problems.append(
            f"{where}: kind is {item.get('kind')!r}, expected "
            f"'slo_alert'"
        )
    detail = item.get("detail")
    if not isinstance(detail, dict):
        problems.append(f"{where}: missing detail payload")
        return problems
    problems += _check_fields(
        detail, _SLO_ALERT_DETAIL_REQUIRED, {}, f"{where}.detail"
    )
    if problems:
        return problems
    if not 0.0 < detail["target"] < 1.0:
        problems.append(
            f"{where}.detail: target {detail['target']} outside (0, 1)"
        )
    if not 0.0 <= detail["error_rate"] <= 1.0:
        problems.append(
            f"{where}.detail: error_rate {detail['error_rate']} "
            f"outside [0, 1]"
        )
    if detail["burn_rate"] < 0:
        problems.append(f"{where}.detail: negative burn_rate")
    if detail["fast_window_s"] >= detail["slow_window_s"]:
        problems.append(
            f"{where}.detail: fast window "
            f"{detail['fast_window_s']} not shorter than slow "
            f"{detail['slow_window_s']}"
        )
    if detail["mode"] not in ("ratio", "threshold"):
        problems.append(
            f"{where}.detail: unknown mode {detail['mode']!r}"
        )
    return problems


# The headroom oracle's output (CapacityOracle.snapshot — rides the
# serve snapshot's ``capacity`` block, beats, router snapshots and the
# rlt_capacity_* prom family).  The derived fields are nullable: the
# oracle refuses to guess before the per-slot service rate has data.
_CAPACITY_SNAPSHOT_REQUIRED = {
    "type": str,          # always "capacity_snapshot"
    "ts": (int, float),
    "window_s": (int, float),
    "tokens_per_s": (int, float),
    "service_rate_per_slot": (int, float, type(None)),
    "capacity_tokens_per_s": (int, float, type(None)),
    "headroom_tokens_per_s": (int, float, type(None)),
    "utilization": (int, float, type(None)),
    "kv_exhaustion_eta_s": (int, float, type(None)),
    "queue_wait_slope_ms_per_s": (int, float, type(None)),
    "queue_depth": (int, float),
    "rejection_rate": (int, float),
}


def validate_capacity_snapshot(snap: Any,
                               where: str = "capacity_snapshot"
                               ) -> List[str]:
    problems = _validate_typed(
        snap, "capacity_snapshot", _CAPACITY_SNAPSHOT_REQUIRED, {}, where
    )
    if problems:
        return problems
    if snap["window_s"] <= 0:
        problems.append(f"{where}: window_s <= 0")
    if snap["tokens_per_s"] < 0:
        problems.append(f"{where}: negative tokens_per_s")
    util = snap["utilization"]
    if isinstance(util, (int, float)) and not 0.0 <= util <= 1.0:
        problems.append(f"{where}: utilization {util} outside [0, 1]")
    rej = snap["rejection_rate"]
    if not 0.0 <= rej <= 1.0:
        problems.append(
            f"{where}: rejection_rate {rej} outside [0, 1]"
        )
    head = snap["headroom_tokens_per_s"]
    if isinstance(head, (int, float)) and head < 0:
        problems.append(f"{where}: negative headroom_tokens_per_s")
    eta = snap["kv_exhaustion_eta_s"]
    if isinstance(eta, (int, float)) and eta < 0:
        problems.append(f"{where}: negative kv_exhaustion_eta_s")
    return problems


# ---------------------------------------------------------------------------
# Disaggregated serving (serve/dist/): KV handoff envelope, router
# snapshot, bench block
# ---------------------------------------------------------------------------

# The prefill worker → decode replica handoff envelope.  Like the MPMD
# transfer frame, the bulk tensor payload (encode_tree bytes of
# {"kv", "logits"}) rides EXACTLY ONE of data/shm and is deliberately
# outside the schema; the request riding in "req" is a full
# serve_request (validated recursively, sample_seed required — a
# handoff without the router's fleet-wide seed would break failover
# stream stability).
_SERVE_HANDOFF_REQUIRED = {
    "type": str,          # always "serve_kv_handoff"
    "rid": str,
    "bucket": int,        # prefill bucket length (tokens)
    "prompt_len": int,
    "req": dict,
}
_SERVE_HANDOFF_OPTIONAL = {
    "data": bytes,
    "shm": str,
    # The prefill worker's trace envelope (span_id = its prefill span;
    # ts = send time, the replica books handoff_transfer from it).
    "trace": dict,
}


def validate_serve_kv_handoff(item: Any,
                              where: str = "serve_kv_handoff"
                              ) -> List[str]:
    problems = _validate_typed(
        item, "serve_kv_handoff", _SERVE_HANDOFF_REQUIRED,
        _SERVE_HANDOFF_OPTIONAL, where,
    )
    if problems:
        return problems
    if ("data" in item) == ("shm" in item):
        problems.append(
            f"{where}: exactly one of data/shm payload required"
        )
    if item["prompt_len"] < 1:
        problems.append(f"{where}: prompt_len < 1")
    if item["bucket"] < item["prompt_len"]:
        problems.append(
            f"{where}: bucket {item['bucket']} smaller than prompt_len "
            f"{item['prompt_len']}"
        )
    problems += validate_serve_request(item["req"], f"{where}.req")
    seed = item["req"].get("sample_seed") \
        if isinstance(item["req"], dict) else None
    if not isinstance(seed, int) or isinstance(seed, bool):
        problems.append(f"{where}.req: missing/invalid sample_seed")
    problems += _check_optional_trace(item, where)
    return problems


# The draining replica → router → survivor live-migration envelope
# (serve/dist/handoff.py::make_migration_item): one resident
# sequence's KV blocks + scheduler position + the canonical request
# fields, so the survivor resumes decode mid-sequence with zero
# recomputed prefill.  Unlike KV handoffs the payload is ALWAYS inline
# bytes ("data") — migration frames ride the ordered beat lane, and a
# tmpfs segment would dangle if the draining host died mid-drain.
_SERVE_MIGRATION_REQUIRED = {
    "type": str,          # always "serve_migration"
    "rid": str,
    "req": dict,          # request_fields dict (reply + sample_seed)
    "generated": list,    # tokens already emitted to the client
    "cur_token": int,     # last sampled token (next tick's input)
    "seq_len": int,       # KV positions written (prompt+gen-1)
    "data": bytes,        # encode_tree({"kv": ...})
}
_SERVE_MIGRATION_OPTIONAL = {
    "trace": dict,
}


def validate_serve_migration(item: Any,
                             where: str = "serve_migration"
                             ) -> List[str]:
    problems = _validate_typed(
        item, "serve_migration", _SERVE_MIGRATION_REQUIRED,
        _SERVE_MIGRATION_OPTIONAL, where,
    )
    if problems:
        return problems
    if not item["generated"]:
        problems.append(
            f"{where}: empty generated — a sequence with no emitted "
            f"tokens has nothing worth migrating (recompute failover "
            f"covers it)"
        )
    if item["seq_len"] < 1:
        problems.append(f"{where}: seq_len < 1")
    problems += validate_serve_request(item["req"], f"{where}.req")
    req = item["req"] if isinstance(item["req"], dict) else {}
    seed = req.get("sample_seed")
    if not isinstance(seed, int) or isinstance(seed, bool):
        # Without the fleet seed the survivor cannot continue the
        # stream bitwise at temperature > 0.
        problems.append(f"{where}.req: missing/invalid sample_seed")
    prompt = req.get("prompt")
    if isinstance(prompt, list) and item["generated"] \
            and item["seq_len"] >= 1 \
            and item["seq_len"] != len(prompt) \
            + len(item["generated"]) - 1:
        # The invariant the importer's block math depends on: the
        # final sampled token's KV is never written until its own
        # decode tick.
        problems.append(
            f"{where}: seq_len {item['seq_len']} != prompt + "
            f"generated - 1 ({len(prompt) + len(item['generated']) - 1})"
        )
    problems += _check_optional_trace(item, where)
    return problems


# The router/operator → member adapter hot-load envelope (multi-tenant
# LoRA; serve/dist/handoff.py::make_adapter_load_item).  Like KV
# handoffs, the bulk factor payload (encode_adapter bytes) rides
# EXACTLY ONE of data/shm and is deliberately outside the schema.
_SERVE_ADAPTER_LOAD_REQUIRED = {
    "type": str,          # always "serve_adapter_load"
    "name": str,          # tenant name (the pool registry key)
    "rank": int,          # stacked-buffer rank the pool must match
}
_SERVE_ADAPTER_LOAD_OPTIONAL = {
    "data": bytes,
    "shm": str,
}


def validate_serve_adapter_load(item: Any,
                                where: str = "serve_adapter_load"
                                ) -> List[str]:
    problems = _validate_typed(
        item, "serve_adapter_load", _SERVE_ADAPTER_LOAD_REQUIRED,
        _SERVE_ADAPTER_LOAD_OPTIONAL, where,
    )
    if problems:
        return problems
    if ("data" in item) == ("shm" in item):
        problems.append(
            f"{where}: exactly one of data/shm payload required"
        )
    if item["rank"] < 1:
        problems.append(f"{where}: rank must be >= 1")
    if not item["name"]:
        problems.append(f"{where}: empty adapter name")
    return problems


# router-live.json (Router.snapshot — the rlt_top router pane and the
# per-replica rlt_serve_* OpenMetrics labels parse this).
_ROUTER_SNAPSHOT_REQUIRED = {
    "ts": (int, float),
    "counters": dict,
    "replicas": list,
    "workers": list,
}
_ROUTER_REPLICA_OPTIONAL = {
    "last_beat_age_s": (int, float, type(None)),
    "slots_active": (int, float),
    "num_slots": (int, float),
    "queue_depth": (int, float),
    "blocks_free": (int, float),
    "num_blocks": (int, float),
    "spec_acceptance_rate": (int, float),
    "prefix_cache_hit_rate": (int, float),
    "recompiles": int,
    "adapters": int,       # loaded LoRA tenants (pool-capable members)
    # Capacity-plane members only: lifted from the capacity_snapshot
    # riding the beat's serve snapshot (serve/capacity.py).
    "headroom_tokens_per_s": (int, float, type(None)),
    "utilization": (int, float, type(None)),
    "kv_exhaustion_eta_s": (int, float, type(None)),
}
# The fleet-wide capacity roll-up (serve/capacity.py::aggregate_fleet)
# the router attaches when any member reports a capacity block, and
# the brownout ladder's current rung (brownout-enabled routers only;
# 0 = healthy, 1 = spec off, 2 = max_new capped, 3 = shedding).
_ROUTER_SNAPSHOT_OPTIONAL = {
    "capacity": dict,
    "brownout_level": int,
}
_FLEET_CAPACITY_REQUIRED = {
    "replicas_reporting": int,
    "tokens_per_s": (int, float),
    "capacity_tokens_per_s": (int, float, type(None)),
    "headroom_tokens_per_s": (int, float, type(None)),
    "utilization": (int, float, type(None)),
    "kv_exhaustion_eta_s": (int, float, type(None)),
}
_ROUTER_WORKER_OPTIONAL = {
    "last_beat_age_s": (int, float, type(None)),
    "adapters": int,
}


def _validate_router_member(entry: Any, where: str, count_key: str,
                            optional: dict) -> List[str]:
    if not isinstance(entry, dict):
        return [f"{where}: expected object"]
    problems = []
    if not isinstance(entry.get("id"), str):
        problems.append(f"{where}: missing/invalid id")
    if not isinstance(entry.get("alive"), bool):
        problems.append(f"{where}: missing/invalid alive")
    n = entry.get(count_key)
    if not isinstance(n, int) or isinstance(n, bool) or n < 0:
        problems.append(f"{where}: missing/invalid {count_key}")
    for key, types in optional.items():
        if key in entry and not isinstance(entry[key], types):
            problems.append(
                f"{where}: key {key!r} has type "
                f"{type(entry[key]).__name__}"
            )
    unknown = set(entry) - {"id", "alive", count_key} - set(optional)
    if unknown:
        problems.append(f"{where}: unknown keys {sorted(unknown)}")
    rate = entry.get("spec_acceptance_rate")
    if isinstance(rate, (int, float)) and not 0.0 <= rate <= 1.0:
        problems.append(
            f"{where}: spec_acceptance_rate {rate} outside [0, 1]"
        )
    hit = entry.get("prefix_cache_hit_rate")
    if isinstance(hit, (int, float)) and not 0.0 <= hit <= 1.0:
        problems.append(
            f"{where}: prefix_cache_hit_rate {hit} outside [0, 1]"
        )
    util = entry.get("utilization")
    if isinstance(util, (int, float)) and not 0.0 <= util <= 1.0:
        problems.append(f"{where}: utilization {util} outside [0, 1]")
    return problems


def validate_router_snapshot(doc: Any,
                             where: str = "router_snapshot") -> List[str]:
    problems = _check_fields(
        doc, _ROUTER_SNAPSHOT_REQUIRED, _ROUTER_SNAPSHOT_OPTIONAL, where
    )
    if problems:
        return problems
    if "capacity" in doc:
        cap_problems = _check_fields(
            doc["capacity"], _FLEET_CAPACITY_REQUIRED, {},
            f"{where}.capacity",
        )
        if not cap_problems:
            util = doc["capacity"]["utilization"]
            if isinstance(util, (int, float)) \
                    and not 0.0 <= util <= 1.0:
                cap_problems.append(
                    f"{where}.capacity: utilization {util} "
                    f"outside [0, 1]"
                )
            if doc["capacity"]["replicas_reporting"] < 1:
                cap_problems.append(
                    f"{where}.capacity: replicas_reporting < 1"
                )
        problems += cap_problems
    lvl = doc.get("brownout_level")
    if lvl is not None and not 0 <= lvl <= 3:
        problems.append(
            f"{where}: brownout_level {lvl} outside [0, 3]"
        )
    for key, value in doc["counters"].items():
        if not isinstance(value, int) or isinstance(value, bool) \
                or value < 0:
            problems.append(
                f"{where}: counter {key!r} is not a non-negative int"
            )
    for i, entry in enumerate(doc["replicas"]):
        problems += _validate_router_member(
            entry, f"{where}.replicas[{i}]", "inflight",
            _ROUTER_REPLICA_OPTIONAL,
        )
    for i, entry in enumerate(doc["workers"]):
        problems += _validate_router_member(
            entry, f"{where}.workers[{i}]", "pending",
            _ROUTER_WORKER_OPTIONAL,
        )
    return problems


# The bench_serve.py artifact block: serving rounds become comparable
# only if every round spells the SLO numbers the same way.  The A/B
# ratio and sweep arms are nullable (best-effort probes), the headline
# latency/throughput numbers are not — a serve bench that cannot
# measure them has failed.
_BENCH_SERVE_REQUIRED = {
    "requests_per_sec": (int, float),
    "p50_token_latency_ms": (int, float),
    "p99_token_latency_ms": (int, float),
    "recompiles_steady_state": int,
}
_BENCH_SERVE_OPTIONAL = {
    "tokens_per_sec": (int, float, type(None)),
    "p50_ttft_ms": (int, float, type(None)),
    "p99_ttft_ms": (int, float, type(None)),
    "continuous_vs_sequential": (int, float, type(None)),
    "sequential_requests_per_sec": (int, float, type(None)),
    "sequential_tokens_per_sec": (int, float, type(None)),
    "num_slots": int,
    "block_size": int,
    "num_blocks": int,
    "completed": int,
    "preempted": int,
    "rejected": int,
    "expired": int,
    "rate_sweep": list,       # per-offered-rate open-loop arms
}
_BENCH_SERVE_SWEEP_REQUIRED = {
    "offered_rps": (int, float),
    "requests_per_sec": (int, float),
    "p50_token_latency_ms": (int, float, type(None)),
    "p99_token_latency_ms": (int, float, type(None)),
}
_BENCH_SERVE_SWEEP_OPTIONAL = {
    "p50_ttft_ms": (int, float, type(None)),
    "p99_ttft_ms": (int, float, type(None)),
    "completed": int,
    "expired": int,
    "rejected": int,
    "queue_depth_max": int,
}


def validate_bench_serve(block: Any, where: str = "serve") -> List[str]:
    """Validate the ``serve`` block of a bench artifact (absent on
    pre-serving rounds)."""
    problems = _check_fields(
        block, _BENCH_SERVE_REQUIRED, _BENCH_SERVE_OPTIONAL, where
    )
    if problems:
        return problems
    if block["recompiles_steady_state"] < 0:
        problems.append(f"{where}: negative recompiles_steady_state")
    for i, arm in enumerate(block.get("rate_sweep", [])):
        problems += _check_fields(
            arm, _BENCH_SERVE_SWEEP_REQUIRED, _BENCH_SERVE_SWEEP_OPTIONAL,
            f"{where}.rate_sweep[{i}]",
        )
    return problems


# The bench_serve.py SLO/capacity-plane block: the oracle-calibration
# gate (predicted saturation knee vs the measured Poisson-sweep knee),
# the burn-rate alert discrimination check (fires hot, silent cold),
# the zero-recompile pin and the plane-overhead A/B.  Headline numbers
# are non-nullable — a round that cannot calibrate has failed; the
# overhead ratio is best-effort (CPU noise floor).
_BENCH_SLO_REQUIRED = {
    "predicted_saturation_rps": (int, float),
    "measured_saturation_rps": (int, float),
    "prediction_error_pct": (int, float),
    "alerts_hot": int,        # slo_alert events in the 1.5x arm
    "alerts_cold": int,       # slo_alert events in the 0.5x arm
    "recompiles_steady_state": int,
}
_BENCH_SLO_OPTIONAL = {
    "overhead_pct": (int, float, type(None)),
    "capacity_tokens_per_s": (int, float, type(None)),
    "service_rate_per_slot": (int, float, type(None)),
    "hot_rps": (int, float),
    "cold_rps": (int, float),
    "hot_utilization": (int, float, type(None)),
    "ts_points": int,         # persisted timeseries_point count
}


def validate_bench_slo(block: Any, where: str = "slo") -> List[str]:
    """Validate the ``slo`` block of a bench artifact (absent on
    pre-capacity-plane rounds)."""
    problems = _check_fields(
        block, _BENCH_SLO_REQUIRED, _BENCH_SLO_OPTIONAL, where
    )
    if problems:
        return problems
    for key in ("predicted_saturation_rps", "measured_saturation_rps"):
        if block[key] <= 0:
            problems.append(f"{where}: {key} must be > 0")
    if block["prediction_error_pct"] < 0:
        problems.append(f"{where}: negative prediction_error_pct")
    for key in ("alerts_hot", "alerts_cold",
                "recompiles_steady_state"):
        if block[key] < 0:
            problems.append(f"{where}: negative {key}")
    return problems


# The bench_serve.py speculative-decoding A/B block: the spec arm and
# its non-spec baseline must both pin their recompile counters (the
# zero-recompile steady state is the contract, not a best-effort), and
# the acceptance sweep scans tokens/s across draft quality.
_BENCH_SPEC_REQUIRED = {
    "spec_k": int,
    "tokens_per_sec": (int, float),            # spec arm, emitted
    "baseline_tokens_per_sec": (int, float),   # non-spec decode arm
    "vs_baseline": (int, float),               # the >= 1.5x headline
    "acceptance_rate": (int, float),
    "recompiles_steady_state": int,
    "baseline_recompiles_steady_state": int,
}
_BENCH_SPEC_OPTIONAL = {
    "draft_layers": int,
    "target_layers": int,
    "drafted": int,
    "accepted": int,
    "emitted": int,
    "greedy_parity": bool,        # spec tokens == non-spec tokens
    "requests": int,
    "max_new_tokens": int,
    "acceptance_sweep": list,     # per-noise arms
}
_BENCH_SPEC_SWEEP_REQUIRED = {
    "noise": (int, float),        # identity-tail perturbation scale
    "acceptance_rate": (int, float),
    "tokens_per_sec": (int, float),
    "vs_baseline": (int, float),
}


def validate_bench_spec_decode(block: Any,
                               where: str = "spec_decode") -> List[str]:
    """Validate the ``spec_decode`` block of a bench artifact (absent
    on pre-speculation rounds)."""
    problems = _check_fields(
        block, _BENCH_SPEC_REQUIRED, _BENCH_SPEC_OPTIONAL, where
    )
    if problems:
        return problems
    if block["spec_k"] < 1:
        problems.append(f"{where}: spec_k must be >= 1")
    if not 0.0 <= block["acceptance_rate"] <= 1.0:
        problems.append(
            f"{where}: acceptance_rate {block['acceptance_rate']} "
            "outside [0, 1]"
        )
    for key in ("recompiles_steady_state",
                "baseline_recompiles_steady_state"):
        if block[key] < 0:
            problems.append(f"{where}: negative {key}")
    for i, arm in enumerate(block.get("acceptance_sweep", [])):
        arm_problems = _check_fields(
            arm, _BENCH_SPEC_SWEEP_REQUIRED, {},
            f"{where}.acceptance_sweep[{i}]",
        )
        # Per-arm guard: an earlier arm's failure must not suppress
        # THIS arm's range check.
        if not arm_problems and not 0.0 <= arm["acceptance_rate"] <= 1.0:
            arm_problems.append(
                f"{where}.acceptance_sweep[{i}]: acceptance_rate "
                "outside [0, 1]"
            )
        problems += arm_problems
    return problems


# The bench_serve.py prefix-cache A/B block: the cached arm serves a
# shared-prefix workload mix against its cache-off baseline.  Both
# arms must pin recompiles_steady_state (sharing is operand-only by
# construction — a recompile would mean the claim leaked into a
# shape), and the parity flag asserts the cached arm's tokens are
# bitwise the baseline's.
_BENCH_PREFIX_REQUIRED = {
    "prefix_share": (int, float),       # fraction of prompt in the shared prefix
    "requests": int,
    "hit_rate": (int, float),
    "blocks_claimed": int,
    "ttft_p50_ms": (int, float),                # cached arm
    "baseline_ttft_p50_ms": (int, float),       # cache-off arm
    "ttft_speedup": (int, float),               # the >= 1.5x headline
    "tokens_per_sec": (int, float),
    "baseline_tokens_per_sec": (int, float),
    "recompiles_steady_state": int,
    "baseline_recompiles_steady_state": int,
}
_BENCH_PREFIX_OPTIONAL = {
    "token_parity": bool,       # cached tokens == baseline tokens
    "blocks_inserted": int,
    "cached_blocks": int,
    "prefill_chunks": int,
    "max_new_tokens": int,
}


def validate_bench_prefix_cache(block: Any,
                                where: str = "prefix_cache") -> List[str]:
    """Validate the ``prefix_cache`` block of a bench artifact (absent
    on pre-cache rounds)."""
    problems = _check_fields(
        block, _BENCH_PREFIX_REQUIRED, _BENCH_PREFIX_OPTIONAL, where
    )
    if problems:
        return problems
    if not 0.0 <= block["hit_rate"] <= 1.0:
        problems.append(
            f"{where}: hit_rate {block['hit_rate']} outside [0, 1]"
        )
    if not 0.0 <= block["prefix_share"] <= 1.0:
        problems.append(
            f"{where}: prefix_share {block['prefix_share']} "
            "outside [0, 1]"
        )
    for key in ("recompiles_steady_state",
                "baseline_recompiles_steady_state"):
        if block[key] < 0:
            problems.append(f"{where}: negative {key}")
    if block["requests"] < 1:
        problems.append(f"{where}: requests < 1")
    return problems


# The bench_long_context.py serving-side chunked-prefill block: a long
# prompt admitted against resident decode traffic, with the no-stall
# contract surfaced as the max per-step emission gap of the resident
# slots (1 = a token landed every step; the acceptance bound).
_BENCH_CHUNKED_REQUIRED = {
    "prompt_len": int,
    "chunk_width": int,
    "chunks": int,
    "resident_max_stall_ticks": int,
    "recompiles_steady_state": int,
}
_BENCH_CHUNKED_OPTIONAL = {
    "ttft_ms": (int, float, type(None)),
    "resident_requests": int,
    "tokens_per_sec": (int, float, type(None)),
}


def validate_bench_chunked_prefill(block: Any,
                                   where: str = "chunked_prefill"
                                   ) -> List[str]:
    """Validate the ``chunked_prefill`` block of a bench artifact."""
    problems = _check_fields(
        block, _BENCH_CHUNKED_REQUIRED, _BENCH_CHUNKED_OPTIONAL, where
    )
    if problems:
        return problems
    if block["chunk_width"] < 1:
        problems.append(f"{where}: chunk_width < 1")
    if block["chunks"] < 1:
        problems.append(f"{where}: chunks < 1")
    if block["prompt_len"] < 1:
        problems.append(f"{where}: prompt_len < 1")
    if block["resident_max_stall_ticks"] < 0:
        problems.append(f"{where}: negative resident_max_stall_ticks")
    if block["recompiles_steady_state"] < 0:
        problems.append(f"{where}: negative recompiles_steady_state")
    return problems


# The bench_serve.py disaggregated-serving block: the disagg-vs-
# monolith A/B plus the kill-a-replica chaos arm.  The chaos arm's
# loss accounting is required when the arm ran — a chaos block that
# cannot say how many requests survived has failed — and
# lost_requests is the zero-lost acceptance surface.
_BENCH_DISAGG_REQUIRED = {
    "replicas": int,
    "prefill_workers": int,
    "requests_per_sec": (int, float),
    "recompiles_steady_state": int,
}
_BENCH_DISAGG_OPTIONAL = {
    "requests": int,
    "tokens_per_sec": (int, float, type(None)),
    "monolith_requests_per_sec": (int, float, type(None)),
    "vs_monolith": (int, float, type(None)),
    "kv_imports": int,
    "prefill_dispatches": int,
    "p50_ttft_ms": (int, float, type(None)),
    "p99_ttft_ms": (int, float, type(None)),
    "chaos": dict,
}
_BENCH_DISAGG_CHAOS_REQUIRED = {
    "killed_replica": str,
    "submitted": int,
    "completed": int,
    "lost_requests": int,
    "failed_over_requests": int,
}
_BENCH_DISAGG_CHAOS_OPTIONAL = {
    "failover_detect_s": (int, float, type(None)),
    "re_emitted_tokens": int,
    "survivor_recompiles_steady_state": int,
    "offered_rps": (int, float),
}


def validate_bench_serve_disagg(block: Any,
                                where: str = "serve_disagg") -> List[str]:
    """Validate the ``serve_disagg`` block of a bench artifact (absent
    on pre-disaggregation rounds)."""
    problems = _check_fields(
        block, _BENCH_DISAGG_REQUIRED, _BENCH_DISAGG_OPTIONAL, where
    )
    if problems:
        return problems
    if block["replicas"] < 1:
        problems.append(f"{where}: replicas must be >= 1")
    if block["prefill_workers"] < 0:
        problems.append(f"{where}: negative prefill_workers")
    if block["recompiles_steady_state"] < 0:
        problems.append(f"{where}: negative recompiles_steady_state")
    chaos = block.get("chaos")
    if chaos is not None:
        chaos_problems = _check_fields(
            chaos, _BENCH_DISAGG_CHAOS_REQUIRED,
            _BENCH_DISAGG_CHAOS_OPTIONAL, f"{where}.chaos",
        )
        if not chaos_problems:
            if chaos["lost_requests"] < 0:
                chaos_problems.append(
                    f"{where}.chaos: negative lost_requests"
                )
            if chaos["completed"] + chaos["lost_requests"] \
                    > chaos["submitted"]:
                chaos_problems.append(
                    f"{where}.chaos: completed + lost > submitted"
                )
        problems += chaos_problems
    return problems


# The bench_serve.py serving-chaos block (ISSUE 19): the
# migration-vs-failover A/B.  Both arms drain/kill a replica
# mid-stream; the migration arm must lose zero requests, re-emit zero
# tokens (the KV moved, nothing was recomputed), and keep token parity
# with the uninterrupted engine — the failover arm is the recompute
# baseline it beats on time-to-recover.  Both arms pin steady-state
# recompiles.
_BENCH_SERVE_CHAOS_REQUIRED = {
    "migrations": int,                      # migration frames landed
    "migration_ttr_s": (int, float),        # drain -> stream resumed
    "failover_ttr_s": (int, float),         # kill -> stream resumed
    "migration_vs_failover": (int, float),  # failover_ttr / migration_ttr
    "lost_requests": int,
    "migration_re_emitted_tokens": int,     # MUST be 0 (no recompute)
    "recompiles_steady_state": int,
}
_BENCH_SERVE_CHAOS_OPTIONAL = {
    # bool keys ride the optional dict (the required-path bool guard
    # exists to catch True-as-int); presence is enforced below.
    "parity": bool,                         # tokens == uninterrupted run
    "failover_re_emitted_tokens": int,
    "requests": int,
    "shed": int,                 # brownout arm: typed shed replies
    "brownout_level_max": int,
    "hedges": int,
    "hedge_cancels": int,
}


def validate_bench_serve_chaos(block: Any,
                               where: str = "serve_chaos") -> List[str]:
    """Validate the ``serve_chaos`` block of a bench artifact (absent
    on pre-chaos rounds)."""
    problems = _check_fields(
        block, _BENCH_SERVE_CHAOS_REQUIRED, _BENCH_SERVE_CHAOS_OPTIONAL,
        where,
    )
    if problems:
        return problems
    if "parity" not in block:
        problems.append(f"{where}: missing required key 'parity'")
    for key in ("migrations", "lost_requests",
                "migration_re_emitted_tokens",
                "recompiles_steady_state"):
        if block[key] < 0:
            problems.append(f"{where}: negative {key}")
    for key in ("migration_ttr_s", "failover_ttr_s",
                "migration_vs_failover"):
        if block[key] < 0:
            problems.append(f"{where}: negative {key}")
    lvl = block.get("brownout_level_max")
    if lvl is not None and not 0 <= lvl <= 3:
        problems.append(
            f"{where}: brownout_level_max {lvl} outside [0, 3]"
        )
    return problems


# The bench_serve.py multi-tenant LoRA block: N adapters multiplexed
# over ONE resident base engine vs the merge-and-swap-per-tenant
# baseline (fold tenant k's factors into the weights, serve its batch,
# swap for the next tenant — the pre-pool serving shape).  Both arms
# pin their steady-state recompile counters (the zero-recompile
# contract covers adapter joins and hot-adds); fairness_spread is
# min/max lifetime tokens across tenants under uniform offered load
# (1.0 = perfectly fair, the DRR grant surface); greedy_parity pins
# every tenant's multiplexed stream token-for-token against its
# merged-model baseline.
_BENCH_MULTI_LORA_REQUIRED = {
    "adapters": int,                           # tenant count (N)
    "rank": int,                               # stacked-buffer rank
    "tokens_per_sec": (int, float),            # multiplexed arm
    "baseline_tokens_per_sec": (int, float),   # merge-and-swap arm
    "vs_baseline": (int, float),               # the >= 3x headline
    "fairness_spread": (int, float),
    "recompiles_steady_state": int,
    "baseline_recompiles_steady_state": int,
}
_BENCH_MULTI_LORA_OPTIONAL = {
    "requests": int,
    "max_new_tokens": int,
    "requests_per_sec": (int, float, type(None)),
    "greedy_parity": bool,
    "hot_adds": int,              # tenants joined AFTER warmup
    "pool_loads": int,
    "bgmv_impl": str,             # "xla" | "pallas" (engine-resolved)
    "completed": int,
}


def validate_bench_multi_lora(block: Any,
                              where: str = "multi_lora") -> List[str]:
    """Validate the ``multi_lora`` block of a bench artifact (absent on
    pre-multi-tenant rounds)."""
    problems = _check_fields(
        block, _BENCH_MULTI_LORA_REQUIRED, _BENCH_MULTI_LORA_OPTIONAL,
        where,
    )
    if problems:
        return problems
    if block["adapters"] < 1:
        problems.append(f"{where}: adapters must be >= 1")
    if block["rank"] < 1:
        problems.append(f"{where}: rank must be >= 1")
    if not 0.0 <= block["fairness_spread"] <= 1.0:
        problems.append(
            f"{where}: fairness_spread {block['fairness_spread']} "
            "outside [0, 1]"
        )
    for key in ("recompiles_steady_state",
                "baseline_recompiles_steady_state"):
        if block[key] < 0:
            problems.append(f"{where}: negative {key}")
    impl = block.get("bgmv_impl")
    if impl is not None and impl not in ("xla", "pallas"):
        problems.append(f"{where}: unknown bgmv_impl {impl!r}")
    return problems


# The bench_serve.py distributed-tracing block: the stitch-coverage /
# per-phase-percentile / overhead acceptance surface.  ``coverage`` is
# the fraction of COMPLETED requests whose stitched trace carries a
# complete queue_wait→…→first_token phase chain (the >=0.95 bar);
# ``overhead_pct`` is the measured closed-loop headline cost of
# cheap-tier tracing (the <2% bar); ``phases`` maps each critical-path
# phase to its p50/p95 over the traced run.
_BENCH_TRACE_REQUIRED = {
    "coverage": (int, float),
    "requests": int,
    "phases": dict,
    "overhead_pct": (int, float, type(None)),
}
_BENCH_TRACE_OPTIONAL = {
    "complete_chains": int,
    "spans": int,
    "traced_requests_per_sec": (int, float, type(None)),
    "baseline_requests_per_sec": (int, float, type(None)),
    "replicas": int,
    "prefill_workers": int,
}


def validate_bench_trace(block: Any, where: str = "trace") -> List[str]:
    """Validate the ``trace`` block of a bench artifact (absent on
    pre-tracing rounds)."""
    problems = _check_fields(
        block, _BENCH_TRACE_REQUIRED, _BENCH_TRACE_OPTIONAL, where
    )
    if problems:
        return problems
    if not 0.0 <= block["coverage"] <= 1.0:
        problems.append(
            f"{where}: coverage {block['coverage']} outside [0, 1]"
        )
    if block["requests"] < 0:
        problems.append(f"{where}: negative requests")
    for phase, summary in block["phases"].items():
        problems += _check_fields(
            summary, _SERVE_PHASE_FIELDS, {}, f"{where}.phases.{phase}"
        )
    return problems


# ---------------------------------------------------------------------------
# MPMD pipeline plane (mpmd/): stream items, transfer frames, live
# snapshot, bench block
# ---------------------------------------------------------------------------

# Per-optimizer-step stage beat on the worker→driver queue (the MPMD
# plane's live signal — stage workers run no heartbeat publisher).
_MPMD_STAGE_REQUIRED = {
    "type": str,          # always "mpmd_stage"
    "stage": int,
    "step": int,
    "bubble_fraction": (int, float),
    "stage_occupancy": (int, float),
}
_MPMD_STAGE_OPTIONAL = {
    "loss": (int, float),         # loss-hosting worker only
    "busy_s": (int, float),
    "blocked_s": (int, float),
    "trace": dict,                # the step's trace-context envelope
}

# The inter-stage transfer frame (mpmd/transfer.py wire contract):
# exactly one of ``data`` (inline payload) / ``shm`` (segment path).
_MPMD_XFER_REQUIRED = {
    "type": str,          # always "mpmd_xfer"
    "kind": str,          # "act" | "grad"
    "step": int,
    "mb": int,
    "chunk": int,
}
_MPMD_XFER_OPTIONAL = {
    "data": bytes,
    "shm": str,
    "trace": dict,        # sender's trace envelope (cross-stage stitch)
    "enc": str,           # wire codec ("act:bf16,grad:int8"); absent=f32
}

# mpmd-live.json (MpmdStrategy's live export, the rlt_top mpmd pane).
_MPMD_SNAPSHOT_REQUIRED = {
    "schedule": str,
    "interleave": int,
    "n_micro": int,
    "n_stages": int,
    "stages": list,       # per-stage mpmd_stage items
}


def validate_mpmd_stage_item(item: Any,
                             where: str = "mpmd_stage") -> List[str]:
    problems = _validate_typed(
        item, "mpmd_stage", _MPMD_STAGE_REQUIRED, _MPMD_STAGE_OPTIONAL,
        where,
    )
    if not problems:
        if item["stage"] < 0:
            problems.append(f"{where}: negative stage")
        if not 0.0 <= item["bubble_fraction"] <= 1.0:
            problems.append(
                f"{where}: bubble_fraction {item['bubble_fraction']} "
                "outside [0, 1]"
            )
        problems += _check_optional_trace(item, where)
    return problems


def validate_mpmd_xfer(item: Any, where: str = "mpmd_xfer") -> List[str]:
    problems = _validate_typed(
        item, "mpmd_xfer", _MPMD_XFER_REQUIRED, _MPMD_XFER_OPTIONAL, where
    )
    if problems:
        return problems
    if item["kind"] not in ("act", "grad"):
        problems.append(f"{where}: unknown kind {item['kind']!r}")
    if ("data" in item) == ("shm" in item):
        problems.append(
            f"{where}: exactly one of data/shm payload required"
        )
    for key in ("step", "mb", "chunk"):
        if item[key] < 0:
            problems.append(f"{where}: negative {key}")
    problems += _check_optional_trace(item, where)
    return problems


def validate_mpmd_snapshot(doc: Any,
                           where: str = "mpmd_snapshot") -> List[str]:
    """Validate the ``mpmd`` block of a live snapshot document."""
    problems = _check_fields(doc, _MPMD_SNAPSHOT_REQUIRED, {}, where)
    if problems:
        return problems
    for i, item in enumerate(doc["stages"]):
        problems += validate_mpmd_stage_item(
            item, f"{where}.stages[{i}]"
        )
    return problems


# The bench mpmd block: the pipeline A/B becomes round-over-round
# comparable only if bubble/throughput are spelled the same way.
# Headline identification is required; each probe arm is nullable.
_BENCH_MPMD_REQUIRED = {
    "schedule": str,
    "n_stages": int,
    "n_micro": int,
}
_BENCH_MPMD_OPTIONAL = {
    "interleave": int,
    "bubble_fraction": (int, float, type(None)),
    "gpipe_bubble_fraction": (int, float, type(None)),
    "stage_occupancy": (int, float, type(None)),
    "stage_skew_ms": (int, float, type(None)),
    "tokens_per_sec": (int, float, type(None)),
    "single_mesh_tokens_per_sec": (int, float, type(None)),
    "vs_single_mesh": (int, float, type(None)),
    "loss_parity_max_diff": (int, float, type(None)),
    "op_costs_ms": dict,
}


def validate_bench_mpmd(block: Any, where: str = "mpmd") -> List[str]:
    """Validate the ``mpmd`` block of a ``BENCH_*.json`` artifact
    (absent on pre-MPMD rounds)."""
    problems = _check_fields(
        block, _BENCH_MPMD_REQUIRED, _BENCH_MPMD_OPTIONAL, where
    )
    if problems:
        return problems
    if block["n_stages"] < 1:
        problems.append(f"{where}: n_stages must be >= 1")
    if block["n_micro"] < 1:
        problems.append(f"{where}: n_micro must be >= 1")
    for key in ("bubble_fraction", "gpipe_bubble_fraction"):
        value = block.get(key)
        if isinstance(value, (int, float)) and not 0 <= value <= 1:
            problems.append(f"{where}: {key} {value} outside [0, 1]")
    return problems


# The bench comm_overlap block: the backward-overlapped grad-sync A/B
# (round 25).  Both arms run the SAME int8_ef grad-comm config on the
# same mesh; only `segments` differs (0 = step-end sync, G >= 1 =
# tapped backward).  ``loss_rel_diff`` is the A/B fit parity at the EF
# tolerance; ``bytes_ratio`` = overlap grad_sync_bytes / step-end
# (bucket re-planning pads per group, so ~1.0 within 10%);
# ``collectives_before_last_dot_*`` is the HLO-structural proof that
# the overlapped arm's bucket collectives are data-dependence-ordered
# INTO the backward rather than appended after it (step-end arm: 0).
# ``mpmd_*`` keys record the quantized-DCN-wire probe.  Probe keys are
# nullable — each arm is best-effort.
_BENCH_COMM_OVERLAP_REQUIRED = {
    "segments": int,
    "mode": str,
    "loss_rel_diff": (int, float),
}
_BENCH_COMM_OVERLAP_OPTIONAL = {
    "devices": (int, type(None)),
    "loss_step_end": (int, float, type(None)),
    "loss_overlap": (int, float, type(None)),
    "grad_sync_bytes_step_end": (int, float, type(None)),
    "grad_sync_bytes_overlap": (int, float, type(None)),
    "bytes_ratio": (int, float, type(None)),
    "dispatches_per_opt_step_step_end": (int, float, type(None)),
    "dispatches_per_opt_step_overlap": (int, float, type(None)),
    "recompiles_step_end": (int, type(None)),
    "recompiles_overlap": (int, type(None)),
    "collectives_before_last_dot_step_end": (int, type(None)),
    "collectives_before_last_dot_overlap": (int, type(None)),
    "hlo_gate": (bool, type(None)),
    "mpmd_wire_enc": (str, type(None)),
    "mpmd_wire_ratio": (int, float, type(None)),
    "mpmd_loss_rel_diff": (int, float, type(None)),
}


def validate_bench_comm_overlap(
    block: Any, where: str = "comm_overlap"
) -> List[str]:
    """Validate the ``comm_overlap`` block of a ``BENCH_*.json``
    artifact (absent on pre-overlap rounds)."""
    problems = _check_fields(
        block, _BENCH_COMM_OVERLAP_REQUIRED,
        _BENCH_COMM_OVERLAP_OPTIONAL, where,
    )
    if problems:
        return problems
    if block["segments"] < 1:
        problems.append(
            f"{where}: segments must be >= 1 (the overlapped arm), got "
            f"{block['segments']}"
        )
    if block["loss_rel_diff"] < 0:
        problems.append(f"{where}: negative loss_rel_diff")
    ratio = block.get("bytes_ratio")
    if isinstance(ratio, (int, float)) and not 0.9 <= ratio <= 1.1:
        problems.append(
            f"{where}: bytes_ratio {ratio} outside [0.9, 1.1] — "
            "overlap bucketing must not change the wire volume"
        )
    if block.get("hlo_gate") is True:
        before = block.get("collectives_before_last_dot_overlap")
        if not isinstance(before, int) or before < 1:
            problems.append(
                f"{where}: hlo_gate claims interleaving but "
                "collectives_before_last_dot_overlap is not a positive "
                "count"
            )
    wire = block.get("mpmd_wire_ratio")
    if isinstance(wire, (int, float)) and wire < 1.0:
        problems.append(
            f"{where}: mpmd_wire_ratio {wire} < 1 (codec inflated the "
            "payload)"
        )
    return problems


# The bench telemetry block contract: BENCH_*.json rounds become
# machine-comparable only if every round spells these the same way.
_BENCH_REQUIRED = {
    "tier": str,
}
_BENCH_OPTIONAL = {
    "overhead_pct": (int, float, type(None)),
    "heartbeat_overhead_pct": (int, float, type(None)),
    "monitor_events": int,
    "report": dict,
    "headline": dict,
    "probe": dict,
}


def validate_bench_telemetry(block: Any,
                             where: str = "telemetry") -> List[str]:
    """Validate the ``telemetry`` block of a ``BENCH_*.json`` artifact
    (absence of the block entirely is the caller's call — pre-telemetry
    rounds legitimately lack it)."""
    return _check_fields(block, _BENCH_REQUIRED, _BENCH_OPTIONAL, where)


# The bench fault block: recovery cost lands in the perf trajectory
# (crash → resumed wall time, drain checkpoint write time, the backoff
# actually slept; since the elastic-world round: lost worker → resumed
# -at-smaller-world wall delta).  Every key is nullable — each probe is
# best-effort.
_BENCH_FAULT_OPTIONAL = {
    "time_to_recover_s": (int, float, type(None)),
    "drain_checkpoint_s": (int, float, type(None)),
    "backoff_s": (int, float, type(None)),
    "resize_time_to_recover_s": (int, float, type(None)),
    "resize_old_world": (int, type(None)),
    "resize_new_world": (int, type(None)),
}


def validate_bench_fault(block: Any, where: str = "fault") -> List[str]:
    """Validate the ``fault`` block of a ``BENCH_*.json`` artifact
    (absent on pre-recovery-plane rounds)."""
    problems = _check_fields(block, {}, _BENCH_FAULT_OPTIONAL, where)
    if not problems and isinstance(block, dict):
        for key in ("resize_old_world", "resize_new_world"):
            value = block.get(key)
            if isinstance(value, int) and value < 0:
                problems.append(f"{where}: negative {key}")
    return problems


# The bench host_overhead block: how much of the step the HOST costs
# (the megastep round's acceptance surface).  ``fit_vs_raw`` is the
# Trainer-path overhead budget; ``dispatches_per_opt_step`` counts jit
# dispatches per optimizer update on the headline (per-step) fit;
# ``megastep_*`` record the K-fused A/B arm.  Nullable per probe — each
# arm is best-effort, a failed probe must never cost the headline line.
_BENCH_HOST_OVERHEAD_OPTIONAL = {
    "fit_vs_raw": (int, float, type(None)),
    "dispatches_per_opt_step": (int, float, type(None)),
    "megastep_k": (int, type(None)),
    "megastep_dispatches_per_opt_step": (int, float, type(None)),
    "megastep_tokens_per_sec": (int, float, type(None)),
    "megastep_speedup": (int, float, type(None)),
}


def validate_bench_host_overhead(block: Any,
                                 where: str = "host_overhead") -> List[str]:
    """Validate the ``host_overhead`` block of a ``BENCH_*.json``
    artifact (absent on pre-megastep rounds)."""
    problems = _check_fields(block, {}, _BENCH_HOST_OVERHEAD_OPTIONAL, where)
    k = block.get("megastep_k") if isinstance(block, dict) else None
    if not problems and isinstance(k, int) and k < 1:
        problems.append(f"{where}: megastep_k must be >= 1, got {k}")
    return problems


# The bench opt_state block: the HBM-traffic diet's acceptance surface.
# ``bytes_*`` are ANALYTIC persistent AdamW moment bytes
# (models/optim.py:opt_state_bytes — the chip truth is the optimizer
# line in the per-op profile, tools/hw_session.sh); ``hbm_ratio`` =
# bytes_f32 / bytes_int8 (the >= 3.5x acceptance bar);
# ``loss_rel_diff_vs_f32`` is the measured A/B fit parity at the int8_ef
# grad-comm tolerance; ``update_sharding`` records the resolved
# cross-replica sharded-update arm.  Measured keys nullable per probe.
_BENCH_OPT_STATE_REQUIRED = {
    "dtype": str,
    "block_size": int,
    "bytes_f32": (int, float),
    "bytes_int8": (int, float),
    "bytes_active": (int, float),
    "hbm_ratio": (int, float),
}
_BENCH_OPT_STATE_OPTIONAL = {
    "loss_rel_diff_vs_f32": (int, float, type(None)),
    "tokens_per_sec": (int, float, type(None)),
    "vs_baseline": (int, float, type(None)),
    "update_sharding": (str, type(None)),
}


def validate_bench_opt_state(block: Any,
                             where: str = "opt_state") -> List[str]:
    """Validate the ``opt_state`` block of a ``BENCH_*.json`` artifact
    (absent on pre-round-15 artifacts)."""
    problems = _check_fields(
        block, _BENCH_OPT_STATE_REQUIRED, _BENCH_OPT_STATE_OPTIONAL, where
    )
    if not problems:
        if block["hbm_ratio"] <= 0:
            problems.append(f"{where}: hbm_ratio must be > 0")
        if block["block_size"] < 1:
            problems.append(f"{where}: block_size must be >= 1")
    return problems


# The bench residual_policy block: scan-residual compression A/B.
# ``*_bytes_per_step`` are ANALYTIC remat-saved residual bytes
# (models/gpt.py:residual_save_bytes; the chip truth is the profiler's
# dynamic-update-slice lines); ``vs_baseline`` is the measured
# tokens/sec ratio of the active arm against the baseline policy when
# the probe ran (remat fits measure nothing on the CPU container —
# nullable, chip numbers via tools/hw_session.sh).
_BENCH_RESIDUAL_REQUIRED = {
    "policy": str,
    "baseline_policy": str,
    "residual_bytes_per_step": (int, float),
    "baseline_residual_bytes_per_step": (int, float),
    "bytes_saved_pct": (int, float),
}
_BENCH_RESIDUAL_OPTIONAL = {
    "tokens_per_sec": (int, float, type(None)),
    "vs_baseline": (int, float, type(None)),
    "loss_rel_diff_vs_baseline": (int, float, type(None)),
}


def validate_bench_residual_policy(
    block: Any, where: str = "residual_policy"
) -> List[str]:
    """Validate the ``residual_policy`` block of a ``BENCH_*.json``
    artifact (absent on pre-round-15 artifacts)."""
    return _check_fields(
        block, _BENCH_RESIDUAL_REQUIRED, _BENCH_RESIDUAL_OPTIONAL, where
    )
