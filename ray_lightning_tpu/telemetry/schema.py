"""Telemetry artifact schemas + validators (the drift gate).

Three artifact families leave this subsystem: JSONL span dumps, Chrome
``trace_event`` documents, and the ``telemetry`` block inside
``BENCH_*.json``.  Downstream consumers (Perfetto, the trace-summary
tool, round-over-round bench comparison) parse them long after the
producing code has moved on — so the schema is written down HERE, and
``tools/check_telemetry_schema.py`` (wired into ``format.sh``) fails
fast when a producer drifts.

Validators return a list of problem strings (empty = valid) instead of
raising, so the CLI can report every problem in one pass.  jax-free.
"""

from __future__ import annotations

from typing import Any, Dict, List

__all__ = [
    "validate_span",
    "validate_span_jsonl",
    "validate_chrome_trace",
    "validate_bench_telemetry",
]

# JSONL span schema: required key → allowed types.
_SPAN_REQUIRED = {
    "name": str,
    "ts": (int, float),
    "dur": (int, float),
    "rank": int,
    "tid": int,
    "depth": int,
}
_SPAN_OPTIONAL = {"args": dict}

# Chrome complete-event schema (the subset our exporter emits and
# Perfetto requires).
_CHROME_X_REQUIRED = {
    "name": str,
    "ts": (int, float),
    "dur": (int, float),
    "pid": int,
    "tid": int,
}


def _check_fields(obj: Dict[str, Any], required: dict, optional: dict,
                  where: str) -> List[str]:
    problems = []
    if not isinstance(obj, dict):
        return [f"{where}: expected object, got {type(obj).__name__}"]
    for key, types in required.items():
        if key not in obj:
            problems.append(f"{where}: missing required key {key!r}")
        elif not isinstance(obj[key], types) or isinstance(obj[key], bool):
            problems.append(
                f"{where}: key {key!r} has type "
                f"{type(obj[key]).__name__}"
            )
    for key, types in optional.items():
        if key in obj and not isinstance(obj[key], types):
            problems.append(
                f"{where}: optional key {key!r} has type "
                f"{type(obj[key]).__name__}"
            )
    unknown = set(obj) - set(required) - set(optional)
    if unknown:
        problems.append(f"{where}: unknown keys {sorted(unknown)}")
    return problems


def validate_span(span: Dict[str, Any], where: str = "span") -> List[str]:
    problems = _check_fields(span, _SPAN_REQUIRED, _SPAN_OPTIONAL, where)
    if not problems and span["dur"] < 0:
        problems.append(f"{where}: negative dur {span['dur']}")
    return problems


def validate_span_jsonl(lines: List[str], where: str = "jsonl") -> List[str]:
    """Validate a span JSONL dump given as decoded lines."""
    import json

    problems = []
    for i, line in enumerate(lines):
        line = line.strip()
        if not line:
            continue
        try:
            obj = json.loads(line)
        except ValueError as e:
            problems.append(f"{where}:{i + 1}: not JSON ({e})")
            continue
        problems.extend(validate_span(obj, f"{where}:{i + 1}"))
    return problems


def validate_chrome_trace(doc: Any, where: str = "trace") -> List[str]:
    """Validate a Chrome ``trace_event`` document (our exporter's
    ``{"traceEvents": [...]}`` form; ``ph=="X"`` events only — other
    phases pass through, Perfetto tolerates them)."""
    problems: List[str] = []
    if not isinstance(doc, dict):
        return [f"{where}: expected a trace document object"]
    events = doc.get("traceEvents")
    if not isinstance(events, list):
        return [f"{where}: missing/invalid traceEvents list"]
    for i, ev in enumerate(events):
        if not isinstance(ev, dict):
            problems.append(f"{where}[{i}]: event is not an object")
            continue
        if ev.get("ph") != "X":
            continue
        for key, types in _CHROME_X_REQUIRED.items():
            if key not in ev:
                problems.append(f"{where}[{i}]: missing {key!r}")
            elif (not isinstance(ev[key], types)
                  or isinstance(ev[key], bool)):
                problems.append(
                    f"{where}[{i}]: {key!r} has type "
                    f"{type(ev[key]).__name__}"
                )
        if isinstance(ev.get("dur"), (int, float)) and ev["dur"] < 0:
            problems.append(f"{where}[{i}]: negative dur")
    return problems


# The bench telemetry block contract: BENCH_*.json rounds become
# machine-comparable only if every round spells these the same way.
_BENCH_REQUIRED = {
    "tier": str,
}
_BENCH_OPTIONAL = {
    "overhead_pct": (int, float, type(None)),
    "report": dict,
    "headline": dict,
    "probe": dict,
}


def validate_bench_telemetry(block: Any,
                             where: str = "telemetry") -> List[str]:
    """Validate the ``telemetry`` block of a ``BENCH_*.json`` artifact
    (absence of the block entirely is the caller's call — pre-telemetry
    rounds legitimately lack it)."""
    return _check_fields(block, _BENCH_REQUIRED, _BENCH_OPTIONAL, where)
