"""Trace-context propagation: request identity across process hops.

The per-process :class:`~.spans.SpanTracer` answers "where did THIS
process spend its time"; this module makes the spans of DIFFERENT
processes stitchable into one request timeline.  A
:class:`TraceContext` is three strings —

* ``trace_id`` — the request/step identity, constant across every hop
  (for serve requests it IS the rid, so a failover re-submission or a
  recompute-preemption replay lands in the same trace by construction);
* ``span_id`` — the id of the span that caused this hop (the sender's
  span);
* ``parent_span_id`` — that span's own parent, carried for flow-arrow
  rendering.

The context rides wire frames as an optional ``"trace"`` dict
(:func:`inject` / :func:`extract` — schema-pinned as
``telemetry/schema.py::validate_trace_context``, OPTIONAL on every
frame family so old producers stay wire-compatible), and the receiving
process continues it with :meth:`SpanTracer.start_remote` — its spans
record ``trace_id``/``span_id``/``parent_span_id`` in their args, which
is all ``telemetry/trace_collect.py`` needs to stitch per-process JSONL
exports into one Perfetto trace with cross-process arrows.

Root span ids are DERIVED (``<trace_id>.root``), not random: any
process that knows the trace id can parent a span to the root without
a registry — the router's failover hop links to the request root even
though the root span was opened before the failover was conceivable.

jax-free; the schema gate imports it.
"""

from __future__ import annotations

import time
import uuid
from typing import Any, Dict, NamedTuple, Optional

__all__ = [
    "TraceContext",
    "new_span_id",
    "root_context",
    "child_context",
    "inject",
    "extract",
    "trace_args",
]


class TraceContext(NamedTuple):
    trace_id: str
    span_id: str
    parent_span_id: Optional[str] = None

    @property
    def root_span_id(self) -> str:
        return self.trace_id + ".root"


def new_span_id() -> str:
    return uuid.uuid4().hex[:16]


def root_context(trace_id: str) -> TraceContext:
    """The root of a trace.  The span id is derived from the trace id,
    so every process agrees on it without coordination."""
    trace_id = str(trace_id)
    return TraceContext(trace_id, trace_id + ".root", None)


def child_context(ctx: TraceContext,
                  span_id: Optional[str] = None) -> TraceContext:
    """A fresh span under ``ctx`` (the caller's span becomes the
    parent)."""
    return TraceContext(ctx.trace_id, span_id or new_span_id(),
                        ctx.span_id)


def inject(item: Dict[str, Any], ctx: Optional[TraceContext],
           ts: Optional[float] = None) -> Dict[str, Any]:
    """Stamp ``ctx`` into a wire frame (no-op when ``ctx`` is None).
    ``ts`` (wall-clock seconds, default now) records the SEND time so
    the consumer can book the transfer interval as a span without a
    second round trip."""
    if ctx is None:
        return item
    trace: Dict[str, Any] = {
        "trace_id": ctx.trace_id,
        "span_id": ctx.span_id,
        "ts": time.time() if ts is None else ts,
    }
    if ctx.parent_span_id is not None:
        trace["parent_span_id"] = ctx.parent_span_id
    item["trace"] = trace
    return item


def extract(item: Any) -> Optional[TraceContext]:
    """Recover the context a frame carries (None when absent or
    malformed — an old producer's frame must never fail the consumer)."""
    if not isinstance(item, dict):
        return None
    trace = item.get("trace")
    if not isinstance(trace, dict):
        return None
    trace_id, span_id = trace.get("trace_id"), trace.get("span_id")
    if not isinstance(trace_id, str) or not isinstance(span_id, str):
        return None
    parent = trace.get("parent_span_id")
    return TraceContext(trace_id, span_id,
                        parent if isinstance(parent, str) else None)


def sent_ts(item: Any) -> Optional[float]:
    """The producer-stamped wall-clock send time of a traced frame."""
    if not isinstance(item, dict):
        return None
    trace = item.get("trace")
    if not isinstance(trace, dict):
        return None
    ts = trace.get("ts")
    return float(ts) if isinstance(ts, (int, float)) else None


def trace_args(ctx: TraceContext, **extra: Any) -> Dict[str, Any]:
    """Span-args dict carrying the trace linkage (what
    ``trace_collect`` stitches on)."""
    args: Dict[str, Any] = {
        "trace_id": ctx.trace_id,
        "span_id": ctx.span_id,
    }
    if ctx.parent_span_id is not None:
        args["parent_span_id"] = ctx.parent_span_id
    args.update(extra)
    return args
