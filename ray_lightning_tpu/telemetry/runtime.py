"""Telemetry runtime: config coercion + the per-fit Telemetry object.

One :class:`Telemetry` instance lives on each rank's loop context for
the duration of a stage.  It owns the three collectors:

* :class:`~.spans.SpanTracer` — phase spans (full tier only);
* :class:`~.step_stats.StepStats` — the step-time breakdown engine;
* **counters** — a flat name→number registry (grad-sync wire bytes,
  non-finite log counts, checkpoint writes, …) that replaces the ad-hoc
  per-subsystem stat dicts PR 1 started.

Tiers (``TelemetryConfig.tier``):

* ``off``   — nothing recorded, no listener installed, no metric keys;
* ``cheap`` — **the default**: counters + step stats + headline metrics
  in ``callback_metrics``.  Budget: <1% per-step overhead (asserted by
  the overhead smoke test, measured precisely in ``BENCH_*``);
* ``full``  — cheap + span recording + JSONL/Chrome export at fit end.

Config sources, strongest first: an explicit ``telemetry=`` on the
strategy/loop call → the ``RLT_TELEMETRY`` env bus (forwarded to worker
actors exactly like ``RLT_GRAD_COMM``) → the cheap default.
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Any, Dict, Optional

from .spans import SpanTracer
from .step_stats import StepStats

__all__ = ["TelemetryConfig", "Telemetry", "TIERS"]

TIERS = ("off", "cheap", "full")


@dataclasses.dataclass(frozen=True)
class TelemetryConfig:
    """User-facing telemetry knobs (see module docstring for tiers).

    ``sample_every`` is the ``block_until_ready`` cadence of the device
    -step sampling window; ``span_buffer`` bounds the span ring buffer;
    ``export_dir`` overrides where the full tier drops its artifacts
    (default ``<default_root_dir>/telemetry``); ``heartbeat_s`` is the
    live-heartbeat publish cadence (``telemetry/heartbeat.py`` — 0
    disables the publisher, the tier gates it like everything else).
    """

    tier: str = "cheap"
    sample_every: int = 32
    span_buffer: int = 4096
    export_dir: Optional[str] = None
    heartbeat_s: float = 5.0

    def __post_init__(self):
        if self.tier not in TIERS:
            raise ValueError(
                f"telemetry tier {self.tier!r}: expected one of {TIERS}"
            )
        if self.sample_every < 1:
            raise ValueError("sample_every must be >= 1")
        if self.span_buffer < 1:
            raise ValueError("span_buffer must be >= 1")
        if self.heartbeat_s < 0:
            raise ValueError("heartbeat_s must be >= 0 (0 = disabled)")

    @classmethod
    def coerce(cls, value: Any) -> "TelemetryConfig":
        """None | str | dict | TelemetryConfig → TelemetryConfig.

        ``None`` reads the ``RLT_TELEMETRY`` env bus (tier name), with
        ``RLT_TELEMETRY_SAMPLE`` / ``RLT_TELEMETRY_DIR`` refining it —
        the same env-forwarding contract as ``RLT_GRAD_COMM``.
        """
        if isinstance(value, cls):
            return value
        if value is None:
            value = os.environ.get("RLT_TELEMETRY") or "cheap"
        if isinstance(value, str):
            kw: dict = {"tier": value}
        elif isinstance(value, dict):
            kw = dict(value)
            kw.setdefault("tier", "cheap")
        else:
            raise TypeError(
                "telemetry must be a tier string, dict or TelemetryConfig; "
                f"got {type(value).__name__}"
            )
        env_sample = os.environ.get("RLT_TELEMETRY_SAMPLE")
        if env_sample and "sample_every" not in kw:
            kw["sample_every"] = int(env_sample)
        env_dir = os.environ.get("RLT_TELEMETRY_DIR")
        if env_dir and "export_dir" not in kw:
            kw["export_dir"] = env_dir
        env_hb = os.environ.get("RLT_HEARTBEAT_S")
        if env_hb and "heartbeat_s" not in kw:
            kw["heartbeat_s"] = float(env_hb)
        return cls(**kw)


class Telemetry:
    """Per-rank, per-stage telemetry state (see module docstring)."""

    def __init__(self, config: TelemetryConfig, global_rank: int = 0,
                 world_size: int = 1, n_chips: int = 1):
        self.config = config
        self.global_rank = global_rank
        self.world_size = world_size
        self.enabled = config.tier != "off"
        self.tracer = SpanTracer(
            enabled=config.tier == "full",
            maxlen=config.span_buffer,
            rank=global_rank,
        )
        # StepStats installs the process-wide jax.monitoring listener;
        # the off tier must not touch jax at all.
        self.step_stats: Optional[StepStats] = (
            StepStats(sample_every=config.sample_every, n_chips=n_chips)
            if self.enabled else None
        )
        self.counters: Dict[str, float] = {}
        self.meta: Dict[str, Any] = {}

    @classmethod
    def build(cls, value: Any, global_rank: int = 0, world_size: int = 1,
              n_chips: int = 1) -> "Telemetry":
        return cls(TelemetryConfig.coerce(value), global_rank,
                   world_size, n_chips=n_chips)

    # -- counters -----------------------------------------------------------
    def add_counter(self, name: str, value: float) -> None:
        if not self.enabled:
            return
        self.counters[name] = self.counters.get(name, 0) + value

    def set_counter(self, name: str, value: float) -> None:
        if self.enabled:
            self.counters[name] = value

    def set_meta(self, name: str, value: Any) -> None:
        if self.enabled:
            self.meta[name] = value

    # -- spans (delegation keeps call sites one-attribute deep) -------------
    def span(self, name: str, **args):
        return self.tracer.span(name, **args)

    # -- surfaces -----------------------------------------------------------
    def headline_metrics(self) -> Dict[str, float]:
        """The numbers a plain ``fit()`` folds into callback_metrics."""
        if not self.enabled or self.step_stats is None:
            return {}
        return self.step_stats.headline()

    def snapshot(self) -> Dict[str, Any]:
        """Picklable per-rank snapshot — rides the result package the
        way ``comm_stats`` already does; merged fleet-wide by
        :func:`~.aggregate.merge_snapshots`."""
        if not self.enabled:
            return {}
        snap: Dict[str, Any] = {
            "rank": self.global_rank,
            "tier": self.config.tier,
            "counters": dict(self.counters),
            "meta": dict(self.meta),
        }
        if self.step_stats is not None:
            snap["step_stats"] = self.step_stats.summary()
        if self.tracer.enabled:
            snap["spans_recorded"] = (
                len(self.tracer.events()) + self.tracer.dropped
            )
            snap["spans_dropped"] = self.tracer.dropped
        return snap

    # -- export (full tier / TelemetryCallback) -----------------------------
    def export_dir_for(self, default_root_dir: str) -> str:
        return self.config.export_dir or os.path.join(
            default_root_dir, "telemetry"
        )

    def export(self, out_dir: str) -> Dict[str, str]:
        """Write spans (JSONL + Chrome trace) and the snapshot for this
        rank; returns the artifact paths."""
        tag = f"rank{self.global_rank}"
        paths = {
            "spans_jsonl": os.path.join(out_dir, f"spans-{tag}.jsonl"),
            "chrome_trace": os.path.join(out_dir, f"trace-{tag}.json"),
            "snapshot": os.path.join(out_dir, f"snapshot-{tag}.json"),
        }
        self.tracer.export_jsonl(paths["spans_jsonl"])
        self.tracer.export_chrome(paths["chrome_trace"])
        os.makedirs(out_dir, exist_ok=True)
        with open(paths["snapshot"], "w") as f:
            json.dump(self.snapshot(), f, indent=2, sort_keys=True)
        return paths
