"""Chrome-trace parsing: shared by the profile harness and trace tools.

Factored out of ``tools/profile_step.py`` so ANY run's exported Chrome
trace — a ``jax.profiler`` capture (``*.trace.json.gz``, written by
``ProfilerCallback`` or the profile harness) or this framework's own
span export (``telemetry/spans.py``) — parses through one code path:

* :func:`load_trace_events` — events from a ``.json`` / ``.json.gz``
  trace file (``{"traceEvents": [...]}`` documents or bare lists);
* :func:`collect` — aggregate ``ph == "X"`` self-durations by op name
  from the newest trace under a directory (the profiler layout);
* :func:`op_bucket` / :func:`bucket_totals` — the coarse phase buckets
  (matmul / attention / CE / layout / elementwise) the perf notes use.
"""

from __future__ import annotations

import collections
import glob
import gzip
import json
import os
from typing import Dict, List

__all__ = [
    "load_trace_events",
    "collect",
    "collect_file",
    "op_bucket",
    "bucket_totals",
    "top_ops",
]


def op_bucket(name: str) -> str:
    """Coarse cost bucket for one XLA/span event name."""
    n = name.lower()
    if "flash" in n or "attention" in n:
        return "attention-kernel"
    if "ce_fwd" in n or "ce_bwd" in n or "cross_entropy" in n:
        return "ce-kernel"
    if "dot" in n or "conv" in n or "einsum" in n:
        return "matmul"
    if "dynamic-update-slice" in n or "dynamic_update" in n:
        return "residual-save"
    if "copy" in n or "transpose" in n or "bitcast" in n:
        return "layout"
    if "reduce" in n or "add" in n or "multiply" in n or "fused" in n:
        return "elementwise/fused"
    return "other"


def load_trace_events(path: str) -> List[dict]:
    """Events from one Chrome-trace file (gzip or plain; document or
    bare-list form)."""
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rt") as f:
        doc = json.load(f)
    if isinstance(doc, dict):
        return doc.get("traceEvents", [])
    if isinstance(doc, list):
        return doc
    raise ValueError(f"{path}: not a Chrome trace (got {type(doc).__name__})")


def _host_side_noise(name: str) -> bool:
    """Host-side python/runtime events that dominate CPU traces and
    double-count wall time; keep device-lane XLA ops only."""
    return (".py" in name or name.startswith("$")
            or "ThunkExecutor" in name or "np.asarray" in name)


def collect_file(path: str, keep_host: bool = False) -> Dict[str, float]:
    """Aggregate ``ph=='X'`` durations (µs) by event name from one file."""
    durs: Dict[str, float] = collections.defaultdict(float)
    for e in load_trace_events(path):
        if e.get("ph") != "X" or "dur" not in e:
            continue
        name = e.get("name", "?")
        if not keep_host and _host_side_noise(name):
            continue
        durs[name] += e["dur"]
    return dict(durs)


def collect(trace_dir: str, keep_host: bool = False) -> Dict[str, float]:
    """Aggregate durations from the NEWEST trace under ``trace_dir``
    (the ``jax.profiler`` directory layout; also finds this framework's
    ``trace-rank*.json`` span exports)."""
    patterns = ("**/*.trace.json.gz", "**/*.trace.json",
                "trace-rank*.json")
    paths: List[str] = []
    for pat in patterns:
        paths.extend(
            glob.glob(os.path.join(trace_dir, pat), recursive=True)
        )
    if not paths:
        raise FileNotFoundError(f"no Chrome trace under {trace_dir}")
    newest = max(paths, key=os.path.getmtime)
    return collect_file(newest, keep_host=keep_host)


def bucket_totals(durs: Dict[str, float]) -> Dict[str, float]:
    buckets: Dict[str, float] = collections.defaultdict(float)
    for name, d in durs.items():
        buckets[op_bucket(name)] += d
    return dict(buckets)


def top_ops(durs: Dict[str, float], n: int = 25):
    """``[(name, total_dur_us)]``, costliest first."""
    return sorted(durs.items(), key=lambda kv: -kv[1])[:n]
