"""Low-overhead span tracer: monotonic-clock phase timing per rank.

The tracing half of the telemetry subsystem (SURVEY §5: the reference
ships zero observability).  A :class:`SpanTracer` records named phases —
``compile``, ``data_wait``, ``dispatch``, ``validation``,
``checkpoint_write``, ``grad_sync``, ``host_transfer`` — into a bounded
ring buffer, one tracer per rank.  Two export formats:

* **JSONL** — one span object per line (the machine-diffable form the
  schema checker validates, ``tools/check_telemetry_schema.py``);
* **Chrome ``trace_event``** — a ``{"traceEvents": [...]}`` document of
  ``ph == "X"`` complete events, loadable in Perfetto / ``chrome://tracing``
  next to the ``jax.profiler`` traces ``ProfilerCallback`` captures.

Overhead discipline: the tracer is OFF at the default cheap telemetry
tier.  A disabled tracer's ``span()`` returns one preallocated no-op
context manager (no generator, no allocation), so leaving instrumentation
in the hot loop costs a single attribute check per call.  This module is
deliberately jax-free — the schema checker imports it from ``format.sh``
and must not pay (or require) a jax import.
"""

from __future__ import annotations

import collections
import json
import os
import threading
import time
from typing import Any, Dict, List, NamedTuple, Optional

__all__ = ["PHASES", "Span", "SpanTracer"]

#: Canonical phase names the loop instruments.  Free-form names are also
#: accepted — these exist so dashboards and tests agree on spelling.
PHASES = (
    "compile",
    "data_wait",
    "dispatch",
    "validation",
    "checkpoint_write",
    "grad_sync",
    "host_transfer",
)


class Span(NamedTuple):
    name: str
    ts: float        # perf_counter seconds at open
    dur: float       # seconds
    rank: int
    tid: int         # python thread id (checkpoint writer ≠ loop thread)
    depth: int       # nesting depth within its thread (0 = top level)
    args: Optional[Dict[str, Any]] = None


class _NullCtx:
    """Shared no-op context manager for the disabled tracer.  ``ctx``
    mirrors :class:`_SpanCtx` so ``start_remote`` call sites read the
    trace context unconditionally."""

    __slots__ = ()

    ctx = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL_CTX = _NullCtx()


class _SpanCtx:
    """One live span: records on exit, tracks per-thread nesting depth.

    ``ctx`` (a :class:`~.propagate.TraceContext` on spans opened via
    :meth:`SpanTracer.start_remote`) is exposed so the body can inject
    the span's OWN identity into outgoing frames — the receiving
    process then parents its spans here."""

    __slots__ = ("_tracer", "_name", "_args", "_t0", "_depth", "ctx")

    def __init__(self, tracer: "SpanTracer", name: str, args, ctx=None):
        self._tracer = tracer
        self._name = name
        self._args = args
        self.ctx = ctx

    def __enter__(self):
        stack = self._tracer._stack()
        self._depth = len(stack)
        stack.append(self._name)
        self._tracer.open_span = self._name
        self._t0 = self._tracer._clock()
        return self

    def __exit__(self, *exc):
        t1 = self._tracer._clock()
        stack = self._tracer._stack()
        stack.pop()
        self._tracer.open_span = stack[-1] if stack else None
        self._tracer.record(
            self._name, self._t0, t1 - self._t0,
            depth=self._depth, args=self._args,
        )
        return False


class SpanTracer:
    """Bounded ring buffer of :class:`Span` records for one rank.

    ``maxlen`` bounds memory (a week-long fit cannot OOM the host on
    telemetry); the *newest* spans win, and ``dropped`` counts evictions
    so exports are honest about truncation.
    """

    def __init__(self, enabled: bool = False, maxlen: int = 4096,
                 rank: int = 0, clock=None):
        if maxlen < 1:
            raise ValueError("maxlen must be >= 1")
        self.enabled = enabled
        self.rank = rank
        self.maxlen = maxlen
        # Default: monotonic perf_counter (per-process phase timing).
        # The DISTRIBUTED tracers pass time.time — cross-process stitch
        # needs one shared epoch, and a perf_counter origin is
        # process-private.
        self._clock = clock or time.perf_counter
        self._buf: collections.deque = collections.deque(maxlen=maxlen)
        self._recorded = 0
        self._local = threading.local()
        # Name of the deepest currently-open span (last writer wins
        # across threads).  Exists so the heartbeat publisher — a
        # DIFFERENT thread, which cannot see the thread-local stack —
        # can report what phase the loop is inside right now.
        self.open_span: Optional[str] = None

    def _stack(self) -> List[str]:
        stack = getattr(self._local, "stack", None)
        if stack is None:
            stack = self._local.stack = []
        return stack

    # -- recording ----------------------------------------------------------
    def span(self, name: str, **args):
        """Context manager timing one phase.  No-op when disabled."""
        if not self.enabled:
            return _NULL_CTX
        return _SpanCtx(self, name, args or None)

    def record(self, name: str, ts: float, dur: float, depth: int = 0,
               args: Optional[Dict[str, Any]] = None) -> None:
        """Append an already-measured span (the loop measures data-wait
        and dispatch anyway for step stats; re-timing them would skew)."""
        if not self.enabled:
            return
        self._buf.append(
            Span(name, ts, dur, self.rank,
                 threading.get_ident() & 0x7FFFFFFF, depth, args)
        )
        self._recorded += 1

    def start_remote(self, ctx, name: str, **args):
        """Context manager for a span CONTINUING a remote trace: the
        span parents to ``ctx`` (a :class:`~.propagate.TraceContext`
        from another process's wire frame) and carries its own fresh
        span id, exposed as ``.ctx`` on the returned manager so the
        body can propagate further downstream.  No-op (and ``.ctx`` is
        None) when the tracer is disabled or ``ctx`` is None."""
        if not self.enabled or ctx is None:
            return _NULL_CTX
        from ray_lightning_tpu.telemetry.propagate import (
            child_context, trace_args,
        )

        child = child_context(ctx)
        return _SpanCtx(self, name, trace_args(child, **args), ctx=child)

    def instant(self, name: str, **args) -> None:
        """Zero-duration metadata marker (e.g. the grad-sync plan)."""
        self.record(name, self._clock(), 0.0, args=args or None)

    # -- introspection ------------------------------------------------------
    def events(self) -> List[Span]:
        return list(self._buf)

    @property
    def dropped(self) -> int:
        return self._recorded - len(self._buf)

    def clear(self) -> None:
        self._buf.clear()
        self._recorded = 0

    # -- export -------------------------------------------------------------
    def _span_dict(self, s: Span) -> Dict[str, Any]:
        d = {
            "name": s.name,
            "ts": s.ts,
            "dur": s.dur,
            "rank": s.rank,
            "tid": s.tid,
            "depth": s.depth,
        }
        if s.args:
            d["args"] = s.args
        return d

    def export_jsonl(self, path: str) -> int:
        """One span per line; returns the number of spans written."""
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        spans = self.events()
        with open(path, "w") as f:
            for s in spans:
                f.write(json.dumps(self._span_dict(s)) + "\n")
        return len(spans)

    def chrome_trace(self) -> Dict[str, Any]:
        """Chrome ``trace_event`` document (``ph=="X"`` complete events,
        microsecond timestamps, pid = rank so a fleet's traces merge into
        one per-rank-lane Perfetto view)."""
        events = []
        for s in self.events():
            ev = {
                "ph": "X",
                "name": s.name,
                "ts": s.ts * 1e6,
                "dur": s.dur * 1e6,
                "pid": s.rank,
                "tid": s.tid,
            }
            if s.args:
                ev["args"] = s.args
            events.append(ev)
        return {
            "traceEvents": events,
            "displayTimeUnit": "ms",
            "otherData": {
                "producer": "ray_lightning_tpu.telemetry",
                "rank": self.rank,
                "dropped_spans": self.dropped,
            },
        }

    def export_chrome(self, path: str) -> int:
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        doc = self.chrome_trace()
        with open(path, "w") as f:
            json.dump(doc, f)
        return len(doc["traceEvents"])
