"""Stitch per-process span exports into ONE request-scoped trace.

The distributed tracers (serve engine/router/prefill worker, MPMD
stage runners) each export wall-clock span JSONL named
``trace-<component>.jsonl`` into a shared telemetry dir; every span's
``args`` carries its ``trace_id``/``span_id``/``parent_span_id``
(:mod:`.propagate`).  This module is the consumer side:

* :func:`load_trace_dir` — all component exports under a dir;
* :func:`stitch_chrome` — ONE Perfetto-loadable Chrome ``trace_event``
  document: one pid lane per component, ``ph=="X"`` slices, and
  cross-process **flow arrows** (``ph=="s"``/``"f"`` pairs) wherever a
  span's parent lives in a different component's export;
* :func:`request_traces` / :func:`coverage` /
  :func:`phase_percentiles` / :func:`critical_path` — the per-request
  critical-path decomposition: group spans by ``trace_id``, check each
  completed request for a complete ``queue_wait → … → first_token``
  phase chain (topology-aware: ``placement`` is required only when a
  router traced, ``handoff_transfer`` implies ``decode_admission``),
  and summarize each phase's p50/p95 across the corpus;
* :func:`mpmd_step_report` — per-step per-worker compute vs
  blocked-recv decomposition of MPMD traces.

jax-free, stdlib-only — the schema gate and ``tools/trace_stitch.py``
both import it.
"""

from __future__ import annotations

import collections
import glob
import json
import os
from typing import Any, Dict, List, Optional, Sequence, Tuple

__all__ = [
    "SERVE_PHASE_ORDER",
    "load_trace_file",
    "load_trace_dir",
    "stitch_chrome",
    "request_traces",
    "chain_for",
    "chain_complete",
    "coverage",
    "phase_percentiles",
    "critical_path",
    "slowest_requests",
    "mpmd_step_report",
    "format_report",
]

#: The serve critical path, in causal order.  A given request carries
#: the subset its topology produces: a monolith engine has no
#: placement/handoff legs, a disaggregated request has all of them.
SERVE_PHASE_ORDER = (
    "queue_wait",
    "placement",
    "prefill_compute",
    "handoff_transfer",
    "decode_admission",
    "first_token",
)

_MPMD_STEP_NAMES = ("mpmd_step", "mpmd_stage_step")


def load_trace_file(path: str) -> List[Dict[str, Any]]:
    """Spans from one JSONL export, annotated with their source name
    (``_src`` — the stitcher's pid lane key; stripped before schema
    validation)."""
    src = os.path.basename(path)
    if src.startswith("trace-"):
        src = src[len("trace-"):]
    if src.endswith(".jsonl"):
        src = src[: -len(".jsonl")]
    spans = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                span = json.loads(line)
            except ValueError:
                continue  # a torn final line must not fail the stitch
            if isinstance(span, dict):
                span["_src"] = src
                spans.append(span)
    return spans


def load_trace_dir(trace_dir: str) -> List[Dict[str, Any]]:
    """Every component export under ``trace_dir`` (the distributed
    tracers' ``trace-*.jsonl`` family — per-fit ``spans-rank*.jsonl``
    exports are perf_counter-clocked and deliberately excluded: they
    share no epoch with the wall-clock distributed spans)."""
    spans: List[Dict[str, Any]] = []
    for path in sorted(glob.glob(os.path.join(trace_dir,
                                              "trace-*.jsonl"))):
        spans.extend(load_trace_file(path))
    return spans


def _targs(span: Dict[str, Any]) -> Dict[str, Any]:
    args = span.get("args")
    return args if isinstance(args, dict) else {}


def _trace_id(span: Dict[str, Any]) -> Optional[str]:
    tid = _targs(span).get("trace_id")
    return tid if isinstance(tid, str) else None


# ---------------------------------------------------------------------------
# Perfetto stitch
# ---------------------------------------------------------------------------

def stitch_chrome(spans: Sequence[Dict[str, Any]]) -> Dict[str, Any]:
    """One Chrome ``trace_event`` document from many components' spans.

    Each source gets its own pid lane (named via ``M`` metadata
    events); cross-process parent→child links become flow arrows
    (``s`` at the parent slice, ``f`` binding to the child's enclosing
    slice) — the Perfetto view reads client→router→prefill→replica as
    one connected timeline."""
    sources = sorted({s.get("_src", "?") for s in spans})
    pid_of = {src: i + 1 for i, src in enumerate(sources)}
    events: List[Dict[str, Any]] = []
    for src, pid in pid_of.items():
        events.append({
            "ph": "M", "name": "process_name", "pid": pid, "tid": 0,
            "args": {"name": src},
        })
    by_span_id: Dict[str, Dict[str, Any]] = {}
    for span in spans:
        sid = _targs(span).get("span_id")
        if isinstance(sid, str) and sid not in by_span_id:
            by_span_id[sid] = span
    flow_id = 0
    for span in spans:
        pid = pid_of.get(span.get("_src", "?"), 0)
        ev = {
            "ph": "X",
            "name": span.get("name", "?"),
            "ts": float(span.get("ts", 0.0)) * 1e6,
            "dur": max(0.0, float(span.get("dur", 0.0))) * 1e6,
            "pid": pid,
            "tid": int(span.get("tid", 0)),
        }
        args = _targs(span)
        if args:
            ev["args"] = {k: v for k, v in args.items()}
        events.append(ev)
        parent_id = args.get("parent_span_id")
        parent = by_span_id.get(parent_id) if parent_id else None
        if parent is not None and parent.get("_src") != span.get("_src"):
            flow_id += 1
            p_pid = pid_of.get(parent.get("_src", "?"), 0)
            p_ts = float(parent.get("ts", 0.0)) * 1e6
            p_dur = max(0.0, float(parent.get("dur", 0.0))) * 1e6
            # 's' must sit INSIDE the parent slice; 'f' binds to the
            # child's enclosing slice at its start.
            events.append({
                "ph": "s", "id": flow_id, "name": "trace",
                "cat": "trace", "pid": p_pid,
                "tid": int(parent.get("tid", 0)),
                "ts": min(p_ts + p_dur, max(p_ts, ev["ts"] - 1.0)),
            })
            events.append({
                "ph": "f", "id": flow_id, "name": "trace",
                "cat": "trace", "bp": "e", "pid": pid,
                "tid": ev["tid"], "ts": ev["ts"] + 0.5,
            })
    return {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "otherData": {
            "producer": "ray_lightning_tpu.telemetry.trace_collect",
            "sources": sources,
        },
    }


# ---------------------------------------------------------------------------
# Serve critical path
# ---------------------------------------------------------------------------

def request_traces(
    spans: Sequence[Dict[str, Any]]
) -> Dict[str, List[Dict[str, Any]]]:
    """Spans grouped by trace_id, serve-request traces only (MPMD step
    traces are excluded — see :func:`mpmd_step_report`)."""
    groups: Dict[str, List[Dict[str, Any]]] = collections.defaultdict(list)
    mpmd_ids = {
        _trace_id(s) for s in spans if s.get("name") in _MPMD_STEP_NAMES
    }
    for span in spans:
        tid = _trace_id(span)
        if tid is not None and tid not in mpmd_ids:
            groups[tid].append(span)
    return dict(groups)


def chain_for(trace_spans: Sequence[Dict[str, Any]]
              ) -> List[Tuple[str, float, float]]:
    """The trace's phase chain as ``(phase, ts, dur)``, causal order.
    Re-emissions (preemption replays, failover re-placements) repeat a
    phase; the FIRST occurrence by timestamp represents the phase in
    the chain."""
    first: Dict[str, Tuple[float, float]] = {}
    for span in trace_spans:
        name = span.get("name")
        if name not in SERVE_PHASE_ORDER:
            continue
        ts = float(span.get("ts", 0.0))
        if name not in first or ts < first[name][0]:
            first[name] = (ts, float(span.get("dur", 0.0)))
    return [(p, *first[p]) for p in SERVE_PHASE_ORDER if p in first]


def chain_complete(trace_spans: Sequence[Dict[str, Any]],
                   require_placement: bool = False) -> bool:
    """True when the trace carries a complete critical path for its
    topology: ``queue_wait`` and ``first_token`` always; a compute
    source (``prefill_compute`` or ``decode_admission``); a
    ``handoff_transfer`` leg implies the import (``decode_admission``)
    landed; and ``placement`` when the corpus shows a tracing router."""
    present = {p for p, _, _ in chain_for(trace_spans)}
    if not {"queue_wait", "first_token"} <= present:
        return False
    if not present & {"prefill_compute", "decode_admission"}:
        return False
    if "handoff_transfer" in present and "decode_admission" not in present:
        return False
    if require_placement and "placement" not in present:
        return False
    return True


def _completed(trace_spans: Sequence[Dict[str, Any]]) -> bool:
    return any(
        s.get("name") == "request"
        and _targs(s).get("status") in ("finished", "completed")
        for s in trace_spans
    )


def coverage(spans: Sequence[Dict[str, Any]]
             ) -> Tuple[int, int, float]:
    """``(complete, completed_total, fraction)`` over COMPLETED
    requests — the bench's stitch-coverage acceptance number.  Expired/
    rejected requests legitimately have truncated chains and are not
    counted against coverage."""
    groups = request_traces(spans)
    routed = any(
        s.get("name") == "placement"
        for g in groups.values() for s in g
    )
    total = complete = 0
    for trace_spans in groups.values():
        if not _completed(trace_spans):
            continue
        total += 1
        if chain_complete(trace_spans, require_placement=routed):
            complete += 1
    return complete, total, (complete / total if total else 0.0)


def phase_percentiles(
    spans: Sequence[Dict[str, Any]]
) -> Dict[str, Dict[str, float]]:
    """Corpus-wide per-phase latency summary (p50/p95 ms) — the same
    spelling ``ServeStats`` exports live and the bench trace block
    commits."""
    from ray_lightning_tpu.serve.metrics import percentile

    durs: Dict[str, List[float]] = collections.defaultdict(list)
    for trace_spans in request_traces(spans).values():
        for phase, _, dur in chain_for(trace_spans):
            durs[phase].append(dur)
    out = {}
    for phase, vals in durs.items():
        out[phase] = {
            "n": len(vals),
            "p50_ms": round(percentile(vals, 50) * 1e3, 3),
            "p95_ms": round(percentile(vals, 95) * 1e3, 3),
        }
    return out


def critical_path(trace_spans: Sequence[Dict[str, Any]]
                  ) -> Dict[str, Any]:
    """One request's decomposition: phase durations in causal order,
    the unattributed remainder against the root span, and any failover
    hops."""
    chain = chain_for(trace_spans)
    root = next(
        (s for s in trace_spans if s.get("name") == "request"), None
    )
    e2e = float(root["dur"]) if root is not None else (
        max((float(s.get("ts", 0)) + float(s.get("dur", 0))
             for s in trace_spans), default=0.0)
        - min((float(s.get("ts", 0)) for s in trace_spans), default=0.0)
    )
    attributed = sum(d for _, _, d in chain)
    failovers = [
        _targs(s) for s in trace_spans if s.get("name") == "failover"
    ]
    return {
        "trace_id": _trace_id(trace_spans[0]) if trace_spans else None,
        "e2e_s": e2e,
        "phases": [(p, d) for p, _, d in chain],
        "unattributed_s": max(0.0, e2e - attributed),
        "failovers": failovers,
        "status": (_targs(root).get("status")
                   if root is not None else None),
    }


def slowest_requests(spans: Sequence[Dict[str, Any]],
                     k: int = 5) -> List[Dict[str, Any]]:
    """Critical paths of the K slowest completed requests by e2e."""
    paths = [
        critical_path(g) for g in request_traces(spans).values()
        if _completed(g)
    ]
    return sorted(paths, key=lambda p: -p["e2e_s"])[:k]


# ---------------------------------------------------------------------------
# MPMD step decomposition
# ---------------------------------------------------------------------------

def mpmd_step_report(spans: Sequence[Dict[str, Any]]
                     ) -> List[Dict[str, Any]]:
    """Per-step per-worker compute vs blocked-recv from MPMD stage
    traces: compute = FWD/BWD/UPDATE span time, blocked = the measured
    mailbox wait inside RECV spans (the bubble signal, now stitched
    across workers under one step trace_id)."""
    steps: Dict[str, Dict[str, Any]] = {}
    for span in spans:
        tid = _trace_id(span)
        if tid is None:
            continue
        args = _targs(span)
        name = span.get("name", "")
        if name in _MPMD_STEP_NAMES:
            entry = steps.setdefault(
                tid, {"trace_id": tid, "step": args.get("step"),
                      "workers": {}},
            )
            entry["step"] = args.get("step")
        elif name in ("fwd", "bwd", "update", "recv_act", "recv_grad",
                      "send_act", "send_grad"):
            entry = steps.setdefault(
                tid, {"trace_id": tid, "step": args.get("step"),
                      "workers": {}},
            )
            w = entry["workers"].setdefault(
                str(args.get("worker", "?")),
                {"compute_s": 0.0, "blocked_s": 0.0, "send_s": 0.0},
            )
            dur = float(span.get("dur", 0.0))
            if name in ("fwd", "bwd", "update"):
                w["compute_s"] += dur
            elif name.startswith("send"):
                w["send_s"] += dur
            else:
                w["blocked_s"] += float(args.get("blocked_s", dur))
    out = [e for e in steps.values() if e["workers"]]
    out.sort(key=lambda e: (e["step"] is None, e["step"]))
    return out


# ---------------------------------------------------------------------------
# Human-readable report
# ---------------------------------------------------------------------------

def format_report(spans: Sequence[Dict[str, Any]],
                  slowest_k: int = 5) -> str:
    """The text report ``tools/trace_stitch.py`` prints."""
    lines: List[str] = []
    complete, total, frac = coverage(spans)
    groups = request_traces(spans)
    if groups:
        lines.append(
            f"serve: {len(groups)} trace(s), {total} completed, "
            f"chain coverage {complete}/{total} ({frac:.1%})"
        )
        pct = phase_percentiles(spans)
        for phase in SERVE_PHASE_ORDER:
            if phase in pct:
                s = pct[phase]
                lines.append(
                    f"  {phase:<17} n={s['n']:<5} "
                    f"p50={s['p50_ms']:>9.3f}ms p95={s['p95_ms']:>9.3f}ms"
                )
        slow = slowest_requests(spans, slowest_k)
        if slow:
            lines.append(f"slowest {len(slow)} request(s):")
            for p in slow:
                phases = " -> ".join(
                    f"{name} {1e3 * d:.2f}ms" for name, d in p["phases"]
                )
                lines.append(
                    f"  {p['trace_id']}: e2e {1e3 * p['e2e_s']:.2f}ms"
                    f" [{phases}]"
                    + (f" +{1e3 * p['unattributed_s']:.2f}ms other"
                       if p["unattributed_s"] > 0 else "")
                    + (f"  FAILOVER x{len(p['failovers'])}"
                       if p["failovers"] else "")
                )
    mpmd = mpmd_step_report(spans)
    if mpmd:
        lines.append(f"mpmd: {len(mpmd)} stitched step(s)")
        for entry in mpmd[:slowest_k]:
            per_w = "  ".join(
                f"w{w}: compute {1e3 * v['compute_s']:.2f}ms"
                f" blocked {1e3 * v['blocked_s']:.2f}ms"
                for w, v in sorted(entry["workers"].items())
            )
            lines.append(f"  step {entry['step']}: {per_w}")
    if not lines:
        lines.append("no distributed-trace spans found")
    return "\n".join(lines)
