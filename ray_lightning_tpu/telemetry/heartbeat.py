"""Worker-side heartbeat publisher: the live end of the telemetry plane.

PR 2's snapshots ride the *end-of-run* result package — during the fit
the fleet is a black box.  This module closes that gap: every
``heartbeat_s`` seconds (``TelemetryConfig.heartbeat_s`` /
``RLT_HEARTBEAT_S``, default 5, tier-gated like everything else) a
background thread composes a compact rank-tagged heartbeat — step
counters, loop phase, step-time headline, device memory, host load,
the deepest open span — and ships it to the driver over the existing
``DriverQueue`` channel, where :class:`~.monitor.RunMonitor` consumes
it.

Design notes:

* **A thread, not a loop hook.**  Beats must keep flowing while the
  loop thread is wedged inside a collective — that is exactly when the
  driver needs them (beats flowing + progress frozen = hang; beats
  gone = process/network death).  The thread only *reads* loop state
  (GIL-atomic attribute loads), so its steady-state cost is a few
  dict builds per interval — unmeasurable against a training step.
* **Queue-or-file sink.**  Remote workers publish through their
  ``QueueHandle``; a :class:`~..parallel.strategies.LocalStrategy` fit
  has no queue, so beats append to
  ``<telemetry_dir>/heartbeats-rank<k>.jsonl`` instead — the same
  documents, tail-able by ``tools/rlt_top.py``.
* jax-free imports: device memory is read only when jax is already
  loaded in the process, and every probe degrades to absence.
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time
from typing import Any, Dict, Optional

__all__ = ["HeartbeatPublisher", "make_beat", "device_memory_stats"]


def device_memory_stats() -> Dict[str, float]:
    """Best-effort device-0 memory stats.  Never imports jax (a probe
    must not pay PJRT init); never raises."""
    jax = sys.modules.get("jax")
    if jax is None:
        return {}
    try:
        stats = jax.local_devices()[0].memory_stats()
    except Exception:  # noqa: BLE001 - absent on CPU, racy mid-teardown
        return {}
    if not stats:
        return {}
    out = {}
    for key in ("bytes_in_use", "peak_bytes_in_use", "bytes_limit"):
        if key in stats:
            out[key] = float(stats[key])
    return out


def _host_load() -> Optional[float]:
    try:
        return round(os.getloadavg()[0], 2)
    except (OSError, AttributeError):
        return None


def make_beat(rank: int, seq: int, ctx: Any,
              telemetry: Any = None, done: bool = False) -> Dict[str, Any]:
    """Compose one heartbeat document (schema:
    ``telemetry/schema.py:validate_heartbeat``) from live loop state.

    ``ctx`` is duck-typed (the LoopContext, or any object with the step
    counters) so the schema self-test can feed a stub without jax."""
    beat: Dict[str, Any] = {
        "type": "heartbeat",
        "rank": rank,
        "seq": seq,
        "ts": time.time(),
        "global_step": int(getattr(ctx, "global_step", 0)),
        "micro_step": int(getattr(ctx, "micro_step", 0)),
        "epoch": int(getattr(ctx, "current_epoch", 0)),
        "progress": int(getattr(ctx, "progress", 0)),
        "phase": str(getattr(ctx, "phase", "init")),
    }
    if done:
        beat["done"] = True
    if telemetry is not None:
        stats = getattr(telemetry, "step_stats", None)
        if stats is not None:
            headline = stats.headline()
            for key in ("step_time_ms", "data_wait_ms", "examples_per_sec"):
                if key in headline:
                    beat[key] = round(float(headline[key]), 3)
            # Wall seconds the process has spent inside XLA compiles:
            # a rank wedged "compiling" reads as exactly that on the
            # driver instead of as frozen progress.
            total_s = getattr(stats, "_compile_s_at_start", None)
            if total_s is not None:
                from .step_stats import compile_time_total_s

                beat["compile_total_s"] = round(
                    compile_time_total_s(), 3
                )
        tracer = getattr(telemetry, "tracer", None)
        open_span = getattr(tracer, "open_span", None)
        if open_span:
            beat["open_span"] = open_span
    mem = device_memory_stats()
    if mem:
        beat["device_memory"] = mem
    load = _host_load()
    if load is not None:
        beat["host_load"] = load
    return beat


class _FileSink:
    """JSONL append sink for queue-less (local) fits."""

    def __init__(self, path: str):
        self._path = path
        self._f = None

    def put(self, item: Dict[str, Any]) -> None:
        if self._f is None:
            os.makedirs(os.path.dirname(self._path) or ".", exist_ok=True)
            self._f = open(self._path, "a")
        self._f.write(json.dumps(item) + "\n")
        self._f.flush()

    def close(self) -> None:
        if self._f is not None:
            try:
                self._f.close()
            finally:
                self._f = None


class HeartbeatPublisher:
    """Background publisher of one rank's heartbeat stream."""

    def __init__(self, rank: int, ctx: Any, sink: Any,
                 interval_s: float, telemetry: Any = None):
        self.rank = rank
        self._ctx = ctx
        self._sink = sink
        self._interval_s = interval_s
        self._telemetry = telemetry
        # Publish lock: stop() sends the final done beat from the
        # CALLER's thread after joining the publisher with a timeout —
        # a wedged sink can outlive that join, leaving two threads in
        # _publish concurrently (duplicate seq numbers, interleaved
        # file-sink writes).  The lock serializes them.
        self._lock = threading.Lock()
        self._seq = 0                # guarded by self._lock
        self.beats_sent = 0          # guarded by self._lock
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    @classmethod
    def maybe_start(cls, telemetry: Any, ctx: Any, queue: Any,
                    config: Any) -> Optional["HeartbeatPublisher"]:
        """Build + start a publisher, or ``None`` when the tier is off,
        the interval is 0, or there is nowhere to publish to."""
        if telemetry is None or not getattr(telemetry, "enabled", False):
            return None
        interval_s = float(
            getattr(telemetry.config, "heartbeat_s", 0.0) or 0.0
        )
        if interval_s <= 0:
            return None
        sink = queue
        if sink is not None and hasattr(sink, "host") and hasattr(
            sink, "port"
        ):
            # Dedicated connection (fresh QueueHandle, own client_id/
            # seq space): the shared handle serializes puts under one
            # lock with a size-scaled send budget — a GB-scale
            # checkpoint thunk would block beats for minutes and read
            # driver-side as a dead rank.  Liveness needs its own lane.
            sink = type(sink)(sink.host, sink.port)
        if sink is None:
            tel_dir = getattr(ctx, "telemetry_dir", None)
            if tel_dir is None:
                return None
            sink = _FileSink(os.path.join(
                tel_dir, f"heartbeats-rank{telemetry.global_rank}.jsonl"
            ))
        pub = cls(telemetry.global_rank, ctx, sink, interval_s,
                  telemetry=telemetry)
        pub.start()
        return pub

    # -- publishing ---------------------------------------------------------
    def _publish(self, done: bool = False) -> bool:
        with self._lock:
            return self._publish_locked(done=done)

    def _publish_locked(self, done: bool = False) -> bool:
        # rlt: holds self._lock
        self._seq += 1
        beat = make_beat(self.rank, self._seq, self._ctx,
                         self._telemetry, done=done)
        try:
            self._sink.put(beat)
        except Exception:  # noqa: BLE001 - the queue dies at
            # teardown / driver restart; heartbeats are
            # diagnostics, never load-bearing.
            return False
        self.beats_sent += 1
        return True

    def _run(self) -> None:
        # First beat immediately: the monitor learns the rank exists
        # (and its socket works) before the first full interval.
        alive = self._publish()
        while alive and not self._stop.wait(self._interval_s):
            alive = self._publish()

    def start(self) -> None:
        if self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._run, name=f"rlt-heartbeat-r{self.rank}",
            daemon=True,
        )
        self._thread.start()

    def stop(self, final: bool = True, timeout_s: float = 5.0) -> None:
        """Stop the thread; ``final=True`` sends one last ``done`` beat
        so the monitor retires the rank instead of flagging the silence
        that legitimately follows fit completion."""
        if self._thread is None:
            return
        self._stop.set()
        self._thread.join(timeout=timeout_s)
        self._thread = None
        if final:
            # Bounded acquire, not `with`: when the join above timed
            # out the publisher thread may be wedged INSIDE a sink put
            # holding the lock — a final beat could never land on that
            # sink anyway, so skip it rather than hang teardown
            # unboundedly.
            if self._lock.acquire(timeout=timeout_s):
                try:
                    self._publish_locked(done=True)
                finally:
                    self._lock.release()
        close = getattr(self._sink, "close", None)
        if close is not None:
            try:
                close()
            except Exception:  # noqa: BLE001 - best-effort teardown
                pass
