"""Declarative SLOs with multi-window multi-burn-rate alerting.

The Google-SRE alerting recipe on top of
``telemetry/timeseries.py``: an :class:`SloSpec` names an objective
(availability ratio between two counters, or a latency/queue gauge
against a threshold), a target, and window pairs; the
:class:`SloEvaluator` computes the error-budget **burn rate** over
each pair and fires only when BOTH the fast and the slow window burn
above the pair's threshold — fast-only spikes (noise) and slow-only
drift (already-burned budget) stay silent.

    burn_rate = error_rate / (1 - target)

A burn rate of 1.0 spends exactly the budget over the SLO period;
14.4 over (5 min, 1 h) is the classic page threshold.  Our default
pairs are scaled down to serving horizons (seconds–minutes) because
the store retains minutes, not days — the MATH is unchanged.

Alerts are schema-valid ``slo_alert`` events on the existing event
plane (``make_event`` shape, SLO specifics riding the ``detail``
dict — ``telemetry/schema.py::validate_slo_alert``), deduplicated
until the spec re-arms (burn drops below threshold).  ``snapshot()``
feeds the ``rlt_slo_*`` OpenMetrics family and the bench gate.
jax-free; clock injectable per RLT004.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple

from ray_lightning_tpu.telemetry.timeseries import TimeSeriesStore

__all__ = ["SloSpec", "SloEvaluator", "default_serve_slos"]

# (fast_window_s, slow_window_s, burn-rate threshold) — fire only when
# BOTH windows burn above the threshold.  Scaled to serving horizons.
_DEFAULT_WINDOWS: Tuple[Tuple[float, float, float], ...] = (
    (10.0, 60.0, 10.0),
    (30.0, 180.0, 4.0),
)


@dataclass(frozen=True)
class SloSpec:
    """One objective.

    ``ratio`` mode: ``error_rate = rate(bad) / rate(total)`` over the
    window (two counter series — e.g. rejected vs submitted).
    ``threshold`` mode: ``error_rate`` = fraction of window bins where
    the gauge exceeds ``threshold`` (e.g. queue-wait p50 above bound).
    """

    name: str
    target: float                       # e.g. 0.99 — budget is 1-target
    mode: str = "ratio"                 # "ratio" | "threshold"
    bad: Optional[str] = None           # ratio: bad-count counter
    total: Optional[str] = None         # ratio: total-count counter
    gauge: Optional[str] = None         # threshold: gauge series name
    threshold: float = 0.0              # threshold: the bound
    windows: Tuple[Tuple[float, float, float], ...] = \
        field(default=_DEFAULT_WINDOWS)

    def __post_init__(self):
        if not 0.0 < self.target < 1.0:
            raise ValueError(
                f"SLO {self.name!r}: target {self.target} outside (0,1)"
            )
        if self.mode == "ratio":
            if not (self.bad and self.total):
                raise ValueError(
                    f"SLO {self.name!r}: ratio mode needs bad= and "
                    f"total= counter names"
                )
        elif self.mode == "threshold":
            if not self.gauge:
                raise ValueError(
                    f"SLO {self.name!r}: threshold mode needs gauge="
                )
        else:
            raise ValueError(
                f"SLO {self.name!r}: unknown mode {self.mode!r}"
            )


def default_serve_slos(queue_wait_ms: float = 500.0
                       ) -> Tuple[SloSpec, ...]:
    """The stock serving objectives the engine evaluates when the SLO
    plane is on: admission availability (rejections burn the budget)
    and queue-wait latency (p50 beyond the bound burns it)."""
    return (
        SloSpec(name="serve_availability", target=0.99, mode="ratio",
                bad="rejected", total="submitted"),
        SloSpec(name="serve_queue_wait", target=0.9, mode="threshold",
                gauge="queue_wait_p50_ms", threshold=queue_wait_ms),
    )


def _alert_detail(spec: SloSpec, worst: dict) -> dict:
    """The ``slo_alert`` event's ``detail`` payload — the one place
    the wire shape is built (RLT006-checked against
    ``_SLO_ALERT_DETAIL_*`` in ``telemetry/schema.py``)."""
    return {
        "slo": spec.name,
        "mode": spec.mode,
        "target": spec.target,
        "burn_rate": worst["burn_rate"],
        "error_rate": worst["error_rate"],
        "fast_window_s": worst["fast_window_s"],
        "slow_window_s": worst["slow_window_s"],
        "threshold_burn": worst["threshold_burn"],
    }


class SloEvaluator:
    """Evaluates specs against a :class:`TimeSeriesStore` and emits
    deduplicated ``slo_alert`` events."""

    def __init__(self, store: TimeSeriesStore, specs,
                 clock: Optional[Callable[[], float]] = None,
                 emit: Optional[Callable[[dict], None]] = None):
        import time

        self.store = store
        self.specs: Tuple[SloSpec, ...] = tuple(specs)
        names = [s.name for s in self.specs]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate SLO names: {sorted(names)}")
        self._clock = clock if clock is not None else time.time
        self._emit = emit
        self._firing: Dict[str, bool] = {s.name: False for s in self.specs}
        self._alerts_total: Dict[str, int] = \
            {s.name: 0 for s in self.specs}
        self._last: Dict[str, dict] = {}

    # -- the math ------------------------------------------------------------
    def _error_rate(self, spec: SloSpec,
                    window_s: float) -> Optional[float]:
        if spec.mode == "ratio":
            bad = self.store.rate(spec.bad, window_s)
            total = self.store.rate(spec.total, window_s)
            if bad is None or total is None or total <= 0:
                return None
            return min(max(bad / total, 0.0), 1.0)
        points = self.store.series(spec.gauge, window_s)
        if not points:
            return None
        over = sum(1 for _, v in points if v > spec.threshold)
        return over / len(points)

    def _burn(self, spec: SloSpec,
              window_s: float) -> Optional[float]:
        err = self._error_rate(spec, window_s)
        if err is None:
            return None
        return err / max(1.0 - spec.target, 1e-9)

    # -- evaluation ----------------------------------------------------------
    def evaluate(self) -> List[dict]:
        """One evaluation pass: returns the NEW alerts (events already
        handed to ``emit``), updating the firing/re-arm state."""
        from ray_lightning_tpu.telemetry.monitor import make_event

        alerts = []
        for spec in self.specs:
            worst = None  # the window pair burning hardest
            firing = False
            for fast_s, slow_s, bound in spec.windows:
                fast = self._burn(spec, fast_s)
                slow = self._burn(spec, slow_s)
                if fast is None or slow is None:
                    continue
                pair_firing = fast >= bound and slow >= bound
                burn = min(fast, slow)  # the pair burns at its floor
                if worst is None or burn > worst["burn_rate"]:
                    worst = {
                        "burn_rate": burn,
                        "fast_window_s": fast_s,
                        "slow_window_s": slow_s,
                        "threshold_burn": bound,
                        "error_rate": self._error_rate(spec, slow_s)
                        or 0.0,
                    }
                firing = firing or pair_firing
            self._last[spec.name] = {
                "firing": firing,
                "burn_rate": worst["burn_rate"] if worst else 0.0,
                "error_rate": worst["error_rate"] if worst else 0.0,
                "target": spec.target,
                "alerts_total": self._alerts_total[spec.name],
            }
            was = self._firing[spec.name]
            self._firing[spec.name] = firing
            if firing and not was and worst is not None:
                self._alerts_total[spec.name] += 1
                self._last[spec.name]["alerts_total"] = \
                    self._alerts_total[spec.name]
                alert = make_event(
                    "slo_alert", -1,
                    message=(
                        f"SLO {spec.name} burning "
                        f"{worst['burn_rate']:.1f}x budget "
                        f"(threshold {worst['threshold_burn']:.1f}x "
                        f"over {worst['fast_window_s']:.0f}s/"
                        f"{worst['slow_window_s']:.0f}s)"
                    ),
                    detail=_alert_detail(spec, worst),
                )
                if self._emit is not None:
                    self._emit(alert)
                alerts.append(alert)
        return alerts

    def snapshot(self) -> dict:
        """Per-SLO burn/firing state for the prom family and the live
        export (``rlt_slo_*``; rlt_top's capacity pane)."""
        return {name: dict(state) for name, state in self._last.items()}

    @property
    def alerts_total(self) -> int:
        return sum(self._alerts_total.values())
