"""Bounded fixed-interval time-series store (the fleet sensing layer).

Every observability surface before this round — prom export, rlt_top,
serve-live.json, ``ServeStats`` snapshots, the program ledger — is
point-in-time.  The fleet scheduler (ROADMAP item 4) needs *trends*:
windowed rates, percentiles over a horizon, slopes, and
ETA-to-threshold predictions.  This module is that retention layer:

- **Fixed-interval ring bins.**  Each named series owns a bounded
  ``deque`` of ``(bin_start_ts, payload)`` bins, one bin per
  ``interval_s`` of wall time.  Memory is O(capacity) per series no
  matter the observation rate — a hot serve loop feeding every export
  tick can never grow the store without bound.
- **Three kinds.**  ``counter`` bins retain the latest cumulative
  value (rates come from differencing across bins, reset-safe);
  ``gauge`` bins are last-write-wins; ``hist`` bins keep a bounded
  sample list that windowed-percentile queries merge.
- **Injectable clock** (RLT004): tests and replay drive time
  explicitly; production passes ``time.time``.
- **JSONL persistence**: ``dump_jsonl`` emits one
  ``timeseries_point`` per bin, shape enforced by
  ``telemetry/schema.py::validate_timeseries_point`` (format.sh
  layer 4 self-tests against this real producer).

Consumers: ``telemetry/slo.py`` (burn-rate windows),
``serve/capacity.py`` (headroom oracle), ``telemetry/monitor.py``
(heartbeat step-stats), ``serve/dist/router.py`` (per-replica beats).
jax-free, import-light.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional, Tuple

__all__ = ["TimeSeriesStore"]

_KINDS = ("counter", "gauge", "hist")
# Per-bin sample bound for hist series: windowed percentiles stay
# meaningful while a pathological producer cannot balloon one bin.
_HIST_BIN_SAMPLES = 256


class _Series:
    """One named series: a ring of fixed-interval bins."""

    __slots__ = ("kind", "bins")

    def __init__(self, kind: str, capacity: int):
        self.kind = kind
        # (bin_index, payload): payload is a float for counter/gauge,
        # a bounded list of floats for hist.
        self.bins: deque = deque(maxlen=capacity)


class TimeSeriesStore:
    """Bounded fixed-interval ring store with windowed queries.

    All public methods are thread-safe: the serve loop observes while
    the SLO evaluator / capacity oracle / bench harness query.
    """

    def __init__(self, interval_s: float = 1.0, capacity: int = 600,
                 clock: Optional[Callable[[], float]] = None):
        if interval_s <= 0:
            raise ValueError(f"interval_s must be > 0, got {interval_s}")
        if capacity < 2:
            raise ValueError(f"capacity must be >= 2, got {capacity}")
        self.interval_s = float(interval_s)
        self.capacity = int(capacity)
        self._clock = clock if clock is not None else time.time
        self._series: Dict[str, _Series] = {}  # guarded by self._lock
        self._lock = threading.Lock()

    # -- ingestion -----------------------------------------------------------
    def observe(self, name: str, value: float, kind: str = "gauge",
                ts: Optional[float] = None) -> None:
        """Record one observation.  ``counter`` values are CUMULATIVE
        (monotonic totals; rates come from :meth:`rate`), ``gauge``
        values are instantaneous, ``hist`` values are individual
        samples merged for windowed percentiles."""
        if kind not in _KINDS:
            raise ValueError(f"unknown series kind {kind!r}")
        if ts is None:
            ts = self._clock()
        idx = int(ts // self.interval_s)
        v = float(value)
        with self._lock:
            series = self._series.get(name)
            if series is None:
                series = self._series[name] = _Series(kind, self.capacity)
            elif series.kind != kind:
                raise ValueError(
                    f"series {name!r} is a {series.kind}, observed as "
                    f"{kind}"
                )
            bins = series.bins
            if bins and bins[-1][0] == idx:
                if kind == "hist":
                    samples = bins[-1][1]
                    if len(samples) < _HIST_BIN_SAMPLES:
                        samples.append(v)
                else:
                    # counter: latest cumulative wins; gauge: last
                    # write wins.  Same update either way.
                    bins[-1] = (idx, v)
            elif bins and bins[-1][0] > idx:
                pass  # out-of-order past the live bin: drop, stay O(1)
            else:
                bins.append((idx, [v] if kind == "hist" else v))

    # -- queries -------------------------------------------------------------
    def names(self) -> List[str]:
        with self._lock:
            return sorted(self._series)

    def kind(self, name: str) -> Optional[str]:
        with self._lock:
            series = self._series.get(name)
            return series.kind if series is not None else None

    def last(self, name: str) -> Optional[float]:
        """Latest value (counter: cumulative total; gauge: last write;
        hist: last sample)."""
        with self._lock:
            series = self._series.get(name)
            if series is None or not series.bins:
                return None
            payload = series.bins[-1][1]
            if series.kind == "hist":
                return payload[-1] if payload else None
            return payload

    def series(self, name: str, window_s: Optional[float] = None
               ) -> List[Tuple[float, float]]:
        """``(bin_start_ts, value)`` pairs inside the window (hist bins
        surface their per-bin mean)."""
        points = []
        for idx, payload, kind in self._window_bins(name, window_s):
            if kind == "hist":
                if not payload:
                    continue
                value = sum(payload) / len(payload)
            else:
                value = payload
            points.append((idx * self.interval_s, value))
        return points

    def rate(self, name: str, window_s: float) -> Optional[float]:
        """Counter increase per second across the window, reset-safe
        (a cumulative value that shrinks restarts the ramp at 0).
        ``None`` until two bins exist inside the window."""
        bins = self._window_bins(name, window_s)
        if len(bins) < 2:
            return None
        if bins[0][2] != "counter":
            raise ValueError(f"rate() wants a counter, {name!r} is "
                             f"a {bins[0][2]}")
        total = 0.0
        prev = bins[0][1]
        for _, value, _ in bins[1:]:
            total += value - prev if value >= prev else value
            prev = value
        dt = (bins[-1][0] - bins[0][0]) * self.interval_s
        return max(total, 0.0) / dt if dt > 0 else None

    def mean(self, name: str, window_s: float) -> Optional[float]:
        points = self.series(name, window_s)
        if not points:
            return None
        return sum(v for _, v in points) / len(points)

    def percentile(self, name: str, q: float,
                   window_s: float) -> Optional[float]:
        """Windowed nearest-rank percentile.  hist series merge their
        per-bin samples; gauge/counter series rank their bin values."""
        merged: List[float] = []
        for _, payload, kind in self._window_bins(name, window_s):
            if kind == "hist":
                merged.extend(payload)
            else:
                merged.append(payload)
        if not merged:
            return None
        merged.sort()
        rank = max(0, min(len(merged) - 1,
                          int(round(q / 100.0 * (len(merged) - 1)))))
        return merged[rank]

    def slope(self, name: str, window_s: float) -> Optional[float]:
        """Least-squares trend in value-units per second over the
        window.  ``None`` until two bins exist."""
        points = self.series(name, window_s)
        if len(points) < 2:
            return None
        n = len(points)
        mean_t = sum(t for t, _ in points) / n
        mean_v = sum(v for _, v in points) / n
        num = sum((t - mean_t) * (v - mean_v) for t, v in points)
        den = sum((t - mean_t) ** 2 for t, _ in points)
        return num / den if den > 0 else None

    def eta_to(self, name: str, threshold: float,
               window_s: float) -> Optional[float]:
        """Seconds until the series' trend line crosses ``threshold``
        — the KV-exhaustion / queue-overflow predictor.  ``None`` when
        the trend points away from the threshold (or is flat/unknown)."""
        slope = self.slope(name, window_s)
        last = self.last(name)
        if slope is None or last is None:
            return None
        gap = threshold - last
        if gap == 0:
            return 0.0
        if slope == 0 or (gap > 0) != (slope > 0):
            return None  # moving away (or not moving) — no crossing
        return gap / slope

    # -- persistence ---------------------------------------------------------
    def points(self, window_s: Optional[float] = None) -> List[dict]:
        """Schema-shaped ``timeseries_point`` dicts for every bin
        (``telemetry/schema.py::validate_timeseries_point``)."""
        out = []
        for name in self.names():
            for idx, payload, kind in self._window_bins(name, window_s):
                point = {
                    "type": "timeseries_point",
                    "name": name,
                    "kind": kind,
                    "ts": idx * self.interval_s,
                }
                if kind == "hist":
                    if not payload:
                        continue
                    ranked = sorted(payload)
                    point["value"] = ranked[len(ranked) // 2]
                    point["n"] = len(ranked)
                else:
                    point["value"] = payload
                out.append(point)
        return out

    def dump_jsonl(self, path: str,
                   window_s: Optional[float] = None) -> int:
        """Append every (windowed) bin as one JSON line; returns the
        number of points written."""
        import json

        points = self.points(window_s)
        with open(path, "a") as f:
            for point in points:
                f.write(json.dumps(point) + "\n")
        return len(points)

    # -- internals -----------------------------------------------------------
    def _window_bins(self, name: str, window_s: Optional[float]
                     ) -> List[Tuple[int, object, str]]:
        """(bin_index, payload, kind) bins inside the window, oldest
        first.  Copies under the lock so callers iterate lock-free."""
        with self._lock:
            series = self._series.get(name)
            if series is None or not series.bins:
                return []
            kind = series.kind
            bins = list(series.bins)
        if window_s is not None:
            floor = bins[-1][0] - int(window_s // self.interval_s)
            bins = [b for b in bins if b[0] >= floor]
        if kind == "hist":
            return [(idx, list(samples), kind) for idx, samples in bins]
        return [(idx, value, kind) for idx, value in bins]
