"""Fleet aggregation: merge per-rank telemetry snapshots on the driver.

Every worker's :meth:`Telemetry.snapshot` rides its result package (the
same channel ``comm_stats`` already uses); the driver merges them into
``trainer.telemetry_report`` — min/max/mean-across-ranks views whose
*skew* is the straggler signal (a healthy SPMD fleet is near-uniform:
one rank with 3x the ``data_wait_ms`` of its peers names the slow host).

Also here: :func:`host_stats`, the host-load/memory probe the node
agent's ``ping()`` and the actors' ``get_host_stats()`` expose so the
driver can attach host context to a straggler rank.  jax-free on
purpose (the driver may be a CPU-only laptop; the agent must not import
jax at all).
"""

from __future__ import annotations

import os
from typing import Any, Dict, List, Optional

__all__ = [
    "merge_snapshots",
    "host_stats",
    "straggler_ranks",
    "format_report",
]

def _summable(name: str) -> bool:
    """Whether a cross-rank ``sum`` view makes sense for a counter.
    Every ``grad_sync_*`` stat is an analytic per-device constant
    (bytes, ratio, buckets, block size, devices) — identical on every
    rank, so a sum would misread as a fleet total."""
    return not name.startswith("grad_sync_")


def _stat_view(values: List[float]) -> Dict[str, float]:
    mean = sum(values) / len(values)
    view = {
        "min": min(values),
        "max": max(values),
        "mean": mean,
    }
    if mean:
        # Relative spread across ranks: the straggler metric.
        view["skew_pct"] = 100.0 * (view["max"] - view["min"]) / abs(mean)
    return view


def merge_snapshots(snapshots: List[Dict[str, Any]]) -> Dict[str, Any]:
    """Merge per-rank snapshots into the driver-side telemetry report.

    Numeric ``step_stats`` keys and counters get min/max/mean(+skew)
    views; non-numeric metadata (tier, modes) is taken from rank 0.
    ``per_rank`` keeps the raw snapshots — they are small dicts, and the
    report must let a human drill from "rank skew 40%" to "which rank".
    """
    snaps = [s for s in snapshots if s]
    if not snaps:
        return {}
    snaps = sorted(snaps, key=lambda s: s.get("rank", 0))
    report: Dict[str, Any] = {
        "world_size": len(snaps),
        "tier": snaps[0].get("tier"),
        "per_rank": snaps,
    }

    def merge_numeric(section: str, pad_missing: bool = False):
        keys: Dict[str, List[float]] = {}
        for s in snaps:
            for k, v in (s.get(section) or {}).items():
                if isinstance(v, bool) or not isinstance(v, (int, float)):
                    continue
                keys.setdefault(k, []).append(float(v))
        out = {}
        for k, vals in keys.items():
            if len(vals) < len(snaps):
                if not pad_missing:
                    continue  # only fleet-complete metrics
                # Rank-0-only counters (checkpoint_writes — file I/O is
                # rank-guarded) and subset events (nonfinite_logs on the
                # one poisoned rank) must SURVIVE the merge: a missing
                # rank contributed zero, it didn't opt out.
                vals = vals + [0.0] * (len(snaps) - len(vals))
            view = _stat_view(vals)
            if len(keys[k]) < len(snaps):
                view["ranks_reporting"] = len(keys[k])
            out[k] = view
        return out

    report["step_stats"] = merge_numeric("step_stats")
    counters = merge_numeric("counters", pad_missing=True)
    for name, view in counters.items():
        if _summable(name):
            view["sum"] = view["mean"] * len(snaps)
    report["counters"] = counters
    meta = snaps[0].get("meta") or {}
    if meta:
        report["meta"] = dict(meta)
    # Non-numeric step_stats fields fall out of the min/max/mean merge
    # above; the MFU basis ("analytic" vs "measured") is the one a
    # report reader needs to interpret the mfu view, so it rides meta.
    basis = (snaps[0].get("step_stats") or {}).get("mfu_basis")
    if basis:
        report.setdefault("meta", {})["mfu_basis"] = basis
    return report


def host_stats() -> Dict[str, Any]:
    """Best-effort host load/memory for straggler context.

    Linux-first (``/proc/meminfo``); every probe degrades to absence,
    never to an exception — a telemetry read must not kill a ping.
    """
    out: Dict[str, Any] = {}
    try:
        la1, la5, la15 = os.getloadavg()
        out["load_1m"] = round(la1, 2)
        out["load_5m"] = round(la5, 2)
        out["load_15m"] = round(la15, 2)
    except (OSError, AttributeError):
        pass
    try:
        out["cpu_count"] = os.cpu_count()
    except Exception:  # noqa: BLE001
        pass
    try:
        with open("/proc/meminfo") as f:
            mem: Dict[str, int] = {}
            for line in f:
                parts = line.split()
                if parts and parts[0].rstrip(":") in (
                    "MemTotal", "MemAvailable"
                ):
                    mem[parts[0].rstrip(":")] = int(parts[1]) * 1024
        if "MemTotal" in mem:
            out["mem_total_bytes"] = mem["MemTotal"]
        if "MemAvailable" in mem:
            out["mem_available_bytes"] = mem["MemAvailable"]
    except (OSError, ValueError, IndexError):
        pass
    return out


def straggler_ranks(report: Dict[str, Any], metric: str = "step_mean_ms",
                    threshold_pct: float = 20.0) -> List[int]:
    """Ranks whose ``metric`` exceeds the fleet mean by more than
    ``threshold_pct`` — the drill-down the skew view points at."""
    view = (report.get("step_stats") or {}).get(metric)
    if not view or not view.get("mean"):
        return []
    cut = view["mean"] * (1.0 + threshold_pct / 100.0)
    out = []
    for snap in report.get("per_rank", []):
        v = (snap.get("step_stats") or {}).get(metric)
        if isinstance(v, (int, float)) and v > cut:
            out.append(int(snap.get("rank", -1)))
    return out


def _fmt_val(v: Optional[float]) -> str:
    if v is None:
        return "-"
    if abs(v) >= 1000:
        return f"{v:,.0f}"
    return f"{v:.3g}"


def format_report(report: Dict[str, Any]) -> str:
    """Human-readable one-screen rendering of a telemetry report."""
    if not report:
        return "telemetry: (empty report)"
    lines = [
        f"telemetry report — {report.get('world_size', '?')} rank(s), "
        f"tier={report.get('tier')}"
    ]
    for section in ("step_stats", "counters"):
        views = report.get(section) or {}
        if not views:
            continue
        lines.append(f"  {section}:")
        for name in sorted(views):
            v = views[name]
            skew = (f"  skew={v['skew_pct']:.1f}%"
                    if "skew_pct" in v else "")
            lines.append(
                f"    {name:<28} mean={_fmt_val(v.get('mean')):>10} "
                f"min={_fmt_val(v.get('min')):>10} "
                f"max={_fmt_val(v.get('max')):>10}{skew}"
            )
    return "\n".join(lines)
