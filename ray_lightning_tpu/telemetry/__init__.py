"""Unified telemetry: spans, step stats, fleet views, live monitoring.

The observability subsystem (ISSUEs 2 + 3).  One import surface:

* :class:`Telemetry` / :class:`TelemetryConfig` — the per-rank runtime
  and its tier knobs (``off`` / ``cheap`` default / ``full``), coerced
  from ``telemetry=`` on the strategies or the ``RLT_TELEMETRY`` env bus;
* :class:`SpanTracer` — phase spans with JSONL + Chrome-trace export;
* :class:`StepStats` — step-time split, throughput, analytic-FLOPs MFU,
  recompile counters, device memory stats;
* :func:`merge_snapshots` / :func:`host_stats` — driver-side fleet
  aggregation (``trainer.telemetry_report``) and straggler host context;
* the **live plane** (ISSUE 3): :class:`HeartbeatPublisher` (worker
  liveness/progress beats over the DriverQueue), :class:`RunMonitor` /
  :class:`MonitorConfig` (driver-side hang/straggler watchdog feeding
  ``trainer.monitor_report``), :class:`FlightRecorder` (crash bundles),
  :class:`RankLogHandler` (rank-tagged log ring + forwarding), and
  :mod:`.export_prom` (OpenMetrics textfile/HTTP export);
* :mod:`.trace_parse` / :mod:`.schema` — Chrome-trace parsing shared by
  the tools, and the artifact-schema validators ``format.sh`` gates on;
* the **SLO & capacity plane** (ISSUE 18): :class:`TimeSeriesStore`
  (bounded fixed-interval ring store with windowed rate/percentile/
  slope/ETA queries), :class:`SloSpec` / :class:`SloEvaluator`
  (multi-window multi-burn-rate alerting), feeding
  ``serve/capacity.py``'s headroom oracle.

See ``docs/OBSERVABILITY.md`` for the workflow.
"""

from ray_lightning_tpu.telemetry.aggregate import (
    format_report,
    host_stats,
    merge_snapshots,
    straggler_ranks,
)
from ray_lightning_tpu.telemetry.flight_recorder import FlightRecorder
from ray_lightning_tpu.telemetry.heartbeat import HeartbeatPublisher
from ray_lightning_tpu.telemetry.logs import RankLogHandler
from ray_lightning_tpu.telemetry.monitor import MonitorConfig, RunMonitor
from ray_lightning_tpu.telemetry.runtime import (
    TIERS,
    Telemetry,
    TelemetryConfig,
)
from ray_lightning_tpu.telemetry.propagate import (
    TraceContext,
    child_context,
    extract,
    inject,
    root_context,
)
from ray_lightning_tpu.telemetry.slo import (
    SloEvaluator,
    SloSpec,
    default_serve_slos,
)
from ray_lightning_tpu.telemetry.spans import PHASES, Span, SpanTracer
from ray_lightning_tpu.telemetry.timeseries import TimeSeriesStore
from ray_lightning_tpu.telemetry.step_stats import (
    StepStats,
    compile_event_count,
    flops_for_module,
    model_flops_per_token,
    peak_flops_per_chip,
    vit_flops_per_example,
)

__all__ = [
    "Telemetry",
    "TelemetryConfig",
    "TIERS",
    "SpanTracer",
    "Span",
    "PHASES",
    "TraceContext",
    "root_context",
    "child_context",
    "inject",
    "extract",
    "StepStats",
    "HeartbeatPublisher",
    "RunMonitor",
    "MonitorConfig",
    "FlightRecorder",
    "RankLogHandler",
    "model_flops_per_token",
    "vit_flops_per_example",
    "flops_for_module",
    "peak_flops_per_chip",
    "compile_event_count",
    "merge_snapshots",
    "host_stats",
    "straggler_ranks",
    "format_report",
    "TimeSeriesStore",
    "SloSpec",
    "SloEvaluator",
    "default_serve_slos",
]
