"""Rank-tagged log capture: the ring buffer behind crash forensics.

A worker that dies takes its process — and everything Python logged in
the minutes before — with it.  :class:`RankLogHandler` is a
``logging.Handler`` the fit loop installs on the root logger at enabled
telemetry tiers: it keeps the last-N formatted records in a bounded
ring (the flight recorder folds them into the crash bundle) and
forwards WARNING+ records to the driver as ``{"type": "log", ...}``
stream items, capped per fit so a log storm cannot flood the queue.

jax-free and allocation-light: format happens at emit (record args may
not outlive the handler), the ring is a ``deque`` with ``maxlen``.
"""

from __future__ import annotations

import collections
import logging
import threading
import time
from typing import Any, Dict, List, Optional

__all__ = ["RankLogHandler", "DEFAULT_RING_SIZE", "DEFAULT_FORWARD_CAP"]

DEFAULT_RING_SIZE = 200
#: Max WARNING+ records forwarded to the driver per fit — a crash loop
#: emitting thousands of warnings must not turn the queue into a DoS.
DEFAULT_FORWARD_CAP = 50
_MAX_MESSAGE_CHARS = 2048


class RankLogHandler(logging.Handler):
    """Bounded ring of formatted records + capped driver forwarding."""

    def __init__(self, rank: int, queue: Optional[Any] = None,
                 ring_size: Optional[int] = None,
                 forward_cap: int = DEFAULT_FORWARD_CAP):
        if ring_size is None:
            import os

            ring_size = int(
                os.environ.get("RLT_LOG_RING") or DEFAULT_RING_SIZE
            )
        super().__init__(level=logging.INFO)
        self.rank = rank
        self._queue = queue
        self._ring: collections.deque = collections.deque(maxlen=ring_size)
        self._forward_cap = forward_cap
        self._forwarded = 0
        self._ring_lock = threading.Lock()

    def emit(self, record: logging.LogRecord) -> None:
        try:
            message = record.getMessage()
        except Exception:  # noqa: BLE001 - malformed args must not kill logging
            message = str(record.msg)
        if len(message) > _MAX_MESSAGE_CHARS:
            message = message[:_MAX_MESSAGE_CHARS] + "…[truncated]"
        entry = {
            "ts": record.created,
            "level": record.levelname,
            "logger": record.name,
            "message": message,
        }
        with self._ring_lock:
            self._ring.append(entry)
        if (
            self._queue is not None
            and record.levelno >= logging.WARNING
            and self._forwarded < self._forward_cap
        ):
            self._forwarded += 1
            item: Dict[str, Any] = {
                "type": "log", "rank": self.rank, **entry,
            }
            try:
                self._queue.put(item)
            except Exception:  # noqa: BLE001 - the queue may be gone at
                # teardown; a log record must never crash the loop.
                self._queue = None

    def records(self) -> List[Dict[str, Any]]:
        """Ring contents, oldest first (the flight-bundle ``logs`` list)."""
        with self._ring_lock:
            return list(self._ring)

    # -- lifecycle ----------------------------------------------------------
    def install(self) -> "RankLogHandler":
        logging.getLogger().addHandler(self)
        return self

    def uninstall(self) -> None:
        logging.getLogger().removeHandler(self)


def make_log_item(rank: int, level: str, logger: str,
                  message: str) -> Dict[str, Any]:
    """A schema-shaped log stream item (shared by tests/self-tests)."""
    return {
        "type": "log",
        "rank": rank,
        "ts": time.time(),
        "level": level,
        "logger": logger,
        "message": message,
    }
