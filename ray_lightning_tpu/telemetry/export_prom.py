"""OpenMetrics export of the driver's live fleet snapshot.

Two transports, both fed by :class:`~.monitor.RunMonitor`:

* **textfile** (``RLT_PROM_FILE`` / ``MonitorConfig.prom_file``) — the
  node-exporter textfile-collector pattern: the snapshot is rendered
  and atomically replaced on every refresh, so any Prometheus scrape
  infrastructure already on the host picks it up with zero new ports;
* **localhost HTTP** (``RLT_PROM_PORT`` / ``prom_port``; port 0 =
  ephemeral) — a daemon-thread ``http.server`` bound to 127.0.0.1
  serving the latest render at ``/metrics`` for ad-hoc scrapes and
  ``curl`` during an incident.

The renderer is a pure function (snapshot dict → text) so tests and
``rlt_top`` can use it without a monitor.  jax-free, stdlib-only.
"""

from __future__ import annotations

import os
import threading
from typing import Any, Dict, Optional

__all__ = ["render_openmetrics", "PromExporter"]

_PREFIX = "rlt"


def _esc(value: Any) -> str:
    return str(value).replace("\\", "\\\\").replace('"', '\\"')


def render_openmetrics(snapshot: Dict[str, Any],
                       event_counts: Optional[Dict[str, int]] = None) -> str:
    """Render a :meth:`RunMonitor.snapshot` as OpenMetrics text."""
    lines = []

    def gauge(name: str, help_: str, samples) -> None:
        samples = list(samples)
        if not samples:
            return
        lines.append(f"# TYPE {_PREFIX}_{name} gauge")
        lines.append(f"# HELP {_PREFIX}_{name} {help_}")
        for labels, value in samples:
            label_s = ",".join(
                f'{k}="{_esc(v)}"' for k, v in sorted(labels.items())
            )
            label_s = "{" + label_s + "}" if label_s else ""
            lines.append(f"{_PREFIX}_{name}{label_s} {value}")

    gauge("fleet_ranks", "ranks that have reported a heartbeat",
          [({}, snapshot.get("ranks_reporting", 0))])
    gauge("monitor_aborted", "1 if the monitor aborted the fit",
          [({}, int(bool(snapshot.get("aborted"))))])
    ranks = snapshot.get("ranks", {})
    per_rank = [
        ("rank_global_step", "optimizer steps completed", "global_step"),
        ("rank_progress", "loop progress counter", "progress"),
        ("rank_heartbeat_age_seconds", "seconds since last heartbeat",
         "age_s"),
        ("rank_step_time_ms", "mean step wall time", "step_time_ms"),
        ("rank_data_wait_ms", "mean input-pipeline wait", "data_wait_ms"),
        ("rank_examples_per_sec", "training throughput",
         "examples_per_sec"),
        ("rank_host_load", "1-minute load average of the rank's host",
         "host_load"),
    ]
    for metric, help_, key in per_rank:
        gauge(metric, help_, (
            ({"rank": rank}, beat[key])
            for rank, beat in sorted(ranks.items())
            if isinstance(beat.get(key), (int, float))
        ))
    status_order = ("ok", "stalled", "lost", "crashed", "done")
    gauge("rank_status", "rank state (one-hot over status label)", (
        ({"rank": rank, "status": status}, int(beat.get("status") == status))
        for rank, beat in sorted(ranks.items())
        for status in status_order
    ))
    if event_counts:
        lines.append(f"# TYPE {_PREFIX}_monitor_events counter")
        lines.append(
            f"# HELP {_PREFIX}_monitor_events monitor events by kind"
        )
        for kind, n in sorted(event_counts.items()):
            lines.append(
                f'{_PREFIX}_monitor_events_total{{kind="{_esc(kind)}"}} {n}'
            )
    serve = snapshot.get("serve")
    if serve:
        lines.extend(_render_serve(serve))
        capacity = serve.get("capacity")
        if capacity:
            lines.extend(_render_capacity(capacity))
    slo = snapshot.get("slo")
    if slo:
        lines.extend(_render_slo(slo))
    router = snapshot.get("router")
    if router:
        lines.extend(_render_router(router))
        fleet = router.get("capacity")
        if fleet:
            lines.extend(_render_fleet_capacity(fleet))
    mpmd = snapshot.get("mpmd")
    if mpmd:
        lines.extend(_render_mpmd(mpmd))
    programs = snapshot.get("programs")
    if programs:
        lines.extend(_render_programs(programs))
    lines.append("# EOF")
    return "\n".join(lines) + "\n"


def _render_programs(programs: Dict[str, Any]) -> list:
    """The program ledger's section (``program_ledger.snapshot()``
    shape — ``telemetry/schema.py::validate_program_snapshot``):
    per-executable compile/cost/memory gauges labelled by dispatch
    site and variant, plus recompile-forensics counters by delta
    kind."""
    lines = []
    rows = programs.get("programs", [])
    per_program = [
        ("program_compile_seconds", "XLA compile wall time",
         "compile_s"),
        ("program_calls", "dispatches through this executable",
         "ncalls"),
        ("program_flops", "XLA cost-analysis FLOPs per dispatch",
         "flops"),
        ("program_bytes_accessed",
         "XLA cost-analysis HBM bytes touched per dispatch",
         "bytes_accessed"),
        ("program_argument_bytes", "executable argument bytes",
         "argument_bytes"),
        ("program_output_bytes", "executable output bytes",
         "output_bytes"),
        ("program_temp_bytes", "executable scratch (temp) bytes",
         "temp_bytes"),
    ]
    for metric, help_, key in per_program:
        samples = [
            ({"site": row.get("site"), "variant": row.get("variant")},
             row[key])
            for row in rows
            if isinstance(row.get(key), (int, float))
        ]
        if not samples:
            continue
        lines.append(f"# TYPE {_PREFIX}_{metric} gauge")
        lines.append(f"# HELP {_PREFIX}_{metric} {help_}")
        for labels, value in samples:
            label_s = ",".join(
                f'{k}="{_esc(v)}"' for k, v in sorted(labels.items())
            )
            lines.append(f"{_PREFIX}_{metric}{{{label_s}}} {value}")
    total_s = programs.get("compile_time_total_s")
    if isinstance(total_s, (int, float)):
        lines.append(
            f"# TYPE {_PREFIX}_program_compile_time_total_seconds gauge"
        )
        lines.append(
            f"# HELP {_PREFIX}_program_compile_time_total_seconds "
            f"process-lifetime wall seconds inside XLA compiles"
        )
        lines.append(
            f"{_PREFIX}_program_compile_time_total_seconds {total_s}"
        )
    recompiles: Dict[tuple, int] = {}
    for event in programs.get("recompiles", []):
        key = (event.get("site", "?"), event.get("kind", "?"))
        recompiles[key] = recompiles.get(key, 0) + 1
    if recompiles:
        lines.append(f"# TYPE {_PREFIX}_program_recompiles counter")
        lines.append(
            f"# HELP {_PREFIX}_program_recompiles recompile events by "
            f"site and delta kind (shape/dtype/structure/donation/"
            f"static)"
        )
        for (site, kind), n in sorted(recompiles.items()):
            lines.append(
                f'{_PREFIX}_program_recompiles_total'
                f'{{kind="{_esc(kind)}",site="{_esc(site)}"}} {n}'
            )
    return lines


def _render_capacity(capacity: Dict[str, Any]) -> list:
    """The headroom oracle's section (``capacity_snapshot`` shape —
    ``telemetry/schema.py::validate_capacity_snapshot``).  Nullable
    fields (the oracle refuses to guess before it has a measured
    per-slot service rate) are simply omitted."""
    lines = []
    metrics = [
        ("capacity_tokens_per_sec", "measured emitted tokens/s over "
         "the oracle window", "tokens_per_s"),
        ("capacity_ceiling_tokens_per_sec", "predicted saturation "
         "throughput (per-slot service rate x num_slots)",
         "capacity_tokens_per_s"),
        ("capacity_headroom_tokens_per_sec", "tokens/s slack below "
         "the predicted ceiling", "headroom_tokens_per_s"),
        ("capacity_utilization", "load as a fraction of the ceiling",
         "utilization"),
        ("capacity_service_rate_per_slot", "measured tokens/s per "
         "busy decode slot", "service_rate_per_slot"),
        ("capacity_kv_exhaustion_eta_seconds", "free-block trend "
         "extrapolated to pool exhaustion", "kv_exhaustion_eta_s"),
        ("capacity_queue_wait_slope_ms_per_sec", "queue-wait p50 "
         "trend over the window", "queue_wait_slope_ms_per_s"),
        ("capacity_rejection_rate", "rejected/submitted rate over "
         "the window", "rejection_rate"),
    ]
    for metric, help_, key in metrics:
        value = capacity.get(key)
        if not isinstance(value, (int, float)):
            continue
        lines.append(f"# TYPE {_PREFIX}_{metric} gauge")
        lines.append(f"# HELP {_PREFIX}_{metric} {help_}")
        lines.append(f"{_PREFIX}_{metric} {value}")
    return lines


def _render_fleet_capacity(fleet: Dict[str, Any]) -> list:
    """The router's fleet-wide capacity roll-up
    (``serve/capacity.py::aggregate_fleet``)."""
    lines = []
    metrics = [
        ("capacity_fleet_replicas_reporting", "members whose beats "
         "carry a capacity block", "replicas_reporting"),
        ("capacity_fleet_tokens_per_sec", "fleet emitted tokens/s",
         "tokens_per_s"),
        ("capacity_fleet_ceiling_tokens_per_sec", "fleet predicted "
         "saturation throughput", "capacity_tokens_per_s"),
        ("capacity_fleet_headroom_tokens_per_sec", "fleet tokens/s "
         "slack", "headroom_tokens_per_s"),
        ("capacity_fleet_utilization", "fleet load as a fraction of "
         "its ceiling", "utilization"),
        ("capacity_fleet_kv_exhaustion_eta_seconds", "worst member "
         "KV-exhaustion ETA", "kv_exhaustion_eta_s"),
    ]
    for metric, help_, key in metrics:
        value = fleet.get(key)
        if not isinstance(value, (int, float)):
            continue
        lines.append(f"# TYPE {_PREFIX}_{metric} gauge")
        lines.append(f"# HELP {_PREFIX}_{metric} {help_}")
        lines.append(f"{_PREFIX}_{metric} {value}")
    return lines


def _render_slo(slo: Dict[str, Any]) -> list:
    """The burn-rate evaluator's section
    (``telemetry/slo.py::SloEvaluator.snapshot`` shape): per-objective
    burn/error/firing gauges plus the lifetime alert counter."""
    lines = []
    per_slo = [
        ("slo_burn_rate", "error-budget burn multiple (worst window "
         "pair's floor)", "burn_rate"),
        ("slo_error_rate", "error rate over the slow window",
         "error_rate"),
        ("slo_target", "the objective", "target"),
    ]
    for metric, help_, key in per_slo:
        samples = [
            (name, state[key]) for name, state in sorted(slo.items())
            if isinstance(state.get(key), (int, float))
        ]
        if not samples:
            continue
        lines.append(f"# TYPE {_PREFIX}_{metric} gauge")
        lines.append(f"# HELP {_PREFIX}_{metric} {help_}")
        for name, value in samples:
            lines.append(
                f'{_PREFIX}_{metric}{{slo="{_esc(name)}"}} {value}'
            )
    lines.append(f"# TYPE {_PREFIX}_slo_firing gauge")
    lines.append(
        f"# HELP {_PREFIX}_slo_firing 1 while both burn windows "
        f"exceed the pair threshold"
    )
    for name, state in sorted(slo.items()):
        lines.append(
            f'{_PREFIX}_slo_firing{{slo="{_esc(name)}"}} '
            f'{int(bool(state.get("firing")))}'
        )
    lines.append(f"# TYPE {_PREFIX}_slo_alerts counter")
    lines.append(
        f"# HELP {_PREFIX}_slo_alerts slo_alert events emitted"
    )
    for name, state in sorted(slo.items()):
        n = state.get("alerts_total", 0)
        lines.append(
            f'{_PREFIX}_slo_alerts_total{{slo="{_esc(name)}"}} {n}'
        )
    return lines


def _render_router(router: Dict[str, Any]) -> list:
    """The disaggregated fleet's section (``router-live.json`` shape —
    ``telemetry/schema.py::validate_router_snapshot``): the
    ``rlt_serve_*`` family grown PER-REPLICA labels — occupancy,
    in-flight, block pool, per-replica spec acceptance — plus the
    router's own counters (routed/failovers/deaths/respawns) and
    prefill-worker gauges."""
    lines = []
    per_replica = [
        ("serve_replica_alive", "1 if the replica is serving", "alive"),
        ("serve_replica_inflight",
         "requests the router holds in flight on this replica",
         "inflight"),
        ("serve_replica_slots_active", "decode slots in flight",
         "slots_active"),
        ("serve_replica_num_slots", "decode program width", "num_slots"),
        ("serve_replica_queue_depth", "requests waiting for admission",
         "queue_depth"),
        ("serve_replica_blocks_free", "free KV-cache blocks",
         "blocks_free"),
        ("serve_replica_spec_acceptance_rate",
         "accepted / drafted on this replica", "spec_acceptance_rate"),
        ("serve_replica_prefix_cache_hit_rate",
         "prefix-cache hit rate on this replica",
         "prefix_cache_hit_rate"),
        ("serve_replica_recompiles",
         "compile events observed in the replica process",
         "recompiles"),
    ]
    replicas = router.get("replicas", [])
    for metric, help_, key in per_replica:
        samples = []
        for entry in replicas:
            value = entry.get(key)
            if key == "alive":
                value = int(bool(value))
            if isinstance(value, (int, float)) \
                    and not isinstance(value, bool):
                samples.append((entry.get("id"), value))
        if not samples:
            continue
        lines.append(f"# TYPE {_PREFIX}_{metric} gauge")
        lines.append(f"# HELP {_PREFIX}_{metric} {help_}")
        for replica, value in samples:
            lines.append(
                f'{_PREFIX}_{metric}{{replica="{_esc(replica)}"}} {value}'
            )
    workers = router.get("workers", [])
    samples = [
        (w.get("id"), int(bool(w.get("alive"))), w.get("pending", 0))
        for w in workers
    ]
    if samples:
        for metric, help_, idx in (
            ("serve_prefill_alive", "1 if the prefill worker is up", 1),
            ("serve_prefill_pending",
             "prompts dispatched and not yet handed off", 2),
        ):
            lines.append(f"# TYPE {_PREFIX}_{metric} gauge")
            lines.append(f"# HELP {_PREFIX}_{metric} {help_}")
            for row in samples:
                lines.append(
                    f'{_PREFIX}_{metric}{{worker="{_esc(row[0])}"}} '
                    f"{row[idx]}"
                )
    counters = router.get("counters", {})
    if counters:
        lines.append(f"# TYPE {_PREFIX}_serve_router counter")
        lines.append(
            f"# HELP {_PREFIX}_serve_router router events by kind "
            f"(routed, failovers, deaths, respawns)"
        )
        for kind in sorted(counters):
            lines.append(
                f'{_PREFIX}_serve_router_total{{kind="{_esc(kind)}"}} '
                f"{counters[kind]}"
            )
    return lines


def _render_mpmd(mpmd: Dict[str, Any]) -> list:
    """The MPMD pipeline plane's section (``mpmd-live.json`` shape —
    ``telemetry/schema.py::validate_mpmd_snapshot``): per-stage
    occupancy/bubble gauges plus the pipeline shape."""
    lines = []
    for name, help_, key in (
        ("mpmd_stages", "pipeline stage workers", "n_stages"),
        ("mpmd_microbatches", "micro-batches per optimizer step",
         "n_micro"),
        ("mpmd_interleave", "model chunks per stage worker",
         "interleave"),
    ):
        if key in mpmd:
            lines.append(f"# TYPE {_PREFIX}_{name} gauge")
            lines.append(f"# HELP {_PREFIX}_{name} {help_}")
            lines.append(f"{_PREFIX}_{name} {mpmd[key]}")
    stages = mpmd.get("stages", [])
    for metric, help_, key in (
        ("mpmd_stage_step", "last completed optimizer step", "step"),
        ("mpmd_stage_bubble_fraction",
         "idle fraction of the stage's pipeline wall", "bubble_fraction"),
        ("mpmd_stage_occupancy",
         "compute fraction of the stage's pipeline wall",
         "stage_occupancy"),
        ("mpmd_stage_loss", "last micro-batch-mean loss (loss stage)",
         "loss"),
        # The trace decomposition pair: how the stage's step wall split
        # into compute vs blocked-recv (the stitched-timeline numbers,
        # live).
        ("mpmd_trace_busy_seconds",
         "per-step stage compute seconds (trace decomposition)",
         "busy_s"),
        ("mpmd_trace_blocked_seconds",
         "per-step stage blocked-recv seconds (trace decomposition)",
         "blocked_s"),
    ):
        samples = [
            (item.get("stage"), item[key])
            for item in stages
            if isinstance(item.get(key), (int, float))
        ]
        if not samples:
            continue
        lines.append(f"# TYPE {_PREFIX}_{metric} gauge")
        lines.append(f"# HELP {_PREFIX}_{metric} {help_}")
        for stage, value in samples:
            lines.append(
                f'{_PREFIX}_{metric}{{stage="{_esc(stage)}"}} {value}'
            )
    return lines


def _render_serve(serve: Dict[str, Any]) -> list:
    """The serving plane's SLO section (``ServeStats.snapshot`` shape —
    ``telemetry/schema.py::validate_serve_snapshot``): admission/slot
    gauges, request counters by state, and TTFT / per-token latency
    percentiles."""
    lines = []
    gauges = serve.get("gauges", {})
    for name, help_ in (
        ("queue_depth", "requests waiting for admission"),
        ("slots_active", "decode slots in flight"),
        ("num_slots", "decode program width"),
        ("blocks_free", "free KV-cache blocks"),
        ("blocks_live", "allocated KV-cache blocks"),
        ("num_blocks", "KV-cache pool size in blocks"),
    ):
        if name in gauges:
            lines.append(f"# TYPE {_PREFIX}_serve_{name} gauge")
            lines.append(f"# HELP {_PREFIX}_serve_{name} {help_}")
            lines.append(f"{_PREFIX}_serve_{name} {gauges[name]}")
    counters = serve.get("counters", {})
    spec_tokens = {
        kind: counters[f"spec_{kind}"]
        for kind in ("drafted", "accepted", "emitted")
        if f"spec_{kind}" in counters
    }
    if counters:
        lines.append(f"# TYPE {_PREFIX}_serve_requests counter")
        lines.append(
            f"# HELP {_PREFIX}_serve_requests serve events by kind"
        )
        for kind in sorted(counters):
            if kind.startswith("spec_") and not kind == "spec_ticks":
                continue  # the rlt_serve_spec_* family below
            lines.append(
                f'{_PREFIX}_serve_requests_total'
                f'{{kind="{_esc(kind)}"}} {counters[kind]}'
            )
    # Speculative decoding (engines with a draft model): token-level
    # draft/accept/emit accounting + the derived SLO gauges.
    if spec_tokens:
        lines.append(f"# TYPE {_PREFIX}_serve_spec_tokens counter")
        lines.append(
            f"# HELP {_PREFIX}_serve_spec_tokens speculative tokens "
            f"by stage (drafted -> accepted -> emitted)"
        )
        for kind, value in sorted(spec_tokens.items()):
            lines.append(
                f'{_PREFIX}_serve_spec_tokens_total'
                f'{{kind="{_esc(kind)}"}} {value}'
            )
    for name, help_ in (
        ("spec_acceptance_rate",
         "accepted / drafted over the engine lifetime"),
        ("spec_goodput_tokens_per_sec",
         "client-visible emitted tokens per second"),
        ("lora_adapters_loaded",
         "LoRA tenants resident in the adapter pool"),
        ("lora_slots_free", "free adapter-pool slots"),
        ("lora_fairness_spread",
         "min/max lifetime tokens across LoRA tenants with traffic "
         "(1.0 = perfectly fair)"),
    ):
        if name in gauges:
            lines.append(f"# TYPE {_PREFIX}_serve_{name} gauge")
            lines.append(f"# HELP {_PREFIX}_serve_{name} {help_}")
            lines.append(f"{_PREFIX}_serve_{name} {gauges[name]}")
    # Multi-tenant LoRA (engines with an adapter pool): per-tenant
    # token/completion accounting — the fairness-spread decomposition.
    adapters = serve.get("adapters", {})
    if adapters:
        lines.append(f"# TYPE {_PREFIX}_serve_lora_tokens counter")
        lines.append(
            f"# HELP {_PREFIX}_serve_lora_tokens emitted tokens per "
            f"LoRA tenant"
        )
        for name in sorted(adapters):
            lines.append(
                f'{_PREFIX}_serve_lora_tokens_total'
                f'{{adapter="{_esc(name)}"}} '
                f"{adapters[name].get('tokens_out', 0)}"
            )
        lines.append(f"# TYPE {_PREFIX}_serve_lora_completed counter")
        lines.append(
            f"# HELP {_PREFIX}_serve_lora_completed completed requests "
            f"per LoRA tenant"
        )
        for name in sorted(adapters):
            lines.append(
                f'{_PREFIX}_serve_lora_completed_total'
                f'{{adapter="{_esc(name)}"}} '
                f"{adapters[name].get('completed', 0)}"
            )
    # Prefix-aware KV reuse (engines with a prefix cache): the block
    # accounting families plus the derived hit-rate/residency gauges.
    prefix = serve.get("prefix")
    if prefix:
        lines.append(f"# TYPE {_PREFIX}_serve_prefix_requests counter")
        lines.append(
            f"# HELP {_PREFIX}_serve_prefix_requests prefix-cache "
            f"lookups and whole-block hits at admission"
        )
        for kind in ("lookup", "hit"):
            lines.append(
                f'{_PREFIX}_serve_prefix_requests_total'
                f'{{kind="{_esc(kind)}"}} '
                f"{prefix.get(kind + 's', 0)}"
            )
        lines.append(f"# TYPE {_PREFIX}_serve_prefix_blocks counter")
        lines.append(
            f"# HELP {_PREFIX}_serve_prefix_blocks KV blocks through "
            f"the prefix cache by event (claimed = prefill skipped)"
        )
        for kind in ("claimed", "inserted", "evicted"):
            lines.append(
                f'{_PREFIX}_serve_prefix_blocks_total'
                f'{{kind="{_esc(kind)}"}} '
                f"{prefix.get('blocks_' + kind, 0)}"
            )
        for name, help_ in (
            ("hit_rate",
             "admissions claiming at least one resident block"),
            ("cached_blocks", "KV blocks resident in the prefix cache"),
        ):
            if name in prefix:
                lines.append(
                    f"# TYPE {_PREFIX}_serve_prefix_{name} gauge"
                )
                lines.append(
                    f"# HELP {_PREFIX}_serve_prefix_{name} {help_}"
                )
                lines.append(
                    f"{_PREFIX}_serve_prefix_{name} {prefix[name]}"
                )
    latency = serve.get("latency", {})
    for family, summary in sorted(latency.items()):
        metric = f"serve_{family}_latency_ms"
        lines.append(f"# TYPE {_PREFIX}_{metric} gauge")
        lines.append(
            f"# HELP {_PREFIX}_{metric} {family} latency percentiles "
            f"over the recent window"
        )
        for q in ("p50_ms", "p99_ms", "max_ms"):
            if q in summary:
                lines.append(
                    f'{_PREFIX}_{metric}{{quantile="{q[:-3]}"}} '
                    f"{summary[q]}"
                )
    # Distributed-tracing critical-path phases (tracing engines only):
    # the per-phase percentile family the TTFT decomposition reads.
    phases = serve.get("phases", {})
    if phases:
        metric = "serve_phase_latency_ms"
        lines.append(f"# TYPE {_PREFIX}_{metric} gauge")
        lines.append(
            f"# HELP {_PREFIX}_{metric} critical-path phase latency "
            f"percentiles (queue_wait/placement/prefill_compute/"
            f"handoff_transfer/decode_admission/first_token)"
        )
        for phase, summary in sorted(phases.items()):
            for q in ("p50_ms", "p95_ms"):
                if q in summary:
                    lines.append(
                        f'{_PREFIX}_{metric}{{phase="{_esc(phase)}",'
                        f'quantile="{q[:-3]}"}} {summary[q]}'
                    )
    return lines


class PromExporter:
    """Textfile writer + optional localhost /metrics server."""

    def __init__(self, textfile: Optional[str] = None,
                 port: Optional[int] = None):
        self.textfile = textfile
        self._text = "# EOF\n"
        self._server = None
        self._thread: Optional[threading.Thread] = None
        self.port: Optional[int] = None
        if port is not None:
            self._start_server(port)

    def _start_server(self, port: int) -> None:
        import http.server

        exporter = self

        class Handler(http.server.BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 - stdlib naming
                if self.path not in ("/metrics", "/"):
                    self.send_error(404)
                    return
                body = exporter._text.encode()
                self.send_response(200)
                self.send_header(
                    "Content-Type",
                    "application/openmetrics-text; version=1.0.0; "
                    "charset=utf-8",
                )
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *args):  # silence per-scrape stderr
                pass

        try:
            self._server = http.server.ThreadingHTTPServer(
                ("127.0.0.1", port), Handler
            )
        except OSError:
            self._server = None  # port taken: textfile still works
            return
        self.port = self._server.server_address[1]
        self._thread = threading.Thread(
            target=self._server.serve_forever, name="rlt-prom",
            daemon=True,
        )
        self._thread.start()

    def update(self, snapshot: Dict[str, Any],
               event_counts: Optional[Dict[str, int]] = None) -> None:
        self._text = render_openmetrics(snapshot, event_counts)
        if self.textfile:
            try:
                parent = os.path.dirname(self.textfile)
                if parent:
                    os.makedirs(parent, exist_ok=True)
                tmp = self.textfile + ".tmp"
                with open(tmp, "w") as f:
                    f.write(self._text)
                os.replace(tmp, self.textfile)
            except OSError:
                pass  # a full disk must not take the fit down

    def close(self) -> None:
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()
            self._server = None
        self._thread = None
