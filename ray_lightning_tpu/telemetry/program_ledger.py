"""Program ledger: the compiled-executable observatory.

Every subsystem in this tree pins "zero steady-state recompiles" via
``compile_event_count()`` deltas, but the counter only *counts* — when
a pin fires nobody learns which call site recompiled or why, and the
XLA compiler's own accounting (``cost_analysis()`` FLOPs and
bytes-accessed, ``memory_analysis()`` argument/output/temp bytes) is
thrown away.  This module closes both gaps with one wrapper:

:func:`ledgered_jit` replaces a ``jax.jit(fn, ...)`` call site.  The
returned :class:`LedgeredFunction` owns dispatch through the
ahead-of-time ``Lowered.compile()`` executable, so at first dispatch it
captures — without a second compile —

* the abstract argument **signature**: per-leaf shapes/dtypes, the
  pytree structure fingerprint, static values, and donation;
* the **compile wall time** (measured directly around ``lower()`` +
  ``compile()``);
* the lowered executable's ``cost_analysis()`` (FLOPs, bytes accessed)
  and ``memory_analysis()`` (argument/output/temp/generated-code
  bytes) — the inputs for roofline MFU and HBM sizing.

When a dispatch misses every compiled variant of its site, the new
signature is diffed against the last one and a schema-valid
``recompile`` record is emitted (``telemetry/schema.py:
validate_recompile_record``) that **names the offending argument and
what changed** — shape vs dtype vs structure vs donation — so every
zero-recompile pin in tests and benches prints an attribution when it
fires instead of a bare count.

Dispatch discipline (why this is safe on hot paths):

* **Fast path** is one attribute load and a direct ``Compiled`` call
  inside ``try/except`` — no per-call fingerprinting.  A signature
  mismatch surfaces as the executable's own ``TypeError``/
  ``ValueError``, which routes to the slow path.  Measured overhead vs
  a bare jit call is tens of nanoseconds (``bench.py`` publishes the
  A/B as ``programs.ledger_overhead_pct``).
* AOT compiles do NOT populate the normal jit call cache, so the
  wrapper never falls back to the plain jitted callable for concrete
  arguments — that would silently double every compile.  The one
  exception is **tracer** inputs (a ledgered program invoked inside an
  enclosing trace), where the plain jit inlines correctly.
* ``RLT_PROGRAM_LEDGER=0`` is the kill switch: :func:`ledgered_jit`
  degrades to a bare ``jax.jit`` (the A/B baseline).

The module imports jax lazily: schema gates and the flight recorder
read :func:`snapshot` from jax-free processes.
"""

from __future__ import annotations

import logging
import os
import threading
import time
from dataclasses import dataclass
from typing import (
    Any, Callable, Dict, List, NamedTuple, Optional, Sequence, Tuple,
)

__all__ = [
    "ArgSig",
    "LedgeredFunction",
    "ProgramLedger",
    "ProgramRecord",
    "Signature",
    "diff_signatures",
    "hbm_report",
    "hlo_text",
    "ledger",
    "ledgered_jit",
    "recompile_records",
    "roofline",
    "snapshot",
]

_LOG = logging.getLogger("ray_lightning_tpu.program_ledger")

#: Ring caps: an observatory must never become the leak it watches.
_MAX_RECORDS = 512
_MAX_RECOMPILES = 128

#: site -> the live LedgeredFunction most recently built for it (latest
#: wins; weak values so the registry never pins a retraced function — or
#: its compiled executables — alive).  Feeds :func:`hlo_text`.
import weakref  # noqa: E402 - grouped with its sole consumer

_SITE_FUNCTIONS: "weakref.WeakValueDictionary[str, Any]" = (
    weakref.WeakValueDictionary()
)


# ---------------------------------------------------------------------------
# Signatures — the per-dispatch abstract fingerprint
# ---------------------------------------------------------------------------

class ArgSig(NamedTuple):
    """One top-level argument's abstract shape: its pytree structure
    string plus per-leaf ``(path, shape, dtype)`` rows."""

    name: str
    treedef: str
    leaves: Tuple[Tuple[str, Tuple[int, ...], str], ...]


class Signature(NamedTuple):
    """The full call-site fingerprint a variant is keyed on."""

    args: Tuple[ArgSig, ...]
    statics: Tuple[Tuple[str, str], ...]   # (name, repr(value))
    donate: Tuple[int, ...]


def _leaf_sig(leaf: Any) -> Tuple[Tuple[int, ...], str]:
    shape = getattr(leaf, "shape", None)
    dtype = getattr(leaf, "dtype", None)
    if shape is not None and dtype is not None:
        return tuple(int(d) for d in shape), str(dtype)
    # Python scalars: weak-typed operands — the *type* is the dtype
    # identity (2 vs 3 share an executable; 2 vs 2.0 do not).
    return (), type(leaf).__name__


_DTYPE_SHORT = {
    "float32": "f32", "bfloat16": "bf16", "float16": "f16",
    "float64": "f64", "int32": "i32", "int64": "i64", "int8": "i8",
    "uint32": "u32", "uint8": "u8", "bool": "b1",
}


def _fmt_leaf(shape: Tuple[int, ...], dtype: str) -> str:
    d = _DTYPE_SHORT.get(dtype, dtype)
    return f"{d}[{','.join(str(s) for s in shape)}]"


def _fmt_sig(sig: Signature) -> str:
    """Compact human-readable signature for ledger rows."""
    parts = []
    for a in sig.args:
        if len(a.leaves) <= 3:
            body = ",".join(_fmt_leaf(s, d) for _, s, d in a.leaves)
        else:
            body = f"<{len(a.leaves)} leaves>"
        parts.append(f"{a.name}:{body}")
    for name, val in sig.statics:
        parts.append(f"{name}={val}")
    out = "|".join(parts)
    if sig.donate:
        out += f"|donate={tuple(sig.donate)}"
    return out


def _clip(s: str, n: int = 160) -> str:
    return s if len(s) <= n else s[: n - 3] + "..."


def diff_signatures(old: Signature, new: Signature) -> Dict[str, Any]:
    """Attribution for a signature change: which argument, what kind of
    delta (``shape`` / ``dtype`` / ``structure`` / ``donation`` /
    ``static``), and the before/after rendering.  Pure — the negative
    schema self-tests drive it without jax."""
    if tuple(old.donate) != tuple(new.donate):
        return {"kind": "donation", "argument": "donate_argnums",
                "old": str(tuple(old.donate)),
                "new": str(tuple(new.donate))}
    if old.statics != new.statics:
        o, n = dict(old.statics), dict(new.statics)
        for name in list(n) + [k for k in o if k not in n]:
            if o.get(name) != n.get(name):
                return {"kind": "static", "argument": name,
                        "old": str(o.get(name)), "new": str(n.get(name))}
    if [a.name for a in old.args] != [a.name for a in new.args]:
        return {"kind": "structure", "argument": "<arity>",
                "old": f"{len(old.args)} args: "
                       f"{[a.name for a in old.args]}",
                "new": f"{len(new.args)} args: "
                       f"{[a.name for a in new.args]}"}
    for oa, na in zip(old.args, new.args):
        if oa.treedef != na.treedef:
            return {"kind": "structure", "argument": na.name,
                    "old": _clip(oa.treedef), "new": _clip(na.treedef)}
        for ol, nl in zip(oa.leaves, na.leaves):
            arg = na.name + (nl[0] or "")
            if ol[1] != nl[1]:
                return {"kind": "shape", "argument": arg,
                        "old": _fmt_leaf(ol[1], ol[2]),
                        "new": _fmt_leaf(nl[1], nl[2])}
            if ol[2] != nl[2]:
                return {"kind": "dtype", "argument": arg,
                        "old": ol[2], "new": nl[2]}
    return {"kind": "structure", "argument": "<unattributed>",
            "old": "", "new": ""}


# ---------------------------------------------------------------------------
# Records
# ---------------------------------------------------------------------------

@dataclass
class ProgramRecord:
    """One compiled executable: identity, cost, and memory accounting."""

    site: str
    variant: int
    signature: str
    compile_s: float
    backend: str = ""
    donated: Tuple[int, ...] = ()
    ncalls: int = 0
    flops: Optional[float] = None
    bytes_accessed: Optional[float] = None
    argument_bytes: Optional[int] = None
    output_bytes: Optional[int] = None
    temp_bytes: Optional[int] = None
    alias_bytes: Optional[int] = None
    generated_code_bytes: Optional[int] = None

    def row(self) -> Dict[str, Any]:
        """Schema row (``validate_program_row``): required identity
        keys always present, accounting keys only when the backend
        produced them."""
        out: Dict[str, Any] = {
            "site": self.site,
            "variant": self.variant,
            "ncalls": int(self.ncalls),
            "compile_s": float(self.compile_s),
            "signature": self.signature,
        }
        if self.backend:
            out["backend"] = self.backend
        if self.donated:
            out["donated"] = str(tuple(self.donated))
        for key in ("flops", "bytes_accessed"):
            val = getattr(self, key)
            if val is not None:
                out[key] = float(val)
        for key in ("argument_bytes", "output_bytes", "temp_bytes",
                    "alias_bytes", "generated_code_bytes"):
            val = getattr(self, key)
            if val is not None:
                out[key] = int(val)
        return out


def _cost_dict(compiled: Any) -> Dict[str, float]:
    """``cost_analysis()`` normalised: this jax returns a single-element
    list of dicts; newer ones return the dict.  Absent/failed analysis
    degrades to empty — accounting is best-effort by contract."""
    try:
        cost = compiled.cost_analysis()
    except Exception:  # noqa: BLE001 - backend-dependent, never fatal
        return {}
    if isinstance(cost, (list, tuple)):
        cost = cost[0] if cost else {}
    return dict(cost) if cost else {}


# ---------------------------------------------------------------------------
# The process-wide ledger
# ---------------------------------------------------------------------------

class ProgramLedger:
    """Registry of every executable dispatched through a
    :class:`LedgeredFunction`, plus the recompile-forensics ring."""

    def __init__(self):
        self._lock = threading.Lock()
        self._records: List[ProgramRecord] = []   # guarded by self._lock
        self._recompiles: List[Dict[str, Any]] = []  # guarded by self._lock
        self._site_last: Dict[str, Signature] = {}   # guarded by self._lock
        self._dropped = 0                         # guarded by self._lock
        self._emitters: List[Callable[[Dict[str, Any]], None]] = []

    # -- recording (called from LedgeredFunction under its own lock) ---------
    def record_program(self, record: ProgramRecord,
                       sig: Signature) -> None:
        with self._lock:
            if len(self._records) < _MAX_RECORDS:
                self._records.append(record)
            else:
                self._dropped += 1
            self._site_last[record.site] = sig

    def last_signature(self, site: str) -> Optional[Signature]:
        with self._lock:
            return self._site_last.get(site)

    def record_recompile(self, site: str, attribution: Dict[str, Any],
                         variant: int) -> Dict[str, Any]:
        """Build, store, log, and fan out one recompile record."""
        event = {
            "type": "recompile",
            "site": site,
            "kind": attribution["kind"],
            "argument": attribution["argument"],
            "old": attribution.get("old", ""),
            "new": attribution.get("new", ""),
            "variant": int(variant),
            "ts": time.time(),
        }
        with self._lock:
            self._recompiles.append(event)
            if len(self._recompiles) > _MAX_RECOMPILES:
                del self._recompiles[0]
            emitters = list(self._emitters)
        # The attribution must be adjacent to any zero-recompile pin
        # that fires: warn unconditionally, not at debug level.
        _LOG.warning(
            "recompile at %s (variant %d): %s change on %r: %s -> %s",
            site, variant, event["kind"], event["argument"],
            event["old"], event["new"],
        )
        for emit in emitters:
            try:
                emit(dict(event))
            except Exception:  # noqa: BLE001 - observers never break dispatch
                _LOG.debug("recompile emitter failed", exc_info=True)
        return event

    def add_emitter(self, fn: Callable[[Dict[str, Any]], None]) -> None:
        """Fan recompile records out to a live channel (the monitor's
        event stream, a test capture list)."""
        with self._lock:
            self._emitters.append(fn)

    def remove_emitter(self, fn: Callable[[Dict[str, Any]], None]) -> None:
        with self._lock:
            try:
                self._emitters.remove(fn)
            except ValueError:
                pass

    # -- reading -------------------------------------------------------------
    def snapshot(self) -> Dict[str, Any]:
        """Picklable observatory state (schema:
        ``validate_program_snapshot``)."""
        with self._lock:
            rows = [r.row() for r in self._records]
            recompiles = [dict(e) for e in self._recompiles]
            dropped = self._dropped
        out: Dict[str, Any] = {
            "programs": rows,
            "recompiles": recompiles,
            "compile_time_total_s": round(
                sum(r["compile_s"] for r in rows), 6
            ),
        }
        if dropped:
            out["dropped"] = dropped
        return out

    def compile_time_total_s(self) -> float:
        with self._lock:
            return sum(r.compile_s for r in self._records)

    def sites(self) -> List[str]:
        with self._lock:
            seen: Dict[str, None] = {}
            for r in self._records:
                seen.setdefault(r.site, None)
            return list(seen)

    def site_flops(self, site: str) -> Optional[float]:
        """FLOPs of the most-called variant at ``site`` (prefix match
        when no exact site exists) — the measured side of the MFU
        drift guard."""
        with self._lock:
            exact = [r for r in self._records if r.site == site]
            rows = exact or [
                r for r in self._records if r.site.startswith(site)
            ]
            rows = [r for r in rows if r.flops is not None]
            if not rows:
                return None
            return float(max(rows, key=lambda r: r.ncalls).flops)

    def site_flops_latest(self, site: str) -> Optional[float]:
        """FLOPs of the most recently compiled variant at ``site``.
        The train loop reads this at step-0 compile time, when the
        latest record IS the program that just compiled; the
        most-called view above would leak a previous fit's program in
        a long-lived process (sequential fits in one pytest run
        register many train/step variants)."""
        with self._lock:
            for r in reversed(self._records):
                if r.site == site and r.flops is not None:
                    return float(r.flops)
        return None

    def reset(self) -> None:
        """Test/bench isolation: drop all records and rings.  Live
        LedgeredFunctions keep their compiled variants (no recompile
        storm) — only the observatory state clears."""
        with self._lock:
            self._records.clear()
            self._recompiles.clear()
            self._site_last.clear()
            self._dropped = 0


_GLOBAL = ProgramLedger()


def ledger() -> ProgramLedger:
    """The process-wide ledger singleton."""
    return _GLOBAL


def snapshot() -> Dict[str, Any]:
    return _GLOBAL.snapshot()


def recompile_records() -> List[Dict[str, Any]]:
    return list(_GLOBAL.snapshot()["recompiles"])


# ---------------------------------------------------------------------------
# The dispatch wrapper
# ---------------------------------------------------------------------------

class _Variant:
    __slots__ = ("sig", "compiled", "statics", "record")

    def __init__(self, sig: Signature, compiled: Any,
                 statics: Tuple[Any, ...], record: ProgramRecord):
        self.sig = sig
        self.compiled = compiled
        self.statics = statics
        self.record = record


class LedgeredFunction:
    """A jit call site that owns dispatch through its AOT-compiled
    executables and reports every compile to the ledger.

    Dispatch: the most-recently-used ``Compiled`` is tried directly
    (its own argument check is the fast-path guard); a mismatch falls
    to the slow path, which fingerprints, reuses a matching variant, or
    lowers+compiles a new one and emits the recompile attribution.
    """

    def __init__(self, fn: Callable, site: str,
                 registry: Optional[ProgramLedger] = None,
                 arg_names: Optional[Sequence[str]] = None,
                 **jit_kwargs: Any):
        import jax

        self._fn = fn
        self.site = site
        self._ledger = registry if registry is not None else _GLOBAL
        donate = jit_kwargs.get("donate_argnums", ())
        if isinstance(donate, int):
            donate = (donate,)
        self._donate: Tuple[int, ...] = tuple(donate)
        static = jit_kwargs.get("static_argnums", ())
        if isinstance(static, int):
            static = (static,)
        self._static: Tuple[int, ...] = tuple(static)
        self._jit = jax.jit(fn, **jit_kwargs)
        if arg_names is None:
            arg_names = _infer_arg_names(fn)
        self._arg_names: Tuple[str, ...] = tuple(arg_names or ())
        self._variants: List[_Variant] = []   # guarded by self._lock
        self._mru: Optional[_Variant] = None
        self._lock = threading.Lock()
        _SITE_FUNCTIONS[site] = self

    # -- introspection (tests, tooling) --------------------------------------
    @property
    def variants(self) -> int:
        with self._lock:
            return len(self._variants)

    def lower(self, *args: Any, **kwargs: Any):
        """Pass through to the underlying jit's ``lower`` (warm-compile
        paths use it)."""
        return self._jit.lower(*args, **kwargs)

    # -- dispatch ------------------------------------------------------------
    def __call__(self, *args: Any, **kwargs: Any):
        mru = self._mru
        if mru is not None and (
            not self._static or self._statics_of(args) == mru.statics
        ):
            try:
                out = mru.compiled(*self._dynamic(args), **kwargs)
            except (TypeError, ValueError):
                # Signature/sharding miss (or a tracer input): the slow
                # path re-resolves and re-raises genuine errors.
                pass
            else:
                mru.record.ncalls += 1
                return out
        return self._dispatch_slow(args, kwargs)

    def _statics_of(self, args: Tuple[Any, ...]) -> Tuple[Any, ...]:
        return tuple(args[i] for i in self._static if i < len(args))

    def _dynamic(self, args: Tuple[Any, ...]) -> Tuple[Any, ...]:
        if not self._static:
            return args
        return tuple(
            a for i, a in enumerate(args) if i not in self._static
        )

    def _dispatch_slow(self, args: Tuple[Any, ...],
                       kwargs: Dict[str, Any]):
        import jax

        leaves = jax.tree_util.tree_leaves((args, kwargs))
        if any(isinstance(leaf, jax.core.Tracer) for leaf in leaves):
            # Invoked inside an enclosing trace: a Compiled cannot take
            # tracers; the plain jit inlines correctly and adds no
            # executable of its own.
            return self._jit(*args, **kwargs)
        sig = self._signature(args, kwargs)
        with self._lock:
            variant = next(
                (v for v in self._variants if v.sig == sig), None
            )
            if variant is None:
                variant = self._compile_locked(sig, args, kwargs)
            self._mru = variant
        out = variant.compiled(*self._dynamic(args), **kwargs)
        variant.record.ncalls += 1
        return out

    def _signature(self, args: Tuple[Any, ...],
                   kwargs: Dict[str, Any]) -> Signature:
        import jax

        arg_sigs: List[ArgSig] = []
        statics: List[Tuple[str, str]] = []
        for i, a in enumerate(args):
            name = (self._arg_names[i] if i < len(self._arg_names)
                    else f"arg{i}")
            if i in self._static:
                statics.append((name, repr(a)))
                continue
            leaves, treedef = jax.tree_util.tree_flatten_with_path(a)
            arg_sigs.append(ArgSig(name, str(treedef), tuple(
                (jax.tree_util.keystr(path),) + _leaf_sig(leaf)
                for path, leaf in leaves
            )))
        for key in sorted(kwargs):
            leaves, treedef = jax.tree_util.tree_flatten_with_path(
                kwargs[key]
            )
            arg_sigs.append(ArgSig(key, str(treedef), tuple(
                (jax.tree_util.keystr(path),) + _leaf_sig(leaf)
                for path, leaf in leaves
            )))
        return Signature(tuple(arg_sigs), tuple(statics), self._donate)

    # rlt: holds self._lock
    def _compile_locked(self, sig: Signature, args: Tuple[Any, ...],
                        kwargs: Dict[str, Any]) -> _Variant:
        import jax

        baseline = (self._mru.sig if self._mru is not None
                    else self._ledger.last_signature(self.site))
        t0 = time.perf_counter()
        compiled = self._jit.lower(*args, **kwargs).compile()
        compile_s = time.perf_counter() - t0
        cost = _cost_dict(compiled)
        record = ProgramRecord(
            site=self.site,
            variant=len(self._variants),
            signature=_fmt_sig(sig),
            compile_s=compile_s,
            backend=jax.default_backend(),
            donated=self._donate,
            flops=cost.get("flops"),
            bytes_accessed=cost.get("bytes accessed"),
        )
        try:
            mem = compiled.memory_analysis()
        except Exception:  # noqa: BLE001 - backend-dependent
            mem = None
        if mem is not None:
            record.argument_bytes = getattr(
                mem, "argument_size_in_bytes", None)
            record.output_bytes = getattr(
                mem, "output_size_in_bytes", None)
            record.temp_bytes = getattr(mem, "temp_size_in_bytes", None)
            record.alias_bytes = getattr(
                mem, "alias_size_in_bytes", None)
            record.generated_code_bytes = getattr(
                mem, "generated_code_size_in_bytes", None)
        if baseline is not None and baseline != sig:
            self._ledger.record_recompile(
                self.site, diff_signatures(baseline, sig),
                variant=len(self._variants),
            )
        variant = _Variant(sig, compiled, self._statics_of(args), record)
        self._variants.append(variant)
        self._ledger.record_program(record, sig)
        return variant


def _infer_arg_names(fn: Callable) -> Tuple[str, ...]:
    import inspect

    try:
        params = inspect.signature(fn).parameters
    except (TypeError, ValueError):
        return ()
    names: List[str] = []
    for p in params.values():
        if p.kind in (p.POSITIONAL_ONLY, p.POSITIONAL_OR_KEYWORD):
            names.append(p.name)
        else:
            break
    return tuple(names)


def _enabled() -> bool:
    return os.environ.get("RLT_PROGRAM_LEDGER", "1") not in ("0", "off")


def ledgered_jit(fn: Callable, *, site: str,
                 arg_names: Optional[Sequence[str]] = None,
                 **jit_kwargs: Any) -> Callable:
    """Drop-in for ``jax.jit(fn, **jit_kwargs)`` that registers the
    call site with the process ledger.  ``site`` names the program in
    every surface (snapshot rows, recompile attributions,
    ``rlt_program_*`` metrics, the rlt_top pane).

    ``RLT_PROGRAM_LEDGER=0`` disables the observatory entirely and
    returns a bare ``jax.jit`` — the overhead-A/B baseline."""
    if not _enabled():
        import jax

        return jax.jit(fn, **jit_kwargs)
    return LedgeredFunction(fn, site, arg_names=arg_names, **jit_kwargs)


def hlo_text(site: str) -> Optional[str]:
    """Optimized HLO of the named site's most-recently-used compiled
    variant, or ``None`` when unavailable (ledger disabled, site never
    dispatched, backend without ``as_text``).  Best-effort by design —
    callers gate structural assertions (the comm/compute-overlap bench
    proof) on a non-``None`` return, they do not branch behavior."""
    fn = _SITE_FUNCTIONS.get(site)
    if fn is None:
        return None
    with fn._lock:
        variant = fn._mru or (fn._variants[-1] if fn._variants else None)
    if variant is None:
        return None
    try:
        text = variant.compiled.as_text()
    except Exception:  # noqa: BLE001 - backend-dependent surface
        return None
    return text if isinstance(text, str) else None


# ---------------------------------------------------------------------------
# Derived reports: HBM budget + roofline
# ---------------------------------------------------------------------------

def _best_rows(snap: Dict[str, Any]) -> Dict[str, Dict[str, Any]]:
    """Most-called variant per site."""
    best: Dict[str, Dict[str, Any]] = {}
    for row in snap.get("programs", ()):
        cur = best.get(row["site"])
        if cur is None or row["ncalls"] > cur["ncalls"]:
            best[row["site"]] = row
    return best


def hbm_report(snap: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
    """Per-site HBM accounting from ``memory_analysis()``: argument
    bytes (resident operands — params/opt-state for train, the KV pool
    for decode), output bytes, and temp bytes (XLA scratch).  Sites
    report their most-called variant; the peaks are the sizing oracle
    (programs run one at a time per device, so temp is a max, not a
    sum; arguments alias across programs, so that is a max too)."""
    snap = snap if snap is not None else _GLOBAL.snapshot()
    sites: Dict[str, Dict[str, int]] = {}
    for site, row in _best_rows(snap).items():
        entry = {
            key: int(row[key])
            for key in ("argument_bytes", "output_bytes", "temp_bytes")
            if row.get(key) is not None
        }
        if entry:
            sites[site] = entry
    out: Dict[str, Any] = {"sites": sites}
    if sites:
        out["peak_argument_bytes"] = max(
            e.get("argument_bytes", 0) for e in sites.values()
        )
        out["peak_temp_bytes"] = max(
            e.get("temp_bytes", 0) for e in sites.values()
        )
    gen = [
        row.get("generated_code_bytes")
        for row in snap.get("programs", ())
        if row.get("generated_code_bytes") is not None
    ]
    if gen:
        out["generated_code_bytes"] = int(sum(gen))
    return out


def roofline(site: str, peak_flops: Optional[float] = None,
             peak_bytes_per_s: Optional[float] = None,
             snap: Optional[Dict[str, Any]] = None
             ) -> Optional[Dict[str, Any]]:
    """Roofline placement of one program: arithmetic intensity from the
    measured FLOPs / bytes-accessed, and — when the chip peaks are
    supplied — the ridge point and whether the program sits
    compute-bound or memory-bound."""
    snap = snap if snap is not None else _GLOBAL.snapshot()
    rows = [
        r for r in _best_rows(snap).values()
        if (r["site"] == site or r["site"].startswith(site))
        and r.get("flops") is not None
    ]
    if not rows:
        return None
    row = max(rows, key=lambda r: r["ncalls"])
    out: Dict[str, Any] = {"site": row["site"],
                           "flops": float(row["flops"])}
    bytes_accessed = row.get("bytes_accessed")
    if bytes_accessed:
        out["bytes_accessed"] = float(bytes_accessed)
        out["arithmetic_intensity"] = float(row["flops"]) / float(
            bytes_accessed
        )
    if peak_flops and peak_bytes_per_s and bytes_accessed:
        ridge = peak_flops / peak_bytes_per_s
        out["ridge_intensity"] = ridge
        out["bound"] = (
            "compute" if out["arithmetic_intensity"] >= ridge
            else "memory"
        )
    return out
