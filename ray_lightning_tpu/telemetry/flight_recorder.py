"""Crash flight recorder: worker-side forensics that outlive the worker.

A worker that raises mid-fit loses its spans, step stats and logs —
the driver gets a traceback and nothing else.  The
:class:`FlightRecorder` persists a **flight bundle** at crash time
under the telemetry dir (``<telemetry_dir>/flight/``):

* ``bundle-rank<k>.json`` — schema-pinned post-mortem
  (``telemetry/schema.py:validate_flight_bundle``): the exception +
  traceback, step counters and loop phase, last-N spans from the
  existing ring, the step-stats snapshot, the rank-tagged log ring
  (``telemetry/logs.py``), all-thread py stacks, device memory, and an
  env/device fingerprint;
* ``fatal-rank<k>.log`` — ``faulthandler`` output armed for the whole
  fit, so a segfault/fatal signal (which Python except blocks never
  see) still leaves native-level stacks behind.

The recorder registers itself in a module-global slot while a fit is
live; the stage wrappers (``_execute_remote``, ``LocalStrategy.run``)
call :func:`record_active_crash` from their except path — no
re-indentation of the fit loop, and a crash anywhere between setup and
result packaging is covered.  When a queue is attached, the bundle
path also travels to the driver as a ``{"type": "event",
"kind": "crash"}`` item so the raised error can *name* the bundle.
"""

from __future__ import annotations

import json
import os
import platform
import sys
import threading
import time
import traceback
from typing import Any, Dict, List, Optional

from .schema import FLIGHT_BUNDLE_SCHEMA_ID

__all__ = [
    "FlightRecorder",
    "record_active_crash",
    "format_all_stacks",
]

_SPAN_TAIL = 256          # last-N spans folded into the bundle
_STACK_CHAR_CAP = 65536   # bound the stacks blob a bundle may carry

_active_lock = threading.Lock()
_active: List["FlightRecorder"] = []


def format_all_stacks() -> str:
    """Formatted stacks of every live thread (``sys._current_frames``)."""
    names = {t.ident: t.name for t in threading.enumerate()}
    chunks = []
    for tid, frame in sorted(sys._current_frames().items()):
        name = names.get(tid, "?")
        chunks.append(
            f"--- thread {tid} ({name}) ---\n"
            + "".join(traceback.format_stack(frame))
        )
    text = "\n".join(chunks)
    if len(text) > _STACK_CHAR_CAP:
        text = text[:_STACK_CHAR_CAP] + "\n…[truncated]"
    return text


def _fingerprint() -> Dict[str, Any]:
    """Env/device identity: enough to answer "what exactly was this
    process" without the process."""
    fp: Dict[str, Any] = {
        "python": sys.version.split()[0],
        "platform": platform.platform(),
        "pid": os.getpid(),
        "argv": " ".join(sys.argv[:4]),
    }
    jax = sys.modules.get("jax")
    if jax is not None:
        fp["jax"] = getattr(jax, "__version__", "?")
        try:
            fp["backend"] = jax.default_backend()
            fp["device_kind"] = jax.local_devices()[0].device_kind
        except Exception:  # noqa: BLE001 - backend may be mid-teardown
            pass
    env = {
        k: v for k, v in sorted(os.environ.items())
        if k.startswith("RLT_") or k in (
            "JAX_PLATFORMS", "XLA_FLAGS", "TPU_VISIBLE_CHIPS",
        )
    }
    if env:
        fp["env"] = env
    return fp


class FlightRecorder:
    """Per-rank crash forensics for one fit (see module docstring)."""

    def __init__(self, rank: int, out_dir: str, ctx: Any,
                 telemetry: Any = None, queue: Any = None,
                 log_handler: Any = None,
                 heartbeat: Any = None,
                 bundles_enabled: bool = True):
        self.rank = rank
        self.out_dir = out_dir
        self._ctx = ctx
        self._telemetry = telemetry
        self._queue = queue
        self._log_handler = log_handler
        self._heartbeat = heartbeat
        self._fatal_file = None
        # RLT_FLIGHT_RECORDER=off gates the bundle/faulthandler OUTPUT
        # only — the recorder still arms, because its crash hook is
        # also what stops the heartbeat thread and removes the log
        # handler when the fit raises (no bundle must never mean a
        # leaked publisher).
        self.bundles_enabled = bundles_enabled
        self.bundle_path: Optional[str] = None

    # -- lifecycle ----------------------------------------------------------
    @classmethod
    def maybe_install(cls, telemetry: Any, ctx: Any, queue: Any,
                      log_handler: Any = None,
                      heartbeat: Any = None) -> Optional["FlightRecorder"]:
        """Arm a recorder for this fit, or ``None`` when telemetry is
        off.  ``RLT_FLIGHT_RECORDER=off`` keeps the recorder (it owns
        crash-path plane cleanup) but disables bundle/fatal-log output."""
        if telemetry is None or not getattr(telemetry, "enabled", False):
            return None
        tel_dir = getattr(ctx, "telemetry_dir", None)
        if tel_dir is None:
            return None
        bundles_enabled = os.environ.get(
            "RLT_FLIGHT_RECORDER", ""
        ).lower() not in ("0", "off", "false")
        rec = cls(telemetry.global_rank, os.path.join(tel_dir, "flight"),
                  ctx, telemetry=telemetry, queue=queue,
                  log_handler=log_handler, heartbeat=heartbeat,
                  bundles_enabled=bundles_enabled)
        rec.install()
        return rec

    def install(self) -> None:
        """Arm faulthandler into the fatal log + register as the live
        recorder of this process (one fit per worker process)."""
        if self.bundles_enabled:
            try:
                import faulthandler

                os.makedirs(self.out_dir, exist_ok=True)
                self._fatal_file = open(
                    os.path.join(self.out_dir,
                                 f"fatal-rank{self.rank}.log"),
                    "w",
                )
                faulthandler.enable(file=self._fatal_file)
            except (OSError, RuntimeError):
                self._fatal_file = None
        with _active_lock:
            _active.append(self)

    def uninstall(self) -> None:
        """Disarm on the success path (and after a recorded crash)."""
        with _active_lock:
            if self in _active:
                _active.remove(self)
        if self._fatal_file is not None:
            try:
                import faulthandler

                faulthandler.disable()
                self._fatal_file.close()
                # An empty fatal log is noise, not forensics.
                path = self._fatal_file.name
                if os.path.exists(path) and os.path.getsize(path) == 0:
                    os.unlink(path)
            except (OSError, RuntimeError):
                pass
            self._fatal_file = None

    # -- the crash path -----------------------------------------------------
    def compose_bundle(self, exc: BaseException) -> Dict[str, Any]:
        ctx, tel = self._ctx, self._telemetry
        doc: Dict[str, Any] = {
            "schema": FLIGHT_BUNDLE_SCHEMA_ID,
            "rank": self.rank,
            "ts": time.time(),
            "error": repr(exc),
            "traceback": "".join(
                traceback.format_exception(type(exc), exc, exc.__traceback__)
            ),
            "global_step": int(getattr(ctx, "global_step", 0)),
            "micro_step": int(getattr(ctx, "micro_step", 0)),
            "epoch": int(getattr(ctx, "current_epoch", 0)),
            "phase": str(getattr(ctx, "phase", "init")),
            "fingerprint": _fingerprint(),
            "stacks": format_all_stacks(),
        }
        metrics = getattr(ctx, "callback_metrics", None)
        if metrics:
            # record_crash flushed the pending async log fetch before
            # composing, so this snapshot carries the latest scheduled
            # boundary — not one-to-two log intervals behind it.
            snap = {}
            for k, v in metrics.items():
                try:  # numpy/jax scalars coerce; non-numerics are skipped
                    snap[k] = float(v)
                except (TypeError, ValueError):
                    pass
            if snap:
                doc["callback_metrics"] = snap
        if tel is not None:
            tracer = getattr(tel, "tracer", None)
            if tracer is not None and tracer.enabled:
                doc["spans"] = [
                    tracer._span_dict(s) for s in tracer.events()[-_SPAN_TAIL:]
                ]
            stats = getattr(tel, "step_stats", None)
            if stats is not None:
                doc["step_stats"] = stats.summary()
            counters = dict(getattr(tel, "counters", {}) or {})
            if counters:
                doc["counters"] = counters
        if self._log_handler is not None:
            doc["logs"] = self._log_handler.records()
        # Program ledger: the compiled-executable inventory plus the
        # recompile-forensics ring — a crash that followed a surprise
        # recompile names the offending argument right in the bundle.
        from .program_ledger import snapshot as _ledger_snapshot

        programs = _ledger_snapshot()
        if programs.get("programs") or programs.get("recompiles"):
            doc["programs"] = programs
        from .heartbeat import device_memory_stats

        mem = device_memory_stats()
        if mem:
            doc["device_memory"] = mem
        return doc

    def record_crash(self, exc: BaseException) -> Optional[str]:
        """Persist the bundle, announce it on the queue, disarm.
        Returns the bundle path (``None`` if even that failed — crash
        handling must never mask the original exception)."""
        # Land any in-flight async log fetch first: the bundle's
        # callback_metrics snapshot must carry the latest scheduled
        # boundary, like the synchronous log path always did.
        flush = getattr(self._ctx, "pending_log_flush", None)
        if flush is not None:
            try:
                flush()
            except Exception:  # noqa: BLE001 - forensics are best-effort
                pass
        # Stop the publisher FIRST: a final "done" beat would make the
        # monitor retire a rank that actually died.
        if self._heartbeat is not None:
            try:
                self._heartbeat.stop(final=False)
            except Exception:  # noqa: BLE001
                pass
        if not self.bundles_enabled:
            # Output disabled: still tear the plane down cleanly.
            if self._log_handler is not None:
                try:
                    self._log_handler.uninstall()
                except Exception:  # noqa: BLE001
                    pass
            self.uninstall()
            return None
        try:
            doc = self.compose_bundle(exc)
            os.makedirs(self.out_dir, exist_ok=True)
            path = os.path.join(
                self.out_dir, f"bundle-rank{self.rank}.json"
            )
            tmp = path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(doc, f, indent=2, sort_keys=True, default=str)
            os.replace(tmp, path)
            self.bundle_path = path
        except Exception:  # noqa: BLE001 - forensics are best-effort
            self.bundle_path = None
        if self._queue is not None and self.bundle_path is not None:
            try:
                self._queue.put({
                    "type": "event",
                    "kind": "crash",
                    "rank": self.rank,
                    "ts": time.time(),
                    "error": repr(exc),
                    "bundle": self.bundle_path,
                })
            except Exception:  # noqa: BLE001 - queue may already be down
                pass
        if self._log_handler is not None:
            try:
                self._log_handler.uninstall()
            except Exception:  # noqa: BLE001
                pass
        self.uninstall()
        return self.bundle_path


def record_active_crash(exc: BaseException) -> Optional[str]:
    """Crash hook for the stage wrappers: route ``exc`` to whatever
    recorder is live in this process.  No-op (returns ``None``) when
    telemetry is off or no fit is in flight."""
    with _active_lock:
        rec = _active[-1] if _active else None
    if rec is None:
        return None
    return rec.record_crash(exc)
