"""The native experiment runner: ``tune_run`` + ``ExperimentAnalysis``.

≙ the ``tune.run(train_fn, config=..., scheduler=..., num_samples=...)``
surface the reference's examples drive (``examples/ray_ddp_example.py:
105-113``, ``examples/ray_ddp_tune.py``).  Nested distribution works the
same way (SURVEY §3.3): each trial's trainable constructs a Trainer with a
(possibly multi-worker) strategy; metric reports flow worker → queue →
driver thunk → trial session → scheduler.

Trials execute sequentially in the driver process — on a TPU pod the
accelerator is a single shared resource, so trial-parallelism is
cross-slice (multiple drivers), not in-process.
"""

from __future__ import annotations

import os
import time
import traceback
from typing import Any, Callable, Dict, List, Optional

from .schedulers import FIFOScheduler, PopulationBasedTraining
from .search import generate_trials
from .session import (
    TrialStopRequested,
    init_trial_session,
    shutdown_trial_session,
)

__all__ = ["Trial", "ExperimentAnalysis", "tune_run"]


class Trial:
    def __init__(self, trial_id: str, config: Dict[str, Any]):
        self.trial_id = trial_id
        self.config = config
        self.reports: List[Dict[str, Any]] = []
        self.status = "PENDING"  # RUNNING | TERMINATED | STOPPED | ERROR
        self.error: Optional[str] = None
        self.duration_s: float = 0.0

    @property
    def last_result(self) -> Dict[str, Any]:
        return self.reports[-1] if self.reports else {}

    @property
    def training_iteration(self) -> int:
        return self.last_result.get("training_iteration", 0)


class ExperimentAnalysis:
    """≙ the ``tune.run`` return object the examples read best configs from."""

    def __init__(self, trials: List[Trial], metric: str, mode: str):
        self.trials = trials
        self.metric = metric
        self.mode = mode

    def _scored(self) -> List[Trial]:
        return [
            t for t in self.trials
            if t.status in ("TERMINATED", "STOPPED")
            and self.metric in t.last_result
        ]

    @property
    def best_trial(self) -> Trial:
        scored = self._scored()
        if not scored:
            raise ValueError(f"No completed trial reported {self.metric!r}")
        key = lambda t: t.last_result[self.metric]  # noqa: E731
        return (
            min(scored, key=key) if self.mode == "min" else max(scored, key=key)
        )

    @property
    def best_config(self) -> Dict[str, Any]:
        return self.best_trial.config

    @property
    def best_result(self) -> Dict[str, Any]:
        return self.best_trial.last_result

    def dataframe(self):
        import pandas as pd

        rows = []
        for t in self.trials:
            row = {"trial_id": t.trial_id, "status": t.status,
                   "training_iteration": t.training_iteration,
                   "duration_s": t.duration_s}
            row.update({f"config/{k}": v for k, v in t.config.items()})
            row.update(t.last_result)
            rows.append(row)
        return pd.DataFrame(rows)


def tune_run(
    trainable: Callable[[Dict[str, Any]], Any],
    config: Dict[str, Any],
    num_samples: int = 1,
    scheduler: Optional[FIFOScheduler] = None,
    metric: str = "loss",
    mode: str = "min",
    local_dir: str = "rlt_tune",
    seed: int = 0,
    raise_on_trial_error: bool = False,
    verbose: bool = True,
) -> ExperimentAnalysis:
    """Run an experiment: sample configs, execute trials, schedule stops.

    ``trainable(config)`` runs in the driver; inside it, the trial session
    is active, so TuneReportCallback thunks arriving through the
    distributed queue report into this trial (≙ SURVEY §3.3's
    "report runs on the driver" indirection).
    """
    scheduler = scheduler or FIFOScheduler()
    configs = generate_trials(config, num_samples=num_samples, seed=seed)
    os.makedirs(local_dir, exist_ok=True)
    trials: List[Trial] = []
    for i, cfg in enumerate(configs):
        if isinstance(scheduler, PopulationBasedTraining) and i > 0:
            cfg = scheduler.next_config(cfg)
        trial = Trial(f"trial_{i:04d}", cfg)
        trials.append(trial)
        if isinstance(scheduler, PopulationBasedTraining):
            scheduler.register_trial(trial.trial_id, cfg)

        def on_report(record: Dict[str, Any], _trial=trial) -> str:
            _trial.reports.append(record)
            return scheduler.on_result(_trial.trial_id, record)

        session = init_trial_session(
            trial.trial_id, local_dir, on_report=on_report
        )
        trial.status = "RUNNING"
        t0 = time.perf_counter()
        try:
            trainable(dict(cfg))
            trial.status = "TERMINATED"
        except TrialStopRequested:
            trial.status = "STOPPED"
        except Exception:  # noqa: BLE001 - record, optionally re-raise
            trial.status = "ERROR"
            trial.error = traceback.format_exc()
            if raise_on_trial_error:
                shutdown_trial_session()
                raise
        finally:
            trial.duration_s = time.perf_counter() - t0
            shutdown_trial_session()
        scheduler.on_trial_complete(trial.trial_id, trial.last_result)
        if verbose:
            last = trial.last_result.get(metric)
            print(
                f"[tune] {trial.trial_id} {trial.status:10s} "
                f"iters={trial.training_iteration:3d} {metric}="
                f"{last if last is not None else 'n/a'} config={cfg}",
                flush=True,
            )
    return ExperimentAnalysis(trials, metric, mode)
