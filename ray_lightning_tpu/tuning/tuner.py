"""The native experiment runner: ``tune_run`` + ``ExperimentAnalysis``.

≙ the ``tune.run(train_fn, config=..., scheduler=..., num_samples=...)``
surface the reference's examples drive (``examples/ray_ddp_example.py:
105-113``, ``examples/ray_ddp_tune.py``).  Nested distribution works the
same way (SURVEY §3.3): each trial's trainable constructs a Trainer with a
(possibly multi-worker) strategy; metric reports flow worker → queue →
driver thunk → trial session → scheduler.

Trials execute sequentially by default — on a TPU pod the accelerator is
a single shared resource, so a trial usually needs the whole slice.
``tune_run(max_concurrent_trials=N)`` opts into N concurrent trial
drivers (one thread each, thread-local trial sessions): the mode for
N independent slices/hosts (each trial's strategy claiming its own
workers via its backend) or for N small ``LocalStrategy`` trials
sharing one host.  ``tune.get_tune_resources`` remains the placement
contract for REAL Ray Tune (PlacementGroupFactory when Ray is
installed); the native runner's resource model is just
``max_concurrent_trials``.
"""

from __future__ import annotations

import os
import time
import traceback
from typing import Any, Callable, Dict, List, Optional

from .schedulers import FIFOScheduler, PopulationBasedTraining
from .search import generate_trials
from .session import (
    TrialStopRequested,
    init_trial_session,
    shutdown_trial_session,
)

__all__ = ["Trial", "ExperimentAnalysis", "tune_run"]


class Trial:
    def __init__(self, trial_id: str, config: Dict[str, Any]):
        self.trial_id = trial_id
        self.config = config
        self.reports: List[Dict[str, Any]] = []
        self.status = "PENDING"  # RUNNING | TERMINATED | STOPPED | ERROR
        self.error: Optional[str] = None
        self.duration_s: float = 0.0

    @property
    def last_result(self) -> Dict[str, Any]:
        return self.reports[-1] if self.reports else {}

    @property
    def training_iteration(self) -> int:
        return self.last_result.get("training_iteration", 0)


class ExperimentAnalysis:
    """≙ the ``tune.run`` return object the examples read best configs from."""

    def __init__(self, trials: List[Trial], metric: str, mode: str):
        self.trials = trials
        self.metric = metric
        self.mode = mode

    def _scored(self) -> List[Trial]:
        return [
            t for t in self.trials
            if t.status in ("TERMINATED", "STOPPED")
            and self.metric in t.last_result
        ]

    @property
    def best_trial(self) -> Trial:
        scored = self._scored()
        if not scored:
            raise ValueError(f"No completed trial reported {self.metric!r}")
        key = lambda t: t.last_result[self.metric]  # noqa: E731
        return (
            min(scored, key=key) if self.mode == "min" else max(scored, key=key)
        )

    @property
    def best_config(self) -> Dict[str, Any]:
        return self.best_trial.config

    @property
    def best_result(self) -> Dict[str, Any]:
        return self.best_trial.last_result

    def dataframe(self):
        import pandas as pd

        rows = []
        for t in self.trials:
            row = {"trial_id": t.trial_id, "status": t.status,
                   "training_iteration": t.training_iteration,
                   "duration_s": t.duration_s}
            row.update({f"config/{k}": v for k, v in t.config.items()})
            row.update(t.last_result)
            rows.append(row)
        return pd.DataFrame(rows)


def _resolve_ckpt_file(path: Optional[str]) -> Optional[str]:
    """last_checkpoint may be a DIRECTORY (trainable used the bare
    ``checkpoint_dir`` API rather than the checkpoint callback).
    Resolve to something the trainable can consume: a lone file, or
    the newest conventionally-named stream file (``checkpoint*`` /
    ``ckpt*`` — what the framework's callbacks write and
    ``Trainer(resume_from_checkpoint=...)`` reads).  A multi-file
    custom layout is returned as the directory itself — a trainable
    that wrote its own format knows its own layout, and guessing a
    member file would feed garbage to ``resume_from_checkpoint``."""
    if path is None or os.path.isfile(path):
        return path
    if os.path.isdir(path):
        entries = os.listdir(path)
        files = [
            os.path.join(path, f) for f in entries
            if os.path.isfile(os.path.join(path, f))
        ]
        if len(files) == 1 and len(entries) == 1:
            return files[0]
        conventional = [
            f for f in files
            if os.path.basename(f).startswith(("checkpoint", "ckpt"))
        ]
        if conventional:
            return max(conventional, key=os.path.getmtime)
        if entries:
            # Custom layout (multi-file, or a directory tree like an
            # Orbax save): hand over the dir — the trainable that
            # wrote it knows how to read it.
            return path
    return None


def tune_run(
    trainable: Callable[[Dict[str, Any]], Any],
    config: Dict[str, Any],
    num_samples: int = 1,
    scheduler: Optional[FIFOScheduler] = None,
    metric: str = "loss",
    mode: str = "min",
    local_dir: str = "rlt_tune",
    seed: int = 0,
    raise_on_trial_error: bool = False,
    verbose: bool = True,
    max_concurrent_trials: int = 1,
    fleet_devices: Optional[int] = None,
    devices_per_trial: Optional[int] = None,
    min_devices_per_trial: Optional[int] = None,
) -> ExperimentAnalysis:
    """Run an experiment: sample configs, execute trials, schedule stops.

    ``trainable(config)`` runs in the driver; inside it, the trial session
    is active, so TuneReportCallback thunks arriving through the
    distributed queue report into this trial (≙ SURVEY §3.3's
    "report runs on the driver" indirection).

    **Concurrency** (≙ reference trials under placement groups,
    ``tune.py:32-56``): ``max_concurrent_trials=N`` runs up to N trial
    DRIVERS concurrently, each in its own thread with its own
    thread-local trial session.  Each driver's trainable builds its own
    Trainer/strategy whose workers claim their own accelerator resources
    — e.g. one RemoteBackend slice per trial, or N ``LocalStrategy``
    trials sharing the host.  The default (1) is strict sequential,
    which is the right mode when every trial needs the whole TPU slice.
    Schedulers are shared and lock-protected; PBT exploits from whatever
    population state exists when a trial STARTS (the same asynchronous
    semantics real concurrent PBT has).

    **Gang-packing** (``fleet_devices=``): with a fleet size set, every
    trial acquires a disjoint sub-mesh allocation from one
    :class:`~ray_lightning_tpu.tuning.pack.FleetPacker` before it runs
    (``devices_per_trial`` slots, defaulting to an even
    ``fleet_devices / max_concurrent_trials`` split; a trial may start
    with as few as ``min_devices_per_trial`` on a busy fleet).
    ``LocalStrategy`` builds its mesh over exactly the allocated
    devices, so concurrent trials stop time-sharing chips — and when a
    trial's elastic restart governor shrinks its world
    (docs/FAULT_TOLERANCE.md "Elastic resume"), the packer re-packs:
    the freed devices immediately become capacity for queued trials.
    """
    import threading

    scheduler = scheduler or FIFOScheduler()
    configs = generate_trials(config, num_samples=num_samples, seed=seed)
    os.makedirs(local_dir, exist_ok=True)
    if max_concurrent_trials < 1:
        raise ValueError("max_concurrent_trials must be >= 1")
    packer = None
    if fleet_devices is not None:
        from .pack import FleetPacker

        packer = FleetPacker(fleet_devices)
        if devices_per_trial is None:
            devices_per_trial = max(
                fleet_devices // max_concurrent_trials, 1
            )
        if not 1 <= devices_per_trial <= fleet_devices:
            raise ValueError(
                f"devices_per_trial must be in [1, {fleet_devices}], "
                f"got {devices_per_trial}"
            )
        if min_devices_per_trial is not None and not (
            1 <= min_devices_per_trial <= devices_per_trial
        ):
            # Validated HERE, not at the first acquire inside a trial
            # thread — a config typo must fail the experiment eagerly,
            # not as a phantom trial error mid-run.
            raise ValueError(
                f"min_devices_per_trial must be in [1, "
                f"{devices_per_trial}], got {min_devices_per_trial}"
            )
    elif devices_per_trial is not None or min_devices_per_trial is not None:
        raise ValueError(
            "devices_per_trial/min_devices_per_trial need fleet_devices"
        )
    trials: List[Optional[Trial]] = [None] * len(configs)
    # Latest checkpoint each trial wrote — the donor pool for PBT's
    # exploit step (config mutation alone is only half of PBT; the
    # exploited trial must also START from the donor's weights).
    last_ckpts: Dict[str, Optional[str]] = {}
    # One lock guards every shared structure (scheduler state, the
    # donor-checkpoint pool, trial report lists read by the scheduler).
    lock = threading.Lock()

    def run_one(i: int, cfg: Dict[str, Any]) -> None:
        with lock:
            restore_path: Optional[str] = None
            if isinstance(scheduler, PopulationBasedTraining) and i > 0:
                cfg = scheduler.next_config(cfg)
                donor = scheduler.best_trial_id
                if donor is not None:
                    restore_path = _resolve_ckpt_file(
                        last_ckpts.get(donor)
                    )
            trial = Trial(f"trial_{i:04d}", cfg)
            trials[i] = trial
            if isinstance(scheduler, PopulationBasedTraining):
                scheduler.register_trial(trial.trial_id, cfg)

        def on_report(record: Dict[str, Any], _trial=trial) -> str:
            with lock:
                _trial.reports.append(record)
                return scheduler.on_result(_trial.trial_id, record)

        # Gang-packing: claim this trial's sub-mesh BEFORE the session
        # exists (a blocked acquire must not hold a half-open session),
        # and wire the elastic-resize hook so a governor shrink frees
        # devices back into the fleet mid-experiment.
        alloc = None
        if packer is not None:
            alloc = packer.acquire(
                devices_per_trial, min_n=min_devices_per_trial
            )
        session = init_trial_session(
            trial.trial_id, local_dir, on_report=on_report,
            restore_path=restore_path,
            devices=alloc.devices if alloc is not None else None,
        )
        if alloc is not None:

            def _on_resize(old_world: int, new_world: int,
                           _alloc=alloc, _sess=session) -> None:
                # Scale the allocation with the world change so devices
                # per worker stay constant: computed off the CURRENT
                # size, so chained resizes (2→1→2) round-trip.
                if old_world <= 0:
                    return
                new_n = max((_alloc.n * new_world) // old_world, 1)
                packer.resize(_alloc, new_n)
                _sess.devices = _alloc.devices

            session.on_resize = _on_resize
        trial.status = "RUNNING"
        t0 = time.perf_counter()
        try:
            trainable(dict(cfg))
            trial.status = "TERMINATED"
        except TrialStopRequested:
            trial.status = "STOPPED"
        except Exception:  # noqa: BLE001 - record, optionally re-raise
            trial.status = "ERROR"
            trial.error = traceback.format_exc()
            if raise_on_trial_error:
                raise  # the finally below releases + shuts down
        finally:
            trial.duration_s = time.perf_counter() - t0
            with lock:
                last_ckpts[trial.trial_id] = session.last_checkpoint
            if alloc is not None:
                packer.release(alloc)
            shutdown_trial_session()
        with lock:
            scheduler.on_trial_complete(trial.trial_id, trial.last_result)
        if verbose:
            last = trial.last_result.get(metric)
            print(
                f"[tune] {trial.trial_id} {trial.status:10s} "
                f"iters={trial.training_iteration:3d} {metric}="
                f"{last if last is not None else 'n/a'} config={cfg}",
                flush=True,
            )

    if max_concurrent_trials == 1:
        # Inline (no worker thread): user trainables keep main-thread
        # affordances like signal handlers, and raise_on_trial_error
        # stops at the FIRST failure exactly as before.
        for i, cfg in enumerate(configs):
            run_one(i, cfg)
    else:
        from concurrent.futures import ThreadPoolExecutor, as_completed

        from .session import set_strict_sessions

        # Strict session resolution for the whole experiment: foreign
        # threads must never silently attach to whichever concurrent
        # trial happens to survive.
        set_strict_sessions(True)
        first: Optional[BaseException] = None
        try:
            with ThreadPoolExecutor(
                max_workers=max_concurrent_trials,
                thread_name_prefix="rlt-trial",
            ) as pool:
                futures = [
                    pool.submit(run_one, i, cfg)
                    for i, cfg in enumerate(configs)
                ]
                # Fail-fast (sequential mode's contract, kept): a future
                # only carries an exception when raise_on_trial_error —
                # the first one cancels every not-yet-started trial
                # instead of burning accelerator time on doomed configs.
                # Already-running trials finish (the `with` joins them);
                # cancelled ones never ran and stay out of the analysis.
                for fut in as_completed(futures):
                    err = fut.exception()
                    if err is not None:
                        first = err
                        for other in futures:
                            other.cancel()
                        break
        finally:
            set_strict_sessions(False)
        if first is not None:  # only when raise_on_trial_error
            raise first
    return ExperimentAnalysis(
        [t for t in trials if t is not None], metric, mode
    )
