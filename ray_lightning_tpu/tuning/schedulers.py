"""Trial schedulers: FIFO, ASHA (successive halving), simplified PBT.

≙ the schedulers the reference's Tune integration is driven by (PBT/ASHA
named at SURVEY §3.3; the reference example uses ASHA-style early stopping
through ``tune.run(scheduler=...)``).  Decisions are made on every metric
report flowing through the trial session.
"""

from __future__ import annotations

import math
import random
from typing import Any, Dict, List, Optional

__all__ = ["FIFOScheduler", "ASHAScheduler", "PopulationBasedTraining"]

CONTINUE = "CONTINUE"
STOP = "STOP"


class FIFOScheduler:
    """Run every trial to completion."""

    def on_result(self, trial_id: str, result: Dict[str, Any]) -> str:
        return CONTINUE

    def on_trial_complete(self, trial_id: str, last: Dict[str, Any]) -> None:
        ...


class ASHAScheduler(FIFOScheduler):
    """Asynchronous Successive Halving: stop trials that fall out of the
    top 1/reduction_factor of their rung."""

    def __init__(
        self,
        metric: str = "loss",
        mode: str = "min",
        max_t: int = 100,
        grace_period: int = 1,
        reduction_factor: int = 3,
    ):
        if mode not in ("min", "max"):
            raise ValueError("mode must be min|max")
        if grace_period < 1:
            raise ValueError("grace_period must be >= 1")
        if reduction_factor < 2:
            raise ValueError("reduction_factor must be >= 2")
        self.metric = metric
        self.mode = mode
        self.max_t = max_t
        self.grace_period = grace_period
        self.rf = reduction_factor
        # rung index -> list of metric values recorded at that rung
        self._rungs: Dict[int, List[float]] = {}
        self._sign = 1.0 if mode == "min" else -1.0

    def _rung_of(self, iteration: int) -> Optional[int]:
        """Rung milestones at grace_period * rf^k."""
        t = self.grace_period
        k = 0
        while t <= self.max_t:
            if iteration == t:
                return k
            t *= self.rf
            k += 1
        return None

    def on_result(self, trial_id: str, result: Dict[str, Any]) -> str:
        value = result.get(self.metric)
        iteration = result.get("training_iteration", 0)
        if value is None:
            return CONTINUE
        # Strictly beyond max_t: a trial whose own budget ends exactly AT
        # max_t finishes naturally (TERMINATED, not STOPPED).
        if iteration > self.max_t:
            return STOP
        rung = self._rung_of(iteration)
        if rung is None:
            return CONTINUE
        scores = self._rungs.setdefault(rung, [])
        score = self._sign * float(value)
        scores.append(score)
        # Continue iff within the top 1/rf of scores seen at this rung
        # (asynchronous: compares against everything seen so far).
        cutoff_index = max(0, math.ceil(len(scores) / self.rf) - 1)
        cutoff = sorted(scores)[cutoff_index]
        return CONTINUE if score <= cutoff else STOP


class PopulationBasedTraining(FIFOScheduler):
    """Simplified synchronous PBT over sequential trials.

    Real PBT exploits/explores a concurrently-running population.  With
    sequential trial execution the same search dynamic is approximated:
    when a trial underperforms the population's best at a perturbation
    interval, it is stopped, and :meth:`next_config` seeds the following
    trial from the best trial's config with mutated hyperparameters
    (explore) — while the tuner hands that trial the best trial's latest
    CHECKPOINT (exploit), via the trial session's ``restore_path``, so it
    continues from the donor's weights rather than from scratch
    (≙ reference ``_TuneCheckpointCallback``'s purpose, ``tune.py:
    136-178``: the weights transfer is the half of PBT that makes it
    work).  Trainables opt in with ``tuning.get_checkpoint()``.
    """

    def __init__(
        self,
        metric: str = "loss",
        mode: str = "min",
        perturbation_interval: int = 2,
        hyperparam_mutations: Optional[Dict[str, Any]] = None,
        quantile_fraction: float = 0.25,
        seed: int = 0,
    ):
        self.metric = metric
        self.mode = mode
        self.interval = perturbation_interval
        self.mutations = hyperparam_mutations or {}
        self.quantile = quantile_fraction
        self._sign = 1.0 if mode == "min" else -1.0
        self._best: Optional[tuple] = None  # (score, trial_id, config)
        self._scores: List[float] = []
        self._rng = random.Random(seed)
        self._configs: Dict[str, Dict[str, Any]] = {}

    def register_trial(self, trial_id: str, config: Dict[str, Any]) -> None:
        self._configs[trial_id] = dict(config)

    def on_result(self, trial_id: str, result: Dict[str, Any]) -> str:
        value = result.get(self.metric)
        iteration = result.get("training_iteration", 0)
        if value is None:
            return CONTINUE
        score = self._sign * float(value)
        if self._best is None or score < self._best[0]:
            self._best = (score, trial_id, self._configs.get(trial_id, {}))
        if iteration % self.interval != 0:
            return CONTINUE
        self._scores.append(score)
        if len(self._scores) < 4:
            return CONTINUE
        if self.quantile <= 0:
            return CONTINUE  # quantile 0 ⇒ never stop (Ray PBT parity)
        idx = min(
            len(self._scores) - 1,
            int(len(self._scores) * (1 - self.quantile)),
        )
        cutoff = sorted(self._scores)[idx]
        return STOP if score > cutoff else CONTINUE

    @property
    def best_trial_id(self) -> Optional[str]:
        """The exploit donor: the trial whose config (and checkpoint)
        seeds the next trial."""
        return self._best[1] if self._best is not None else None

    def next_config(self, base_config: Dict[str, Any]) -> Dict[str, Any]:
        """Exploit-and-explore: start from the best config, mutate."""
        if self._best is None:
            return base_config
        cfg = dict(self._best[2]) or dict(base_config)
        for key, domain in self.mutations.items():
            if isinstance(domain, list):
                cfg[key] = self._rng.choice(domain)
            elif callable(getattr(domain, "sample", None)):
                cfg[key] = domain.sample(self._rng)
            elif key in cfg and isinstance(cfg[key], (int, float)):
                cfg[key] = cfg[key] * self._rng.choice([0.8, 1.25])
        return cfg
