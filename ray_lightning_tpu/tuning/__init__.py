from .search import (
    choice,
    generate_trials,
    grid_search,
    loguniform,
    randint,
    sample_from,
    uniform,
)
from .pack import FleetPacker, SubMeshAllocation
from .schedulers import ASHAScheduler, FIFOScheduler, PopulationBasedTraining
from .session import (
    TrialStopRequested,
    checkpoint_dir,
    get_checkpoint,
    get_trial_session,
    is_trial_session_enabled,
    report,
)
from .tuner import ExperimentAnalysis, Trial, tune_run

__all__ = [
    "choice",
    "generate_trials",
    "grid_search",
    "loguniform",
    "randint",
    "sample_from",
    "uniform",
    "ASHAScheduler",
    "FIFOScheduler",
    "FleetPacker",
    "SubMeshAllocation",
    "PopulationBasedTraining",
    "TrialStopRequested",
    "checkpoint_dir",
    "get_checkpoint",
    "get_trial_session",
    "is_trial_session_enabled",
    "report",
    "ExperimentAnalysis",
    "Trial",
    "tune_run",
]
