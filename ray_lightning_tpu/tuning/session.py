"""Trial session: the driver-side context a running trial reports into.

≙ the Ray Tune *session* the reference's queue-shipped lambdas execute in
(reference ``tune.py:130-134``: ``tune.report`` only works in the Tune
session process — "a key design point", SURVEY §3.3).  Our native tuner
keeps the same indirection: worker rank-0 callbacks ship
``lambda: report(**metrics)`` through the distributed queue; the driver's
result pump executes the thunk *here*, inside the active trial session,
where the scheduler can see the metric and decide to stop the trial.
"""

from __future__ import annotations

import os
import threading
from typing import Any, Callable, Dict, Optional

__all__ = [
    "TrialSession",
    "TrialStopRequested",
    "init_trial_session",
    "get_trial_session",
    "shutdown_trial_session",
    "is_trial_session_enabled",
    "report",
    "checkpoint_dir",
    "get_checkpoint",
    "current_trial_devices",
    "notify_world_resize",
]


class TrialStopRequested(Exception):
    """Raised by ``report`` when the scheduler stops the trial.

    Propagates out of the driver's queue pump (``process_results``) and
    through ``Trainer.fit``; the strategy's ``finally: teardown()`` kills
    the workers — the native analogue of Ray Tune terminating a trial
    actor mid-training.
    """


class TrialSession:
    def __init__(
        self,
        trial_id: str,
        local_dir: str,
        on_report: Optional[Callable[[Dict[str, Any]], str]] = None,
        restore_path: Optional[str] = None,
        devices: Optional[list] = None,
    ):
        self.trial_id = trial_id
        self.local_dir = local_dir
        self._on_report = on_report
        self.reports: list = []
        self.training_iteration = 0
        # Gang-packing (tuning/pack.py): the device INDICES this trial
        # was allocated out of the shared fleet — LocalStrategy builds
        # its mesh over exactly these, so concurrent trials run on
        # disjoint sub-meshes instead of time-sharing every chip.
        # ``on_resize(old_world, new_world)`` is the elastic hook the
        # restart governor calls when it resizes the trial's world; the
        # tuner wires it to the packer so freed devices re-enter the
        # pool mid-experiment.
        self.devices = devices
        self.on_resize: Optional[Callable[[int, int], None]] = None
        # Checkpoint this trial should START from (PBT exploit: the donor
        # trial's weights — reference ``tune.py:136-178``'s reason to
        # exist).  Read by the trainable via :func:`get_checkpoint`.
        self.restore_path = restore_path
        # Most recent checkpoint this trial WROTE (file path when written
        # through ``_driver_write_checkpoint``, dir when the user only
        # called :meth:`checkpoint_dir`).  The tuner harvests it so a
        # later exploited trial can restore from it.
        self.last_checkpoint: Optional[str] = None

    def report(self, **metrics: Any) -> None:
        self.training_iteration += 1
        record = dict(metrics)
        record["training_iteration"] = self.training_iteration
        self.reports.append(record)
        if self._on_report is not None:
            decision = self._on_report(record)
            if decision == "STOP":
                raise TrialStopRequested(self.trial_id)

    def checkpoint_dir(self, step: int) -> str:
        """≙ ``tune.checkpoint_dir`` (reference ``tune.py:169-178``)."""
        path = os.path.join(
            self.local_dir, self.trial_id, f"checkpoint_{step:06d}"
        )
        os.makedirs(path, exist_ok=True)
        self.last_checkpoint = path
        return path

    def note_checkpoint(self, path: str) -> None:
        """Record the exact file a checkpoint writer produced (sharper
        than the dir from :meth:`checkpoint_dir` — directly consumable by
        ``Trainer(resume_from_checkpoint=...)``)."""
        self.last_checkpoint = path


# THREAD-local, not process-global: ``tune_run(max_concurrent_trials=N)``
# runs each trial driver in its own thread, and everything a trial's fit
# touches (report thunks, checkpoint writes, queue pumping) runs in that
# same thread — so thread identity IS trial identity.  A registry of
# active sessions backs the sequential-mode fallback: with exactly ONE
# active session, a call from a foreign thread (a user's helper/monitor
# thread inside the trainable) unambiguously belongs to it — the
# behavior the old process-global provided.  Only under real trial
# concurrency is a foreign-thread call ambiguous, and then it raises.
_tls = threading.local()
_registry_lock = threading.Lock()
_active: dict = {}  # id(session) -> session
# Count of concurrent tune_run experiments in flight.  While nonzero the
# sole-session fallback below is DISABLED: after one concurrent trial
# finishes, a foreign-thread call would otherwise silently resolve to the
# surviving trial's session — attributing trial A's metrics to trial B is
# strictly worse than raising.
_strict_experiments = 0


def set_strict_sessions(on: bool) -> None:
    """Entered/exited by ``tune_run(max_concurrent_trials>1)``."""
    global _strict_experiments
    with _registry_lock:
        _strict_experiments += 1 if on else -1


def _current() -> Optional[TrialSession]:
    sess = getattr(_tls, "session", None)
    if sess is not None:
        return sess
    with _registry_lock:
        if _strict_experiments == 0 and len(_active) == 1:
            return next(iter(_active.values()))
    return None


def init_trial_session(*args, **kwargs) -> TrialSession:
    if getattr(_tls, "session", None) is not None:
        raise ValueError("A trial session is already active.")
    sess = TrialSession(*args, **kwargs)
    _tls.session = sess
    with _registry_lock:
        _active[id(sess)] = sess
    return sess


def get_trial_session() -> TrialSession:
    sess = _current()
    if sess is None:
        with _registry_lock:
            n, strict = len(_active), _strict_experiments
        if n >= 1 and strict:
            raise ValueError(
                f"{n} trial session(s) active in a concurrent experiment "
                f"but this thread owns none of them; under "
                f"max_concurrent_trials>1, report()/checkpoint calls "
                f"must run in the trial's own thread."
            )
        raise ValueError(
            "No trial session is active; report() must run inside a "
            "tune_run trial (driver process)."
        )
    return sess


def shutdown_trial_session() -> None:
    sess = getattr(_tls, "session", None)
    if sess is not None:
        with _registry_lock:
            _active.pop(id(sess), None)
    _tls.session = None


def is_trial_session_enabled() -> bool:
    return _current() is not None


def report(**metrics: Any) -> None:
    """≙ ``tune.report`` — module-level so queue thunks pickle by ref."""
    get_trial_session().report(**metrics)


def checkpoint_dir(step: int) -> str:
    return get_trial_session().checkpoint_dir(step)


def get_checkpoint() -> Optional[str]:
    """Checkpoint path this trial should resume from, or None.

    ≙ Ray Tune's ``session.get_checkpoint()``: a PBT-exploited trial
    receives the donor trial's latest checkpoint here, so the trainable
    can pass it to ``Trainer(resume_from_checkpoint=...)`` and continue
    from the donor's WEIGHTS, not just its config.  Returns None for
    trials starting fresh (or outside any trial session, so trainables
    can call it unconditionally).

    The value is a state-stream FILE when the donor checkpointed through
    the framework's callbacks (or wrote a single/conventionally-named
    file into ``checkpoint_dir``); a donor that wrote a custom
    multi-file layout yields its checkpoint DIRECTORY instead — such a
    trainable restores by its own convention.
    """
    sess = _current()
    if sess is None:
        return None
    return sess.restore_path


def current_trial_devices() -> Optional[list]:
    """Device indices of the active trial's sub-mesh allocation, or
    ``None`` outside a gang-packed trial.  LocalStrategy consults this
    at mesh-build time, so trainables need no packer plumbing."""
    sess = _current()
    if sess is None:
        return None
    return sess.devices


def notify_world_resize(old_world: int, new_world: int) -> None:
    """Elastic-governor → gang-packer bridge: called by the strategy
    when it resizes a trial's world (docs/FAULT_TOLERANCE.md "Elastic
    resume").  No-op outside a trial session or when the tuner wired no
    packer — resizing is an observer concern, never a restart
    dependency."""
    sess = _current()
    if sess is None or sess.on_resize is None:
        return
    sess.on_resize(int(old_world), int(new_world))
