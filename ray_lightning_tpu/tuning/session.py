"""Trial session: the driver-side context a running trial reports into.

≙ the Ray Tune *session* the reference's queue-shipped lambdas execute in
(reference ``tune.py:130-134``: ``tune.report`` only works in the Tune
session process — "a key design point", SURVEY §3.3).  Our native tuner
keeps the same indirection: worker rank-0 callbacks ship
``lambda: report(**metrics)`` through the distributed queue; the driver's
result pump executes the thunk *here*, inside the active trial session,
where the scheduler can see the metric and decide to stop the trial.
"""

from __future__ import annotations

import os
import threading
from typing import Any, Callable, Dict, Optional

__all__ = [
    "TrialSession",
    "TrialStopRequested",
    "init_trial_session",
    "get_trial_session",
    "shutdown_trial_session",
    "is_trial_session_enabled",
    "report",
    "checkpoint_dir",
]


class TrialStopRequested(Exception):
    """Raised by ``report`` when the scheduler stops the trial.

    Propagates out of the driver's queue pump (``process_results``) and
    through ``Trainer.fit``; the strategy's ``finally: teardown()`` kills
    the workers — the native analogue of Ray Tune terminating a trial
    actor mid-training.
    """


class TrialSession:
    def __init__(
        self,
        trial_id: str,
        local_dir: str,
        on_report: Optional[Callable[[Dict[str, Any]], str]] = None,
    ):
        self.trial_id = trial_id
        self.local_dir = local_dir
        self._on_report = on_report
        self.reports: list = []
        self.training_iteration = 0

    def report(self, **metrics: Any) -> None:
        self.training_iteration += 1
        record = dict(metrics)
        record["training_iteration"] = self.training_iteration
        self.reports.append(record)
        if self._on_report is not None:
            decision = self._on_report(record)
            if decision == "STOP":
                raise TrialStopRequested(self.trial_id)

    def checkpoint_dir(self, step: int) -> str:
        """≙ ``tune.checkpoint_dir`` (reference ``tune.py:169-178``)."""
        path = os.path.join(
            self.local_dir, self.trial_id, f"checkpoint_{step:06d}"
        )
        os.makedirs(path, exist_ok=True)
        return path


_lock = threading.Lock()
_session: Optional[TrialSession] = None


def init_trial_session(*args, **kwargs) -> TrialSession:
    global _session
    with _lock:
        if _session is not None:
            raise ValueError("A trial session is already active.")
        _session = TrialSession(*args, **kwargs)
        return _session


def get_trial_session() -> TrialSession:
    if _session is None:
        raise ValueError(
            "No trial session is active; report() must run inside a "
            "tune_run trial (driver process)."
        )
    return _session


def shutdown_trial_session() -> None:
    global _session
    with _lock:
        _session = None


def is_trial_session_enabled() -> bool:
    return _session is not None


def report(**metrics: Any) -> None:
    """≙ ``tune.report`` — module-level so queue thunks pickle by ref."""
    get_trial_session().report(**metrics)


def checkpoint_dir(step: int) -> str:
    return get_trial_session().checkpoint_dir(step)
