"""Gang-packing: concurrent trials on disjoint sub-meshes of one fleet.

``tune_run(max_concurrent_trials=N)`` runs N trial drivers at once, but
until this module each trial's ``LocalStrategy`` built its mesh over
EVERY visible device — concurrent trials silently time-shared the same
chips.  The :class:`FleetPacker` is the missing resource layer: one
fleet of ``total_devices`` device slots, carved into disjoint
allocations that trials acquire before running and release after.
``build_mesh(devices=...)`` already accepts an explicit device list, so
an allocation IS a sub-mesh.

Elastic interplay (the reason this lives in the recovery PR): when a
trial's restart governor resizes its world (``elastic_min_workers``,
docs/FAULT_TOLERANCE.md "Elastic resume"), the strategy notifies the
trial session (``session.notify_world_resize``) and the packer
**re-packs** — a shrunk trial's freed devices immediately become
capacity for queued trials, and a grown trial reclaims free slots
(best-effort: growth never steals from a running peer).

Thread-safe; blocking ``acquire`` with condition-variable wakeups on
every release/shrink.  jax-free — allocations are device *indices*;
the strategy resolves them against ``jax.devices()``.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional

__all__ = ["FleetPacker", "SubMeshAllocation"]


class SubMeshAllocation:
    """A trial's slice of the fleet: a sorted list of device indices.

    The list identity is stable across :meth:`FleetPacker.resize` —
    holders that keep a reference (the trial session) always see the
    current membership.
    """

    def __init__(self, packer: "FleetPacker", devices: List[int]):
        self._packer = packer
        self.devices = sorted(devices)
        self.released = False

    @property
    def n(self) -> int:
        return len(self.devices)

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return f"SubMeshAllocation({self.devices})"


class FleetPacker:
    """Disjoint device-slot allocator for one fleet.

    * :meth:`acquire` blocks until at least ``min_n`` slots are free,
      then takes ``min(n, free)`` — a trial may deliberately START
      shrunk on a busy fleet rather than wait for its full request.
    * :meth:`resize` re-packs a live allocation to ``new_n`` slots:
      shrinking frees the highest-numbered slots (and wakes waiters);
      growing takes free slots up to ``new_n``, keeping the current
      size when the fleet has none spare (growth must never deadlock a
      running trial).  Returns the actual post-resize size.
    * :meth:`release` returns everything and wakes every waiter.
    """

    def __init__(self, total_devices: int):
        if total_devices < 1:
            raise ValueError("total_devices must be >= 1")
        self.total_devices = int(total_devices)
        self._free = set(range(self.total_devices))
        self._cond = threading.Condition()
        self._allocs: List[SubMeshAllocation] = []

    def acquire(self, n: int, min_n: Optional[int] = None,
                timeout: Optional[float] = None) -> SubMeshAllocation:
        n = int(n)
        min_n = n if min_n is None else int(min_n)
        if not 1 <= min_n <= n:
            raise ValueError(
                f"need 1 <= min_n ({min_n}) <= n ({n})"
            )
        if min_n > self.total_devices:
            raise ValueError(
                f"min_n {min_n} exceeds the fleet "
                f"({self.total_devices} devices)"
            )
        with self._cond:
            ok = self._cond.wait_for(
                lambda: len(self._free) >= min_n, timeout=timeout
            )
            if not ok:
                raise TimeoutError(
                    f"no {min_n} free devices within {timeout}s "
                    f"({len(self._free)}/{self.total_devices} free)"
                )
            take = sorted(self._free)[: min(n, len(self._free))]
            self._free.difference_update(take)
            alloc = SubMeshAllocation(self, take)
            self._allocs.append(alloc)
            return alloc

    def resize(self, alloc: SubMeshAllocation, new_n: int) -> int:
        new_n = max(int(new_n), 0)
        with self._cond:
            if alloc.released:
                return 0
            if new_n < alloc.n:
                # Shrink: free the highest slots so the low-numbered
                # prefix stays stable (mesh rebuilds see a prefix of
                # the old device set, not a reshuffle).
                drop = alloc.devices[new_n:]
                del alloc.devices[new_n:]
                self._free.update(drop)
                self._cond.notify_all()
            elif new_n > alloc.n:
                want = new_n - alloc.n
                grab = sorted(self._free)[:want]
                self._free.difference_update(grab)
                alloc.devices.extend(grab)
                alloc.devices.sort()
            return alloc.n

    def release(self, alloc: SubMeshAllocation) -> None:
        with self._cond:
            if alloc.released:
                return
            alloc.released = True
            self._free.update(alloc.devices)
            if alloc in self._allocs:
                self._allocs.remove(alloc)
            self._cond.notify_all()

    def snapshot(self) -> Dict[str, object]:
        with self._cond:
            return {
                "total": self.total_devices,
                "free": sorted(self._free),
                "allocations": [list(a.devices) for a in self._allocs],
            }
