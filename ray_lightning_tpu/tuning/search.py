"""Search-space primitives: grid/choice/uniform/loguniform sampling.

The subset of Ray Tune's search-space API the reference's examples exercise
(``examples/ray_ddp_example.py:105-113`` uses ``tune.choice``-style grids).
"""

from __future__ import annotations

import itertools
import math
import random
from typing import Any, Callable, Dict, Iterator, List, Sequence

__all__ = [
    "grid_search",
    "choice",
    "uniform",
    "loguniform",
    "randint",
    "sample_from",
    "generate_trials",
]


class _Domain:
    def sample(self, rng: random.Random) -> Any:
        raise NotImplementedError


class grid_search(_Domain):  # noqa: N801 - Tune-parity naming
    """Exhaustive grid over the given values (cross-product with other
    grids; multiplies num_samples like Ray Tune)."""

    def __init__(self, values: Sequence[Any]):
        self.values = list(values)


class choice(_Domain):  # noqa: N801
    def __init__(self, values: Sequence[Any]):
        self.values = list(values)

    def sample(self, rng: random.Random) -> Any:
        return rng.choice(self.values)


class uniform(_Domain):  # noqa: N801
    def __init__(self, low: float, high: float):
        self.low, self.high = low, high

    def sample(self, rng: random.Random) -> float:
        return rng.uniform(self.low, self.high)


class loguniform(_Domain):  # noqa: N801
    def __init__(self, low: float, high: float):
        self.low, self.high = low, high

    def sample(self, rng: random.Random) -> float:
        return math.exp(rng.uniform(math.log(self.low), math.log(self.high)))


class randint(_Domain):  # noqa: N801
    def __init__(self, low: int, high: int):
        self.low, self.high = low, high

    def sample(self, rng: random.Random) -> int:
        return rng.randrange(self.low, self.high)


class sample_from(_Domain):  # noqa: N801
    def __init__(self, fn: Callable[[Dict[str, Any]], Any]):
        self.fn = fn


def generate_trials(
    space: Dict[str, Any], num_samples: int = 1, seed: int = 0
) -> List[Dict[str, Any]]:
    """Materialize trial configs: grid cross-product × num_samples random
    draws of the stochastic domains."""
    rng = random.Random(seed)
    grid_keys = [k for k, v in space.items() if isinstance(v, grid_search)]
    grids = (
        itertools.product(*(space[k].values for k in grid_keys))
        if grid_keys
        else [()]
    )
    configs: List[Dict[str, Any]] = []
    for grid_values in grids:
        for _ in range(num_samples):
            cfg: Dict[str, Any] = dict(zip(grid_keys, grid_values))
            for k, v in space.items():
                if k in cfg:
                    continue
                if isinstance(v, sample_from):
                    continue  # resolved after other keys
                cfg[k] = v.sample(rng) if isinstance(v, _Domain) else v
            for k, v in space.items():
                if isinstance(v, sample_from):
                    cfg[k] = v.fn(cfg)
            configs.append(cfg)
    return configs
