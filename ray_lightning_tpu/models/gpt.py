"""GPT — the flagship transformer family (decoder-only LM), TPU-first.

≙ the reference's "large model" example slot (pl_bolts ImageGPT under
``RayShardedPlugin``, ``/root/reference/examples/ray_ddp_sharded_example.py:48-71``
— its GPT is an external torch module).  Here the model is owned by the
framework and written for the hardware:

* **scan-over-layers**: block parameters are stacked with a leading
  ``n_layer`` axis and the forward is one ``lax.scan`` — XLA compiles one
  block body instead of ``n_layer`` inlined copies (compile time stays
  flat as depth grows).
* **mixed precision**: activations in bfloat16 (MXU-native), parameters,
  layer-norm statistics, softmax and the loss in float32.
* **attention dispatch**: :func:`ray_lightning_tpu.ops.causal_attention`
  — Pallas flash kernel on TPU, XLA einsum elsewhere, or ring attention
  over a sequence-parallel mesh axis for long context.
* **parallelism as annotations**: :meth:`GPT.param_partition_specs`
  publishes Megatron-style tensor-parallel PartitionSpecs (column-split
  QKV/MLP-in, row-split proj/MLP-out, vocab-split embedding); the
  strategy layers ZeRO/FSDP sharding on top (see
  ``parallel/sharding.py``) and XLA inserts the collectives.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import PartitionSpec as P

from ray_lightning_tpu.core.data import TpuDataModule, NumpyLoader
from ray_lightning_tpu.core.module import TpuModule
from ray_lightning_tpu.ops import causal_attention

__all__ = ["GPTConfig", "GPT", "SyntheticLMDataModule", "make_block_stage",
           "gpt_adamw", "merge_lora", "extract_lora", "add_lora_adapters",
           "synthetic_lora_adapter", "has_lora_adapters",
           "residual_save_bytes"]


@dataclasses.dataclass(frozen=True)
class GPTConfig:
    vocab_size: int = 50304  # GPT-2 vocab padded to a multiple of 128 (MXU)
    n_layer: int = 12
    n_head: int = 12
    d_model: int = 768
    seq_len: int = 1024
    mlp_ratio: int = 4
    lr: float = 3e-4
    weight_decay: float = 0.1
    warmup_steps: int = 100
    # Mixture-of-Experts (0 = dense MLP).  Experts replace every block's
    # MLP; routed with top-k capacity dispatch (ops/moe.py) and sharded
    # over an ``expert`` mesh axis when present.
    n_experts: int = 0
    moe_top_k: int = 2
    moe_capacity_factor: float = 1.25
    moe_aux_weight: float = 1e-2
    # AdamW first-moment storage dtype.  bf16 momentum halves that
    # state's HBM read+write in the (bandwidth-bound) optimizer update
    # with no measurable loss-curve effect at LM scale; the variance and
    # params stay f32.  Set to "float32" for bit-conservative runs.
    # Resume across a dtype change is safe: the fit loop casts restored
    # optimizer-state leaves to this run's template dtypes on load
    # (core/loop.py resume path), so f32-era checkpoints restore cleanly.
    mu_dtype: str = "bfloat16"
    # Optimizer-state precision policy (generalizes ``mu_dtype`` — that
    # knob is the legacy special case "bf16 first moment only"):
    #  * None       — legacy behavior, ``mu_dtype`` applies as before;
    #  * "float32"  — both moments f32 (bit-conservative);
    #  * "bfloat16" — BOTH moments bf16 (2x less optimizer-state HBM);
    #  * "int8"     — both moments block-scaled int8 with per-block f32
    #    absmax scales (ops/optim_quant.py; ~3.9x less state HBM, and
    #    ZeRO / RLTSHRD2 elastic shards shrink by the same factor).
    # The update math is f32 in every mode — dequant → update → requant
    # happens inside the donated train step, so the f32 moments never
    # persist in HBM.  Loss-parity vs the f32 arm is gated by
    # tests/test_opt_state.py at the int8_ef grad-comm tolerance.
    opt_state_dtype: Optional[str] = None
    # LoRA fine-tuning (0 = off).  rank>0 adds low-rank adapters on the
    # attention projections (qkv column + output proj — the standard
    # target set); the optimizer then trains ONLY the adapters (the base
    # is frozen via optax.multi_transform, so it carries no Adam
    # moments — the memory win that makes LoRA worth it).  Pairs with
    # ``utils/hf_import.py`` + ``initial_params`` for fine-tuning
    # imported checkpoints; ``merge_lora`` folds adapters into the base
    # weights for inference/generation.
    lora_rank: int = 0
    lora_alpha: float = 16.0

    @classmethod
    def tiny(cls) -> "GPTConfig":
        """Test-sized config (CPU-mesh friendly)."""
        return cls(vocab_size=512, n_layer=2, n_head=4, d_model=128,
                   seq_len=128, warmup_steps=2)

    @classmethod
    def gpt2_small(cls) -> "GPTConfig":
        return cls()  # 124M params

    @classmethod
    def tiny_moe(cls, n_experts: int = 4, **kw) -> "GPTConfig":
        return cls(vocab_size=512, n_layer=2, n_head=4, d_model=128,
                   seq_len=128, warmup_steps=2, n_experts=n_experts, **kw)

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_head


def _layer_norm(x: jax.Array, g: jax.Array, b: jax.Array,
                use_pallas: bool = False) -> jax.Array:
    """f32-stats LayerNorm; ``use_pallas`` opts single-chip callers into
    the fused kernels (``ops/layer_norm.py`` — identical math)."""
    from ray_lightning_tpu.ops.layer_norm import layer_norm

    return layer_norm(x, g, b, use_pallas=use_pallas)


def _mlp_residual(x: jax.Array, p: Dict[str, Any], c,
                  ln_pallas: bool = False) -> jax.Array:
    """LN2 + GELU MLP + residual — the dense second half of a GPT block.
    Shape-agnostic over leading dims; shared by the training scan, the
    pipeline stage, and single-token decode so the block math has one
    source."""
    from ray_lightning_tpu.models.quant import resolve_weight

    h = _layer_norm(x, p["ln2_g"], p["ln2_b"], ln_pallas)
    h = jax.nn.gelu(
        h @ resolve_weight(p, "mlp_in_w", c) + p["mlp_in_b"].astype(c)
    )
    return (x + h @ resolve_weight(p, "mlp_out_w", c)
            + p["mlp_out_b"].astype(c))


def _moe_residual(x, p, cfg, groups: int, ln_pallas: bool = False):
    """LN2 + routed expert MLP + residual — the MoE second half of a GPT
    block.  Single source for the training scan and single-token decode
    (≙ the `_mlp_residual` discipline).  Returns ``(x, aux_loss)``."""
    from ray_lightning_tpu.models.quant import resolve_weight
    from ray_lightning_tpu.ops.moe import moe_mlp

    h = _layer_norm(x, p["ln2_g"], p["ln2_b"], ln_pallas)
    y, aux = moe_mlp(
        h, p["gate_w"],
        resolve_weight(p, "moe_in_w", p["gate_w"].dtype), p["moe_in_b"],
        resolve_weight(p, "moe_out_w", p["gate_w"].dtype), p["moe_out_b"],
        top_k=cfg.moe_top_k,
        capacity_factor=cfg.moe_capacity_factor,
        groups=groups,
    )
    return x + y, aux


class GPT(TpuModule):
    """Decoder-only LM.  Batch contract: ``{"tokens": int32 (B, T+1)}``
    — inputs are ``tokens[:, :-1]``, targets ``tokens[:, 1:]``."""

    def __init__(
        self,
        config: Optional[GPTConfig] = None,
        attn_impl: str = "auto",
        seq_axis: str = "sp",
        ring_layout: str = "contiguous",
        remat: bool = False,
        remat_policy: str = "dots+flash",
    ):
        super().__init__()
        self.config = config or GPTConfig.tiny()
        self.attn_impl = attn_impl
        self.seq_axis = seq_axis
        # "zigzag" balances causal work across ring hops (~2x wall-clock
        # for long context); the wrapper permutes the sequence dim in and
        # out, so activations stay normally ordered for the rest of the
        # model.  Data-layer pre-permutation (zigzag_indices) is the
        # gather-free integration for production-scale runs.
        self.ring_layout = ring_layout
        # Rematerialization: recompute block activations in the backward
        # pass instead of holding them in HBM (bandwidth-bound TPU trade:
        # ~30% more FLOPs for ~n_layer× less activation memory — enables
        # bigger per-chip batches / longer sequences).  MXU outputs
        # (matmul results) are kept; cheap elementwise is recomputed.
        #
        # ``remat_policy`` selects what the backward keeps (an on-hardware
        # A/B surface — PERFORMANCE.md "prepared experiments"):
        #  * "dots+flash"     — matmul outputs + ALL named flash residuals
        #    (out/lse/q/k/v).  Never re-runs the attention kernel, but may
        #    double-save the qkv projections (the dots policy already
        #    keeps the (B,T,3d) matmul output the per-head q/k/v are mere
        #    transposes of).
        #  * "dots+flash-out" — matmul outputs + flash out/lse only; the
        #    backward re-derives the per-head transposes from the saved
        #    qkv matmul output (cheap VPU work, ~150 MB/layer less
        #    residual traffic at GPT-2-small/seq-1024 if the double-save
        #    is real).
        #  * "dots"           — matmul outputs only; the backward re-runs
        #    the flash forward kernel (measured dead end, kept as the
        #    control arm).
        #  * "bf16-resid"     — the dots+flash-out save set, PLUS the
        #    layer-scan carry (the residual stream between blocks) is
        #    stored in bf16 and upcast to the compute dtype on read.
        #    The scan's per-layer carry save is the profiler's largest
        #    remaining dynamic-update-slice line; on an f32-precision
        #    run this halves it (on bf16 runs the carry is already
        #    bf16, so the arm costs nothing and saves only the f32
        #    embed-boundary save).  Numerics: equivalent to casting the
        #    residual stream to bf16 at block boundaries — exactly what
        #    precision="bf16" already does — so the f32-run loss delta
        #    is the bf16 rounding of one tensor per layer
        #    (tolerance-pinned by tests/test_gpt.py).
        if remat_policy not in (
            "dots+flash", "dots+flash-out", "dots", "bf16-resid"
        ):
            raise ValueError(
                f"remat_policy {remat_policy!r} not in "
                f"('dots+flash', 'dots+flash-out', 'dots', 'bf16-resid')"
            )
        if self.config.lora_rank > 0 and self.config.n_experts > 0:
            raise ValueError(
                "LoRA adapters target the dense attention projections; "
                "lora_rank > 0 with n_experts > 0 is not supported"
            )
        # Eager knob validation (same discipline as remat_policy): a
        # typo'd state-precision policy fails at construction, not when
        # the optimizer first builds on a worker.
        from ray_lightning_tpu.models.optim import resolve_opt_state_dtype

        resolve_opt_state_dtype(self.config.opt_state_dtype)
        self.remat = remat
        self.remat_policy = remat_policy
        self.save_hyperparameters(
            **dataclasses.asdict(self.config), attn_impl=attn_impl,
            remat=remat, remat_policy=remat_policy,
        )

    # -- params -------------------------------------------------------------
    def init_params(self, rng: jax.Array) -> Dict[str, Any]:
        cfg = self.config
        d, h, L = cfg.d_model, cfg.mlp_ratio * cfg.d_model, cfg.n_layer
        keys = jax.random.split(rng, 8)

        def norm(key, shape, std=0.02):
            return (jax.random.normal(key, shape) * std).astype(jnp.float32)

        # Residual-path projections scaled by 1/sqrt(2L) (GPT-2 init).
        resid_std = 0.02 / np.sqrt(2 * L)
        blocks = {
            "ln1_g": jnp.ones((L, d)),
            "ln1_b": jnp.zeros((L, d)),
            "qkv_w": norm(keys[2], (L, d, 3 * d)),
            "qkv_b": jnp.zeros((L, 3 * d)),
            "proj_w": norm(keys[3], (L, d, d), std=resid_std),
            "proj_b": jnp.zeros((L, d)),
            "ln2_g": jnp.ones((L, d)),
            "ln2_b": jnp.zeros((L, d)),
        }
        if cfg.lora_rank > 0:
            blocks.update(_init_lora_blocks(cfg, keys[6]))
        E = cfg.n_experts
        if E > 0:
            blocks.update({
                "gate_w": norm(keys[6], (L, d, E)),
                "moe_in_w": norm(keys[4], (L, E, d, h)),
                "moe_in_b": jnp.zeros((L, E, h)),
                "moe_out_w": norm(keys[5], (L, E, h, d), std=resid_std),
                "moe_out_b": jnp.zeros((L, E, d)),
            })
        else:
            blocks.update({
                "mlp_in_w": norm(keys[4], (L, d, h)),
                "mlp_in_b": jnp.zeros((L, h)),
                "mlp_out_w": norm(keys[5], (L, h, d), std=resid_std),
                "mlp_out_b": jnp.zeros((L, d)),
            })
        return {
            "wte": norm(keys[0], (cfg.vocab_size, d)),
            "wpe": norm(keys[1], (cfg.seq_len, d), std=0.01),
            "blocks": blocks,
            "ln_f_g": jnp.ones((d,)),
            "ln_f_b": jnp.zeros((d,)),
        }

    def param_partition_specs(self) -> Dict[str, Any]:
        """Tensor-parallel layout over the ``tensor`` mesh axis.

        Megatron recipe: QKV and MLP-in are column-parallel (shard the
        output features ⇒ heads split across devices, no collective
        between the two matmuls of a block half), proj and MLP-out are
        row-parallel (shard the input features ⇒ one psum at the block
        output, inserted by GSPMD).  The tied embedding is sharded on
        d_model, not vocab: under GSPMD a gather from a vocab-sharded
        table forces an involuntary reshard of the lookup output every
        step, whereas a feature-sharded table keeps both the lookup and
        the LM-head contraction in natively partitioned form.  Axes absent
        from the active mesh are dropped by the strategy.
        """
        t, e = "tensor", "expert"
        blocks = {
            "ln1_g": P(), "ln1_b": P(),
            "qkv_w": P(None, None, t), "qkv_b": P(None, t),
            "proj_w": P(None, t, None), "proj_b": P(),
            "ln2_g": P(), "ln2_b": P(),
        }
        if self.config.lora_rank > 0:
            # Adapters follow the host matmul's layout: qkv's B matrix is
            # column-parallel like qkv_w; proj's A contracts the
            # tensor-sharded attention output (GSPMD inserts the psum).
            blocks.update({
                "lora_qkv_a": P(), "lora_qkv_b": P(None, None, t),
                "lora_proj_a": P(None, t, None), "lora_proj_b": P(),
            })
        if self.config.n_experts > 0:
            # ep × tp composition: experts over the expert axis, each
            # expert's hidden dim over tensor (column/row-parallel FFN).
            blocks.update({
                "gate_w": P(),
                "moe_in_w": P(None, e, None, t),
                "moe_in_b": P(None, e, t),
                "moe_out_w": P(None, e, t, None),
                "moe_out_b": P(None, e, None),
            })
        else:
            blocks.update({
                "mlp_in_w": P(None, None, t), "mlp_in_b": P(None, t),
                "mlp_out_w": P(None, t, None), "mlp_out_b": P(),
            })
        return {
            "wte": P(None, t),
            "wpe": P(),
            "blocks": blocks,
            "ln_f_g": P(), "ln_f_b": P(),
        }

    # -- forward ------------------------------------------------------------
    def _compute_dtype(self):
        return jnp.bfloat16 if self.precision in ("bf16", "bfloat16") else (
            jnp.float32
        )

    def _attention(self, q, k, v):
        if self.attn_impl == "ring":
            from ray_lightning_tpu.ops import ring_attention_sharded

            mesh = getattr(self.trainer, "mesh", None)
            if mesh is None or self.seq_axis not in mesh.axis_names:
                # Explicitly-requested ring attention with no seq axis is a
                # misconfiguration — falling back silently would hide an
                # O(seq^2)-memory surprise on a long-context run.
                raise ValueError(
                    f"attn_impl='ring' needs mesh axis {self.seq_axis!r}; "
                    f"active mesh axes: "
                    f"{None if mesh is None else mesh.axis_names}. Add "
                    f"{self.seq_axis!r} to mesh_axes or use attn_impl='auto'."
                )
            return ring_attention_sharded(
                q, k, v, mesh, seq_axis=self.seq_axis,
                layout=self.ring_layout,
            )
        return causal_attention(q, k, v, impl=self.attn_impl)

    def _moe_groups(self) -> int:
        """Routing groups = data-parallel shard count, so each group's
        capacity cumsum stays shard-local (GShard's group dim)."""
        mesh = getattr(getattr(self, "trainer", None), "mesh", None)
        if mesh is None:
            return 1
        from ray_lightning_tpu.parallel import sharding as shardlib

        g = 1
        for axis in shardlib.data_axes(mesh):
            g *= mesh.shape[axis]
        return g

    def _constrain_residual(self, x: jax.Array) -> jax.Array:
        """Anchor the residual stream to its canonical layout: batch over
        the data(+fsdp) axes, seq over the sp axis when ring attention is
        active, features replicated.

        Without the anchor, GSPMD propagates the TP parameter shardings
        into activations and flip-flops between feature-sharded and
        batch-sharded layouts across the block, hitting its "involuntary
        full rematerialization" fallback (an all-gather + re-partition per
        mismatch) in the backward pass.  One explicit constraint per block
        keeps every reshard a cheap local collective on ICI.
        """
        trainer = getattr(self, "trainer", None)
        mesh = getattr(trainer, "mesh", None)
        # Under shard_map (the Horovod-duality flavor) the body is already
        # per-device with Manual axes — a named sharding constraint there
        # is both meaningless and a trace-time error.  gspmd only; the
        # quantized grad-sync island (grad_sync_active) also runs this
        # body per-device under shard_map, so it skips the anchor too.
        if (
            mesh is None
            or getattr(trainer, "step_mode", "gspmd") != "gspmd"
            or getattr(trainer, "grad_sync_active", False)
        ):
            return x
        from jax.sharding import NamedSharding

        from ray_lightning_tpu.parallel import sharding as shardlib

        batch = shardlib.data_axes(mesh)
        seq = self.seq_axis if self.seq_axis in mesh.axis_names else None
        # Batches that don't divide the batch axes (e.g. a 2-row
        # inference call on a module still carrying its 8-way training
        # mesh) cannot take the constraint — skip it rather than fail;
        # the anchor is a perf hint, not a correctness requirement.
        n_shards = 1
        for a in (batch if batch else ()):
            n_shards *= mesh.shape[a]
        if x.shape[0] % n_shards:
            return x
        if seq is not None and x.shape[1] % mesh.shape[seq]:
            return x
        spec = P(batch if batch else None, seq, None)
        return jax.lax.with_sharding_constraint(
            x, NamedSharding(mesh, spec)
        )

    def forward(self, params: Dict[str, Any], tokens: jax.Array) -> jax.Array:
        """tokens (B, T) int32 -> logits (B, T, vocab) float32."""
        return self.forward_with_aux(params, tokens)[0]

    def forward_with_aux(
        self, params: Dict[str, Any], tokens: jax.Array
    ) -> Tuple[jax.Array, jax.Array]:
        """(logits, moe_aux_loss) — aux is 0.0 for dense configs.

        Materializes the full ``(B, T, V)`` logits tensor — inference /
        predict path only.  The training loss goes through
        :meth:`forward_hidden` + the vocab-chunked fused cross-entropy
        (``ops/cross_entropy.py``) so that tensor never exists.
        """
        x, aux = self.forward_hidden(params, tokens)
        c = self._compute_dtype()
        logits = jnp.einsum(
            "btd,vd->btv", x, params["wte"].astype(c),
            preferred_element_type=jnp.float32,
        )
        return logits, aux

    def forward_hidden(
        self, params: Dict[str, Any], tokens: jax.Array
    ) -> Tuple[jax.Array, jax.Array]:
        """Transformer trunk: tokens -> (final hidden (B, T, d), moe_aux)."""
        cfg = self.config
        c = self._compute_dtype()
        B, T = tokens.shape
        # Fused-LN gate: same constraint as the CE kernels — pallas_call
        # is opaque to the GSPMD partitioner, so single chip only.
        mesh = getattr(getattr(self, "trainer", None), "mesh", None)
        lnp = (
            (mesh is None or getattr(mesh, "size", 1) == 1)
            and jax.default_backend() == "tpu"
        )
        x = self._constrain_residual(
            (params["wte"][tokens] + params["wpe"][:T]).astype(c)
        )
        # Scan-residual compression: under the "bf16-resid" arm the
        # CARRY crossing scan iterations — which is exactly what the
        # scan saves per layer for the remat backward — is held in
        # bf16; the block upcasts to the compute dtype on entry (the
        # "f32 recompute on read" half of the trade).  Gated on remat:
        # without remat nothing is saved per layer, so rounding the
        # carry would change numerics for no storage win.
        bf16r = self.remat and self.remat_policy == "bf16-resid"
        if bf16r:
            x = x.astype(jnp.bfloat16)

        lora_s = (
            cfg.lora_alpha / cfg.lora_rank if cfg.lora_rank > 0 else 0.0
        )

        def block(carry, p):
            x, aux = carry
            if bf16r:
                x = x.astype(c)
            h = _layer_norm(x, p["ln1_g"], p["ln1_b"], lnp)
            qkv = h @ p["qkv_w"].astype(c) + p["qkv_b"].astype(c)
            if cfg.lora_rank > 0:
                qkv = qkv + (
                    (h @ p["lora_qkv_a"].astype(c))
                    @ p["lora_qkv_b"].astype(c)
                ) * lora_s
            q, k, v = jnp.split(qkv, 3, axis=-1)

            def heads(z):
                return z.reshape(B, T, cfg.n_head, cfg.head_dim)

            att = self._attention(heads(q), heads(k), heads(v))
            att = att.reshape(B, T, cfg.d_model)
            proj = att @ p["proj_w"].astype(c) + p["proj_b"].astype(c)
            if cfg.lora_rank > 0:
                proj = proj + (
                    (att @ p["lora_proj_a"].astype(c))
                    @ p["lora_proj_b"].astype(c)
                ) * lora_s
            x = x + proj
            if cfg.n_experts > 0:
                x, layer_aux = _moe_residual(
                    x, p, cfg, groups=self._moe_groups(), ln_pallas=lnp
                )
                aux = aux + layer_aux
            else:
                x = _mlp_residual(x, p, c, lnp)
            x = self._constrain_residual(x)
            if bf16r:
                x = x.astype(jnp.bfloat16)
            return (x, aux), None

        if self.remat:
            # Save matmul outputs AND (per remat_policy) the named
            # flash-attention residuals — recomputing elementwise is the
            # remat bargain; re-running the attention kernel is not.
            cp = jax.checkpoint_policies
            if self.remat_policy == "dots":
                policy = cp.dots_with_no_batch_dims_saveable
            else:
                # "bf16-resid" keeps the dots+flash-out (no-double-save)
                # set — its storage win comes from the bf16 carry, not
                # from a different save set.
                names = ("flash_out", "flash_lse")
                if self.remat_policy == "dots+flash":
                    names += ("flash_q", "flash_k", "flash_v")
                policy = cp.save_from_both_policies(
                    cp.dots_with_no_batch_dims_saveable,
                    cp.save_only_these_names(*names),
                )
            block = jax.checkpoint(block, policy=policy)
        # Grad-overlap trunk segmentation (parallel/overlap.py): split
        # the layer scan into G sub-scans so each segment's stacked
        # grads emerge at a segment boundary — tapped there, their
        # bucket collectives overlap the earlier segments' backward
        # instead of waiting for the whole trunk.  The taps sit OUTSIDE
        # the (possibly remat-wrapped) block on the scan's xs input, and
        # each sub-scan runs the same per-layer op sequence as the
        # single scan, so segmentation alone (no plane — e.g. the
        # grad_comm=full arm) is bitwise-neutral.
        trainer = getattr(self, "trainer", None)
        plane = getattr(trainer, "grad_tap_plane", None)
        segs = (
            plane.trunk_segments if plane is not None
            else int(getattr(trainer, "grad_overlap_segments", 0) or 0)
        )
        carry = (x, jnp.zeros((), jnp.float32))
        if segs >= 1:
            from ray_lightning_tpu.parallel.pipeline import layer_splits

            bounds = layer_splits(
                cfg.n_layer, min(segs, max(cfg.n_layer, 1))
            )
            for g in range(len(bounds) - 1):
                b, e = bounds[g], bounds[g + 1]
                sub = {
                    k: jax.lax.slice_in_dim(v, b, e, axis=0)
                    for k, v in params["blocks"].items()
                }
                if plane is not None:
                    sub = plane.tap(f"seg{g}", sub)
                carry, _ = jax.lax.scan(block, carry, sub)
        else:
            carry, _ = jax.lax.scan(block, carry, params["blocks"])
        x, aux = carry
        if bf16r:
            x = x.astype(c)
        # Per-layer mean: the aux weight is depth-independent (balanced
        # routing ⇒ aux ≈ 1 at any n_layer).
        aux = aux / max(cfg.n_layer, 1)
        x = _layer_norm(x, params["ln_f_g"], params["ln_f_b"], lnp)
        return x, aux

    def grad_overlap_groups(self, abstract_params, segments: int):
        """Param partition for the backward-overlapped grad sync
        (``parallel/overlap.py``), ordered by backward completion.

        The final-LN group's cotangent completes *first* in the backward
        (loss → layer N → … → layer 1 → embedding), so its sync hides
        under the entire trunk backward; the trunk segments then
        complete in reverse forward order (``seg{G-1}`` before
        ``seg0``), each overlapping the segments still differentiating
        below it; the embeddings complete last — their sync is the only
        one with no compute left to hide under, ≈ the step-end
        behavior.  ``head``/``embed`` are *entry* groups (top-level
        param keys, applied by dict replacement so the tied-softmax
        ``wte`` read in the CE head sees the tapped value too); the
        ``seg{g}`` groups are tapped by :meth:`forward_hidden` at each
        sub-scan boundary.
        """
        if segments < 1:
            return None
        from ray_lightning_tpu.parallel.pipeline import layer_splits

        cfg = self.config
        bounds = layer_splits(
            cfg.n_layer, min(int(segments), max(cfg.n_layer, 1))
        )
        sds = jax.ShapeDtypeStruct

        def _like(leaf):
            return sds(tuple(leaf.shape), leaf.dtype)

        def _rows(leaf, b, e):
            return sds((e - b,) + tuple(leaf.shape[1:]), leaf.dtype)

        groups = [(
            "head",
            {k: _like(abstract_params[k]) for k in ("ln_f_g", "ln_f_b")},
            True,
        )]
        for g in range(len(bounds) - 1):
            b, e = bounds[g], bounds[g + 1]
            groups.append((
                f"seg{g}",
                {
                    k: _rows(v, b, e)
                    for k, v in abstract_params["blocks"].items()
                },
                False,
            ))
        groups.append((
            "embed",
            {k: _like(abstract_params[k]) for k in ("wte", "wpe")},
            True,
        ))
        return groups

    # -- steps --------------------------------------------------------------
    def _loss(self, params, tokens):
        from ray_lightning_tpu.ops.cross_entropy import (
            fused_lm_head_cross_entropy,
            fused_lm_head_cross_entropy_sharded,
        )

        inputs, targets = tokens[:, :-1], tokens[:, 1:]
        x, aux = self.forward_hidden(params, inputs)
        # Fused tied-LM-head CE: the (B, T, V) logits tensor (3.3 GB f32
        # for GPT-2-small at B=16) is never materialized — the head
        # matmul, logsumexp and label gather run per vocab chunk.
        # Kernel dispatch by topology:
        #  * single chip — Pallas tile kernels directly;
        #  * GSPMD mesh with batch-only sharding and a replicated head
        #    (pure DP / ZeRO-1/2) — the same kernels per device inside a
        #    shard_map island (one dwte psum in the backward);
        #  * anything else (TP head, ZeRO-3 params, SP, shard_map step
        #    mode) — the GSPMD-safe vocab-chunk scan.
        trainer = getattr(self, "trainer", None)
        mesh = getattr(trainer, "mesh", None)
        single = mesh is None or getattr(mesh, "size", 1) == 1
        on_tpu = jax.default_backend() == "tpu"
        c = self._compute_dtype()
        if (not single and on_tpu
                and self._batch_only_mesh(trainer, x.shape[0])):
            loss = fused_lm_head_cross_entropy_sharded(
                x, params["wte"], targets, mesh, compute_dtype=c,
            ).mean()
        else:
            loss = fused_lm_head_cross_entropy(
                x, params["wte"], targets, compute_dtype=c,
                use_pallas=single and on_tpu,
            ).mean()
        return loss, aux

    @staticmethod
    def _batch_only_mesh(trainer, batch_dim: int) -> bool:
        """True when the mesh shards only the batch and the head stays
        replicated: batch-only axes, GSPMD step mode, params unsharded
        (zero_stage < 3), batch divisible over the shards (the island
        cannot pad uneven shards the way plain GSPMD does).
        Conservative: unknown attrs veto."""
        mesh = getattr(trainer, "mesh", None)
        if mesh is None:
            return False
        if not set(mesh.axis_names) <= {"data", "fsdp"}:
            return False
        if getattr(trainer, "step_mode", None) != "gspmd":
            return False
        # Inside the quantized grad-sync island the step body is already
        # per-device shard_map — nesting the CE island would double-wrap;
        # the vocab-chunk scan is the per-device-safe path there.
        if getattr(trainer, "grad_sync_active", False):
            return False
        if batch_dim % getattr(mesh, "size", 1):
            return False
        return getattr(trainer, "zero_stage", 3) < 3

    def training_step(self, params, batch, rng):
        loss, aux = self._loss(params, batch["tokens"])
        logs = {"train_loss": loss}
        if self.config.n_experts > 0:
            logs["moe_aux_loss"] = aux
            loss = loss + self.config.moe_aux_weight * aux
        return loss, logs

    def validation_step(self, params, batch):
        loss, _ = self._loss(params, batch["tokens"])
        return {"val_loss": loss, "val_ppl": jnp.exp(loss)}

    def predict_step(self, params, batch):
        return jnp.argmax(
            self.forward(params, batch["tokens"][:, :-1]), axis=-1
        )

    def configure_optimizers(self):
        cfg = self.config
        adamw = gpt_adamw(cfg)
        if cfg.lora_rank > 0:
            # LoRA: only adapter params train.  The frozen base gets
            # set_to_zero (no Adam moments allocated for it — under
            # multi_transform's masking the optimizer state exists only
            # for the trained subset, the actual memory win of LoRA).
            def labels(params):
                return jax.tree_util.tree_map_with_path(
                    lambda path, _: "train"
                    if str(getattr(path[-1], "key", "")).startswith("lora_")
                    else "freeze",
                    params,
                )

            # Frozen grads are zeroed BEFORE the global-norm clip: the
            # clip must see the ADAPTER gradient norm, not the full
            # model's — otherwise base-weight grads (which never apply)
            # scale down every adapter update.
            return optax.chain(
                optax.multi_transform(
                    {"train": optax.identity(),
                     "freeze": optax.set_to_zero()}, labels
                ),
                optax.clip_by_global_norm(1.0),
                optax.multi_transform(
                    {"train": adamw, "freeze": optax.set_to_zero()}, labels
                ),
            )
        tx = optax.chain(optax.clip_by_global_norm(1.0), adamw)
        return tx


def gpt_adamw(cfg: GPTConfig):
    """The family's scheduled+masked AdamW WITHOUT the global-norm
    clip.  Factored out for the MPMD pipeline plane: ``adamw`` is
    elementwise, so per-stage application equals the single-program
    fit exactly, whereas ``clip_by_global_norm`` couples leaves ACROSS
    stages and does not decompose — the MPMD GPT adapter
    (``mpmd/plan.py``) uses this as its per-stage optimizer and its
    parity reference uses the same (docs/ARCHITECTURE.md round 12)."""
    schedule = optax.warmup_cosine_decay_schedule(
        0.0, cfg.lr, cfg.warmup_steps, max(10 * cfg.warmup_steps, 1000)
    )
    from ray_lightning_tpu.models.optim import (
        apply_opt_state_dtype,
        decay_mask,
        resolve_opt_state_dtype,
    )

    # Optimizer-state precision: an explicit ``opt_state_dtype`` policy
    # overrides the legacy ``mu_dtype`` knob (the inner adamw then keeps
    # f32 moments — the wrapper owns the storage dtype; stacking bf16
    # mu_dtype under an int8 wrapper would quantize already-rounded
    # values for no win).
    osd = resolve_opt_state_dtype(cfg.opt_state_dtype)
    mu_dtype = jnp.dtype(cfg.mu_dtype) if osd is None else jnp.float32

    # Decay matrices only (nanoGPT-style naming rule): LN params and
    # biases are exempt; decay_mask is aware of the stacked-blocks
    # leading layer dim, so per-block biases/LN stay exempt too.
    adamw = optax.adamw(schedule, b1=0.9, b2=0.95,
                        weight_decay=cfg.weight_decay,
                        mask=decay_mask,
                        mu_dtype=mu_dtype)
    return apply_opt_state_dtype(adamw, osd)


def residual_save_bytes(
    cfg: GPTConfig,
    batch_size: int,
    policy: str,
    precision: str = "bf16",
) -> int:
    """Analytic bytes the remat backward SAVES per step under a policy —
    the accounting behind the bench's ``residual_policy`` block (chip
    truth comes from the profiler's dynamic-update-slice lines via
    ``tools/hw_session.sh``; this is the model that says which arm to
    expect to win and by how much).

    Per layer, the saved set is: the scan CARRY (the block's residual-
    stream input, stacked across layers by the scan — the top profiler
    line), the dot outputs the ``dots`` policy keeps (qkv 3d, proj d,
    mlp-in 4d, mlp-out d), and the named flash residuals per arm
    (out ``d``; lse at its 8-lane stat width in f32; q/k/v transposes
    ``3d`` only under ``dots+flash`` — the double-save
    ``dots+flash-out`` exists to drop).  ``bf16-resid`` stores the
    carry in 2 bytes regardless of compute precision.
    """
    if policy not in ("dots+flash", "dots+flash-out", "dots",
                      "bf16-resid"):
        # Same eager discipline as GPT.__init__: a typo'd arm must not
        # return plausible-but-mislabeled accounting.
        raise ValueError(
            f"remat_policy {policy!r} not in "
            f"('dots+flash', 'dots+flash-out', 'dots', 'bf16-resid')"
        )
    c = 2 if precision in ("bf16", "bfloat16") else 4
    carry = 2 if policy == "bf16-resid" else c
    B, T, d, L, H = (batch_size, cfg.seq_len, cfg.d_model, cfg.n_layer,
                     cfg.n_head)
    per_layer = B * T * d * carry  # scan carry
    per_layer += B * T * 9 * d * c  # dot outputs (3d + d + 4d + d)
    if policy != "dots":
        per_layer += B * T * d * c          # flash_out
        per_layer += B * H * T * 8 * 4      # flash_lse (8-lane f32 stat)
    if policy == "dots+flash":
        per_layer += B * T * 3 * d * c      # per-head q/k/v double-save
    return L * per_layer


def has_lora_adapters(params: Dict[str, Any]) -> bool:
    """True when the tree carries unmerged LoRA adapters — the shared
    predicate behind every 'merge first' guard (generation, pipeline,
    quantization, HF export)."""
    return any(
        str(k).startswith("lora_") for k in params.get("blocks", {})
    )


def _init_lora_blocks(cfg: GPTConfig, rng: jax.Array) -> Dict[str, Any]:
    """The four stacked adapter tensors — ONE source for both
    ``GPT.init_params`` and :func:`add_lora_adapters`.  B is
    zero-initialized: the adapter delta starts at exactly 0, so step 0
    reproduces the base model bit-for-bit."""
    L, d, r = cfg.n_layer, cfg.d_model, cfg.lora_rank
    ka, kb = jax.random.split(rng)
    return {
        "lora_qkv_a": (jax.random.normal(ka, (L, d, r)) * 0.02).astype(
            jnp.float32),
        "lora_qkv_b": jnp.zeros((L, r, 3 * d)),
        "lora_proj_a": (jax.random.normal(kb, (L, d, r)) * 0.02).astype(
            jnp.float32),
        "lora_proj_b": jnp.zeros((L, r, d)),
    }


def add_lora_adapters(
    params: Dict[str, Any], cfg: GPTConfig, rng: jax.Array
) -> Dict[str, Any]:
    """Attach fresh LoRA adapters to a lora-free param tree (e.g. one
    imported from a HF checkpoint, ``utils/hf_import.py``) so it can
    warm-start a ``lora_rank > 0`` fit via ``module.initial_params``."""
    if cfg.lora_rank <= 0:
        return params
    if has_lora_adapters(params):
        # Overwriting would silently replace TRAINED adapters with
        # fresh zero-delta ones — reverting the model to the base.
        raise ValueError(
            "params already contain LoRA adapters; refusing to "
            "overwrite them. merge_lora() first, or reuse the existing "
            "adapters."
        )
    return {
        **params,
        "blocks": {**params["blocks"], **_init_lora_blocks(cfg, rng)},
    }


def extract_lora(
    params: Dict[str, Any], cfg: GPTConfig
) -> Tuple[Dict[str, Any], Dict[str, Any]]:
    """``(adapter, base_params)``: pull the four stacked LoRA factors
    out of a ``lora_rank > 0`` tree for multi-tenant serving.

    The adapter dict (``qkv_a/qkv_b/proj_a/proj_b`` + ``scale``) feeds
    :class:`~ray_lightning_tpu.serve.lora.AdapterPool`; ``base_params``
    is the same tree stripped of the adapters — the lora-free resident
    base every tenant shares (byte-identical across tenants fine-tuned
    from the same checkpoint, which is what makes one resident copy
    serve them all).  Inverse direction of :func:`merge_lora`: merge
    folds ONE tenant in forever, extract keeps the base shared.
    """
    if cfg.lora_rank <= 0:
        raise ValueError("extract_lora needs a lora_rank > 0 config")
    if not has_lora_adapters(params):
        raise ValueError(
            "params carry no LoRA adapters — nothing to extract"
        )
    blocks = dict(params["blocks"])
    adapter = {
        "qkv_a": blocks.pop("lora_qkv_a"),
        "qkv_b": blocks.pop("lora_qkv_b"),
        "proj_a": blocks.pop("lora_proj_a"),
        "proj_b": blocks.pop("lora_proj_b"),
        "scale": cfg.lora_alpha / cfg.lora_rank,
    }
    return adapter, {**params, "blocks": blocks}


def synthetic_lora_adapter(
    params: Dict[str, Any], cfg: GPTConfig, rng: jax.Array,
    scale: float = 0.3,
) -> Tuple[Dict[str, Any], Dict[str, Any]]:
    """``(adapter, merged_params)``: ONE synthetic LoRA tenant of a
    lora-free base — random non-zero A *and* B factors, so the tenant
    generates a visibly distinct greedy stream (``add_lora_adapters``
    alone zero-inits B: delta exactly 0, every "tenant" IS the base).

    The multi-tenant serving bench/example/test triple all need N
    distinct tenants plus each tenant's fully-merged tree as the
    parity reference; real tenants come out of a ``lora_rank > 0``
    fine-tune via :func:`extract_lora` instead.  ``cfg.lora_rank``
    must be > 0 (it is the adapter's rank).
    """
    ka, kq, kp = jax.random.split(rng, 3)
    tree = add_lora_adapters(params, cfg, ka)
    blocks = dict(tree["blocks"])
    blocks["lora_qkv_b"] = (
        jax.random.normal(kq, blocks["lora_qkv_b"].shape) * scale
    ).astype(blocks["lora_qkv_b"].dtype)
    blocks["lora_proj_b"] = (
        jax.random.normal(kp, blocks["lora_proj_b"].shape) * scale
    ).astype(blocks["lora_proj_b"].dtype)
    tree = {**tree, "blocks": blocks}
    adapter, _ = extract_lora(tree, cfg)
    return adapter, merge_lora(tree, cfg)


def merge_lora(params: Dict[str, Any], cfg: GPTConfig) -> Dict[str, Any]:
    """Fold LoRA adapters into the base weights and strip them.

    The result is a plain (lora-free) GPT param tree with identical
    forward math — the inference/generation path (``models/generate.py``
    consumes raw ``qkv_w``/``proj_w``) and any lora-unaware tooling run
    it unchanged.  Merged-weight logits equal the adapter-form logits in
    f32 exactly up to one fused-matmul reassociation.
    """
    if cfg.lora_rank <= 0:
        return params
    s = cfg.lora_alpha / cfg.lora_rank
    blocks = dict(params["blocks"])
    blocks["qkv_w"] = blocks["qkv_w"] + jnp.einsum(
        "ldr,lrk->ldk", blocks["lora_qkv_a"], blocks["lora_qkv_b"]
    ) * s
    blocks["proj_w"] = blocks["proj_w"] + jnp.einsum(
        "ldr,lrk->ldk", blocks["lora_proj_a"], blocks["lora_proj_b"]
    ) * s
    for k in ("lora_qkv_a", "lora_qkv_b", "lora_proj_a", "lora_proj_b"):
        blocks.pop(k)
    return {**params, "blocks": blocks}


def make_block_stage(cfg: GPTConfig, compute_dtype=jnp.float32):
    """Stage function for :func:`..parallel.pipeline.pipeline_apply`:
    ``(blocks_shard, x) -> x`` running a contiguous run of DENSE GPT
    blocks (any leading layer count — the pipeline shards the stacked
    layer axis).  The single source of the block math for the pipeline
    tests/example/dryrun; the training path keeps its own scan in
    :meth:`GPT.forward_hidden` (remat + MoE + sharding constraints).
    """
    if cfg.n_experts > 0:
        raise ValueError("make_block_stage covers dense blocks only")
    if cfg.lora_rank > 0:
        raise ValueError(
            "make_block_stage does not apply LoRA adapters; fold them "
            "with merge_lora(params, cfg) first (running unmerged would "
            "silently use the frozen base weights)"
        )

    def stage(blocks, x):
        b, t = x.shape[0], x.shape[1]
        c = compute_dtype
        x = x.astype(c)  # activations in the compute dtype throughout

        def body(x, p):
            h = _layer_norm(x, p["ln1_g"], p["ln1_b"])
            qkv = h @ p["qkv_w"].astype(c) + p["qkv_b"].astype(c)
            q, k, v = jnp.split(qkv, 3, axis=-1)
            att = causal_attention(
                *(z.reshape(b, t, cfg.n_head, cfg.head_dim)
                  for z in (q, k, v)), impl="xla",
            ).reshape(b, t, cfg.d_model)
            x = x + att @ p["proj_w"].astype(c) + p["proj_b"].astype(c)
            return _mlp_residual(x, p, c), None

        x, _ = jax.lax.scan(body, x, blocks)
        return x

    return stage


class SyntheticLMDataModule(TpuDataModule):
    """Deterministic synthetic token stream for smoke tests and benches.

    ≙ the reference's ``RandomDataset`` fixture pattern
    (``tests/utils.py:16-25``), extended to the LM batch contract.
    """

    def __init__(self, config: GPTConfig, batch_size: int = 8,
                 num_batches: int = 16, seed: int = 0):
        super().__init__()
        self.config = config
        self.batch_size = batch_size
        self.num_batches = num_batches
        self.seed = seed
        self._tokens: Optional[np.ndarray] = None

    def setup(self, stage: str) -> None:
        if self._tokens is None:
            rng = np.random.default_rng(self.seed)
            n = self.batch_size * self.num_batches
            self._tokens = rng.integers(
                0, self.config.vocab_size,
                size=(n, self.config.seq_len + 1),
            ).astype(np.int32)

    def _loader(self):
        from ray_lightning_tpu.core.data import ArrayDataset

        ds = ArrayDataset(tokens=self._tokens)
        return NumpyLoader(
            ds, batch_size=self.batch_size,
            shard_index=self.shard_index, num_shards=self.num_shards,
        )

    def train_dataloader(self):
        return self._loader()

    def val_dataloader(self):
        return self._loader()
