"""ResNet-18 for CIFAR-scale images (BASELINE config #3: ResNet-18 /
CIFAR-10 over a multi-host data-parallel mesh).

The reference framework has no vision model of its own — its examples lean
on torchvision/pl_bolts (reference ``examples/ray_ddp_example.py``,
``ray_ddp_sharded_example.py:62``); this module provides the in-framework
equivalent so the BASELINE grid is runnable end to end.

TPU-first design choices (not a torch translation):

* **NHWC layout** — XLA:TPU's native convolution layout; channels-last
  keeps the MXU fed without transposes.
* **GroupNorm instead of BatchNorm** — BatchNorm's running statistics need
  a mutable-state side channel and a cross-replica ``psum`` of batch
  moments every step; GroupNorm is stateless, batch-independent (so DP
  sharding never changes the math), and fuses into the surrounding
  elementwise ops.  This is the standard JAX/TPU substitution.
* **bf16-friendly** — parameters stay f32; the trainer's precision policy
  casts activations, and convs/matmuls land on the MXU in bf16.
* **Data parallel first** — conv channel counts are small (≤512), so
  ``param_partition_specs`` only annotates the classifier head for TP; the
  interesting axes for this model are data/fsdp (ZeRO), composed by
  ``parallel/sharding.py``.
"""

from __future__ import annotations

from typing import Any, Dict, Sequence

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax import lax
from jax.sharding import PartitionSpec as P

from ray_lightning_tpu.core.data import ArrayDataset, NumpyLoader, TpuDataModule
from ray_lightning_tpu.core.module import TpuModule

__all__ = ["ResNet", "CIFARDataModule"]


def _conv_init(key, kh, kw, cin, cout):
    fan_in = kh * kw * cin
    scale = float(np.sqrt(2.0 / fan_in))
    return jax.random.normal(key, (kh, kw, cin, cout)) * scale


def _conv(x, w, stride=1):
    return lax.conv_general_dilated(
        x, w,
        window_strides=(stride, stride),
        padding="SAME",
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
    )


def _group_norm(x, g, b, groups=8, eps=1e-5):
    n, h, w, c = x.shape
    xg = x.reshape(n, h, w, groups, c // groups)
    mean = xg.mean(axis=(1, 2, 4), keepdims=True)
    var = ((xg - mean) ** 2).mean(axis=(1, 2, 4), keepdims=True)
    xg = (xg - mean) * lax.rsqrt(var + eps)
    return xg.reshape(n, h, w, c) * g + b


class ResNet(TpuModule):
    """CIFAR-variant ResNet: 3×3 stem, 4 stages × ``depths`` basic blocks.

    ``ResNet()`` is ResNet-18 shaped (2-2-2-2 basic blocks, 64→512
    channels, ~11M params).
    """

    def __init__(
        self,
        depths: Sequence[int] = (2, 2, 2, 2),
        widths: Sequence[int] = (64, 128, 256, 512),
        num_classes: int = 10,
        lr: float = 1e-3,
        weight_decay: float = 5e-4,
        norm_groups: int = 8,
    ):
        super().__init__()
        self.save_hyperparameters(
            depths=tuple(depths), widths=tuple(widths),
            num_classes=num_classes, lr=lr, weight_decay=weight_decay,
            norm_groups=norm_groups,
        )

    # -- parameters ---------------------------------------------------
    def init_params(self, rng: jax.Array) -> Dict[str, Any]:
        h = self.hparams
        depths, widths = h["depths"], h["widths"]
        keys = iter(jax.random.split(rng, 4 + 4 * sum(depths) + 1))

        def norm(c):
            return {"g": jnp.ones((c,)), "b": jnp.zeros((c,))}

        params: Dict[str, Any] = {
            "stem": {"w": _conv_init(next(keys), 3, 3, 3, widths[0]),
                     "norm": norm(widths[0])},
        }
        cin = widths[0]
        for si, (d, cout) in enumerate(zip(depths, widths)):
            stage = []
            for bi in range(d):
                stride = 2 if (si > 0 and bi == 0) else 1
                block = {
                    "conv1": {"w": _conv_init(next(keys), 3, 3, cin, cout)},
                    "norm1": norm(cout),
                    "conv2": {"w": _conv_init(next(keys), 3, 3, cout, cout)},
                    "norm2": norm(cout),
                }
                if stride != 1 or cin != cout:
                    block["down"] = {
                        "w": _conv_init(next(keys), 1, 1, cin, cout),
                        "norm": norm(cout),
                    }
                stage.append(block)
                cin = cout
            params[f"stage{si}"] = stage
        fan_in = widths[-1]
        params["head"] = {
            "w": jax.random.normal(next(keys), (fan_in, h["num_classes"]))
            * float(np.sqrt(1.0 / fan_in)),
            "b": jnp.zeros((h["num_classes"],)),
        }
        return params

    def param_partition_specs(self) -> Dict[str, Any]:
        """TP annotations: only the classifier head is worth sharding at
        these widths; conv stacks stay replicated on the tensor axis (data
        and fsdp axes are layered on by the strategy)."""
        h = self.hparams

        def norm_spec():
            return {"g": P(), "b": P()}

        specs: Dict[str, Any] = {
            "stem": {"w": P(), "norm": norm_spec()},
            "head": {"w": P(None, "tensor"), "b": P("tensor")},
        }
        cin = h["widths"][0]
        for si, (d, cout) in enumerate(zip(h["depths"], h["widths"])):
            stage = []
            for bi in range(d):
                stride = 2 if (si > 0 and bi == 0) else 1
                block = {
                    "conv1": {"w": P()}, "norm1": norm_spec(),
                    "conv2": {"w": P()}, "norm2": norm_spec(),
                }
                if stride != 1 or cin != cout:
                    block["down"] = {"w": P(), "norm": norm_spec()}
                stage.append(block)
                cin = cout
            specs[f"stage{si}"] = stage
        return specs

    # -- forward ------------------------------------------------------
    def _block(self, p, x, stride, groups):
        out = _conv(x, p["conv1"]["w"], stride)
        out = _group_norm(out, p["norm1"]["g"], p["norm1"]["b"], groups)
        out = jax.nn.relu(out)
        out = _conv(out, p["conv2"]["w"], 1)
        out = _group_norm(out, p["norm2"]["g"], p["norm2"]["b"], groups)
        if "down" in p:
            x = _conv(x, p["down"]["w"], stride)
            x = _group_norm(x, p["down"]["norm"]["g"],
                            p["down"]["norm"]["b"], groups)
        return jax.nn.relu(out + x)

    def forward(self, params, x):
        h = self.hparams
        groups = h["norm_groups"]
        compute_dtype = (
            jnp.bfloat16 if getattr(self, "precision", "f32") == "bf16"
            else jnp.float32
        )
        x = x.astype(compute_dtype)
        cast = lambda t: jax.tree.map(  # noqa: E731
            lambda a: a.astype(compute_dtype), t)

        p = cast(params)
        x = _conv(x, p["stem"]["w"], 1)
        x = _group_norm(x, p["stem"]["norm"]["g"], p["stem"]["norm"]["b"],
                        groups)
        x = jax.nn.relu(x)
        for si in range(len(h["depths"])):
            for bi, block in enumerate(p[f"stage{si}"]):
                stride = 2 if (si > 0 and bi == 0) else 1
                x = self._block(block, x, stride, groups)
        x = x.mean(axis=(1, 2))  # global average pool
        logits = x @ p["head"]["w"] + p["head"]["b"]
        return logits.astype(jnp.float32)

    # -- steps --------------------------------------------------------
    def _loss_acc(self, params, batch):
        logits = self.forward(params, batch["x"])
        labels = batch["y"]
        loss = jnp.mean(
            optax.softmax_cross_entropy_with_integer_labels(logits, labels)
        )
        acc = jnp.mean((jnp.argmax(logits, -1) == labels).astype(jnp.float32))
        return loss, acc

    def training_step(self, params, batch, rng):
        loss, acc = self._loss_acc(params, batch)
        return loss, {"train_loss": loss, "train_accuracy": acc}

    def validation_step(self, params, batch):
        loss, acc = self._loss_acc(params, batch)
        return {"val_loss": loss, "val_accuracy": acc}

    def predict_step(self, params, batch):
        return jnp.argmax(self.forward(params, batch["x"]), axis=-1)

    def configure_optimizers(self):
        h = self.hparams
        return optax.chain(
            optax.add_decayed_weights(
                h["weight_decay"],
                mask=lambda params: jax.tree.map(
                    lambda a: a.ndim > 1, params),
            ),
            optax.adam(h["lr"]),
        )


class CIFARDataModule(TpuDataModule):
    """CIFAR-10-shaped data: real CIFAR if an npz is pointed at via
    ``data_path``, otherwise deterministic class-conditional synthetic
    images (zero-egress environments)."""

    def __init__(self, batch_size: int = 128, num_samples: int = 2048,
                 image_size: int = 32, num_classes: int = 10, seed: int = 0,
                 data_path: str | None = None):
        super().__init__()
        self.batch_size = batch_size
        self.num_samples = num_samples
        self.image_size = image_size
        self.num_classes = num_classes
        self.seed = seed
        self.data_path = data_path
        self._train: ArrayDataset | None = None
        self._val: ArrayDataset | None = None

    def _synthetic(self):
        rng = np.random.default_rng(self.seed)
        n, s = self.num_samples, self.image_size
        labels = rng.integers(0, self.num_classes, n).astype(np.int32)
        base = rng.standard_normal(
            (self.num_classes, s, s, 3), dtype=np.float32)
        imgs = base[labels] + 0.7 * rng.standard_normal(
            (n, s, s, 3), dtype=np.float32)
        return imgs, labels

    def setup(self, stage: str) -> None:
        if self._train is not None:
            return
        if self.data_path:
            blob = np.load(self.data_path)
            imgs = blob["x"].astype(np.float32)
            if imgs.ndim == 4 and imgs.shape[1] == 3:  # NCHW → NHWC
                imgs = imgs.transpose(0, 2, 3, 1)
            if imgs.max() > 2.0:
                imgs = imgs / 255.0
            labels = blob["y"].astype(np.int32)
        else:
            imgs, labels = self._synthetic()
        n_val = max(self.batch_size, len(imgs) // 10)
        self._val = ArrayDataset(x=imgs[:n_val], y=labels[:n_val])
        self._train = ArrayDataset(x=imgs[n_val:], y=labels[n_val:])

    def train_dataloader(self):
        return NumpyLoader(
            self._train, batch_size=self.batch_size, shuffle=True,
            seed=self.seed, shard_index=self.shard_index,
            num_shards=self.num_shards,
        )

    def val_dataloader(self):
        return NumpyLoader(
            self._val, batch_size=self.batch_size,
            shard_index=self.shard_index, num_shards=self.num_shards,
        )

    def test_dataloader(self):
        return self.val_dataloader()

    def predict_dataloader(self):
        return self.val_dataloader()
