from .boring import BoringModel, BoringDataModule, XORModel, XORDataModule
from .gpt import GPT, GPTConfig, SyntheticLMDataModule
from .mnist import MNISTClassifier, MNISTDataModule
from .resnet import ResNet, CIFARDataModule

__all__ = [
    "BoringModel",
    "BoringDataModule",
    "XORModel",
    "XORDataModule",
    "MNISTClassifier",
    "MNISTDataModule",
    "GPT",
    "GPTConfig",
    "SyntheticLMDataModule",
    "ResNet",
    "CIFARDataModule",
]
