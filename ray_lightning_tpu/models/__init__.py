from .boring import BoringModel, BoringDataModule, XORModel, XORDataModule
from .data_text import ByteLMDataModule, decode_bytes
from .generate import decode_step, generate, init_kv_cache, prefill
from .gpt import (
    GPT,
    GPTConfig,
    SyntheticLMDataModule,
    add_lora_adapters,
    extract_lora,
    merge_lora,
    synthetic_lora_adapter,
)
from .mnist import MNISTClassifier, MNISTDataModule
from .quant import is_quantized, quantize_decode_params
from .resnet import ResNet, CIFARDataModule
from .vit import ViT, ViTConfig

__all__ = [
    "decode_step",
    "generate",
    "init_kv_cache",
    "prefill",
    "BoringModel",
    "BoringDataModule",
    "ByteLMDataModule",
    "decode_bytes",
    "XORModel",
    "XORDataModule",
    "MNISTClassifier",
    "MNISTDataModule",
    "GPT",
    "GPTConfig",
    "SyntheticLMDataModule",
    "add_lora_adapters",
    "extract_lora",
    "merge_lora",
    "synthetic_lora_adapter",
    "ResNet",
    "CIFARDataModule",
    "ViT",
    "ViTConfig",
    "is_quantized",
    "quantize_decode_params",
]
