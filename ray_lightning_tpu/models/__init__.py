from .boring import BoringModel, BoringDataModule, XORModel, XORDataModule
from .gpt import GPT, GPTConfig, SyntheticLMDataModule
from .mnist import MNISTClassifier, MNISTDataModule

__all__ = [
    "BoringModel",
    "BoringDataModule",
    "XORModel",
    "XORDataModule",
    "MNISTClassifier",
    "MNISTDataModule",
    "GPT",
    "GPTConfig",
    "SyntheticLMDataModule",
]
