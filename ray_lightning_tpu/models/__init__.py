from .boring import BoringModel, BoringDataModule, XORModel, XORDataModule

__all__ = [
    "BoringModel",
    "BoringDataModule",
    "XORModel",
    "XORDataModule",
]
