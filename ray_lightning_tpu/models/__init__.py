from .boring import BoringModel, BoringDataModule, XORModel, XORDataModule
from .mnist import MNISTClassifier, MNISTDataModule

__all__ = [
    "BoringModel",
    "BoringDataModule",
    "XORModel",
    "XORDataModule",
    "MNISTClassifier",
    "MNISTDataModule",
]
