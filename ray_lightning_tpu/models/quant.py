"""Weight-only int8 quantization for the decode path.

Single-token decode is HBM-bandwidth-bound: every generated token
re-reads every weight matrix, so weight bytes ARE the decode cost.
Symmetric per-output-channel int8 storage halves the weight traffic vs
bf16 (4x vs f32) while activations, cache, and all math stay in the
compute dtype — XLA fuses the ``int8 -> compute-dtype`` convert and the
per-channel scale into the matmul's operand read, so no dequantized
copy of the weights ever lands in HBM.

Scope: inference only.  ``quantize_decode_params`` produces a tree the
generation path (``models/generate.py``) consumes transparently — a
quantized weight ``w`` is stored as ``w_q8`` (int8) + ``w_sc`` (f32
per-output-channel scales) and resolved by :func:`resolve_weight`.
Warm-starting a fit from such a tree (``module.initial_params``) is
rejected with a clear error — the optimizer cannot step int8 storage,
and silently dequantizing would train an already-rounded model.
"""

from __future__ import annotations

from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp

__all__ = [
    "quantize_decode_params",
    "dequantize_decode_params",
    "resolve_weight",
    "is_quantized",
]

# Weights worth quantizing: the 2-D+ matmul operands.  Biases, LN
# params, and the positional table stay f32 (tiny, and bias precision
# is cheap accuracy).
_QUANT_BLOCK_KEYS = ("qkv_w", "proj_w", "mlp_in_w", "mlp_out_w")


def _quantize(w: jax.Array, contract_axis: int) -> Tuple[jax.Array, jax.Array]:
    """Symmetric int8 over ``contract_axis`` (the input/contraction dim):
    one f32 scale per OUTPUT channel, so the matmul result is exact up
    to the 8-bit mantissa of each channel."""
    amax = jnp.max(jnp.abs(w), axis=contract_axis, keepdims=True)
    scale = jnp.where(amax > 0, amax / 127.0, 1.0).astype(jnp.float32)
    q = jnp.clip(jnp.round(w / scale), -127, 127).astype(jnp.int8)
    return q, jnp.squeeze(scale, axis=contract_axis)


def is_quantized(params: Dict[str, Any]) -> bool:
    return any(
        str(k).endswith("_q8") for k in params.get("blocks", {})
    ) or "wte_q8" in params


def resolve_weight(tree: Dict[str, Any], name: str, compute_dtype):
    """``tree[name]`` in ``compute_dtype`` — dequantizing on the fly when
    the tree carries int8 storage.  The convert+scale fuses into the
    consuming matmul; int8 is what HBM streams."""
    q = tree.get(name + "_q8")
    if q is None:
        return tree[name].astype(compute_dtype)
    sc = tree[name + "_sc"].astype(compute_dtype)
    # Scales are per OUTPUT channel; re-insert the contraction axis so
    # they broadcast against (…, d_in, d_out) storage of any rank
    # (plain (d,k), stacked (L,d,k), expert-stacked (L,E,d,h)).
    return q.astype(compute_dtype) * sc[..., None, :]


def quantize_decode_params(
    params: Dict[str, Any], cfg
) -> Dict[str, Any]:
    """Int8-storage copy of a GPT param tree for generation.

    Block matmul weights quantize per output channel over the
    contraction dim; ``wte`` quantizes per vocab ROW (correct for both
    the embedding lookup and the tied LM-head contraction, which reduce
    over d_model).  Everything else passes through.  LoRA trees must be
    merged first (adapters would silently be dropped otherwise).

    ``cfg`` is currently unused — which weights quantize is keyed on
    TREE contents, never config (a cfg/tree mismatch must not skip
    weights) — but stays in the signature for symmetry with the other
    param-tree transforms (``merge_lora``/``add_lora_adapters``) and
    future config-dependent choices (e.g. per-family bit widths).
    """
    from ray_lightning_tpu.models.gpt import has_lora_adapters

    if has_lora_adapters(params):
        raise ValueError(
            "params contain LoRA adapters; merge_lora(params, cfg) "
            "before quantizing for decode"
        )
    if is_quantized(params):
        raise ValueError("params are already int8-quantized")
    blocks = dict(params["blocks"])
    # Keyed on TREE contents, not cfg: a cfg/tree mismatch must never
    # silently leave the dominant (expert) weights unquantized.
    quant_keys = _QUANT_BLOCK_KEYS + ("moe_in_w", "moe_out_w")
    for key in quant_keys:
        if key not in blocks:
            continue
        w = blocks.pop(key)
        # Leading dims (layer L, expert E) are per-matrix; the
        # contraction dim is axis -2 for every (…, d_in, d_out) weight.
        q, sc = _quantize(jnp.asarray(w), contract_axis=-2)
        blocks[key + "_q8"] = q
        blocks[key + "_sc"] = sc
    out = {**params, "blocks": blocks}
    wte = out.pop("wte")
    # Per-row scales: both consumers (lookup, tied-head einsum over d)
    # contract/select over the feature dim, never across rows.
    q, sc = _quantize(jnp.asarray(wte), contract_axis=-1)
    out["wte_q8"] = q
    out["wte_sc"] = sc
    return out


def dequantize_decode_params(params: Dict[str, Any]) -> Dict[str, Any]:
    """Fold int8 storage back into dense f32 weights.

    Exactly the :func:`resolve_weight` / ``_wte`` arithmetic, applied
    ONCE instead of at every consumption site.  Used by the generation
    path to hoist the dequant out of the decode scan on backends where
    weight bytes are not the decode bottleneck (CPU: the per-token
    ``int8 → f32`` convert costs more than the bandwidth it saves —
    BENCH_r05 measured the int8 tree 17% SLOWER there).  The rounding
    already baked into the int8 storage is kept — this is a placement
    change, not a precision change.
    """
    if not is_quantized(params):
        return params
    blocks = dict(params["blocks"])
    for key in [k for k in blocks if str(k).endswith("_q8")]:
        base = key[: -len("_q8")]
        q = blocks.pop(key)
        sc = blocks.pop(base + "_sc")
        blocks[base] = q.astype(jnp.float32) * sc[..., None, :]
    out = {
        k: v for k, v in params.items()
        if k not in ("blocks", "wte_q8", "wte_sc")
    }
    out["blocks"] = blocks
    if "wte_q8" in params:
        out["wte"] = (params["wte_q8"].astype(jnp.float32)
                      * params["wte_sc"].astype(jnp.float32)[:, None])
    return out
