"""Autoregressive decoding for the GPT family: KV cache + sampling.

The reference's inference story ends at ``predict_step`` (batch argmax);
a usable LM needs a decode loop.  TPU-first shape discipline throughout:

* **Static shapes**: the KV cache is allocated once at ``total_len`` and
  written with ``lax.dynamic_update_slice`` — no growing arrays, so the
  whole generation is ONE ``lax.scan`` under ``jit`` (no per-token
  retrace, no host round-trips).
* **Stacked layers**: the cache carries a leading ``n_layer`` axis, and
  the per-token block pass is a ``lax.scan`` over (block params, cache
  layer) pairs — same compile-once-per-depth property as the training
  trunk.
* **Fused prefill**: the prompt runs through ONE full-sequence causal
  pass (:func:`prefill`) that writes every prompt slot of the cache in
  a single MXU-friendly batch — the decode scan then covers only the
  new tokens.  Both paths keep the softmax·V product in f32, so they
  match the training forward exactly in f32; under bf16 kernels they
  can differ at near-tie logits (inference is the higher-precision one).
* **Sampling**: greedy, temperature, top-k and nucleus (top-p) — all
  shape-static so the whole generation stays inside one jit.

MoE models decode through the same routed-MLP math as training
(``groups=1``); see :func:`generate` for the capacity-competition
caveat.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from ray_lightning_tpu.models.gpt import (
    GPT, GPTConfig, _layer_norm, _mlp_residual, _moe_residual,
)
from ray_lightning_tpu.models.quant import resolve_weight
from ray_lightning_tpu.ops.attention import _NEG_INF

__all__ = ["init_kv_cache", "prefill", "decode_step", "generate"]


def init_kv_cache(
    cfg: GPTConfig, batch: int, total_len: int, dtype=jnp.float32
) -> Dict[str, jax.Array]:
    """(L, B, total_len, H, Dh) zero-filled key/value buffers."""
    shape = (cfg.n_layer, batch, total_len, cfg.n_head, cfg.head_dim)
    return {"k": jnp.zeros(shape, dtype), "v": jnp.zeros(shape, dtype)}


def _block_pass(
    cfg: GPTConfig,
    p: Dict[str, Any],
    x: jax.Array,
    k_l: jax.Array,
    v_l: jax.Array,
    off,
    c,
    ad: Optional[Dict[str, jax.Array]] = None,
    ad_ids: Optional[jax.Array] = None,
    lora_impl: str = "xla",
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """One GPT block over ``x (B, T, d)`` against a KV cache layer.

    Writes this chunk's k/v into cache slots ``[off, off + T)`` and
    attends each query ``t`` over cache slots ``<= off + t`` (unwritten
    slots are masked, so their zero-fill never contributes).  The SAME
    code path serves full-prompt prefill (``T = T0, off = 0``) and
    single-token decode (``T = 1, off = pos``) — block math has one
    source, and numerics (f32 scores/softmax/PV) are identical by
    construction.

    ``ad``/``ad_ids`` (multi-tenant LoRA, ``serve/lora.py``): one
    layer's stacked adapter factors plus a per-SEQUENCE int32 slot id
    operand — each row's own adapter delta is added to the qkv/proj
    projections via the gathered BGMV (``ops/lora.py``), slot 0 being
    the zero-delta base model.  ``None`` (every non-serving caller)
    leaves the graph byte-identical to pre-LoRA rounds.
    """
    from ray_lightning_tpu.ops.lora import apply_lora

    B, T = x.shape[0], x.shape[1]
    h = _layer_norm(x, p["ln1_g"], p["ln1_b"])
    qkv = h @ resolve_weight(p, "qkv_w", c) + p["qkv_b"].astype(c)
    qkv = apply_lora(qkv, h, ad, "qkv", ad_ids, lora_impl)
    q, k, v = jnp.split(qkv, 3, axis=-1)

    def heads(z):
        return z.reshape(B, T, cfg.n_head, cfg.head_dim)

    k_l = jax.lax.dynamic_update_slice(
        k_l, heads(k).astype(k_l.dtype), (0, off, 0, 0)
    )
    v_l = jax.lax.dynamic_update_slice(
        v_l, heads(v).astype(v_l.dtype), (0, off, 0, 0)
    )
    S = k_l.shape[1]
    scale = cfg.head_dim ** -0.5
    scores = jnp.einsum(
        "bqhd,bshd->bhqs", heads(q).astype(jnp.float32),
        k_l.astype(jnp.float32),
    ) * scale
    visible = jnp.arange(S)[None, :] <= (off + jnp.arange(T))[:, None]
    scores = jnp.where(visible[None, None], scores, _NEG_INF)
    probs = jax.nn.softmax(scores, axis=-1)
    att = jnp.einsum(
        "bhqs,bshd->bqhd", probs, v_l.astype(jnp.float32)
    ).reshape(B, T, cfg.d_model).astype(c)
    proj = att @ resolve_weight(p, "proj_w", c) + p["proj_b"].astype(c)
    proj = apply_lora(proj, att, ad, "proj", ad_ids, lora_impl)
    x = x + proj
    if cfg.n_experts > 0:
        # Same routed-MLP math as training (groups=1 — inference is
        # chip-local).  Capacity competition is per ROUTED SET: the full
        # forward routes all B*T prompt tokens together, decode routes
        # the B current tokens — identical decisions whenever capacity
        # doesn't saturate (see generate() docstring).
        x, _ = _moe_residual(x, p, cfg, groups=1)
        return x, k_l, v_l
    return _mlp_residual(x, p, c), k_l, v_l


def _trunk_blocks(cfg, params, cache, x, off, c,
                  adapters=None, adapter_ids=None, lora_impl="xla"):
    """Scan :func:`_block_pass` over the stacked layers; return the
    pre-``ln_f`` hidden for EVERY position and the updated cache.

    The building block shared by :func:`_trunk_pass` (full forward →
    last-position logits) and the serving plane's bucketed prefill
    (``serve/kv_cache.py`` needs the hidden at the last *valid* prompt
    position of a padded bucket, not the last slot).  ``adapters``
    (stacked per-layer LoRA factor buffers, leading axis L) rides the
    scan xs exactly like ``params["blocks"]``; ``None`` keeps the
    graph byte-identical to pre-LoRA rounds (the trace-time unpack is
    the same one-body shape the paged decode/verify programs use)."""

    def block(carry, layer):
        x, = carry
        if adapters is None:
            p, k_l, v_l = layer
            ad = None
        else:
            p, k_l, v_l, ad = layer
        x, k_l, v_l = _block_pass(cfg, p, x, k_l, v_l, off, c,
                                  ad=ad, ad_ids=adapter_ids,
                                  lora_impl=lora_impl)
        return (x,), (k_l, v_l)

    xs = (params["blocks"], cache["k"], cache["v"])
    if adapters is not None:
        xs = xs + (adapters,)
    (x,), (k_new, v_new) = jax.lax.scan(block, (x,), xs)
    return x, {"k": k_new, "v": v_new}


def _head_logits(params, h, c):
    """``ln_f`` + tied LM head on hidden ``(..., d)`` → logits
    ``(..., V)`` f32 (int8-storage aware via :func:`_wte`)."""
    h = _layer_norm(h, params["ln_f_g"], params["ln_f_b"])
    return jnp.einsum(
        "...d,vd->...v", h, _wte(params, c),
        preferred_element_type=jnp.float32,
    )


def _trunk_pass(cfg, params, cache, x, off, c):
    """Scan :func:`_block_pass` over the stacked layers; return the
    final LN'd last-position logits and the updated cache."""
    x, cache = _trunk_blocks(cfg, params, cache, x, off, c)
    return _head_logits(params, x[:, -1], c), cache


def _wte(params, c):
    """Token embedding table in compute dtype (int8-storage aware)."""
    if "wte_q8" in params:
        # Per-row scales broadcast over the feature dim.
        return (params["wte_q8"].astype(c)
                * params["wte_sc"].astype(c)[:, None])
    return params["wte"].astype(c)


def _embed(params, tokens, c):
    """Embedding lookup in compute dtype (int8-storage aware): gather
    the int8 rows, then scale — only the LOOKED-UP rows are converted,
    never the whole table."""
    if "wte_q8" in params:
        return (params["wte_q8"][tokens].astype(c)
                * params["wte_sc"][tokens].astype(c)[..., None])
    return params["wte"][tokens].astype(c)


def _reject_unmerged_lora(params: Dict[str, Any]) -> None:
    """The BASE-model decode math consumes raw ``qkv_w``/``proj_w``
    only; a LoRA-bearing tree passed as the base would silently
    generate from the frozen base weights — the one truly-unsupported
    case, rejected here at every public inference entry (trace-time
    cost only — it inspects dict keys, not values).  Serving adapters
    is supported, just not THIS way: the adapter pool applies them as
    per-slot operands over one resident base (docs/SERVING.md
    "Multi-tenant LoRA")."""
    from ray_lightning_tpu.models.gpt import has_lora_adapters

    if has_lora_adapters(params):
        raise ValueError(
            "params contain LoRA adapters, which the base-model decode "
            "path does not apply — running them would silently generate "
            "from the frozen base weights. Either fold ONE tenant in "
            "(params = merge_lora(params, cfg)) or serve MANY tenants "
            "over the shared base through the adapter pool: "
            "adapter, base = extract_lora(params, cfg); "
            "ServeEngine(module, base, ServeConfig(max_adapters=N, "
            "adapter_rank=cfg.lora_rank), adapters={name: adapter}) — "
            "see docs/SERVING.md 'Multi-tenant LoRA'."
        )


def prefill(
    cfg: GPTConfig,
    params: Dict[str, Any],
    cache: Dict[str, jax.Array],
    tokens: jax.Array,
    compute_dtype=jnp.float32,
) -> Tuple[jax.Array, Dict[str, jax.Array]]:
    """Full-sequence prompt pass: ``tokens (B, T0)`` → ``(last-position
    logits (B, V) f32, cache with slots [0, T0) filled)``.

    One causal-attention batch over the whole prompt instead of ``T0``
    sequential single-token steps — the matmuls stay large for the MXU
    and the cache is written once per layer.
    """
    _reject_unmerged_lora(params)
    c = compute_dtype
    T = tokens.shape[1]
    x = _embed(params, tokens, c) + params["wpe"][:T].astype(c)
    return _trunk_pass(cfg, params, cache, x, 0, c)


def decode_step(
    cfg: GPTConfig,
    params: Dict[str, Any],
    cache: Dict[str, jax.Array],
    tokens: jax.Array,
    pos: jax.Array,
    compute_dtype=jnp.float32,
) -> Tuple[jax.Array, Dict[str, jax.Array]]:
    """One token per sequence: ``tokens (B,) at position pos`` →
    ``(logits (B, V) f32, updated cache)``."""
    _reject_unmerged_lora(params)
    c = compute_dtype
    x = (_embed(params, tokens, c)
         + params["wpe"][pos].astype(c))[:, None]
    return _trunk_pass(cfg, params, cache, x, pos, c)


def _sample(
    logits: jax.Array,
    rng: jax.Array,
    temperature: float,
    top_k: Optional[int],
    top_p: Optional[float],
) -> jax.Array:
    """One sampling decision per row of ``logits (B, V)`` → ``(B,)``.

    All filtering is shape-static (mask to ``_NEG_INF``, never shrink the
    vocab axis) so the caller's scan stays a single compiled program.
    """
    if temperature <= 0.0:
        return jnp.argmax(logits, axis=-1)
    logits = logits / temperature
    if top_k is not None:
        kth = jax.lax.top_k(
            logits, min(top_k, logits.shape[-1])
        )[0][..., -1:]
        logits = jnp.where(logits < kth, _NEG_INF, logits)
    if top_p is not None:
        sorted_desc = jnp.sort(logits, axis=-1)[..., ::-1]
        probs = jax.nn.softmax(sorted_desc, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        # Keep tokens whose EXCLUSIVE cumulative mass is < top_p: the
        # nucleus always includes the top token and stops once the kept
        # mass first reaches top_p.
        keep = (cum - probs) < top_p
        num_keep = keep.sum(axis=-1, keepdims=True)
        thresh = jnp.take_along_axis(sorted_desc, num_keep - 1, axis=-1)
        logits = jnp.where(logits < thresh, _NEG_INF, logits)
    return jax.random.categorical(rng, logits)


def generate(
    module: GPT,
    params: Dict[str, Any],
    prompt: jax.Array,
    max_new_tokens: int,
    temperature: float = 0.0,
    top_k: Optional[int] = None,
    top_p: Optional[float] = None,
    rng: Optional[jax.Array] = None,
    eos_token_id: Optional[int] = None,
) -> jax.Array:
    """Greedy (``temperature=0``), temperature, top-k and/or top-p
    (nucleus) sampling.  Prompt slots fill via one fused :func:`prefill`
    pass; the decode scan covers only the new tokens.

    Args:
        prompt: ``(B, T0)`` int32, ``T0 >= 1``.
        top_k: keep only the k highest-probability tokens (``>= 1``).
        top_p: keep the smallest set of tokens whose probability mass
            reaches ``top_p`` (``0 < top_p <= 1``).  Composes with
            ``top_k`` (k-filter first, as in the usual HF semantics).
        rng: sampling key.  Defaults to ``PRNGKey(0)`` — deterministic,
            so repeated calls return the SAME sample; pass a fresh key
            per call for diverse samples.
        eos_token_id: once a sequence samples this token every later
            position repeats it (the sequence is *finished*).  Shapes
            stay static under jit — the scan still runs ``max_new_tokens``
            steps — but finished rows stop changing, the standard
            XLA-friendly stopping semantics.

    MoE models decode with the same routed-MLP math as training
    (``groups=1``).  Caveat: expert-capacity competition happens per
    routed set — training/prefill routes a whole ``(B, T)`` batch while
    decode routes the ``B`` current tokens — so token drops can differ
    when capacity saturates; with headroom
    (``capacity_factor >= n_experts`` guarantees zero drops) decode
    matches the full forward exactly (tested).
    Returns:
        ``(B, T0 + max_new_tokens)`` int32 — prompt followed by the
        generated continuation.
    """
    cfg = module.config
    _reject_unmerged_lora(params)
    B, t0 = prompt.shape
    if t0 < 1:
        raise ValueError("prompt must contain at least one token")
    if max_new_tokens < 0:
        raise ValueError(f"max_new_tokens must be >= 0, got {max_new_tokens}")
    if top_k is not None and top_k < 1:
        raise ValueError(f"top_k must be >= 1, got {top_k}")
    if top_p is not None and not 0.0 < top_p <= 1.0:
        raise ValueError(f"top_p must be in (0, 1], got {top_p}")
    if (top_k is not None or top_p is not None) and temperature <= 0.0:
        raise ValueError(
            "top_k/top_p require temperature > 0 (temperature=0 is "
            "greedy decoding, which would silently ignore them)"
        )
    if (eos_token_id is not None
            and not 0 <= eos_token_id < cfg.vocab_size):
        raise ValueError(
            f"eos_token_id {eos_token_id} outside vocab "
            f"[0, {cfg.vocab_size}) — stopping would silently never "
            f"trigger"
        )
    total = t0 + max_new_tokens
    if total > cfg.seq_len:
        raise ValueError(
            f"prompt ({t0}) + max_new_tokens ({max_new_tokens}) exceeds "
            f"the positional table ({cfg.seq_len})"
        )
    # Accept host pytrees (e.g. ``trainer.params``) as well as device
    # arrays: numpy leaves cannot be gather-indexed by traced tokens.
    params = jax.tree.map(jnp.asarray, params)
    # Int8 weight-only storage pays off where decode is HBM-bandwidth
    # bound (TPU: int8 is what HBM streams, the convert fuses into the
    # matmul).  Off-TPU the per-token dequant inside the decode scan
    # COSTS more than the bandwidth it saves (BENCH_r05: 3345.7 int8 vs
    # 4025.3 fp tokens/s on CPU), so hoist it: dequantize ONCE per call,
    # outside the scan — same math, amortized over every generated
    # token.
    from ray_lightning_tpu.models.quant import (
        dequantize_decode_params, is_quantized,
    )

    if is_quantized(params) and jax.default_backend() != "tpu":
        params = dequantize_decode_params(params)
    prompt = jnp.asarray(prompt).astype(jnp.int32)
    if max_new_tokens == 0:
        return prompt
    c = module._compute_dtype()
    cache = init_kv_cache(cfg, B, total, dtype=c)
    if rng is None:
        rng = jax.random.PRNGKey(0)

    logits, cache = prefill(cfg, params, cache, prompt, compute_dtype=c)
    rng, sub = jax.random.split(rng)
    first = _sample(logits, sub, temperature, top_k, top_p)
    first = first.astype(jnp.int32)
    done0 = (
        first == eos_token_id if eos_token_id is not None
        else jnp.zeros((B,), bool)
    )

    def step(carry, t):
        cache, cur, rng, done = carry
        logits, cache = decode_step(
            cfg, params, cache, cur, t, compute_dtype=c
        )
        rng, sub = jax.random.split(rng)
        nxt = _sample(logits, sub, temperature, top_k, top_p)
        nxt = nxt.astype(jnp.int32)
        if eos_token_id is not None:
            # Finished rows keep emitting eos; the row freezes.
            nxt = jnp.where(done, jnp.int32(eos_token_id), nxt)
            done = done | (nxt == eos_token_id)
        return (cache, nxt, rng, done), nxt

    # Positions t0 .. total-2 emit tokens t0+1 .. total-1.
    (_, _, _, _), rest = jax.lax.scan(
        step, (cache, first, rng, done0), jnp.arange(t0, total - 1)
    )
    return jnp.concatenate([prompt, first[:, None], rest.T], axis=1)
