"""Autoregressive decoding for the GPT family: KV cache + sampling.

The reference's inference story ends at ``predict_step`` (batch argmax);
a usable LM needs a decode loop.  TPU-first shape discipline throughout:

* **Static shapes**: the KV cache is allocated once at ``total_len`` and
  written with ``lax.dynamic_update_slice`` — no growing arrays, so the
  whole generation is ONE ``lax.scan`` under ``jit`` (no per-token
  retrace, no host round-trips).
* **Stacked layers**: the cache carries a leading ``n_layer`` axis, and
  the per-token block pass is a ``lax.scan`` over (block params, cache
  layer) pairs — same compile-once-per-depth property as the training
  trunk.
* **Prompt prefill runs through the same decode step** (teacher-forced
  token feed), which keeps the code single-path.  Decode keeps the
  softmax·V product in f32, so it matches the training forward exactly
  in f32; under bf16 kernels the two paths can differ at near-tie
  logits (decode is the higher-precision one).  A fused full-sequence
  prefill is the obvious optimization when prompt throughput matters.

Dense blocks only (MoE decode needs single-token routing — refused
loudly rather than silently mis-batched).
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from ray_lightning_tpu.models.gpt import (
    GPT, GPTConfig, _layer_norm, _mlp_residual,
)
from ray_lightning_tpu.ops.attention import _NEG_INF

__all__ = ["init_kv_cache", "decode_step", "generate"]


def init_kv_cache(
    cfg: GPTConfig, batch: int, total_len: int, dtype=jnp.float32
) -> Dict[str, jax.Array]:
    """(L, B, total_len, H, Dh) zero-filled key/value buffers."""
    shape = (cfg.n_layer, batch, total_len, cfg.n_head, cfg.head_dim)
    return {"k": jnp.zeros(shape, dtype), "v": jnp.zeros(shape, dtype)}


def decode_step(
    cfg: GPTConfig,
    params: Dict[str, Any],
    cache: Dict[str, jax.Array],
    tokens: jax.Array,
    pos: jax.Array,
    compute_dtype=jnp.float32,
) -> Tuple[jax.Array, Dict[str, jax.Array]]:
    """One token per sequence: ``tokens (B,) at position pos`` →
    ``(logits (B, V) f32, updated cache)``."""
    c = compute_dtype
    B = tokens.shape[0]
    x = (params["wte"][tokens] + params["wpe"][pos]).astype(c)  # (B, d)
    total_len = cache["k"].shape[2]
    # Causal visibility for this token: cache slots [0, pos].
    visible = jnp.arange(total_len) <= pos  # (S,)

    def block(carry, layer):
        x, = carry
        p, k_l, v_l = layer
        h = _layer_norm(x, p["ln1_g"], p["ln1_b"])
        qkv = h @ p["qkv_w"].astype(c) + p["qkv_b"].astype(c)
        q, k, v = jnp.split(qkv, 3, axis=-1)

        def heads(z):
            return z.reshape(B, cfg.n_head, cfg.head_dim)

        # Write this token's k/v into the cache slot.
        k_l = jax.lax.dynamic_update_slice(
            k_l, heads(k)[:, None].astype(k_l.dtype), (0, pos, 0, 0)
        )
        v_l = jax.lax.dynamic_update_slice(
            v_l, heads(v)[:, None].astype(v_l.dtype), (0, pos, 0, 0)
        )
        scale = cfg.head_dim ** -0.5
        scores = jnp.einsum(
            "bhd,bshd->bhs", heads(q).astype(jnp.float32),
            k_l.astype(jnp.float32),
        ) * scale
        scores = jnp.where(visible[None, None, :], scores, _NEG_INF)
        probs = jax.nn.softmax(scores, axis=-1)
        att = jnp.einsum(
            "bhs,bshd->bhd", probs, v_l.astype(jnp.float32)
        ).reshape(B, cfg.d_model).astype(c)
        x = x + att @ p["proj_w"].astype(c) + p["proj_b"].astype(c)
        x = _mlp_residual(x, p, c)
        return (x,), (k_l, v_l)

    (x,), (k_new, v_new) = jax.lax.scan(
        block, (x,), (params["blocks"], cache["k"], cache["v"])
    )
    x = _layer_norm(x, params["ln_f_g"], params["ln_f_b"])
    logits = jnp.einsum(
        "bd,vd->bv", x, params["wte"].astype(c),
        preferred_element_type=jnp.float32,
    )
    return logits, {"k": k_new, "v": v_new}


def generate(
    module: GPT,
    params: Dict[str, Any],
    prompt: jax.Array,
    max_new_tokens: int,
    temperature: float = 0.0,
    rng: Optional[jax.Array] = None,
) -> jax.Array:
    """Greedy (``temperature=0``) or temperature sampling.

    Args:
        prompt: ``(B, T0)`` int32, ``T0 >= 1``.
    Returns:
        ``(B, T0 + max_new_tokens)`` int32 — prompt followed by the
        generated continuation.
    """
    cfg = module.config
    if cfg.n_experts > 0:
        raise NotImplementedError(
            "generate() covers dense GPT blocks; MoE decode needs "
            "single-token routing"
        )
    B, t0 = prompt.shape
    if max_new_tokens < 0:
        raise ValueError(f"max_new_tokens must be >= 0, got {max_new_tokens}")
    total = t0 + max_new_tokens
    if total > cfg.seq_len:
        raise ValueError(
            f"prompt ({t0}) + max_new_tokens ({max_new_tokens}) exceeds "
            f"the positional table ({cfg.seq_len})"
        )
    c = module._compute_dtype()
    # Accept host pytrees (e.g. ``trainer.params``) as well as device
    # arrays: numpy leaves cannot be gather-indexed by traced tokens.
    params = jax.tree.map(jnp.asarray, params)
    prompt = jnp.asarray(prompt)
    cache = init_kv_cache(cfg, B, total, dtype=c)
    if rng is None:
        rng = jax.random.PRNGKey(0)

    def step(carry, t):
        cache, cur, rng = carry
        logits, cache = decode_step(
            cfg, params, cache, cur, t, compute_dtype=c
        )
        rng, sub = jax.random.split(rng)
        if temperature > 0.0:
            sampled = jax.random.categorical(sub, logits / temperature)
        else:
            sampled = jnp.argmax(logits, axis=-1)
        # Teacher-force the prompt region; sample past it.
        forced = prompt[:, jnp.minimum(t + 1, t0 - 1)]
        nxt = jnp.where(t + 1 < t0, forced, sampled).astype(jnp.int32)
        return (cache, nxt, rng), nxt

    (_, _, _), out = jax.lax.scan(
        step, (cache, prompt[:, 0], rng), jnp.arange(total - 1)
    )
    # out[t] is the token at position t+1.
    return jnp.concatenate([prompt[:, :1], out.T], axis=1)
