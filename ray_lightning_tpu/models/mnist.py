"""MNIST classifier (≙ reference ``LightningMNISTClassifier``,
``tests/utils.py:99-148``, and the MNIST examples).

Architecture parity: the reference is a 784→128→256→10 MLP with ReLU and
cross-entropy (``tests/utils.py:108-115``).  Data: with zero network
egress, real MNIST may be unavailable, so the datamodule defaults to the
sklearn 8×8 digits set (a real handwritten-digit dataset shipped with
sklearn) upsampled to 28×28, and falls back to synthetic class-conditional
images if sklearn is missing.  The loss/optimizer/metric surface matches
the reference exactly.
"""

from __future__ import annotations

from typing import Any, Dict

import jax
import jax.numpy as jnp
import numpy as np
import optax

from ray_lightning_tpu.core.data import ArrayDataset, NumpyLoader, TpuDataModule
from ray_lightning_tpu.core.module import TpuModule

__all__ = ["MNISTClassifier", "MNISTDataModule"]


class MNISTClassifier(TpuModule):
    """784→128→256→10 MLP (reference ``tests/utils.py:108-115``)."""

    def __init__(self, hidden_1: int = 128, hidden_2: int = 256,
                 lr: float = 1e-3, num_classes: int = 10):
        super().__init__()
        self.save_hyperparameters(
            hidden_1=hidden_1, hidden_2=hidden_2, lr=lr,
            num_classes=num_classes,
        )

    def init_params(self, rng: jax.Array) -> Dict[str, Any]:
        h = self.hparams
        k1, k2, k3 = jax.random.split(rng, 3)

        def dense(key, fan_in, fan_out):
            scale = float(np.sqrt(2.0 / fan_in))
            return {
                "w": jax.random.normal(key, (fan_in, fan_out)) * scale,
                "b": jnp.zeros((fan_out,)),
            }

        return {
            "l1": dense(k1, 784, h["hidden_1"]),
            "l2": dense(k2, h["hidden_1"], h["hidden_2"]),
            "l3": dense(k3, h["hidden_2"], h["num_classes"]),
        }

    def _forward(self, params, x):
        x = x.reshape((x.shape[0], -1))
        x = jax.nn.relu(x @ params["l1"]["w"] + params["l1"]["b"])
        x = jax.nn.relu(x @ params["l2"]["w"] + params["l2"]["b"])
        return x @ params["l3"]["w"] + params["l3"]["b"]

    def _loss_acc(self, params, batch):
        logits = self._forward(params, batch["x"])
        labels = batch["y"]
        loss = jnp.mean(
            optax.softmax_cross_entropy_with_integer_labels(logits, labels)
        )
        acc = jnp.mean((jnp.argmax(logits, -1) == labels).astype(jnp.float32))
        return loss, acc

    def training_step(self, params, batch, rng):
        loss, acc = self._loss_acc(params, batch)
        return loss, {"ptl/train_loss": loss, "ptl/train_accuracy": acc}

    def validation_step(self, params, batch):
        loss, acc = self._loss_acc(params, batch)
        return {"ptl/val_loss": loss, "ptl/val_accuracy": acc}

    def predict_step(self, params, batch):
        return jnp.argmax(self._forward(params, batch["x"]), axis=-1)

    def configure_optimizers(self):
        return optax.adam(self.hparams["lr"])


def _digits_as_mnist(seed: int = 0):
    """sklearn 8×8 digits → float32 [N, 28, 28] in [0, 1] + labels."""
    try:
        from sklearn.datasets import load_digits

        digits = load_digits()
        imgs = digits.images.astype(np.float32) / 16.0  # [N, 8, 8]
        # Nearest-neighbor upsample 8→24, pad 2 → 28×28.
        imgs = imgs.repeat(3, axis=1).repeat(3, axis=2)
        imgs = np.pad(imgs, ((0, 0), (2, 2), (2, 2)))
        labels = digits.target.astype(np.int32)
    except ImportError:  # synthetic fallback: class-conditional blobs
        rng = np.random.default_rng(seed)
        n = 1797
        labels = rng.integers(0, 10, n).astype(np.int32)
        base = rng.standard_normal((10, 28, 28), dtype=np.float32)
        imgs = base[labels] + 0.5 * rng.standard_normal(
            (n, 28, 28), dtype=np.float32
        )
    order = np.random.default_rng(seed).permutation(len(imgs))
    return imgs[order], labels[order]


class MNISTDataModule(TpuDataModule):
    """Train/val split of the digit data with per-host sharding."""

    def __init__(self, batch_size: int = 32, val_fraction: float = 0.2,
                 seed: int = 0):
        super().__init__()
        self.batch_size = batch_size
        self.val_fraction = val_fraction
        self.seed = seed
        self._train: ArrayDataset | None = None
        self._val: ArrayDataset | None = None

    def setup(self, stage: str) -> None:
        if self._train is not None:
            return
        imgs, labels = _digits_as_mnist(self.seed)
        n_val = int(len(imgs) * self.val_fraction)
        self._val = ArrayDataset(x=imgs[:n_val], y=labels[:n_val])
        self._train = ArrayDataset(x=imgs[n_val:], y=labels[n_val:])

    def train_dataloader(self):
        return NumpyLoader(
            self._train, batch_size=self.batch_size, shuffle=True,
            seed=self.seed, shard_index=self.shard_index,
            num_shards=self.num_shards,
        )

    def val_dataloader(self):
        return NumpyLoader(
            self._val, batch_size=self.batch_size,
            shard_index=self.shard_index, num_shards=self.num_shards,
        )

    def test_dataloader(self):
        return self.val_dataloader()

    def predict_dataloader(self):
        return self.val_dataloader()
