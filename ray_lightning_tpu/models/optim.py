"""Shared optimizer helpers for the in-framework model families.

Besides the weight-decay mask, this module owns the **optimizer-state
precision policy** (``GPTConfig.opt_state_dtype`` /
``ViTConfig.opt_state_dtype``): :func:`quantize_opt_state` wraps any
adam-family ``optax.GradientTransformation`` so its moments are STORED
in bf16 or block-scaled int8 (``ops/optim_quant.py``) while the update
math stays f32 — dequant → f32 update → requant runs inside the donated
train step, so the f32 moments never persist in HBM.
:func:`opt_state_bytes` is the analytic accounting the bench's
``opt_state`` block reports.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import optax

from ray_lightning_tpu.ops.optim_quant import (
    DEFAULT_BLOCK_SIZE,
    MIN_QUANT_SIZE,
    BlockQuantized,
    dequantize_moment,
    is_block_quantized,
    quantize_moment,
)

__all__ = [
    "decay_mask",
    "OPT_STATE_DTYPES",
    "resolve_opt_state_dtype",
    "quantize_opt_state",
    "apply_opt_state_dtype",
    "opt_state_bytes",
]

# Matrix-valued params by naming convention (GPT/ViT family): ``*_w``
# projections, plus the token embedding (tied to the LM head — it IS the
# output matrix).  Everything else — biases (``*_b``), LayerNorm gains
# (``*_g``), positional tables (``wpe``/``pos``) — is exempt, in both
# families.
_DECAY_EXACT = {"wte"}


def decay_mask(params: Dict[str, Any]):
    """AdamW weight-decay mask: decay matmul weights, never LayerNorm
    params or biases.

    Keyed on the family's naming convention rather than ndim: stacked
    blocks carry a leading layer dim and MoE tensors an expert dim, so
    a per-block MoE bias is 3-D while still being a bias — any raw
    ``ndim > k`` rule misclassifies one group or another.
    """

    def rule(path, leaf):
        name = getattr(path[-1], "key", "") if path else ""
        return name.endswith("_w") or name in _DECAY_EXACT

    return jax.tree_util.tree_map_with_path(rule, params)


# -- optimizer-state precision ------------------------------------------------

# ``None`` is a valid resolved value: "no policy" — the family keeps its
# legacy behavior (GPT: bf16 first moment via optax's ``mu_dtype``,
# which the explicit "bfloat16" policy generalizes to BOTH moments).
OPT_STATE_DTYPES = ("float32", "bfloat16", "int8")

_OPT_DTYPE_ALIASES = {
    "f32": "float32", "fp32": "float32",
    "bf16": "bfloat16",
}


def resolve_opt_state_dtype(value: Optional[str]) -> Optional[str]:
    """Normalize an ``opt_state_dtype`` knob value; typos fail loudly at
    optimizer construction, not minutes into a fit."""
    if value is None:
        return None
    name = _OPT_DTYPE_ALIASES.get(str(value), str(value))
    if name not in OPT_STATE_DTYPES:
        raise ValueError(
            f"opt_state_dtype {value!r} not in {OPT_STATE_DTYPES} "
            f"(aliases: {sorted(_OPT_DTYPE_ALIASES)})"
        )
    return name


def _is_adam_state(node: Any) -> bool:
    return isinstance(node, optax.ScaleByAdamState)


def _map_adam_moments(state: Any, mu_fn, nu_fn) -> Any:
    """Apply ``mu_fn``/``nu_fn`` to every moment LEAF of every
    ``ScaleByAdamState`` in an optimizer-state tree, leaving all other
    state (schedule counts, clip state, MultiSteps bookkeeping)
    untouched.  ``is_leaf``-based so it finds adam states at any
    nesting depth (chains, masked transforms, MultiSteps inner)."""

    def conv(node):
        if _is_adam_state(node):
            return optax.ScaleByAdamState(
                count=node.count,
                mu=jax.tree_util.tree_map(
                    mu_fn, node.mu, is_leaf=is_block_quantized
                ),
                nu=jax.tree_util.tree_map(
                    nu_fn, node.nu, is_leaf=is_block_quantized
                ),
            )
        return node

    return jax.tree_util.tree_map(conv, state, is_leaf=_is_adam_state)


def _compress_fns(dtype: str, block_size: int, min_quant_size: int):
    """(store, load) leaf converters for one moment kind."""

    def store_bf16(v):
        if is_block_quantized(v):
            return v
        if hasattr(v, "dtype") and jnp.issubdtype(v.dtype, jnp.floating):
            return v.astype(jnp.bfloat16)
        return v

    def load_bf16(v):
        if hasattr(v, "dtype") and v.dtype == jnp.bfloat16:
            return v.astype(jnp.float32)
        return v

    def make_store_int8(sqrt_domain: bool):
        def store(v):
            if is_block_quantized(v):
                return v
            if (hasattr(v, "dtype")
                    and jnp.issubdtype(v.dtype, jnp.floating)
                    and v.size >= min_quant_size):
                return quantize_moment(
                    v, block_size=block_size, sqrt_domain=sqrt_domain
                )
            return v

        return store

    def load_int8(v):
        if is_block_quantized(v):
            return dequantize_moment(v)
        return v

    if dtype == "bfloat16":
        return (store_bf16, store_bf16), (load_bf16, load_bf16)
    return (
        (make_store_int8(False), make_store_int8(True)),
        (load_int8, load_int8),
    )


def quantize_opt_state(
    inner: "optax.GradientTransformation",
    dtype: str,
    block_size: int = DEFAULT_BLOCK_SIZE,
    min_quant_size: int = MIN_QUANT_SIZE,
) -> "optax.GradientTransformation":
    """Wrap ``inner`` so its adam moments persist in ``dtype``.

    ``dtype="int8"`` stores both moments block-scaled
    (:mod:`ops.optim_quant` — first moment linear, second moment sqrt
    domain; leaves under ``min_quant_size`` stay float).
    ``dtype="bfloat16"`` casts both moments to bf16.  Either way the
    inner update runs on a transient f32 view — inside a jitted donated
    step the conversion fuses into the update program, so only the
    compressed state occupies HBM between steps.
    """
    dtype = resolve_opt_state_dtype(dtype)
    if dtype in (None, "float32"):
        return inner
    (store_mu, store_nu), (load_mu, load_nu) = _compress_fns(
        dtype, block_size, min_quant_size
    )

    def compress(state):
        return _map_adam_moments(state, store_mu, store_nu)

    def decompress(state):
        return _map_adam_moments(state, load_mu, load_nu)

    def init(params):
        return compress(inner.init(params))

    def update(updates, state, params=None):
        new_updates, new_state = inner.update(
            updates, decompress(state), params
        )
        return new_updates, compress(new_state)

    return optax.GradientTransformation(init, update)


def apply_opt_state_dtype(adamw_tx, opt_state_dtype: Optional[str],
                          block_size: int = DEFAULT_BLOCK_SIZE):
    """The one-liner both model families call: wrap their adamw in the
    configured state-precision policy (``None``/``"float32"`` =
    unchanged)."""
    dtype = resolve_opt_state_dtype(opt_state_dtype)
    if dtype in (None, "float32"):
        return adamw_tx
    return quantize_opt_state(adamw_tx, dtype, block_size=block_size)


def opt_state_bytes(
    params: Any,
    dtype: Optional[str],
    block_size: int = DEFAULT_BLOCK_SIZE,
    min_quant_size: int = MIN_QUANT_SIZE,
) -> int:
    """Analytic HBM bytes of the PERSISTENT AdamW moment state under a
    precision policy — the bench ``opt_state`` block's accounting.
    Counts both moments per parameter leaf; scalars/counts are noise
    and ignored.  ``dtype=None`` models the GPT legacy default (bf16
    first moment via ``mu_dtype``, f32 second)."""
    dtype = resolve_opt_state_dtype(dtype) if dtype is not None else None
    total = 0
    for leaf in jax.tree_util.tree_leaves(params):
        size = int(getattr(leaf, "size", 0) or 0)
        if size == 0:
            continue
        if dtype == "int8" and size >= min_quant_size:
            padded = size + ((-size) % block_size)
            per_moment = padded + 4 * (padded // block_size)
            total += 2 * per_moment
        elif dtype == "bfloat16":
            total += 2 * 2 * size
        elif dtype is None:
            total += (2 + 4) * size  # bf16 mu + f32 nu
        else:  # float32 policy, or int8 policy's small-leaf carve-out
            total += 2 * 4 * size
    return total
