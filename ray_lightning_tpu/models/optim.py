"""Shared optimizer helpers for the in-framework model families."""

from __future__ import annotations

from typing import Any, Dict

import jax

__all__ = ["decay_mask"]

# Matrix-valued params by naming convention (GPT/ViT family): ``*_w``
# projections, plus the token embedding (tied to the LM head — it IS the
# output matrix).  Everything else — biases (``*_b``), LayerNorm gains
# (``*_g``), positional tables (``wpe``/``pos``) — is exempt, in both
# families.
_DECAY_EXACT = {"wte"}


def decay_mask(params: Dict[str, Any]):
    """AdamW weight-decay mask: decay matmul weights, never LayerNorm
    params or biases.

    Keyed on the family's naming convention rather than ndim: stacked
    blocks carry a leading layer dim and MoE tensors an expert dim, so
    a per-block MoE bias is 3-D while still being a bias — any raw
    ``ndim > k`` rule misclassifies one group or another.
    """

    def rule(path, leaf):
        name = getattr(path[-1], "key", "") if path else ""
        return name.endswith("_w") or name in _DECAY_EXACT

    return jax.tree_util.tree_map_with_path(rule, params)
