"""Vision Transformer for CIFAR/ImageNet-scale classification.

The reference framework ships no vision transformer — its large-model
example leans on pl_bolts' ImageGPT (reference
``examples/ray_ddp_sharded_example.py:62``); this module provides the
in-framework attention-based vision family so the sharded/TP strategies
have a second transformer workload besides the GPT LM.

TPU-first design choices (not a torch translation):

* **Patchify as reshape + one dense matmul** — a (P·P·C → d) projection
  is a single large MXU matmul; no im2col, no conv kernels needed.
* **Bidirectional attention as batched einsum softmax** — ViT sequences
  are short (64 patches at 32²/4²), so the O(S²) XLA path is optimal;
  the flash kernels exist for causal LM-scale sequences and are not
  used here.
* **Mean-pool head instead of a CLS token** — stateless, shape-static,
  and one fewer special-cased row in every sharding spec.
* **Megatron TP layout shared with GPT** — qkv/mlp-in column-parallel,
  proj/mlp-out row-parallel over the ``tensor`` axis, so the same mesh
  that trains GPT trains ViT (``param_partition_specs``).
* **Stacked-layer scan** — blocks live in one pytree with a leading
  layer dim and run under ``lax.scan``: one compiled block regardless
  of depth.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import PartitionSpec as P

from ray_lightning_tpu.core.module import TpuModule
from ray_lightning_tpu.models.optim import decay_mask
from ray_lightning_tpu.ops.layer_norm import layer_norm

__all__ = ["ViT", "ViTConfig"]


@dataclasses.dataclass(frozen=True)
class ViTConfig:
    image_size: int = 32
    patch_size: int = 4
    in_channels: int = 3
    num_classes: int = 10
    n_layer: int = 6
    n_head: int = 6
    d_model: int = 384
    mlp_ratio: int = 4
    lr: float = 1e-3
    weight_decay: float = 0.05
    warmup_steps: int = 100
    # Optimizer-state precision policy — same contract as
    # ``GPTConfig.opt_state_dtype`` (models/gpt.py): None/"float32" =
    # plain f32 moments, "bfloat16" = both moments bf16, "int8" = both
    # moments block-scaled int8 (ops/optim_quant.py).
    opt_state_dtype: Optional[str] = None

    @classmethod
    def tiny(cls) -> "ViTConfig":
        """Test-sized config (CPU-mesh friendly)."""
        return cls(n_layer=2, n_head=4, d_model=128, warmup_steps=2)

    @property
    def n_patches(self) -> int:
        return (self.image_size // self.patch_size) ** 2

    @property
    def patch_dim(self) -> int:
        return self.patch_size * self.patch_size * self.in_channels

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_head


class ViT(TpuModule):
    """Vision Transformer encoder + linear classifier head."""

    def __init__(self, config: Optional[ViTConfig] = None,
                 remat: bool = False):
        super().__init__()
        self.config = config or ViTConfig.tiny()
        cfg = self.config
        if cfg.image_size % cfg.patch_size != 0:
            raise ValueError(
                f"patch_size {cfg.patch_size} must divide image_size "
                f"{cfg.image_size}"
            )
        if cfg.d_model % cfg.n_head != 0:
            raise ValueError("n_head must divide d_model")
        from ray_lightning_tpu.models.optim import resolve_opt_state_dtype

        resolve_opt_state_dtype(cfg.opt_state_dtype)
        self.remat = remat
        self.save_hyperparameters(
            **dataclasses.asdict(cfg), remat=remat,
        )

    # -- params -------------------------------------------------------------
    def init_params(self, rng: jax.Array) -> Dict[str, Any]:
        cfg = self.config
        d, h, L = cfg.d_model, cfg.mlp_ratio * cfg.d_model, cfg.n_layer
        keys = jax.random.split(rng, 7)

        def norm(key, shape, std=0.02):
            return (jax.random.normal(key, shape) * std).astype(jnp.float32)

        resid_std = 0.02 / np.sqrt(2 * L)
        blocks = {
            "ln1_g": jnp.ones((L, d)), "ln1_b": jnp.zeros((L, d)),
            "qkv_w": norm(keys[2], (L, d, 3 * d)),
            "qkv_b": jnp.zeros((L, 3 * d)),
            "proj_w": norm(keys[3], (L, d, d), std=resid_std),
            "proj_b": jnp.zeros((L, d)),
            "ln2_g": jnp.ones((L, d)), "ln2_b": jnp.zeros((L, d)),
            "mlp_in_w": norm(keys[4], (L, d, h)),
            "mlp_in_b": jnp.zeros((L, h)),
            "mlp_out_w": norm(keys[5], (L, h, d), std=resid_std),
            "mlp_out_b": jnp.zeros((L, d)),
        }
        return {
            "patch_w": norm(keys[0], (cfg.patch_dim, d)),
            "patch_b": jnp.zeros((d,)),
            "pos": norm(keys[1], (cfg.n_patches, d), std=0.01),
            "blocks": blocks,
            "ln_f_g": jnp.ones((d,)), "ln_f_b": jnp.zeros((d,)),
            "head_w": norm(keys[6], (d, cfg.num_classes)),
            "head_b": jnp.zeros((cfg.num_classes,)),
        }

    def param_partition_specs(self) -> Dict[str, Any]:
        """Megatron TP over the ``tensor`` axis — the same column/row
        split as GPT (``models/gpt.py param_partition_specs``): one psum
        per block half, inserted by GSPMD.  The head is row-parallel
        (classes are few; shard the d_model contraction)."""
        t = "tensor"
        return {
            "patch_w": P(None, t), "patch_b": P(t),
            "pos": P(None, t),
            "blocks": {
                "ln1_g": P(), "ln1_b": P(),
                "qkv_w": P(None, None, t), "qkv_b": P(None, t),
                "proj_w": P(None, t, None), "proj_b": P(),
                "ln2_g": P(), "ln2_b": P(),
                "mlp_in_w": P(None, None, t), "mlp_in_b": P(None, t),
                "mlp_out_w": P(None, t, None), "mlp_out_b": P(),
            },
            "ln_f_g": P(), "ln_f_b": P(),
            "head_w": P(t, None), "head_b": P(),
        }

    # -- forward ------------------------------------------------------------
    def _compute_dtype(self):
        return jnp.bfloat16 if self.precision in ("bf16", "bfloat16") else (
            jnp.float32
        )

    def _patchify(self, x: jax.Array) -> jax.Array:
        """(B, H, W, C) NHWC -> (B, N, P*P*C): pure reshape/transpose, no
        data movement beyond one layout change, feeding a single dense
        projection matmul."""
        cfg = self.config
        B = x.shape[0]
        s, p = cfg.image_size, cfg.patch_size
        g = s // p
        x = x.reshape(B, g, p, g, p, cfg.in_channels)
        x = x.transpose(0, 1, 3, 2, 4, 5)  # B, g, g, p, p, C
        return x.reshape(B, g * g, cfg.patch_dim)

    @staticmethod
    def _mha(q, k, v):
        """Bidirectional multi-head attention, f32 softmax statistics.
        q/k/v: (B, N, H, Dh)."""
        dh = q.shape[-1]
        logits = jnp.einsum(
            "bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32
        ) / np.sqrt(dh)
        w = jax.nn.softmax(logits, axis=-1)
        return jnp.einsum(
            "bhqk,bkhd->bqhd", w.astype(v.dtype), v,
            preferred_element_type=jnp.float32,
        ).astype(v.dtype)

    def forward(self, params: Dict[str, Any], x: jax.Array) -> jax.Array:
        """(B, H, W, C) images -> (B, num_classes) logits."""
        cfg = self.config
        c = self._compute_dtype()
        B = x.shape[0]
        patches = self._patchify(x.astype(c))
        h = (patches @ params["patch_w"].astype(c)
             + params["patch_b"].astype(c) + params["pos"].astype(c))

        def block(carry, p):
            x = carry
            a = layer_norm(x, p["ln1_g"], p["ln1_b"])
            qkv = a @ p["qkv_w"].astype(c) + p["qkv_b"].astype(c)
            q, k, v = jnp.split(qkv, 3, axis=-1)

            def heads(z):
                return z.reshape(B, cfg.n_patches, cfg.n_head, cfg.head_dim)

            att = self._mha(heads(q), heads(k), heads(v))
            att = att.reshape(B, cfg.n_patches, cfg.d_model)
            x = x + att @ p["proj_w"].astype(c) + p["proj_b"].astype(c)
            m = layer_norm(x, p["ln2_g"], p["ln2_b"])
            m = jax.nn.gelu(
                m @ p["mlp_in_w"].astype(c) + p["mlp_in_b"].astype(c)
            )
            x = x + m @ p["mlp_out_w"].astype(c) + p["mlp_out_b"].astype(c)
            return x, None

        if self.remat:
            block = jax.checkpoint(
                block,
                policy=jax.checkpoint_policies.dots_with_no_batch_dims_saveable,
            )
        h, _ = jax.lax.scan(block, h, params["blocks"])
        h = layer_norm(h, params["ln_f_g"], params["ln_f_b"])
        pooled = h.mean(axis=1)  # stateless mean-pool (no CLS token)
        return (pooled @ params["head_w"].astype(c)
                + params["head_b"].astype(c)).astype(jnp.float32)

    # -- steps --------------------------------------------------------------
    def _loss_acc(self, params, batch):
        logits = self.forward(params, batch["x"])
        labels = batch["y"]
        loss = jnp.mean(
            optax.softmax_cross_entropy_with_integer_labels(logits, labels)
        )
        acc = jnp.mean((jnp.argmax(logits, -1) == labels).astype(jnp.float32))
        return loss, acc

    def training_step(self, params, batch, rng):
        loss, acc = self._loss_acc(params, batch)
        return loss, {"train_loss": loss, "train_accuracy": acc}

    def validation_step(self, params, batch):
        loss, acc = self._loss_acc(params, batch)
        return {"val_loss": loss, "val_accuracy": acc}

    def predict_step(self, params, batch):
        return jnp.argmax(self.forward(params, batch["x"]), axis=-1)

    def configure_optimizers(self):
        from ray_lightning_tpu.models.optim import apply_opt_state_dtype

        cfg = self.config
        schedule = optax.warmup_cosine_decay_schedule(
            0.0, cfg.lr, cfg.warmup_steps, max(10 * cfg.warmup_steps, 1000)
        )
        adamw = apply_opt_state_dtype(
            optax.adamw(schedule, weight_decay=cfg.weight_decay,
                        mask=decay_mask),
            cfg.opt_state_dtype,
        )
        return optax.chain(optax.clip_by_global_norm(1.0), adamw)
