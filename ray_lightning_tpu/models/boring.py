"""Fixture models (≙ reference ``tests/utils.py:16-210``), shipped in the
package because they double as minimal usage examples.

* :class:`BoringModel` ≙ reference ``BoringModel`` (``tests/utils.py:28-96``):
  one linear layer over :class:`~ray_lightning_tpu.core.data.RandomDataset`,
  loss drives outputs to zero — enough structure to verify that training
  moves weights (``train_test``, ``tests/utils.py:236-245``).
* :class:`XORModel` ≙ reference ``XORModel`` (``tests/utils.py:151-188``):
  tiny MLP on the 4-point XOR table with an accuracy metric — enough to
  verify convergence (``predict_test`` accuracy ≥ 0.5,
  ``tests/utils.py:256-272``).
"""

from __future__ import annotations

from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax

from ray_lightning_tpu.core.data import (
    ArrayDataset,
    NumpyLoader,
    RandomDataset,
    TpuDataModule,
)
from ray_lightning_tpu.core.module import TpuModule

__all__ = ["BoringModel", "BoringDataModule", "XORModel", "XORDataModule"]


class BoringModel(TpuModule):
    def __init__(self, in_dim: int = 32, out_dim: int = 2, lr: float = 1e-1):
        super().__init__()
        self.save_hyperparameters(in_dim=in_dim, out_dim=out_dim, lr=lr)

    def init_params(self, rng: jax.Array) -> Dict[str, Any]:
        k_w, _ = jax.random.split(rng)
        h = self.hparams
        return {
            "w": jax.random.normal(k_w, (h["in_dim"], h["out_dim"]))
            * (1.0 / np.sqrt(h["in_dim"])),
            "b": jnp.zeros((h["out_dim"],)),
        }

    def _forward(self, params, x):
        return x @ params["w"] + params["b"]

    def training_step(self, params, batch, rng):
        out = self._forward(params, batch["x"])
        loss = jnp.mean(out**2)
        return loss, {"train_loss": loss}

    def validation_step(self, params, batch):
        out = self._forward(params, batch["x"])
        return {"val_loss": jnp.mean(out**2)}

    def predict_step(self, params, batch):
        return self._forward(params, batch["x"])

    def configure_optimizers(self):
        return optax.sgd(self.hparams["lr"])


class BoringDataModule(TpuDataModule):
    def __init__(self, length: int = 64, batch_size: int = 16, in_dim: int = 32):
        super().__init__()
        self.length = length
        self.batch_size = batch_size
        self.in_dim = in_dim

    def _loader(self, seed: int) -> NumpyLoader:
        return NumpyLoader(
            RandomDataset(size=self.in_dim, length=self.length, seed=seed),
            batch_size=self.batch_size,
            shard_index=self.shard_index,
            num_shards=self.num_shards,
        )

    def train_dataloader(self):
        return self._loader(seed=0)

    def val_dataloader(self):
        return self._loader(seed=1)

    def test_dataloader(self):
        return self._loader(seed=2)

    def predict_dataloader(self):
        return self._loader(seed=3)


def _xor_table(batch_size: int) -> Dict[str, np.ndarray]:
    """XOR truth table tiled to ``batch_size`` rows."""
    x = np.array([[0, 0], [0, 1], [1, 0], [1, 1]], dtype=np.float32)
    y = np.array([0, 1, 1, 0], dtype=np.int32)
    reps = max(1, batch_size // 4)
    return {
        "x": np.tile(x, (reps, 1)),
        "y": np.tile(y, reps),
    }


class XORModel(TpuModule):
    def __init__(self, hidden: int = 8, lr: float = 0.1):
        super().__init__()
        self.save_hyperparameters(hidden=hidden, lr=lr)

    def init_params(self, rng: jax.Array) -> Dict[str, Any]:
        k1, k2 = jax.random.split(rng)
        h = self.hparams["hidden"]
        return {
            "w1": jax.random.normal(k1, (2, h)) * 0.7,
            "b1": jnp.zeros((h,)),
            "w2": jax.random.normal(k2, (h, 2)) * 0.7,
            "b2": jnp.zeros((2,)),
        }

    def _forward(self, params, x):
        hidden = jnp.tanh(x @ params["w1"] + params["b1"])
        return hidden @ params["w2"] + params["b2"]

    def _loss_acc(self, params, batch):
        logits = self._forward(params, batch["x"])
        labels = batch["y"]
        loss = jnp.mean(
            optax.softmax_cross_entropy_with_integer_labels(logits, labels)
        )
        acc = jnp.mean((jnp.argmax(logits, -1) == labels).astype(jnp.float32))
        return loss, acc

    def training_step(self, params, batch, rng):
        loss, acc = self._loss_acc(params, batch)
        return loss, {"train_loss": loss, "train_acc": acc}

    def validation_step(self, params, batch):
        loss, acc = self._loss_acc(params, batch)
        return {"val_loss": loss, "val_acc": acc}

    def predict_step(self, params, batch):
        return jnp.argmax(self._forward(params, batch["x"]), axis=-1)

    def configure_optimizers(self):
        return optax.adam(self.hparams["lr"])


class XORDataModule(TpuDataModule):
    """≙ reference ``XORDataModule`` (``tests/utils.py:191-210``)."""

    def __init__(self, batch_size: int = 16, batches_per_epoch: int = 8):
        super().__init__()
        self.batch_size = batch_size
        self.batches_per_epoch = batches_per_epoch

    def _loader(self) -> NumpyLoader:
        table = _xor_table(self.batch_size * self.batches_per_epoch)
        return NumpyLoader(
            ArrayDataset(**table),
            batch_size=self.batch_size,
            shard_index=self.shard_index,
            num_shards=self.num_shards,
        )

    def train_dataloader(self):
        return self._loader()

    def val_dataloader(self):
        return self._loader()

    def test_dataloader(self):
        return self._loader()

    def predict_dataloader(self):
        return self._loader()
