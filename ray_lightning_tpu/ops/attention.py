"""Causal multi-head attention: XLA reference + implementation dispatcher.

All implementations share one contract::

    causal_attention(q, k, v) -> out      # shapes (batch, seq, heads, dim)

* ``impl="xla"`` — einsum + masked softmax; XLA fuses this well and it runs
  anywhere (CPU test meshes included).  This is also the numerical
  reference the Pallas/ring implementations are tested against.
* ``impl="flash"`` — the Pallas TPU kernel (:mod:`.flash_attention`):
  blocked online-softmax, O(seq) memory, causal blocks skipped.
* ``impl="auto"`` — flash on TPU when shapes allow, else XLA.

Ring (sequence-parallel) attention has a different calling convention — it
runs *inside* ``shard_map`` over a sequence-sharded axis — and lives in
:mod:`.ring_attention`.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

__all__ = ["causal_attention", "xla_causal_attention"]

_NEG_INF = -1e30  # large-negative instead of -inf: keeps masked softmax
# rows finite (causal rows always have >=1 unmasked entry, but -inf
# produces nan gradients through where()).


def xla_causal_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    scale: Optional[float] = None,
) -> jax.Array:
    """Reference causal attention, (B, S, H, D) -> (B, S, H, D).

    Softmax is computed in float32 regardless of input dtype (bfloat16
    activations keep full-precision normalizers — the standard TPU mixed-
    precision recipe), output is cast back to the input dtype.
    """
    b, s, h, d = q.shape
    scale = (d ** -0.5) if scale is None else scale
    logits = jnp.einsum(
        "bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32
    )
    logits = logits * scale
    q_pos = jnp.arange(s)[:, None]
    k_pos = jnp.arange(s)[None, :]
    logits = jnp.where(q_pos >= k_pos, logits, _NEG_INF)
    probs = jax.nn.softmax(logits, axis=-1)
    out = jnp.einsum(
        "bhqk,bkhd->bqhd", probs.astype(v.dtype), v,
        preferred_element_type=jnp.float32,
    )
    return out.astype(q.dtype)


def _flash_supported(q: jax.Array) -> bool:
    from ray_lightning_tpu.ops.kernel_probe import kernel_family_disabled

    if kernel_family_disabled("flash"):
        return False
    try:
        platform = jax.default_backend()
    except Exception:  # noqa: BLE001
        return False
    if platform != "tpu":
        return False
    _, s, _, d = q.shape
    from ray_lightning_tpu.ops import flash_attention as fa

    # Kernel constraints: some 128-multiple block must divide seq (per-row
    # softmax stats are stored broadcast across a 128-lane minor dim, and
    # the backward kernels tile them in block_k/128 repeats).
    return fa.pick_block(s) is not None and d in (64, 128, 256)


def causal_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    scale: Optional[float] = None,
    impl: str = "auto",
) -> jax.Array:
    """Dispatching causal attention (see module docstring)."""
    if impl == "auto":
        impl = "flash" if _flash_supported(q) else "xla"
    if impl == "xla":
        return xla_causal_attention(q, k, v, scale)
    if impl == "flash":
        from ray_lightning_tpu.ops.flash_attention import flash_attention

        return flash_attention(q, k, v, scale)
    raise ValueError(f"Unknown attention impl {impl!r} (auto|xla|flash)")
