"""Block-scaled int8 codecs + compressed all-reduce for gradient sync.

The wire format of :mod:`ray_lightning_tpu.parallel.grad_sync` (EQuARX-style,
arXiv:2506.17615): a flat f32 vector is split into fixed-size blocks, each
block carries one f32 absmax scale and int8 payloads — 1 byte/element plus
``4/block_size`` bytes of scale overhead instead of 4 bytes/element.

The all-reduce itself is the classic two-phase compressed ring:

1. **reduce-scatter** (``all_to_all``): every device ships the *quantized*
   chunk ``d`` of its local partial to device ``d``, which dequantizes the
   world's versions and sums them — it now owns the exact reduced chunk;
2. **all-gather**: the owner re-quantizes its reduced chunk and broadcasts
   int8 + scales; everyone dequantizes the full reduced vector.

Everything that crosses the wire is int8 payload + f32 block scales; the
f32 math (dequant, sum, requant) is device-local.  Both phases are plain
``lax`` collectives inside a ``shard_map`` body, so XLA schedules them over
ICI/DCN like any other collective (and can overlap independent buckets).

Per-element quantization error is bounded by ``scale/2 = absmax/254`` per
phase; callers wanting exactness over time carry the error-feedback
residual (``error`` outputs) and re-inject it next step.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

__all__ = [
    "quantize_block_scaled",
    "dequantize_block_scaled",
    "int8_all_reduce",
    "composite_axis_index",
]


def quantize_block_scaled(
    v: jax.Array, block_size: int
) -> Tuple[jax.Array, jax.Array]:
    """Flat f32 vector → (int8 payload, f32 per-block absmax scales).

    ``v.size`` must be a multiple of ``block_size`` (callers pad; zero
    pads quantize exactly to zero).  An all-zero block gets scale 1.0 so
    the dequant never divides by / multiplies with garbage.
    """
    vb = v.reshape(-1, block_size)
    amax = jnp.max(jnp.abs(vb), axis=1, keepdims=True)
    scale = jnp.where(amax > 0, amax / 127.0, 1.0).astype(jnp.float32)
    q = jnp.clip(jnp.round(vb / scale), -127, 127).astype(jnp.int8)
    return q.reshape(-1), scale.reshape(-1)


def dequantize_block_scaled(
    q: jax.Array, scales: jax.Array, block_size: int
) -> jax.Array:
    """Inverse of :func:`quantize_block_scaled` (up to rounding)."""
    vb = q.astype(jnp.float32).reshape(-1, block_size)
    return (vb * scales[:, None]).reshape(-1)


def composite_axis_index(axis_names: Sequence[str]) -> jax.Array:
    """Linear device index over a (possibly composite) mesh-axis tuple,
    row-major in the order given — matches how tuple-axis collectives
    (``all_gather``/``all_to_all`` over ``("data", "fsdp")``) order their
    participants."""
    idx = jnp.zeros((), jnp.int32)
    for name in axis_names:
        idx = idx * jax.lax.psum(1, name) + jax.lax.axis_index(name)
    return idx


def int8_all_reduce(
    v: jax.Array,
    axis_names: Sequence[str],
    n_shards: int,
    block_size: int,
    want_error: bool = False,
) -> Tuple[jax.Array, Optional[jax.Array]]:
    """Sum ``v`` across ``axis_names`` with an int8 block-scaled wire.

    Must run inside ``shard_map`` with ``axis_names`` manual.  ``v`` is
    this device's flat f32 partial, ``v.size`` a multiple of
    ``n_shards * block_size``.

    Returns ``(reduced, error)``; ``error`` (when ``want_error``) is this
    device's share of the compression error — its local phase-1
    quantization error plus, on the chunk it owns, the phase-2
    requantization error.  Summing ``error`` over devices recovers
    exactly ``sum(exact partials) - reduced``, so re-adding each
    device's ``error`` to its next-step partial (error feedback) makes
    the bias telescope instead of accumulate.
    """
    axes = tuple(axis_names)
    chunk = v.size // n_shards

    # Phase 1: quantize the local partial, ship chunk d to device d.
    q, s = quantize_block_scaled(v, block_size)
    q_peer = jax.lax.all_to_all(
        q.reshape(n_shards, chunk), axes, 0, 0, tiled=True
    )
    s_peer = jax.lax.all_to_all(
        s.reshape(n_shards, chunk // block_size), axes, 0, 0, tiled=True
    )
    # Dequantize every peer's version of MY chunk and sum → exact sum of
    # the quantized partials for the chunk this device owns.
    deq = (
        q_peer.astype(jnp.float32).reshape(n_shards, -1, block_size)
        * s_peer[:, :, None]
    )
    reduced_chunk = deq.sum(axis=0).reshape(-1)

    # Phase 2: requantize the reduced chunk, broadcast int8 + scales.
    q2, s2 = quantize_block_scaled(reduced_chunk, block_size)
    q_all = jax.lax.all_gather(q2, axes, tiled=True)
    s_all = jax.lax.all_gather(s2, axes, tiled=True)
    reduced = dequantize_block_scaled(q_all, s_all, block_size)

    if not want_error:
        return reduced, None
    # Local phase-1 error over the full vector...
    err = v - dequantize_block_scaled(q, s, block_size)
    # ...plus the phase-2 error on the owned chunk (each chunk has
    # exactly one owner, so the world-sum counts it once).
    e2 = reduced_chunk - dequantize_block_scaled(q2, s2, block_size)
    idx = composite_axis_index(axes)
    err = jax.lax.dynamic_update_slice(
        err, jax.lax.dynamic_slice(err, (idx * chunk,), (chunk,)) + e2,
        (idx * chunk,),
    )
    return reduced, err
