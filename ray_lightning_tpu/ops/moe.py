"""Mixture-of-Experts routing + expert-parallel MLP (GShard/Switch style).

Net-new over the reference (SURVEY §2.3: "EP (expert parallel / MoE):
absent"), built TPU-first:

* **Dense dispatch, static shapes.** Routing is expressed as einsums
  against one-hot dispatch/combine tensors (the GShard formulation) —
  no gathers/scatters with data-dependent shapes, so XLA tiles
  everything onto the MXU and the program never recompiles.  Capacity
  ``C`` bounds per-expert work; overflow tokens are dropped from the
  expert path (they still flow through the residual).
* **Grouped routing.** Tokens are routed within ``groups`` independent
  groups (GShard's group dim), sized by the caller to the data-parallel
  shard count: dispatch tensors are ``[G, s, E, C]`` with ``s = S/G``
  (linear in S, not quadratic), and the capacity cumsum runs *within*
  a group — shard-local under GSPMD, no cross-shard router state.
* **Expert parallelism as an annotation.** Expert-stacked weights
  ``[E, d, h]`` carry ``P("expert", ...)`` specs; with an ``expert``
  mesh axis, GSPMD turns the dispatch einsum into the all-to-all that
  ships token slots to their expert's device, composing with tensor
  parallelism on the hidden dim.
* **Load balancing** via the Switch-Transformer auxiliary loss,
  normalized so a perfectly uniform assignment scores 1.0 for any
  ``top_k``.
"""

from __future__ import annotations

import math
from typing import Tuple

import jax
import jax.numpy as jnp

__all__ = ["topk_capacity_routing", "moe_mlp", "load_balance_loss"]


def topk_capacity_routing(
    probs: jax.Array, top_k: int, capacity: int
) -> Tuple[jax.Array, jax.Array]:
    """Greedy top-k assignment with per-expert capacity (one group).

    probs: ``[s, E]`` router probabilities (f32).
    Returns ``(combine, dispatch)``, both ``[s, E, C]``: ``dispatch`` is
    the 0/1 token→(expert, slot) assignment; ``combine`` additionally
    carries the (renormalized) gate weight of each assignment.
    """
    s, E = probs.shape
    top_k = min(top_k, E)  # k > E would re-route masked tokens to expert 0
    remaining = probs
    slots_used = jnp.zeros((1, E), jnp.float32)
    dispatch = jnp.zeros((s, E, capacity), jnp.float32)
    combine = jnp.zeros((s, E, capacity), jnp.float32)
    for _ in range(top_k):  # static, small
        choice = jnp.argmax(remaining, axis=-1)                   # [s]
        onehot = jax.nn.one_hot(choice, E, dtype=jnp.float32)     # [s, E]
        # Queue position of each token within its chosen expert, offset
        # by slots already consumed in earlier rounds.
        position = jnp.cumsum(onehot, axis=0) - onehot + slots_used
        fits = (position < capacity) * onehot                     # [s, E]
        slot = jax.nn.one_hot(
            position.astype(jnp.int32), capacity, dtype=jnp.float32
        )                                                         # [s, E, C]
        d = slot * fits[..., None]
        gate = (probs * onehot).sum(-1)                           # [s]
        dispatch = dispatch + d
        combine = combine + d * gate[:, None, None]
        slots_used = slots_used + fits.sum(0, keepdims=True)
        remaining = remaining * (1.0 - onehot)
    # Normalize gates over the (≤ top_k) experts that accepted the token.
    denom = combine.sum(axis=(1, 2), keepdims=True)
    combine = combine / jnp.maximum(denom, 1e-9)
    return combine, dispatch


def load_balance_loss(probs: jax.Array, dispatch: jax.Array) -> jax.Array:
    """Switch-Transformer aux loss: ``E · Σ_e f_e · p̄_e``.

    ``f_e`` = fraction of *dispatches* landing on expert e (normalized by
    the total dispatch count, so the result is 1.0 for a uniform
    assignment regardless of ``top_k``), ``p̄_e`` = mean router
    probability.
    """
    E = probs.shape[-1]
    per_expert = dispatch.sum(axis=(0, 2))                        # [E]
    frac = per_expert / jnp.maximum(per_expert.sum(), 1.0)
    mean_prob = probs.mean(axis=0)                                # [E]
    return E * jnp.sum(frac * mean_prob)


def moe_mlp(
    x: jax.Array,
    gate_w: jax.Array,
    w_in: jax.Array,
    b_in: jax.Array,
    w_out: jax.Array,
    b_out: jax.Array,
    top_k: int = 2,
    capacity_factor: float = 1.25,
    groups: int = 1,
) -> Tuple[jax.Array, jax.Array]:
    """Expert-parallel MLP block: route → dispatch → expert FFN → combine.

    x ``[B, T, d]``; gate_w ``[d, E]``; w_in ``[E, d, h]``; b_in
    ``[E, h]``; w_out ``[E, h, d]``; b_out ``[E, d]``.  ``groups`` should
    equal the data-parallel shard count (see module docstring); it is
    clamped to 1 when it does not divide the token count.  Returns
    ``(y [B, T, d], aux_loss scalar)``.  Router math in f32 regardless of
    the compute dtype (gate decisions must not flip with bf16 rounding).
    """
    B, T, d = x.shape
    E = gate_w.shape[-1]
    S = B * T
    G = groups if groups > 0 and S % groups == 0 else 1
    s = S // G
    capacity = max(1, int(math.ceil(s / E * capacity_factor)))
    xg = x.reshape(G, s, d)

    logits = jnp.einsum(
        "gsd,de->gse", xg.astype(jnp.float32), gate_w.astype(jnp.float32)
    )
    probs = jax.nn.softmax(logits, axis=-1)                       # [G, s, E]
    combine, dispatch = jax.vmap(
        lambda p: topk_capacity_routing(p, top_k, capacity)
    )(probs)
    # Aux loss over *globally aggregated* statistics, not a per-group
    # mean: E·Σ f_e·p̄_e with f_e and p̄_e formed from the all-group
    # dispatch counts / router probs.  A per-group mean of the loss is
    # mesh-dependent (E[f·p] ≠ E[f]·E[p] across groups), which broke
    # sharded parity; plain sums stay shard-local-friendly under GSPMD.
    aux = load_balance_loss(
        probs.reshape(G * s, E), dispatch.reshape(G * s, E, capacity)
    )

    c = x.dtype
    # Dispatch: the ep all-to-all under GSPMD (token slots → expert shard).
    xd = jnp.einsum("gsec,gsd->gecd", dispatch.astype(c), xg,
                    preferred_element_type=jnp.float32).astype(c)
    h = jax.nn.gelu(
        jnp.einsum("gecd,edh->gech", xd, w_in,
                   preferred_element_type=jnp.float32).astype(c)
        + b_in[None, :, None, :].astype(c)
    )
    yo = (jnp.einsum("gech,ehd->gecd", h, w_out,
                     preferred_element_type=jnp.float32).astype(c)
          + b_out[None, :, None, :].astype(c))
    # Combine: the return all-to-all, weighted by the gates.
    y = jnp.einsum("gsec,gecd->gsd", combine.astype(c), yo,
                   preferred_element_type=jnp.float32)
    return y.reshape(B, T, d).astype(c), aux
