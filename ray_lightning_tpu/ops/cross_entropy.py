"""Fused LM-head + cross-entropy, vocab-chunked — no (B, T, V) tensor.

The naive LM loss (``logits = x @ wte.T`` then softmax-CE) materializes a
``(B, T, V)`` float32 logits tensor in HBM — for GPT-2-small at B=16,
T=1024, V=50304 that is a ~3.3 GB intermediate written and re-read every
step (and its ``(B, T, V)`` gradient again in the backward), which alone
costs ~17% of the step on a v5e.  The reference framework never faces
this because its models are external torch modules
(``/root/reference/examples/ray_ddp_sharded_example.py:48-71``); a
TPU-native framework that owns its flagship LM must own the fix.

Design (TPU/XLA-first):

* **Vocab chunking with online logsumexp.**  ``lax.scan`` over chunks of
  the vocabulary: each iteration computes ``(B, T, Vc)`` logits on the
  fly (bf16 MXU matmul, f32 accumulation), folds them into running
  ``(max, sumexp)`` statistics and the gathered gold-label logit, then
  discards them.  Peak live logits memory drops from ``N*V`` to
  ``N*Vc``.
* **Why chunk vocab, not tokens:** under GSPMD the batch/seq dims are
  sharded over the ``data``(+``fsdp``/``sp``) mesh axes and ``wte`` is
  feature-sharded ``P(None, "tensor")`` (see
  ``models/gpt.py:param_partition_specs``).  Scanning over *vocab* rows
  slices only the replicated dim — no resharding, no cross-device
  gathers; the contraction over the tensor-sharded ``d`` stays a local
  matmul + psum exactly as in the unchunked head.
* **Custom VJP with chunk recompute.**  Residuals are just
  ``(x, wte, targets, lse)`` — the backward rebuilds each chunk's
  logits, forms ``dlogits = (softmax - onehot) * g`` chunk-locally, and
  accumulates ``dx`` (f32 carry) and the per-chunk ``dwte`` rows.  The
  ``(B, T, V)`` gradient tensor never exists either.

Numerics: matmuls run in ``compute_dtype`` (bf16 on TPU) with float32
``preferred_element_type`` accumulation; softmax statistics, the loss and
both gradients accumulate in float32.  With ``compute_dtype=float32``
the result matches the naive path to ~1e-6 (tested in
``tests/test_ops.py``).
"""

from __future__ import annotations

from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ray_lightning_tpu.ops.kernel_probe import _interpret

__all__ = [
    "fused_lm_head_cross_entropy",
    "fused_lm_head_cross_entropy_sharded",
    "naive_lm_head_cross_entropy",
]

_NEG_INF = -1e30  # finite stand-in for -inf: keeps exp/max well-defined


def _pick_num_chunks(vocab_size: int, target_chunk: int = 8192) -> int:
    return max(1, -(-vocab_size // target_chunk))  # ceil div


def _chunk_wte(wte: jax.Array, num_chunks: int) -> Tuple[jax.Array, int]:
    """(V, d) -> (K, Vc, d), zero-padding V up to K*Vc.

    Vc is rounded up to a multiple of 128 so every chunk matmul and the
    (..., Vc) softmax/onehot ops tile cleanly on the 8x128 vector lanes
    (the valid-mask already neutralizes the padded rows)."""
    V, d = wte.shape
    Vc = -(-V // num_chunks)
    Vc = -(-Vc // 128) * 128
    pad = num_chunks * Vc - V
    if pad:
        wte = jnp.concatenate(
            [wte, jnp.zeros((pad, d), wte.dtype)], axis=0
        )
    return wte.reshape(num_chunks, Vc, d), Vc


def _chunk_logits(x, wte_chunk, offset, vocab_size, compute_dtype):
    """x (..., d) @ wte_chunk (Vc, d)^T -> (..., Vc) f32, padded rows
    masked to -inf."""
    Vc = wte_chunk.shape[0]
    logits = jnp.einsum(
        "...d,vd->...v",
        x.astype(compute_dtype),
        wte_chunk.astype(compute_dtype),
        preferred_element_type=jnp.float32,
    )
    # Mask vocab ids >= vocab_size (zero-padded rows of the last chunk).
    valid = (offset + jnp.arange(Vc)) < vocab_size
    return jnp.where(valid, logits, _NEG_INF)


# Tile sizes chosen for the ~16 MB/core VMEM budget with double-buffered
# input blocks: at the d=1536 cap the worst kernel (dw, vocab-major
# accumulator) holds ~10 MB.  Token counts that don't divide _CE_BLOCK_T
# are zero-padded (a padded row's cotangent is zero, so it contributes
# nothing backward); larger models fall back to the GSPMD-safe scan.
_CE_BLOCK_T = 512
_CE_BLOCK_V = 512
_CE_MAX_D = 1536
_LANE = 128


def _ce_fwd_kernel(x_ref, w_ref, t_ref, loss_ref, lse_ref, m_sc, s_sc, g_sc,
                   *, vocab_size, block_v, num_vb, vma=()):
    """Forward CE tile: one (token-block × vocab-block) step.

    Grid is (token blocks, vocab blocks) with vocab innermost: the online
    softmax statistics (running max / sumexp / gold logit) live in VMEM
    scratch across the vocab sweep, so the (Tb, Vb) logits tile never
    leaves VMEM — zero HBM logits traffic (the scan fallback writes and
    re-reads every chunk).
    """
    from jax.experimental import pallas as pl

    vi = pl.program_id(1)

    def _c(val):  # promote kernel constants under the interpreter
        return jax.lax.pvary(val, tuple(vma)) if vma else val

    @pl.when(vi == 0)
    def _init():
        m_sc[...] = _c(jnp.full(m_sc.shape, _NEG_INF, jnp.float32))
        s_sc[...] = _c(jnp.zeros(s_sc.shape, jnp.float32))
        g_sc[...] = _c(jnp.zeros(g_sc.shape, jnp.float32))

    logits = jax.lax.dot_general(
        x_ref[...], w_ref[...], (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )                                                  # (Tb, Vb) f32
    tb, vb = logits.shape
    vpos = _c(vi * block_v
              + jax.lax.broadcasted_iota(jnp.int32, (tb, vb), 1))
    logits = jnp.where(
        vpos < _c(jnp.int32(vocab_size)), logits,
        _c(jnp.float32(_NEG_INF))
    )
    m_old = m_sc[:, :1]
    m_new = jnp.maximum(m_old, jnp.max(logits, axis=1, keepdims=True))
    corr = jnp.exp(m_old - m_new)
    s_new = s_sc[:, :1] * corr + jnp.sum(
        jnp.exp(logits - m_new), axis=1, keepdims=True
    )
    # Gold logit: exactly one (or zero) hit per row in this vocab block.
    hit = vpos == t_ref[:, :1]
    g_new = g_sc[:, :1] + jnp.sum(
        jnp.where(hit, logits, _c(jnp.float32(0.0))), axis=1,
        keepdims=True
    )
    m_sc[...] = jnp.broadcast_to(m_new, m_sc.shape)
    s_sc[...] = jnp.broadcast_to(s_new, s_sc.shape)
    g_sc[...] = jnp.broadcast_to(g_new, g_sc.shape)

    @pl.when(vi == num_vb - 1)
    def _emit():
        lse = m_new + jnp.log(s_new)
        lse_ref[...] = jnp.broadcast_to(lse, lse_ref.shape)
        loss_ref[...] = jnp.broadcast_to(lse - g_new, loss_ref.shape)


def _flatten_pad(x, targets, compute_dtype, extras=()):
    """Flatten (..., d) tokens and zero-pad to a _CE_BLOCK_T multiple.

    Padded rows produce garbage forward values (their target of 0 DOES
    match vocab position 0) — inertness comes from the caller slicing
    outputs back to ``n`` rows, and, in the backward, from the cotangent
    ``g`` being zero-padded here so padded rows contribute nothing to
    dx/dwte.  Returns (x2, t2, n_valid, n_pad, padded_extras).
    """
    d = x.shape[-1]
    x2 = x.reshape(-1, d).astype(compute_dtype)
    t1 = targets.reshape(-1)
    n = x2.shape[0]
    n_pad = -(-n // _CE_BLOCK_T) * _CE_BLOCK_T
    if n_pad != n:
        x2 = jnp.concatenate(
            [x2, jnp.zeros((n_pad - n, d), x2.dtype)], axis=0
        )
        t1 = jnp.concatenate(
            [t1, jnp.zeros((n_pad - n,), t1.dtype)], axis=0
        )
    t2 = jnp.broadcast_to(t1[:, None], (n_pad, _LANE))
    out = []
    for extra in extras:
        e1 = extra.reshape(-1).astype(jnp.float32)
        if n_pad != n:
            e1 = jnp.concatenate([e1, jnp.zeros((n_pad - n,), e1.dtype)])
        out.append(jnp.broadcast_to(e1[:, None], (n_pad, _LANE)))
    return x2, t2, n, n_pad, tuple(out)


def _pad_vocab(wte, compute_dtype):
    V, d = wte.shape
    vpad = -(-V // _CE_BLOCK_V) * _CE_BLOCK_V
    wp = wte.astype(compute_dtype)
    if vpad != V:
        wp = jnp.concatenate(
            [wp, jnp.zeros((vpad - V, d), wp.dtype)], axis=0
        )
    return wp, vpad


def _vma_of(val) -> frozenset:
    """Manual mesh axes ``val`` varies over (empty outside shard_map)."""
    try:
        return frozenset(jax.typeof(val).vma)
    except (AttributeError, TypeError):
        return frozenset()


def _out_struct(shape, dtype, vma):
    """ShapeDtypeStruct carrying the varying-manual-axes type when inside
    a shard_map region (pallas_call requires explicit out vma there)."""
    if vma:
        return jax.ShapeDtypeStruct(shape, dtype, vma=vma)
    return jax.ShapeDtypeStruct(shape, dtype)


def _ce_fwd_pallas(x, wte, targets, compute_dtype):
    """Kernel-path forward over flattened tokens.  Returns (loss, lse),
    both f32 with ``targets``'s shape."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    shape = targets.shape
    d = x.shape[-1]
    V = wte.shape[0]
    bt = _CE_BLOCK_T
    bv = _CE_BLOCK_V
    x2, t2, n, n_pad, _ = _flatten_pad(x, targets, compute_dtype)
    wp, vpad = _pad_vocab(wte, compute_dtype)
    # Inside shard_map every pallas operand/output must carry one
    # consistent vma type: promote the (replicated) head to the token
    # operands' axes; outputs vary the same way.
    vma = _vma_of(x2) | _vma_of(t2) | _vma_of(wp)
    if vma:
        x2, t2, wp = (jax.lax.pvary(v, tuple(vma - _vma_of(v)))
                      for v in (x2, t2, wp))
    num_vb = vpad // bv
    interp = _interpret()
    kernel = partial(
        _ce_fwd_kernel, vocab_size=V, block_v=bv, num_vb=num_vb,
        vma=tuple(sorted(vma)) if interp else (),
    )
    loss, lse = pl.pallas_call(
        kernel,
        out_shape=(
            _out_struct((n_pad, _LANE), jnp.float32, vma),
            _out_struct((n_pad, _LANE), jnp.float32, vma),
        ),
        grid=(n_pad // bt, num_vb),
        in_specs=[
            pl.BlockSpec((bt, d), lambda t, v: (t, 0)),
            pl.BlockSpec((bv, d), lambda t, v: (v, 0)),
            pl.BlockSpec((bt, _LANE), lambda t, v: (t, 0)),
        ],
        out_specs=(
            pl.BlockSpec((bt, _LANE), lambda t, v: (t, 0)),
            pl.BlockSpec((bt, _LANE), lambda t, v: (t, 0)),
        ),
        scratch_shapes=[
            pltpu.VMEM((bt, _LANE), jnp.float32),
            pltpu.VMEM((bt, _LANE), jnp.float32),
            pltpu.VMEM((bt, _LANE), jnp.float32),
        ],
        interpret=interp,
    )(x2, wp, t2)
    return loss[:n, 0].reshape(shape), lse[:n, 0].reshape(shape)


def _pallas_fwd_ok(x, wte, targets, compute_dtype) -> bool:
    """The kernel path needs a lane-aligned, VMEM-sized feature dim;
    other shapes use the scan path (ragged token counts are fine — they
    are zero-padded).  The d cap is in compute-dtype BYTES: the VMEM
    budget was sized for bf16 tiles, so f32 compute halves the allowed
    feature dim rather than overflowing VMEM at lowering time."""
    d = x.shape[-1]
    max_d = _CE_MAX_D * 2 // jnp.dtype(compute_dtype).itemsize
    return d % 128 == 0 and d <= max_d


def _kernel_path_available(d: int, compute_dtype) -> bool:
    """Per-(d, dtype) Mosaic probe: compile+run the fwd and both bwd
    kernels at the caller's feature dim and compute dtype (tile VMEM
    footprint depends on exactly these), falling back to the scan path
    if the backend rejects them (see :mod:`.kernel_probe`)."""
    from ray_lightning_tpu.ops.kernel_probe import kernel_available

    def probe():
        x = jnp.ones((_CE_BLOCK_T, d), jnp.float32) * 0.01
        w = jnp.ones((_CE_BLOCK_V, d), jnp.float32) * 0.01
        t = jnp.zeros((_CE_BLOCK_T,), jnp.int32)

        def probe_loss(x, w):
            return _fused_ce(
                x, w, t, 1, jnp.dtype(compute_dtype), True
            ).mean()

        jax.block_until_ready(jax.grad(probe_loss, argnums=(0, 1))(x, w))

    return kernel_available(
        ("ce", d, jnp.dtype(compute_dtype).name), probe
    )


def _ce_logits_tile(x_ref, w_ref, vi, block_v, vocab_size, vma=()):
    """Shared tile recompute: (Tb, d) x (Vb, d)^T -> masked f32 logits.

    ``vma`` is non-empty only under the Pallas INTERPRETER inside a
    shard_map region, where the kernel body is evaluated as jax ops and
    fresh constants (iota) must be promoted to the refs' varying type.
    Compiled Mosaic never sees it."""
    def _c(val):
        return jax.lax.pvary(val, tuple(vma)) if vma else val

    logits = jax.lax.dot_general(
        x_ref[...], w_ref[...], (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    tb, vb = logits.shape
    vpos = _c(vi * block_v
              + jax.lax.broadcasted_iota(jnp.int32, (tb, vb), 1))
    valid = vpos < _c(jnp.int32(vocab_size))
    return jnp.where(valid, logits, _c(jnp.float32(_NEG_INF))), vpos


def _ce_dlogits(logits, vpos, t_ref, lse_ref, g_ref):
    p = jnp.exp(logits - lse_ref[:, :1])
    onehot = (vpos == t_ref[:, :1]).astype(jnp.float32)
    return (p - onehot) * g_ref[:, :1]


def _ce_bwd_dx_kernel(x_ref, w_ref, t_ref, lse_ref, g_ref, dx_ref, acc_sc,
                      *, vocab_size, block_v, num_vb, vma=()):
    """dx tile: token-major grid, vocab innermost; dx accumulates in VMEM
    across the vocab sweep.  The (Tb, Vb) dlogits tile never reaches HBM
    (the scan backward round-trips every chunk's logits AND dlogits)."""
    from jax.experimental import pallas as pl

    vi = pl.program_id(1)

    @pl.when(vi == 0)
    def _init():
        zeros = jnp.zeros(acc_sc.shape, jnp.float32)
        acc_sc[...] = jax.lax.pvary(zeros, tuple(vma)) if vma else zeros

    logits, vpos = _ce_logits_tile(
        x_ref, w_ref, vi, block_v, vocab_size, vma
    )
    dlog = _ce_dlogits(logits, vpos, t_ref, lse_ref, g_ref)
    acc_sc[...] += jax.lax.dot_general(
        dlog.astype(x_ref.dtype), w_ref[...], (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )

    @pl.when(vi == num_vb - 1)
    def _emit():
        dx_ref[...] = acc_sc[...]


def _ce_bwd_dw_kernel(x_ref, w_ref, t_ref, lse_ref, g_ref, dw_ref, acc_sc,
                      *, vocab_size, block_v, num_tb, vma=()):
    """dwte tile: vocab-major grid, tokens innermost; the (Vb, d) row
    gradient accumulates in VMEM across the token sweep."""
    from jax.experimental import pallas as pl

    vi = pl.program_id(0)
    ti = pl.program_id(1)

    @pl.when(ti == 0)
    def _init():
        zeros = jnp.zeros(acc_sc.shape, jnp.float32)
        acc_sc[...] = jax.lax.pvary(zeros, tuple(vma)) if vma else zeros

    logits, vpos = _ce_logits_tile(
        x_ref, w_ref, vi, block_v, vocab_size, vma
    )
    dlog = _ce_dlogits(logits, vpos, t_ref, lse_ref, g_ref)
    acc_sc[...] += jax.lax.dot_general(
        dlog.astype(x_ref.dtype), x_ref[...], (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )

    @pl.when(ti == num_tb - 1)
    def _emit():
        dw_ref[...] = acc_sc[...]


def _ce_bwd_pallas(x, wte, targets, lse, g, compute_dtype):
    """Kernel-path backward: (dx, dwte) with zero HBM logits traffic.

    Two passes re-deriving the dlogits tile in VMEM: token-major for dx
    (contract over vocab), vocab-major for dwte (contract over tokens).
    One extra logits matmul vs the scan backward — MXU FLOPs traded for
    the HBM round-trips of every (N, Vc) chunk intermediate, the right
    side of the bargain on a bandwidth-bound step.
    """
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    d = x.shape[-1]
    V = wte.shape[0]
    bt = _CE_BLOCK_T
    bv = _CE_BLOCK_V
    x2, t2, n, n_pad, (g2, lse2) = _flatten_pad(
        x, targets, compute_dtype, extras=(g, lse)
    )
    wp, vpad = _pad_vocab(wte, compute_dtype)
    vma = (_vma_of(x2) | _vma_of(t2) | _vma_of(wp) | _vma_of(g2)
           | _vma_of(lse2))
    if vma:
        x2, t2, wp, g2, lse2 = (
            jax.lax.pvary(v, tuple(vma - _vma_of(v)))
            for v in (x2, t2, wp, g2, lse2)
        )
    num_vb = vpad // bv
    num_tb = n_pad // bt
    interp = _interpret()
    kvma = tuple(sorted(vma)) if interp else ()

    dx = pl.pallas_call(
        partial(_ce_bwd_dx_kernel, vocab_size=V, block_v=bv, num_vb=num_vb,
                vma=kvma),
        out_shape=_out_struct((n_pad, d), jnp.float32, vma),
        grid=(num_tb, num_vb),
        in_specs=[
            pl.BlockSpec((bt, d), lambda t, v: (t, 0)),
            pl.BlockSpec((bv, d), lambda t, v: (v, 0)),
            pl.BlockSpec((bt, _LANE), lambda t, v: (t, 0)),
            pl.BlockSpec((bt, _LANE), lambda t, v: (t, 0)),
            pl.BlockSpec((bt, _LANE), lambda t, v: (t, 0)),
        ],
        out_specs=pl.BlockSpec((bt, d), lambda t, v: (t, 0)),
        scratch_shapes=[pltpu.VMEM((bt, d), jnp.float32)],
        interpret=interp,
    )(x2, wp, t2, lse2, g2)

    dw = pl.pallas_call(
        partial(_ce_bwd_dw_kernel, vocab_size=V, block_v=bv, num_tb=num_tb,
                vma=kvma),
        out_shape=_out_struct((vpad, d), jnp.float32, vma),
        grid=(num_vb, num_tb),
        in_specs=[
            pl.BlockSpec((bt, d), lambda v, t: (t, 0)),
            pl.BlockSpec((bv, d), lambda v, t: (v, 0)),
            pl.BlockSpec((bt, _LANE), lambda v, t: (t, 0)),
            pl.BlockSpec((bt, _LANE), lambda v, t: (t, 0)),
            pl.BlockSpec((bt, _LANE), lambda v, t: (t, 0)),
        ],
        out_specs=pl.BlockSpec((bv, d), lambda v, t: (v, 0)),
        scratch_shapes=[pltpu.VMEM((bv, d), jnp.float32)],
        interpret=interp,
    )(x2, wp, t2, lse2, g2)

    dx = dx[:n].reshape(x.shape)
    return dx, dw[:V]


@partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _fused_ce(x, wte, targets, num_chunks, compute_dtype, use_pallas):
    loss, _ = _fused_ce_vjp_fwd(
        x, wte, targets, num_chunks, compute_dtype, use_pallas
    )
    return loss


def _fused_ce_vjp_fwd(x, wte, targets, num_chunks, compute_dtype,
                      use_pallas):
    if use_pallas:
        loss, lse = _ce_fwd_pallas(x, wte, targets, compute_dtype)
        return loss, (x, wte, targets, lse)
    return _fused_ce_fwd(x, wte, targets, num_chunks, compute_dtype)


def _fused_ce_fwd(x, wte, targets, num_chunks, compute_dtype):
    V = wte.shape[0]
    wte_chunks, Vc = _chunk_wte(wte, num_chunks)

    def scan_body(carry, inp):
        m, s, gold = carry
        k, wc = inp
        offset = k * Vc
        logits = _chunk_logits(x, wc, offset, V, compute_dtype)
        cmax = jnp.max(logits, axis=-1)
        m_new = jnp.maximum(m, cmax)
        s = s * jnp.exp(m - m_new) + jnp.sum(
            jnp.exp(logits - m_new[..., None]), axis=-1
        )
        # Gold-label logit if the target falls in this chunk.
        shifted = targets - offset
        in_chunk = (shifted >= 0) & (shifted < Vc)
        picked = jnp.take_along_axis(
            logits, jnp.clip(shifted, 0, Vc - 1)[..., None], axis=-1
        )[..., 0]
        gold = jnp.where(in_chunk, picked, gold)
        return (m_new, s, gold), None

    # Derive the init carry from `targets` so it inherits the input's
    # varying-manual-axes type under shard_map (a constant init makes the
    # scan carry type mismatch its output inside a Manual-mesh region).
    zeros = (targets * 0).astype(jnp.float32)
    init = (zeros + _NEG_INF, zeros, zeros)
    (m, s, gold), _ = jax.lax.scan(
        scan_body, init, (jnp.arange(num_chunks), wte_chunks)
    )
    lse = m + jnp.log(s)
    loss = lse - gold
    return loss, (x, wte, targets, lse)


def _match_vma(val: jax.Array, ref: jax.Array) -> jax.Array:
    """psum ``val`` over manual mesh axes it varies over but ``ref`` does
    not.  Under shard_map the cotangent of a *replicated* (unvarying)
    primal must itself be unvarying — for built-in ops JAX inserts this
    psum when transposing the implicit ``pvary``; a custom_vjp bwd rule
    must do it by hand (VMA type checking rejects the rule otherwise)."""
    try:
        extra = tuple(sorted(jax.typeof(val).vma - jax.typeof(ref).vma))
    except (AttributeError, TypeError):
        return val
    return jax.lax.psum(val, extra) if extra else val


def _fused_ce_bwd(num_chunks, compute_dtype, use_pallas, res, g):
    x, wte, targets, lse = res
    dx, dwte = _ce_bwd_core(
        x, wte, targets, lse, g, num_chunks, compute_dtype, use_pallas
    )
    return (
        _match_vma(dx.astype(x.dtype), x),
        _match_vma(dwte.astype(wte.dtype), wte),
        np.zeros(targets.shape, jax.dtypes.float0),
    )


def _ce_bwd_core(x, wte, targets, lse, g, num_chunks, compute_dtype,
                 use_pallas):
    """(dx, dwte) in f32, no vma handling — shared by the GSPMD custom
    vjp and the shard_map island."""
    V, d = wte.shape
    if use_pallas:
        return _ce_bwd_pallas(
            x, wte, targets, lse, g.astype(jnp.float32), compute_dtype
        )
    wte_chunks, Vc = _chunk_wte(wte, num_chunks)
    g32 = g.astype(jnp.float32)

    def scan_body(dx, inp):
        k, wc = inp
        offset = k * Vc
        logits = _chunk_logits(x, wc, offset, V, compute_dtype)
        p = jnp.exp(logits - lse[..., None])
        shifted = targets - offset
        onehot = (
            (shifted[..., None] == jnp.arange(Vc))
        ).astype(jnp.float32)
        dlogits = (p - onehot) * g32[..., None]
        dl_c = dlogits.astype(compute_dtype)
        dx = dx + jnp.einsum(
            "...v,vd->...d", dl_c, wc.astype(compute_dtype),
            preferred_element_type=jnp.float32,
        )
        dw_c = jnp.einsum(
            "...v,...d->vd", dl_c, x.astype(compute_dtype),
            preferred_element_type=jnp.float32,
        )
        return dx, dw_c

    dx, dw_chunks = jax.lax.scan(
        scan_body,
        x.astype(jnp.float32) * 0,  # varying-typed zeros (see fwd init)
        (jnp.arange(num_chunks), wte_chunks),
    )
    dwte = dw_chunks.reshape(num_chunks * Vc, d)[:V]
    return dx, dwte


_fused_ce.defvjp(_fused_ce_vjp_fwd, _fused_ce_bwd)


def fused_lm_head_cross_entropy(
    x: jax.Array,
    wte: jax.Array,
    targets: jax.Array,
    *,
    num_chunks: Optional[int] = None,
    compute_dtype: jnp.dtype = jnp.bfloat16,
    use_pallas: Optional[bool] = None,
) -> jax.Array:
    """Per-token CE loss of the tied LM head, without materializing logits.

    Args:
        x: final hidden states ``(..., d)`` (any float dtype).
        wte: tied embedding table ``(V, d)``.
        targets: int labels, shape ``x.shape[:-1]``.
        num_chunks: vocab chunks to scan over (default: ~8192-wide chunks).
        compute_dtype: matmul input dtype (f32 accumulation regardless).
        use_pallas: run forward AND backward through the Pallas tile
            kernels (zero HBM logits traffic in both directions).
            Callers that know they are on one chip (no GSPMD-sharded
            operands — a ``pallas_call`` is opaque to the partitioner)
            opt in; default off falls back to the GSPMD-safe scan.

    Returns:
        float32 per-token losses, shape ``targets.shape``.
    """
    if num_chunks is None:
        num_chunks = _pick_num_chunks(wte.shape[0])
    pallas = (
        bool(use_pallas)
        and _pallas_fwd_ok(x, wte, targets, compute_dtype)
        and _kernel_path_available(x.shape[-1], compute_dtype)
    )
    return _fused_ce(
        x, wte, targets, num_chunks, jnp.dtype(compute_dtype), pallas
    )


@partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _fused_ce_shmap(x, wte, targets, mesh, batch_axes, num_chunks,
                    compute_dtype, use_pallas):
    loss, _ = _fused_ce_shmap_fwd(
        x, wte, targets, mesh, batch_axes, num_chunks, compute_dtype,
        use_pallas,
    )
    return loss


def _fused_ce_shmap_fwd(x, wte, targets, mesh, batch_axes, num_chunks,
                        compute_dtype, use_pallas):
    from jax.sharding import PartitionSpec as P

    Pb = P(batch_axes)

    def local(xl, w, tl):
        if use_pallas:
            return _ce_fwd_pallas(xl, w, tl, compute_dtype)
        loss, (_, _, _, lse) = _fused_ce_fwd(
            xl, w, tl, num_chunks, compute_dtype
        )
        return loss, lse

    from ray_lightning_tpu.utils.jax_compat import shard_map

    loss, lse = shard_map(
        local, mesh=mesh, in_specs=(Pb, P(), Pb), out_specs=(Pb, Pb),
        check_vma=False,
    )(x, wte, targets)
    return loss, (x, wte, targets, lse)


def _fused_ce_shmap_bwd(mesh, batch_axes, num_chunks, compute_dtype,
                        use_pallas, res, g):
    from jax.sharding import PartitionSpec as P

    x, wte, targets, lse = res
    Pb = P(batch_axes)
    axes = tuple(a for spec in batch_axes
                 for a in (spec if isinstance(spec, tuple) else (spec,)))

    def local(xl, w, tl, lsel, gl):
        dxl, dwp = _ce_bwd_core(
            xl, w, tl, lsel, gl, num_chunks, compute_dtype, use_pallas
        )
        # check_vma=False shard_map does NOT insert the replicated-input
        # cotangent psum — do it explicitly (each device holds the
        # partial dwte of its batch shard).
        return dxl, jax.lax.psum(dwp, axes)

    from ray_lightning_tpu.utils.jax_compat import shard_map

    dx, dwte = shard_map(
        local, mesh=mesh,
        in_specs=(Pb, P(), Pb, Pb, Pb), out_specs=(Pb, P()),
        check_vma=False,
    )(x, wte, targets, lse, g.astype(jnp.float32))
    return (
        dx.astype(x.dtype),
        dwte.astype(wte.dtype),
        np.zeros(targets.shape, jax.dtypes.float0),
    )


_fused_ce_shmap.defvjp(_fused_ce_shmap_fwd, _fused_ce_shmap_bwd)


def fused_lm_head_cross_entropy_sharded(
    x: jax.Array,
    wte: jax.Array,
    targets: jax.Array,
    mesh,
    *,
    batch_axes: Optional[Tuple[str, ...]] = None,
    num_chunks: Optional[int] = None,
    compute_dtype: jnp.dtype = jnp.bfloat16,
    use_pallas: Optional[bool] = None,
) -> jax.Array:
    """Multi-chip fused CE: a shard_map island running the Pallas kernels
    per device (jit → shard_map → pallas, the canonical distributed-kernel
    pattern).

    Requirements: ``x``/``targets`` batch-sharded on dim 0 over
    ``batch_axes`` and ``wte`` fully replicated (pure DP / ZeRO-1/2 —
    NOT tensor-sharded heads or ZeRO-3). Each device runs the kernel on
    its local tokens against the full vocab; the only collective is one
    psum of the dwte partials in the backward — identical math to the
    GSPMD scan path, minus every chunk intermediate's HBM round-trip.

    Falls back to the scan inside the island when the kernel gate
    (shape/probe) rejects, so callers can use it unconditionally for
    replicated-head meshes.
    """
    if batch_axes is None:
        batch_axes = tuple(
            a for a in mesh.axis_names if a in ("data", "fsdp")
        )
    if not batch_axes:
        raise ValueError(
            f"no batch axes among mesh axes {mesh.axis_names}"
        )
    n_shards = 1
    for a in batch_axes:
        n_shards *= mesh.shape[a]
    if x.shape[0] % n_shards:
        raise ValueError(
            f"batch dim {x.shape[0]} not divisible by "
            f"{batch_axes}={n_shards}"
        )
    if num_chunks is None:
        num_chunks = _pick_num_chunks(wte.shape[0])
    pallas = use_pallas is not False and _pallas_fwd_ok(
        x, wte, targets, compute_dtype
    ) and _kernel_path_available(x.shape[-1], compute_dtype)
    return _fused_ce_shmap(
        x, wte, targets, mesh, tuple(batch_axes), num_chunks,
        jnp.dtype(compute_dtype), pallas,
    )


def naive_lm_head_cross_entropy(
    x: jax.Array, wte: jax.Array, targets: jax.Array,
    compute_dtype: jnp.dtype = jnp.bfloat16,
) -> jax.Array:
    """Reference path: full ``(..., V)`` f32 logits + softmax CE.  Used
    for parity tests and as the small-vocab fallback."""
    import optax

    logits = jnp.einsum(
        "...d,vd->...v",
        x.astype(compute_dtype), wte.astype(compute_dtype),
        preferred_element_type=jnp.float32,
    )
    return optax.softmax_cross_entropy_with_integer_labels(logits, targets)
