"""Fused LM-head + cross-entropy, vocab-chunked — no (B, T, V) tensor.

The naive LM loss (``logits = x @ wte.T`` then softmax-CE) materializes a
``(B, T, V)`` float32 logits tensor in HBM — for GPT-2-small at B=16,
T=1024, V=50304 that is a ~3.3 GB intermediate written and re-read every
step (and its ``(B, T, V)`` gradient again in the backward), which alone
costs ~17% of the step on a v5e.  The reference framework never faces
this because its models are external torch modules
(``/root/reference/examples/ray_ddp_sharded_example.py:48-71``); a
TPU-native framework that owns its flagship LM must own the fix.

Design (TPU/XLA-first):

* **Vocab chunking with online logsumexp.**  ``lax.scan`` over chunks of
  the vocabulary: each iteration computes ``(B, T, Vc)`` logits on the
  fly (bf16 MXU matmul, f32 accumulation), folds them into running
  ``(max, sumexp)`` statistics and the gathered gold-label logit, then
  discards them.  Peak live logits memory drops from ``N*V`` to
  ``N*Vc``.
* **Why chunk vocab, not tokens:** under GSPMD the batch/seq dims are
  sharded over the ``data``(+``fsdp``/``sp``) mesh axes and ``wte`` is
  feature-sharded ``P(None, "tensor")`` (see
  ``models/gpt.py:param_partition_specs``).  Scanning over *vocab* rows
  slices only the replicated dim — no resharding, no cross-device
  gathers; the contraction over the tensor-sharded ``d`` stays a local
  matmul + psum exactly as in the unchunked head.
* **Custom VJP with chunk recompute.**  Residuals are just
  ``(x, wte, targets, lse)`` — the backward rebuilds each chunk's
  logits, forms ``dlogits = (softmax - onehot) * g`` chunk-locally, and
  accumulates ``dx`` (f32 carry) and the per-chunk ``dwte`` rows.  The
  ``(B, T, V)`` gradient tensor never exists either.

Numerics: matmuls run in ``compute_dtype`` (bf16 on TPU) with float32
``preferred_element_type`` accumulation; softmax statistics, the loss and
both gradients accumulate in float32.  With ``compute_dtype=float32``
the result matches the naive path to ~1e-6 (tested in
``tests/test_ops.py``).
"""

from __future__ import annotations

from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["fused_lm_head_cross_entropy", "naive_lm_head_cross_entropy"]

_NEG_INF = -1e30  # finite stand-in for -inf: keeps exp/max well-defined


def _pick_num_chunks(vocab_size: int, target_chunk: int = 8192) -> int:
    return max(1, -(-vocab_size // target_chunk))  # ceil div


def _chunk_wte(wte: jax.Array, num_chunks: int) -> Tuple[jax.Array, int]:
    """(V, d) -> (K, Vc, d), zero-padding V up to K*Vc.

    Vc is rounded up to a multiple of 128 so every chunk matmul and the
    (..., Vc) softmax/onehot ops tile cleanly on the 8x128 vector lanes
    (the valid-mask already neutralizes the padded rows)."""
    V, d = wte.shape
    Vc = -(-V // num_chunks)
    Vc = -(-Vc // 128) * 128
    pad = num_chunks * Vc - V
    if pad:
        wte = jnp.concatenate(
            [wte, jnp.zeros((pad, d), wte.dtype)], axis=0
        )
    return wte.reshape(num_chunks, Vc, d), Vc


def _chunk_logits(x, wte_chunk, offset, vocab_size, compute_dtype):
    """x (..., d) @ wte_chunk (Vc, d)^T -> (..., Vc) f32, padded rows
    masked to -inf."""
    Vc = wte_chunk.shape[0]
    logits = jnp.einsum(
        "...d,vd->...v",
        x.astype(compute_dtype),
        wte_chunk.astype(compute_dtype),
        preferred_element_type=jnp.float32,
    )
    # Mask vocab ids >= vocab_size (zero-padded rows of the last chunk).
    valid = (offset + jnp.arange(Vc)) < vocab_size
    return jnp.where(valid, logits, _NEG_INF)


@partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _fused_ce(x, wte, targets, num_chunks, compute_dtype):
    loss, _ = _fused_ce_fwd(x, wte, targets, num_chunks, compute_dtype)
    return loss


def _fused_ce_fwd(x, wte, targets, num_chunks, compute_dtype):
    V = wte.shape[0]
    wte_chunks, Vc = _chunk_wte(wte, num_chunks)

    def scan_body(carry, inp):
        m, s, gold = carry
        k, wc = inp
        offset = k * Vc
        logits = _chunk_logits(x, wc, offset, V, compute_dtype)
        cmax = jnp.max(logits, axis=-1)
        m_new = jnp.maximum(m, cmax)
        s = s * jnp.exp(m - m_new) + jnp.sum(
            jnp.exp(logits - m_new[..., None]), axis=-1
        )
        # Gold-label logit if the target falls in this chunk.
        shifted = targets - offset
        in_chunk = (shifted >= 0) & (shifted < Vc)
        picked = jnp.take_along_axis(
            logits, jnp.clip(shifted, 0, Vc - 1)[..., None], axis=-1
        )[..., 0]
        gold = jnp.where(in_chunk, picked, gold)
        return (m_new, s, gold), None

    # Derive the init carry from `targets` so it inherits the input's
    # varying-manual-axes type under shard_map (a constant init makes the
    # scan carry type mismatch its output inside a Manual-mesh region).
    zeros = (targets * 0).astype(jnp.float32)
    init = (zeros + _NEG_INF, zeros, zeros)
    (m, s, gold), _ = jax.lax.scan(
        scan_body, init, (jnp.arange(num_chunks), wte_chunks)
    )
    lse = m + jnp.log(s)
    loss = lse - gold
    return loss, (x, wte, targets, lse)


def _match_vma(val: jax.Array, ref: jax.Array) -> jax.Array:
    """psum ``val`` over manual mesh axes it varies over but ``ref`` does
    not.  Under shard_map the cotangent of a *replicated* (unvarying)
    primal must itself be unvarying — for built-in ops JAX inserts this
    psum when transposing the implicit ``pvary``; a custom_vjp bwd rule
    must do it by hand (VMA type checking rejects the rule otherwise)."""
    try:
        extra = tuple(sorted(jax.typeof(val).vma - jax.typeof(ref).vma))
    except (AttributeError, TypeError):
        return val
    return jax.lax.psum(val, extra) if extra else val


def _fused_ce_bwd(num_chunks, compute_dtype, res, g):
    x, wte, targets, lse = res
    V, d = wte.shape
    wte_chunks, Vc = _chunk_wte(wte, num_chunks)
    g32 = g.astype(jnp.float32)

    def scan_body(dx, inp):
        k, wc = inp
        offset = k * Vc
        logits = _chunk_logits(x, wc, offset, V, compute_dtype)
        p = jnp.exp(logits - lse[..., None])
        shifted = targets - offset
        onehot = (
            (shifted[..., None] == jnp.arange(Vc))
        ).astype(jnp.float32)
        dlogits = (p - onehot) * g32[..., None]
        dl_c = dlogits.astype(compute_dtype)
        dx = dx + jnp.einsum(
            "...v,vd->...d", dl_c, wc.astype(compute_dtype),
            preferred_element_type=jnp.float32,
        )
        dw_c = jnp.einsum(
            "...v,...d->vd", dl_c, x.astype(compute_dtype),
            preferred_element_type=jnp.float32,
        )
        return dx, dw_c

    dx, dw_chunks = jax.lax.scan(
        scan_body,
        x.astype(jnp.float32) * 0,  # varying-typed zeros (see fwd init)
        (jnp.arange(num_chunks), wte_chunks),
    )
    dwte = dw_chunks.reshape(num_chunks * Vc, d)[:V]
    dtargets = np.zeros(targets.shape, jax.dtypes.float0)
    return (
        _match_vma(dx.astype(x.dtype), x),
        _match_vma(dwte.astype(wte.dtype), wte),
        dtargets,
    )


_fused_ce.defvjp(_fused_ce_fwd, _fused_ce_bwd)


def fused_lm_head_cross_entropy(
    x: jax.Array,
    wte: jax.Array,
    targets: jax.Array,
    *,
    num_chunks: Optional[int] = None,
    compute_dtype: jnp.dtype = jnp.bfloat16,
) -> jax.Array:
    """Per-token CE loss of the tied LM head, without materializing logits.

    Args:
        x: final hidden states ``(..., d)`` (any float dtype).
        wte: tied embedding table ``(V, d)``.
        targets: int labels, shape ``x.shape[:-1]``.
        num_chunks: vocab chunks to scan over (default: ~8192-wide chunks).
        compute_dtype: matmul input dtype (f32 accumulation regardless).

    Returns:
        float32 per-token losses, shape ``targets.shape``.
    """
    if num_chunks is None:
        num_chunks = _pick_num_chunks(wte.shape[0])
    return _fused_ce(x, wte, targets, num_chunks, jnp.dtype(compute_dtype))


def naive_lm_head_cross_entropy(
    x: jax.Array, wte: jax.Array, targets: jax.Array,
    compute_dtype: jnp.dtype = jnp.bfloat16,
) -> jax.Array:
    """Reference path: full ``(..., V)`` f32 logits + softmax CE.  Used
    for parity tests and as the small-vocab fallback."""
    import optax

    logits = jnp.einsum(
        "...d,vd->...v",
        x.astype(compute_dtype), wte.astype(compute_dtype),
        preferred_element_type=jnp.float32,
    )
    return optax.softmax_cross_entropy_with_integer_labels(logits, targets)
