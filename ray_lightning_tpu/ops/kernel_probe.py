"""Shared Mosaic-availability probing for optional Pallas kernels.

Every optional kernel in :mod:`ray_lightning_tpu.ops` has a numerically
identical XLA/scan fallback; a training step must never die on a
kernel-compile error when the fallback exists.  :func:`kernel_available`
runs a caller-supplied probe (compile+execute the kernels at
representative shapes) once per cache key and downgrades failures:

* compile-class errors (``NotImplementedError``, or any message naming
  Mosaic, VMEM, lowering, or INVALID_ARGUMENT) cache ``False`` — the
  kernel will never work here, use the fallback permanently;
* everything else — including bare ``ValueError``/``TypeError``, which
  can be raised transiently at dispatch time under momentary device
  pressure — falls back for the current call and re-probes next time,
  but only up to :data:`_MAX_IDENTICAL_FAILURES` consecutive *identical*
  failures: a permanent breakage whose message the marker list misses
  must not re-run a multi-second compile on every dispatch forever.  A
  different message resets the count (a changing error is evidence of a
  transient environment, not a fixed compiler verdict).

Off-TPU (the Pallas interpreter) kernels always work: probes are
skipped.
"""

from __future__ import annotations

import os
from typing import Callable, Hashable

import jax

__all__ = ["kernel_available", "kernel_family_disabled", "_interpret"]

_CACHE: dict = {}
# key -> (last failure message, consecutive identical-failure count).
_FAILURES: dict = {}
_MAX_IDENTICAL_FAILURES = 3


def kernel_family_disabled(family: str) -> bool:
    """A/B switch for on-hardware kernel experiments: set
    ``RLT_DISABLE_KERNELS=ce,ln,flash`` (any subset) to force the
    fallback path for those kernel families.  Read per call, so one
    process can bench both arms.  ``bench.py``'s ``kernel_path`` field
    reports the effective result."""
    raw = os.environ.get("RLT_DISABLE_KERNELS", "")
    return family in {s.strip() for s in raw.split(",") if s.strip()}


def _interpret() -> bool:
    """Mosaic compiles only for TPU; every other backend (the CPU test
    meshes) runs the kernels under the Pallas interpreter — the single
    source for that decision across all optional kernels."""
    return jax.default_backend() != "tpu"

# Substrings that mark an exception as "will never compile here".  Kept
# compiler-specific on purpose: a bare ValueError/TypeError raised at
# dispatch time (e.g. under momentary device pressure) must stay
# retryable, so generic words like "lower" alone do not qualify.
_COMPILE_ERROR_MARKERS = (
    "mosaic",
    "vmem",
    "invalid_argument",
    "failed to lower",
    "lowering rule",
    "unsupported lowering",
    "not implemented",
)


def kernel_available(key: Hashable, probe: Callable[[], None]) -> bool:
    """True when the kernels behind ``key`` work on this backend.

    Keys are ``(family, ...)`` tuples; a family disabled via
    ``RLT_DISABLE_KERNELS`` reports unavailable regardless of backend.
    """
    family = key[0] if isinstance(key, tuple) and key else str(key)
    if kernel_family_disabled(family):
        return False
    if _interpret():
        return True
    cached = _CACHE.get(key)
    if cached is not None:
        return cached
    try:
        probe()
        _CACHE[key] = True
        _FAILURES.pop(key, None)
        return True
    except Exception as e:
        import warnings

        msg = f"{type(e).__name__}: {e}"
        permanent = isinstance(e, NotImplementedError) or any(
            m in msg.lower() for m in _COMPILE_ERROR_MARKERS
        )
        if not permanent:
            # Bounded retry for unrecognized failures: N consecutive
            # IDENTICAL messages ⇒ treat as permanent (the marker list
            # missed it) instead of paying the probe compile on every
            # dispatch.  A different message resets the count.
            last_msg, count = _FAILURES.get(key, (None, 0))
            count = count + 1 if msg == last_msg else 1
            _FAILURES[key] = (msg, count)
            if count >= _MAX_IDENTICAL_FAILURES:
                permanent = True
                _FAILURES.pop(key, None)
        if permanent:
            _CACHE[key] = False
        warnings.warn(
            f"Pallas kernels {key!r} unavailable ({msg}); using the "
            f"fallback path{'' if permanent else ' for this call'}."
        )
        return False
