"""Shared Mosaic-availability probing for optional Pallas kernels.

Every optional kernel in :mod:`ray_lightning_tpu.ops` has a numerically
identical XLA/scan fallback; a training step must never die on a
kernel-compile error when the fallback exists.  :func:`kernel_available`
runs a caller-supplied probe (compile+execute the kernels at
representative shapes) once per cache key and downgrades failures:

* compile-class errors (Mosaic lowering, VMEM overflow, invalid
  argument, and the standard Python signature errors) cache ``False`` —
  the kernel will never work here, use the fallback permanently;
* transient runtime errors (e.g. RESOURCE_EXHAUSTED while the device is
  momentarily full) fall back for the current call only and re-probe
  next time.

Off-TPU (the Pallas interpreter) kernels always work: probes are
skipped.
"""

from __future__ import annotations

from typing import Callable, Hashable

import jax

__all__ = ["kernel_available", "_interpret"]

_CACHE: dict = {}


def _interpret() -> bool:
    """Mosaic compiles only for TPU; every other backend (the CPU test
    meshes) runs the kernels under the Pallas interpreter — the single
    source for that decision across all optional kernels."""
    return jax.default_backend() != "tpu"

# Substrings that mark an exception as "will never compile here".
_COMPILE_ERROR_MARKERS = ("mosaic", "vmem", "lower", "invalid_argument")


def kernel_available(key: Hashable, probe: Callable[[], None]) -> bool:
    """True when the kernels behind ``key`` work on this backend."""
    if _interpret():
        return True
    cached = _CACHE.get(key)
    if cached is not None:
        return cached
    try:
        probe()
        _CACHE[key] = True
        return True
    except Exception as e:
        import warnings

        msg = f"{type(e).__name__}: {e}"
        permanent = isinstance(
            e, (NotImplementedError, TypeError, ValueError)
        ) or any(m in msg.lower() for m in _COMPILE_ERROR_MARKERS)
        if permanent:
            _CACHE[key] = False
        warnings.warn(
            f"Pallas kernels {key!r} unavailable ({msg}); using the "
            f"fallback path{'' if permanent else ' for this call'}."
        )
        return False
