"""Pallas TPU flash-attention (causal): forward + fused backward kernels.

The hot op of the transformer family, written TPU-first per the Pallas
playbook (``/opt/skills/guides/pallas_guide.md``):

* grid ``(batch*heads, seq/block_q)`` — one program per query block;
* K/V live in VMEM per (batch,head) and are walked in ``block_k`` slices
  with online softmax (running max/denominator in float32 scratch carries)
  — memory is O(seq · head_dim) instead of the O(seq²) logits tensor;
* the causal structure bounds the inner loop: query block ``i`` visits only
  key blocks ``<= i`` (the upper half of the score matrix is never
  computed, ~2× fewer MXU ops than mask-and-discard);
* logits/accumulators in float32, inputs/outputs in the caller's dtype
  (bfloat16 in the mixed-precision recipe).

Backward pass (FlashAttention-2 style, two kernels):

* the forward additionally emits the per-row log-sum-exp ``lse = m +
  log l``, broadcast across a 128-lane minor dim (the TPU-native layout
  for per-row scalars — same trick as jax.experimental.pallas.ops.tpu);
* ``delta = rowsum(dO · O)`` is computed in-kernel from the O block (a
  few VPU ops on resident data — no O(S·lane) HBM round-trip);
* **dq kernel**: one program per query block, walks key blocks ``<= i``,
  recomputes ``p = exp(s − lse)`` and accumulates ``ds @ K``;
* **dk/dv kernel**: one program per key block, walks query blocks
  ``>= floor(k/block_q)``, accumulating ``pᵀ @ dO`` and ``dsᵀ @ Q``.

So the O(S²) logits tensor is never materialized in either direction —
memory stays O(S·D) at any context length, which is what makes long-
context (ring/sequence-parallel) training viable.

(The reference framework has no analogue — its compute is opaque torch
modules; this file exists because the TPU build owns its model math.)
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

__all__ = ["flash_attention", "DEFAULT_BLOCK_Q", "DEFAULT_BLOCK_K"]

DEFAULT_BLOCK_Q = 512  # tuned on v5e: 512² beats 256² by ~30% fwd+bwd
DEFAULT_BLOCK_K = 512


def pick_block(seq_len: int, prefer: int = DEFAULT_BLOCK_Q) -> Optional[int]:
    """Largest lane-aligned block (<= prefer) that divides ``seq_len``.

    Keeps short/odd sequence lengths (768, 1280, ...) on the flash path
    instead of silently falling back when they don't divide the tuned
    default.  Returns None when no 128-multiple block fits."""
    block = min(prefer, seq_len)
    while block >= 128:
        if seq_len % block == 0 and block % 128 == 0:
            return block
        block //= 2
    return None
_NEG_INF = -1e30
# Lane quantum for block_k (per-row stats are broadcast across lanes in
# VMEM, and the backward tiles them in block_k-wide sweeps).
_LANE = 128
# HBM width of the per-row lse stat.  In VMEM the tile is lane-padded
# anyway, but the HBM array is (BH, S, _STAT_W) — at 128 the saved-
# residual traffic was ~100 MB/layer of 128x-redundant f32 (the single
# largest line in the step profile); 8 keeps a legal f32 tile while
# cutting that 16x.
_STAT_W = 8


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref=None, *, scale, block_q,
                block_k, head_dim):
    # MXU discipline: dot inputs stay in the CALLER's dtype (bf16 in the
    # mixed-precision recipe — f32 inputs would run the MXU at a fraction
    # of peak); accumulation is always f32 via preferred_element_type, and
    # the softmax statistics never leave f32.  ``scale`` is folded into
    # the f32 scores, not pre-multiplied into q (no bf16 rounding of q).
    q = q_ref[0]  # (block_q, d)
    qi = pl.program_id(1)
    q_base = qi * block_q

    def make_body(masked):
        def body(kb, carry):
            acc, m, l = carry
            k_blk = k_ref[0, pl.ds(kb * block_k, block_k), :]
            v_blk = v_ref[0, pl.ds(kb * block_k, block_k), :]
            s = jax.lax.dot_general(
                q, k_blk, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            ) * scale  # (block_q, block_k) f32
            if masked:
                q_pos = q_base + jax.lax.broadcasted_iota(
                    jnp.int32, (block_q, block_k), 0
                )
                k_pos = kb * block_k + jax.lax.broadcasted_iota(
                    jnp.int32, (block_q, block_k), 1
                )
                s = jnp.where(q_pos >= k_pos, s, _NEG_INF)
            m_new = jnp.maximum(m, jnp.max(s, axis=1, keepdims=True))
            p = jnp.exp(s - m_new)
            corr = jnp.exp(m - m_new)
            l_new = l * corr + jnp.sum(p, axis=1, keepdims=True)
            acc_new = acc * corr + jax.lax.dot_general(
                p.astype(v_blk.dtype), v_blk, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            return acc_new, m_new, l_new
        return body

    # Causal structure: key blocks entirely below the diagonal need no
    # mask (saves the iota/compare/where VPU passes on ~all blocks); only
    # blocks straddling the diagonal mask.  Last visible block index:
    # cdiv(q_base + block_q, block_k).
    num_full = q_base // block_k            # fully-visible blocks
    num_kb = pl.cdiv(q_base + block_q, block_k)
    acc0 = jnp.zeros((block_q, head_dim), jnp.float32)
    m0 = jnp.full((block_q, 1), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((block_q, 1), jnp.float32)
    carry = jax.lax.fori_loop(0, num_full, make_body(False), (acc0, m0, l0))
    acc, m, l = jax.lax.fori_loop(num_full, num_kb, make_body(True), carry)
    o_ref[0] = (acc / l).astype(o_ref.dtype)
    if lse_ref is not None:
        lse_ref[0] = jnp.broadcast_to(m + jnp.log(l), (block_q, _STAT_W))


def _interpret() -> bool:
    from ray_lightning_tpu.ops.kernel_probe import _interpret as shared

    return shared()


def _flash_fwd_bhsd(q, k, v, scale, block_q, block_k, want_lse=True):
    """q/k/v: (BH, S, D) merged batch-heads layout -> (out, lse|None).

    ``want_lse=False`` (the primal, non-differentiated path — eval/
    predict) compiles a forward-only kernel with a single output, so no
    O(BH·S·lane) f32 lse tensor is allocated or written.
    """
    bh, s, d = q.shape
    grid = (bh, s // block_q)
    kernel = functools.partial(
        _fwd_kernel, scale=scale, block_q=block_q, block_k=block_k,
        head_dim=d,
    )
    out_shape = jax.ShapeDtypeStruct((bh, s, d), q.dtype)
    out_spec = pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0))
    lse_spec = pl.BlockSpec((1, block_q, _STAT_W), lambda b, i: (b, i, 0))
    result = pl.pallas_call(
        kernel,
        out_shape=(
            out_shape,
            jax.ShapeDtypeStruct((bh, s, _STAT_W), jnp.float32),
        ) if want_lse else out_shape,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, s, d), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, s, d), lambda b, i: (b, 0, 0)),
        ],
        out_specs=(out_spec, lse_spec) if want_lse else out_spec,
        interpret=_interpret(),
    )(q, k, v)
    return result if want_lse else (result, None)


def _bwd_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, o_ref, dk_ref,
                dv_ref, dqp_ref, *, scale, block_q, block_k, head_dim,
                seq_len):
    """One program per KEY block: dk/dv accumulate in registers across the
    query-block walk, and dq contributions are written as a per-key-block
    PARTIAL plane (summed by one cheap XLA reduction afterwards).

    Fusing dq into the dk/dv walk shares the s/p/dp/ds recomputation both
    would otherwise do independently — 5 MXU dots per block pair instead
    of 7 across two kernels.
    """
    ki = pl.program_id(1)
    k_base = ki * block_k
    k = k_ref[0]                                      # (block_k, d)
    v = v_ref[0]
    # Query blocks before the causal frontier contribute nothing — zero
    # exactly those rows (the walk below rewrites everything from the
    # frontier on; zeroing the whole plane would double-write ~half of it
    # on this bandwidth-sensitive path).
    zero_blk = jnp.zeros((block_q, head_dim), dqp_ref.dtype)

    def _zero_dead(qb, _):
        dqp_ref[0, 0, pl.ds(qb * block_q, block_q), :] = zero_blk
        return 0

    jax.lax.fori_loop(0, k_base // block_q, _zero_dead, 0)

    def make_body(masked):
        def body(qb, carry):
            dk_acc, dv_acc = carry
            q_blk = q_ref[0, pl.ds(qb * block_q, block_q), :]
            do_blk = do_ref[0, pl.ds(qb * block_q, block_q), :]
            lse = jnp.broadcast_to(
                lse_ref[0, pl.ds(qb * block_q, block_q), :1],
                (block_q, block_k),
            )
            o_blk = o_ref[0, pl.ds(qb * block_q, block_q), :]
            # delta = rowsum(dO · O) in-kernel: a few VPU ops on resident
            # data instead of an O(S·lane) f32 HBM round-trip per layer.
            delta = jnp.sum(
                do_blk.astype(jnp.float32) * o_blk.astype(jnp.float32),
                axis=1, keepdims=True,
            )
            di = jnp.broadcast_to(delta, (block_q, block_k))
            s = jax.lax.dot_general(
                q_blk, k, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            ) * scale                                 # (block_q, block_k)
            if masked:
                q_pos = qb * block_q + jax.lax.broadcasted_iota(
                    jnp.int32, (block_q, block_k), 0
                )
                k_pos = k_base + jax.lax.broadcasted_iota(
                    jnp.int32, (block_q, block_k), 1
                )
                s = jnp.where(q_pos >= k_pos, s, _NEG_INF)
            p = jnp.exp(s - lse)
            dv_new = dv_acc + jax.lax.dot_general(
                p.astype(do_blk.dtype), do_blk, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )                                         # (block_k, d)
            dp = jax.lax.dot_general(
                do_blk, v, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            # scale folded into ds: dk = (ds*scale)^T @ Q, dq = (ds*scale) @ K.
            ds = (p * (dp - di) * scale).astype(q_blk.dtype)
            dk_new = dk_acc + jax.lax.dot_general(
                ds, q_blk, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            dq_part = jax.lax.dot_general(
                ds, k, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )                                         # (block_q, d)
            dqp_ref[0, 0, pl.ds(qb * block_q, block_q), :] = (
                dq_part.astype(dqp_ref.dtype)
            )
            return dk_new, dv_new
        return body

    # Causal bound from below: query blocks before this key block see
    # nothing here; blocks straddling the diagonal mask, later blocks see
    # the whole key block and skip the mask.
    qb_start = k_base // block_q
    qb_mask_end = pl.cdiv(k_base + block_k, block_q)
    zeros = jnp.zeros((block_k, head_dim), jnp.float32)
    carry = jax.lax.fori_loop(
        qb_start, qb_mask_end, make_body(True), (zeros, zeros)
    )
    dk, dv = jax.lax.fori_loop(
        qb_mask_end, seq_len // block_q, make_body(False), carry
    )
    dk_ref[0] = dk.astype(dk_ref.dtype)
    dv_ref[0] = dv.astype(dv_ref.dtype)


def _flash_bwd_bhsd(q, k, v, out, lse, g, scale, block_q, block_k):
    """Backward over (BH, S, D) tensors; returns (dq, dk, dv)."""
    bh, s, d = q.shape
    nkb = s // block_k
    dk, dv, dqp = pl.pallas_call(
        functools.partial(
            _bwd_kernel, scale=scale, block_q=block_q, block_k=block_k,
            head_dim=d, seq_len=s,
        ),
        out_shape=(
            jax.ShapeDtypeStruct((bh, s, d), k.dtype),
            jax.ShapeDtypeStruct((bh, s, d), v.dtype),
            # dq partials per key block, in the input dtype: each partial
            # is one f32-accumulated dot rounded once (same rounding the
            # two-kernel design paid), and the few-term cross-block sum
            # below runs in f32 — while the partial plane's HBM round-trip
            # is half the width.
            jax.ShapeDtypeStruct((bh, nkb, s, d), q.dtype),
        ),
        grid=(bh, nkb),
        in_specs=[
            pl.BlockSpec((1, s, d), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, s, d), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, s, _STAT_W), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, s, d), lambda b, i: (b, 0, 0)),
        ],
        out_specs=(
            pl.BlockSpec((1, block_k, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, 1, s, d), lambda b, i: (b, i, 0, 0)),
        ),
        interpret=_interpret(),
    )(q, k, v, g, lse, out)
    dq = jnp.sum(dqp.astype(jnp.float32), axis=1).astype(q.dtype)
    return dq, dk, dv


@functools.partial(jax.custom_vjp, nondiff_argnums=(0, 1, 2))
def _flash(scale, block_q, block_k, q, k, v):
    b, s, h, d = q.shape

    def to_bhsd(x):
        return x.transpose(0, 2, 1, 3).reshape(b * h, s, d)

    out, _ = _flash_fwd_bhsd(
        to_bhsd(q), to_bhsd(k), to_bhsd(v), scale, block_q, block_k,
        want_lse=False,
    )
    return out.reshape(b, h, s, d).transpose(0, 2, 1, 3)


def _flash_vjp_fwd(scale, block_q, block_k, q, k, v):
    b, s, h, d = q.shape

    def to_bhsd(x):
        return x.transpose(0, 2, 1, 3).reshape(b * h, s, d)

    # Named so a rematerialized block can SAVE these residuals (policy
    # save_only_these_names / save_from_both_policies) instead of
    # re-running the forward kernel (out/lse) or re-transposing the
    # inputs (q/k/v in kernel layout) to regenerate them.
    from jax.ad_checkpoint import checkpoint_name

    qm = checkpoint_name(to_bhsd(q), "flash_q")
    km = checkpoint_name(to_bhsd(k), "flash_k")
    vm = checkpoint_name(to_bhsd(v), "flash_v")
    out, lse = _flash_fwd_bhsd(qm, km, vm, scale, block_q, block_k)
    out = checkpoint_name(out, "flash_out")
    lse = checkpoint_name(lse, "flash_lse")
    return (
        out.reshape(b, h, s, d).transpose(0, 2, 1, 3),
        (qm, km, vm, out, lse, (b, s, h, d)),
    )


def _flash_vjp_bwd(scale, block_q, block_k, residuals, g):
    qm, km, vm, out, lse, (b, s, h, d) = residuals
    gm = g.transpose(0, 2, 1, 3).reshape(b * h, s, d)
    dq, dk, dv = _flash_bwd_bhsd(
        qm, km, vm, out, lse, gm, scale, block_q, block_k
    )

    def from_bhsd(x):
        return x.reshape(b, h, s, d).transpose(0, 2, 1, 3)

    return from_bhsd(dq), from_bhsd(dk), from_bhsd(dv)


_flash.defvjp(_flash_vjp_fwd, _flash_vjp_bwd)


def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    scale: Optional[float] = None,
    block_q: Optional[int] = None,
    block_k: Optional[int] = None,
) -> jax.Array:
    """Causal flash attention, (B, S, H, D) -> (B, S, H, D)."""
    _, s, _, d = q.shape
    scale = (d ** -0.5) if scale is None else scale
    if block_q is None:
        block_q = pick_block(s) or min(DEFAULT_BLOCK_Q, s)
    if block_k is None:
        block_k = pick_block(s, DEFAULT_BLOCK_K) or min(DEFAULT_BLOCK_K, s)
    if s % block_q or s % block_k:
        raise ValueError(
            f"seq_len {s} must be divisible by block_q={block_q} and "
            f"block_k={block_k}"
        )
    if block_k % _LANE:
        raise ValueError(
            f"block_k={block_k} must be a multiple of {_LANE} (lane "
            f"quantum of the blocked score sweeps)"
        )
    return _flash(scale, block_q, block_k, q, k, v)
