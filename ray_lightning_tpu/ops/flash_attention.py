"""Pallas TPU flash-attention (causal, forward kernel + recompute VJP).

The hot op of the transformer family, written TPU-first per the Pallas
playbook (``/opt/skills/guides/pallas_guide.md``):

* grid ``(batch*heads, seq/block_q)`` — one program per query block;
* K/V live in VMEM per (batch,head) and are walked in ``block_k`` slices
  with online softmax (running max/denominator in float32 scratch carries)
  — memory is O(seq · head_dim) instead of the O(seq²) logits tensor;
* the causal structure bounds the inner loop: query block ``i`` visits only
  key blocks ``<= i`` (the upper half of the score matrix is never
  computed, ~2× fewer MXU ops than mask-and-discard);
* logits/accumulators in float32, inputs/outputs in the caller's dtype
  (bfloat16 in the mixed-precision recipe).

Backward pass: recompute-based ``custom_vjp`` — residuals are just
(q, k, v); the VJP re-runs the XLA reference attention under ``jax.vjp``.
Rematerialization trades FLOPs for HBM exactly like ``jax.checkpoint``;
a fused Pallas backward kernel is the natural next optimization.

(The reference framework has no analogue — its compute is opaque torch
modules; this file exists because the TPU build owns its model math.)
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

__all__ = ["flash_attention", "DEFAULT_BLOCK_Q", "DEFAULT_BLOCK_K"]

DEFAULT_BLOCK_Q = 256
DEFAULT_BLOCK_K = 256
_NEG_INF = -1e30


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, *, scale, block_q, block_k,
                head_dim):
    q = q_ref[0].astype(jnp.float32) * scale  # (block_q, d)
    qi = pl.program_id(1)
    q_base = qi * block_q

    def body(kb, carry):
        acc, m, l = carry
        k_blk = k_ref[0, pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        v_blk = v_ref[0, pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # (block_q, block_k)
        q_pos = q_base + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0
        )
        k_pos = kb * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1
        )
        s = jnp.where(q_pos >= k_pos, s, _NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=1, keepdims=True))
        p = jnp.exp(s - m_new)
        corr = jnp.exp(m - m_new)
        l_new = l * corr + jnp.sum(p, axis=1, keepdims=True)
        acc_new = acc * corr + jax.lax.dot_general(
            p, v_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        return acc_new, m_new, l_new

    # Causal bound: the last key position this query block can see is
    # q_base + block_q - 1, so visit cdiv(q_base + block_q, block_k) blocks.
    num_kb = pl.cdiv(q_base + block_q, block_k)
    acc0 = jnp.zeros((block_q, head_dim), jnp.float32)
    m0 = jnp.full((block_q, 1), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((block_q, 1), jnp.float32)
    acc, _, l = jax.lax.fori_loop(0, num_kb, body, (acc0, m0, l0))
    o_ref[0] = (acc / l).astype(o_ref.dtype)


def _flash_fwd_bhsd(q, k, v, scale, block_q, block_k):
    """q/k/v: (BH, S, D) merged batch-heads layout."""
    bh, s, d = q.shape
    grid = (bh, s // block_q)
    kernel = functools.partial(
        _fwd_kernel, scale=scale, block_q=block_q, block_k=block_k,
        head_dim=d,
    )
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((bh, s, d), q.dtype),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, s, d), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, s, d), lambda b, i: (b, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
        # Mosaic compiles only for TPU; CPU test meshes run the kernel
        # under the Pallas interpreter (same program, host execution).
        interpret=(jax.default_backend() != "tpu"),
    )(q, k, v)


@functools.partial(jax.custom_vjp, nondiff_argnums=(0, 1, 2))
def _flash(scale, block_q, block_k, q, k, v):
    b, s, h, d = q.shape

    def to_bhsd(x):
        return x.transpose(0, 2, 1, 3).reshape(b * h, s, d)

    out = _flash_fwd_bhsd(
        to_bhsd(q), to_bhsd(k), to_bhsd(v), scale, block_q, block_k
    )
    return out.reshape(b, h, s, d).transpose(0, 2, 1, 3)


def _flash_vjp_fwd(scale, block_q, block_k, q, k, v):
    return _flash(scale, block_q, block_k, q, k, v), (q, k, v)


def _flash_vjp_bwd(scale, block_q, block_k, residuals, g):
    from ray_lightning_tpu.ops.attention import xla_causal_attention

    q, k, v = residuals
    _, vjp = jax.vjp(
        lambda q_, k_, v_: xla_causal_attention(q_, k_, v_, scale), q, k, v
    )
    return vjp(g)


_flash.defvjp(_flash_vjp_fwd, _flash_vjp_bwd)


def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    scale: Optional[float] = None,
    block_q: Optional[int] = None,
    block_k: Optional[int] = None,
) -> jax.Array:
    """Causal flash attention, (B, S, H, D) -> (B, S, H, D)."""
    _, s, _, d = q.shape
    scale = (d ** -0.5) if scale is None else scale
    block_q = min(DEFAULT_BLOCK_Q, s) if block_q is None else block_q
    block_k = min(DEFAULT_BLOCK_K, s) if block_k is None else block_k
    if s % block_q or s % block_k:
        raise ValueError(
            f"seq_len {s} must be divisible by block_q={block_q} and "
            f"block_k={block_k}"
        )
    return _flash(scale, block_q, block_k, q, k, v)
