"""Block-scaled int8 storage for optimizer moments — the HBM-traffic diet.

The per-op profile (docs/PERFORMANCE.md "where the remaining time goes")
prices the optimizer line at ~6 ms of pure HBM bandwidth: AdamW reads
and writes two f32 moments per parameter every update.  This module
stores those moments the way :mod:`ray_lightning_tpu.ops.collective_quant`
stores gradient wire traffic — int8 payloads with one f32 absmax scale
per fixed-size block — so the PERSISTENT state costs ~2.06 bytes/param
instead of 8 (a 3.88x cut at ``block_size=128``), and the f32 view
exists only transiently inside the donated train step (dequant → f32
update → requant fuses into the update program; the f32 moments never
round-trip HBM between steps).

Storage unit is :class:`BlockQuantized` — a registered pytree node
carrying the int8 payload + scales as CHILDREN (so jit, donation, ZeRO
sharding, ``eval_shape``, checkpoint writers and the ``RLTSHRD2``
index-selective reshard reader all see two ordinary array leaves) and
the logical shape + quantization mode as static aux data (pickled with
the treedef into checkpoint META, so a round-trip reconstructs the node
bit-exactly).

Numerics choices, argued in docs/PERFORMANCE.md "Optimizer-state
precision & update sharding":

* the FIRST moment quantizes linearly (signed absmax — the same codec
  as the gradient wire, whose error-feedback loss-parity this repo has
  already measured);
* the SECOND moment quantizes in **sqrt domain** (store
  ``sqrt(nu)``): nu spans the square of the gradient's dynamic range,
  and a linear absmax codec would zero any element ~4 orders below its
  block's max — turning ``1/(sqrt(nu)+eps)`` into a 1e8x update spike.
  The sqrt halves the dynamic range in log space, so an element must
  sit ~8 orders below the block max before it rounds to zero.
"""

from __future__ import annotations

from typing import Any, Tuple

import jax
import jax.numpy as jnp

from ray_lightning_tpu.ops.collective_quant import (
    dequantize_block_scaled,
    quantize_block_scaled,
)

__all__ = [
    "BlockQuantized",
    "quantize_moment",
    "dequantize_moment",
    "is_block_quantized",
    "DEFAULT_BLOCK_SIZE",
    "MIN_QUANT_SIZE",
]

# Matches the gradient wire's default block granularity
# (parallel/grad_sync.py): 4 bytes of scale amortized over 128 payload
# bytes = 3.1% overhead, small enough blocks that one outlier only
# poisons 128 neighbours.
DEFAULT_BLOCK_SIZE = 128

# Leaves below this size stay in their float dtype: biases / LayerNorm
# gains are O(d) while the matmul moments are O(d^2) — quantizing them
# buys nothing measurable and costs the riskiest numerics (tiny tensors
# have the least intra-block statistics).  Mirrors the sharding layer's
# ``min_leaf_size`` philosophy.
MIN_QUANT_SIZE = 4096


@jax.tree_util.register_pytree_with_keys_class
class BlockQuantized:
    """One quantized moment tensor: flat padded int8 + per-block scales.

    Children (dynamic, array leaves): ``q`` — int8, 1-D, length padded
    up to a multiple of ``block_size``; ``scale`` — f32,
    ``(q.size // block_size,)``.  Aux (static, rides the treedef):
    ``shape`` — the logical tensor shape; ``block_size``;
    ``sqrt_domain`` — whether the payload encodes ``sqrt(value)``
    (second-moment mode).
    """

    def __init__(self, q: Any, scale: Any, shape: Tuple[int, ...],
                 block_size: int, sqrt_domain: bool):
        self.q = q
        self.scale = scale
        self.shape = tuple(shape)
        self.block_size = int(block_size)
        self.sqrt_domain = bool(sqrt_domain)

    def tree_flatten_with_keys(self):
        return (
            ((jax.tree_util.GetAttrKey("q"), self.q),
             (jax.tree_util.GetAttrKey("scale"), self.scale)),
            (self.shape, self.block_size, self.sqrt_domain),
        )

    @classmethod
    def tree_unflatten(cls, aux, children):
        # Deliberately validation-free: children may be arrays,
        # ShapeDtypeStructs, NamedShardings or None depending on which
        # transform is walking the tree.
        shape, block_size, sqrt_domain = aux
        return cls(children[0], children[1], shape, block_size, sqrt_domain)

    def __repr__(self):
        return (
            f"BlockQuantized(shape={self.shape}, "
            f"block_size={self.block_size}, sqrt={self.sqrt_domain})"
        )


def is_block_quantized(x: Any) -> bool:
    return isinstance(x, BlockQuantized)


def quantize_moment(
    v: jax.Array,
    block_size: int = DEFAULT_BLOCK_SIZE,
    sqrt_domain: bool = False,
) -> BlockQuantized:
    """Float tensor → :class:`BlockQuantized` (flatten, zero-pad to a
    block multiple, optional sqrt transform, absmax block quant)."""
    shape = tuple(v.shape)
    flat = jnp.ravel(v).astype(jnp.float32)
    if sqrt_domain:
        # nu >= 0 by construction; abs() guards the requant of values
        # that dequantization noise nudged below zero.
        flat = jnp.sqrt(jnp.abs(flat))
    pad = (-flat.size) % block_size
    if pad:
        flat = jnp.concatenate([flat, jnp.zeros((pad,), jnp.float32)])
    q, scale = quantize_block_scaled(flat, block_size)
    return BlockQuantized(q, scale, shape, block_size, sqrt_domain)


def dequantize_moment(bq: BlockQuantized,
                      dtype: Any = jnp.float32) -> jax.Array:
    """Inverse of :func:`quantize_moment` (up to rounding)."""
    flat = dequantize_block_scaled(bq.q, bq.scale, bq.block_size)
    if bq.sqrt_domain:
        flat = flat * flat
    size = 1
    for dim in bq.shape:
        size *= dim
    return flat[:size].reshape(bq.shape).astype(dtype)
