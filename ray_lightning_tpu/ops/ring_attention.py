"""Ring attention: causal attention over a sequence-sharded mesh axis.

Long-context support is a first-class capability of this framework and
net-new relative to the reference, which has no sequence-parallel concept
anywhere (SURVEY §5 "long-context: ABSENT ENTIRELY").

Mechanism (Liu et al., "Ring Attention with Blockwise Transformers", 2023):
shard the sequence axis of Q/K/V across a mesh axis; each device keeps its
Q shard resident and the K/V shards travel around the ring via
``lax.ppermute`` (compiler-scheduled over ICI), one hop per step, while an
online-softmax accumulator (running max + denominator, float32) folds in
each visiting block.  After ``axis_size`` steps every query has seen every
(causally visible) key with O(seq/ring) memory per device — sequence
length scales linearly with the ring size.

The core function :func:`ring_causal_attention` is written in per-device
SPMD style and must run inside ``shard_map`` with the sequence axis mapped;
:func:`ring_attention_sharded` is the convenience wrapper that builds the
``shard_map`` for a given mesh.

Causal load balance: with the plain contiguous layout half the ring hops
deliver fully-masked blocks to the low-index devices (device 0's queries
see only chunk 0 — it idles through n-1 hops while device n-1 works every
hop).  The **zig-zag layout** (``layout="zigzag"``, ≙ Megatron context-
parallel's striped sharding) fixes this: the sequence is split into ``2n``
chunks and device ``j`` holds chunks ``j`` and ``2n-1-j`` — one early and
one late chunk — so every device does ~equal unmasked work on every hop
(~2× better causal wall-clock at the same communication volume).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

__all__ = [
    "ring_causal_attention",
    "ring_attention_sharded",
    "zigzag_indices",
]

_NEG_INF = -1e30


def zigzag_indices(seq_len: int, n_shards: int) -> np.ndarray:
    """Permutation taking a normally-ordered sequence to zig-zag shard
    order: shard ``j``'s rows are chunks ``j`` and ``2n-1-j`` of ``2n``
    equal chunks.  ``inverse_permutation(zigzag_indices(...))`` restores
    order; integrated users apply this at the DATA layer (token loader)
    so no runtime gather is needed."""
    if seq_len % (2 * n_shards):
        raise ValueError(
            f"zigzag layout needs seq_len ({seq_len}) divisible by "
            f"2*n_shards ({2 * n_shards})"
        )
    c = seq_len // (2 * n_shards)
    order = []
    for j in range(n_shards):
        order.extend(range(j * c, (j + 1) * c))
        lo = (2 * n_shards - 1 - j) * c
        order.extend(range(lo, lo + c))
    return np.asarray(order, np.int32)


def ring_causal_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    axis_name: str,
    scale: Optional[float] = None,
    layout: str = "contiguous",
) -> jax.Array:
    """Per-device body: q/k/v are the LOCAL sequence shards (B, S/n, H, D).

    Must execute inside ``shard_map`` with ``axis_name`` mapped over the
    sequence-parallel mesh axis.  Differentiable (reverse-mode flows back
    through the ``ppermute`` ring).  With ``layout="zigzag"`` the local
    shard must hold global chunks ``(j, 2n-1-j)`` (see
    :func:`zigzag_indices`); masking is driven purely by global positions,
    so the fold logic is layout-agnostic.
    """
    if layout not in ("contiguous", "zigzag"):
        raise ValueError(
            f"layout={layout!r}: expected 'contiguous' or 'zigzag'"
        )
    axis_size = jax.lax.psum(1, axis_name)
    my_idx = jax.lax.axis_index(axis_name)
    b, s_loc, h, d = q.shape
    scale = (d ** -0.5) if scale is None else scale

    qf = q.astype(jnp.float32) * scale

    def shard_positions(dev_idx):
        """Global sequence positions of device ``dev_idx``'s local rows."""
        if layout == "zigzag":
            c = s_loc // 2
            lo = dev_idx * c
            hi = (2 * axis_size - 1 - dev_idx) * c
            return jnp.concatenate(
                [lo + jnp.arange(c), hi + jnp.arange(c)]
            )
        return dev_idx * s_loc + jnp.arange(s_loc)

    q_pos = shard_positions(my_idx)  # global query positions
    perm = [(j, (j + 1) % axis_size) for j in range(axis_size)]

    def fold(acc, m, l, k_cur, v_cur, i):
        # Which global chunk the ring has delivered to us at step i:
        # data moves j -> j+1 each hop, so after i hops we hold chunk
        # (my_idx - i) mod n.
        src_idx = jax.lax.rem(my_idx - i + axis_size, axis_size)
        k_pos = shard_positions(src_idx)
        logits = jnp.einsum(
            "bqhd,bkhd->bhqk", qf, k_cur.astype(jnp.float32),
            preferred_element_type=jnp.float32,
        )
        mask = q_pos[:, None] >= k_pos[None, :]  # (S/n, S/n), causal-global
        logits = jnp.where(mask[None, None], logits, _NEG_INF)
        m_new = jnp.maximum(m, jnp.max(logits, axis=-1, keepdims=True))
        p = jnp.exp(logits - m_new)
        # Fully-masked block: logits == m_new == NEG_INF makes exp(0)=1 —
        # re-apply the mask so dead blocks contribute exactly zero.
        p = jnp.where(mask[None, None], p, 0.0)
        corr = jnp.exp(m - m_new)
        l_new = l * corr + jnp.sum(p, axis=-1, keepdims=True)
        acc_new = acc * corr + jnp.einsum(
            "bhqk,bkhd->bhqd", p, v_cur.astype(jnp.float32),
            preferred_element_type=jnp.float32,
        )
        return acc_new, m_new, l_new

    def step(carry, i):
        # Permute FIRST: the local (i=0) block is folded before the scan,
        # so every hop's transfer is consumed — no wasted final ppermute.
        k_cur, v_cur, acc, m, l = carry
        k_cur = jax.lax.ppermute(k_cur, axis_name, perm)
        v_cur = jax.lax.ppermute(v_cur, axis_name, perm)
        acc, m, l = fold(acc, m, l, k_cur, v_cur, i)
        return (k_cur, v_cur, acc, m, l), None

    # Initial carries must carry the same varying-manual-axes type as the
    # loop outputs (shard_map VMA typing) — mark them varying over every
    # axis the inputs vary over.
    from ray_lightning_tpu.utils.jax_compat import pcast, vma_of

    vma = vma_of(q)

    def varying(x):
        return pcast(x, vma, to="varying")

    acc0 = varying(jnp.zeros((b, h, s_loc, d), jnp.float32))
    m0 = varying(jnp.full((b, h, s_loc, 1), _NEG_INF, jnp.float32))
    l0 = varying(jnp.zeros((b, h, s_loc, 1), jnp.float32))
    acc0, m0, l0 = fold(acc0, m0, l0, k, v, 0)
    (_, _, acc, _, l), _ = jax.lax.scan(
        step, (k, v, acc0, m0, l0), jnp.arange(1, axis_size)
    )
    out = acc / l  # (b, h, s_loc, d)
    return out.transpose(0, 2, 1, 3).astype(q.dtype)


def ring_attention_sharded(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mesh: Mesh,
    seq_axis: str = "sp",
    data_axis="auto",
    scale: Optional[float] = None,
    layout: str = "contiguous",
) -> jax.Array:
    """Global-view wrapper: (B, S, H, D) arrays, S sharded over ``seq_axis``.

    ``data_axis="auto"`` shards the batch dim over every batch-parallel
    mesh axis (``data`` and ``fsdp`` — matching the train step's batch
    sharding, so no resharding happens at the attention boundary);
    pass ``None`` for a pure sequence-parallel mesh.

    ``layout="zigzag"``: inputs/outputs stay NORMALLY ordered — this
    wrapper applies the zig-zag permutation going in and inverts it going
    out (two sequence-dim gathers).  Long-running training integrations
    should instead permute tokens once at the data layer
    (:func:`zigzag_indices`) and call the per-device body directly.
    """
    from ray_lightning_tpu.utils.jax_compat import shard_map

    from ray_lightning_tpu.parallel import sharding as shardlib

    if layout not in ("contiguous", "zigzag"):
        raise ValueError(
            f"layout={layout!r}: expected 'contiguous' or 'zigzag'"
        )
    if data_axis == "auto":
        batch_axes = shardlib.data_axes(mesh) or None
    elif data_axis in mesh.axis_names:
        batch_axes = data_axis
    else:
        batch_axes = None
    spec = P(batch_axes, seq_axis, None, None)
    fn = functools.partial(
        ring_causal_attention, axis_name=seq_axis, scale=scale,
        layout=layout,
    )
    if layout == "zigzag":
        n = mesh.shape[seq_axis]
        order = jnp.asarray(zigzag_indices(q.shape[1], n))
        inv = jnp.argsort(order)
        q, k, v = (jnp.take(x, order, axis=1) for x in (q, k, v))
    out = shard_map(
        fn, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec
    )(q, k, v)
    if layout == "zigzag":
        out = jnp.take(out, inv, axis=1)
    return out
