"""Fused LayerNorm: one HBM pass per direction instead of XLA's stats +
normalize chains.

LayerNorm is pure bandwidth: per call the residual stream is read for
the mean/var pass and again for the normalization, plus f32 temporaries
— at GPT-2-small shapes the 25 LN sites cost ~7 ms of a ~172 ms step
(docs/PERFORMANCE.md "where the remaining time goes").  The Pallas
kernels read each (block_t, d) tile once, keep the f32 statistics in
registers, and write the output once; the backward recomputes x̂ from
the saved per-row (mu, rstd) — d-sized reductions stay in-tile, and the
cross-token dgamma/dbeta reductions emit tiny per-block partials summed
by one XLA reduction.  The no-grad (eval) primal compiles a y-only
kernel: no statistics are written at all.

Dispatch mirrors :mod:`.cross_entropy`: callers opt in on single-chip
paths (``pallas_call`` is opaque to the GSPMD partitioner), shapes must
be lane-aligned, and a one-time Mosaic probe (:mod:`.kernel_probe`)
falls back to the plain XLA math — which is also the exact
reference-numerics path (f32 stats, tested parity 1e-6).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

from ray_lightning_tpu.ops.kernel_probe import _interpret, kernel_available

__all__ = ["layer_norm"]

_LN_BLOCK_T = 512
# Saved-statistic lane width: 8 (one sublane), the flash-attention lse
# pattern — wide enough for Mosaic tiling, 16x less residual memory
# than a full 128-lane broadcast.
_STAT_W = 8
_LANE = 128
_EPS = 1e-5


def _xla_layer_norm(x, g, b):
    """Reference math (identical to the historical models/gpt.py inline
    implementation — numerics are frozen by parity tests)."""
    x32 = x.astype(jnp.float32)
    mu = jnp.mean(x32, axis=-1, keepdims=True)
    var = jnp.var(x32, axis=-1, keepdims=True)
    y = (x32 - mu) * jax.lax.rsqrt(var + _EPS)
    return (y * g + b).astype(x.dtype)


def _ln_fwd_kernel(x_ref, g_ref, b_ref, y_ref, mu_ref=None, rs_ref=None):
    """Forward tile; ``mu_ref``/``rs_ref`` absent = y-only (eval) mode."""
    x = x_ref[...].astype(jnp.float32)                  # (bt, d)
    mu = jnp.mean(x, axis=1, keepdims=True)
    xc = x - mu
    var = jnp.mean(xc * xc, axis=1, keepdims=True)
    rs = jax.lax.rsqrt(var + _EPS)
    y = xc * rs * g_ref[...].astype(jnp.float32) + b_ref[...].astype(
        jnp.float32
    )
    y_ref[...] = y.astype(y_ref.dtype)
    if mu_ref is not None:
        mu_ref[...] = jnp.broadcast_to(mu, mu_ref.shape)
        rs_ref[...] = jnp.broadcast_to(rs, rs_ref.shape)


def _ln_bwd_kernel(x_ref, g_ref, dy_ref, mu_ref, rs_ref, dx_ref, dg_ref,
                   db_ref):
    x = x_ref[...].astype(jnp.float32)
    dy = dy_ref[...].astype(jnp.float32)
    mu = mu_ref[:, :1]
    rs = rs_ref[:, :1]
    xhat = (x - mu) * rs
    dyg = dy * g_ref[...].astype(jnp.float32)
    m1 = jnp.mean(dyg, axis=1, keepdims=True)
    m2 = jnp.mean(dyg * xhat, axis=1, keepdims=True)
    dx_ref[...] = ((dyg - m1 - xhat * m2) * rs).astype(dx_ref.dtype)
    # Cross-token reductions: per-block partials, summed by XLA (the
    # partial tensors are (num_blocks, d) — negligible traffic).
    dg_ref[...] = jnp.sum(dy * xhat, axis=0, keepdims=True)
    db_ref[...] = jnp.sum(dy, axis=0, keepdims=True)


def _pad_tokens(x2, n):
    n_pad = -(-n // _LN_BLOCK_T) * _LN_BLOCK_T
    if n_pad != n:
        pad_shape = (n_pad - n,) + x2.shape[1:]
        x2 = jnp.concatenate(
            [x2, jnp.zeros(pad_shape, x2.dtype)], axis=0
        )
    return x2, n_pad


def _ln_fwd_pallas(x, g, b, want_stats):
    """Returns ``y`` (x's shape/dtype) and, when ``want_stats``, PADDED
    ``(n_pad, _STAT_W)`` f32 (mu, rstd) ready for the backward."""
    from jax.experimental import pallas as pl

    shape = x.shape
    d = shape[-1]
    x2 = x.reshape(-1, d)
    n = x2.shape[0]
    x2, n_pad = _pad_tokens(x2, n)
    bt = _LN_BLOCK_T
    row_spec = pl.BlockSpec((bt, d), lambda i: (i, 0))
    vec_spec = pl.BlockSpec((1, d), lambda i: (0, 0))
    stat_spec = pl.BlockSpec((bt, _STAT_W), lambda i: (i, 0))
    out_shape = jax.ShapeDtypeStruct((n_pad, d), x.dtype)
    stat_shape = jax.ShapeDtypeStruct((n_pad, _STAT_W), jnp.float32)
    result = pl.pallas_call(
        _ln_fwd_kernel,
        out_shape=(out_shape, stat_shape, stat_shape)
        if want_stats else out_shape,
        grid=(n_pad // bt,),
        in_specs=[row_spec, vec_spec, vec_spec],
        out_specs=(row_spec, stat_spec, stat_spec)
        if want_stats else row_spec,
        interpret=_interpret(),
    )(x2, g.reshape(1, d), b.reshape(1, d))
    if want_stats:
        y, mu, rs = result
        return y[:n].reshape(shape), mu, rs
    return result[:n].reshape(shape), None, None


def _ln_bwd_pallas(x, g, dy, mu_pad, rs_pad):
    from jax.experimental import pallas as pl

    shape = x.shape
    d = shape[-1]
    x2 = x.reshape(-1, d)
    n = x2.shape[0]
    x2, n_pad = _pad_tokens(x2, n)
    # dy stays in its native dtype — the kernel casts per tile; padded
    # rows carry zero cotangent so they contribute nothing.
    dy2, _ = _pad_tokens(dy.reshape(-1, d), n)
    bt = _LN_BLOCK_T
    nb = n_pad // bt
    row_spec = pl.BlockSpec((bt, d), lambda i: (i, 0))
    vec_spec = pl.BlockSpec((1, d), lambda i: (0, 0))
    stat_spec = pl.BlockSpec((bt, _STAT_W), lambda i: (i, 0))
    part_spec = pl.BlockSpec((1, d), lambda i: (i, 0))
    dx, dg_p, db_p = pl.pallas_call(
        _ln_bwd_kernel,
        out_shape=(
            jax.ShapeDtypeStruct((n_pad, d), x.dtype),
            jax.ShapeDtypeStruct((nb, d), jnp.float32),
            jax.ShapeDtypeStruct((nb, d), jnp.float32),
        ),
        grid=(nb,),
        in_specs=[row_spec, vec_spec, row_spec, stat_spec, stat_spec],
        out_specs=(row_spec, part_spec, part_spec),
        interpret=_interpret(),
    )(x2, g.reshape(1, d), dy2, mu_pad, rs_pad)
    return dx[:n].reshape(shape), dg_p.sum(0), db_p.sum(0)


@jax.custom_vjp
def _fused_ln(x, g, b):
    y, _, _ = _ln_fwd_pallas(x, g, b, want_stats=False)
    return y


def _fused_ln_fwd(x, g, b):
    y, mu, rs = _ln_fwd_pallas(x, g, b, want_stats=True)
    return y, (x, g, mu, rs)


def _fused_ln_bwd(res, dy):
    x, g, mu, rs = res
    dx, dg, db = _ln_bwd_pallas(x, g, dy, mu, rs)
    return dx, dg.astype(g.dtype), db.astype(g.dtype)


_fused_ln.defvjp(_fused_ln_fwd, _fused_ln_bwd)


def _kernels_available(d: int, dtype) -> bool:
    def probe():
        x = jnp.ones((_LN_BLOCK_T, d), dtype)
        g = jnp.ones((d,), jnp.float32)
        b = jnp.zeros((d,), jnp.float32)
        jax.block_until_ready(
            jax.grad(lambda x, g, b: _fused_ln(x, g, b).mean().astype(
                jnp.float32
            ), argnums=(0, 1, 2))(x, g, b)
        )

    return kernel_available(("ln", d, jnp.dtype(dtype).name), probe)


def layer_norm(x, g, b, use_pallas: bool = False):
    """LayerNorm over the last dim; f32 statistics, output in ``x.dtype``.

    ``use_pallas=True`` opts into the fused kernels on lane-aligned
    shapes (single-chip / explicit-SPMD callers only — the kernel is
    opaque to the GSPMD partitioner); anything else runs the identical
    XLA math.
    """
    d = x.shape[-1]
    if (use_pallas and d % _LANE == 0
            and _kernels_available(d, x.dtype)):
        return _fused_ln(x, g, b)
    return _xla_layer_norm(x, g, b)
