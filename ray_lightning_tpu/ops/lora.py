"""Batched-gather LoRA application (BGMV): ``y += (x @ A[ids]) @ B[ids]``.

The device-side primitive of multi-tenant LoRA serving
(``serve/lora.py``): every hook site holds the pool's adapters STACKED
in one resident buffer — ``A (N, d, r)`` / ``B (N, r, k)`` per layer —
and a dispatch applies each row's own adapter by gathering its factors
with an int32 ``ids`` operand (the S-LoRA/Punica shape).  ``ids`` is a
VALUE, never a shape, so a batch can mix any adapters and the serving
plane's compiled-once program set never grows with the tenant count.

Slot 0 is the pool's NULL adapter (zero factors): rows with no adapter
gather zeros and pay one rank-``r`` matmul pair for a delta of exactly
0.0 — no branch in the program, mixed base/adapter batches ride the
same dispatch.

Two implementations, selected ONCE at engine build (never per call):

* ``xla`` — gathered einsum pair.  Works everywhere; on CPU (the test
  container) it is the only sensible path.
* ``pallas`` — a per-row kernel that scalar-prefetches ``ids`` and DMAs
  ONLY the selected adapter's factors into VMEM (the gathered einsum
  materializes an ``(W, d, r)`` copy first).  TPU-gated through the
  shared :mod:`.kernel_probe` machinery with the xla path as fallback;
  ``RLT_LORA_BGMV=xla|pallas`` forces an arm for A/B runs
  (``tools/hw_session.sh``).
"""

from __future__ import annotations

import os
from typing import Optional

import jax
import jax.numpy as jnp

from ray_lightning_tpu.ops.kernel_probe import kernel_available

__all__ = ["lora_delta", "apply_lora", "bgmv_xla", "bgmv_pallas",
           "resolve_bgmv_impl"]


def apply_lora(y: jax.Array, h: jax.Array, ad, site: str,
               ids, impl: str) -> jax.Array:
    """``y`` plus hook-site ``site``'s per-slot adapter delta — the ONE
    application hook every program family (static trunk, paged decode,
    paged verify) traces, so the contract (factor naming, id
    semantics, a future per-site operand) has a single edit point.
    ``ad is None`` (every non-serving caller) returns ``y`` unchanged:
    the traced graph is byte-identical to pre-LoRA rounds."""
    if ad is None:
        return y
    return y + lora_delta(h, ad[f"{site}_a"], ad[f"{site}_b"], ids,
                          impl=impl)


def bgmv_xla(h: jax.Array, a: jax.Array, b: jax.Array,
             ids: jax.Array) -> jax.Array:
    """Gathered two-matmul delta for ``h (W, d)``: ``(h @ a[ids]) @
    b[ids]`` → ``(W, k)``.  ``b`` carries the adapter's LoRA scale
    pre-folded (``AdapterPool.add``), so there is no per-row scale
    operand."""
    t = jnp.einsum("wd,wdr->wr", h, a[ids].astype(h.dtype))
    return jnp.einsum("wr,wrk->wk", t, b[ids].astype(h.dtype))


def bgmv_pallas(h: jax.Array, a: jax.Array, b: jax.Array,
                ids: jax.Array) -> jax.Array:
    """Per-row BGMV kernel: grid over the W rows; each step
    scalar-prefetches ``ids[w]`` and block-indexes the stacked factor
    buffers with it, so only the SELECTED adapter's ``(d, r)``/``(r,
    k)`` factors cross HBM→VMEM — the whole point over the gather."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    from ray_lightning_tpu.ops.kernel_probe import _interpret

    W, d = h.shape
    k = b.shape[-1]

    def kernel(ids_ref, h_ref, a_ref, b_ref, out_ref):
        del ids_ref  # consumed by the index maps
        t = jnp.dot(h_ref[...], a_ref[0],
                    preferred_element_type=jnp.float32)
        out_ref[...] = jnp.dot(
            t, b_ref[0].astype(jnp.float32),
            preferred_element_type=jnp.float32,
        ).astype(out_ref.dtype)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(W,),
        in_specs=[
            pl.BlockSpec((1, d), lambda w, ids: (w, 0)),
            pl.BlockSpec((1, a.shape[1], a.shape[2]),
                         lambda w, ids: (ids[w], 0, 0)),
            pl.BlockSpec((1, b.shape[1], b.shape[2]),
                         lambda w, ids: (ids[w], 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, k), lambda w, ids: (w, 0)),
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((W, k), h.dtype),
        interpret=_interpret(),
    )(ids.astype(jnp.int32), h, a.astype(h.dtype), b.astype(h.dtype))


def resolve_bgmv_impl(d: int, r: int, k: int, dtype) -> str:
    """Pick the BGMV arm once (engine build time, never per dispatch).

    ``RLT_LORA_BGMV`` forces an arm; otherwise the Pallas kernel is
    probed at the call shapes through :func:`kernel_available` — on TPU
    a failed probe (tiny ranks Mosaic will not tile) falls back to the
    gathered einsum permanently, off-TPU the gather is simply the
    faster path so the kernel is not selected at all.
    """
    forced = os.environ.get("RLT_LORA_BGMV", "").strip().lower()
    if forced in ("xla", "pallas"):
        return forced
    if jax.default_backend() != "tpu":
        return "xla"

    def probe():
        h = jnp.zeros((2, d), dtype)
        a = jnp.zeros((2, d, r), dtype)
        b = jnp.zeros((2, r, k), dtype)
        jax.block_until_ready(
            bgmv_pallas(h, a, b, jnp.zeros((2,), jnp.int32))
        )

    ok = kernel_available(("lora_bgmv", d, r, k, jnp.dtype(dtype).name),
                          probe)
    return "pallas" if ok else "xla"


def lora_delta(h: jax.Array, a: jax.Array, b: jax.Array,
               ids: jax.Array, impl: str = "xla") -> jax.Array:
    """Adapter delta for ``h`` of shape ``(W, d)`` or ``(B, T, d)``.

    ``ids`` matches the leading axis (one adapter per row/sequence).
    The 3-D form (prefill buckets, verify windows) flattens to rows
    with per-position repeated ids, so both arms serve every program
    family from one entry point.
    """
    if h.ndim == 3:
        B, T, d = h.shape
        flat = lora_delta(
            h.reshape(B * T, d), a, b, jnp.repeat(ids, T), impl=impl
        )
        return flat.reshape(B, T, -1)
    if impl == "pallas":
        return bgmv_pallas(h, a, b, ids)
    return bgmv_xla(h, a, b, ids)
