"""TPU compute ops: XLA reference implementations + Pallas kernels.

The reference framework has no custom compute ops at all — its hot path is
torch DDP + NCCL (``/root/reference/ray_lightning/ray_ddp.py:483``).  This
package is where the TPU build keeps the ops that XLA alone doesn't already
fuse optimally:

* :mod:`.attention` — causal multi-head attention dispatcher
  (XLA einsum reference / Pallas flash kernel / ring sequence-parallel).
* :mod:`.flash_attention` — Pallas TPU flash-attention forward kernel
  (online softmax, blocked over VMEM).
* :mod:`.ring_attention` — causal ring attention over a sequence-sharded
  mesh axis (``shard_map`` + ``lax.ppermute``), the long-context/context-
  parallel primitive (net-new vs the reference, SURVEY §5 "long-context").
"""

from ray_lightning_tpu.ops.attention import causal_attention
from ray_lightning_tpu.ops.collective_quant import (
    dequantize_block_scaled,
    int8_all_reduce,
    quantize_block_scaled,
)
from ray_lightning_tpu.ops.ring_attention import (
    ring_attention_sharded,
    ring_causal_attention,
)

__all__ = [
    "causal_attention",
    "ring_causal_attention",
    "ring_attention_sharded",
    "quantize_block_scaled",
    "dequantize_block_scaled",
    "int8_all_reduce",
]
