"""Version bridges for jax APIs the framework uses.

``shard_map`` graduated from ``jax.experimental.shard_map`` (keyword
``check_rep``) to ``jax.shard_map`` (keyword ``check_vma``); this shim
resolves whichever the installed jax provides so every call site in the
framework spells it one way.  Semantics of the flag are identical for our
purposes: ``False`` disables the replication checker AND the automatic
psum of replicated-input cotangents — the property the custom gradient
reductions (CE island dwte, quantized grad sync) rely on.

``pcast``/``vma_of`` bridge the varying-manual-axes (VMA) typing that
newer jax enforces inside ``shard_map`` loops: on a jax without VMA the
distinction doesn't exist, so ``pcast`` degrades to identity and
``vma_of`` to the empty tuple — both exactly preserve the semantics the
call sites need (marking loop carries varying is a type annotation, not
a computation).
"""

from __future__ import annotations

from typing import Any, Optional, Sequence, Tuple

__all__ = ["shard_map", "pcast", "vma_of"]


def shard_map(
    f,
    mesh=None,
    in_specs=None,
    out_specs=None,
    check_vma: Optional[bool] = None,
    **kwargs: Any,
):
    try:
        from jax import shard_map as _shard_map  # jax >= 0.6

        if check_vma is not None:
            kwargs["check_vma"] = check_vma
    except ImportError:  # pre-graduation jax: experimental home + check_rep
        from jax.experimental.shard_map import shard_map as _shard_map

        if check_vma is not None:
            kwargs["check_rep"] = check_vma
    return _shard_map(
        f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kwargs
    )


def pcast(x: Any, axis_names: Sequence[str], to: str = "varying") -> Any:
    """``jax.lax.pcast`` when the installed jax has VMA typing; identity
    otherwise (pre-VMA shard_map has no varying/invariant distinction to
    cast between)."""
    import jax

    fn = getattr(jax.lax, "pcast", None)
    if fn is None:
        return x
    return fn(x, tuple(axis_names), to=to)


def vma_of(x: Any) -> Tuple[str, ...]:
    """The varying-manual-axes of ``x``'s type; ``()`` on a jax without
    VMA typing."""
    import jax

    typeof = getattr(jax, "typeof", None)
    if typeof is None:
        return ()
    return tuple(typeof(x).vma)
