"""Sentinel for optional dependencies.

TPU-native analogue of the reference's ``Unavailable`` placeholder
(``/root/reference/ray_lightning/util.py:40-44``): a class that can be
referenced at import time but raises with a helpful message the moment a user
tries to instantiate (or otherwise use) it.  Used to gate optional
integrations — real Ray, Ray Tune, torch — so the framework degrades
gracefully when they are absent (the reference's CI exercises exactly this,
``.github/workflows/test.yaml:196-225``).
"""

from __future__ import annotations


class Unavailable:
    """Stand-in for an optional dependency that is not installed."""

    #: Subclasses/instances may override with the missing requirement name.
    _missing_requirement: str = "an optional dependency"

    def __init__(self, *args, **kwargs):
        raise ImportError(
            f"This feature requires {self._missing_requirement}, which is not "
            "installed in this environment."
        )

    def __init_subclass__(cls, **kwargs):
        super().__init_subclass__(**kwargs)

    def __getattr__(self, item):  # pragma: no cover - defensive
        raise ImportError(
            f"This feature requires {self._missing_requirement}, which is not "
            "installed in this environment."
        )


def make_unavailable(requirement: str) -> type:
    """Create an ``Unavailable`` subclass naming the missing requirement."""
    return type(
        f"Unavailable[{requirement}]",
        (Unavailable,),
        {"_missing_requirement": requirement},
    )
