from .unavailable import Unavailable, make_unavailable
from .state_stream import (
    to_state_stream,
    load_state_stream,
    tree_to_bytes,
    tree_from_bytes,
)

__all__ = [
    "Unavailable",
    "make_unavailable",
    "to_state_stream",
    "load_state_stream",
    "tree_to_bytes",
    "tree_from_bytes",
]
