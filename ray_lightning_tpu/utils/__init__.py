from .unavailable import Unavailable, make_unavailable
from .state_stream import (
    to_state_stream,
    load_state_stream,
    tree_to_bytes,
    tree_from_bytes,
)

__all__ = [
    "Unavailable",
    "make_unavailable",
    "to_state_stream",
    "load_state_stream",
    "tree_to_bytes",
    "tree_from_bytes",
    "ORBAX_INSTALLED",
    "save_orbax",
    "load_orbax",
    "import_gpt2",
    "export_gpt2",
    "gpt_config_from_hf",
]

_ORBAX_NAMES = ("ORBAX_INSTALLED", "save_orbax", "load_orbax")
_HF_NAMES = ("import_gpt2", "export_gpt2", "gpt_config_from_hf")


def __getattr__(name):
    # PEP 562 lazy re-exports: importing orbax (~3s of tensorstore) or
    # the HF bridge (torch/transformers) must cost nothing until used.
    if name in _ORBAX_NAMES:
        from . import orbax_io

        return getattr(orbax_io, name)
    if name in _HF_NAMES:
        from . import hf_import

        return getattr(hf_import, name)
    raise AttributeError(name)
