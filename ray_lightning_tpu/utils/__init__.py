from .unavailable import Unavailable, make_unavailable
from .state_stream import (
    to_state_stream,
    load_state_stream,
    tree_to_bytes,
    tree_from_bytes,
)

__all__ = [
    "Unavailable",
    "make_unavailable",
    "to_state_stream",
    "load_state_stream",
    "tree_to_bytes",
    "tree_from_bytes",
    "ORBAX_INSTALLED",
    "save_orbax",
    "load_orbax",
]

_ORBAX_NAMES = ("ORBAX_INSTALLED", "save_orbax", "load_orbax")


def __getattr__(name):
    # PEP 562 lazy re-export: importing orbax costs ~3s (tensorstore),
    # so `import ray_lightning_tpu` must not pay it — only an actual
    # use of the interop bridge does.
    if name in _ORBAX_NAMES:
        from . import orbax_io

        return getattr(orbax_io, name)
    raise AttributeError(name)
