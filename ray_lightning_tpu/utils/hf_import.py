"""Hugging Face GPT-2 weight import: torch checkpoints → the in-framework GPT.

Migration path for reference users: the reference trains torch modules
(its examples wrap torchvision / pl_bolts / HF models in a
LightningModule), so users arriving from it hold torch-format weights.
This module maps a ``transformers`` GPT-2 LM checkpoint onto
:class:`ray_lightning_tpu.models.gpt.GPT`'s parameter pytree, after
which every strategy (ZeRO/TP/SP sharding, generation, tuning) applies
unchanged.

Architecture correspondence (verified numerically in
``tests/test_hf_import.py``):

* HF ``Conv1D`` stores ``(in, out)`` weights — the SAME orientation as
  this framework's right-multiplied matmuls; no transposes.
* HF ``gelu_new`` (tanh approximation) == ``jax.nn.gelu`` default.
* LayerNorm epsilon 1e-5 on both sides; pre-LN blocks; tied LM head.
* Vocab is NOT padded on import: a zero-padded row still contributes
  ``exp(0)`` to every softmax partition, silently shifting the loss, so
  imported configs keep HF's exact vocab (50257) and the vocab-chunked
  CE masks the ragged tail.
"""

from __future__ import annotations

from typing import Any, Dict, Tuple

import numpy as np

__all__ = ["import_gpt2", "export_gpt2", "gpt_config_from_hf"]


def gpt_config_from_hf(hf_config) -> "GPTConfig":  # noqa: F821
    """Map a ``transformers.GPT2Config`` onto :class:`GPTConfig`."""
    from ray_lightning_tpu.models.gpt import GPTConfig

    if getattr(hf_config, "activation_function", "gelu_new") not in (
        "gelu_new", "gelu_pytorch_tanh"
    ):
        raise ValueError(
            f"activation {hf_config.activation_function!r} differs from "
            f"this framework's tanh-approximated GELU; import would be "
            f"numerically wrong"
        )
    eps = float(getattr(hf_config, "layer_norm_epsilon", 1e-5))
    if abs(eps - 1e-5) > 1e-12:
        raise ValueError(
            f"layer_norm_epsilon {eps} != 1e-5 (the framework's fused-LN "
            f"constant); import would drift"
        )
    # Attention-math variants this framework does not implement: each
    # would import cleanly and produce silently divergent logits.
    if getattr(hf_config, "scale_attn_by_inverse_layer_idx", False):
        raise ValueError(
            "scale_attn_by_inverse_layer_idx=True divides attention "
            "scores by (layer_idx+1); this framework scales by "
            "1/sqrt(head_dim) only — import would be numerically wrong"
        )
    if getattr(hf_config, "reorder_and_upcast_attn", False):
        raise ValueError(
            "reorder_and_upcast_attn=True is a different attention "
            "compute order; import would drift"
        )
    if not getattr(hf_config, "scale_attn_weights", True):
        raise ValueError(
            "scale_attn_weights=False omits the 1/sqrt(head_dim) score "
            "scale; this framework always applies it — import would be "
            "numerically wrong"
        )
    n_inner = getattr(hf_config, "n_inner", None)
    if n_inner is not None and n_inner != 4 * hf_config.n_embd:
        raise ValueError(
            f"n_inner {n_inner} != 4*n_embd (the framework's mlp_ratio "
            f"is integral); import unsupported"
        )
    return GPTConfig(
        vocab_size=hf_config.vocab_size,
        n_layer=hf_config.n_layer,
        n_head=hf_config.n_head,
        d_model=hf_config.n_embd,
        seq_len=hf_config.n_positions,
    )


def _t(tensor) -> np.ndarray:
    return tensor.detach().cpu().numpy().astype(np.float32)


def export_gpt2(params, cfg) -> "transformers.GPT2LMHeadModel":  # noqa: F821
    """The inverse of :func:`import_gpt2`: an in-framework GPT param
    tree → a ``transformers.GPT2LMHeadModel`` carrying those weights.

    The migration-OUT path: train/fine-tune here, then serve with the
    HF ecosystem (pipelines, ONNX export, hub upload).  LoRA trees must
    be merged first (``models.gpt.merge_lora``) — adapters have no HF
    GPT-2 representation, so exporting them unmerged is rejected.
    """
    import torch
    import transformers

    from ray_lightning_tpu.models.gpt import has_lora_adapters

    if has_lora_adapters(params):
        raise ValueError(
            "params contain LoRA adapters with no GPT-2 representation; "
            "merge_lora(params, cfg) before export"
        )
    if getattr(cfg, "n_experts", 0) > 0:
        raise ValueError(
            "MoE blocks have no GPT-2 representation; export is dense-only"
        )
    if getattr(cfg, "mlp_ratio", 4) != 4:
        raise ValueError(
            f"mlp_ratio {cfg.mlp_ratio} != 4: GPT-2's n_inner is 4*n_embd "
            f"(the import side enforces the same symmetry)"
        )
    hf_config = transformers.GPT2Config(
        vocab_size=cfg.vocab_size,
        n_positions=cfg.seq_len,
        n_embd=cfg.d_model,
        n_layer=cfg.n_layer,
        n_head=cfg.n_head,
        activation_function="gelu_new",
        layer_norm_epsilon=1e-5,
    )
    model = transformers.GPT2LMHeadModel(hf_config)
    tr = model.transformer

    def put(torch_param, value):
        with torch.no_grad():
            torch_param.copy_(torch.from_numpy(np.asarray(value,
                                                          np.float32)))

    put(tr.wte.weight, params["wte"])
    put(tr.wpe.weight, params["wpe"])
    b = params["blocks"]
    for i, block in enumerate(tr.h):
        put(block.ln_1.weight, b["ln1_g"][i])
        put(block.ln_1.bias, b["ln1_b"][i])
        put(block.attn.c_attn.weight, b["qkv_w"][i])
        put(block.attn.c_attn.bias, b["qkv_b"][i])
        put(block.attn.c_proj.weight, b["proj_w"][i])
        put(block.attn.c_proj.bias, b["proj_b"][i])
        put(block.ln_2.weight, b["ln2_g"][i])
        put(block.ln_2.bias, b["ln2_b"][i])
        put(block.mlp.c_fc.weight, b["mlp_in_w"][i])
        put(block.mlp.c_fc.bias, b["mlp_in_b"][i])
        put(block.mlp.c_proj.weight, b["mlp_out_w"][i])
        put(block.mlp.c_proj.bias, b["mlp_out_b"][i])
    put(tr.ln_f.weight, params["ln_f_g"])
    put(tr.ln_f.bias, params["ln_f_b"])
    model.tie_weights()  # lm_head shares wte, as in the source tree
    model.eval()
    return model


def import_gpt2(hf_model) -> Tuple["GPTConfig", Dict[str, Any]]:  # noqa: F821
    """(config, params) from a ``transformers.GPT2LMHeadModel``.

    Layers are stacked along a leading L dim — the ``lax.scan`` layout
    :meth:`GPT.init_params` uses — so the result drops into any
    strategy/sharding unchanged.
    """
    cfg = gpt_config_from_hf(hf_model.config)
    tr = hf_model.transformer

    def stack(fetch):
        return np.stack([fetch(block) for block in tr.h], axis=0)

    blocks = {
        "ln1_g": stack(lambda b: _t(b.ln_1.weight)),
        "ln1_b": stack(lambda b: _t(b.ln_1.bias)),
        "qkv_w": stack(lambda b: _t(b.attn.c_attn.weight)),
        "qkv_b": stack(lambda b: _t(b.attn.c_attn.bias)),
        "proj_w": stack(lambda b: _t(b.attn.c_proj.weight)),
        "proj_b": stack(lambda b: _t(b.attn.c_proj.bias)),
        "ln2_g": stack(lambda b: _t(b.ln_2.weight)),
        "ln2_b": stack(lambda b: _t(b.ln_2.bias)),
        "mlp_in_w": stack(lambda b: _t(b.mlp.c_fc.weight)),
        "mlp_in_b": stack(lambda b: _t(b.mlp.c_fc.bias)),
        "mlp_out_w": stack(lambda b: _t(b.mlp.c_proj.weight)),
        "mlp_out_b": stack(lambda b: _t(b.mlp.c_proj.bias)),
    }
    params = {
        "wte": _t(tr.wte.weight),
        "wpe": _t(tr.wpe.weight),
        "blocks": blocks,
        "ln_f_g": _t(tr.ln_f.weight),
        "ln_f_b": _t(tr.ln_f.bias),
    }
    return cfg, params
