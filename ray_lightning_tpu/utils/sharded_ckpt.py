"""Per-host sharded restart checkpoints — no all-gather, any-topology load.

The elastic-restart path used to funnel every checkpoint through
``LoopContext._gathered_state`` — a full replication of the train state
onto every host (an XLA all-gather) just so rank 0 could write one file.
For a ZeRO-3 run that defeats parameter sharding exactly at the scale it
targets (SURVEY §7 hard-part #4; VERDICT r3 weak #2).

Here every process writes only its ADDRESSABLE shards:

* ``save_shard``: one file per process inside a checkpoint DIRECTORY
  (``<tag>/shard-00002-of-00008.ckpt``), holding, for every pytree leaf,
  the host-local shard byte blocks plus their global index — deduped per
  unique index, so replicated leaves cost one copy per host, and ZeRO-3
  parameters cost exactly ``1/hosts`` of the model per file.
* ``save_meta`` (rank 0, AFTER a mesh barrier): the pickled treedef, the
  shard count, and the loop metadata (epoch/step/callback states).  A
  directory without ``META.ckpt`` is an incomplete write and is ignored
  by resume discovery — the same torn-file discipline as the atomic
  single-file path.
* ``load_sharded``: reads all shard files, reassembles full host numpy
  leaves by index, and returns the same payload dict the single-file
  format yields — so resume stays topology-independent (save on N hosts,
  restore on 1 or M; the caller re-places onto its own shardings).
* **reshard-on-load** (``load_sharded(dir, shardings=...)``): given the
  NEW mesh's shardings, each host reads only the byte ranges of the
  shard files that overlap its own addressable shards (the ``RLTSHRD2``
  file layout keeps every leaf entry's offset/length in a small header,
  so a ZeRO-3 restore on M hosts never reassembles the full model on
  any of them) and the leaves come back as device-placed ``jax.Array``s
  on the new mesh.  The shard layout problem of arXiv:2004.13336 —
  re-partitioning weight-update shards for a different replica count —
  reduces to index intersection against the recorded global indices.

Trust model matches ``state_stream``: leaf DATA is raw msgpack bytes;
the treedef/metadata are pickled, so checkpoints are only as trustworthy
as their source.
"""

from __future__ import annotations

import os
import pickle
import struct
import zlib
from typing import Any, Dict, List, Optional, Tuple

import jax
import msgpack
import numpy as np

from ray_lightning_tpu.utils.state_stream import (
    CorruptCheckpointError,
    verify_stream_file,
)

__all__ = [
    "save_shard",
    "save_meta",
    "load_sharded",
    "load_meta",
    "is_sharded_ckpt",
    "verify_sharded",
    "verify_checkpoint",
    "CorruptCheckpointError",
    "UnsupportedLeafDtypeError",
    "LEAF_DTYPE_CODECS",
    "LOAD_STATS",
]


class UnsupportedLeafDtypeError(TypeError):
    """A checkpoint leaf's recorded dtype has no registered codec.

    Raised at the checkpoint BOUNDARY (header decode) instead of
    letting ``np.dtype(name)`` crash mid-reassembly: a future
    state-dtype addition (fp8 moments, packed int4, …) that forgets to
    register here fails with the registry in the message — and
    ``verify_sharded`` flags it, so restart discovery walks back to a
    loadable candidate rather than dying inside ``load_sharded``.
    """

_META = "META.ckpt"
_CRC_SUFFIX = ".crc32"
# v2 shard file layout: magic + u32 header length + msgpack header +
# raw data section.  The header carries, per leaf entry, the entry's
# global index AND its (offset, length, crc32) inside the data section,
# so a reshard-on-load reader can seek straight to the bytes its own
# shards need.  v1 files (a bare msgpack blob) still load.
_SHARD_MAGIC = b"RLTSHRD2"

# Accounting of the most recent load_sharded call in this process
# (read-only diagnostics; tests pin the selective reader's I/O here):
# bytes_read counts shard-file payload bytes actually read, selective
# says whether the index-selective path ran.
LOAD_STATS: Dict[str, Any] = {
    "bytes_read": 0, "entries_read": 0, "selective": False,
}


def _shard_name(rank: int, world: int) -> str:
    return f"shard-{rank:05d}-of-{world:05d}.ckpt"


def _np_of(x) -> np.ndarray:
    return np.asarray(jax.device_get(x))


def _leaf_record(leaf: Any) -> Dict[str, Any]:
    """Encode the host-addressable pieces of one pytree leaf."""
    entries: List[Dict[str, Any]] = []
    if isinstance(leaf, jax.Array) and hasattr(leaf, "addressable_shards"):
        shape = leaf.shape
        seen = set()
        for sh in leaf.addressable_shards:
            idx = tuple(
                (
                    0 if s.start is None else int(s.start),
                    dim if s.stop is None else int(s.stop),
                )
                for s, dim in zip(sh.index, shape)
            )
            if idx in seen:  # local replicas: one copy per host
                continue
            seen.add(idx)
            data = _np_of(sh.data)
            entries.append({"i": [list(p) for p in idx], "b": data.tobytes()})
        return {"s": list(shape), "d": _codec_name(leaf.dtype),
                "e": entries}
    arr = _np_of(leaf) if leaf is not None else None
    if arr is None:
        return {"s": None, "d": None, "e": []}
    idx = [[0, dim] for dim in arr.shape]
    return {
        "s": list(arr.shape),
        "d": _codec_name(arr.dtype),
        "e": [{"i": idx, "b": arr.tobytes()}],
    }


def _codec_name(dtype) -> str:
    """Write-side codec gate: refusing an unregistered dtype at SAVE
    time beats writing a checkpoint no reader can open."""
    name = str(dtype)
    if name not in LEAF_DTYPE_CODECS:
        raise UnsupportedLeafDtypeError(
            f"cannot checkpoint a leaf of dtype {name!r}: no registered "
            f"codec (registered: {sorted(LEAF_DTYPE_CODECS)}) — add one "
            "to ray_lightning_tpu.utils.sharded_ckpt.LEAF_DTYPE_CODECS"
        )
    return name


def _encode_shard_v2(rank: int, world: int,
                     records: List[Dict[str, Any]]) -> bytes:
    """Serialize leaf records into the seekable v2 layout: each entry's
    raw bytes move to a trailing data section, and the header keeps the
    entry's global index plus ``(offset, length, crc32)`` so a selective
    reader can fetch exactly the blocks overlapping its shards."""
    data_parts: List[bytes] = []
    offset = 0
    header_leaves: List[Dict[str, Any]] = []
    for rec in records:
        entries = []
        for e in rec["e"]:
            b = e["b"]
            entries.append({
                "i": e["i"], "o": offset, "n": len(b),
                "c": zlib.crc32(b),
            })
            data_parts.append(b)
            offset += len(b)
        header_leaves.append({"s": rec["s"], "d": rec["d"], "e": entries})
    header = msgpack.packb(
        {"rank": rank, "world": world, "leaves": header_leaves},
        use_bin_type=True,
    )
    return b"".join(
        [_SHARD_MAGIC, struct.pack("<I", len(header)), header, *data_parts]
    )


def _read_shard_header(
    path: str, expected_crc: Optional[int] = None,
) -> Tuple[Dict[str, Any], int]:
    """Parse a shard file's header WITHOUT reading its data section.

    Returns ``(header, data_offset)``.  v1 files (no magic) are read in
    full and normalized to the v2 header shape with the entry bytes
    inlined under ``"b"`` (``data_offset == -1`` marks them) — their
    bytes are in memory anyway, so ``expected_crc`` (the META-recorded
    whole-file checksum) is checked here: a v1 shard has no per-entry
    checksums for the selective reader to fall back on.
    """
    with open(path, "rb") as f:
        magic = f.read(len(_SHARD_MAGIC))
        if magic == _SHARD_MAGIC:
            (hlen,) = struct.unpack("<I", f.read(4))
            try:
                header = msgpack.unpackb(f.read(hlen), raw=False)
            except Exception as e:  # noqa: BLE001 - corrupt ≠ crash
                raise CorruptCheckpointError(
                    f"{path}: unparsable shard header ({e})"
                ) from e
            LOAD_STATS["bytes_read"] += len(_SHARD_MAGIC) + 4 + hlen
            return header, len(_SHARD_MAGIC) + 4 + hlen
        raw = magic + f.read()
    LOAD_STATS["bytes_read"] += len(raw)
    if expected_crc is not None and zlib.crc32(raw) != expected_crc:
        raise CorruptCheckpointError(
            f"{path}: checksum mismatch — torn write or bit corruption"
        )
    try:
        payload = msgpack.unpackb(raw, raw=False)
    except Exception as e:  # noqa: BLE001
        raise CorruptCheckpointError(
            f"{path}: unparsable shard file ({e})"
        ) from e
    return payload, -1


def _entry_bytes(path: str, entry: Dict[str, Any], data_offset: int,
                 fh=None) -> bytes:
    """One entry's raw bytes: an inline v1 payload, or a seek+read of
    the v2 data section (verified against the entry's own crc32, so a
    selective load that skips the whole-file checksum still never
    deserializes silently corrupted bytes).  ``fh`` is an already-open
    handle on ``path`` — callers reading many entries of one shard file
    pass it so the read costs one open per FILE, not one per entry."""
    if data_offset < 0:
        return entry["b"]
    if fh is None:
        with open(path, "rb") as f:
            f.seek(data_offset + entry["o"])
            b = f.read(entry["n"])
    else:
        fh.seek(data_offset + entry["o"])
        b = fh.read(entry["n"])
    LOAD_STATS["bytes_read"] += len(b)
    LOAD_STATS["entries_read"] += 1
    expected = entry.get("c")
    if len(b) != entry["n"] or (
        expected is not None and zlib.crc32(b) != expected
    ):
        raise CorruptCheckpointError(
            f"{path}: shard entry at offset {entry['o']} failed its "
            "crc32 — torn write or bit corruption"
        )
    return b


def _bf16_dtype() -> np.dtype:
    import ml_dtypes

    return np.dtype(ml_dtypes.bfloat16)


# The closed set of leaf dtypes this checkpoint format can (de)serialize
# — name → np.dtype factory.  Every dtype a TrainState can legitimately
# carry is here: float params/moments, bf16 moments/activations, int8
# block-quantized optimizer payloads (ops/optim_quant.py), integer
# step/count leaves, bool masks.  Writers of NEW leaf dtypes must
# register a codec (and its round-trip test) or every save becomes a
# checkpoint no reader can open.
LEAF_DTYPE_CODECS = {
    name: (lambda n=name: np.dtype(n))
    for name in (
        "float16", "float32", "float64",
        "int8", "int16", "int32", "int64",
        "uint8", "uint16", "uint32", "uint64",
        "bool",
    )
}
LEAF_DTYPE_CODECS["bfloat16"] = _bf16_dtype


def _dtype_of(name: str) -> np.dtype:
    codec = LEAF_DTYPE_CODECS.get(name)
    if codec is None:
        raise UnsupportedLeafDtypeError(
            f"checkpoint leaf dtype {name!r} has no registered codec "
            f"(registered: {sorted(LEAF_DTYPE_CODECS)}); a new state "
            "dtype must be added to "
            "ray_lightning_tpu.utils.sharded_ckpt.LEAF_DTYPE_CODECS"
        )
    return codec()


def save_shard(tree: Any, dirpath: str, rank: int, world: int) -> str:
    """Write this process's addressable shards of ``tree`` (atomic).

    A ``<shard>.crc32`` sidecar records the blob's checksum; rank 0
    folds every sidecar into META (the authoritative, post-barrier
    record) so loads can verify each shard without trusting the shard
    file's own bytes.
    """
    os.makedirs(dirpath, exist_ok=True)
    leaves, _ = jax.tree_util.tree_flatten(tree)
    blob = _encode_shard_v2(
        rank, world, [_leaf_record(leaf) for leaf in leaves]
    )
    path = os.path.join(dirpath, _shard_name(rank, world))
    tmp = f"{path}.tmp{os.getpid()}"
    with open(tmp, "wb") as f:
        f.write(blob)
    os.replace(tmp, path)
    crc_tmp = f"{path}{_CRC_SUFFIX}.tmp{os.getpid()}"
    with open(crc_tmp, "w") as f:
        f.write(str(zlib.crc32(blob)))
    os.replace(crc_tmp, f"{path}{_CRC_SUFFIX}")
    from ray_lightning_tpu.fault import inject as _chaos

    _chaos.fire("ckpt_write", path=path, rank=rank)
    return path


def _collect_shard_crcs(dirpath: str, world: int) -> Dict[str, int]:
    """Rank-0, post-barrier: gather every shard's sidecar checksum."""
    crcs: Dict[str, int] = {}
    for r in range(world):
        sidecar = os.path.join(
            dirpath, _shard_name(r, world) + _CRC_SUFFIX
        )
        try:
            with open(sidecar) as f:
                crcs[str(r)] = int(f.read().strip())
        except (OSError, ValueError):
            continue  # written by an older save_shard — verification
            # simply skips this rank
    return crcs


def save_meta(tree: Any, dirpath: str, world: int,
              extra: Optional[Dict[str, Any]] = None) -> str:
    """Rank-0 completeness marker.  Callers MUST barrier after
    ``save_shard`` and before this — META asserts every shard is durable.

    v2 format: the body (treedef + extra + per-shard checksums) is
    wrapped in a self-checksummed envelope, so a torn/corrupted META is
    DETECTED rather than unpickled into garbage.  v1 METAs still load.
    """
    _, treedef = jax.tree_util.tree_flatten(tree)
    body = msgpack.packb(
        {"world": world,
         "treedef": pickle.dumps(treedef),
         "extra": pickle.dumps(extra or {}),
         "shard_crcs": _collect_shard_crcs(dirpath, world)},
        use_bin_type=True,
    )
    blob = msgpack.packb(
        {"v": 2, "crc": zlib.crc32(body), "body": body},
        use_bin_type=True,
    )
    path = os.path.join(dirpath, _META)
    tmp = f"{path}.tmp{os.getpid()}"
    with open(tmp, "wb") as f:
        f.write(blob)
    os.replace(tmp, path)
    from ray_lightning_tpu.fault import inject as _chaos

    _chaos.fire("meta_write", path=path)
    return path


def _load_meta(dirpath: str) -> Dict[str, Any]:
    """Read + verify META (v2 envelope or v1 raw body)."""
    path = os.path.join(dirpath, _META)
    try:
        with open(path, "rb") as f:
            raw = f.read()
    except OSError as e:
        raise CorruptCheckpointError(f"{path}: unreadable ({e})") from e
    try:
        doc = msgpack.unpackb(raw, raw=False)
    except Exception as e:  # noqa: BLE001 - any parse failure = corrupt
        raise CorruptCheckpointError(
            f"{path}: unparsable META ({e})"
        ) from e
    if isinstance(doc, dict) and "body" in doc and "crc" in doc:
        body = doc["body"]
        actual = zlib.crc32(body)
        if actual != doc["crc"]:
            raise CorruptCheckpointError(
                f"{path}: META checksum mismatch (stored "
                f"{doc['crc']:#010x}, computed {actual:#010x})"
            )
        try:
            doc = msgpack.unpackb(body, raw=False)
        except Exception as e:  # noqa: BLE001
            raise CorruptCheckpointError(
                f"{path}: unparsable META body ({e})"
            ) from e
    if not isinstance(doc, dict) or "world" not in doc:
        raise CorruptCheckpointError(f"{path}: META has no world size")
    return doc


def is_sharded_ckpt(path: str) -> bool:
    return os.path.isdir(path) and os.path.exists(
        os.path.join(path, _META)
    )


def load_meta(dirpath: str) -> Dict[str, Any]:
    """META alone — the cheap pre-load peek: ``{"world": <shard count>,
    "extra": {...}}`` without touching any shard file.  The elastic
    resume path reads the recorded ``world_size``/``accum`` here BEFORE
    building the optimizer, so the accumulation factor can be re-derived
    for a different world size (global-batch invariance)."""
    meta = _load_meta(dirpath)
    return {
        "world": meta["world"],
        "extra": pickle.loads(meta["extra"]) if "extra" in meta else {},
    }


def _parse_header_from_blob(
    raw: bytes, path: str
) -> Tuple[Dict[str, Any], int]:
    """Parse an in-memory shard blob's header WITHOUT materializing
    entry bytes — the one place the file framing is decoded from bytes
    (``_parse_shard_blob`` layers byte inlining on top;
    ``verify_sharded``'s codec pre-flight uses the header alone).

    Returns ``(header, data_offset)``; ``data_offset == -1`` marks a
    v1 blob, whose "header" is the full payload with bytes already
    inline.  Any framing damage — truncation included — raises
    :class:`CorruptCheckpointError`, never a bare decode error.
    """
    if raw[: len(_SHARD_MAGIC)] == _SHARD_MAGIC:
        base = len(_SHARD_MAGIC) + 4
        if len(raw) < base:
            raise CorruptCheckpointError(
                f"{path}: truncated shard header — torn write"
            )
        (hlen,) = struct.unpack("<I", raw[len(_SHARD_MAGIC): base])
        try:
            header = msgpack.unpackb(raw[base: base + hlen], raw=False)
        except Exception as e:  # noqa: BLE001
            raise CorruptCheckpointError(
                f"{path}: unparsable shard header ({e})"
            ) from e
        return header, base + hlen
    try:
        return msgpack.unpackb(raw, raw=False), -1
    except Exception as e:  # noqa: BLE001 - corrupt ≠ crash-on-load
        raise CorruptCheckpointError(
            f"{path}: unparsable shard file ({e})"
        ) from e


def _parse_shard_blob(raw: bytes, path: str) -> Dict[str, Any]:
    """An in-memory shard blob → normalized v1-shaped payload (entry
    bytes inlined under ``"b"``), accepting both file layouts."""
    header, data_off = _parse_header_from_blob(raw, path)
    if data_off >= 0:
        for rec in header["leaves"]:
            for e in rec["e"]:
                e["b"] = raw[data_off + e["o"]: data_off + e["o"] + e["n"]]
    return header


def _check_shard_identity(payload: Dict[str, Any], dirpath: str,
                          path: str, rank: int, world: int) -> None:
    # Guard against rank mixups / stale copies: the file must agree
    # with its own name about who wrote it for which world size.
    if payload.get("rank") != rank or payload.get("world") != world:
        raise ValueError(
            f"sharded checkpoint {dirpath}: {os.path.basename(path)} "
            f"claims rank={payload.get('rank')} world="
            f"{payload.get('world')} — rank mixup or stale copy"
        )


def _needed_regions(sharding: Any, shape: tuple) -> Optional[List[tuple]]:
    """The unique global index regions THIS PROCESS's devices hold under
    ``sharding``, as ``((start, stop), ...)`` per-dim tuples — or
    ``None`` when the target is not a device sharding (caller falls
    back to a full host read of that leaf)."""
    index_map_fn = getattr(
        sharding, "addressable_devices_indices_map", None
    )
    if index_map_fn is None:
        return None
    try:
        index_map = index_map_fn(tuple(shape))
    except Exception:  # noqa: BLE001 - shape/sharding mismatch: the
        # caller's coverage check will say so on the full-read path.
        return None
    regions = set()
    for idx in index_map.values():
        regions.add(tuple(
            (0 if s.start is None else int(s.start),
             dim if s.stop is None else int(s.stop))
            for s, dim in zip(idx, shape)
        ))
    return sorted(regions)


def _regions_overlap(a: tuple, b: tuple) -> bool:
    return all(
        max(a0, b0) < min(a1, b1) for (a0, a1), (b0, b1) in zip(a, b)
    )


def _flatten_shardings(shardings: Any, treedef) -> Optional[List[Any]]:
    """Sharding leaves congruent with the CHECKPOINT's treedef, or
    ``None`` when the structures differ (a checkpoint carrying an EF
    residual restored into a run without one, and vice versa) — the
    caller then falls back to the topology-independent full read."""
    if shardings is None:
        return None
    try:
        flat, sh_def = jax.tree_util.tree_flatten(shardings)
    except Exception:  # noqa: BLE001
        return None
    if sh_def != treedef:
        return None
    return flat


def load_sharded(dirpath: str, shardings: Any = None) -> Dict[str, Any]:
    """Reassemble a payload dict: ``{"state": tree, **extra}``.

    Without ``shardings`` every leaf comes back as a full host numpy
    array (save on N hosts, restore anywhere).  With ``shardings`` — a
    pytree of ``jax.sharding.Sharding`` congruent with the saved state —
    the **index-selective** path runs: this process reads only the shard
    -file byte ranges overlapping its own addressable shards and the
    leaves come back as ``jax.Array``s already placed on the new mesh
    (reshard-on-load; no full-model reassembly on any host).  A
    structure mismatch between ``shardings`` and the checkpoint falls
    back to the full host read.

    Verify-on-load: the full path checks every shard file's bytes
    against the META-recorded checksum; the selective path checks each
    entry it reads against the entry's own crc32 (whole-file checksums
    would force reading the bytes selectivity exists to skip) — either
    way a bit-flipped or torn block raises
    :class:`CorruptCheckpointError` instead of silently resuming garbage
    into the params.
    """
    meta = _load_meta(dirpath)
    world = meta["world"]
    treedef = pickle.loads(meta["treedef"])
    extra = pickle.loads(meta["extra"])
    shard_crcs = meta.get("shard_crcs") or {}
    LOAD_STATS.update(bytes_read=0, entries_read=0, selective=False)

    shard_files = [
        os.path.join(dirpath, _shard_name(r, world)) for r in range(world)
    ]
    missing = [p for p in shard_files if not os.path.exists(p)]
    if missing:
        raise FileNotFoundError(
            f"sharded checkpoint {dirpath} is missing "
            f"{len(missing)}/{world} shard files (e.g. {missing[0]})"
        )

    sharding_leaves = _flatten_shardings(shardings, treedef)
    if sharding_leaves is not None:
        out = _load_selective(
            dirpath, shard_files, world, sharding_leaves, shard_crcs
        )
        if out is not None:
            LOAD_STATS["selective"] = True
            return {"state": jax.tree_util.tree_unflatten(treedef, out),
                    **extra}

    leaves: List[Optional[np.ndarray]] = []
    covered: List[Optional[np.ndarray]] = []
    for rank, path in enumerate(shard_files):
        with open(path, "rb") as f:
            raw = f.read()
        LOAD_STATS["bytes_read"] += len(raw)
        expected = shard_crcs.get(str(rank))
        if expected is not None and zlib.crc32(raw) != expected:
            raise CorruptCheckpointError(
                f"sharded checkpoint {dirpath}: "
                f"{os.path.basename(path)} checksum mismatch — torn "
                "write or bit corruption"
            )
        payload = _parse_shard_blob(raw, f"sharded checkpoint {dirpath}")
        _check_shard_identity(payload, dirpath, path, rank, world)
        records = payload["leaves"]
        if not leaves:
            leaves = [None] * len(records)
            covered = [None] * len(records)
        for i, rec in enumerate(records):
            if rec["s"] is None:
                continue
            shape = tuple(rec["s"])
            dtype = _dtype_of(rec["d"])
            if leaves[i] is None:
                leaves[i] = np.empty(shape, dtype)
                covered[i] = np.zeros(shape, bool)
            for entry in rec["e"]:
                idx = tuple(slice(a, b) for a, b in entry["i"])
                block_shape = tuple(b - a for a, b in entry["i"])
                block = np.frombuffer(
                    entry["b"], dtype=dtype
                ).reshape(block_shape)
                if idx:
                    leaves[i][idx] = block
                    covered[i][idx] = True
                else:  # 0-d leaf
                    leaves[i] = block.copy()
                    covered[i] = np.ones((), bool)

    # Coverage check: every REGION of every leaf must have been written
    # by some shard (a per-region mask, not an element count — duplicate
    # writes of one region must not mask a hole elsewhere, which would be
    # np.empty garbage silently resumed into the params).
    for i, mask in enumerate(covered):
        if mask is None or leaves[i] is None or leaves[i].size == 0:
            continue
        if not bool(np.all(mask)):
            missing = int(mask.size - np.count_nonzero(mask))
            raise ValueError(
                f"sharded checkpoint {dirpath}: leaf {i} has {missing}/"
                f"{mask.size} uncovered elements — shard entries are "
                f"incomplete or corrupt"
            )

    tree = jax.tree_util.tree_unflatten(treedef, leaves)
    return {"state": tree, **extra}


def _load_selective(
    dirpath: str,
    shard_files: List[str],
    world: int,
    sharding_leaves: List[Any],
    shard_crcs: Dict[str, int],
) -> Optional[List[Any]]:
    """The index-selective reader: per leaf, read only the shard-file
    entries overlapping this process's addressable regions, assemble a
    host buffer spanning just their bounding box, and place the leaf as
    a ``jax.Array`` via ``make_array_from_callback``.  Returns the leaf
    list, or ``None`` when any target leaf is not a device sharding
    (the caller then runs the topology-independent full read)."""
    # Per-leaf plan, fixed by the FIRST shard file's header (every shard
    # file records the same leaf shapes — only entry coverage differs).
    # v1 files (fully in memory anyway) verify their META whole-file
    # checksum here; v2 files verify per ENTRY at read time.
    first_header, first_off = _read_shard_header(
        shard_files[0], shard_crcs.get("0")
    )
    _check_shard_identity(
        first_header, dirpath, shard_files[0], 0, world
    )
    n_leaves = len(first_header["leaves"])
    if n_leaves != len(sharding_leaves):
        return None

    needs: List[Optional[List[tuple]]] = []
    for rec, sharding in zip(first_header["leaves"], sharding_leaves):
        if rec["s"] is None:
            needs.append(None)
            continue
        regions = _needed_regions(sharding, tuple(rec["s"]))
        if regions is None:
            # Host-side target (e.g. a residual the caller rebuilds):
            # selective placement is impossible for this tree — let the
            # full path produce host leaves uniformly.
            return None
        needs.append(regions)

    # Bounding box + host buffer per leaf (None shape leaves stay None).
    box_lo: List[Optional[tuple]] = []
    bufs: List[Optional[np.ndarray]] = []
    masks: List[Optional[np.ndarray]] = []
    dtypes: List[Any] = []
    for rec, regions in zip(first_header["leaves"], needs):
        if rec["s"] is None or regions is None:
            box_lo.append(None)
            bufs.append(None)
            masks.append(None)
            dtypes.append(None)
            continue
        shape = tuple(rec["s"])
        dtype = _dtype_of(rec["d"])
        dtypes.append(dtype)
        if not shape:
            box_lo.append(())
            bufs.append(np.empty((), dtype))
            masks.append(np.zeros((), bool))
            continue
        lo = tuple(
            min(r[d][0] for r in regions) for d in range(len(shape))
        )
        hi = tuple(
            max(r[d][1] for r in regions) for d in range(len(shape))
        )
        box_lo.append(lo)
        box_shape = tuple(h - l for l, h in zip(lo, hi))
        bufs.append(np.empty(box_shape, dtype))
        masks.append(np.zeros(box_shape, bool))

    seen_entries: List[set] = [set() for _ in range(n_leaves)]
    headers = [(first_header, first_off)]
    for rank in range(1, world):
        header, off = _read_shard_header(
            shard_files[rank], shard_crcs.get(str(rank))
        )
        _check_shard_identity(
            header, dirpath, shard_files[rank], rank, world
        )
        headers.append((header, off))

    for rank, (header, data_off) in enumerate(headers):
        path = shard_files[rank]
        # One open per shard FILE, not one per entry: thousands of
        # pytree leaves would otherwise pay an open/close round-trip
        # each (a metadata RPC apiece on network filesystems).
        fh = open(path, "rb") if data_off >= 0 else None
        try:
            for i, rec in enumerate(header["leaves"]):
                regions = needs[i]
                if regions is None or rec["s"] is None:
                    continue
                shape = tuple(rec["s"])
                lo = box_lo[i]
                for entry in rec["e"]:
                    eidx = tuple((a, b) for a, b in entry["i"])
                    if eidx in seen_entries[i]:
                        continue  # local replica already read elsewhere
                    if not shape:  # 0-d leaf: any entry IS the value
                        seen_entries[i].add(eidx)
                        b = _entry_bytes(path, entry, data_off, fh)
                        bufs[i] = np.frombuffer(
                            b, dtype=dtypes[i]
                        ).reshape(()).copy()
                        masks[i] = np.ones((), bool)
                        continue
                    if not any(
                        _regions_overlap(eidx, r) for r in regions
                    ):
                        continue
                    seen_entries[i].add(eidx)
                    b = _entry_bytes(path, entry, data_off, fh)
                    block = np.frombuffer(b, dtype=dtypes[i]).reshape(
                        tuple(b1 - a1 for a1, b1 in eidx)
                    )
                    # Clip the entry to the bounding box and copy in.
                    box_shape = bufs[i].shape
                    dst = tuple(
                        slice(max(a1 - l, 0), min(b1 - l, sz))
                        for (a1, b1), l, sz in zip(eidx, lo, box_shape)
                    )
                    src = tuple(
                        slice(d.start + l - a1, d.stop + l - a1)
                        for d, l, (a1, _) in zip(dst, lo, eidx)
                    )
                    if any(d.start >= d.stop for d in dst):
                        continue
                    bufs[i][dst] = block[src]
                    masks[i][dst] = True
        finally:
            if fh is not None:
                fh.close()

    # Coverage: every NEEDED region must be fully present.
    for i, regions in enumerate(needs):
        if regions is None or masks[i] is None:
            continue
        lo = box_lo[i]
        for r in regions:
            if not r:
                sub = masks[i]
            else:
                sub = masks[i][tuple(
                    slice(a - l, b - l) for (a, b), l in zip(r, lo)
                )]
            if not bool(np.all(sub)):
                raise ValueError(
                    f"sharded checkpoint {dirpath}: leaf {i} region "
                    f"{r} is not fully covered by any shard — entries "
                    "are incomplete or corrupt"
                )

    out: List[Any] = []
    for i, rec in enumerate(first_header["leaves"]):
        if rec["s"] is None:
            out.append(None)
            continue
        shape = tuple(rec["s"])
        sharding = sharding_leaves[i]
        buf, lo = bufs[i], box_lo[i]

        def cb(idx, buf=buf, lo=lo, shape=shape):
            if not shape:
                return buf
            return buf[tuple(
                slice(
                    (0 if s.start is None else s.start) - l,
                    (dim if s.stop is None else s.stop) - l,
                )
                for s, l, dim in zip(idx, lo, shape)
            )]

        out.append(
            jax.make_array_from_callback(shape, sharding, cb)
        )
    return out


def verify_sharded(dirpath: str) -> List[str]:
    """Integrity problems of a sharded checkpoint (empty = valid):
    META parse + self-checksum, every shard present, every shard's
    bytes matching its META-recorded checksum.  Reads shard bytes but
    deserializes nothing — restart discovery calls this on every
    candidate while walking back to the newest verified one."""
    problems: List[str] = []
    try:
        meta = _load_meta(dirpath)
    except CorruptCheckpointError as e:
        return [str(e)]
    world = meta["world"]
    shard_crcs = meta.get("shard_crcs") or {}
    # Shard-count agreement (elastic discovery's pre-flight): any shard
    # file whose NAME disagrees with META's recorded world size marks a
    # stale copy or a half-migrated directory — resuming would either
    # miss shards (FileNotFoundError mid-restart) or mix topologies.
    # Flagging it here lets restart discovery skip the candidate with a
    # ``ckpt_corrupt`` event and walk back to the previous verified set
    # instead of failing inside ``load_sharded``.
    try:
        names = [
            n for n in os.listdir(dirpath)
            if n.startswith("shard-") and n.endswith(".ckpt")
        ]
    except OSError as e:
        return [f"{dirpath}: unreadable ({e})"]
    for name in sorted(names):
        try:
            claimed_world = int(name.split("-of-")[1].split(".")[0])
        except (IndexError, ValueError):
            problems.append(f"{dirpath}/{name}: unparsable shard name")
            continue
        if claimed_world != world:
            problems.append(
                f"{dirpath}/{name}: shard written for world size "
                f"{claimed_world} but META records {world} — stale "
                "copy or mixed-topology write"
            )
    for r in range(world):
        path = os.path.join(dirpath, _shard_name(r, world))
        try:
            with open(path, "rb") as f:
                raw = f.read()
        except OSError as e:
            problems.append(f"{path}: unreadable ({e})")
            continue
        expected = shard_crcs.get(str(r))
        if expected is not None and zlib.crc32(raw) != expected:
            problems.append(
                f"{path}: checksum mismatch — torn write or bit "
                "corruption"
            )
            continue
        # Codec pre-flight: every recorded leaf dtype must have a
        # registered codec, so a checkpoint written by a NEWER state-
        # dtype scheme is flagged here (discovery skips it with a
        # ``ckpt_corrupt`` event and walks back) instead of throwing
        # ``UnsupportedLeafDtypeError`` inside ``load_sharded``
        # mid-restart.  v2 blobs only: their header parses without
        # touching the data section, whereas a v1 "header" IS the full
        # payload — deserializing it here would double the walk's
        # memory per candidate, and every v1 writer predates every
        # unregistered dtype anyway.
        if raw[: len(_SHARD_MAGIC)] != _SHARD_MAGIC:
            continue
        try:
            header, _ = _parse_header_from_blob(raw, path)
        except CorruptCheckpointError as e:
            problems.append(str(e))
            continue
        records = (
            header.get("leaves", []) if isinstance(header, dict) else []
        )
        unknown = sorted({
            rec["d"] for rec in records
            if isinstance(rec, dict) and rec.get("d") is not None
            and rec["d"] not in LEAF_DTYPE_CODECS
        })
        if unknown:
            problems.append(
                f"{path}: leaf dtypes {unknown} have no registered "
                "codec (newer writer?) — this checkpoint cannot be "
                "loaded by this build"
            )
    return problems


def verify_checkpoint(path: str) -> List[str]:
    """Integrity problems of ANY checkpoint — sharded directory or
    single-file state stream (empty = valid)."""
    if os.path.isdir(path):
        if not os.path.exists(os.path.join(path, _META)):
            return [f"{path}: incomplete sharded checkpoint (no META)"]
        return verify_sharded(path)
    if os.path.isfile(path):
        return verify_stream_file(path)
    return [f"{path}: no such checkpoint"]


RESTART_CKPT_PREFIXES = ("restart-epoch-", "drain-step-")


def list_restart_candidates(restart_dir: str) -> List[tuple]:
    """COMPLETE restart/drain checkpoints in ``restart_dir``, newest
    first — the one enumeration both restart discovery and retention
    pruning share (diverging filters would let pruning delete a kind
    discovery still resumes from).

    Ordering: completion mtime (META for directories), then — for the
    1-second-granularity filesystems shared restart dirs often live on
    (NFS) — drain checkpoints rank ABOVE epoch checkpoints at equal
    mtime: a drain is written after the epoch checkpoint it follows in
    program order, and resuming the older one would replay steps the
    drain already covered.  Name order breaks remaining ties (both
    prefixes zero-pad their counters).
    """
    entries = []
    try:
        names = os.listdir(restart_dir)
    except OSError:
        return []
    for name in names:
        if not (name.endswith(".ckpt")
                and name.startswith(RESTART_CKPT_PREFIXES)):
            continue
        path = os.path.join(restart_dir, name)
        if os.path.isdir(path):
            meta = os.path.join(path, _META)
            if not os.path.exists(meta):
                continue  # incomplete write — never a candidate
            mtime = os.path.getmtime(meta)
        elif os.path.isfile(path):
            mtime = os.path.getmtime(path)
        else:
            continue
        drain_rank = 1 if name.startswith("drain-step-") else 0
        entries.append((mtime, drain_rank, name, path))
    return sorted(entries, reverse=True)
