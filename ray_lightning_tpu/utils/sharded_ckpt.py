"""Per-host sharded restart checkpoints — no all-gather, any-topology load.

The elastic-restart path used to funnel every checkpoint through
``LoopContext._gathered_state`` — a full replication of the train state
onto every host (an XLA all-gather) just so rank 0 could write one file.
For a ZeRO-3 run that defeats parameter sharding exactly at the scale it
targets (SURVEY §7 hard-part #4; VERDICT r3 weak #2).

Here every process writes only its ADDRESSABLE shards:

* ``save_shard``: one file per process inside a checkpoint DIRECTORY
  (``<tag>/shard-00002-of-00008.ckpt``), holding, for every pytree leaf,
  the host-local shard byte blocks plus their global index — deduped per
  unique index, so replicated leaves cost one copy per host, and ZeRO-3
  parameters cost exactly ``1/hosts`` of the model per file.
* ``save_meta`` (rank 0, AFTER a mesh barrier): the pickled treedef, the
  shard count, and the loop metadata (epoch/step/callback states).  A
  directory without ``META.ckpt`` is an incomplete write and is ignored
  by resume discovery — the same torn-file discipline as the atomic
  single-file path.
* ``load_sharded``: reads all shard files, reassembles full host numpy
  leaves by index, and returns the same payload dict the single-file
  format yields — so resume stays topology-independent (save on N hosts,
  restore on 1 or M; the caller re-places onto its own shardings).

Trust model matches ``state_stream``: leaf DATA is raw msgpack bytes;
the treedef/metadata are pickled, so checkpoints are only as trustworthy
as their source.
"""

from __future__ import annotations

import os
import pickle
from typing import Any, Dict, List, Optional

import jax
import msgpack
import numpy as np

__all__ = ["save_shard", "save_meta", "load_sharded", "is_sharded_ckpt"]

_META = "META.ckpt"


def _shard_name(rank: int, world: int) -> str:
    return f"shard-{rank:05d}-of-{world:05d}.ckpt"


def _np_of(x) -> np.ndarray:
    return np.asarray(jax.device_get(x))


def _leaf_record(leaf: Any) -> Dict[str, Any]:
    """Encode the host-addressable pieces of one pytree leaf."""
    entries: List[Dict[str, Any]] = []
    if isinstance(leaf, jax.Array) and hasattr(leaf, "addressable_shards"):
        shape = leaf.shape
        seen = set()
        for sh in leaf.addressable_shards:
            idx = tuple(
                (
                    0 if s.start is None else int(s.start),
                    dim if s.stop is None else int(s.stop),
                )
                for s, dim in zip(sh.index, shape)
            )
            if idx in seen:  # local replicas: one copy per host
                continue
            seen.add(idx)
            data = _np_of(sh.data)
            entries.append({"i": [list(p) for p in idx], "b": data.tobytes()})
        return {"s": list(shape), "d": str(leaf.dtype), "e": entries}
    arr = _np_of(leaf) if leaf is not None else None
    if arr is None:
        return {"s": None, "d": None, "e": []}
    idx = [[0, dim] for dim in arr.shape]
    return {
        "s": list(arr.shape),
        "d": str(arr.dtype),
        "e": [{"i": idx, "b": arr.tobytes()}],
    }


def _dtype_of(name: str) -> np.dtype:
    if name == "bfloat16":
        import ml_dtypes

        return np.dtype(ml_dtypes.bfloat16)
    return np.dtype(name)


def save_shard(tree: Any, dirpath: str, rank: int, world: int) -> str:
    """Write this process's addressable shards of ``tree`` (atomic)."""
    os.makedirs(dirpath, exist_ok=True)
    leaves, _ = jax.tree_util.tree_flatten(tree)
    blob = msgpack.packb(
        {"rank": rank, "world": world,
         "leaves": [_leaf_record(leaf) for leaf in leaves]},
        use_bin_type=True,
    )
    path = os.path.join(dirpath, _shard_name(rank, world))
    tmp = f"{path}.tmp{os.getpid()}"
    with open(tmp, "wb") as f:
        f.write(blob)
    os.replace(tmp, path)
    return path


def save_meta(tree: Any, dirpath: str, world: int,
              extra: Optional[Dict[str, Any]] = None) -> str:
    """Rank-0 completeness marker.  Callers MUST barrier after
    ``save_shard`` and before this — META asserts every shard is durable."""
    _, treedef = jax.tree_util.tree_flatten(tree)
    blob = msgpack.packb(
        {"world": world,
         "treedef": pickle.dumps(treedef),
         "extra": pickle.dumps(extra or {})},
        use_bin_type=True,
    )
    path = os.path.join(dirpath, _META)
    tmp = f"{path}.tmp{os.getpid()}"
    with open(tmp, "wb") as f:
        f.write(blob)
    os.replace(tmp, path)
    return path


def is_sharded_ckpt(path: str) -> bool:
    return os.path.isdir(path) and os.path.exists(
        os.path.join(path, _META)
    )


def load_sharded(dirpath: str) -> Dict[str, Any]:
    """Reassemble a payload dict: ``{"state": host_tree, **extra}``."""
    with open(os.path.join(dirpath, _META), "rb") as f:
        meta = msgpack.unpackb(f.read(), raw=False)
    world = meta["world"]
    treedef = pickle.loads(meta["treedef"])
    extra = pickle.loads(meta["extra"])

    shard_files = [
        os.path.join(dirpath, _shard_name(r, world)) for r in range(world)
    ]
    missing = [p for p in shard_files if not os.path.exists(p)]
    if missing:
        raise FileNotFoundError(
            f"sharded checkpoint {dirpath} is missing "
            f"{len(missing)}/{world} shard files (e.g. {missing[0]})"
        )

    leaves: List[Optional[np.ndarray]] = []
    covered: List[Optional[np.ndarray]] = []
    for rank, path in enumerate(shard_files):
        with open(path, "rb") as f:
            payload = msgpack.unpackb(f.read(), raw=False)
        # Guard against rank mixups / stale copies: the file must agree
        # with its own name about who wrote it for which world size.
        if payload.get("rank") != rank or payload.get("world") != world:
            raise ValueError(
                f"sharded checkpoint {dirpath}: {os.path.basename(path)} "
                f"claims rank={payload.get('rank')} world="
                f"{payload.get('world')} — rank mixup or stale copy"
            )
        records = payload["leaves"]
        if not leaves:
            leaves = [None] * len(records)
            covered = [None] * len(records)
        for i, rec in enumerate(records):
            if rec["s"] is None:
                continue
            shape = tuple(rec["s"])
            dtype = _dtype_of(rec["d"])
            if leaves[i] is None:
                leaves[i] = np.empty(shape, dtype)
                covered[i] = np.zeros(shape, bool)
            for entry in rec["e"]:
                idx = tuple(slice(a, b) for a, b in entry["i"])
                block_shape = tuple(b - a for a, b in entry["i"])
                block = np.frombuffer(
                    entry["b"], dtype=dtype
                ).reshape(block_shape)
                if idx:
                    leaves[i][idx] = block
                    covered[i][idx] = True
                else:  # 0-d leaf
                    leaves[i] = block.copy()
                    covered[i] = np.ones((), bool)

    # Coverage check: every REGION of every leaf must have been written
    # by some shard (a per-region mask, not an element count — duplicate
    # writes of one region must not mask a hole elsewhere, which would be
    # np.empty garbage silently resumed into the params).
    for i, mask in enumerate(covered):
        if mask is None or leaves[i] is None or leaves[i].size == 0:
            continue
        if not bool(np.all(mask)):
            missing = int(mask.size - np.count_nonzero(mask))
            raise ValueError(
                f"sharded checkpoint {dirpath}: leaf {i} has {missing}/"
                f"{mask.size} uncovered elements — shard entries are "
                f"incomplete or corrupt"
            )

    tree = jax.tree_util.tree_unflatten(treedef, leaves)
    return {"state": tree, **extra}
