"""Per-host sharded restart checkpoints — no all-gather, any-topology load.

The elastic-restart path used to funnel every checkpoint through
``LoopContext._gathered_state`` — a full replication of the train state
onto every host (an XLA all-gather) just so rank 0 could write one file.
For a ZeRO-3 run that defeats parameter sharding exactly at the scale it
targets (SURVEY §7 hard-part #4; VERDICT r3 weak #2).

Here every process writes only its ADDRESSABLE shards:

* ``save_shard``: one file per process inside a checkpoint DIRECTORY
  (``<tag>/shard-00002-of-00008.ckpt``), holding, for every pytree leaf,
  the host-local shard byte blocks plus their global index — deduped per
  unique index, so replicated leaves cost one copy per host, and ZeRO-3
  parameters cost exactly ``1/hosts`` of the model per file.
* ``save_meta`` (rank 0, AFTER a mesh barrier): the pickled treedef, the
  shard count, and the loop metadata (epoch/step/callback states).  A
  directory without ``META.ckpt`` is an incomplete write and is ignored
  by resume discovery — the same torn-file discipline as the atomic
  single-file path.
* ``load_sharded``: reads all shard files, reassembles full host numpy
  leaves by index, and returns the same payload dict the single-file
  format yields — so resume stays topology-independent (save on N hosts,
  restore on 1 or M; the caller re-places onto its own shardings).

Trust model matches ``state_stream``: leaf DATA is raw msgpack bytes;
the treedef/metadata are pickled, so checkpoints are only as trustworthy
as their source.
"""

from __future__ import annotations

import os
import pickle
import zlib
from typing import Any, Dict, List, Optional

import jax
import msgpack
import numpy as np

from ray_lightning_tpu.utils.state_stream import (
    CorruptCheckpointError,
    verify_stream_file,
)

__all__ = [
    "save_shard",
    "save_meta",
    "load_sharded",
    "is_sharded_ckpt",
    "verify_sharded",
    "verify_checkpoint",
    "CorruptCheckpointError",
]

_META = "META.ckpt"
_CRC_SUFFIX = ".crc32"


def _shard_name(rank: int, world: int) -> str:
    return f"shard-{rank:05d}-of-{world:05d}.ckpt"


def _np_of(x) -> np.ndarray:
    return np.asarray(jax.device_get(x))


def _leaf_record(leaf: Any) -> Dict[str, Any]:
    """Encode the host-addressable pieces of one pytree leaf."""
    entries: List[Dict[str, Any]] = []
    if isinstance(leaf, jax.Array) and hasattr(leaf, "addressable_shards"):
        shape = leaf.shape
        seen = set()
        for sh in leaf.addressable_shards:
            idx = tuple(
                (
                    0 if s.start is None else int(s.start),
                    dim if s.stop is None else int(s.stop),
                )
                for s, dim in zip(sh.index, shape)
            )
            if idx in seen:  # local replicas: one copy per host
                continue
            seen.add(idx)
            data = _np_of(sh.data)
            entries.append({"i": [list(p) for p in idx], "b": data.tobytes()})
        return {"s": list(shape), "d": str(leaf.dtype), "e": entries}
    arr = _np_of(leaf) if leaf is not None else None
    if arr is None:
        return {"s": None, "d": None, "e": []}
    idx = [[0, dim] for dim in arr.shape]
    return {
        "s": list(arr.shape),
        "d": str(arr.dtype),
        "e": [{"i": idx, "b": arr.tobytes()}],
    }


def _dtype_of(name: str) -> np.dtype:
    if name == "bfloat16":
        import ml_dtypes

        return np.dtype(ml_dtypes.bfloat16)
    return np.dtype(name)


def save_shard(tree: Any, dirpath: str, rank: int, world: int) -> str:
    """Write this process's addressable shards of ``tree`` (atomic).

    A ``<shard>.crc32`` sidecar records the blob's checksum; rank 0
    folds every sidecar into META (the authoritative, post-barrier
    record) so loads can verify each shard without trusting the shard
    file's own bytes.
    """
    os.makedirs(dirpath, exist_ok=True)
    leaves, _ = jax.tree_util.tree_flatten(tree)
    blob = msgpack.packb(
        {"rank": rank, "world": world,
         "leaves": [_leaf_record(leaf) for leaf in leaves]},
        use_bin_type=True,
    )
    path = os.path.join(dirpath, _shard_name(rank, world))
    tmp = f"{path}.tmp{os.getpid()}"
    with open(tmp, "wb") as f:
        f.write(blob)
    os.replace(tmp, path)
    crc_tmp = f"{path}{_CRC_SUFFIX}.tmp{os.getpid()}"
    with open(crc_tmp, "w") as f:
        f.write(str(zlib.crc32(blob)))
    os.replace(crc_tmp, f"{path}{_CRC_SUFFIX}")
    from ray_lightning_tpu.fault import inject as _chaos

    _chaos.fire("ckpt_write", path=path, rank=rank)
    return path


def _collect_shard_crcs(dirpath: str, world: int) -> Dict[str, int]:
    """Rank-0, post-barrier: gather every shard's sidecar checksum."""
    crcs: Dict[str, int] = {}
    for r in range(world):
        sidecar = os.path.join(
            dirpath, _shard_name(r, world) + _CRC_SUFFIX
        )
        try:
            with open(sidecar) as f:
                crcs[str(r)] = int(f.read().strip())
        except (OSError, ValueError):
            continue  # written by an older save_shard — verification
            # simply skips this rank
    return crcs


def save_meta(tree: Any, dirpath: str, world: int,
              extra: Optional[Dict[str, Any]] = None) -> str:
    """Rank-0 completeness marker.  Callers MUST barrier after
    ``save_shard`` and before this — META asserts every shard is durable.

    v2 format: the body (treedef + extra + per-shard checksums) is
    wrapped in a self-checksummed envelope, so a torn/corrupted META is
    DETECTED rather than unpickled into garbage.  v1 METAs still load.
    """
    _, treedef = jax.tree_util.tree_flatten(tree)
    body = msgpack.packb(
        {"world": world,
         "treedef": pickle.dumps(treedef),
         "extra": pickle.dumps(extra or {}),
         "shard_crcs": _collect_shard_crcs(dirpath, world)},
        use_bin_type=True,
    )
    blob = msgpack.packb(
        {"v": 2, "crc": zlib.crc32(body), "body": body},
        use_bin_type=True,
    )
    path = os.path.join(dirpath, _META)
    tmp = f"{path}.tmp{os.getpid()}"
    with open(tmp, "wb") as f:
        f.write(blob)
    os.replace(tmp, path)
    from ray_lightning_tpu.fault import inject as _chaos

    _chaos.fire("meta_write", path=path)
    return path


def _load_meta(dirpath: str) -> Dict[str, Any]:
    """Read + verify META (v2 envelope or v1 raw body)."""
    path = os.path.join(dirpath, _META)
    try:
        with open(path, "rb") as f:
            raw = f.read()
    except OSError as e:
        raise CorruptCheckpointError(f"{path}: unreadable ({e})") from e
    try:
        doc = msgpack.unpackb(raw, raw=False)
    except Exception as e:  # noqa: BLE001 - any parse failure = corrupt
        raise CorruptCheckpointError(
            f"{path}: unparsable META ({e})"
        ) from e
    if isinstance(doc, dict) and "body" in doc and "crc" in doc:
        body = doc["body"]
        actual = zlib.crc32(body)
        if actual != doc["crc"]:
            raise CorruptCheckpointError(
                f"{path}: META checksum mismatch (stored "
                f"{doc['crc']:#010x}, computed {actual:#010x})"
            )
        try:
            doc = msgpack.unpackb(body, raw=False)
        except Exception as e:  # noqa: BLE001
            raise CorruptCheckpointError(
                f"{path}: unparsable META body ({e})"
            ) from e
    if not isinstance(doc, dict) or "world" not in doc:
        raise CorruptCheckpointError(f"{path}: META has no world size")
    return doc


def is_sharded_ckpt(path: str) -> bool:
    return os.path.isdir(path) and os.path.exists(
        os.path.join(path, _META)
    )


def load_sharded(dirpath: str) -> Dict[str, Any]:
    """Reassemble a payload dict: ``{"state": host_tree, **extra}``.

    Verify-on-load: every shard file's bytes are checked against the
    META-recorded checksum before anything is deserialized — a
    bit-flipped or torn shard raises :class:`CorruptCheckpointError`
    instead of silently resuming garbage into the params.
    """
    meta = _load_meta(dirpath)
    world = meta["world"]
    treedef = pickle.loads(meta["treedef"])
    extra = pickle.loads(meta["extra"])
    shard_crcs = meta.get("shard_crcs") or {}

    shard_files = [
        os.path.join(dirpath, _shard_name(r, world)) for r in range(world)
    ]
    missing = [p for p in shard_files if not os.path.exists(p)]
    if missing:
        raise FileNotFoundError(
            f"sharded checkpoint {dirpath} is missing "
            f"{len(missing)}/{world} shard files (e.g. {missing[0]})"
        )

    leaves: List[Optional[np.ndarray]] = []
    covered: List[Optional[np.ndarray]] = []
    for rank, path in enumerate(shard_files):
        with open(path, "rb") as f:
            raw = f.read()
        expected = shard_crcs.get(str(rank))
        if expected is not None and zlib.crc32(raw) != expected:
            raise CorruptCheckpointError(
                f"sharded checkpoint {dirpath}: "
                f"{os.path.basename(path)} checksum mismatch — torn "
                "write or bit corruption"
            )
        try:
            payload = msgpack.unpackb(raw, raw=False)
        except Exception as e:  # noqa: BLE001 - corrupt ≠ crash-on-load
            raise CorruptCheckpointError(
                f"sharded checkpoint {dirpath}: "
                f"{os.path.basename(path)} is unparsable ({e})"
            ) from e
        # Guard against rank mixups / stale copies: the file must agree
        # with its own name about who wrote it for which world size.
        if payload.get("rank") != rank or payload.get("world") != world:
            raise ValueError(
                f"sharded checkpoint {dirpath}: {os.path.basename(path)} "
                f"claims rank={payload.get('rank')} world="
                f"{payload.get('world')} — rank mixup or stale copy"
            )
        records = payload["leaves"]
        if not leaves:
            leaves = [None] * len(records)
            covered = [None] * len(records)
        for i, rec in enumerate(records):
            if rec["s"] is None:
                continue
            shape = tuple(rec["s"])
            dtype = _dtype_of(rec["d"])
            if leaves[i] is None:
                leaves[i] = np.empty(shape, dtype)
                covered[i] = np.zeros(shape, bool)
            for entry in rec["e"]:
                idx = tuple(slice(a, b) for a, b in entry["i"])
                block_shape = tuple(b - a for a, b in entry["i"])
                block = np.frombuffer(
                    entry["b"], dtype=dtype
                ).reshape(block_shape)
                if idx:
                    leaves[i][idx] = block
                    covered[i][idx] = True
                else:  # 0-d leaf
                    leaves[i] = block.copy()
                    covered[i] = np.ones((), bool)

    # Coverage check: every REGION of every leaf must have been written
    # by some shard (a per-region mask, not an element count — duplicate
    # writes of one region must not mask a hole elsewhere, which would be
    # np.empty garbage silently resumed into the params).
    for i, mask in enumerate(covered):
        if mask is None or leaves[i] is None or leaves[i].size == 0:
            continue
        if not bool(np.all(mask)):
            missing = int(mask.size - np.count_nonzero(mask))
            raise ValueError(
                f"sharded checkpoint {dirpath}: leaf {i} has {missing}/"
                f"{mask.size} uncovered elements — shard entries are "
                f"incomplete or corrupt"
            )

    tree = jax.tree_util.tree_unflatten(treedef, leaves)
    return {"state": tree, **extra}


def verify_sharded(dirpath: str) -> List[str]:
    """Integrity problems of a sharded checkpoint (empty = valid):
    META parse + self-checksum, every shard present, every shard's
    bytes matching its META-recorded checksum.  Reads shard bytes but
    deserializes nothing — restart discovery calls this on every
    candidate while walking back to the newest verified one."""
    problems: List[str] = []
    try:
        meta = _load_meta(dirpath)
    except CorruptCheckpointError as e:
        return [str(e)]
    world = meta["world"]
    shard_crcs = meta.get("shard_crcs") or {}
    for r in range(world):
        path = os.path.join(dirpath, _shard_name(r, world))
        try:
            with open(path, "rb") as f:
                raw = f.read()
        except OSError as e:
            problems.append(f"{path}: unreadable ({e})")
            continue
        expected = shard_crcs.get(str(r))
        if expected is None:
            continue  # v1 writer: no checksum recorded for this rank
        if zlib.crc32(raw) != expected:
            problems.append(
                f"{path}: checksum mismatch — torn write or bit "
                "corruption"
            )
    return problems


def verify_checkpoint(path: str) -> List[str]:
    """Integrity problems of ANY checkpoint — sharded directory or
    single-file state stream (empty = valid)."""
    if os.path.isdir(path):
        if not os.path.exists(os.path.join(path, _META)):
            return [f"{path}: incomplete sharded checkpoint (no META)"]
        return verify_sharded(path)
    if os.path.isfile(path):
        return verify_stream_file(path)
    return [f"{path}: no such checkpoint"]


RESTART_CKPT_PREFIXES = ("restart-epoch-", "drain-step-")


def list_restart_candidates(restart_dir: str) -> List[tuple]:
    """COMPLETE restart/drain checkpoints in ``restart_dir``, newest
    first — the one enumeration both restart discovery and retention
    pruning share (diverging filters would let pruning delete a kind
    discovery still resumes from).

    Ordering: completion mtime (META for directories), then — for the
    1-second-granularity filesystems shared restart dirs often live on
    (NFS) — drain checkpoints rank ABOVE epoch checkpoints at equal
    mtime: a drain is written after the epoch checkpoint it follows in
    program order, and resuming the older one would replay steps the
    drain already covered.  Name order breaks remaining ties (both
    prefixes zero-pad their counters).
    """
    entries = []
    try:
        names = os.listdir(restart_dir)
    except OSError:
        return []
    for name in names:
        if not (name.endswith(".ckpt")
                and name.startswith(RESTART_CKPT_PREFIXES)):
            continue
        path = os.path.join(restart_dir, name)
        if os.path.isdir(path):
            meta = os.path.join(path, _META)
            if not os.path.exists(meta):
                continue  # incomplete write — never a candidate
            mtime = os.path.getmtime(meta)
        elif os.path.isfile(path):
            mtime = os.path.getmtime(path)
        else:
            continue
        drain_rank = 1 if name.startswith("drain-step-") else 0
        entries.append((mtime, drain_rank, name, path))
    return sorted(entries, reverse=True)
