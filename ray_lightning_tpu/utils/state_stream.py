"""Byte-level state ("weight") transfer for JAX pytrees.

TPU-native analogue of the reference's state-stream transport
(``/root/reference/ray_lightning/util.py:71-90``): the rank-0 worker
serializes its model/optimizer state to raw bytes, ships them over the
control plane (object store / queue / actor result), and the driver
deserializes on its own devices.  The reference used ``torch.save`` into a
``BytesIO``; here the state is a JAX pytree of arrays, so we:

* pull every leaf to host memory (``jax.device_get``) — the TPU-side arrays
  may be sharded over a mesh the driver does not have;
* encode numpy leaves with msgpack (raw dtype/shape/bytes — no pickle on
  the *leaf data* path; the treedef itself IS pickled, so state streams are
  only as trustworthy as their source, same trust model as the reference's
  ``torch.save``/``torch.load``);
* rebuild on load and optionally ``jax.device_put`` onto the caller's
  devices/sharding.

The format is *topology independent*: a state stream saved from an N-host
mesh restores on 1 host or M hosts (the analogue of the reference's
worker-downsizing resume test, ``tests/test_ddp_sharded.py:119-138``).
"""

from __future__ import annotations

import io
import struct
import zlib
from typing import Any, Optional

import jax
import msgpack
import numpy as np

__all__ = [
    "to_state_stream",
    "load_state_stream",
    "tree_to_bytes",
    "tree_from_bytes",
    "verify_stream_file",
    "CorruptCheckpointError",
]


class CorruptCheckpointError(RuntimeError):
    """A checkpoint failed integrity verification (checksum mismatch,
    torn file, unparsable payload).  Distinguished from plain IO errors
    so restart discovery can WALK BACK to the previous verified
    checkpoint instead of crashing every subsequent resume attempt on
    the same bad file."""


# On-disk frame for checkpoint FILES: magic + crc32 of the payload.
# Network/state streams stay unframed (they live and die inside one
# process pair); files survive crashes, bit rot and torn writes — the
# cases the checksum exists for.  Legacy files (raw msgpack, first byte
# 0x8*) never start with this magic, so readers accept both.
_FILE_MAGIC = b"RLTCKPT1"


def _frame_stream(stream: bytes) -> bytes:
    return _FILE_MAGIC + struct.pack("<I", zlib.crc32(stream)) + stream


def _unframe_stream(data: bytes, where: str = "stream") -> bytes:
    """Strip (and verify) the file frame if present; raw legacy bytes
    pass through untouched."""
    if not data.startswith(_FILE_MAGIC):
        return data
    if len(data) < len(_FILE_MAGIC) + 4:
        raise CorruptCheckpointError(
            f"{where}: truncated checkpoint frame ({len(data)} bytes)"
        )
    (expected,) = struct.unpack_from("<I", data, len(_FILE_MAGIC))
    body = data[len(_FILE_MAGIC) + 4:]
    actual = zlib.crc32(body)
    if actual != expected:
        raise CorruptCheckpointError(
            f"{where}: checksum mismatch (stored {expected:#010x}, "
            f"computed {actual:#010x}) — torn write or bit corruption"
        )
    return body

_KIND_ARRAY = 0
_KIND_SCALAR = 1
_KIND_NONE = 2
_KIND_STRING = 3

# bfloat16 is not a native numpy dtype; encode via its name and raw bytes.
_BFLOAT16 = "bfloat16"


def _leaf_to_msg(leaf: Any) -> dict:
    if leaf is None:
        return {"k": _KIND_NONE}
    if isinstance(leaf, str):
        return {"k": _KIND_STRING, "v": leaf}
    if isinstance(leaf, (int, float, bool)):
        return {"k": _KIND_SCALAR, "v": leaf}
    arr = np.asarray(jax.device_get(leaf))
    return {
        "k": _KIND_ARRAY,
        "d": str(arr.dtype),
        "s": list(arr.shape),
        "b": arr.tobytes(),  # always a C-order copy, bf16 included
    }


def _leaf_from_msg(msg: dict) -> Any:
    kind = msg["k"]
    if kind == _KIND_NONE:
        return None
    if kind in (_KIND_SCALAR, _KIND_STRING):
        return msg["v"]
    dtype_name = msg["d"]
    shape = tuple(msg["s"])
    if dtype_name == _BFLOAT16:
        import ml_dtypes

        dtype = np.dtype(ml_dtypes.bfloat16)
    else:
        dtype = np.dtype(dtype_name)
    return np.frombuffer(msg["b"], dtype=dtype).reshape(shape).copy()


def tree_to_bytes(tree: Any) -> bytes:
    """Serialize a pytree of arrays/scalars to a compact byte string."""
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    import pickle

    payload = {
        "treedef": pickle.dumps(treedef),
        "leaves": [_leaf_to_msg(l) for l in leaves],
    }
    return msgpack.packb(payload, use_bin_type=True)


def tree_from_bytes(data: bytes) -> Any:
    """Inverse of :func:`tree_to_bytes`.  Accepts both raw streams and
    crc-framed file bytes (callers legitimately pass whole checkpoint
    files read with a plain ``open().read()``)."""
    import pickle

    payload = msgpack.unpackb(_unframe_stream(data), raw=False)
    treedef = pickle.loads(payload["treedef"])
    leaves = [_leaf_from_msg(m) for m in payload["leaves"]]
    return jax.tree_util.tree_unflatten(treedef, leaves)


def to_state_stream(state: Any) -> bytes:
    """Full state (params / optimizer / step counters) → bytes.

    Reference parity: ``util.py:71-75`` (``torch.save`` → ``BytesIO``).
    """
    return tree_to_bytes(state)


def load_state_stream(
    stream: bytes,
    device: Optional[Any] = None,
) -> Any:
    """Bytes → pytree, optionally placed on ``device`` (or a sharding).

    Reference parity: ``util.py:78-90`` (load with ``map_location`` remap).
    ``device`` may be a ``jax.Device`` or a ``jax.sharding.Sharding``; when
    ``None`` the leaves stay as host numpy arrays (cheap, lazy).
    """
    tree = tree_from_bytes(stream)
    if device is not None:
        tree = jax.tree_util.tree_map(
            lambda x: jax.device_put(x, device)
            if isinstance(x, np.ndarray)
            else x,
            tree,
        )
    return tree


def state_stream_to_file(stream: bytes, path: str) -> None:
    """Write a state stream to a file (checkpoint transport helper).

    Atomic (temp + rename): a writer killed mid-checkpoint — the very
    event elastic restart recovers from — must never leave a truncated
    file where a resume would pick it up.  The file carries a crc32
    frame so rename-survived corruption (torn flush, bit rot) is caught
    at read time instead of resumed into the params.
    """
    import os

    tmp = f"{path}.tmp{os.getpid()}"
    with open(tmp, "wb") as f:
        f.write(_frame_stream(stream))
    os.replace(tmp, path)
    from ray_lightning_tpu.fault import inject as _chaos

    _chaos.fire("ckpt_write", path=path)


def state_stream_from_file(path: str) -> bytes:
    with open(path, "rb") as f:
        return _unframe_stream(f.read(), where=path)


def verify_stream_file(path: str) -> list:
    """Integrity problems of a single-file checkpoint (empty = valid).
    Framed files verify by checksum; legacy unframed files verify by a
    full parse — slower, but only restart discovery pays it."""
    try:
        with open(path, "rb") as f:
            data = f.read()
    except OSError as e:
        return [f"{path}: unreadable ({e})"]
    try:
        if data.startswith(_FILE_MAGIC):
            _unframe_stream(data, where=path)
        else:
            msgpack.unpackb(data, raw=False)
    except CorruptCheckpointError as e:
        return [str(e)]
    except Exception as e:  # noqa: BLE001 - any parse failure = corrupt
        return [f"{path}: unparsable checkpoint ({e})"]
    return []
