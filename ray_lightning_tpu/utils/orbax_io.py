"""Orbax interop: export/import checkpoints in the JAX ecosystem format.

The framework's own formats stay canonical — msgpack state streams for
driver-bound transfer (``utils/state_stream.py``) and per-host shard
files for elastic restarts (``utils/sharded_ckpt.py``) — but users
migrating models into or out of the wider JAX ecosystem (flax/orbax
trainers, serving stacks) need the standard on-disk format.  These are
thin, dependency-gated bridges over ``orbax.checkpoint``:

* :func:`save_orbax` — write any array pytree (params, TrainState
  fields, ...) as a standard Orbax checkpoint; sharded ``jax.Array``
  leaves are handled by Orbax natively (each host writes its shards).
* :func:`load_orbax` — restore, optionally resharded onto a target
  pytree of ``jax.ShapeDtypeStruct``/shardings (any mesh, any world
  size — Orbax reads and re-lays-out).

The reference has no analogue (torch pickles only, ``util.py:71-90``);
this is ecosystem parity for the JAX world.
"""

from __future__ import annotations

import os
from typing import Any, Optional

try:
    import orbax.checkpoint as _ocp
except ImportError:  # pragma: no cover - orbax is in the base image
    _ocp = None

__all__ = ["save_orbax", "load_orbax", "ORBAX_INSTALLED"]

ORBAX_INSTALLED = _ocp is not None


def _require_orbax():
    if _ocp is None:
        raise ImportError(
            "This feature requires orbax-checkpoint, which is not "
            "installed in this environment."
        )


def save_orbax(path: str, tree: Any, *, overwrite: bool = False) -> str:
    """Write ``tree`` (any array pytree) as an Orbax checkpoint at
    ``path`` (a directory).  Returns the absolute path."""
    _require_orbax()
    path = os.path.abspath(path)
    with _ocp.StandardCheckpointer() as ckptr:
        ckptr.save(path, tree, force=overwrite)
    return path


def load_orbax(path: str, target: Optional[Any] = None) -> Any:
    """Restore an Orbax checkpoint.

    Args:
        path: checkpoint directory (as produced by :func:`save_orbax`
            or any Orbax ``StandardCheckpointer``/flax trainer).
        target: optional abstract pytree (``jax.ShapeDtypeStruct``
            leaves, optionally carrying ``sharding``) controlling
            restore placement — pass ``jax.eval_shape`` output with
            ``NamedSharding`` to land shards directly on a mesh.
            ``None`` restores host-local numpy-backed arrays.
    """
    _require_orbax()
    path = os.path.abspath(path)
    with _ocp.StandardCheckpointer() as ckptr:
        return ckptr.restore(path, target)
