"""ray_lightning_tpu — TPU-native distributed training strategies.

A brand-new, TPU-first framework with the capabilities of ``ray_lightning``
(PyTorch Lightning distributed-training plugins on Ray), re-designed for
JAX/XLA: one worker actor per TPU host forms a multi-controller device
mesh; gradient sync is XLA collectives over ICI/DCN (``psum`` /
GSPMD-inserted) instead of NCCL; ZeRO-style sharding is a ``NamedSharding``
annotation instead of a wrapper class; and the driver stays a CPU-only
process that ships models out and recovers weights/metrics via an object
store and a distributed queue.

Public surface (≙ reference ``/root/reference/ray_lightning/__init__.py:1-5``):

* :class:`RayStrategy` — data-parallel training strategy (≙ ``RayPlugin``)
* :class:`HorovodRayStrategy` — explicit-collective (shard_map) flavor
  (≙ ``HorovodRayPlugin``; on TPU the "second comm protocol" is per-device
  explicit collectives vs GSPMD global-view)
* :class:`RayShardedStrategy` — GSPMD/ZeRO sharded strategy
  (≙ ``RayShardedPlugin``)
* :class:`Trainer` / :class:`TpuModule` — the Lightning-shaped training
  surface, JAX-native.
"""

from ray_lightning_tpu.session import (
    get_actor_rank,
    get_session,
    init_session,
    is_session_enabled,
    put_queue,
    shutdown_session,
)
from ray_lightning_tpu.util import process_results
from ray_lightning_tpu.utils import (
    Unavailable,
    load_state_stream,
    to_state_stream,
)

__version__ = "0.1.0"

__all__ = [
    "RayStrategy",
    "HorovodRayStrategy",
    "RayShardedStrategy",
    "MpmdStrategy",
    "RayPlugin",
    "HorovodRayPlugin",
    "RayShardedPlugin",
    "LocalStrategy",
    "Trainer",
    "TpuModule",
    "get_actor_rank",
    "get_session",
    "init_session",
    "is_session_enabled",
    "put_queue",
    "shutdown_session",
    "process_results",
    "Unavailable",
    "to_state_stream",
    "load_state_stream",
    "PreemptedError",
    "CorruptCheckpointError",
]


_STRATEGY_NAMES = (
    "RayStrategy",
    "HorovodRayStrategy",
    "RayShardedStrategy",
    "MpmdStrategy",
    "RayPlugin",
    "HorovodRayPlugin",
    "RayShardedPlugin",
    "LocalStrategy",
)


def __getattr__(name):
    # Lazy imports keep `import ray_lightning_tpu` light (no jax tracing
    # machinery touched until a strategy/trainer is actually used).
    if name in _STRATEGY_NAMES:
        from ray_lightning_tpu.parallel import strategies

        return getattr(strategies, name)
    if name in ("Trainer", "TpuModule"):
        from ray_lightning_tpu.core import module as _module
        from ray_lightning_tpu.core import trainer as _trainer

        return {"Trainer": _trainer.Trainer, "TpuModule": _module.TpuModule}[name]
    if name == "PreemptedError":
        from ray_lightning_tpu.fault.drain import PreemptedError

        return PreemptedError
    if name == "CorruptCheckpointError":
        from ray_lightning_tpu.utils.state_stream import (
            CorruptCheckpointError,
        )

        return CorruptCheckpointError
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
