"""Graceful-drain coordination: the preemption-safe half of the fault plane.

TPU fleets preempt with a SIGTERM and a short grace window (Podracer,
arXiv:2104.06272, treats this as the NORMAL worker lifecycle).  The seed
framework converted that signal into a hard worker death — losing up to
``restart_every_n_epochs`` epochs and burning an elastic-restart budget
slot on an event that is not a failure.  This module is the process-wide
drain switchboard:

* a signal handler (installed in the actor child's main thread at
  startup, and on the driver's main thread around inline fits) converts
  the FIRST SIGTERM/SIGINT into a **drain request**; a second signal
  escalates to the old hard-exit behavior, so a stuck drain can still
  be killed;
* the fit loop polls :func:`drain_requested` once per step (collectively
  agreed across a multi-process mesh — every rank must drain at the SAME
  step or the sharded drain checkpoint would tear), writes a
  step-granular drain checkpoint, and raises :class:`PreemptedError`;
* the driver can request a drain out-of-band over the actor control
  lane (``ProcessActor.request_drain`` → the ``drain`` control op →
  :func:`request_drain` in the worker) — e.g. when the DRIVER received
  the preemption notice.

Signal handlers are process-global and only installable from the main
thread, hence the module-level state (exactly the constraint that makes
this a module, not a loop-local object).  jax-free.
"""

from __future__ import annotations

import logging
import os
import signal
import threading
from typing import Any, Dict, Optional

__all__ = [
    "PreemptedError",
    "request_drain",
    "drain_requested",
    "drain_reason",
    "reset_drain",
    "set_fit_active",
    "fit_active",
    "sync_point_crossed",
    "install_signal_handlers",
    "uninstall_signal_handlers",
]

log = logging.getLogger(__name__)

_DRAIN_EXIT_CODE = 143  # 128 + SIGTERM: the no-fit/second-signal hard exit


class PreemptedError(RuntimeError):
    """The fit drained on a preemption request instead of completing.

    Distinguished from a crash on purpose: the strategy converts it into
    an elastic restart that does NOT consume the failure budget, or (no
    elastic recovery configured) re-raises it to the caller with the
    drain checkpoint named — a clean resumable exit, not a failure.

    Attributes: ``checkpoint`` (drain-checkpoint path, ``None`` if none
    could be written), ``step``/``epoch`` (loop position at drain),
    ``rank``, ``reason`` (what requested the drain), ``drain_s``
    (seconds the drain checkpoint write took).
    """

    def __init__(self, message: str = "fit preempted", *,
                 checkpoint: Optional[str] = None, step: int = 0,
                 epoch: int = 0, rank: int = 0,
                 reason: Optional[str] = None,
                 drain_s: Optional[float] = None):
        super().__init__(message)
        self.checkpoint = checkpoint
        self.step = step
        self.epoch = epoch
        self.rank = rank
        self.reason = reason
        self.drain_s = drain_s

    # The exception crosses the actor RPC boundary by value (cloudpickle
    # of the instance) — make reconstruction explicit and stable.
    def __reduce__(self):
        return (
            _rebuild_preempted,
            (self.args[0] if self.args else "fit preempted", {
                "checkpoint": self.checkpoint,
                "step": self.step,
                "epoch": self.epoch,
                "rank": self.rank,
                "reason": self.reason,
                "drain_s": self.drain_s,
            }),
        )


def _rebuild_preempted(message: str, fields: Dict[str, Any]):
    return PreemptedError(message, **fields)


# ---------------------------------------------------------------------------
# Process-wide drain state
# ---------------------------------------------------------------------------

_drain_event = threading.Event()
_state_lock = threading.Lock()
_reason: Optional[str] = None
_fit_active = False
_installed = False
_prev_handlers: Dict[int, Any] = {}


def request_drain(reason: str = "requested") -> None:
    """Flip the process-wide drain flag (idempotent; first reason wins).
    Safe from signal handlers and any thread."""
    global _reason
    if not _drain_event.is_set():
        # No lock here: callable from a signal handler, where a lock the
        # interrupted main thread holds would deadlock.  A racy double
        # write of _reason is harmless (both are true reasons).
        if _reason is None:
            _reason = reason
        _drain_event.set()


def drain_requested() -> bool:
    return _drain_event.is_set()


def drain_reason() -> Optional[str]:
    return _reason


def reset_drain() -> None:
    """Clear drain state at fit start: a drained fit followed by a
    resumed fit in the SAME process (inline strategies, tests) must not
    instantly re-drain."""
    global _reason
    with _state_lock:
        _drain_event.clear()
        _reason = None


def sync_point_crossed(prev_step: int, step: int, every: int) -> bool:
    """Did the micro-step counter cross a multiple of ``every`` moving
    from ``prev_step`` to ``step``?  The drain-agreement cadence for BOTH
    loop shapes: the per-step path advances by 1 (equivalent to the old
    ``step % every == 0``), a megastep stride advances by K — either
    way the collective fires iff a sync point lies inside the advance,
    so every rank's collective call count stays aligned regardless of
    stride shape (strides are config-deterministic and identical
    fleet-wide)."""
    if every <= 1:
        return True
    return (step // every) > (prev_step // every)


def set_fit_active(active: bool) -> None:
    """Fit-in-flight marker: a SIGTERM with no fit running keeps its
    plain meaning (exit) — only a live fit converts it into a drain."""
    global _fit_active
    _fit_active = active


def fit_active() -> bool:
    return _fit_active


# ---------------------------------------------------------------------------
# Signal plumbing
# ---------------------------------------------------------------------------

def _handle(signum, frame) -> None:
    name = signal.Signals(signum).name
    if not _fit_active:
        # No fit to drain: preserve plain semantics.  SIGINT falls
        # through to the previous handler (KeyboardInterrupt in the
        # default case); SIGTERM exits with the conventional code.
        if signum == signal.SIGINT:
            prev = _prev_handlers.get(signum)
            if callable(prev):
                prev(signum, frame)
                return
            raise KeyboardInterrupt
        os._exit(_DRAIN_EXIT_CODE)
    if _drain_event.is_set():
        # Second signal while already draining: escalate — a wedged
        # drain must still be stoppable.  SIGINT escalates to a
        # CATCHABLE KeyboardInterrupt (the driver may be a notebook
        # kernel or pytest process whose finally/atexit must run);
        # only SIGTERM (the preemptor's kill path) hard-exits.
        log.warning("second %s during drain: escalating", name)
        if signum == signal.SIGINT:
            raise KeyboardInterrupt
        os._exit(_DRAIN_EXIT_CODE)
    request_drain(f"signal:{name}")


def install_signal_handlers() -> bool:
    """Install the SIGTERM/SIGINT drain handlers.  Returns ``True`` when
    installed; ``False`` when not possible (non-main thread — Python
    only allows signal handler changes from the main thread — or the
    handlers are already in place)."""
    global _installed
    if _installed:
        return False
    if threading.current_thread() is not threading.main_thread():
        return False
    try:
        for signum in (signal.SIGTERM, signal.SIGINT):
            _prev_handlers[signum] = signal.signal(signum, _handle)
    except (ValueError, OSError):  # non-main thread race / exotic host
        return False
    _installed = True
    return True


def uninstall_signal_handlers() -> None:
    """Restore whatever handlers were in place before :func:`install_
    signal_handlers` (driver-side inline fits must not permanently
    steal pytest's/user code's SIGINT)."""
    global _installed
    if not _installed:
        return
    for signum, prev in list(_prev_handlers.items()):
        try:
            # getsignal() returns None for handlers installed from C
            # (embedded interpreters); signal() rejects None — restore
            # the default disposition instead.
            signal.signal(
                signum, prev if prev is not None else signal.SIG_DFL
            )
        except (ValueError, OSError, TypeError):
            pass
    _prev_handlers.clear()
    _installed = False
