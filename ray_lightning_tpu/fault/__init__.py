"""Fault plane: graceful drain + deterministic fault injection.

Two halves of the recovery story live here:

* :mod:`.drain` — preemption-safe **graceful drain**: SIGTERM/SIGINT
  (or an out-of-band driver request over the actor control lane) sets a
  process-wide drain flag; the fit loop finishes the in-flight step,
  writes a step-granular drain checkpoint and exits with
  :class:`PreemptedError` — which the strategy converts into either a
  clean resumable raise or an elastic restart that does NOT consume the
  failure budget (Podracer-style: preemption is the normal case, not an
  error).
* :mod:`.inject` — the deterministic **chaos plane**: ``RLT_FAULT``
  describes crash/hang/slow/sigterm/torn-write/bit-flip faults pinned
  to exact (point, rank, step, nth) coordinates; injection points are
  threaded through actor spawn, the fit loop, queue sends and
  checkpoint writes, so every recovery path is provable end-to-end in
  CI (``tests/test_fault_tolerance.py``, ``tools/chaos_sweep.py``).
"""

from ray_lightning_tpu.fault.drain import (
    PreemptedError,
    drain_reason,
    drain_requested,
    install_signal_handlers,
    request_drain,
    reset_drain,
    set_fit_active,
    uninstall_signal_handlers,
)
from ray_lightning_tpu.fault.inject import (
    FaultInjected,
    FaultSpec,
    fire,
    parse_faults,
    set_rank,
)

__all__ = [
    "PreemptedError",
    "request_drain",
    "drain_requested",
    "drain_reason",
    "reset_drain",
    "set_fit_active",
    "install_signal_handlers",
    "uninstall_signal_handlers",
    "FaultSpec",
    "FaultInjected",
    "parse_faults",
    "fire",
    "set_rank",
]
