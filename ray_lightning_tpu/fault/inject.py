"""Deterministic fault injection (the chaos plane).

Recovery machinery is only trustworthy when its failure modes can be
reproduced on demand (the MPMD-pipeline lesson, arXiv:2412.14374): a
recovery path that has never fired in CI is a recovery path that does
not work.  This module turns the ``RLT_FAULT`` env knob into faults
fired at exact, deterministic coordinates inside the framework.

Grammar (``RLT_FAULT``)::

    RLT_FAULT  = spec (";" spec)*
    spec       = kind "@" cond ("," cond)*
    cond       = key ":" value

    kinds: crash   — os._exit(13): hard process death (OOM/preemption
                     without grace)
           blackhole — raise FaultBlackhole at the injection point:
                     send-sites (beats, KV handoffs) catch it and
                     silently DROP the frame — the network-partition
                     signature (process alive, messages vanish)
           shm_vanish — unlink the injection point's ``path`` (a tmpfs
                     KV segment): the frame still ships but its
                     payload is gone when the consumer maps it — the
                     segment-TTL / cross-host race signature
           lose_worker — crash, PLUS a fleet-capacity loss recorded in
                     the ``RLT_FAULT_STATE`` dir: the restart governor's
                     capacity oracle (:func:`lost_worker_count`) then
                     reports one fewer available worker, driving the
                     elastic shrink path deterministically.  ``secs``
                     is the regain time — the lost host "comes back"
                     after that many seconds (omit it for a permanent
                     loss), exercising grow-back
           exc     — raise FaultInjected (the deterministic-user-bug
                     path: must fail fast, never burn restart budget)
           hang    — sleep ``secs`` (default 3600) on the calling
                     thread: the wedged-collective signature (beats
                     keep flowing, progress freezes)
           slow    — sleep ``secs`` (default 1.0): a straggler rank
           sigterm — deliver SIGTERM to this process: the graceful-
                     drain / preemption path (fault/drain.py)
           torn    — truncate the file at the injection point's
                     ``path`` to half: a torn checkpoint write
           bitflip — XOR one byte mid-file: silent media corruption a
                     checksum must catch

    keys:  point — injection point name (default "step"):
                   spawn | step | queue_put | ckpt_write | meta_write
                   | handoff_send | handoff_read | replica_tick | beat
                   | adapter_load
                   (the serve plane: a prefill worker's handoff send,
                   a replica's handoff admission, one engine step, a
                   member's liveness beat, an adapter-load frame)
           rank  — only this global rank (default: any)
           replica — only the decode replica with this member id
                   (serve plane; see :func:`set_member`)
           worker — only the prefill worker with this member id
           rid   — only the request with this id (handoff points)
           stage — alias for ``rank`` on the MPMD pipeline plane: the
                   stage WORKER index (= actor rank; under
                   ``interleave=v`` worker ``p`` hosts the virtual
                   stages ``{c*P+p}``, which cannot be pinned
                   individually — they share a process).
                   ``crash@stage:1,step:3`` kills stage worker 1's
                   actor at optimizer step 3 (the stage-kill recovery
                   acceptance pin)
           step  — only this micro-step (``step`` point only)
           epoch — only this epoch
           nth   — only the Nth matching occurrence (1-based; counted
                   per process — combine with the fired-marker state
                   dir for exactly-once across restarts)
           secs  — hang/slow duration
           once  — 1 (default): fire at most once, recorded in the
                   ``RLT_FAULT_STATE`` marker dir so a respawned
                   worker does not re-fire it; 0: fire on every match

Examples::

    RLT_FAULT="crash@step:7,rank:1"
    RLT_FAULT="hang@step:5,rank:0,secs:120"
    RLT_FAULT="sigterm@step:3,rank:0"
    RLT_FAULT="bitflip@point:ckpt_write,nth:2;crash@step:9"
    RLT_FAULT="blackhole@point:beat,replica:decode-0"
    RLT_FAULT="torn@point:handoff_send,worker:prefill-0,nth:2"
    RLT_FAULT="shm_vanish@point:handoff_send,rid:abc123"
    RLT_FAULT="slow@point:replica_tick,replica:decode-1,secs:0.5,once:0"

Determinism across elastic restarts: set ``RLT_FAULT_STATE=<dir>`` (a
directory shared by all workers); each fired ``once`` spec drops a
``fault-<index>.fired`` marker there, so the respawned worker set
trains through instead of re-dying forever.  Both env vars ride the
strategy env bus (like ``RLT_GRAD_COMM``), so driver-side settings
reach remote workers.

Cost discipline: :func:`fire` is called on hot paths (every step, every
queue put).  With ``RLT_FAULT`` unset it is one dict lookup + one
``is None`` check — nothing is parsed, no state dir is touched.
jax-free.
"""

from __future__ import annotations

import dataclasses
import logging
import os
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

__all__ = [
    "FaultSpec",
    "FaultInjected",
    "FaultBlackhole",
    "parse_faults",
    "fire",
    "set_rank",
    "set_member",
    "step_fault_in_range",
    "record_worker_loss",
    "lost_worker_count",
    "POINTS",
    "KINDS",
]

log = logging.getLogger(__name__)

KINDS = ("crash", "exc", "hang", "slow", "sigterm", "torn", "bitflip",
         "lose_worker", "blackhole", "shm_vanish")
POINTS = ("spawn", "step", "queue_put", "ckpt_write", "meta_write",
          "handoff_send", "handoff_read", "replica_tick", "beat",
          "adapter_load")

_CRASH_EXIT_CODE = 13


class FaultInjected(RuntimeError):
    """The exception the ``exc`` fault kind raises."""


class FaultBlackhole(FaultInjected):
    """The ``blackhole`` kind: raised at a send-site injection point,
    caught THERE, and the frame silently dropped — the process stays
    alive while its messages vanish (a network partition, not a death).
    Subclasses :class:`FaultInjected` so generic chaos handlers still
    recognise it."""


@dataclasses.dataclass(frozen=True)
class FaultSpec:
    """One parsed fault: a kind pinned to match coordinates."""

    kind: str
    point: str = "step"
    rank: Optional[int] = None
    step: Optional[int] = None
    epoch: Optional[int] = None
    nth: Optional[int] = None
    secs: Optional[float] = None
    once: bool = True
    replica: Optional[str] = None  # decode-member pin (serve plane)
    worker: Optional[str] = None   # prefill-member pin (serve plane)
    rid: Optional[str] = None      # request pin (handoff points)
    index: int = 0  # position in the RLT_FAULT list (marker identity)

    def matches(self, point: str, rank: Optional[int],
                step: Optional[int], epoch: Optional[int], *,
                replica: Optional[str] = None,
                worker: Optional[str] = None,
                rid: Optional[str] = None) -> bool:
        """Coordinate match — everything except the nth/once gates,
        which are stateful and live on the plan."""
        if self.point != point:
            return False
        if self.rank is not None and rank != self.rank:
            return False
        if self.step is not None and step != self.step:
            return False
        if self.epoch is not None and epoch != self.epoch:
            return False
        if self.replica is not None and replica != self.replica:
            return False
        if self.worker is not None and worker != self.worker:
            return False
        if self.rid is not None and rid != self.rid:
            return False
        return True


def parse_faults(value: str) -> List[FaultSpec]:
    """Parse an ``RLT_FAULT`` string; raises ``ValueError`` on any typo
    (a chaos spec that silently matches nothing would "prove" recovery
    paths that never actually fired)."""
    specs: List[FaultSpec] = []
    for index, raw in enumerate(s for s in value.split(";") if s.strip()):
        raw = raw.strip()
        kind, sep, conds = raw.partition("@")
        kind = kind.strip()
        if kind not in KINDS:
            raise ValueError(
                f"RLT_FAULT spec {raw!r}: unknown kind {kind!r} "
                f"(expected one of {KINDS})"
            )
        kw: Dict[str, Any] = {"kind": kind, "index": index}
        if sep:
            for cond in conds.split(","):
                key, csep, val = cond.partition(":")
                key, val = key.strip(), val.strip()
                if not csep or not val:
                    raise ValueError(
                        f"RLT_FAULT spec {raw!r}: condition {cond!r} is "
                        "not key:value"
                    )
                if key == "point":
                    if val not in POINTS:
                        raise ValueError(
                            f"RLT_FAULT spec {raw!r}: unknown point "
                            f"{val!r} (expected one of {POINTS})"
                        )
                    kw["point"] = val
                elif key in ("rank", "step", "epoch", "nth"):
                    kw[key] = int(val)
                elif key in ("replica", "worker", "rid"):
                    kw[key] = val
                elif key == "stage":
                    # MPMD alias: a stage worker's process rank IS its
                    # stage index (StageRunner fires with rank=stage).
                    kw["rank"] = int(val)
                elif key == "secs":
                    kw[key] = float(val)
                elif key == "once":
                    kw["once"] = val not in ("0", "false", "off")
                else:
                    raise ValueError(
                        f"RLT_FAULT spec {raw!r}: unknown key {key!r}"
                    )
        specs.append(FaultSpec(**kw))
    return specs


class FaultPlan:
    """Parsed specs + per-process occurrence counters + the shared
    fired-marker directory."""

    def __init__(self, specs: List[FaultSpec], state_dir: Optional[str]):
        self.specs = specs
        self.state_dir = state_dir
        self._counts: Dict[int, int] = {}

    def _marker(self, spec: FaultSpec) -> Optional[str]:
        if self.state_dir is None:
            return None
        return os.path.join(self.state_dir, f"fault-{spec.index}.fired")

    def already_fired(self, spec: FaultSpec) -> bool:
        marker = self._marker(spec)
        return marker is not None and os.path.exists(marker)

    def mark_fired(self, spec: FaultSpec) -> None:
        marker = self._marker(spec)
        if marker is None:
            return
        try:
            os.makedirs(self.state_dir, exist_ok=True)
            with open(marker, "w") as f:
                f.write(f"{time.time()}\n")
        except OSError:
            log.warning("fault marker %s could not be written", marker)

    def due(self, point: str, rank: Optional[int], step: Optional[int],
            epoch: Optional[int],
            replica: Optional[str] = None,
            worker: Optional[str] = None,
            rid: Optional[str] = None) -> List[FaultSpec]:
        due = []
        for spec in self.specs:
            if not spec.matches(point, rank, step, epoch,
                                replica=replica, worker=worker, rid=rid):
                continue
            if spec.nth is not None:
                # Occurrence counting happens on COORDINATE matches, so
                # nth stays deterministic regardless of fired state.
                n = self._counts.get(spec.index, 0) + 1
                self._counts[spec.index] = n
                if n != spec.nth:
                    continue
            if spec.once and self.already_fired(spec):
                continue
            due.append(spec)
        return due


# Cache keyed by the (RLT_FAULT, RLT_FAULT_STATE) values so env changes
# between fits (tests) re-parse, while the hot path stays two dict
# lookups when faults are configured and one when they are not.
_plan_key: Optional[Tuple[str, Optional[str]]] = None
_plan: Optional[FaultPlan] = None

_ctx_rank: Optional[int] = None
# Serve-member identity is THREAD-local, not process-global: an inproc
# fleet runs every replica/worker of the fleet inside one driver
# process, each on its own serve/beat threads — a process-global pin
# would attribute one member's faults to whichever member registered
# last.  Each member-owned thread (engine serve loop, runner beat loop,
# prefill work thread) declares its own identity.
_ctx_member = threading.local()


def set_rank(rank: Optional[int]) -> None:
    """Record this process's global rank so injection points that don't
    naturally know it (queue sends, checkpoint writers) still honor
    ``rank:`` conditions."""
    global _ctx_rank
    _ctx_rank = rank


def set_member(role: Optional[str], member_id: Optional[str]) -> None:
    """Record the CALLING THREAD's serve-fleet identity (``role`` is
    ``"decode"`` or ``"prefill"``) so serve injection points honor
    ``replica:``/``worker:`` pins without threading ids through every
    call site.  ``set_member(None, None)`` clears it (tests)."""
    if role is None:
        _ctx_member.replica = _ctx_member.worker = None
    elif role == "decode":
        _ctx_member.replica = str(member_id)
        _ctx_member.worker = None
    else:
        _ctx_member.replica = None
        _ctx_member.worker = str(member_id)


def _current_plan() -> Optional[FaultPlan]:
    global _plan_key, _plan
    value = os.environ.get("RLT_FAULT")
    if not value:
        if _plan is not None:
            _plan_key, _plan = None, None
        return None
    key = (value, os.environ.get("RLT_FAULT_STATE") or None)
    if key != _plan_key:
        _plan = FaultPlan(parse_faults(value), key[1])
        _plan_key = key
    return _plan


# ---------------------------------------------------------------------------
# Fault actions
# ---------------------------------------------------------------------------

def _corrupt_torn(path: str) -> None:
    """Truncate ``path`` to half: the classic torn write (writer died
    mid-flush after the rename — or a filesystem that lied about
    durability)."""
    try:
        size = os.path.getsize(path)
        with open(path, "r+b") as f:
            f.truncate(max(size // 2, 1))
    except OSError as e:
        log.warning("torn fault on %s failed: %r", path, e)


def _corrupt_bitflip(path: str) -> None:
    """XOR one bit mid-file: silent media corruption only a checksum
    catches (the payload still parses more often than not)."""
    try:
        size = os.path.getsize(path)
        pos = size // 2
        with open(path, "r+b") as f:
            f.seek(pos)
            byte = f.read(1)
            f.seek(pos)
            f.write(bytes([(byte[0] if byte else 0) ^ 0x01]))
    except OSError as e:
        log.warning("bitflip fault on %s failed: %r", path, e)


# ---------------------------------------------------------------------------
# Fleet-capacity oracle (the elastic shrink/grow test plane)
# ---------------------------------------------------------------------------

def record_worker_loss(rank: Optional[int],
                       regain_s: Optional[float] = None,
                       state_dir: Optional[str] = None) -> None:
    """Record a fleet-capacity loss in the shared ``RLT_FAULT_STATE``
    dir: the host carrying ``rank`` is gone, coming back after
    ``regain_s`` seconds (``None`` = permanently).  The restart
    governor's default capacity oracle reads these markers, so a
    ``lose_worker`` chaos fault drives the whole shrink→grow path
    deterministically with no real fleet."""
    import json

    state_dir = state_dir or os.environ.get("RLT_FAULT_STATE") or None
    if state_dir is None:
        log.warning(
            "lose_worker fired without RLT_FAULT_STATE — capacity loss "
            "not recorded (the governor will respawn at full size)"
        )
        return
    try:
        os.makedirs(state_dir, exist_ok=True)
        path = os.path.join(
            state_dir, f"lost-worker-{rank if rank is not None else 0}.json"
        )
        with open(path, "w") as f:
            json.dump({"ts": time.time(), "regain_s": regain_s}, f)
    except OSError:
        log.warning("lost-worker marker in %s could not be written",
                    state_dir)


def lost_worker_count(now: Optional[float] = None,
                      state_dir: Optional[str] = None) -> int:
    """Workers currently lost per the ``RLT_FAULT_STATE`` markers (a
    marker whose ``regain_s`` has elapsed no longer counts — the
    replacement host arrived).  0 with no chaos state configured."""
    import json

    state_dir = state_dir or os.environ.get("RLT_FAULT_STATE") or None
    if not state_dir or not os.path.isdir(state_dir):
        return 0
    now = time.time() if now is None else now
    n = 0
    for name in os.listdir(state_dir):
        if not name.startswith("lost-worker-"):
            continue
        try:
            with open(os.path.join(state_dir, name)) as f:
                doc = json.load(f)
        except (OSError, ValueError):
            continue
        regain = doc.get("regain_s")
        if regain is None or now - float(doc.get("ts", 0.0)) < float(regain):
            n += 1
    return n


def _execute(spec: FaultSpec, point: str, path: Optional[str]) -> None:
    log.warning("chaos: firing %s@%s (spec #%d)", spec.kind, point,
                spec.index)
    if spec.kind == "crash":
        os._exit(_CRASH_EXIT_CODE)
    if spec.kind == "lose_worker":
        # A preempted HOST: record the capacity loss (``secs`` = when a
        # replacement arrives), then die exactly like ``crash`` — the
        # governor sees an ActorDiedError whose capacity oracle now
        # reports one fewer worker, and shrinks instead of respawning
        # into the hole.
        record_worker_loss(
            _ctx_rank if _ctx_rank is not None else spec.rank, spec.secs
        )
        os._exit(_CRASH_EXIT_CODE)
    if spec.kind == "exc":
        raise FaultInjected(
            f"injected exception at {point} (spec #{spec.index})"
        )
    if spec.kind == "blackhole":
        raise FaultBlackhole(
            f"injected blackhole at {point} (spec #{spec.index})"
        )
    if spec.kind == "shm_vanish":
        if path is None:
            log.warning(
                "chaos: shm_vanish fault at %s has no segment path — "
                "skipped", point,
            )
            return
        try:
            os.unlink(path)
        except OSError as e:
            log.warning("shm_vanish fault on %s failed: %r", path, e)
        return
    if spec.kind == "hang":
        time.sleep(spec.secs if spec.secs is not None else 3600.0)
        return
    if spec.kind == "slow":
        time.sleep(spec.secs if spec.secs is not None else 1.0)
        return
    if spec.kind == "sigterm":
        import signal

        os.kill(os.getpid(), signal.SIGTERM)
        return
    if spec.kind in ("torn", "bitflip"):
        if path is None:
            log.warning(
                "chaos: %s fault at %s has no file path — skipped",
                spec.kind, point,
            )
            return
        (_corrupt_torn if spec.kind == "torn" else _corrupt_bitflip)(path)
        return


def step_fault_in_range(start: int, stop: int, *,
                        epoch: Optional[int] = None,
                        rank: Optional[int] = None) -> bool:
    """Could a ``step``-point fault fire anywhere in micro-steps
    ``[start, stop)``?  The megastep loop asks this BEFORE fusing a
    stride: a pinned injection inside the stride means those steps must
    run singly so the fault fires at its exact inner-step index (a fault
    fired "somewhere inside the scan" would not be deterministic, and a
    fault skipped entirely would "prove" recovery paths that never ran).

    Near-zero cost with ``RLT_FAULT`` unset (one dict lookup).  ``nth``
    pins are treated conservatively (any occurrence could be the Nth),
    and so are ``rank`` pins: the degrade decision must be IDENTICAL
    fleet-wide — strides shape the compiled program and its collective
    call sequence, so a rank that fuses while the fault's pinned rank
    runs singles would execute a divergent global program and hang in
    the first collective.  Every rank lowers K around the injection;
    :func:`fire` still honors the rank pin, so the fault itself fires
    only where it was aimed.  (``rank`` is accepted for signature
    stability but does not narrow the match.)  ``once`` specs that
    already fired stop degrading strides — the markers live in the
    shared ``RLT_FAULT_STATE`` dir, so that call too stays rank-aligned
    — and a chaos A/B keeps its megastep performance after the
    injection.
    """
    plan = _current_plan()
    if plan is None:
        return False
    for spec in plan.specs:
        if spec.point != "step":
            continue
        if (spec.epoch is not None and epoch is not None
                and spec.epoch != epoch):
            continue
        if spec.step is not None and not (start <= spec.step < stop):
            continue
        if spec.once and spec.nth is None and plan.already_fired(spec):
            # Fired-and-done — but keep degrading when an nth pin is
            # present: its occurrence counter must keep seeing every
            # coordinate match to stay deterministic.
            continue
        return True
    return False


def fire(point: str, *, step: Optional[int] = None,
         epoch: Optional[int] = None, rank: Optional[int] = None,
         path: Optional[str] = None, rid: Optional[str] = None) -> None:
    """An injection point: fire every due fault for these coordinates.

    Near-zero cost when ``RLT_FAULT`` is unset.  ``rank`` defaults to
    the process context set by :func:`set_rank`; serve member pins
    (``replica:``/``worker:``) resolve against :func:`set_member`.
    """
    plan = _current_plan()
    if plan is None:
        return
    if rank is None:
        rank = _ctx_rank
    for spec in plan.due(point, rank, step, epoch,
                         replica=getattr(_ctx_member, "replica", None),
                         worker=getattr(_ctx_member, "worker", None),
                         rid=rid):
        # Mark BEFORE executing: crash/sigterm never return, and the
        # whole contract is that the respawned worker trains through.
        if spec.once:
            plan.mark_fired(spec)
        _execute(spec, point, path)
