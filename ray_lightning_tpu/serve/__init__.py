"""Continuous-batching inference serving plane (ISSUE 6).

The "serve heavy traffic" half of the north star: a TPU-shaped serving
engine on the existing actor/queue substrate.  Shape discipline is the
same one the training core lives by — every steady-state program is
compiled ONCE and re-dispatched forever:

* :mod:`.kv_cache` — **paged KV cache**: the per-layer cache is a pool
  of fixed-size token blocks shared by every in-flight sequence, with a
  host-side block allocator and device-side block tables.  Finished
  requests free their blocks immediately; prefill writes whole blocks,
  decode scatters one slot per step;
* :mod:`.scheduler` — **continuous batcher**: bounded admission queue
  with per-request deadlines, join-on-arrival / evict-on-finish between
  decode steps, recompute-style preemption when the block pool runs dry;
* :mod:`.engine` — the driver-side serve loop: bucketed prefill
  programs + ONE fixed-width decode program, SLO stats (TTFT, per-token
  latency, queue depth, occupancy) and OpenMetrics export;
* :mod:`.client` — request submission/streaming over the DriverQueue
  plane, with backpressure surfaced as typed rejections;
* :mod:`.draft` — draft-model construction for **speculative
  decoding**: a small draft proposes K tokens per tick, the target
  verifies them in ONE fixed-width dispatch (``spec_k``/``spec=``
  knobs; lossless for greedy, position-keyed sampling elsewhere);
* :mod:`.lora` — **multi-tenant LoRA multiplexing**: one resident
  lora-free base model, up to ``max_adapters`` tenants' A/B factors
  stacked in resident device buffers, applied per-slot via a gathered
  BGMV with an int32 ``adapter_ids`` operand — any tenant mix shares
  the compiled-once program set (zero steady-state recompiles);
* :mod:`.metrics` — the jax-free SLO stats engine the bench and the
  exporters share;
* :mod:`.dist` — **disaggregated multi-replica serving**: prefill
  workers shipping paged-KV blocks over the queue plane to N decode
  replicas behind a load-aware router with heartbeat failover
  (imported lazily — ``from ray_lightning_tpu.serve.dist import ...``).

See ``docs/SERVING.md`` for architecture, knobs and the bench
methodology (``bench_serve.py``).
"""

from ray_lightning_tpu.serve.client import ServeClient, ServeRejected
from ray_lightning_tpu.serve.draft import (
    early_exit_draft,
    pad_identity_layers,
)
from ray_lightning_tpu.serve.engine import ServeConfig, ServeEngine
from ray_lightning_tpu.serve.lora import (
    AdapterPool,
    decode_adapter,
    encode_adapter,
)
from ray_lightning_tpu.serve.kv_cache import (
    BlockAllocator,
    PagedKVCache,
    paged_decode_step,
    paged_prefill,
    paged_verify_step,
    sample_tokens,
)
from ray_lightning_tpu.serve.metrics import ServeStats
from ray_lightning_tpu.serve.scheduler import (
    Request,
    RequestState,
    Scheduler,
)

__all__ = [
    "ServeEngine",
    "ServeConfig",
    "ServeClient",
    "ServeRejected",
    "ServeStats",
    "PagedKVCache",
    "BlockAllocator",
    "paged_prefill",
    "paged_decode_step",
    "paged_verify_step",
    "sample_tokens",
    "early_exit_draft",
    "pad_identity_layers",
    "AdapterPool",
    "encode_adapter",
    "decode_adapter",
    "Request",
    "RequestState",
    "Scheduler",
]
