"""Paged/blocked KV cache: one block pool shared by every sequence.

``models/generate.py`` allocates ONE contiguous ``(L, B, total, H, Dh)``
cache per batch — fine for a fixed batch generating in lockstep, fatal
for serving: every request would own ``total_len`` slots for its whole
lifetime, and a new request could not join until the whole batch
finished.  The serving cache is instead a pool of fixed-size token
blocks (the vLLM/PagedAttention layout, TPU-shaped):

* **pool** — ``k``/``v`` each ``(L, num_blocks, block_size, H, Dh)``.
  One allocation for the whole server, sized by memory, not by batch;
* **block tables** — per-slot ``(max_blocks_per_seq,)`` int32 rows
  mapping a sequence's logical block index → physical pool block.
  Tables live host-side (numpy, mutated by the scheduler between steps)
  and ride into the compiled step as ordinary int32 operands — shapes
  never change, so steady-state serving never recompiles;
* **allocator** — a host-side free list.  Finished/evicted requests
  free their blocks immediately; the next admission reuses them.

Physical block 0 is reserved as the **trash block**: inactive slots
point their writes at it, so the fixed-width decode program needs no
active-mask branch — garbage lands where nothing ever reads.

Device programs (pure functions, jitted by the engine):

* :func:`paged_prefill` — one padded prompt bucket through the SAME
  stacked-layer block scan the static path uses
  (``generate._trunk_blocks``), then the per-layer k/v scattered into
  the sequence's pool blocks.  Compiled once per bucket length;
* :func:`paged_decode_step` — one token for EVERY slot: scatter the new
  k/v into each slot's current block, gather each slot's blocks, and
  attend under a ``position <= seq_len`` mask.  ONE fixed-width program
  for the server's lifetime.

Numerics match the contiguous path by construction: the gather lays a
sequence's blocks back into logical order, the mask hides exactly the
slots the static path's causal mask hides, and scores/softmax/PV stay
f32 (see ``generate._block_pass``).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp

from ray_lightning_tpu.models.generate import (
    _embed, _head_logits, _trunk_blocks,
)
from ray_lightning_tpu.models.gpt import (
    GPTConfig, _layer_norm, _mlp_residual, _moe_residual,
)
from ray_lightning_tpu.models.quant import resolve_weight
from ray_lightning_tpu.ops.attention import _NEG_INF
from ray_lightning_tpu.ops.lora import apply_lora

__all__ = [
    "BlockAllocator",
    "PagedKVCache",
    "PrefixIndex",
    "paged_prefill",
    "paged_decode_step",
    "paged_verify_step",
    "sample_tokens",
    "make_slot_keys",
    "extend_block_coverage",
    "truncate_to",
    "import_blocks",
    "copy_blocks",
]

# Physical block 0 is never allocated: it is the write target for
# inactive slots (and the padding entry of short block tables), so the
# decode program stays branch-free.
TRASH_BLOCK = 0


class BlockAllocator:
    """Host-side free list over the physical block pool, with per-block
    reference counts.

    jax-free and O(1) per op.  Double-free and foreign-id frees raise —
    a scheduler bug that silently re-issued a live block would corrupt
    another request's cache, the one failure mode a serving cache must
    never shrug off.

    Refcounts are the sharing substrate of the prefix cache: a freshly
    allocated block carries one reference (its owning chain);
    :meth:`retain` hands the SAME physical block to another holder
    (another request's block table, or the resident
    :class:`PrefixIndex`), and :meth:`free` becomes decrement-release —
    the block returns to the free list only when its LAST holder drops
    it.  Every holder frees through the same call, so no caller needs
    to know whether it was the last one.
    """

    def __init__(self, num_blocks: int):
        if num_blocks < 2:
            raise ValueError(
                f"num_blocks must be >= 2 (block {TRASH_BLOCK} is "
                f"reserved), got {num_blocks}"
            )
        self.num_blocks = num_blocks
        # LIFO free list: recently-freed blocks are re-issued first
        # (their pool pages are the warmest).
        self._free: List[int] = list(range(num_blocks - 1, TRASH_BLOCK, -1))
        self._refs: Dict[int, int] = {}

    @property
    def free_blocks(self) -> int:
        return len(self._free)

    @property
    def live_blocks(self) -> int:
        return len(self._refs)

    def refcount(self, b: int) -> int:
        """Holders of physical block ``b`` (0 = not live)."""
        return self._refs.get(b, 0)

    def is_shared(self, b: int) -> bool:
        """True when more than one holder references ``b`` — the block
        is read-only to every holder until copy-on-write or release."""
        return self._refs.get(b, 0) > 1

    def alloc(self, n: int) -> Optional[List[int]]:
        """``n`` physical block ids, or ``None`` (all-or-nothing) when
        the pool cannot cover the request."""
        if n < 0:
            raise ValueError(f"alloc({n})")
        if n > len(self._free):
            return None
        ids = [self._free.pop() for _ in range(n)]
        for b in ids:
            self._refs[b] = 1
        return ids

    def retain(self, ids) -> None:
        """Bump the refcount of live blocks ``ids`` — the claim half of
        prefix sharing (zero device work: the new holder just points
        its block table at the same physical blocks)."""
        for b in ids:
            if b not in self._refs:
                raise RuntimeError(
                    f"retain of block {b} which is not live — a chain "
                    f"cannot share blocks nobody owns"
                )
        for b in ids:
            self._refs[b] += 1

    def free(self, ids) -> None:
        for b in ids:
            if b not in self._refs:
                raise RuntimeError(
                    f"free of block {b} which is not live (double-free "
                    f"or foreign id) — scheduler bookkeeping bug"
                )
            self._refs[b] -= 1
            if self._refs[b] == 0:
                del self._refs[b]
                self._free.append(b)


def extend_block_coverage(
    allocator: BlockAllocator,
    blocks: List[int],
    table_row,
    upto_pos: int,
    block_size: int,
) -> bool:
    """Grow ``blocks``/``table_row`` until cache position ``upto_pos``
    is writable.  All-or-nothing: either every missing block is
    allocated (True) or none are (False = pool dry) — a partially
    covered multi-token write would scatter past its allocation.

    The multi-token append primitive of the speculative-decoding path:
    a verify step writes K+1 positions in one dispatch, so coverage is
    claimed for the whole window BEFORE the dispatch, and
    :func:`truncate_to` returns the rejected tail's blocks afterwards.
    """
    need = (upto_pos // block_size) + 1 - len(blocks)
    if need <= 0:
        return True
    ids = allocator.alloc(need)
    if ids is None:
        return False
    start = len(blocks)
    blocks.extend(ids)
    table_row[start: start + len(ids)] = ids
    return True


def truncate_to(
    allocator: BlockAllocator,
    blocks: List[int],
    table_row,
    n_tokens: int,
    block_size: int,
) -> int:
    """Shrink a sequence's block coverage to exactly ``n_tokens`` cache
    slots: blocks past the covering prefix are freed back to the pool
    and their table entries restored to the trash block.  Returns the
    number of blocks freed.

    Pure ``seq_lens``/allocator arithmetic — the rollback half of a
    speculative verify tick (rejected drafts' cache slots are garbage
    the visibility mask already hides; this returns their BLOCKS).
    """
    keep = -(-n_tokens // block_size) if n_tokens > 0 else 0
    freed = blocks[keep:]
    if not freed:
        return 0
    del blocks[keep:]
    allocator.free(freed)
    table_row[keep: keep + len(freed)] = TRASH_BLOCK
    return len(freed)


class PagedKVCache:
    """The device block pool + its allocator.

    ``pool`` is a ``{"k", "v"}`` dict of ``(L, N, Bs, H, Dh)`` arrays —
    the same stacked-layer leading axis as the static cache, so the
    layer scan is shared.  The engine owns the authoritative pool arrays
    (they flow through the donated compiled steps); this object carries
    the geometry and the allocator.
    """

    def __init__(self, cfg: GPTConfig, num_blocks: int, block_size: int,
                 dtype=jnp.float32):
        if block_size < 1:
            raise ValueError(f"block_size must be >= 1, got {block_size}")
        self.cfg = cfg
        self.block_size = block_size
        self.num_blocks = num_blocks
        self.dtype = dtype
        self.allocator = BlockAllocator(num_blocks)

    def init_pool(self) -> Dict[str, jax.Array]:
        cfg = self.cfg
        shape = (cfg.n_layer, self.num_blocks, self.block_size,
                 cfg.n_head, cfg.head_dim)
        return {"k": jnp.zeros(shape, self.dtype),
                "v": jnp.zeros(shape, self.dtype)}

    def blocks_for(self, n_tokens: int) -> int:
        """Physical blocks needed to hold ``n_tokens`` cache slots."""
        return -(-n_tokens // self.block_size)

    def export_blocks(self, pool: Dict[str, jax.Array],
                      block_ids) -> Dict[str, Any]:
        """Gather ``block_ids``'s k/v content to HOST numpy — the
        producer half of a disaggregated KV handoff.

        A prefill worker prefills into its OWN pool blocks, exports
        them here, frees the blocks, and ships the payload over the
        queue plane; the consuming decode replica scatters it into
        whatever free blocks ITS allocator hands out
        (:func:`import_blocks`) — physical ids never cross the wire,
        only logical block content, so producer and consumer pools
        need not agree on anything but geometry.
        """
        import numpy as np

        ids = np.asarray(list(block_ids), np.int32)
        if ids.size and (ids.min() <= TRASH_BLOCK
                         or ids.max() >= self.num_blocks):
            raise ValueError(
                f"export_blocks: ids outside (trash, {self.num_blocks})"
            )
        return {key: np.asarray(pool[key][:, ids]) for key in ("k", "v")}


def import_blocks(
    pool: Dict[str, jax.Array],
    payload: Dict[str, jax.Array],
    block_ids: jax.Array,
) -> Dict[str, jax.Array]:
    """Scatter an exported KV payload into ``block_ids`` of ``pool`` —
    the consumer half of a disaggregated handoff (jittable; the engine
    compiles one executable per bucket block count, exactly like the
    bucketed prefill set, so steady-state imports never recompile).

    ``block_ids`` come from the CONSUMER's allocator (never the trash
    block — the allocator cannot issue it), and the caller rewrites the
    slot's block table to these ids, so every trash-block invariant of
    the decode/verify programs is preserved by construction.
    """
    return {
        key: pool[key].at[:, block_ids].set(
            payload[key].astype(pool[key].dtype)
        )
        for key in ("k", "v")
    }


def copy_blocks(
    pool: Dict[str, jax.Array],
    src_ids: jax.Array,
    dst_ids: jax.Array,
) -> Dict[str, jax.Array]:
    """Copy the k/v content of ``src_ids`` into ``dst_ids`` — the
    copy-on-write primitive of the shared-block discipline (jittable;
    one fixed-width program per COW fan-out, compiled like the import
    set).

    A holder about to WRITE into a block whose refcount is > 1 must not
    (the other holders' caches would change under them): it allocates
    fresh blocks, copies the shared content here, swaps its block-table
    entries to the copies, and drops its references on the originals.
    The admission-time claim cap (the last prompt token is always
    recomputed, so every decode/verify/suffix write lands strictly past
    the shared frontier) means the serving plane never hits this in
    nominal flow — COW is the safety net that keeps the invariant
    locally checkable rather than globally assumed.
    """
    return {
        key: pool[key].at[:, dst_ids].set(pool[key][:, src_ids])
        for key in ("k", "v")
    }


class _ChainNode:
    """One radix-tree edge: a run of whole blocks with no branch."""

    __slots__ = ("keys", "ids", "children", "parent", "stamp")

    def __init__(self, keys, ids, parent, stamp):
        self.keys: List[Tuple[int, ...]] = keys   # per-block token tuples
        self.ids: List[int] = ids                 # physical block ids
        self.children: Dict[Tuple[int, ...], "_ChainNode"] = {}
        self.parent: Optional["_ChainNode"] = parent
        self.stamp = stamp


class PrefixIndex:
    """Radix tree of resident KV block chains, keyed by prompt tokens.

    The prefix cache of the serving plane: after a prompt is prefilled,
    its FULL blocks (every block whose ``block_size`` tokens were all
    written — the partial tail block keeps growing under decode and is
    never indexed) are inserted as a chain, and the index RETAINS a
    reference on each, so the chain stays resident after the request
    finishes.  A later request claims its longest whole-block shared
    prefix with :meth:`claim` — refcount bumps only, zero device work —
    and prefills just the uncovered suffix.

    Granularity is the block, deliberately: a physical block either
    holds exactly the claimed tokens' KV or it is not claimed, so
    sharing never needs sub-block copies, and the radix edges are runs
    of ``(tokens-per-block,)`` tuples compared whole.  Chains are keyed
    per ``key`` (the adapter name, or ``None`` for the base model),
    because adapter-bearing prefill writes adapter-specific KV — one
    tenant's chain must never satisfy another's lookup.

    Eviction (:meth:`evict`) walks least-recently-used LEAF edges and
    releases blocks tail-first, and ONLY blocks whose refcount is 1 —
    a block some live chain still holds is never evicted out from
    under it (releasing it would not free memory anyway; the holder's
    reference keeps it live).  Interior edges are pinned by their
    children: chain integrity means a prefix block never leaves before
    the blocks extending it.
    """

    def __init__(self, allocator: BlockAllocator, block_size: int):
        self.allocator = allocator
        self.block_size = block_size
        self._roots: Dict[Any, _ChainNode] = {}
        self._clock = 0
        self.cached_blocks = 0
        self.lookups = 0
        self.hits = 0
        self.blocks_claimed = 0
        self.blocks_inserted = 0
        self.blocks_evicted = 0

    def _tick(self) -> int:
        self._clock += 1
        return self._clock

    def _block_keys(self, tokens) -> List[Tuple[int, ...]]:
        Bs = self.block_size
        n = len(tokens) // Bs
        return [tuple(int(t) for t in tokens[i * Bs:(i + 1) * Bs])
                for i in range(n)]

    def _match(self, key: Any, blocks: List[Tuple[int, ...]]) -> List[int]:
        root = self._roots.get(key)
        out: List[int] = []
        if root is None:
            return out
        node, i, stamp = root, 0, self._tick()
        while i < len(blocks):
            child = node.children.get(blocks[i])
            if child is None:
                break
            j = 0
            while (j < len(child.keys) and i < len(blocks)
                   and child.keys[j] == blocks[i]):
                out.append(child.ids[j])
                i += 1
                j += 1
            child.stamp = stamp
            if j < len(child.keys):
                break
            node = child
        return out

    def claim(self, key: Any, tokens, max_blocks: int) -> List[int]:
        """Longest resident whole-block prefix of ``tokens`` under
        ``key``, capped at ``max_blocks``, with a reference RETAINED on
        every returned block (the caller owns one free() per id, same
        as an alloc).  ``max_blocks`` is the caller's write-safety cap:
        the engine passes ``(prompt_len - 1) // block_size`` so the
        final prompt token is always recomputed (it produces the
        first-token logits) and every subsequent write lands strictly
        past the shared blocks."""
        self.lookups += 1
        if max_blocks <= 0:
            return []
        ids = self._match(key, self._block_keys(tokens))[:max_blocks]
        if not ids:
            return []
        self.allocator.retain(ids)
        self.hits += 1
        self.blocks_claimed += len(ids)
        return ids

    def insert(self, key: Any, tokens, block_ids) -> int:
        """Register ``tokens``'s full blocks (held in ``block_ids``, the
        owning chain's physical blocks in logical order) as a resident
        chain under ``key``.  Blocks already covered by an existing
        chain are skipped (the walk matches them by token content);
        newly stored blocks are RETAINED by the index.  Returns the
        number of blocks newly inserted."""
        blocks = self._block_keys(tokens)
        if len(block_ids) < len(blocks):
            raise ValueError(
                f"insert: {len(blocks)} full blocks of tokens but only "
                f"{len(block_ids)} block ids"
            )
        if not blocks:
            return 0
        root = self._roots.get(key)
        if root is None:
            root = self._roots[key] = _ChainNode([], [], None, 0)
        node, i, stamp, added = root, 0, self._tick(), 0
        while i < len(blocks):
            child = node.children.get(blocks[i])
            if child is None:
                keys = blocks[i:]
                ids = [int(b) for b in block_ids[i:len(blocks)]]
                self.allocator.retain(ids)
                new = _ChainNode(keys, ids, node, stamp)
                node.children[keys[0]] = new
                added += len(ids)
                break
            j = 0
            while (j < len(child.keys) and i < len(blocks)
                   and child.keys[j] == blocks[i]):
                i += 1
                j += 1
            child.stamp = stamp
            if j == len(child.keys):
                node = child
                continue
            if i == len(blocks):
                break  # strict prefix of an existing edge: fully covered
            # Diverged mid-edge: split the edge at j, then loop — the
            # next iteration hangs the new suffix under the split point.
            tail = _ChainNode(child.keys[j:], child.ids[j:], child,
                              child.stamp)
            tail.children = child.children
            for grand in tail.children.values():
                grand.parent = tail
            child.keys = child.keys[:j]
            child.ids = child.ids[:j]
            child.children = {tail.keys[0]: tail}
            node = child
        self.cached_blocks += added
        self.blocks_inserted += added
        return added

    def _leaves(self):
        out = []
        for root in self._roots.values():
            stack = list(root.children.values())
            while stack:
                n = stack.pop()
                if n.children:
                    stack.extend(n.children.values())
                else:
                    out.append(n)
        return out

    def evict(self, n_blocks: int) -> int:
        """Release up to ``n_blocks`` resident blocks, LRU leaves first,
        tail-first within a leaf, skipping any block a live chain still
        holds (refcount > 1).  Returns the number of blocks actually
        returned to the free list."""
        freed = 0
        visited: set = set()
        while freed < n_blocks:
            leaf = None
            for cand in self._leaves():
                if id(cand) in visited:
                    continue
                if leaf is None or cand.stamp < leaf.stamp:
                    leaf = cand
            if leaf is None:
                break
            visited.add(id(leaf))
            while leaf.keys and freed < n_blocks:
                b = leaf.ids[-1]
                if self.allocator.refcount(b) > 1:
                    break  # a live chain holds it: pinned
                leaf.keys.pop()
                leaf.ids.pop()
                self.allocator.free([b])
                self.cached_blocks -= 1
                self.blocks_evicted += 1
                freed += 1
            if not leaf.keys and leaf.parent is not None:
                leaf.parent.children = {
                    k: v for k, v in leaf.parent.children.items()
                    if v is not leaf
                }
        return freed

    def drop(self, key: Any) -> int:
        """Release every chain under ``key`` (adapter replaced/removed:
        its KV is stale the moment the factors change).  Blocks shared
        with in-flight chains stay live until those chains drop them."""
        root = self._roots.pop(key, None)
        if root is None:
            return 0
        dropped = 0
        stack = list(root.children.values())
        while stack:
            n = stack.pop()
            stack.extend(n.children.values())
            self.allocator.free(n.ids)
            dropped += len(n.ids)
        self.cached_blocks -= dropped
        return dropped

    def drop_all(self) -> int:
        """Release every resident chain (engine stop)."""
        return sum(self.drop(k) for k in list(self._roots))

    def stats(self) -> Dict[str, int]:
        return {
            "lookups": self.lookups,
            "hits": self.hits,
            "blocks_claimed": self.blocks_claimed,
            "blocks_inserted": self.blocks_inserted,
            "blocks_evicted": self.blocks_evicted,
            "cached_blocks": self.cached_blocks,
        }


def paged_prefill(
    cfg: GPTConfig,
    params: Dict[str, Any],
    pool: Dict[str, jax.Array],
    tokens: jax.Array,
    prompt_len: jax.Array,
    block_ids: jax.Array,
    compute_dtype=jnp.float32,
    adapters: Optional[Dict[str, jax.Array]] = None,
    adapter_id: Optional[jax.Array] = None,
    lora_impl: str = "xla",
) -> Tuple[jax.Array, Dict[str, jax.Array]]:
    """One prompt through the full-sequence causal pass, cache written
    into the sequence's pool blocks.

    Args:
        tokens: ``(T,)`` int32, the prompt right-padded to a bucket
            length ``T`` that is a multiple of the pool's block size.
        prompt_len: scalar int32, the number of VALID leading tokens.
        block_ids: ``(T // block_size,)`` int32 physical blocks that
            will hold cache positions ``[0, T)`` of this sequence.

    Returns:
        ``(next-token logits (V,) f32 at position prompt_len - 1,
        updated pool)``.  Padding positions write garbage into the tail
        of the sequence's own blocks; decode masks ``s <= seq_len`` so
        it is never attended, and the sequence's own growth overwrites
        it slot by slot.

    Compiled once per bucket length ``T`` — the "few bucketed prompt
    lengths" prefill programs of the serving plane.

    ``adapters``/``adapter_id`` (multi-tenant LoRA): the pool's stacked
    per-layer factor buffers plus THIS prompt's scalar int32 slot id
    (an operand — any tenant rides the same bucket program; slot 0 is
    the zero-delta base model).  ``None`` keeps the graph
    byte-identical to pre-LoRA rounds.
    """
    c = compute_dtype
    T = tokens.shape[0]
    Bs = pool["k"].shape[2]
    if T % Bs != 0:
        raise ValueError(
            f"prefill bucket length {T} is not a multiple of the "
            f"block size {Bs}"
        )
    x = _embed(params, tokens[None], c) + params["wpe"][:T].astype(c)
    # The contiguous temp cache reuses the static path's stacked-layer
    # scan verbatim (ONE source for the block math), then the per-layer
    # k/v reshape into whole blocks and scatter into the pool.
    tmp = {
        "k": jnp.zeros((cfg.n_layer, 1, T, cfg.n_head, cfg.head_dim),
                       pool["k"].dtype),
        "v": jnp.zeros((cfg.n_layer, 1, T, cfg.n_head, cfg.head_dim),
                       pool["v"].dtype),
    }
    ad_ids = None if adapter_id is None else adapter_id.reshape((1,))
    hidden, tmp = _trunk_blocks(cfg, params, tmp, x, 0, c,
                                adapters=adapters, adapter_ids=ad_ids,
                                lora_impl=lora_impl)
    h_last = jax.lax.dynamic_index_in_dim(
        hidden[0], prompt_len - 1, axis=0, keepdims=False
    )
    logits = _head_logits(params, h_last, c)
    n = T // Bs
    out = {}
    for key in ("k", "v"):
        per_block = tmp[key][:, 0].reshape(
            cfg.n_layer, n, Bs, cfg.n_head, cfg.head_dim
        )
        out[key] = pool[key].at[:, block_ids].set(per_block)
    return logits, out


def paged_decode_step(
    cfg: GPTConfig,
    params: Dict[str, Any],
    pool: Dict[str, jax.Array],
    block_tables: jax.Array,
    seq_lens: jax.Array,
    tokens: jax.Array,
    compute_dtype=jnp.float32,
    write_limit: Optional[jax.Array] = None,
    adapters: Optional[Dict[str, jax.Array]] = None,
    adapter_ids: Optional[jax.Array] = None,
    lora_impl: str = "xla",
) -> Tuple[jax.Array, Dict[str, jax.Array]]:
    """One token for every slot of the fixed-width active set.

    Args:
        block_tables: ``(W, M)`` int32 — each slot's physical blocks in
            logical order; unused entries (and whole inactive rows)
            point at the trash block.
        seq_lens: ``(W,)`` int32 — tokens already IN the cache per slot;
            the current token is written at this position.
        tokens: ``(W,)`` int32 — the token each slot feeds this step
            (inactive slots: anything; their row is masked by pointing
            at the trash block and never being read).
        write_limit: optional ``(W,)`` int32 — positions ``>= limit``
            write into the trash block instead of the slot's own blocks.
            The draft chain of the speculative path dispatches this
            program at positions past some slots' allocated coverage
            (uniform chain length over non-uniform per-slot widths);
            the limit redirects those strays.  ``None`` = the plain
            serve decode program, graph-identical to pre-spec rounds.
        adapters: optional stacked per-layer LoRA factor buffers
            (``serve/lora.py`` pool; leading axis L rides the scan
            like the KV pool) with per-slot ``adapter_ids`` int32 —
            each slot's own adapter delta lands on its qkv/proj
            projections (slot 0 = zero delta).  ``None`` = the
            pre-LoRA program, byte-identical.

    Returns:
        ``(logits (W, V) f32, updated pool)``.

    ONE compiled program for any mix of sequence lengths: the per-slot
    write position, the gather, and the visibility mask are all data,
    never shapes — join-on-arrival/evict-on-finish between steps only
    changes operand VALUES, so steady-state serving never recompiles.
    """
    c = compute_dtype
    Bs = pool["k"].shape[2]
    W, M = block_tables.shape
    S = M * Bs
    pos = seq_lens
    # Clamp the positional lookup: inactive slots carry pos 0, active
    # ones are scheduler-bounded to < seq_len; the clamp only guards
    # garbage from ever indexing out of the table.
    safe_pos = jnp.minimum(pos, params["wpe"].shape[0] - 1)
    x = _embed(params, tokens, c) + params["wpe"][safe_pos].astype(c)
    blk_idx = pos // Bs
    if write_limit is not None:
        # Chain positions may run past the table width; the clamp keeps
        # the gather in bounds and the limit sends the write to trash.
        blk_idx = jnp.minimum(blk_idx, M - 1)
    write_blk = jnp.take_along_axis(
        block_tables, blk_idx[:, None], axis=1
    )[:, 0]
    if write_limit is not None:
        write_blk = jnp.where(pos < write_limit, write_blk, TRASH_BLOCK)
    write_off = pos % Bs
    scale = cfg.head_dim ** -0.5
    # Visible: cache positions [0, pos] inclusive — the current token's
    # k/v are written before the gather, exactly the static path's
    # causal frontier.
    visible = jnp.arange(S)[None, :] <= pos[:, None]

    def block(carry, layer):
        x, = carry
        if adapters is None:
            p, k_pool, v_pool = layer  # (N, Bs, H, Dh) each
            ad = None
        else:
            p, k_pool, v_pool, ad = layer
        h = _layer_norm(x, p["ln1_g"], p["ln1_b"])
        qkv = h @ resolve_weight(p, "qkv_w", c) + p["qkv_b"].astype(c)
        qkv = apply_lora(qkv, h, ad, "qkv", adapter_ids, lora_impl)
        q, k, v = jnp.split(qkv, 3, axis=-1)

        def heads(z):
            return z.reshape(W, cfg.n_head, cfg.head_dim)

        k_pool = k_pool.at[write_blk, write_off].set(
            heads(k).astype(k_pool.dtype)
        )
        v_pool = v_pool.at[write_blk, write_off].set(
            heads(v).astype(v_pool.dtype)
        )
        ctx_k = k_pool[block_tables].reshape(W, S, cfg.n_head, cfg.head_dim)
        ctx_v = v_pool[block_tables].reshape(W, S, cfg.n_head, cfg.head_dim)
        scores = jnp.einsum(
            "whd,wshd->whs", heads(q).astype(jnp.float32),
            ctx_k.astype(jnp.float32),
        ) * scale
        scores = jnp.where(visible[:, None, :], scores, _NEG_INF)
        probs = jax.nn.softmax(scores, axis=-1)
        att = jnp.einsum(
            "whs,wshd->whd", probs, ctx_v.astype(jnp.float32)
        ).reshape(W, cfg.d_model).astype(c)
        proj = att @ resolve_weight(p, "proj_w", c) + p["proj_b"].astype(c)
        proj = apply_lora(proj, att, ad, "proj", adapter_ids, lora_impl)
        x = x + proj
        if cfg.n_experts > 0:
            # Same routed-MLP math as the static decode; the routed set
            # here is the W current tokens (see generate() caveat).
            x2, _ = _moe_residual(x[:, None], p, cfg, groups=1)
            x = x2[:, 0]
        else:
            x = _mlp_residual(x, p, c)
        return (x,), (k_pool, v_pool)

    xs = (params["blocks"], pool["k"], pool["v"])
    if adapters is not None:
        xs = xs + (adapters,)
    (x,), (k_new, v_new) = jax.lax.scan(block, (x,), xs)
    logits = _head_logits(params, x, c)
    return logits, {"k": k_new, "v": v_new}


def paged_verify_step(
    cfg: GPTConfig,
    params: Dict[str, Any],
    pool: Dict[str, jax.Array],
    block_tables: jax.Array,
    seq_lens: jax.Array,
    tokens: jax.Array,
    write_limit: jax.Array,
    compute_dtype=jnp.float32,
    adapters: Optional[Dict[str, jax.Array]] = None,
    adapter_ids: Optional[jax.Array] = None,
    lora_impl: str = "xla",
) -> Tuple[jax.Array, Dict[str, jax.Array]]:
    """``T`` tokens for every slot in ONE dispatch — the target model's
    speculative verification program.  ``adapters``/``adapter_ids``
    apply each slot's own LoRA delta across its whole window (see
    :func:`paged_decode_step`); verification composes with the
    adapter pool because the TARGET is what carries the tenant's
    adapter — a base-model draft just proposes, and disagreements are
    corrected by the adapter-bearing verify sample.

    Where :func:`paged_decode_step` feeds one token per slot at
    ``seq_lens``, this feeds a ``(W, T)`` window — each slot's current
    token followed by its ``K = T - 1`` drafted tokens — at positions
    ``seq_lens + [0, T)``, writes all ``T`` k/v entries into the slot's
    blocks, and returns logits at EVERY window position, so the target
    scores K draft proposals at the cost of one (wider) dispatch
    instead of K sequential ones.  Causality within the window is the
    static path's frontier: query ``i`` sees cache positions
    ``<= seq_lens + i`` (its own fresh write included — the scatter
    lands before the gather, exactly like the decode step).

    Args:
        tokens: ``(W, T)`` int32 window per slot.  Slots speculating
            fewer than ``T - 1`` tokens pad with anything; their
            ``write_limit`` trashes the pad writes and the engine
            ignores the pad logits.
        write_limit: ``(W,)`` int32 — positions ``>= limit`` write into
            the trash block (inactive slots carry 0: every write
            trashed).

    Returns:
        ``(logits (W, T, V) f32, updated pool)``.

    Fixed ``(W, T)`` width for the engine's lifetime: accept/reject,
    rollback, and per-slot draft widths are all operand values, so the
    speculative steady state stays on the compiled-once program set.
    """
    c = compute_dtype
    Bs = pool["k"].shape[2]
    W, M = block_tables.shape
    T = tokens.shape[1]
    S = M * Bs
    pos = seq_lens[:, None] + jnp.arange(T)[None, :]          # (W, T)
    safe_pos = jnp.minimum(pos, params["wpe"].shape[0] - 1)
    x = _embed(params, tokens, c) + params["wpe"][safe_pos].astype(c)
    write_blk = jnp.take_along_axis(
        block_tables, jnp.minimum(pos // Bs, M - 1), axis=1
    )
    write_blk = jnp.where(pos < write_limit[:, None], write_blk,
                          TRASH_BLOCK)
    write_off = pos % Bs
    scale = cfg.head_dim ** -0.5
    visible = jnp.arange(S)[None, None, :] <= pos[:, :, None]  # (W, T, S)

    def block(carry, layer):
        x, = carry
        if adapters is None:
            p, k_pool, v_pool = layer  # (N, Bs, H, Dh) each
            ad = None
        else:
            p, k_pool, v_pool, ad = layer
        h = _layer_norm(x, p["ln1_g"], p["ln1_b"])
        qkv = h @ resolve_weight(p, "qkv_w", c) + p["qkv_b"].astype(c)
        qkv = apply_lora(qkv, h, ad, "qkv", adapter_ids, lora_impl)
        q, k, v = jnp.split(qkv, 3, axis=-1)

        def heads(z):
            return z.reshape(W, T, cfg.n_head, cfg.head_dim)

        k_pool = k_pool.at[write_blk, write_off].set(
            heads(k).astype(k_pool.dtype)
        )
        v_pool = v_pool.at[write_blk, write_off].set(
            heads(v).astype(v_pool.dtype)
        )
        ctx_k = k_pool[block_tables].reshape(W, S, cfg.n_head, cfg.head_dim)
        ctx_v = v_pool[block_tables].reshape(W, S, cfg.n_head, cfg.head_dim)
        scores = jnp.einsum(
            "wthd,wshd->whts", heads(q).astype(jnp.float32),
            ctx_k.astype(jnp.float32),
        ) * scale
        scores = jnp.where(visible[:, None], scores, _NEG_INF)
        probs = jax.nn.softmax(scores, axis=-1)
        att = jnp.einsum(
            "whts,wshd->wthd", probs, ctx_v.astype(jnp.float32)
        ).reshape(W, T, cfg.d_model).astype(c)
        proj = att @ resolve_weight(p, "proj_w", c) + p["proj_b"].astype(c)
        proj = apply_lora(proj, att, ad, "proj", adapter_ids, lora_impl)
        x = x + proj
        if cfg.n_experts > 0:
            # Routed set = the W*T window tokens (see generate() caveat).
            x, _ = _moe_residual(x, p, cfg, groups=1)
        else:
            x = _mlp_residual(x, p, c)
        return (x,), (k_pool, v_pool)

    xs = (params["blocks"], pool["k"], pool["v"])
    if adapters is not None:
        xs = xs + (adapters,)
    (x,), (k_new, v_new) = jax.lax.scan(block, (x,), xs)
    logits = _head_logits(params, x, c)
    return logits, {"k": k_new, "v": v_new}


def make_slot_keys(
    base_key: jax.Array,
    seeds: jax.Array,
    positions: jax.Array,
) -> jax.Array:
    """Per-slot sampling keys ``fold_in(fold_in(base, seed), position)``.

    The serving sampler's whole RNG discipline: ``seed`` is stable per
    REQUEST (assigned at submit), ``position`` is the cache position of
    the logits being sampled — both deterministic functions of the
    request's own history, never of the batch around it.  So a request
    re-decoded after a recompute preemption (possibly in a different
    slot, among different neighbours) regenerates bitwise-identical
    tokens at any temperature, which is what makes the speculative
    rollback path (and index-based client dedup) safe beyond greedy.
    """
    def one(seed, p):
        return jax.random.fold_in(jax.random.fold_in(base_key, seed), p)

    return jax.vmap(one)(seeds, positions)


def sample_tokens(
    logits: jax.Array,
    keys: jax.Array,
    temperatures: jax.Array,
    top_ks: Optional[jax.Array] = None,
) -> jax.Array:
    """Per-slot sampling decision: greedy where ``temperature <= 0``,
    categorical at ``logits / temperature`` elsewhere, optionally
    truncated to the ``top_ks[w]`` highest-probability tokens.
    Shape-static ``(W, V)`` → ``(W,)`` int32 so it fuses into the
    decode/verify programs.

    Args:
        keys: ``(W,)`` per-slot PRNG keys (:func:`make_slot_keys`) —
            one independent stream per slot, so a slot's draw never
            depends on who else is in the batch.
        top_ks: optional ``(W,)`` int32 — ``k <= 0`` disables the
            truncation for that slot.  The filter is a full-vocab sort
            + threshold mask (k is an operand VALUE, never a shape), so
            any per-request k rides the same compiled program.

    Per-request top-p is intentionally not offered; greedy/temperature/
    top-k covers the serving SLO bench and the static path keeps the
    full sampler family.
    """
    greedy = jnp.argmax(logits, axis=-1)
    masked = logits
    if top_ks is not None:
        v = logits.shape[-1]
        sorted_desc = jnp.sort(logits, axis=-1)[..., ::-1]
        kth = jnp.take_along_axis(
            sorted_desc, jnp.clip(top_ks - 1, 0, v - 1)[:, None], axis=-1
        )
        masked = jnp.where(
            (top_ks > 0)[:, None] & (logits < kth), _NEG_INF, logits
        )
    temps = jnp.maximum(temperatures, 1e-6)[:, None]
    sampled = jax.vmap(jax.random.categorical)(keys, masked / temps)
    return jnp.where(
        temperatures <= 0.0, greedy, sampled
    ).astype(jnp.int32)
