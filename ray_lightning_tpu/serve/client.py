"""Request submission + token streaming over the DriverQueue plane.

The wire shape mirrors the training stream items: every message is a
small dict with a ``type`` tag (schema-pinned in ``telemetry/schema.py``
— ``validate_serve_request`` / ``validate_serve_reply``).  Transport is
the existing :class:`~ray_lightning_tpu.cluster.queue.DriverQueue`
machinery in BOTH directions:

* **requests** flow client → engine over the engine's inbox (the
  picklable :meth:`ServeEngine.queue_handle`);
* **replies** (per-token stream + completion) flow engine → client over
  a reply queue the CLIENT owns, its ``(host, port)`` carried inside
  each request — so one engine serves any number of clients on any
  host, exactly like workers stream into the training driver.

Backpressure is explicit: a full admission queue comes back as a
``serve_done(status="rejected")`` reply and surfaces as
:class:`ServeRejected` — clients decide whether to retry, the server
never buffers unboundedly.

After a preemption the engine re-streams a request's tokens from index
0 (recompute preemption regenerates them); the client dedups on the
token index, so consumers see each index exactly once.

Client resilience (ISSUE 19): :class:`RetryPolicy` gives ``generate``
a per-request wall-clock budget, typed-rejection retry with jittered
exponential backoff (``rejected``/``expired``/``shed``/``cancelled``
are the retryable outcomes — ``invalid`` and engine errors are not),
and optional HEDGED resubmission: when a request's first attempt
outlives the client's p99 latency estimate (or a fixed trigger), the
same rid is resubmitted with a ``hedge`` marker — the router places a
duplicate on a second replica, both emit the identical seeded stream,
the index dedup below merges them, and the router cancels whichever
placement loses the race.  Hedging never changes tokens, only tail
latency.
"""

from __future__ import annotations

import os
import queue as _pyqueue
import random
import threading
import time
import uuid
from collections import deque
from dataclasses import dataclass
from typing import Dict, Iterator, List, Optional, Sequence

from ray_lightning_tpu.cluster.queue import DriverQueue, QueueHandle
from ray_lightning_tpu.serve.engine import ServeRejected

__all__ = ["RetryPolicy", "ServeClient", "ServeRejected"]


@dataclass
class RetryPolicy:
    """Knobs for :meth:`ServeClient.generate` resilience.

    ``max_attempts`` counts submissions (1 = no retry).  Backoff before
    attempt ``n`` is ``min(backoff_max_s, backoff_s * 2**(n-1))`` with
    full jitter (a uniform draw up to the computed value — retry storms
    from many clients must decorrelate).  ``budget_s`` is the
    per-request wall-clock budget across ALL attempts and backoffs
    (None = the call's ``timeout`` governs alone).  ``hedge`` enables
    hedged resubmission; ``hedge_after_s`` fixes the trigger delay, or
    None to adapt it to the client's observed p99 completion latency
    (no hedging until ``_HEDGE_MIN_SAMPLES`` completions are seen)."""

    max_attempts: int = 3
    backoff_s: float = 0.05
    backoff_max_s: float = 2.0
    budget_s: Optional[float] = None
    hedge: bool = False
    hedge_after_s: Optional[float] = None

    @classmethod
    def from_env(cls) -> "RetryPolicy":
        """Env-resolved policy (knobs registered in
        ``parallel/env_bus.py``): ``RLT_RETRY_MAX``,
        ``RLT_RETRY_BACKOFF_S``, ``RLT_HEDGE``."""
        return cls(
            max_attempts=int(os.environ.get("RLT_RETRY_MAX", "3")),
            backoff_s=float(os.environ.get("RLT_RETRY_BACKOFF_S",
                                           "0.05")),
            hedge=os.environ.get("RLT_HEDGE", "0") == "1",
        )


class _Pending:
    """Client-side state for one in-flight request."""

    def __init__(self, rid: str):
        self.rid = rid
        self.tokens: List[int] = []
        self.stream: _pyqueue.Queue = _pyqueue.Queue()
        self.done = threading.Event()
        self.status: Optional[str] = None
        self.reason: Optional[str] = None
        self.error: Optional[str] = None
        self.item: Optional[dict] = None  # the wire item, for hedging
        self.hedged = False


class ServeClient:
    """One consumer of a serving engine.

    Thread-safe: many threads may ``generate``/``stream`` concurrently
    through one client; replies are demuxed by request id on a single
    reader thread.
    """

    _HEDGE_MIN_SAMPLES = 20

    def __init__(self, handle: QueueHandle,
                 retry: Optional[RetryPolicy] = None):
        self._inbox = handle
        self._replies = DriverQueue()
        self._reply_addr = (self._replies.handle.host,
                            self._replies.handle.port)
        self._pending: Dict[str, _Pending] = {}
        self._lock = threading.Lock()
        self._closed = threading.Event()
        # Tokens whose index had already streamed (preemption, router
        # failover, or hedged-duplicate re-emissions, deduped below) —
        # the disagg bench's re-emission accounting.
        self.re_emitted_tokens = 0
        # Resilience accounting + the p99 estimate hedging adapts to.
        self.retry = retry if retry is not None else RetryPolicy.from_env()
        self.retries = 0
        self.hedges = 0
        self._latencies: deque = deque(maxlen=256)  # guarded by _lock
        self._reader = threading.Thread(
            target=self._read_loop, name="rlt-serve-client", daemon=True
        )
        self._reader.start()

    # -- submission ----------------------------------------------------------
    def submit(self, prompt: Sequence[int], max_new_tokens: int,
               temperature: float = 0.0,
               eos_token_id: Optional[int] = None,
               top_k: Optional[int] = None,
               spec: Optional[int] = None,
               adapter: Optional[str] = None,
               deadline_s: Optional[float] = None,
               priority: int = 0) -> str:
        """Ship one request; returns its id immediately (streaming and
        completion arrive asynchronously).  ``spec`` caps the engine's
        speculative draft count for this request (0 = plain decode);
        tokens stream back in variable-width bursts either way, deduped
        by index like any re-emission.  ``adapter`` names the LoRA
        tenant to decode through (multi-tenant serving; a router
        places the request on — or hot-loads — a member holding it).
        ``priority`` is the brownout shed class: 0 sheds first under
        fleet overload, >= 1 survives to the shed rung."""
        rid = uuid.uuid4().hex[:12]
        pend = _Pending(rid)
        item = {
            "type": "serve_request",
            "rid": rid,
            "prompt": [int(t) for t in prompt],
            "max_new_tokens": int(max_new_tokens),
            "temperature": float(temperature),
            "eos_token_id": eos_token_id,
            "top_k": None if top_k is None else int(top_k),
            "spec": None if spec is None else int(spec),
            "adapter": None if adapter is None else str(adapter),
            "deadline_s": deadline_s,
            "priority": int(priority),
            "reply": list(self._reply_addr),
        }
        pend.item = item
        with self._lock:
            self._pending[rid] = pend
        self._inbox.put(item)
        return rid

    def hedge(self, rid: str) -> bool:
        """Resubmit an in-flight request's rid with the ``hedge``
        marker — a routed fleet places a duplicate on a second replica
        (same fleet-wide seed: identical tokens, merged by the index
        dedup); a single engine ignores the duplicate rid.  At most one
        hedge per request; returns whether one was sent."""
        pend = self._pending.get(rid)
        if pend is None or pend.item is None or pend.hedged \
                or pend.done.is_set():
            return False
        pend.hedged = True
        self._inbox.put(dict(pend.item, hedge=True))
        self.hedges += 1
        return True

    def generate(self, prompt: Sequence[int], max_new_tokens: int,
                 timeout: Optional[float] = 60.0,
                 retry: Optional[RetryPolicy] = None, **kw) -> List[int]:
        """Blocking round trip → the generated tokens, with the
        client's :class:`RetryPolicy` applied: retryable outcomes
        (``rejected``/``expired``/``shed``/``cancelled``) back off with
        jitter and resubmit under a fresh rid, hedging (enabled)
        duplicates a straggling attempt after the trigger delay, and
        ``budget_s`` bounds the whole affair in wall-clock terms."""
        policy = retry if retry is not None else self.retry
        deadline = None if policy.budget_s is None \
            else time.monotonic() + policy.budget_s

        def remaining(default: Optional[float]) -> Optional[float]:
            if deadline is None:
                return default
            left = deadline - time.monotonic()
            if left <= 0:
                raise TimeoutError(
                    f"request budget ({policy.budget_s}s) exhausted"
                )
            return left if default is None else min(default, left)

        last: Optional[ServeRejected] = None
        for attempt in range(max(1, policy.max_attempts)):
            if attempt:
                self.retries += 1
                pause = min(policy.backoff_max_s,
                            policy.backoff_s * (2 ** (attempt - 1)))
                # Full jitter: many clients retrying the same typed
                # rejection must not resubmit in lockstep.
                time.sleep(random.uniform(0.0,
                                          remaining(pause) or pause))
            t_submit = time.monotonic()
            rid = self.submit(prompt, max_new_tokens, **kw)
            pend = self._pending[rid]
            hedge_after = self._hedge_delay(policy)
            wait = remaining(timeout)
            if hedge_after is not None and not pend.done.is_set() \
                    and (wait is None or hedge_after < wait):
                if not pend.done.wait(hedge_after):
                    self.hedge(rid)
                if wait is not None:
                    wait = max(0.0, wait - hedge_after)
            try:
                tokens = self.result(rid, timeout=wait)
            except ServeRejected as e:
                last = e
                continue
            with self._lock:
                self._latencies.append(time.monotonic() - t_submit)
            return tokens
        assert last is not None
        raise last

    def _hedge_delay(self,
                     policy: RetryPolicy) -> Optional[float]:
        """The hedge trigger delay: the fixed knob when set, else the
        client's observed p99 completion latency (None — no hedge —
        until enough completions are banked to estimate one)."""
        if not policy.hedge:
            return None
        if policy.hedge_after_s is not None:
            return policy.hedge_after_s
        with self._lock:
            if len(self._latencies) < self._HEDGE_MIN_SAMPLES:
                return None
            ordered = sorted(self._latencies)
        return ordered[min(len(ordered) - 1,
                           int(0.99 * len(ordered)))]

    def stream(self, prompt: Sequence[int], max_new_tokens: int,
               timeout: Optional[float] = 60.0, **kw) -> Iterator[int]:
        """Submit and yield tokens as the engine emits them (indices
        deduped across preemptions)."""
        rid = self.submit(prompt, max_new_tokens, **kw)
        pend = self._pending[rid]
        next_idx = 0
        while True:
            try:
                kind, payload = pend.stream.get(timeout=timeout)
            except _pyqueue.Empty:
                raise TimeoutError(
                    f"request {rid}: no stream item within {timeout}s"
                ) from None
            if kind == "token":
                idx, tok = payload
                if idx == next_idx:  # dedup re-emissions after preempt
                    next_idx += 1
                    yield tok
            else:  # done
                self._check_done(pend)
                return

    def result(self, rid: str, timeout: Optional[float] = 60.0
               ) -> List[int]:
        pend = self._pending.get(rid)
        if pend is None:
            raise KeyError(f"unknown request id {rid}")
        if not pend.done.wait(timeout):
            raise TimeoutError(f"request {rid} not finished in {timeout}s")
        self._check_done(pend)
        return list(pend.tokens)

    def _check_done(self, pend: _Pending) -> None:
        with self._lock:
            self._pending.pop(pend.rid, None)
        if pend.status == "invalid":
            raise ValueError(
                f"request {pend.rid} invalid: {pend.error}"
            )
        if pend.status == "error":
            raise RuntimeError(
                f"serve engine died with request {pend.rid} in flight: "
                f"{pend.error}"
            )
        if pend.status in ("shed", "cancelled") \
                or pend.reason in ("rejected", "expired"):
            # All four are RETRYABLE: the fleet declined or dropped the
            # work without partial side effects a retry would duplicate
            # ("shed" is the brownout ladder's overload reply,
            # "cancelled" an operator/hedge-path drop).
            raise ServeRejected(
                f"request {pend.rid} "
                f"{pend.reason or pend.status}"
            )

    # -- reply demux ---------------------------------------------------------
    def _read_loop(self) -> None:
        while not self._closed.is_set():
            try:
                item = self._replies.get(timeout=0.5)
            except _pyqueue.Empty:
                continue
            except (OSError, ValueError):
                return  # queue shut down
            if not isinstance(item, dict):
                continue
            pend = self._pending.get(str(item.get("rid")))
            if pend is None:
                continue
            kind = item.get("type")
            if kind == "serve_token":
                idx, tok = int(item["index"]), int(item["token"])
                if idx == len(pend.tokens):
                    pend.tokens.append(tok)
                elif idx < len(pend.tokens):
                    pend.tokens[idx] = tok  # preemption re-emission
                    self.re_emitted_tokens += 1
                pend.stream.put(("token", (idx, tok)))
            elif kind == "serve_done":
                if pend.done.is_set():
                    # Hedged pair: the first terminal report won; the
                    # loser's later "cancelled" (or duplicate
                    # "completed") must not overwrite it.
                    continue
                pend.status = item.get("status")
                pend.reason = item.get("reason")
                pend.error = item.get("error")
                if item.get("tokens"):
                    pend.tokens = [int(t) for t in item["tokens"]]
                pend.stream.put(("done", None))
                pend.done.set()

    def close(self) -> None:
        self._closed.set()
        self._replies.shutdown()
        self._inbox.close()
