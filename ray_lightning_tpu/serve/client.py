"""Request submission + token streaming over the DriverQueue plane.

The wire shape mirrors the training stream items: every message is a
small dict with a ``type`` tag (schema-pinned in ``telemetry/schema.py``
— ``validate_serve_request`` / ``validate_serve_reply``).  Transport is
the existing :class:`~ray_lightning_tpu.cluster.queue.DriverQueue`
machinery in BOTH directions:

* **requests** flow client → engine over the engine's inbox (the
  picklable :meth:`ServeEngine.queue_handle`);
* **replies** (per-token stream + completion) flow engine → client over
  a reply queue the CLIENT owns, its ``(host, port)`` carried inside
  each request — so one engine serves any number of clients on any
  host, exactly like workers stream into the training driver.

Backpressure is explicit: a full admission queue comes back as a
``serve_done(status="rejected")`` reply and surfaces as
:class:`ServeRejected` — clients decide whether to retry, the server
never buffers unboundedly.

After a preemption the engine re-streams a request's tokens from index
0 (recompute preemption regenerates them); the client dedups on the
token index, so consumers see each index exactly once.
"""

from __future__ import annotations

import queue as _pyqueue
import threading
import uuid
from typing import Dict, Iterator, List, Optional, Sequence

from ray_lightning_tpu.cluster.queue import DriverQueue, QueueHandle
from ray_lightning_tpu.serve.engine import ServeRejected

__all__ = ["ServeClient", "ServeRejected"]


class _Pending:
    """Client-side state for one in-flight request."""

    def __init__(self, rid: str):
        self.rid = rid
        self.tokens: List[int] = []
        self.stream: _pyqueue.Queue = _pyqueue.Queue()
        self.done = threading.Event()
        self.status: Optional[str] = None
        self.reason: Optional[str] = None
        self.error: Optional[str] = None


class ServeClient:
    """One consumer of a serving engine.

    Thread-safe: many threads may ``generate``/``stream`` concurrently
    through one client; replies are demuxed by request id on a single
    reader thread.
    """

    def __init__(self, handle: QueueHandle):
        self._inbox = handle
        self._replies = DriverQueue()
        self._reply_addr = (self._replies.handle.host,
                            self._replies.handle.port)
        self._pending: Dict[str, _Pending] = {}
        self._lock = threading.Lock()
        self._closed = threading.Event()
        # Tokens whose index had already streamed (preemption or router
        # failover re-emissions, deduped below) — the disagg bench's
        # re-emission accounting.
        self.re_emitted_tokens = 0
        self._reader = threading.Thread(
            target=self._read_loop, name="rlt-serve-client", daemon=True
        )
        self._reader.start()

    # -- submission ----------------------------------------------------------
    def submit(self, prompt: Sequence[int], max_new_tokens: int,
               temperature: float = 0.0,
               eos_token_id: Optional[int] = None,
               top_k: Optional[int] = None,
               spec: Optional[int] = None,
               adapter: Optional[str] = None,
               deadline_s: Optional[float] = None) -> str:
        """Ship one request; returns its id immediately (streaming and
        completion arrive asynchronously).  ``spec`` caps the engine's
        speculative draft count for this request (0 = plain decode);
        tokens stream back in variable-width bursts either way, deduped
        by index like any re-emission.  ``adapter`` names the LoRA
        tenant to decode through (multi-tenant serving; a router
        places the request on — or hot-loads — a member holding it)."""
        rid = uuid.uuid4().hex[:12]
        with self._lock:
            self._pending[rid] = _Pending(rid)
        self._inbox.put({
            "type": "serve_request",
            "rid": rid,
            "prompt": [int(t) for t in prompt],
            "max_new_tokens": int(max_new_tokens),
            "temperature": float(temperature),
            "eos_token_id": eos_token_id,
            "top_k": None if top_k is None else int(top_k),
            "spec": None if spec is None else int(spec),
            "adapter": None if adapter is None else str(adapter),
            "deadline_s": deadline_s,
            "reply": list(self._reply_addr),
        })
        return rid

    def generate(self, prompt: Sequence[int], max_new_tokens: int,
                 timeout: Optional[float] = 60.0, **kw) -> List[int]:
        """Blocking round trip → the generated tokens."""
        rid = self.submit(prompt, max_new_tokens, **kw)
        return self.result(rid, timeout=timeout)

    def stream(self, prompt: Sequence[int], max_new_tokens: int,
               timeout: Optional[float] = 60.0, **kw) -> Iterator[int]:
        """Submit and yield tokens as the engine emits them (indices
        deduped across preemptions)."""
        rid = self.submit(prompt, max_new_tokens, **kw)
        pend = self._pending[rid]
        next_idx = 0
        while True:
            try:
                kind, payload = pend.stream.get(timeout=timeout)
            except _pyqueue.Empty:
                raise TimeoutError(
                    f"request {rid}: no stream item within {timeout}s"
                ) from None
            if kind == "token":
                idx, tok = payload
                if idx == next_idx:  # dedup re-emissions after preempt
                    next_idx += 1
                    yield tok
            else:  # done
                self._check_done(pend)
                return

    def result(self, rid: str, timeout: Optional[float] = 60.0
               ) -> List[int]:
        pend = self._pending.get(rid)
        if pend is None:
            raise KeyError(f"unknown request id {rid}")
        if not pend.done.wait(timeout):
            raise TimeoutError(f"request {rid} not finished in {timeout}s")
        self._check_done(pend)
        return list(pend.tokens)

    def _check_done(self, pend: _Pending) -> None:
        with self._lock:
            self._pending.pop(pend.rid, None)
        if pend.status == "invalid":
            raise ValueError(
                f"request {pend.rid} invalid: {pend.error}"
            )
        if pend.status == "error":
            raise RuntimeError(
                f"serve engine died with request {pend.rid} in flight: "
                f"{pend.error}"
            )
        if pend.reason in ("rejected", "expired"):
            raise ServeRejected(f"request {pend.rid} {pend.reason}")

    # -- reply demux ---------------------------------------------------------
    def _read_loop(self) -> None:
        while not self._closed.is_set():
            try:
                item = self._replies.get(timeout=0.5)
            except _pyqueue.Empty:
                continue
            except (OSError, ValueError):
                return  # queue shut down
            if not isinstance(item, dict):
                continue
            pend = self._pending.get(str(item.get("rid")))
            if pend is None:
                continue
            kind = item.get("type")
            if kind == "serve_token":
                idx, tok = int(item["index"]), int(item["token"])
                if idx == len(pend.tokens):
                    pend.tokens.append(tok)
                elif idx < len(pend.tokens):
                    pend.tokens[idx] = tok  # preemption re-emission
                    self.re_emitted_tokens += 1
                pend.stream.put(("token", (idx, tok)))
            elif kind == "serve_done":
                pend.status = item.get("status")
                pend.reason = item.get("reason")
                pend.error = item.get("error")
                if item.get("tokens"):
                    pend.tokens = [int(t) for t in item["tokens"]]
                pend.stream.put(("done", None))
                pend.done.set()

    def close(self) -> None:
        self._closed.set()
        self._replies.shutdown()
        self._inbox.close()
