"""Overload brownout ladder for the serving router (ISSUE 19).

Graceful degradation under sustained overload, as a tiny hysteresis
state machine the router feeds with fleet utilization (the
``aggregate_fleet`` view built from per-replica
:class:`~ray_lightning_tpu.serve.capacity.CapacityOracle` beat
blocks).  Levels, each subsuming the one below:

* **0 — healthy**: no intervention.
* **1 — degrade**: speculative draft lanes forced off (``spec -> 0``).
  Draft FLOPs are the cheapest capacity to reclaim: acceptance-rate
  upside evaporates exactly when the fleet is saturated, because the
  target-model verify pass is the bottleneck either way.
* **2 — clamp**: ``max_new_tokens`` capped at ``max_new_cap`` on top.
  Bounded responses bound per-request slot residency, which bounds
  queue wait — the dominant p99 term under overload.
* **3 — shed**: best-effort traffic (``priority < 1``) gets the typed
  retryable ``shed`` reply on top.  Paying/priority traffic
  (``priority >= 1``) still admits.  One **half-open probe** request
  per ``probe_every_s`` is let through the shed gate so the ladder can
  sense recovery from the probe's effect on utilization — without it
  a fully-shedding fleet reports zero load and looks healthy while
  serving nobody.

Hysteresis: climbing rung ``i`` requires utilization >= ``enter[i]``;
descending requires utilization < ``enter[i] - exit_margin``, and
every move waits out ``min_dwell_s`` since the last one — a noisy
utilization signal oscillating around a threshold must not flap the
admission policy every poll tick.

jax-free host logic; the clock is injectable for deterministic tests
(``rlt: clock-injectable``).
"""

from __future__ import annotations

import time
from typing import Callable, Optional, Sequence

__all__ = ["BrownoutLadder"]


class BrownoutLadder:
    """See module docstring.  Not thread-safe by itself — the router
    only touches it under its own control-plane lock."""

    def __init__(
        self,
        *,
        enter: Sequence[float] = (0.85, 0.95, 1.0),
        exit_margin: float = 0.10,
        min_dwell_s: float = 2.0,
        probe_every_s: float = 5.0,
        max_new_cap: int = 64,
        clock: Callable[[], float] = time.monotonic,
    ):
        if len(enter) != 3:
            raise ValueError(f"enter must name 3 rung thresholds: {enter}")
        if sorted(enter) != list(enter):
            raise ValueError(f"enter thresholds must be ascending: {enter}")
        if exit_margin <= 0:
            raise ValueError(f"exit_margin must be > 0: {exit_margin}")
        if max_new_cap < 1:
            raise ValueError(f"max_new_cap must be >= 1: {max_new_cap}")
        self.enter = tuple(float(e) for e in enter)
        self.exit_margin = float(exit_margin)
        self.min_dwell_s = float(min_dwell_s)
        self.probe_every_s = float(probe_every_s)
        self.max_new_cap = int(max_new_cap)
        self._clock = clock
        self.level = 0
        self._last_change: Optional[float] = None
        self._last_probe: Optional[float] = None

    def observe(self, utilization: float,
                now: Optional[float] = None) -> int:
        """Ingest one fleet-utilization sample; returns the (possibly
        updated) level.  Moves one rung at a time: a single wild sample
        cannot jump a healthy fleet straight to shedding."""
        now = self._clock() if now is None else now
        dwelt = (self._last_change is None
                 or now - self._last_change >= self.min_dwell_s)
        if self.level < 3 and utilization >= self.enter[self.level]:
            # First climb off healthy is immediate — overload response
            # latency matters more than flap protection at level 0.
            if dwelt or self.level == 0:
                self.level += 1
                self._last_change = now
        elif self.level > 0 \
                and utilization < self.enter[self.level - 1] \
                - self.exit_margin:
            if dwelt:
                self.level -= 1
                self._last_change = now
        return self.level

    def allow_probe(self, now: Optional[float] = None) -> bool:
        """At shed level, admit one best-effort request per
        ``probe_every_s`` as the half-open recovery probe."""
        now = self._clock() if now is None else now
        if self._last_probe is None \
                or now - self._last_probe >= self.probe_every_s:
            self._last_probe = now
            return True
        return False
