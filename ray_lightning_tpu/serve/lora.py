"""Multi-tenant LoRA serving: one resident base model, many adapters.

``models/`` trains LoRA adapters and ``merge_lora`` folds one of them
into the base weights — fine for a single tenant, an HBM cliff for
many: each tenant's merged copy is a full resident model.  The serving
plane instead keeps ONE lora-free base resident and multiplexes up to
``max_adapters`` tenants over it:

* :class:`AdapterPool` — the host-side slot registry (free-list, hot
  add/remove, name → slot) over device-resident STACKED factor
  buffers: per hook site (attention qkv / proj), every adapter's A/B
  factors live in one ``(L, N+1, ...)`` array whose leading layer axis
  rides the engine's block scan exactly like the KV pool.  Slot 0 is
  reserved as the NULL adapter (zero factors — the base model), so
  requests without an adapter share the same program;
* **batched per-slot application** — each compiled dispatch takes a
  per-slot ``adapter_ids`` int32 OPERAND (never a shape) and applies
  ``y += (x @ A[ids]) @ B[ids]`` as a gathered einsum / Pallas BGMV
  kernel (``ops/lora.py``), so one decode/verify/prefill program
  serves ANY mix of tenants and hot add/remove never recompiles —
  the round-11 zero-recompile contract, test- and bench-asserted.

The pool mirrors :class:`~.kv_cache.BlockAllocator` discipline: the
registry is jax-free host state, device mutation happens through ONE
jitted scatter program built at pool init (slot index is an operand),
and misuse (unknown name, rank drift, capacity, double-add) raises
typed errors instead of corrupting a co-tenant's traffic.

Wire form: adapters ride the queue plane as ``serve_adapter_load``
frames (``serve/dist/handoff.py::make_adapter_load_item``) whose bulk
payload is :func:`encode_adapter` bytes — chunk-sent past 8MB exactly
like KV handoffs, so a router can hot-load a tenant onto any replica
or prefill worker mid-traffic.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional

__all__ = [
    "ADAPTER_KEYS",
    "AdapterPool",
    "encode_adapter",
    "decode_adapter",
    "validate_adapter",
]

#: The four stacked factor tensors every adapter carries
#: (models/gpt.py::extract_lora emits exactly these + "scale").
ADAPTER_KEYS = ("qkv_a", "qkv_b", "proj_a", "proj_b")


def validate_adapter(adapter: Dict[str, Any], cfg, rank: int) -> None:
    """Shape/rank gate for one adapter against a pool's geometry.
    Raises ``ValueError`` — a mis-shaped adapter scattered into the
    stacked buffers would serve garbage to ONE tenant while every
    neighbour stays healthy, the quiet failure mode a multi-tenant
    pool must never allow."""
    if not isinstance(adapter, dict):
        raise ValueError(
            f"adapter must be a dict, got {type(adapter).__name__}"
        )
    missing = [k for k in ADAPTER_KEYS if k not in adapter]
    if missing:
        raise ValueError(f"adapter missing factor(s) {missing}")
    L, d = cfg.n_layer, cfg.d_model
    expect = {
        "qkv_a": (L, d, rank),
        "qkv_b": (L, rank, 3 * d),
        "proj_a": (L, d, rank),
        "proj_b": (L, rank, d),
    }
    for key, shape in expect.items():
        got = tuple(adapter[key].shape)
        if got != shape:
            raise ValueError(
                f"adapter factor {key!r} has shape {got}, pool expects "
                f"{shape} (rank {rank} over L={L}, d={d} — every "
                f"adapter in a pool shares the stacked-buffer rank)"
            )


def encode_adapter(adapter: Dict[str, Any]) -> bytes:
    """Serialize an adapter (factors + scale) for the queue plane —
    the ``serve_adapter_load`` frame's bulk payload, same codec as KV
    handoffs."""
    import numpy as np

    from ray_lightning_tpu.mpmd.transfer import encode_tree

    tree = {k: np.asarray(adapter[k]) for k in ADAPTER_KEYS}
    tree["scale"] = np.float32(adapter.get("scale", 1.0))
    return encode_tree(tree)


def decode_adapter(item: Dict[str, Any]) -> Dict[str, Any]:
    """Inverse of :func:`encode_adapter` over a ``serve_adapter_load``
    frame (resolves the data/shm payload form like a KV handoff)."""
    from ray_lightning_tpu.mpmd.transfer import decode_tree, resolve_payload

    tree = decode_tree(resolve_payload(item))
    tree["scale"] = float(tree["scale"])
    return tree


class AdapterPool:
    """Up to ``max_adapters`` LoRA adapters stacked in resident device
    buffers + the host-side slot registry (see module docstring).

    Thread-safe: loads arrive from the queue-drain path or driver
    threads while the serve loop dispatches — ``buffers`` is swapped
    atomically (immutable jax arrays under one reference), so an
    in-flight dispatch keeps the tree it already read, and a new slot
    can only be REFERENCED after :meth:`add` returned.
    """

    def __init__(self, model_cfg, max_adapters: int, rank: int,
                 dtype=None, impl: Optional[str] = None):
        import jax
        import jax.numpy as jnp

        from ray_lightning_tpu.ops.lora import resolve_bgmv_impl

        if max_adapters < 1:
            raise ValueError(
                f"max_adapters must be >= 1, got {max_adapters}"
            )
        if rank < 1:
            raise ValueError(f"adapter rank must be >= 1, got {rank}")
        self.cfg = model_cfg
        self.max_adapters = max_adapters
        self.rank = rank
        dtype = jnp.float32 if dtype is None else dtype
        self.dtype = dtype
        L, d, N1 = model_cfg.n_layer, model_cfg.d_model, max_adapters + 1
        # Slot 0 = the NULL adapter: zero factors, delta exactly 0.0.
        self.buffers: Dict[str, jax.Array] = {
            "qkv_a": jnp.zeros((L, N1, d, rank), dtype),
            "qkv_b": jnp.zeros((L, N1, rank, 3 * d), dtype),
            "proj_a": jnp.zeros((L, N1, d, rank), dtype),
            "proj_b": jnp.zeros((L, N1, rank, d), dtype),
        }
        self.impl = impl or resolve_bgmv_impl(d, rank, 3 * d, dtype)
        # ONE scatter program for any slot (slot index is an operand) —
        # built here so a hot add can never construct a fresh jit on
        # the request path (rlt-lint RLT001 guards add()).  NO buffer
        # donation: the atomic-swap thread-safety contract (an
        # in-flight dispatch keeps the tree it already read) requires
        # the OLD buffers to stay alive until every reader drops them —
        # donation would delete them under a concurrently-dispatching
        # serve tick.  Hot adds are rare; the copy is the price of the
        # contract.

        def _scatter(buffers, factors, slot):
            return {
                k: buffers[k].at[:, slot].set(
                    factors[k].astype(buffers[k].dtype)
                )
                for k in buffers
            }

        from ray_lightning_tpu.telemetry.program_ledger import ledgered_jit

        self._scatter_fn = ledgered_jit(_scatter, site="serve/lora_scatter")
        self._slots: Dict[str, int] = {}      # guarded by self._lock
        # LIFO free list, mirroring BlockAllocator: recently freed
        # slots re-issue first.
        self._free: List[int] = list(range(max_adapters, 0, -1))
        self._lock = threading.Lock()
        self.loads = 0
        self.unloads = 0

    # -- registry ------------------------------------------------------------
    @property
    def loaded(self) -> int:
        with self._lock:
            return len(self._slots)

    @property
    def slots_free(self) -> int:
        with self._lock:
            return len(self._free)

    def names(self) -> List[str]:
        with self._lock:
            return sorted(self._slots)

    def slot_of(self, name: str) -> int:
        """Device slot for ``name``; raises ``KeyError`` when the
        adapter is not loaded (submit()'s typed-rejection path)."""
        with self._lock:
            return self._slots[name]

    def has(self, name: str) -> bool:
        with self._lock:
            return name in self._slots

    # -- device mutation -----------------------------------------------------
    def add(self, name: str, adapter: Dict[str, Any]) -> int:
        """Load (or replace) ``name``'s factors; returns its slot.

        Replacing reuses the existing slot — callers gate replacement
        of an IN-USE adapter (``ServeEngine.add_adapter`` refuses while
        any queued/active request references the name; the pool itself
        cannot see the scheduler).  The scale is folded into the B
        factors here, so dispatches need no per-slot scale operand.
        """
        import jax.numpy as jnp
        import numpy as np

        validate_adapter(adapter, self.cfg, self.rank)
        scale = float(adapter.get("scale", 1.0))
        with self._lock:
            slot = self._slots.get(name)
            if slot is None:
                if not self._free:
                    raise RuntimeError(
                        f"adapter pool full ({self.max_adapters} "
                        f"slots) — remove a tenant or raise "
                        f"ServeConfig.max_adapters"
                    )
                slot = self._free.pop()
                self._slots[name] = slot
            factors = {
                "qkv_a": jnp.asarray(np.asarray(adapter["qkv_a"])),
                "qkv_b": jnp.asarray(
                    np.asarray(adapter["qkv_b"]) * scale
                ),
                "proj_a": jnp.asarray(np.asarray(adapter["proj_a"])),
                "proj_b": jnp.asarray(
                    np.asarray(adapter["proj_b"]) * scale
                ),
            }
            self.buffers = self._scatter_fn(
                self.buffers, factors, np.int32(slot)
            )
            self.loads += 1
            return slot

    def remove(self, name: str) -> None:
        """Free ``name``'s slot back to the pool.  The stale factors
        stay in the buffer until the slot is re-issued — harmless by
        construction, because no request can resolve the name anymore
        (the same reasoning as freed KV blocks keeping stale content).
        """
        with self._lock:
            slot = self._slots.pop(name, None)
            if slot is None:
                raise KeyError(f"adapter {name!r} is not loaded")
            self._free.append(slot)
            self.unloads += 1

    # -- introspection -------------------------------------------------------
    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "loaded": len(self._slots),
                "slots_free": len(self._free),
                "max_adapters": self.max_adapters,
                "rank": self.rank,
                "loads": self.loads,
                "unloads": self.unloads,
                "impl": self.impl,
            }
