"""Continuous batcher: admission queue, slot table, preemption policy.

jax-free host-side control plane for the serving engine.  The unit of
scheduling is the **slot** — one of ``num_slots`` rows of the compiled
decode program's fixed width.  Between decode steps the scheduler:

1. **expires** queued requests whose deadline passed (never admitted —
   cheaper to reject at the queue than to evict mid-decode);
2. **evicts** finished slots, freeing their blocks immediately;
3. **admits** queued requests while a free slot AND enough blocks for
   the request's prefill bucket exist (join-on-arrival: a request never
   waits for the running batch to drain);
4. **grows** active sequences one block at a time as they cross block
   boundaries.  When the pool is dry, the YOUNGEST active request is
   preempted (recompute-style: blocks freed, request requeued at the
   FRONT so it re-admits first) — latency already invested in old
   requests is never thrown away for a newcomer.

Everything here mutates small numpy arrays (block tables, seq lens,
temperatures) that the engine ships into the compiled step as operand
VALUES — admission and eviction never change a shape, so the scheduler
is recompile-free by construction.
"""

from __future__ import annotations

import enum
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Callable, Deque, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ray_lightning_tpu.serve.kv_cache import (
    BlockAllocator, TRASH_BLOCK, extend_block_coverage, truncate_to,
)

__all__ = ["Request", "RequestState", "Scheduler", "default_buckets",
           "derive_geometry"]

# Deficit-round-robin "no grant yet" marker (None is a real key: the
# base model).
_RR_NEVER = object()


class RequestState(enum.Enum):
    QUEUED = "queued"
    RUNNING = "running"
    FINISHED = "finished"
    EXPIRED = "expired"     # deadline passed while queued
    REJECTED = "rejected"   # admission-queue backpressure


@dataclass
class Request:
    """One generation request and its runtime state."""

    rid: str
    prompt: List[int]
    max_new_tokens: int
    temperature: float = 0.0
    eos_token_id: Optional[int] = None
    # Shape-static top-k truncation for temperature sampling (ridden as
    # an int32 operand value; None/0 = off).
    top_k: Optional[int] = None
    # Speculative-decoding draft count for this request: None = the
    # engine default, 0 = plain target decode, K > 0 = up to K drafted
    # tokens verified per tick (capped per tick by the tokens left).
    spec: Optional[int] = None
    # Multi-tenant LoRA: the adapter (tenant) this request decodes
    # through (None = the shared base model).  The engine resolves the
    # name to its pool slot at submit; the slot id rides the compiled
    # step as the per-slot ``adapter_ids`` operand.
    adapter: Optional[str] = None
    # Seconds from arrival the FIRST token must land by (TTFT SLO at
    # admission; None = no deadline).
    deadline_s: Optional[float] = None
    # Brownout shed class: 0 (default) sheds first when the router's
    # overload ladder reaches its shed rung; >= 1 keeps its seat.
    priority: int = 0
    # Called with (token_index, token_id) as tokens stream out; after a
    # preemption the engine re-emits from index 0 — consumers dedup on
    # the index (greedy regenerates identical tokens).
    on_token: Optional[Callable[[int, int], None]] = None

    # -- runtime (scheduler-owned) ------------------------------------------
    state: RequestState = RequestState.QUEUED
    arrival_t: float = field(default_factory=time.monotonic)
    admitted_t: Optional[float] = None
    first_token_t: Optional[float] = None
    finished_t: Optional[float] = None
    generated: List[int] = field(default_factory=list)
    slot: Optional[int] = None
    preemptions: int = 0
    # Admission ordinal — the preemption victim ordering key.
    _seq_no: int = -1
    # The request's sampling-stream identity (kv_cache.make_slot_keys).
    # None = assigned from the submission ordinal ONCE at submit (never
    # re-assigned on preemption requeue), so a recompute re-decode
    # replays the exact same per-position key stream.  A PRESET value
    # survives submit untouched — the disaggregated router assigns
    # fleet-wide seeds so a failover re-submission to a DIFFERENT
    # replica regenerates the identical stream.
    sample_seed: Optional[int] = None
    # Distributed-tracing context (telemetry/propagate.TraceContext).
    # Set once at submit and NEVER cleared on preemption requeue, so a
    # recompute replay's spans land in the original trace.
    trace: Optional[object] = None
    # The adapter's resolved pool slot (engine-set at submit; 0 = the
    # NULL/base slot).  Stable across preemption requeues — the pool
    # refuses to remove an adapter any queued/active request holds.
    _adapter_slot: int = 0
    # Prompt tokens covered by a prefix-cache claim at the CURRENT
    # admission (0 = no shared prefix).  Re-derived on every admission:
    # a preempted request re-claims on requeue admission, and the cache
    # may have evicted (or grown) its chain in between.
    claimed_tokens: int = 0

    @property
    def prompt_len(self) -> int:
        return len(self.prompt)

    @property
    def done_reason(self) -> Optional[str]:
        if self.state is RequestState.FINISHED:
            return "eos" if (
                self.eos_token_id is not None
                and self.generated
                and self.generated[-1] == self.eos_token_id
            ) else "length"
        if self.state in (RequestState.EXPIRED, RequestState.REJECTED):
            return self.state.value
        return None


def default_buckets(block_size: int, max_prompt_len: int) -> List[int]:
    """Power-of-two block counts: ``block_size * (1, 2, 4, ...)`` up to
    the first bucket covering ``max_prompt_len``.  A handful of prefill
    programs covers every prompt length with <= 2x padding waste."""
    buckets = []
    b = block_size
    while True:
        buckets.append(b)
        if b >= max_prompt_len:
            return buckets
        b *= 2


def derive_geometry(serve_cfg, model_cfg) -> Tuple[int, List[int]]:
    """``(max_model_len, retained prefill buckets)`` for a serve config
    over a model config — THE one derivation rule, shared by
    :class:`~..engine.ServeEngine` and the disaggregated prefill
    workers (``serve/dist/prefill.py``), so a worker and its replicas
    can never disagree on bucket shapes (drift would fail every
    handoff at the replica's geometry check).

    A bucket longer than ``max_model_len`` cannot run (the prefill
    indexes the positional table at ``[0, T)``), so the longest
    RETAINED bucket bounds the admissible prompt length — the bound
    only bites when ``max_model_len`` is not bucket-aligned
    (docs/SERVING.md "Knobs")."""
    max_model_len = serve_cfg.max_model_len or model_cfg.seq_len
    buckets = list(serve_cfg.prefill_buckets or default_buckets(
        serve_cfg.block_size, max(1, max_model_len - 1)
    ))
    buckets = sorted(b for b in buckets if b <= max_model_len)
    if not buckets:
        raise ValueError(
            f"no prefill bucket fits max_model_len {max_model_len} "
            f"(block_size {serve_cfg.block_size} too large? smallest "
            f"bucket is one block)"
        )
    return max_model_len, buckets


class Scheduler:
    """Slot table + admission queue + block accounting.

    The engine drives it: ``poll()`` between decode steps returns what
    changed (admissions to prefill, expiries to report); ``append`` /
    ``finish`` / ``preempt_for_growth`` mutate per-slot state as tokens
    land.
    """

    def __init__(
        self,
        num_slots: int,
        allocator: BlockAllocator,
        block_size: int,
        max_blocks_per_seq: int,
        buckets: Sequence[int],
        max_queue: int = 64,
        max_queue_per_adapter: Optional[int] = None,
    ):
        if num_slots < 1:
            raise ValueError(f"num_slots must be >= 1, got {num_slots}")
        for b in buckets:
            if b % block_size:
                raise ValueError(
                    f"prefill bucket {b} is not a multiple of the "
                    f"block size {block_size}"
                )
        self.num_slots = num_slots
        self.allocator = allocator
        self.block_size = block_size
        self.max_blocks_per_seq = max_blocks_per_seq
        self.buckets = sorted(buckets)
        self.max_queue = max_queue
        # Per-tenant admission-queue bound: one tenant's burst must not
        # consume the whole shared queue (None = shared bound only).
        self.max_queue_per_adapter = max_queue_per_adapter
        self.queue: Deque[Request] = deque()
        self.slots: List[Optional[Request]] = [None] * num_slots
        # Per-slot allocated physical blocks, in logical order.
        self._blocks: List[List[int]] = [[] for _ in range(num_slots)]
        # The compiled step's operands (value-only mutation).
        self.block_tables = np.full(
            (num_slots, max_blocks_per_seq), TRASH_BLOCK, np.int32
        )
        self.seq_lens = np.zeros((num_slots,), np.int32)
        self.temperatures = np.zeros((num_slots,), np.float32)
        self.top_ks = np.zeros((num_slots,), np.int32)
        self.sample_seeds = np.zeros((num_slots,), np.int32)
        # Draft-cache frontier per slot (speculative decoding): the
        # draft pool shares this table's block ids, valid through
        # position draft_lens[slot] - 1.  Trails seq_lens by at most 1
        # (the bonus-token tick), never leads it.
        self.draft_lens = np.zeros((num_slots,), np.int32)
        # Multi-tenant LoRA: each slot's adapter-pool slot id, ridden
        # into the compiled step as the ``adapter_ids`` operand (0 =
        # the NULL/base adapter — inactive slots gather a zero delta).
        self.adapter_slots = np.zeros((num_slots,), np.int32)
        self._admit_counter = 0
        self._submit_counter = 0
        # Prefix-cache / chunked-prefill hooks, engine-wired after
        # construction (all None/off = the pre-cache scheduler,
        # behaviour byte-identical).  ``claim_fn(req)`` returns
        # RETAINED shared-prefix block ids for a request at admission;
        # ``reclaim(n)`` asks the resident cache to evict up to ``n``
        # blocks when the pool runs dry (tried BEFORE preemption — a
        # resident chain is always cheaper to drop than a running
        # request); ``chunk_width`` admits prompts whose uncovered
        # suffix exceeds it with EXACT block coverage instead of a
        # prefill bucket (the suffix runs through the fixed-width chunk
        # program, which needs no bucket-shaped block set).
        self.claim_fn: Optional[Callable[[Request], List[int]]] = None
        self.reclaim: Optional[Callable[[int], int]] = None
        self.chunk_width: Optional[int] = None
        # Fairness state: the adapter key granted the LAST slot —
        # deficit-round-robin with a unit quantum (request costs are
        # uniform at admission: one slot, one bucket) cycles grants
        # across the tenants with queued work starting after this key.
        # The sentinel distinguishes "never granted" from "last grant
        # was the base (None) key".
        self._rr_last: object = _RR_NEVER

    # -- queue side ----------------------------------------------------------
    @property
    def queue_depth(self) -> int:
        return len(self.queue)

    @property
    def active_slots(self) -> int:
        return sum(r is not None for r in self.slots)

    def has_work(self) -> bool:
        return bool(self.queue) or self.active_slots > 0

    def queued_for(self, adapter: Optional[str]) -> int:
        """Queued requests for one adapter key (None = base model)."""
        return sum(1 for r in self.queue if r.adapter == adapter)

    def references_adapter(self, name: str) -> bool:
        """True while any queued or active request decodes through
        ``name`` — the engine's remove-adapter guard (freeing a slot a
        live request still gathers would serve it a neighbour's —
        or stale — delta)."""
        return any(r.adapter == name for r in self.queue) or any(
            r is not None and r.adapter == name for r in self.slots
        )

    def submit(self, req: Request) -> bool:
        """Enqueue, or reject (backpressure) when the shared queue — or
        the request's PER-ADAPTER bound — is full.  Rejection is
        synchronous and typed — the client decides whether to retry,
        never the server.  The per-adapter cap is the multi-tenant
        admission contract: one tenant's burst saturates its own bound
        and starts bouncing while every other tenant keeps its seats.
        """
        if len(self.queue) >= self.max_queue:
            req.state = RequestState.REJECTED
            return False
        if (self.max_queue_per_adapter is not None
                and self.queued_for(req.adapter)
                >= self.max_queue_per_adapter):
            req.state = RequestState.REJECTED
            return False
        req.state = RequestState.QUEUED
        if req.sample_seed is None:
            req.sample_seed = self._submit_counter
        self._submit_counter += 1
        self.queue.append(req)
        return True

    def bucket_for(self, prompt_len: int) -> int:
        for b in self.buckets:
            if b >= prompt_len:
                return b
        raise ValueError(
            f"prompt length {prompt_len} exceeds the largest prefill "
            f"bucket {self.buckets[-1]}"
        )

    # -- between-steps poll --------------------------------------------------
    def poll(
        self, now: Optional[float] = None
    ) -> Tuple[List[Tuple[int, Request, int]], List[Request]]:
        """Expire, then admit.  Returns ``(admissions, expired)`` where
        each admission is ``(slot, request, bucket_len)`` with blocks
        already allocated and the slot row populated — the engine only
        has to run the bucket's prefill program."""
        now = time.monotonic() if now is None else now
        expired: List[Request] = []
        fresh: Deque[Request] = deque()
        while self.queue:
            req = self.queue.popleft()
            # deadline_s is a TTFT-at-admission SLO: once a request has
            # been admitted and streamed (then got preempted back into
            # the queue), its deadline is already MET — expiring it on
            # requeue would throw away the invested latency the
            # front-requeue policy exists to protect.
            if (req.deadline_s is not None
                    and req.preemptions == 0
                    and now - req.arrival_t > req.deadline_s):
                req.state = RequestState.EXPIRED
                req.finished_t = now
                expired.append(req)
            else:
                fresh.append(req)
        self.queue = fresh

        admissions: List[Tuple[int, Request, int]] = []
        while self.queue:
            slot = next(
                (i for i, r in enumerate(self.slots) if r is None), None
            )
            if slot is None:
                break
            pick = self._next_grant_index()
            req = self.queue[pick]
            claimed: List[int] = []
            if self.claim_fn is not None:
                claimed = self.claim_fn(req)
            c_tokens = len(claimed) * self.block_size
            chunked = (self.chunk_width is not None
                       and getattr(req, "_handoff", None) is None
                       and (req.prompt_len - c_tokens > self.chunk_width
                            or req.prompt_len > self.buckets[-1]))
            if claimed or chunked:
                # Claimed and/or chunked admissions take exact coverage
                # (ceil(prompt/Bs) blocks, bucket sentinel 0): the
                # uncovered suffix runs through the engine's fixed-width
                # chunk program, so no bucket-shaped padding blocks are
                # needed — and prompts past the largest bucket admit.
                bucket = 0
                need = (-(-req.prompt_len // self.block_size)
                        - len(claimed))
            else:
                bucket = self.bucket_for(req.prompt_len)
                need = bucket // self.block_size
            ids = self._alloc(need)
            if ids is None:
                if claimed:
                    self.allocator.free(claimed)  # drop the claim refs
                break  # pool dry: wait for evictions, keep grant order
            ids = claimed + ids
            req.claimed_tokens = c_tokens
            del self.queue[pick]
            if not req.preemptions:
                # Only ROTATION grants advance the fairness pointer: a
                # preempted request rides the priority lane, and letting
                # it move _rr_last would skip the tenants between the
                # last rotation grant and its key — one tenant's
                # repeated preemptions would systematically defer the
                # others a full cycle each time.
                self._rr_last = req.adapter
            req.state = RequestState.RUNNING
            req.slot = slot
            req.admitted_t = now
            req.generated = []
            req._seq_no = self._admit_counter
            self._admit_counter += 1
            self.slots[slot] = req
            self._blocks[slot] = ids
            row = self.block_tables[slot]
            row[:] = TRASH_BLOCK
            row[: len(ids)] = ids
            self.seq_lens[slot] = req.prompt_len
            self.temperatures[slot] = req.temperature
            self.top_ks[slot] = req.top_k or 0
            self.sample_seeds[slot] = req.sample_seed
            self.draft_lens[slot] = req.prompt_len
            self.adapter_slots[slot] = req._adapter_slot
            admissions.append((slot, req, bucket))
        return admissions, expired

    def _next_grant_index(self) -> int:
        """Queue index of the next slot grant.

        Priority 1 — preempted requests, in queue order: the
        front-requeue contract (latency already invested is never
        thrown away) outranks fairness.  Priority 2 —
        deficit-round-robin over the adapter keys with queued work
        (unit quantum: every admission costs one slot and one bucket,
        so the deficit counter degenerates to strict rotation), FIFO
        within a key: the grant goes to the first key cyclically AFTER
        the last granted one, so one tenant's burst cannot monopolize
        slot turnover while others queue.  Single-key traffic (the
        whole pre-LoRA world: every request keys to the base model)
        reduces exactly to the old FIFO order.
        """
        for i, r in enumerate(self.queue):
            if r.preemptions:
                return i
        first_idx: Dict[Optional[str], int] = {}
        for i, r in enumerate(self.queue):
            if r.adapter not in first_idx:
                first_idx[r.adapter] = i
        if len(first_idx) == 1:
            return next(iter(first_idx.values()))

        def keypos(k: Optional[str]) -> Tuple[bool, str]:
            # Canonical cyclic order: base (None) first, then names.
            return (k is not None, k or "")

        order = sorted(first_idx, key=keypos)
        if self._rr_last is not _RR_NEVER:
            last = keypos(self._rr_last)
            for k in order:
                if keypos(k) > last:
                    return first_idx[k]
        return first_idx[order[0]]

    # -- per-step slot transitions ------------------------------------------
    def append_token(self, slot: int, token: int,
                     now: Optional[float] = None) -> bool:
        """Record one generated token for ``slot``; returns True when
        the request just finished (eos or length)."""
        now = time.monotonic() if now is None else now
        req = self.slots[slot]
        assert req is not None, f"append_token on empty slot {slot}"
        if req.first_token_t is None:
            req.first_token_t = now
        idx = len(req.generated)
        req.generated.append(token)
        if req.on_token is not None:
            try:
                req.on_token(idx, token)
            except Exception:  # noqa: BLE001 - a raising stream consumer
                # must never take the serve loop down with it
                import logging

                logging.getLogger(__name__).warning(
                    "serve: on_token callback raised for %s", req.rid,
                    exc_info=True,
                )
        done = (
            len(req.generated) >= req.max_new_tokens
            or (req.eos_token_id is not None and token == req.eos_token_id)
        )
        return done

    def needs_block(self, slot: int, upto_pos: Optional[int] = None) -> bool:
        """True when a write at ``upto_pos`` (default: the NEXT decode
        write, ``seq_lens[slot]``) crosses into an unallocated block.
        Speculative ticks pass ``seq_lens + width`` — the last position
        the verify window scatters."""
        pos = int(self.seq_lens[slot]) if upto_pos is None else int(upto_pos)
        return pos // self.block_size >= len(self._blocks[slot])

    def _alloc(self, n: int) -> Optional[List[int]]:
        """:meth:`BlockAllocator.alloc` with one reclaim retry: when the
        pool is dry and a prefix cache is wired, ask it to evict enough
        resident (idle) blocks first — dropping a cached chain is
        always cheaper than preempting a running request."""
        ids = self.allocator.alloc(n)
        if ids is None and self.reclaim is not None:
            self.reclaim(n - self.allocator.free_blocks)
            ids = self.allocator.alloc(n)
        return ids

    def grow(self, slot: int) -> bool:
        """Allocate the next block for ``slot``.  False = pool dry."""
        if len(self._blocks[slot]) >= self.max_blocks_per_seq:
            raise RuntimeError(
                f"slot {slot} exceeded max_blocks_per_seq "
                f"{self.max_blocks_per_seq} — engine admission bound bug"
            )
        ids = self._alloc(1)
        if ids is None:
            return False
        self._blocks[slot].extend(ids)
        self.block_tables[slot, len(self._blocks[slot]) - 1] = ids[0]
        return True

    def append_tokens(self, slot: int, tokens: Sequence[int],
                      now: Optional[float] = None) -> Tuple[int, bool]:
        """Record a TICK's worth of generated tokens for ``slot`` —
        the variable-width emission of a speculative verify (accepted
        prefix + corrected/bonus token).  Stops early at eos or the
        request's ``max_new_tokens``; returns ``(n_emitted, done)``.
        ``on_token`` fires per token with its stream index, exactly as
        the one-token path does, so client-side index dedup is
        width-agnostic."""
        req = self.slots[slot]
        assert req is not None, f"append_tokens on empty slot {slot}"
        emitted = 0
        for tok in tokens:
            if len(req.generated) >= req.max_new_tokens:
                return emitted, True
            done = self.append_token(slot, int(tok), now=now)
            emitted += 1
            if done:
                return emitted, True
        return emitted, len(req.generated) >= req.max_new_tokens

    def truncate_slot_to(self, slot: int, n_tokens: int) -> int:
        """Roll the slot's cache coverage back to ``n_tokens`` positions
        (the post-accept frontier of a speculative tick): ``seq_lens``
        shrinks to the value, blocks past the covering prefix return to
        the pool, their table entries go back to trash.  Returns blocks
        freed."""
        freed = truncate_to(
            self.allocator, self._blocks[slot], self.block_tables[slot],
            n_tokens, self.block_size,
        )
        self.seq_lens[slot] = n_tokens
        self.draft_lens[slot] = min(int(self.draft_lens[slot]), n_tokens)
        return freed

    def cover(self, slot: int, upto_pos: int) -> bool:
        """Multi-block :meth:`grow`: allocate until position
        ``upto_pos`` is writable (all-or-nothing).  False = pool dry."""
        if upto_pos // self.block_size >= self.max_blocks_per_seq:
            raise RuntimeError(
                f"slot {slot} coverage request past max_blocks_per_seq "
                f"{self.max_blocks_per_seq} — engine width-cap bug"
            )
        ok = extend_block_coverage(
            self.allocator, self._blocks[slot], self.block_tables[slot],
            upto_pos, self.block_size,
        )
        if not ok and self.reclaim is not None:
            need = (upto_pos // self.block_size) + 1 \
                - len(self._blocks[slot])
            self.reclaim(need - self.allocator.free_blocks)
            ok = extend_block_coverage(
                self.allocator, self._blocks[slot],
                self.block_tables[slot], upto_pos, self.block_size,
            )
        return ok

    def cow_slot(self, slot: int, upto_block: int
                 ) -> Optional[Tuple[List[int], List[int]]]:
        """Copy-on-write bookkeeping for ``slot``: every SHARED block
        (refcount > 1) among its first ``upto_block`` blocks is swapped
        for a freshly allocated private one — table entries and the
        slot's block list point at the copies, references on the
        originals are dropped.  Returns ``(src_ids, dst_ids)`` for the
        engine's ``copy_blocks`` program (empty lists = nothing
        shared), or ``None`` when the pool cannot cover the copies
        (nothing mutated: all-or-nothing, like every alloc here).

        The admission claim cap keeps nominal serving from ever needing
        this (writes land strictly past the shared frontier) — it is
        the escape hatch for any path that must WRITE below it.
        """
        blocks = self._blocks[slot]
        shared = [i for i in range(min(upto_block, len(blocks)))
                  if self.allocator.is_shared(blocks[i])]
        if not shared:
            return [], []
        fresh = self._alloc(len(shared))
        if fresh is None:
            return None
        src = [blocks[i] for i in shared]
        for i, dst in zip(shared, fresh):
            blocks[i] = dst
            self.block_tables[slot, i] = dst
        self.allocator.free(src)
        return src, fresh

    def preempt_youngest(self, protect: Optional[int] = None
                         ) -> Optional[Request]:
        """Evict the most recently admitted active request (recompute
        preemption): free its blocks, requeue it at the FRONT.  Returns
        the victim, or None when no slot (other than ``protect``) is
        evictable."""
        victims = [
            (req._seq_no, slot)
            for slot, req in enumerate(self.slots)
            if req is not None and slot != protect
        ]
        if not victims:
            return None
        _, slot = max(victims)
        req = self.slots[slot]
        self._release(slot)
        req.state = RequestState.QUEUED
        req.slot = None
        req.preemptions += 1
        req.generated = []
        req.first_token_t = None
        self.queue.appendleft(req)
        return req

    def adopt(self, req: Request, ids: List[int], seq_len: int,
              now: Optional[float] = None) -> Optional[int]:
        """Place an ALREADY-RUNNING request (a live-KV migration
        import) directly into a free slot: ``ids`` are blocks the
        caller allocated from THIS scheduler's pool and scattered the
        imported KV into; ``seq_len`` is the KV frontier those blocks
        cover.  Mirrors :meth:`poll`'s slot population exactly — minus
        the queue/claim bookkeeping the request already paid on its
        draining home replica.  Returns the slot, or None when no slot
        is free (the caller falls back to recompute resubmission)."""
        now = time.monotonic() if now is None else now
        slot = next(
            (i for i, r in enumerate(self.slots) if r is None), None
        )
        if slot is None:
            return None
        req.state = RequestState.RUNNING
        req.slot = slot
        if req.admitted_t is None:
            req.admitted_t = now
        if req.first_token_t is None and req.generated:
            req.first_token_t = now
        req._seq_no = self._admit_counter
        self._admit_counter += 1
        self.slots[slot] = req
        self._blocks[slot] = list(ids)
        row = self.block_tables[slot]
        row[:] = TRASH_BLOCK
        row[: len(ids)] = ids
        self.seq_lens[slot] = seq_len
        self.temperatures[slot] = req.temperature
        self.top_ks[slot] = req.top_k or 0
        self.sample_seeds[slot] = req.sample_seed
        self.draft_lens[slot] = seq_len
        self.adapter_slots[slot] = req._adapter_slot
        return slot

    def cancel(self, rid: str) -> Optional[Request]:
        """Drop ``rid`` wherever it is — queued (removed) or active
        (slot released, blocks freed).  Returns the request (terminal
        status is the CALLER's call — the hedge cancel path reports
        ``cancelled``, never a client-visible state), or None when the
        rid is unknown here."""
        for i, r in enumerate(self.queue):
            if r.rid == rid:
                del self.queue[i]
                r.slot = None
                return r
        for slot, r in enumerate(self.slots):
            if r is not None and r.rid == rid:
                self._release(slot)
                r.slot = None
                return r
        return None

    def finish(self, slot: int, now: Optional[float] = None) -> Request:
        now = time.monotonic() if now is None else now
        req = self.slots[slot]
        assert req is not None, f"finish on empty slot {slot}"
        req.state = RequestState.FINISHED
        req.finished_t = now
        req.slot = None
        self._release(slot)
        return req

    def _release(self, slot: int) -> None:
        self.allocator.free(self._blocks[slot])
        self._blocks[slot] = []
        self.slots[slot] = None
        self.block_tables[slot, :] = TRASH_BLOCK
        self.seq_lens[slot] = 0
        self.temperatures[slot] = 0.0
        self.top_ks[slot] = 0
        self.sample_seeds[slot] = 0
        self.draft_lens[slot] = 0
        self.adapter_slots[slot] = 0

    # -- introspection -------------------------------------------------------
    def snapshot(self) -> dict:
        return {
            "queue_depth": self.queue_depth,
            "slots_active": self.active_slots,
            "num_slots": self.num_slots,
            "blocks_free": self.allocator.free_blocks,
            "blocks_live": self.allocator.live_blocks,
            "num_blocks": self.allocator.num_blocks,
        }
