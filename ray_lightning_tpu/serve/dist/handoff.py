"""KV-handoff wire format: prefill worker → decode replica frames.

The disaggregated serving plane's tensor frames reuse the queue-plane
conventions the MPMD transfer lane established (``mpmd/transfer.py``):
every frame is a small typed dict whose bulk payload rides EITHER
inline (``data`` bytes, chunk-sent by ``cluster/queue.py`` past 8MB —
the cross-host DCN form) OR as a tmpfs segment path (``shm`` — the
same-host zero-copy form, ``SegmentStore`` prefix ``rlt-kv``).
Consumers resolve either through ``transfer.resolve_payload`` (read
once, unlink once).

Frame families (envelopes schema-pinned in ``telemetry/schema.py``;
the tensor payload itself is an ``encode_tree`` blob, deliberately
outside the schema like MPMD activation bytes):

* ``serve_prefill_dispatch`` — router → prefill worker: the full
  client request plus the target decode replica's inbox address;
* ``serve_kv_handoff`` — prefill worker → decode replica: the request
  plus its exported per-layer KV blocks and final-position logits
  (``validate_serve_kv_handoff``);
* ``serve_replica_hello`` / ``serve_replica_beat`` — member → router:
  registration (inbox address + capabilities) and the periodic
  liveness/occupancy/completion feed the router's failover and
  placement decisions run on.

Everything here is jax-free given payload bytes, so the schema gate
(``tools/check_telemetry_schema.py``) drives the REAL producers.
"""

from __future__ import annotations

import logging
import queue as _pyqueue
import threading
import time
from typing import Any, Callable, Dict, Optional, Sequence, Tuple

__all__ = [
    "KV_SEGMENT_PREFIX",
    "CachedSender",
    "MemberOutbox",
    "request_fields",
    "make_dispatch_item",
    "make_handoff_item",
    "make_adapter_load_item",
    "make_hello_item",
    "make_beat_item",
    "make_migration_item",
    "make_cancel_item",
    "encode_kv_payload",
    "decode_kv_payload",
]

log = logging.getLogger(__name__)


class CachedSender:
    """One persistent ``QueueHandle`` per destination address, evicted
    on send failure so the next attempt reconnects fresh — the send
    helper the router (dispatch/replies) and the prefill workers
    (handoffs) share, so dead-peer handling can only evolve in ONE
    place."""

    def __init__(self):
        self._handles: Dict[Tuple[str, int], Any] = {}

    def put(self, addr, item: Dict[str, Any]) -> None:
        from ray_lightning_tpu.cluster.queue import QueueHandle

        addr = (addr[0], int(addr[1]))
        handle = self._handles.get(addr)
        if handle is None:
            handle = QueueHandle(addr[0], addr[1])
            self._handles[addr] = handle
        try:
            handle.put(item)
        except BaseException:
            self._handles.pop(addr, None)
            raise

    def close(self) -> None:
        for handle in self._handles.values():
            handle.close()
        self._handles.clear()


class MemberOutbox:
    """Per-destination send thread with a bounded queue — the router's
    control plane must never block inside a TCP connect to a wedged
    member (the PR-12 documented limit: a blackholed host held the
    router lock for a full ~60s connect timeout, freezing every client
    of the fleet).  Sends enqueue in O(1); the outbox thread pays the
    network; a send failure (or a FULL queue — a member that stopped
    draining for ``maxsize`` frames is wedged) reports through
    ``on_error`` exactly once per incident, which the router routes
    into its existing death/failover path.

    ``put`` takes an optional ``on_sent(enqueue_ts)`` callback fired
    after the wire write completes — the tracer's ``placement`` span is
    recorded there, so it measures REAL dispatch latency (queue wait +
    connect + serialize + send), not the lock convoy the synchronous
    sender measured."""

    def __init__(self, addr: Tuple[str, int],
                 on_error: Optional[Callable[[BaseException], None]] = None,
                 maxsize: int = 256):
        self.addr = (addr[0], int(addr[1]))
        self._on_error = on_error
        self._q: _pyqueue.Queue = _pyqueue.Queue(maxsize=maxsize)
        self._sender = CachedSender()
        self._closed = threading.Event()
        self._dead = False
        self._sending = False
        # Idle-reap bookkeeping (the router closes outboxes that have
        # not sent for a while — clients come and go; their reply
        # lanes must not accumulate threads forever).
        self.last_used = time.monotonic()
        self._thread = threading.Thread(
            target=self._run, daemon=True,
            name=f"rlt-outbox-{self.addr[0]}:{self.addr[1]}",
        )
        self._thread.start()

    def put(self, item: Dict[str, Any],
            on_sent: Optional[Callable[[float], None]] = None) -> None:
        """Enqueue one frame.  Raises ``ConnectionError`` when the
        outbox is already dead or its queue is full — the caller's
        existing (OSError, ConnectionError) handling then runs the same
        death path a synchronous send failure did."""
        if self._dead or self._closed.is_set():
            raise ConnectionError(f"outbox to {self.addr} is closed")
        self.last_used = time.monotonic()
        try:
            self._q.put_nowait((item, on_sent, time.monotonic()))
        except _pyqueue.Full:
            raise ConnectionError(
                f"outbox to {self.addr} is full ({self._q.maxsize} "
                f"frames undrained — member wedged?)"
            ) from None

    def _run(self) -> None:
        while not self._closed.is_set():
            try:
                item, on_sent, t_enq = self._q.get(timeout=0.2)
            except _pyqueue.Empty:
                continue
            self._sending = True
            try:
                try:
                    self._sender.put(self.addr, item)
                except Exception as e:  # noqa: BLE001 - any send
                    # failure marks the member; the router decides
                    # what it means
                    self._dead = True
                    if self._on_error is not None:
                        try:
                            self._on_error(e)
                        except Exception:  # noqa: BLE001 - observer bug
                            log.warning("outbox on_error raised",
                                        exc_info=True)
                    return
                if on_sent is not None:
                    try:
                        on_sent(t_enq)
                    except Exception:  # noqa: BLE001 - tracing is
                        # best-effort; a raising observer must not
                        # kill the lane
                        log.warning("outbox on_sent raised",
                                    exc_info=True)
            finally:
                self._sending = False

    @property
    def depth(self) -> int:
        return self._q.qsize()

    @property
    def pending(self) -> int:
        """Frames enqueued or mid-send (the flush condition)."""
        return self._q.qsize() + (1 if self._sending else 0)

    def close(self, drain_s: float = 2.0) -> None:
        """Stop the thread, best-effort draining queued frames first
        (a planned teardown should not drop the last replies).  Safe to
        call from the outbox thread itself (the error-callback path),
        and NEVER joins a dead box's thread — that thread may be
        blocked on the caller's own lock inside on_error, and it exits
        on its own the moment the callback returns (joining it from
        under the router lock would burn the full join timeout as a
        control-plane stall)."""
        deadline = time.monotonic() + drain_s
        while (not self._dead and self._q.qsize()
               and time.monotonic() < deadline):
            time.sleep(0.01)
        self._closed.set()
        if (not self._dead
                and threading.current_thread() is not self._thread):
            self._thread.join(timeout=5)
        self._sender.close()


# Serve-plane handoff segments get their own family so teardown sweeps
# (engine close, router failover, actor kill) can collect dead prefill
# handoffs without touching a co-resident MPMD fit's rlt-seg frames.
KV_SEGMENT_PREFIX = "rlt-kv"


def request_fields(
    rid: str,
    prompt: Sequence[int],
    max_new_tokens: int,
    *,
    reply: Sequence,
    sample_seed: int,
    temperature: float = 0.0,
    eos_token_id: Optional[int] = None,
    top_k: Optional[int] = None,
    spec: Optional[int] = None,
    adapter: Optional[str] = None,
    deadline_s: Optional[float] = None,
    priority: int = 0,
    trace=None,
) -> Dict[str, Any]:
    """The canonical request dict that rides inside dispatch/handoff
    frames (a ``serve_request`` body with the router's fleet-wide
    ``sample_seed`` — and, on tracing routers, the request's
    ``TraceContext`` — attached).  ``priority`` is the brownout shed
    class: 0 (default) sheds first under overload, >= 1 survives."""
    item = {
        "type": "serve_request",
        "rid": str(rid),
        "prompt": [int(t) for t in prompt],
        "max_new_tokens": int(max_new_tokens),
        "temperature": float(temperature),
        "eos_token_id": eos_token_id,
        "top_k": None if top_k is None else int(top_k),
        "spec": None if spec is None else int(spec),
        "adapter": None if adapter is None else str(adapter),
        "deadline_s": deadline_s,
        "sample_seed": int(sample_seed),
        "priority": int(priority),
        "reply": list(reply),
    }
    if trace is not None:
        from ray_lightning_tpu.telemetry.propagate import inject

        inject(item, trace)
    return item


def make_dispatch_item(req: Dict[str, Any], kv_to: Tuple[str, int],
                       same_host: bool = False) -> Dict[str, Any]:
    """Router → prefill worker: run ``req``'s prompt and hand the KV
    off to the decode replica inbox at ``kv_to``.  ``same_host`` gates
    the tmpfs-segment payload form — the router computes it from the
    worker's and replica's advertised hosts; the default is the
    conservative inline-bytes form, which works anywhere (a tmpfs path
    shipped across hosts would fail every large handoff)."""
    return {
        "type": "serve_prefill_dispatch",
        "rid": req["rid"],
        "req": dict(req),
        "kv_to": [kv_to[0], int(kv_to[1])],
        "same_host": bool(same_host),
    }


def make_handoff_item(
    req: Dict[str, Any],
    bucket: int,
    *,
    data: Optional[bytes] = None,
    shm: Optional[str] = None,
    trace=None,
) -> Dict[str, Any]:
    """Prefill worker → decode replica: the prefilled request.  Exactly
    one of ``data``/``shm`` carries the ``encode_kv_payload`` blob.
    ``trace`` (the worker's prefill-span context) stamps the envelope
    with the wall-clock send time the replica books
    ``handoff_transfer`` from."""
    if (data is None) == (shm is None):
        raise ValueError("exactly one of data/shm payload required")
    item: Dict[str, Any] = {
        "type": "serve_kv_handoff",
        "rid": req["rid"],
        "bucket": int(bucket),
        "prompt_len": len(req["prompt"]),
        "req": dict(req),
    }
    if data is not None:
        item["data"] = data
    else:
        item["shm"] = shm
    if trace is not None:
        from ray_lightning_tpu.telemetry.propagate import inject

        inject(item, trace)
    return item


def make_adapter_load_item(
    name: str,
    rank: int,
    *,
    data: Optional[bytes] = None,
    shm: Optional[str] = None,
) -> Dict[str, Any]:
    """Router/operator → member (decode replica OR prefill worker):
    hot-load one tenant's LoRA adapter into the member's pool.
    Exactly one of ``data``/``shm`` carries the
    ``serve/lora.py::encode_adapter`` blob — the same dual transport
    as KV handoffs (inline bytes chunk-sent past 8MB cross-host, a
    tmpfs segment path same-host)."""
    if (data is None) == (shm is None):
        raise ValueError("exactly one of data/shm payload required")
    item: Dict[str, Any] = {
        "type": "serve_adapter_load",
        "name": str(name),
        "rank": int(rank),
    }
    if data is not None:
        item["data"] = data
    else:
        item["shm"] = shm
    return item


def make_hello_item(role: str, member_id: str, inbox: Tuple[str, int],
                    **caps: Any) -> Dict[str, Any]:
    """Member registration: the router learns the inbox address and the
    capabilities placement runs on (``num_slots``, ``max_queue``,
    ``spec_k``, ``max_prompt_len``)."""
    return {
        "type": "serve_replica_hello",
        "role": role,
        "id": str(member_id),
        "inbox": [inbox[0], int(inbox[1])],
        **caps,
    }


def make_beat_item(
    role: str,
    member_id: str,
    *,
    done: Sequence[Tuple[str, str]] = (),
    failed: Sequence[Tuple[str, str]] = (),
    snapshot: Optional[Dict[str, Any]] = None,
    recompiles: Optional[int] = None,
    adapters: Optional[Sequence[str]] = None,
    closing: bool = False,
    migrating: Optional[Sequence[str]] = None,
) -> Dict[str, Any]:
    """Periodic member liveness + completion feed.  ``done`` carries
    terminal ``(rid, status)`` pairs since the last beat (the router's
    in-flight pruning signal); ``failed`` carries ``(rid, error)``
    pairs a member could not serve (the router re-routes them);
    ``adapters`` advertises the member's loaded LoRA tenants
    (adapter-aware placement routes a tenant's requests to members
    already holding its factors); ``migrating`` claims a rid set whose
    live-KV export is in flight — the router suppresses beat-loss
    failover for the member until the claim resolves or expires."""
    item: Dict[str, Any] = {
        "type": "serve_replica_beat",
        "role": role,
        "id": str(member_id),
        "ts": time.time(),
        "done": [[str(r), str(s)] for r, s in done],
        "failed": [[str(r), str(e)] for r, e in failed],
    }
    if snapshot is not None:
        item["snapshot"] = snapshot
    if recompiles is not None:
        item["recompiles"] = int(recompiles)
    if adapters is not None:
        item["adapters"] = [str(a) for a in adapters]
    if closing:
        item["closing"] = True
    if migrating is not None:
        item["migrating"] = [str(r) for r in migrating]
    return item


def make_migration_item(
    req: Dict[str, Any],
    *,
    generated: Sequence[int],
    cur_token: int,
    seq_len: int,
    data: bytes,
    trace=None,
) -> Dict[str, Any]:
    """Draining replica → router → survivor replica: one resident
    sequence's live state.  ``req`` is the canonical ``request_fields``
    dict (reply address + fleet-wide ``sample_seed`` included — the
    position-keyed sampler makes the continued stream bitwise-identical
    on any survivor slot).  ``generated`` are the tokens already
    emitted, ``cur_token`` the last sampled token (the next decode
    tick's input), ``seq_len`` the KV positions written
    (``prompt_len + len(generated) - 1`` — the final sampled token's KV
    is never written until its own tick).  ``data`` is the
    ``encode_tree({"kv": ...})`` export of the sequence's blocks;
    migration frames ride the ordered beat lane, so the payload is
    always inline bytes (never a tmpfs segment that would dangle if the
    draining host dies)."""
    item: Dict[str, Any] = {
        "type": "serve_migration",
        "rid": str(req["rid"]),
        "req": dict(req),
        "generated": [int(t) for t in generated],
        "cur_token": int(cur_token),
        "seq_len": int(seq_len),
        "data": data,
    }
    if trace is not None:
        from ray_lightning_tpu.telemetry.propagate import inject

        inject(item, trace)
    return item


def make_cancel_item(rid: str) -> Dict[str, Any]:
    """Router → decode replica: drop ``rid`` wherever it is (queued or
    mid-decode), silently — the first-winner cancel of a hedged pair.
    The replica reports it terminal with status ``cancelled`` on its
    done feed (never to the client — the winner already replied)."""
    return {"type": "serve_cancel", "rid": str(rid)}


def encode_kv_payload(kv: Dict[str, Any], logits: Any) -> bytes:
    """Serialize a prefill's exported blocks + final-position logits
    (the handoff frame's bulk payload)."""
    from ray_lightning_tpu.mpmd.transfer import encode_tree

    return encode_tree({"kv": kv, "logits": logits})


def decode_kv_payload(item: Dict[str, Any]) -> Dict[str, Any]:
    """Inverse of :func:`encode_kv_payload` over a handoff frame
    (resolves data/shm; shm segments are read once and unlinked)."""
    from ray_lightning_tpu.mpmd.transfer import decode_tree, resolve_payload

    return decode_tree(resolve_payload(item))
