"""Decode replicas + fleet construction for disaggregated serving.

A decode replica is ONE :class:`~ray_lightning_tpu.serve.engine.
ServeEngine` — its own mesh/params/pool — plus the fleet plumbing: a
hello that registers its inbox and capabilities with the router, and a
periodic beat carrying its live ``ServeStats`` snapshot, its terminal
``(rid, status)`` feed (the router's in-flight pruning signal), and
its process's compile-event counter (the bench pins ZERO steady-state
recompiles per replica from exactly this field).

Two deployment shapes over the SAME runner code:

* **in-process** (:class:`InprocReplica` / :class:`InprocPrefill`) —
  engines on driver threads, beats over real TCP loopback.  The cheap
  shape for tests and the example; ``kill(hard=True)`` simulates
  abrupt death (beats stop, inbox refuses, no cleanup) for failover
  drills;
* **actor** (:class:`ActorReplica` / :class:`ActorPrefill`) — one
  :class:`~ray_lightning_tpu.cluster.actor.ProcessActor` per member,
  each owning its own devices (the TPU shape; the CPU container proves
  the dataflow with 1-device actors).  Graceful stop rides the
  existing control lane (``request_drain`` → the runner loop drains,
  stops its engine, sweeps); chaos kills ride SIGKILL.

``launch_inproc_fleet`` / ``launch_actor_fleet`` wire N replicas + M
prefill workers + a started :class:`~.router.Router` into a
:class:`ServeFleet` with one ``close()``.
"""

from __future__ import annotations

import os
import threading
import time
import uuid
from typing import Any, Dict, List, Optional, Tuple

from ray_lightning_tpu.fault.inject import (
    FaultBlackhole, fire as _fault_fire, set_member,
)
from ray_lightning_tpu.serve.dist.handoff import (
    make_beat_item, make_hello_item, make_migration_item,
)
from ray_lightning_tpu.serve.dist.router import RestartGovernor, Router

__all__ = [
    "DecodeReplicaRunner",
    "InprocReplica",
    "InprocPrefill",
    "ActorReplica",
    "ActorPrefill",
    "ServeFleet",
    "launch_inproc_fleet",
    "launch_actor_fleet",
    "run_decode_replica",
    "run_prefill_worker",
]


class DecodeReplicaRunner:
    """The replica-side loop around one engine: hello, then beats until
    stopped.  The engine's serve thread does the actual work."""

    def __init__(self, replica_id: str, engine, beat_handle,
                 beat_s: float = 0.25):
        self.replica_id = replica_id
        self.engine = engine
        self._beat_handle = beat_handle
        self.beat_s = beat_s
        self.suppress_final = False  # hard-kill simulation: no last beat
        self._last = 0.0
        # Fleet identity for the fault grammar: the engine's serve
        # thread declares itself on start (thread-local member context).
        engine.fault_member = ("decode", replica_id)
        # Torn/vanished handoff payloads become beat-reported retryable
        # failures (the router re-routes the prefill) instead of
        # terminal invalid replies — replica mode only.
        engine.report_handoff_failures = True

    def hello(self) -> None:
        engine = self.engine
        handle = engine.queue_handle()
        self._beat_handle.put(make_hello_item(
            "decode", self.replica_id, (handle.host, handle.port),
            num_slots=engine.config.num_slots,
            max_queue=engine.config.max_queue,
            spec_k=engine.spec_k,
            max_prompt_len=engine.max_prompt_len,
            max_model_len=engine.max_model_len,
            block_size=engine.config.block_size,
            max_adapters=engine.config.max_adapters,
        ))

    def publish_beat(self, closing: bool = False,
                     migrating: Optional[List[str]] = None) -> None:
        from ray_lightning_tpu.telemetry import compile_event_count

        # Fire BEFORE draining the feeds: a blackholed beat must lose
        # nothing — the next beat carries the same completions, exactly
        # as a real dropped datagram would play out.
        _fault_fire("beat")
        engine = self.engine
        self._beat_handle.put(make_beat_item(
            "decode", self.replica_id,
            done=engine.drain_done(),
            failed=engine.drain_failed(),
            snapshot=engine.snapshot(),
            recompiles=compile_event_count(),
            adapters=(engine.adapter_names()
                      if engine.adapters is not None else None),
            closing=closing, migrating=migrating,
        ))

    def run(self, stop=None) -> None:
        """Beat until ``stop()`` goes true, then stop the engine (which
        sweeps stale ``rlt-kv`` segments) and publish the final feed —
        completions that landed between the last beat and the stop must
        still reach the router.  On a PLANNED drain (stop requested, no
        hard kill) the resident sequences are first live-migrated to
        router-chosen survivors (``RLT_MIGRATE_ON_DRAIN=0`` disables;
        abrupt death keeps the recompute failover path)."""
        set_member("decode", self.replica_id)
        self.hello()
        self.engine.start()
        try:
            while not (stop() if stop is not None else False):
                time.sleep(min(self.beat_s, 0.05))
                self._maybe_beat()
        finally:
            if not self.suppress_final and \
                    os.environ.get("RLT_MIGRATE_ON_DRAIN", "1") != "0":
                try:
                    self._migrate_out()
                except (OSError, ConnectionError, FaultBlackhole):
                    pass  # router gone/partitioned: recompute failover
            self.engine.stop()
            if not self.suppress_final:
                try:
                    self.publish_beat(closing=True)
                except (OSError, ConnectionError, FaultBlackhole):
                    pass  # router already gone

    def _migrate_out(self) -> bool:
        """Planned-drain live migration: quiesce the serve loop, claim
        the resident rid set on the beat lane (the router suppresses
        beat-loss failover for a claimed set), then ship each resident
        sequence's KV + position as ``serve_migration`` frames.  The
        frames ride the SAME ordered connection as the beats, so every
        one is processed before the closing beat that follows."""
        engine = self.engine
        engine.halt_loop()
        sched = engine.scheduler
        rids = [
            r.rid for slot, r in enumerate(sched.slots)
            if r is not None and slot not in engine._chunk_jobs
            and r.generated
        ]
        if not rids:
            return False
        # The claim beat goes FIRST — it refreshes last_beat AND
        # registers the claim, so a multi-second export on a loaded box
        # cannot race the router's death path into double-placement.
        self.publish_beat(migrating=rids)
        from ray_lightning_tpu.mpmd.transfer import encode_tree

        sent = 0
        for entry in engine.export_resident():
            rid = str(entry["req"]["rid"])
            try:
                _fault_fire("handoff_send", rid=rid)
            except FaultBlackhole:
                continue  # injected partition: this frame is lost —
                # the claim expires and recompute failover covers it
            item = make_migration_item(
                entry["req"], generated=entry["generated"],
                cur_token=entry["cur_token"],
                seq_len=entry["seq_len"],
                data=encode_tree({"kv": entry["kv"]}),
            )
            self._beat_handle.put(item)
            sent += 1
        return sent > 0

    def _maybe_beat(self) -> None:
        now = time.monotonic()
        if now - self._last < self.beat_s:
            return
        self._last = now
        try:
            self.publish_beat()
        except (OSError, ConnectionError, FaultBlackhole):
            pass  # router restarting/gone (or injected partition);
            # keep serving — the feeds drain on the next beat


# ---------------------------------------------------------------------------
# Actor entry points (module-level so cloudpickle ships them by reference)
# ---------------------------------------------------------------------------

def run_decode_replica(replica_id: str, module, params,
                       cfg_kwargs: Dict[str, Any],
                       beat_addr: Tuple[str, int],
                       beat_s: float = 0.25,
                       draft_module=None, draft_params=None,
                       trace_dir: Optional[str] = None) -> dict:
    """Actor main for one decode replica: serve until the driver sends
    a drain over the control lane (``ProcessActor.request_drain``) or
    kills the process.  Returns the final SLO snapshot."""
    from ray_lightning_tpu.cluster.queue import QueueHandle
    from ray_lightning_tpu.fault import drain
    from ray_lightning_tpu.serve.engine import ServeConfig, ServeEngine

    engine = ServeEngine(
        module, params, ServeConfig(**cfg_kwargs),
        draft_module=draft_module, draft_params=draft_params,
        trace_dir=trace_dir, trace_name=replica_id,
    )
    runner = DecodeReplicaRunner(
        replica_id, engine, QueueHandle(*beat_addr), beat_s=beat_s
    )
    runner.run(stop=drain.drain_requested)
    return engine.snapshot()


def run_prefill_worker(worker_id: str, module, params, serve_cfg,
                       beat_addr: Tuple[str, int],
                       beat_s: float = 0.25,
                       trace_dir: Optional[str] = None) -> int:
    """Actor main for one prefill worker.  Returns prompts prefilled."""
    from ray_lightning_tpu.cluster.queue import QueueHandle
    from ray_lightning_tpu.fault import drain
    from ray_lightning_tpu.serve.dist.prefill import PrefillRunner

    runner = PrefillRunner(
        worker_id, module, params, serve_cfg,
        QueueHandle(*beat_addr), beat_s=beat_s, trace_dir=trace_dir,
    )
    runner.run(stop=drain.drain_requested)
    return runner.prefills


# ---------------------------------------------------------------------------
# Driver-side member handles (the Router's liveness/teardown interface)
# ---------------------------------------------------------------------------

class InprocReplica:
    """A decode replica on driver threads (engine serve thread + beat
    thread).  ``kill(hard=True)`` simulates abrupt death for failover
    drills: the serve loop halts mid-stream, the inbox refuses new
    frames, beats stop — everything a SIGKILL'd actor looks like from
    the router's side, without the process."""

    role = "decode"

    def __init__(self, replica_id: str, engine, beat_handle,
                 beat_s: float = 0.2):
        self.id = replica_id
        self.engine = engine
        self._runner = DecodeReplicaRunner(
            replica_id, engine, beat_handle, beat_s=beat_s
        )
        self._stop = threading.Event()
        self._dead = False
        self._thread = threading.Thread(
            target=self._runner.run, args=(self._stop.is_set,),
            name=f"rlt-serve-{replica_id}", daemon=True,
        )
        self._thread.start()

    def is_alive(self) -> bool:
        return not self._dead and self._thread.is_alive()

    def kill(self, hard: bool = False) -> None:
        if self._dead:
            return
        self._runner.suppress_final = hard
        if hard:
            # Abrupt death: halt the serve loop wherever it is and make
            # the inbox refuse (a dead process's port would).
            self._dead = True
            self.engine._stop.set()
            if self.engine._inbox is not None:
                self.engine._inbox.shutdown()
            self._stop.set()
        else:
            # Planned drain: the handle must read ALIVE until the
            # runner's teardown (live migration + closing beat) is
            # done — marking it dead first would race the router's
            # liveness sweep into a spurious failover mid-drain.
            self._stop.set()
            self._thread.join(timeout=30)
            self._dead = True


class InprocPrefill:
    """A prefill worker on a driver thread."""

    role = "prefill"

    def __init__(self, worker_id: str, module, params, serve_cfg,
                 beat_handle, beat_s: float = 0.2,
                 trace_dir: Optional[str] = None):
        from ray_lightning_tpu.serve.dist.prefill import PrefillRunner

        self.id = worker_id
        self.runner = PrefillRunner(
            worker_id, module, params, serve_cfg, beat_handle,
            beat_s=beat_s, trace_dir=trace_dir,
        )
        self._stop = threading.Event()
        self._dead = False
        self._thread = threading.Thread(
            target=self.runner.run, args=(self._stop.is_set,),
            name=f"rlt-serve-{worker_id}", daemon=True,
        )
        self._thread.start()

    def is_alive(self) -> bool:
        return not self._dead and self._thread.is_alive()

    def kill(self, hard: bool = False) -> None:
        if self._dead:
            return
        self._dead = True
        self.runner.suppress_final = hard
        if hard:
            self.runner._inbox.shutdown()
        self._stop.set()
        if not hard:
            self._thread.join(timeout=30)


class _ActorMember:
    """Shared ProcessActor plumbing for actor-backed members."""

    def __init__(self, member_id: str, name_prefix: str):
        from ray_lightning_tpu.cluster.actor import ProcessActor

        self.id = member_id
        self.actor = ProcessActor(name=f"{name_prefix}-{member_id}")
        self._fut = None

    def is_alive(self) -> bool:
        return self.actor.is_alive()

    def kill(self, hard: bool = False) -> None:
        if hard and self.actor._proc.poll() is None:
            # Chaos: SIGKILL, no grace — the failure the failover path
            # exists for.  actor.kill() below reaps and sweeps.
            self.actor._proc.kill()
        elif not hard and self.actor.is_alive():
            try:
                # Graceful: the runner loop polls the drain flag, stops
                # its engine (segment sweep included) and returns.
                self.actor.request_drain(wait=False)
                if self._fut is not None:
                    self._fut.result(timeout=60)
            except Exception:  # noqa: BLE001 - a wedged drain falls
                # through to the hard kill below
                pass
        self.actor.kill()


class ActorReplica(_ActorMember):
    role = "decode"

    def __init__(self, replica_id: str, module, params,
                 cfg_kwargs: Dict[str, Any], beat_addr: Tuple[str, int],
                 beat_s: float = 0.25, draft_module=None,
                 draft_params=None, trace_dir: Optional[str] = None):
        super().__init__(replica_id, "rlt-serve-replica")
        self._fut = self.actor.submit(
            run_decode_replica, replica_id, module, params, cfg_kwargs,
            beat_addr, beat_s, draft_module, draft_params, trace_dir,
        )


class ActorPrefill(_ActorMember):
    role = "prefill"

    def __init__(self, worker_id: str, module, params, serve_cfg,
                 beat_addr: Tuple[str, int], beat_s: float = 0.25,
                 trace_dir: Optional[str] = None):
        super().__init__(worker_id, "rlt-serve-prefill")
        self._fut = self.actor.submit(
            run_prefill_worker, worker_id, module, params, serve_cfg,
            beat_addr, beat_s, trace_dir,
        )


# ---------------------------------------------------------------------------
# Fleet construction
# ---------------------------------------------------------------------------

class ServeFleet:
    """One handle on router + replicas + workers with one teardown."""

    def __init__(self, router: Router, replicas: List[Any],
                 workers: List[Any]):
        self.router = router
        self.replicas = replicas
        self.workers = workers

    def queue_handle(self):
        return self.router.queue_handle()

    def register_adapter(self, name: str, adapter: Dict[str, Any]) -> None:
        """Register one LoRA tenant fleet-wide (see
        :meth:`~.router.Router.register_adapter`): members are
        hot-loaded lazily at placement time."""
        self.router.register_adapter(name, adapter)

    def close(self) -> None:
        # Router first: a planned teardown must not read as member
        # deaths (spurious failovers/respawns on the way down).
        self.router.stop()
        for member in self.workers + self.replicas:
            try:
                member.kill()
            except Exception:  # noqa: BLE001 - teardown is best-effort
                pass


def _host_params(params):
    """Numpy-ify a param tree once so actor shipping (cloudpickle) does
    not serialize device buffers."""
    import jax
    import numpy as np

    return jax.tree.map(np.asarray, params)


def _cfg_kwargs(serve_cfg) -> Dict[str, Any]:
    from dataclasses import asdict

    kw = asdict(serve_cfg)
    if kw.get("prefill_buckets") is not None:
        kw["prefill_buckets"] = list(kw["prefill_buckets"])
    return kw


def launch_inproc_fleet(module, params, serve_cfg, *, n_replicas: int = 2,
                        n_prefill: int = 0, draft_module=None,
                        draft_params=None, beat_s: float = 0.1,
                        lost_after_s: float = 1.0,
                        trace_dir: Optional[str] = None,
                        adapters: Optional[Dict[str, Any]] = None,
                        **router_kwargs) -> ServeFleet:
    """N engines + M prefill workers on driver threads behind a started
    router — the cheap fleet for tests/examples (real TCP beat/handoff
    wire, no subprocesses).  ``trace_dir`` turns on request-scoped
    distributed tracing fleet-wide (router + every member exports
    per-component span JSONL there; stitch with
    ``tools/trace_stitch.py``).  ``adapters`` pre-registers LoRA
    tenants with the router (``serve_cfg.max_adapters`` sizes every
    member's pool; members are hot-loaded lazily at placement)."""
    from ray_lightning_tpu.serve.engine import ServeConfig, ServeEngine

    router = Router(lost_after_s=lost_after_s, trace_dir=trace_dir,
                    **router_kwargs)
    for name, adapter in (adapters or {}).items():
        router.register_adapter(name, adapter)

    def make_engine(name):
        return ServeEngine(
            module, params, ServeConfig(**_cfg_kwargs(serve_cfg)),
            draft_module=draft_module, draft_params=draft_params,
            trace_dir=trace_dir, trace_name=name,
        )

    replicas = [
        InprocReplica(f"r{i}", make_engine(f"r{i}"), router.beat_handle,
                      beat_s=beat_s)
        for i in range(n_replicas)
    ]
    workers = [
        InprocPrefill(f"p{i}", module, params, serve_cfg,
                      router.beat_handle, beat_s=beat_s,
                      trace_dir=trace_dir)
        for i in range(n_prefill)
    ]
    if n_prefill:
        router._prefill_factory = lambda: InprocPrefill(
            f"p{uuid.uuid4().hex[:6]}", module, params, serve_cfg,
            router.beat_handle, beat_s=beat_s, trace_dir=trace_dir,
        )
    for r in replicas:
        router.add_replica(r)
    for w in workers:
        router.add_prefill(w)
    router.start()
    router.wait_ready(timeout=60)
    return ServeFleet(router, replicas, workers)


def launch_actor_fleet(module, params, serve_cfg, *, n_replicas: int = 2,
                       n_prefill: int = 1, draft_module=None,
                       draft_params=None, beat_s: float = 0.25,
                       lost_after_s: float = 2.0,
                       governor: Optional[RestartGovernor] = None,
                       startup_timeout_s: float = 180.0,
                       trace_dir: Optional[str] = None,
                       adapters: Optional[Dict[str, Any]] = None,
                       **router_kwargs) -> ServeFleet:
    """The real fleet: one ProcessActor per member, each owning its own
    devices (1 CPU device per actor on this container; a TPU host's
    chips in production), beats and handoffs over the queue plane.
    ``trace_dir`` (a SHARED path — same-host fleets, or a shared mount)
    turns on fleet-wide request tracing; members export their span
    JSONL on graceful teardown.  ``adapters`` pre-registers LoRA
    tenants with the router for lazy hot-load."""
    router = Router(lost_after_s=lost_after_s, governor=governor,
                    trace_dir=trace_dir, **router_kwargs)
    for name, adapter in (adapters or {}).items():
        router.register_adapter(name, adapter)
    beat_addr = (router.beat_handle.host, router.beat_handle.port)
    params = _host_params(params)
    draft_params = (_host_params(draft_params)
                    if draft_params is not None else None)
    cfg_kwargs = _cfg_kwargs(serve_cfg)
    replicas = [
        ActorReplica(f"r{i}", module, params, cfg_kwargs, beat_addr,
                     beat_s=beat_s, draft_module=draft_module,
                     draft_params=draft_params, trace_dir=trace_dir)
        for i in range(n_replicas)
    ]
    workers = [
        ActorPrefill(f"p{i}", module, params, serve_cfg, beat_addr,
                     beat_s=beat_s, trace_dir=trace_dir)
        for i in range(n_prefill)
    ]
    if n_prefill:
        router._prefill_factory = lambda: ActorPrefill(
            f"p{uuid.uuid4().hex[:6]}", module, params, serve_cfg,
            beat_addr, beat_s=beat_s, trace_dir=trace_dir,
        )
    for r in replicas:
        router.add_replica(r)
    for w in workers:
        router.add_prefill(w)
    router.start()
    router.wait_ready(timeout=startup_timeout_s)
    return ServeFleet(router, replicas, workers)
